"""Runtime health layer (ISSUE 11): always-on flight recorder, stall
watchdog, latency SLO histograms.

Pins the acceptance criteria: the flight ring captures compact timeline
events at ``HEAT_TPU_TELEMETRY=1`` (where the verbose timeline stays
empty) and auto-dumps a validated Perfetto trace + forensics bundle on an
injected OOM and on a fused-dispatch degrade; the watchdog detects an
injected stall (naming the in-flight program key and the pending DAG
roots) without false positives on a healthy mesh, and its ``raise``
policy surfaces a non-degradable ``StallError``; the log-bucketed
histograms track numpy percentiles within the bucket error bound and
surface per-program p50/p90/p99 in ``report()["health"]``; SLO gauges
count breaches; and none of it ever forces a pending chain or initializes
the backend. Runs green at mesh 1/3/8 (matrix legs), under
``HEAT_TPU_FAULTS=ci`` (explicit injections suspend the ambient mix) and
with the matrix's flight leg armed from the environment (setUp re-arms
per test and tearDown restores the ambient config).
"""

import importlib
import importlib.util
import io
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
import unittest
import warnings

import numpy as np

import heat_tpu as ht
from heat_tpu.core import fusion, health_runtime, memledger, resilience, telemetry

from harness import TestCase

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class HealthCase(TestCase):
    """Clean flight/watchdog/histogram state per test, exact under the
    ambient CI fault mix and under the matrix flight leg's env knobs
    (every test re-arms its own config and restores the ambient one)."""

    def setUp(self):
        self._suspend = resilience.suspended()
        self._suspend.__enter__()
        fusion.clear_cache()
        telemetry.reset()  # cascades into health_runtime.reset()
        memledger.reset()
        self._prev_budget = memledger.set_budget(None)
        self._prev_mode = telemetry.set_mode(1)
        self._prev_flight = health_runtime.set_flight(True, 256)
        self._prev_wd = health_runtime.set_watchdog(enabled=False)
        self._tmp = tempfile.mkdtemp(prefix="heat_tpu_flight_test_")
        self._prev_dir = health_runtime.set_dump_dir(self._tmp)

    def tearDown(self):
        health_runtime.set_dump_dir(self._prev_dir)
        health_runtime.set_watchdog(
            self._prev_wd[0], policy=self._prev_wd[1], enabled=self._prev_wd[2]
        )
        health_runtime.set_flight(self._prev_flight[0], self._prev_flight[1])
        telemetry.set_mode(self._prev_mode)
        telemetry.reset()
        memledger.set_budget(self._prev_budget[0], self._prev_budget[1])
        memledger.reset()
        self._suspend.__exit__(None, None, None)
        shutil.rmtree(self._tmp, ignore_errors=True)

    def _split_input(self, seed=0, n_mult=4):
        n = n_mult * self.get_size()
        return ht.array(
            np.random.default_rng(seed).standard_normal((n, 3)).astype(np.float32),
            split=0,
        )

    def _run_chain(self, seed=0):
        a = self._split_input(seed)
        return float((ht.exp(a * 0.25) + 1.0).sum())

    def _await_stall(self, timeout_s=3.0):
        end = time.monotonic() + timeout_s
        while time.monotonic() < end:
            stall = health_runtime.last_stall()
            if stall is not None:
                return stall
            time.sleep(0.02)
        return health_runtime.last_stall()


class TestFlightRing(HealthCase):
    @unittest.skipUnless(fusion.active(), "flight events ride the fused dispatch/sync seams")
    def test_ring_records_at_mode1_while_verbose_timeline_stays_empty(self):
        self._run_chain()
        kinds = {ev.get("kind") for ev in health_runtime.flight_events()}
        self.assertIn("blocking_sync", kinds)
        if fusion.active():
            self.assertIn("dispatch", kinds)
        # mode 1 is aggregate-only: the verbose per-state timeline must not
        # have been fed — the ring is the ONLY event capture at this mode
        self.assertEqual(len(telemetry._GLOBAL.events), 0)

    @unittest.skipUnless(fusion.active(), "flight events ride the fused dispatch/sync seams")
    def test_ring_cap_evicts_and_counts_drops(self):
        health_runtime.set_flight(True, 16)
        a = self._split_input()
        for i in range(24):  # every iteration emits >= 1 sync event
            float((a + float(i)).sum())
        stats = health_runtime.flight_stats()
        self.assertLessEqual(len(health_runtime.flight_events()), 16)
        self.assertEqual(stats["cap"], 16)
        self.assertGreater(stats["dropped"], 0)

    def test_disabled_recorder_is_a_noop(self):
        health_runtime.set_flight(False)
        self._run_chain()
        self.assertEqual(health_runtime.flight_events(), [])
        self.assertIsNone(health_runtime.auto_dump("oom"))

    def test_env_knobs_configure_a_fresh_interpreter(self):
        code = (
            "from heat_tpu.core import health_runtime as hr\n"
            "assert hr._ENABLED is False, hr._ENABLED\n"
            "assert hr._RING_CAP == 64, hr._RING_CAP\n"
            "print('OK')\n"
        )
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["HEAT_TPU_FLIGHT"] = "0"
        env["HEAT_TPU_FLIGHT_EVENTS"] = "64"
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, env=env, cwd=_REPO,
        )
        self.assertEqual(out.returncode, 0, out.stderr)
        self.assertIn("OK", out.stdout)


class TestFlightDump(HealthCase):
    @unittest.skipUnless(fusion.active(), "flight events ride the fused dispatch/sync seams")
    def test_manual_dump_validates_and_carries_forensics(self):
        self._run_chain()
        dump = health_runtime.dump_flight(reason="manual")
        self.assertEqual(dump["problems"], [])
        self.assertTrue(os.path.exists(dump["trace_path"]))
        with open(dump["path"]) as fh:
            bundle = json.load(fh)
        for key in (
            "reason", "captured_utc", "telemetry_mode", "events", "ring_cap",
            "trace_path", "watchdog", "stalls", "health", "programs", "memory",
        ):
            self.assertIn(key, bundle)
        self.assertEqual(bundle["reason"], "manual")
        self.assertEqual(bundle["trace_problems"], [])
        self.assertGreater(bundle["events"], 0)

    @unittest.skipUnless(fusion.active(), "fusion disabled via HEAT_TPU_FUSION")
    def test_auto_dump_on_injected_oom_names_program(self):
        a = self._split_input(7)
        x = ht.exp(a * 0.25) + 1.0
        with resilience.inject("memory.exhausted", times=1):
            with warnings.catch_warnings(record=True):
                warnings.simplefilter("always")
                float(x.sum())
        self.assertIsNotNone(health_runtime.last_dump(), "OOM must auto-dump")
        # the exhaustion writes the "oom" bundle, then the guarded replay's
        # degrade seam writes a second one — find the OOM forensic itself
        oom_bundles = [
            os.path.join(self._tmp, name)
            for name in sorted(os.listdir(self._tmp))
            if "_oom_" in name and not name.endswith(".trace.json")
        ]
        self.assertTrue(oom_bundles, "no oom-reason bundle written")
        with open(oom_bundles[-1]) as fh:
            bundle = json.load(fh)
        self.assertEqual(bundle["reason"], "oom")
        self.assertEqual(bundle["trace_problems"], [])
        oom = bundle["memory"]["last_oom"]
        self.assertTrue(oom["program"], "bundle must name the failing program key")
        self.assertIn(oom["program"], bundle["programs"]["program_keys"])

    @unittest.skipUnless(fusion.active(), "fusion disabled via HEAT_TPU_FUSION")
    def test_auto_dump_on_fused_degrade(self):
        a = self._split_input(5)
        y = ht.log(ht.abs(a) + 2.0)
        with resilience.inject("fusion.compile", times=1):
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                got = float(y.sum())
        self.assertIn(resilience.DegradedDispatchWarning, {w.category for w in caught})
        expect = float(np.sum(np.log(np.abs(np.asarray(a.larray)) + 2.0)))
        self.assertAlmostEqual(got / expect, 1.0, places=5)
        dump = health_runtime.last_dump()
        self.assertIsNotNone(dump, "degrade must trigger a flight auto-dump")
        with open(dump["path"]) as fh:
            self.assertEqual(json.load(fh)["reason"], "degrade")

    def test_auto_dump_throttles_per_reason(self):
        self._run_chain()
        first = health_runtime.auto_dump("degrade")
        self.assertIsNotNone(first)
        self.assertIsNone(health_runtime.auto_dump("degrade"), "throttled")
        self.assertIsNotNone(health_runtime.auto_dump("oom"), "per-reason throttle")


@unittest.skipUnless(fusion.active(), "fusion disabled via HEAT_TPU_FUSION")
class TestWatchdog(HealthCase):
    def test_detects_injected_stall_naming_program_and_pending_roots(self):
        health_runtime.set_watchdog(deadline_ms=80, policy="warn", enabled=True)
        a = self._split_input(3)
        with resilience.inject("watchdog.stall:dispatch", times=1):
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                float((a * 2.0 + 1.0).sum())
                stall = self._await_stall()
        self.assertIsNotNone(stall, "watchdog must trip on the injected stall")
        self.assertEqual(stall["site"], "dispatch")
        self.assertIn(stall["program"], fusion.cache_stats()["program_keys"])
        self.assertTrue(stall["cids"], "diagnosis must carry the in-flight cids")
        self.assertIsInstance(stall["pending_roots"], list)
        self.assertGreaterEqual(health_runtime.watchdog_stats()["trips"], 1)
        stall_warns = [w for w in caught if w.category is resilience.StallWarning]
        self.assertTrue(stall_warns, "warn policy must emit a StallWarning")
        # the blocked sync's outer guard may trip too; at least one warning
        # must name the in-flight program key
        self.assertTrue(
            any(str(stall["program"]) in str(w.message) for w in stall_warns),
            [str(w.message) for w in stall_warns],
        )

    def test_no_false_positive_on_healthy_chain(self):
        health_runtime.set_watchdog(deadline_ms=30000, policy="warn", enabled=True)
        for i in range(3):
            self._run_chain(seed=i)
        self.assertIsNone(health_runtime.last_stall())
        stats = health_runtime.watchdog_stats()
        self.assertEqual(stats["trips"], 0)
        self.assertGreater(stats["arms"], 0, "guards must actually have armed")

    def test_raise_policy_raises_stall_error_and_chain_recovers(self):
        health_runtime.set_watchdog(deadline_ms=80, policy="raise", enabled=True)
        a = self._split_input(4)
        with resilience.inject("watchdog.stall:dispatch", times=1):
            with warnings.catch_warnings(record=True):
                warnings.simplefilter("always")
                with self.assertRaises(resilience.StallError):
                    float((a + 3.0).sum())
        # StallError must NOT degrade-to-eager (force_recoverable excludes
        # it) and the chain must stay re-forcible after the trip
        health_runtime.set_watchdog(policy="warn")
        got = float((a + 3.0).sum())
        expect = float(np.sum(np.asarray(a.larray) + 3.0))
        self.assertAlmostEqual(got / expect, 1.0, places=5)

    def test_dump_policy_writes_a_stall_bundle(self):
        health_runtime.set_watchdog(deadline_ms=80, policy="dump", enabled=True)
        a = self._split_input(6)
        with resilience.inject("watchdog.stall:dispatch", times=1):
            with warnings.catch_warnings(record=True):
                warnings.simplefilter("always")
                float((a - 1.0).sum())
                self.assertIsNotNone(self._await_stall())
        end = time.monotonic() + 3.0
        while time.monotonic() < end and health_runtime.last_dump() is None:
            time.sleep(0.02)
        dump = health_runtime.last_dump()
        self.assertIsNotNone(dump, "dump policy must write a bundle on trip")
        with open(dump["path"]) as fh:
            bundle = json.load(fh)
        self.assertEqual(bundle["reason"], "stall")
        self.assertTrue(bundle["stalls"], "bundle must carry the stall diagnosis")

    def test_set_watchdog_rejects_unknown_policy(self):
        with self.assertRaises(ValueError):
            health_runtime.set_watchdog(policy="panic")


class TestHistograms(HealthCase):
    def test_percentiles_track_numpy_within_bucket_error(self):
        rng = np.random.default_rng(42)
        samples = rng.lognormal(mean=-7.0, sigma=1.5, size=4000)
        h = health_runtime._Hist()
        for v in samples:
            h.observe(float(v))
        for q in (50, 90, 99):
            want = float(np.percentile(samples, q))
            got = h.percentile(q)
            self.assertLessEqual(
                abs(got - want) / want, 0.10,
                f"p{q}: hist {got} vs numpy {want}",
            )
        snap = h.snapshot()
        self.assertEqual(snap["count"], len(samples))
        self.assertLessEqual(snap["p50_s"], snap["p90_s"])
        self.assertLessEqual(snap["p90_s"], snap["p99_s"])

    @unittest.skipUnless(fusion.active(), "fusion disabled via HEAT_TPU_FUSION")
    def test_report_health_has_per_program_percentiles(self):
        for i in range(4):
            self._run_chain(seed=i)
        health = telemetry.report()["health"]
        disp = health["dispatch"]
        self.assertIn("*", disp)
        self.assertGreaterEqual(disp["*"]["count"], 1)
        programs = [k for k in disp if k != "*"]
        self.assertTrue(programs, "dispatch table must be keyed by program")
        for key in programs:
            self.assertIn(key, fusion.cache_stats()["program_keys"])
            for field in ("p50_s", "p90_s", "p99_s"):
                self.assertIn(field, disp[key])
        self.assertIn("*", health["compile"])  # the first run compiled

    @unittest.skipUnless(fusion.active(), "flight events ride the fused dispatch/sync seams")
    def test_sync_wait_aggregate_in_nonverbose_report(self):
        self._run_chain()
        sync_wait = telemetry.report()["async_forcing"]["sync_wait"]
        self.assertTrue(sync_wait, "mode 1 must aggregate blocking host waits")
        rec = next(iter(sync_wait.values()))
        self.assertGreaterEqual(rec["count"], 1)
        self.assertGreaterEqual(rec["total_s"], 0.0)
        self.assertGreaterEqual(rec["max_s"], 0.0)
        self.assertLessEqual(rec["max_s"], rec["total_s"] + 1e-9)

    @unittest.skipUnless(fusion.active(), "flight events ride the fused dispatch/sync seams")
    def test_scope_isolates_and_rolls_up(self):
        self._run_chain(seed=1)  # ambient-only traffic
        with telemetry.scope("inner"):
            before = health_runtime.health_block()["sync"]
            self.assertEqual(
                before.get("*", {}).get("count", 0), 0, "scope view must start empty"
            )
            self._run_chain(seed=2)
            inner = health_runtime.health_block()["sync"]["*"]["count"]
            self.assertGreaterEqual(inner, 1)
        overall = health_runtime.health_block(global_view=True)["sync"]["*"]["count"]
        self.assertGreater(overall, inner, "global view keeps ambient traffic")

    @unittest.skipUnless(fusion.active(), "flight events ride the fused dispatch/sync seams")
    def test_reset_clears_session_keeps_config(self):
        health_runtime.set_flight(True, 32)
        self._run_chain()
        self.assertTrue(health_runtime.flight_events())
        telemetry.reset()  # must cascade into the health layer
        self.assertEqual(health_runtime.flight_events(), [])
        health = health_runtime.health_block(global_view=True)
        self.assertEqual(health["sync"].get("*", {}).get("count", 0), 0)
        self.assertEqual(health["watchdog"]["trips"], 0)
        self.assertEqual(health_runtime.flight_stats()["cap"], 32, "config survives")


class TestSLO(HealthCase):
    @unittest.skipUnless(fusion.active(), "flight events ride the fused dispatch/sync seams")
    def test_breach_counts_and_ring_event(self):
        prev = health_runtime.set_slo(sync_ms=0.0001)
        try:
            self._run_chain()
            slo = health_runtime.health_block()["slo"]["sync"]
            self.assertGreaterEqual(slo["breaches_total"], 1)
            self.assertIsNotNone(slo["limit_ms"])
            kinds = {ev.get("kind") for ev in health_runtime.flight_events()}
            self.assertIn("slo_breach", kinds)
        finally:
            health_runtime.set_slo(
                sync_ms=None if prev["sync"] is None else prev["sync"] * 1e3
            )

    @unittest.skipUnless(fusion.active(), "flight events ride the fused dispatch/sync seams")
    def test_healthy_slo_reports_ok_ratio(self):
        prev = health_runtime.set_slo(sync_ms=60000.0)
        try:
            self._run_chain()
            slo = health_runtime.health_block()["slo"]["sync"]
            self.assertEqual(slo.get("window_breaches", 0), 0)
            self.assertEqual(slo.get("ok_ratio", 1.0), 1.0)
        finally:
            health_runtime.set_slo(
                sync_ms=None if prev["sync"] is None else prev["sync"] * 1e3
            )


class TestContracts(HealthCase):
    @unittest.skipUnless(fusion.active(), "fusion disabled via HEAT_TPU_FUSION")
    def test_health_surfaces_never_force_a_pending_chain(self):
        a = self._split_input(9)
        pending = a * 0.5 + 2.0
        self.assertTrue(fusion.is_deferred(pending))
        health_runtime.flight_stats()
        health_runtime.health_block(global_view=True)
        telemetry.report()
        self.assertTrue(
            fusion.is_deferred(pending), "health reads must not force the DAG"
        )
        self.assert_array_equal(
            pending, np.asarray(a.larray) * 0.5 + 2.0
        )

    def test_health_layer_never_initializes_the_backend(self):
        # a fresh interpreter arms flight + watchdog + SLO, reads every
        # health surface, and the lazy mesh singletons must stay untouched
        code = (
            "from heat_tpu.core import health_runtime as hr\n"
            "from heat_tpu.core import telemetry, communication\n"
            "hr.set_watchdog(deadline_ms=1000, policy='warn', enabled=True)\n"
            "hr.set_slo(sync_ms=5.0)\n"
            "hr.flight_stats(); hr.health_block(global_view=True)\n"
            "hr.watchdog_stats(); hr.stalls()\n"
            "telemetry.report()\n"
            "assert communication.MESH_WORLD is None, 'backend was initialized'\n"
            "print('OK')\n"
        )
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["HEAT_TPU_FLIGHT"] = "1"
        env["HEAT_TPU_TELEMETRY"] = "1"
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, env=env, cwd=_REPO,
        )
        self.assertEqual(out.returncode, 0, out.stderr)
        self.assertIn("OK", out.stdout)


class TestHealthCLI(HealthCase):
    def _cli(self):
        return importlib.import_module("heat_tpu.telemetry")

    @unittest.skipUnless(fusion.active(), "flight events ride the fused dispatch/sync seams")
    def test_health_verb_renders_a_bundle(self):
        self._run_chain()
        dump = health_runtime.dump_flight(reason="manual")
        out = io.StringIO()
        rc = self._cli().main(["health", dump["path"]], out=out)
        self.assertEqual(rc, 0)
        text = out.getvalue()
        self.assertIn("watchdog", text)
        self.assertIn("flight", text)

    @unittest.skipUnless(fusion.active(), "flight events ride the fused dispatch/sync seams")
    def test_health_verb_live_json(self):
        self._run_chain()
        out = io.StringIO()
        rc = self._cli().main(["health", "--json"], out=out)
        self.assertEqual(rc, 0)
        doc = json.loads(out.getvalue())
        self.assertIn("sync", doc["health"])
        self.assertIn("watchdog", doc["health"])
        self.assertGreaterEqual(doc["health"]["sync"]["*"]["count"], 1)


class TestBenchSentinel(unittest.TestCase):
    """compare_records / --against: the noise-robust regression gate."""

    @classmethod
    def setUpClass(cls):
        spec = importlib.util.spec_from_file_location(
            "heat_bench_under_test", os.path.join(_REPO, "bench.py")
        )
        cls.bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cls.bench)
        cls.base = {
            "metric": "kmeans_iters_per_sec", "value": 10.0, "platform": "tpu",
            "lloyd_tflops": 0.8, "flight_overhead_pct": 0.5,
            "telemetry_overhead_pct": 3.0, "lint_findings": 0,
        }

    def test_identical_records_pass(self):
        verdict = self.bench.compare_records(dict(self.base), dict(self.base))
        self.assertTrue(verdict["ok"], verdict)

    def test_rate_regression_detected(self):
        fresh = dict(self.base, lloyd_tflops=0.3)
        verdict = self.bench.compare_records(fresh, dict(self.base))
        self.assertFalse(verdict["ok"])
        self.assertTrue(any("lloyd_tflops" in r for r in verdict["regressions"]))

    def test_noise_within_slack_passes(self):
        fresh = dict(self.base, lloyd_tflops=0.8 * 0.75, value=10.0 * 0.75)
        verdict = self.bench.compare_records(fresh, dict(self.base))
        self.assertTrue(verdict["ok"], verdict)

    def test_overhead_ceiling_enforced_even_without_banked(self):
        banked = {k: v for k, v in self.base.items() if k != "flight_overhead_pct"}
        fresh = dict(self.base, flight_overhead_pct=7.5)
        verdict = self.bench.compare_records(fresh, banked)
        self.assertFalse(verdict["ok"])
        self.assertTrue(any("flight_overhead_pct" in r for r in verdict["regressions"]))

    def test_platform_mismatch_skips_rates(self):
        fresh = dict(self.base, platform="cpu", lloyd_tflops=0.01, value=0.1)
        verdict = self.bench.compare_records(fresh, dict(self.base))
        self.assertTrue(verdict["ok"], verdict)
        self.assertTrue(any("platform" in n for n in verdict["notes"]))

    def test_monotone_counter_growth_regresses(self):
        fresh = dict(self.base, lint_findings=2)
        verdict = self.bench.compare_records(fresh, dict(self.base))
        self.assertFalse(verdict["ok"])

    def test_missing_keys_are_notes_not_failures(self):
        fresh = {"metric": "kmeans_iters_per_sec", "value": 9.5, "platform": "tpu"}
        verdict = self.bench.compare_records(fresh, dict(self.base))
        self.assertTrue(verdict["ok"], verdict)
        self.assertTrue(verdict["notes"])

    def test_load_record_unwraps_round_artifact(self):
        rec = self.bench._load_record(os.path.join(_REPO, "BENCH_r05.json"))
        self.assertIn("value", rec)
        self.assertNotIn("parsed", rec)


if __name__ == "__main__":
    unittest.main()

"""Atomic + retrying I/O (core/resilience.py wired through core/io.py).

Pins the ISSUE-3 acceptance criterion: an injected ``OSError`` on the first
write attempt of ``save_npy``/``save_hdf5`` succeeds via retry and
``os.listdir`` shows no temp/partial files afterward; exhausted retries
raise and STILL leave nothing behind. Every test uses a fast retry policy
(no real backoff sleeps) and shields itself with ``resilience.suspended()``
where exact attempt counts matter.
"""

import os
import pathlib
import tempfile

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import multihost, resilience, telemetry

from harness import TestCase


class IOCase(TestCase):
    def setUp(self):
        self.dir = pathlib.Path(tempfile.mkdtemp())
        self._prev_policy = resilience.retry_policy
        resilience.retry_policy = resilience.RetryPolicy(retries=2, base_delay=0.001)
        self.x_np = np.arange(24, dtype=np.float32).reshape(6, 4)
        self.x = ht.array(self.x_np, split=0)

    def tearDown(self):
        resilience.retry_policy = self._prev_policy

    def _listing(self):
        return sorted(os.listdir(self.dir))


class TestRetryingSaves(IOCase):
    """Acceptance: one injected transient OSError per save, retried to
    success, nothing but the final file on disk."""

    def test_save_npy_retries_first_write_fault(self):
        path = str(self.dir / "a.npy")
        with resilience.suspended():
            with resilience.inject("io.write", exc=OSError, times=1) as spec:
                ht.save_npy(self.x, path)
        self.assertEqual(spec.fired, 1)
        self.assertEqual(self._listing(), ["a.npy"])  # no temp/partial files
        self.assert_array_equal(ht.load_npy(path, split=0), self.x_np)

    def test_save_hdf5_retries_first_write_fault(self):
        path = str(self.dir / "b.h5")
        with resilience.suspended():
            with resilience.inject("io.write", exc=OSError, times=1) as spec:
                ht.save_hdf5(self.x, path, "data")
        self.assertEqual(spec.fired, 1)
        self.assertEqual(self._listing(), ["b.h5"])
        self.assert_array_equal(ht.load_hdf5(path, "data", split=0), self.x_np)

    def test_save_csv_retries_first_write_fault(self):
        path = str(self.dir / "c.csv")
        with resilience.suspended():
            with resilience.inject("io.write", exc=OSError, times=1):
                ht.save_csv(self.x, path)
        self.assertIn("c.csv", self._listing())
        self.assertFalse(any(f.startswith(".") for f in self._listing()))
        self.assert_array_equal(ht.load_csv(path, split=0), self.x_np)

    def test_rename_fault_retries_whole_attempt(self):
        path = str(self.dir / "d.npy")
        with resilience.suspended():
            with resilience.inject("io.rename", exc=OSError, times=1) as spec:
                ht.save_npy(self.x, path)
        self.assertEqual(spec.fired, 1)
        self.assertEqual(self._listing(), ["d.npy"])
        self.assert_array_equal(ht.load_npy(path, split=0), self.x_np)

    def test_retries_are_counted_in_telemetry(self):
        path = str(self.dir / "t.npy")
        with resilience.suspended(), telemetry.enabled():
            telemetry.reset()
            with resilience.inject("io.write", exc=OSError, times=1):
                ht.save_npy(self.x, path)
            self.assertEqual(telemetry.io_retries().get("io.write"), 1)
            self.assertIn("io_retries", telemetry.report())


class TestExhaustedAndNonTransient(IOCase):
    def test_exhausted_retries_raise_and_leave_nothing(self):
        for name, save in (
            ("e.npy", lambda p: ht.save_npy(self.x, p)),
            ("e.h5", lambda p: ht.save_hdf5(self.x, p, "data")),
            ("e.csv", lambda p: ht.save_csv(self.x, p)),
        ):
            path = str(self.dir / name)
            with resilience.suspended():
                with resilience.inject("io.write", exc=OSError, times=None):
                    with pytest.raises(OSError):
                        save(path)
            self.assertNotIn(name, self._listing(), name)
        # interrupted saves leave NO partial/temp files behind
        self.assertEqual(self._listing(), [])

    def test_non_transient_oserror_does_not_retry(self):
        # ENOENT (missing directory) must surface immediately, not after
        # retries-worth of backoff: classification is by errno
        missing = str(self.dir / "no" / "such" / "dir" / "x.npy")
        calls = []
        orig = resilience.retry_policy.is_transient

        class Spy(resilience.RetryPolicy):
            def is_transient(self, exc):
                calls.append(exc)
                return orig(exc)

        resilience.retry_policy = Spy(retries=2, base_delay=0.001)
        with pytest.raises((FileNotFoundError, OSError)):
            ht.save_npy(self.x, missing)
        self.assertEqual(len(calls), 1)  # classified once, never retried

    def test_rplus_on_missing_target_names_the_users_path(self):
        # the error must name the target, not the hidden staging temp
        missing = str(self.dir / "never_existed.h5")
        with pytest.raises(FileNotFoundError) as exc_info:
            ht.save_hdf5(self.x, missing, "d", mode="r+")
        self.assertIn("never_existed.h5", str(exc_info.value))
        self.assertNotIn(".tmp-", str(exc_info.value))
        self.assertEqual(self._listing(), [])

    def test_failed_append_keeps_original_intact(self):
        # an interrupted append must not corrupt the existing file: the temp
        # copy absorbs the damage, the target is untouched. io.rename faults
        # fire AFTER the append body fully ran against the temp, so this
        # drives the writer end-to-end, not just the pre-write check
        path = str(self.dir / "keep.h5")
        ht.save_hdf5(self.x, path, "one")
        with resilience.suspended():
            for site in ("io.write", "io.rename"):
                with resilience.inject(site, exc=OSError, times=None):
                    with pytest.raises(OSError):
                        ht.save_hdf5(ht.arange(4, dtype=ht.float32), path, "two", mode="a")
        self.assertEqual(self._listing(), ["keep.h5"])
        self.assert_array_equal(ht.load_hdf5(path, "one", split=0), self.x_np)
        with pytest.raises(KeyError):
            ht.load_hdf5(path, "two")  # the aborted append never published


class TestRetryingReads(IOCase):
    def test_sharded_npy_read_retries(self):
        path = str(self.dir / "r.npy")
        ht.save_npy(self.x, path)
        with resilience.suspended():
            with resilience.inject("io.read", exc=OSError, times=1) as spec:
                back = ht.load_npy(path, split=0)
        self.assertEqual(spec.fired, 1)
        self.assert_array_equal(back, self.x_np)

    def test_sharded_hdf5_read_retries(self):
        path = str(self.dir / "r.h5")
        ht.save_hdf5(self.x, path, "data")
        with resilience.suspended():
            with resilience.inject("io.read", exc=OSError, times=1):
                back = ht.load_hdf5(path, "data", split=0)
        self.assert_array_equal(back, self.x_np)

    def test_read_retries_exhaust_to_the_real_error(self):
        path = str(self.dir / "r2.npy")
        ht.save_npy(self.x, path)
        with resilience.suspended():
            with resilience.inject("io.read", exc=OSError, times=None):
                with pytest.raises(OSError):
                    ht.load_npy(path, split=0)


class TestMultihostPublication(IOCase):
    """Only the owning process renames (the multihost.py seam)."""

    def test_io_owner_is_process_zero(self):
        self.assertTrue(multihost.io_owner(0))
        self.assertFalse(multihost.io_owner(1))
        self.assertTrue(multihost.io_owner())  # single-controller: process 0

    def test_non_owner_discards_instead_of_publishing(self):
        path = str(self.dir / "never.npy")
        prev = multihost.io_owner
        multihost.io_owner = lambda proc=None: False
        try:
            ht.save_npy(self.x, path)
        finally:
            multihost.io_owner = prev
        # nothing published, nothing leaked
        self.assertEqual(self._listing(), [])

    def test_split_streaming_save_refuses_partial_addressability(self):
        # a controller that cannot address the whole mesh must refuse the
        # streaming split save loudly (a single-file write would be short),
        # and leave nothing behind
        if self.get_size() == 1:
            self.skipTest("a 1-device mesh takes the replicated fast path")
        prev = multihost.process_index
        multihost.process_index = lambda: 1  # pose as a non-zero controller
        try:
            with pytest.raises(NotImplementedError):
                ht.save_npy(self.x, str(self.dir / "part.npy"))
            with pytest.raises(NotImplementedError):
                ht.save_hdf5(self.x, str(self.dir / "part.h5"), "d")
        finally:
            multihost.process_index = prev
        self.assertEqual(self._listing(), [])

    def test_atomic_write_primitive_cleans_on_error(self):
        target = str(self.dir / "prim.bin")
        with pytest.raises(RuntimeError):
            with resilience.atomic_write(target) as tmp:
                with open(tmp, "wb") as fh:
                    fh.write(b"partial")
                raise RuntimeError("writer died mid-stream")
        self.assertEqual(self._listing(), [])

    def test_failed_preserve_copy_leaves_nothing(self):
        # regression: a failed seed copy (append modes) must clean its own
        # partial temp — same leave-nothing-behind contract as the body
        import shutil

        target = str(self.dir / "seed.h5")
        ht.save_hdf5(self.x, target, "one")
        orig = shutil.copy2

        def dying_copy(src, dst, **kw):
            with open(dst, "wb") as fh:
                fh.write(b"partial")  # the copy got partway...
            raise OSError(28, "No space left on device")

        shutil.copy2 = dying_copy
        try:
            with pytest.raises(OSError):
                ht.save_hdf5(ht.arange(4, dtype=ht.float32), target, "two", mode="a")
        finally:
            shutil.copy2 = orig
        self.assertEqual(self._listing(), ["seed.h5"])  # no temp orphaned
        self.assert_array_equal(ht.load_hdf5(target, "one", split=0), self.x_np)

    def test_atomic_write_publishes_complete_files_only(self):
        target = str(self.dir / "ok.bin")
        with resilience.atomic_write(target) as tmp:
            with open(tmp, "wb") as fh:
                fh.write(b"complete")
        self.assertEqual(self._listing(), ["ok.bin"])
        with open(target, "rb") as fh:
            self.assertEqual(fh.read(), b"complete")

"""QR depth: oracle sweeps across all splits/shapes (incl. ragged and
short-wide) plus HLO schedule assertions — the tall-skinny split-0 TSQR must
not all-gather the full operand (reference heat/core/linalg/qr.py:319-1042 is
the spec; its tile-CAQR never gathers the operand either)."""

import re
import warnings

import numpy as np
import pytest

import heat_tpu as ht

from harness import TestCase


class TestQRAllSplits(TestCase):
    def _check(self, m, n, split, seed=0):
        rng = np.random.default_rng(seed)
        a_np = rng.standard_normal((m, n))
        a = ht.array(a_np, split=split)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            Q, R = ht.linalg.qr(a)
        q, r = Q.numpy(), R.numpy()
        k = min(m, n)
        self.assertEqual(q.shape, (m, k))
        self.assertEqual(r.shape, (k, n))
        np.testing.assert_allclose(q @ r, a_np, atol=1e-10)
        np.testing.assert_allclose(q.T @ q, np.eye(k), atol=1e-10)
        self.assertLess(np.abs(np.tril(r, -1)).max(), 1e-12)
        return Q, R

    def test_tall_skinny_divisible(self):
        p = self.get_size()
        Q, R = self._check(8 * p, 6, 0)
        self.assertEqual(Q.split, 0)
        self.assertEqual(R.split, None)

    def test_tall_skinny_ragged(self):
        p = self.get_size()
        for m in (8 * p + 1, 8 * p + p - 1, 2 * p + 1):
            self._check(m, min(4, m), 0, seed=m)

    def test_split1_all_shapes(self):
        p = self.get_size()
        for (m, n) in [(6 * p, 4 * p), (6 * p + 1, 2 * p + 1), (3 * p + 2, p + 1)]:
            if m < n:
                continue
            Q, R = self._check(m, n, 1, seed=m * n)
            if p > 1:
                self.assertEqual(Q.split, 1)
                self.assertEqual(R.split, 1)

    def test_short_wide(self):
        p = self.get_size()
        for split in (None, 0, 1):
            self._check(p + 1, 4 * p + 3, split, seed=11)

    def test_square_and_none(self):
        p = self.get_size()
        self._check(4 * p, 4 * p, None)
        self._check(4 * p, 4 * p, 0)

    def test_calc_q_false(self):
        a = ht.array(np.random.default_rng(1).standard_normal((4 * self.get_size(), 3)), split=0)
        out = ht.linalg.qr(a, calc_q=False)
        self.assertIsNone(out.Q)
        self.assertEqual(out.R.shape, (3, 3))

    def test_short_wide_large_warns(self):
        p = self.get_size()
        if p == 1:
            self.skipTest("warning only fires for distributed operands")
        import importlib

        qr_mod = importlib.import_module("heat_tpu.core.linalg.qr")
        old = qr_mod._REPLICATED_MAX_ELEMENTS
        qr_mod._REPLICATED_MAX_ELEMENTS = 10
        try:
            a = ht.array(np.random.default_rng(2).standard_normal((p, 3 * p)), split=1)
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                ht.linalg.qr(a)
            self.assertTrue(any("replicated" in str(x.message).lower() for x in w))
        finally:
            qr_mod._REPLICATED_MAX_ELEMENTS = old

    def test_int_input_promotes(self):
        p = self.get_size()
        a_np = np.arange(8 * p * 3).reshape(8 * p, 3)
        a = ht.array(a_np, split=0)
        Q, R = ht.linalg.qr(a)
        self.assertTrue(ht.core.types.heat_type_is_inexact(Q.dtype))
        np.testing.assert_allclose(Q.numpy() @ R.numpy(), a_np, atol=1e-8)


class TestQRSchedule(TestCase):
    """HLO assertions: the distributed schedules never gather the operand."""

    def test_tsqr_never_gathers_operand(self):
        p = self.get_size()
        if p == 1:
            self.skipTest("schedule only exists on a distributed mesh")
        from heat_tpu.core.linalg.qr import _tsqr_program

        m, n = 64 * p, 8
        comm = self.comm
        fn = _tsqr_program(comm.mesh, comm.axis_name, m // p, n, p, "float64")
        import jax.numpy as jnp

        hlo = fn.lower(jnp.zeros((m, n), jnp.float64)).compile().as_text()
        # every all-gather/all-reduce in the program must move only R-tile
        # volume (p * n * n), never the (m, n) operand
        coll = re.findall(r"(?:all-gather|all-reduce)[^\n]*", hlo)
        self.assertTrue(coll, "TSQR lost its R-factor all-gather")
        for line in coll:
            for shape in re.findall(r"f\d+\[([\d,]+)\]", line):
                elems = int(np.prod([int(d) for d in shape.split(",")]))
                self.assertLessEqual(
                    elems,
                    p * n * n,
                    f"collective moves more than the R tiles: {line[:120]}",
                )

    def test_panel_qr_collective_budget(self):
        p = self.get_size()
        if p == 1:
            self.skipTest("schedule only exists on a distributed mesh")
        from heat_tpu.core.linalg.qr import _panel_program

        m, n = 16 * p, 2 * p
        c = n // p
        comm = self.comm
        fn = _panel_program(comm.mesh, comm.axis_name, m, c, n, p, "float64")
        import jax.numpy as jnp

        hlo = fn.lower(jnp.zeros((m, n), jnp.float64)).compile().as_text()
        # each panel broadcast moves one (m, c) panel (+ its (c, c) R block),
        # possibly fused into a single psum tuple — never the full operand
        coll = re.findall(r"(?:all-gather|all-reduce)[^\n]*", hlo)
        self.assertTrue(coll, "panel loop lost its broadcasts")
        budget = m * c + c * c
        for line in coll:
            for shape in re.findall(r"f\d+\[([\d,]+)\]", line):
                elems = int(np.prod([int(d) for d in shape.split(",")]))
                self.assertLessEqual(
                    elems,
                    budget,
                    f"collective moves more than one panel: {line[:120]}",
                )


class TestQRGuards(TestCase):
    def test_wide_block_never_silently_gathers(self):
        # block = ceil(m/p) < n would make the TSQR R-gather move the FULL
        # operand; such shapes must take the (warned above threshold)
        # replicated fallback instead
        p = self.get_size()
        if p == 1:
            self.skipTest("needs a distributed mesh")
        import importlib

        qr_mod = importlib.import_module("heat_tpu.core.linalg.qr")
        m, n = 2 * p, p + 2  # m >= n but block=2 < n
        a = ht.array(np.random.default_rng(0).standard_normal((m, n)), split=0)
        old = qr_mod._REPLICATED_MAX_ELEMENTS
        qr_mod._REPLICATED_MAX_ELEMENTS = 1
        try:
            with warnings.catch_warnings(record=True) as w:
                warnings.simplefilter("always")
                Q, R = ht.linalg.qr(a)
            self.assertTrue(any("replicated" in str(x.message).lower() for x in w))
        finally:
            qr_mod._REPLICATED_MAX_ELEMENTS = old
        np.testing.assert_allclose(Q.numpy() @ R.numpy(), a.numpy(), atol=1e-10)


class TestCholQR2(TestCase):
    """CholeskyQR2: the MXU-native tall-skinny method (opt-in)."""

    def test_orthonormal_and_reconstructs(self):
        rng = np.random.default_rng(20)
        for shape in ((64, 6), (37, 5)):  # divisible and ragged rows
            a_np = rng.standard_normal(shape).astype(np.float32)
            for split in (None, 0):
                a = ht.resplit(ht.array(a_np), split)
                q, r = ht.linalg.qr(a, method="cholqr2")
                q_np = np.asarray(q.larray)
                r_np = np.asarray(r.larray)
                np.testing.assert_allclose(q_np.T @ q_np, np.eye(shape[1]), atol=2e-4)
                np.testing.assert_allclose(q_np @ r_np, a_np, atol=2e-4)
                assert np.allclose(r_np, np.triu(r_np))  # upper triangular
                if split == 0:
                    assert q.split == 0 and r.split is None

    def test_r_matches_tsqr_up_to_sign(self):
        rng = np.random.default_rng(21)
        a_np = rng.standard_normal((48, 4)).astype(np.float32)
        a = ht.array(a_np, split=0)
        _, r_chol = ht.linalg.qr(a, method="cholqr2")
        _, r_tsqr = ht.linalg.qr(a, method="tsqr")
        # QR is unique up to column signs of Q / row signs of R
        np.testing.assert_allclose(
            np.abs(np.asarray(r_chol.larray)), np.abs(np.asarray(r_tsqr.larray)), rtol=1e-3, atol=1e-4
        )

    def test_calc_q_false(self):
        a = ht.random.randn(32, 3)
        q, r = ht.linalg.qr(a, method="cholqr2", calc_q=False)
        assert q is None and r.shape == (3, 3)

    def test_breakdown_raises(self):
        col = np.arange(24, dtype=np.float32)[:, None]
        a_np = np.concatenate([col, col, col], axis=1)  # rank 1
        with pytest.raises(ValueError, match="cholqr2 broke down"):
            ht.linalg.qr(ht.array(a_np, split=0), method="cholqr2")

    def test_auto_is_the_default_method(self):
        # the default flipped to "auto" on the measured 6.7x v5e margin
        # (benchmarks/TPU_WINDOW_r04.json cholqr2 stage): a bare qr() on a
        # well-conditioned tall-skinny operand must take the cholqr2 path
        rng = np.random.default_rng(23)
        a_np = rng.standard_normal((64, 4)).astype(np.float32)
        q, r = ht.linalg.qr(ht.array(a_np, split=0))
        assert (np.diag(np.asarray(r.larray)) > 0).all()  # cholqr2 signature
        q_np = np.asarray(q.larray)
        np.testing.assert_allclose(q_np.T @ q_np, np.eye(4), atol=2e-4)

    def test_auto_uses_cholqr2_when_well_conditioned(self):
        rng = np.random.default_rng(22)
        a_np = rng.standard_normal((48, 4)).astype(np.float32)
        a = ht.array(a_np, split=0)
        q, r = ht.linalg.qr(a, method="auto")
        q_np, r_np = np.asarray(q.larray), np.asarray(r.larray)
        np.testing.assert_allclose(q_np.T @ q_np, np.eye(4), atol=2e-4)
        np.testing.assert_allclose(q_np @ r_np, a_np, atol=2e-4)
        # auto must pick cholqr2 here: its R diagonal is positive by
        # construction (Cholesky factors), while TSQR signs are arbitrary
        assert (np.diag(r_np) > 0).all()

    def test_auto_falls_back_on_breakdown(self):
        # rank-1: cholqr2 breaks down; auto must return valid TSQR factors
        # instead of raising
        col = np.arange(24, dtype=np.float32)[:, None]
        a_np = np.concatenate([col, col + 0.001, col - 0.001], axis=1)
        a_np[0] += np.array([1e-4, -1e-4, 2e-4], np.float32)
        q, r = ht.linalg.qr(ht.array(a_np, split=0), method="auto")
        np.testing.assert_allclose(
            np.asarray(q.larray) @ np.asarray(r.larray), a_np, atol=1e-3
        )

    def test_bf16_stream_kernel(self):
        # the raw kernel's half-width stream (stage_qr_marginal's bf16
        # variant): operand/Q stay bfloat16, Gram accumulates f32, the
        # small Cholesky/inverse run f32 — and the probe accepts a
        # well-conditioned operand at bf16's own noise floor
        import importlib
        import jax
        import jax.numpy as jnp

        qr_mod = importlib.import_module("heat_tpu.core.linalg.qr")
        x = jax.random.normal(jax.random.PRNGKey(0), (512, 16), jnp.float32).astype(
            jnp.bfloat16
        )
        q, r, ok = qr_mod._cholqr2_kernel(x)
        assert bool(ok)
        assert q.dtype == jnp.bfloat16 and r.dtype == jnp.float32
        qn = np.asarray(q, np.float32)
        assert np.abs(qn.T @ qn - np.eye(16)).max() < 0.03  # bf16 ulp class
        np.testing.assert_allclose(
            qn @ np.asarray(r), np.asarray(x, np.float32), atol=0.15
        )

    def test_probe_rejects_finite_but_degraded_orthogonality(self):
        # advisor r04#3: a finite Gram Cholesky is NOT sufficient — near the
        # 1/sqrt(eps) conditioning bound Q1 drifts from orthonormal while
        # everything stays finite. The probe must gate on ||Q1^H Q1 - I|| too.
        # The exact operand regime where that window opens is platform- and
        # build-sensitive, so the threshold logic is unit-tested directly.
        import importlib
        import jax.numpy as jnp

        qr_mod = importlib.import_module("heat_tpu.core.linalg.qr")
        probe = qr_mod._cholqr2_probe_ok
        n = 4
        eye = jnp.eye(n, dtype=jnp.float32)
        r_ok = jnp.triu(jnp.ones((n, n), jnp.float32))
        # finite factors, tiny orthogonality error: accept
        assert bool(probe(r_ok, r_ok, eye + 1e-6, eye))
        # finite factors, error past the 0.5 recovery band: reject
        g_bad = eye.at[0, 1].set(0.6)
        assert not bool(probe(r_ok, r_ok, g_bad, eye))
        # non-finite first-pass factor: reject even with a clean-looking g2
        r_nan = r_ok.at[0, 0].set(jnp.nan)
        assert not bool(probe(r_nan, r_ok, eye, eye))
        assert not bool(probe(r_ok, r_nan, eye, eye))

    def test_auto_square_skips_cholqr2_probe(self):
        # a square (or insufficiently tall) operand must NOT run the probe:
        # its (n, n) Gram would be a silent full-size replication
        import importlib
        import unittest.mock

        qr_mod = importlib.import_module("heat_tpu.core.linalg.qr")

        a_np = np.random.default_rng(24).standard_normal((12, 12)).astype(np.float32)
        with unittest.mock.patch.object(
            qr_mod, "_cholqr2_kernel",
            side_effect=AssertionError("auto probed a non-tall operand"),
        ):
            q, r = ht.linalg.qr(ht.array(a_np, split=0), method="auto")
        np.testing.assert_allclose(
            np.asarray(q.larray) @ np.asarray(r.larray), a_np, atol=1e-4
        )

    def test_auto_split1_keeps_panel_layout(self):
        # split=1 R layout must not depend on conditioning: auto always
        # routes the panel path there (R split=1 by contract)
        p = self.get_size()
        if p == 1:
            self.skipTest("panel layout only exists on a distributed mesh")
        a_np = np.random.default_rng(25).standard_normal((8 * p, 2 * p)).astype(np.float32)
        q, r = ht.linalg.qr(ht.array(a_np, split=1), method="auto")
        assert r.split == 1
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a_np, atol=1e-3)

    def test_auto_short_wide_goes_householder(self):
        a_np = np.random.default_rng(23).standard_normal((3, 9)).astype(np.float32)
        q, r = ht.linalg.qr(ht.array(a_np), method="auto")
        np.testing.assert_allclose(
            np.asarray(q.larray) @ np.asarray(r.larray), a_np, atol=1e-4
        )

    def test_validation(self):
        with pytest.raises(ValueError, match="tall operand"):
            ht.linalg.qr(ht.ones((3, 8)), method="cholqr2")
        with pytest.raises(ValueError, match="unknown qr method"):
            ht.linalg.qr(ht.ones((8, 3)), method="nope")

    def test_complex_operand_unitary(self):
        rng = np.random.default_rng(22)
        a_np = (rng.standard_normal((40, 4)) + 1j * rng.standard_normal((40, 4))).astype(
            np.complex64
        )
        q, r = ht.linalg.qr(ht.array(a_np, split=0), method="cholqr2")
        q_np = np.asarray(q.larray)
        np.testing.assert_allclose(q_np.conj().T @ q_np, np.eye(4), atol=3e-4)
        np.testing.assert_allclose(q_np @ np.asarray(r.larray), a_np, atol=3e-4)

"""Operator × dtype × split matrix sweep.

The reference CI's backbone is ``assert_func_equal``: every op run over a
dtype matrix and EVERY split axis against the numpy oracle (reference
basic_test.py:142-217, 295-306). This suite turns that crank over the core
reduction/manipulation/elementwise surface on 2-D and 3-D shapes — broad
shallow coverage complementing the targeted depth suites.
"""

import numpy as np

import heat_tpu as ht

from harness import TestCase


class TestReductionMatrix(TestCase):
    def test_sum(self):
        self.assert_func_equal((4, 5), ht.sum, np.sum, rtol=1e-4, atol=1e-2)
        self.assert_func_equal(
            (3, 4, 5), ht.sum, np.sum, heat_args={"axis": 1}, numpy_args={"axis": 1},
            rtol=1e-4, atol=1e-2,
        )

    def test_mean_var_std(self):
        self.assert_func_equal((6, 5), ht.mean, np.mean, rtol=1e-4, atol=1e-3)
        self.assert_func_equal(
            (3, 4, 5), ht.mean, np.mean, heat_args={"axis": 0}, numpy_args={"axis": 0},
            rtol=1e-4, atol=1e-3,
        )
        self.assert_func_equal(
            (6, 5), ht.var, np.var, data_types=(np.float32, np.float64), rtol=1e-3, atol=1e-2
        )
        self.assert_func_equal(
            (6, 5), ht.std, np.std, data_types=(np.float32, np.float64), rtol=1e-3, atol=1e-2
        )

    def test_min_max(self):
        self.assert_func_equal((4, 7), ht.min, np.min)
        self.assert_func_equal((4, 7), ht.max, np.max)
        self.assert_func_equal(
            (4, 7), ht.max, np.max, heat_args={"axis": 1}, numpy_args={"axis": 1}
        )
        self.assert_func_equal(
            (2, 3, 4), ht.min, np.min, heat_args={"axis": 2}, numpy_args={"axis": 2}
        )

    def test_prod_small_values(self):
        # |x| kept near 1 so the product neither overflows nor underflows
        self.assert_func_equal(
            (3, 4), ht.prod, np.prod,
            data_types=(np.float32, np.float64), low=-2, high=2, rtol=1e-3, atol=1e-3,
        )

    def test_argminmax_flat(self):
        # flat arg-reductions return the global index regardless of split
        self.assert_func_equal((5, 4), ht.argmax, np.argmax)
        self.assert_func_equal((5, 4), ht.argmin, np.argmin)


class TestManipulationMatrix(TestCase):
    def test_sort_flat_axes(self):
        self.assert_func_equal(
            (6, 4),
            lambda a, **k: ht.sort(a, **k)[0],
            np.sort,
            heat_args={"axis": 0},
            numpy_args={"axis": 0},
        )
        self.assert_func_equal(
            (6, 4),
            lambda a, **k: ht.sort(a, **k)[0],
            np.sort,
            heat_args={"axis": 1},
            numpy_args={"axis": 1},
        )

    def test_flip_roll(self):
        self.assert_func_equal(
            (4, 5), ht.flip, np.flip, heat_args={"axis": 0}, numpy_args={"axis": 0}
        )
        self.assert_func_equal(
            (4, 5), ht.roll, np.roll, heat_args={"shift": 2, "axis": 1},
            numpy_args={"shift": 2, "axis": 1},
        )
        self.assert_func_equal(
            (3, 4, 2), ht.roll, np.roll, heat_args={"shift": -1, "axis": 0},
            numpy_args={"shift": -1, "axis": 0},
        )

    def test_cumops(self):
        self.assert_func_equal(
            (5, 4), ht.cumsum, np.cumsum, heat_args={"axis": 0}, numpy_args={"axis": 0},
            rtol=1e-4, atol=1e-2,
        )
        self.assert_func_equal(
            (5, 4), ht.cumsum, np.cumsum, heat_args={"axis": 1}, numpy_args={"axis": 1},
            rtol=1e-4, atol=1e-2,
        )
        self.assert_func_equal(
            (4, 3), ht.cumprod, np.cumprod,
            data_types=(np.float32, np.float64),
            heat_args={"axis": 0}, numpy_args={"axis": 0},
            low=-2, high=2, rtol=1e-3, atol=1e-3,
        )

    def test_transpose_squeeze_expand(self):
        self.assert_func_equal((4, 6), ht.transpose, np.transpose)
        self.assert_func_equal(
            (2, 3, 4),
            ht.transpose,
            np.transpose,
            heat_args={"axes": (2, 0, 1)},
            numpy_args={"axes": (2, 0, 1)},
        )
        self.assert_func_equal(
            (3, 5),
            lambda a, **k: ht.expand_dims(a, **k),
            np.expand_dims,
            heat_args={"axis": 1},
            numpy_args={"axis": 1},
        )


class TestElementwiseMatrix(TestCase):
    def test_composed_chain(self):
        # a chain crossing several modules: rounding, exponential, trig
        def ht_chain(a):
            return ht.round(ht.exp(ht.sin(a / 100.0)) + ht.sqrt(ht.abs(a)))

        def np_chain(a):
            return np.round(np.exp(np.sin(a / 100.0)) + np.sqrt(np.abs(a)))

        self.assert_func_equal(
            (5, 6), ht_chain, np_chain, data_types=(np.float32, np.float64),
            rtol=1e-4, atol=1e-4,
        )

    def test_where_and_clip(self):
        self.assert_func_equal(
            (4, 5),
            lambda a: ht.where(a > 0, a, -a),
            lambda a: np.where(a > 0, a, -a),
        )
        self.assert_func_equal(
            (4, 5), ht.clip, np.clip,
            heat_args={"min": -10.0, "max": 10.0},
            numpy_args={"a_min": -10.0, "a_max": 10.0},
        )

    def test_logical_family(self):
        self.assert_func_equal((4, 4), lambda a: ht.logical_not(a > 0), lambda a: ~(a > 0))
        self.assert_func_equal(
            (4, 4),
            lambda a: ht.logical_and(a > 0, a < 100),
            lambda a: (a > 0) & (a < 100),
        )


class TestRaggedMatrix(TestCase):
    """The same sweeps at sizes indivisible by any mesh size 2..8."""

    def test_reductions_prime_sizes(self):
        self.assert_func_equal((7, 11), ht.sum, np.sum, rtol=1e-4, atol=1e-2)
        self.assert_func_equal(
            (11, 13), ht.mean, np.mean, heat_args={"axis": 0}, numpy_args={"axis": 0},
            rtol=1e-4, atol=1e-3,
        )
        self.assert_func_equal(
            (13, 7), ht.max, np.max, heat_args={"axis": 1}, numpy_args={"axis": 1}
        )

    def test_manipulations_prime_sizes(self):
        self.assert_func_equal(
            (11, 5),
            lambda a, **k: ht.sort(a, **k)[0],
            np.sort,
            heat_args={"axis": 0},
            numpy_args={"axis": 0},
        )
        self.assert_func_equal(
            (7, 9), ht.cumsum, np.cumsum, heat_args={"axis": 0}, numpy_args={"axis": 0},
            rtol=1e-4, atol=1e-2,
        )
        self.assert_func_equal((9, 7), ht.transpose, np.transpose)

#!/usr/bin/env python
"""Interactive heat_tpu console.

The reference ships an MPI-aware REPL (`/root/reference/scripts/interactive.py`)
whose whole job is prompt/stdin choreography across ranks under
``mpirun -stdin all``. Under heat_tpu's single-controller model there is
exactly one Python process no matter how many devices the mesh has, so a
plain interpreter suffices — the TPU rendering of "interactive" is a banner
that shows the live mesh and a preloaded namespace.

Run:  python scripts/interactive.py [--devices N]
      (--devices forces an N-device CPU mesh for experimentation)
"""

import argparse
import code
import os
import sys


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--devices",
        type=int,
        default=None,
        help="force an N-virtual-device CPU mesh (for trying out split semantics "
        "without accelerators)",
    )
    args = parser.parse_args()

    if args.devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}"
        )
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax

    import heat_tpu as ht

    comm = ht.get_comm()
    banner = (
        f"heat_tpu {ht.__version__} interactive console\n"
        f"mesh: {comm.size} x {jax.devices()[0].platform} "
        f"({', '.join(str(d) for d in comm.devices[:4])}"
        f"{', ...' if comm.size > 4 else ''})\n"
        f"preloaded: ht (heat_tpu), jax, jnp, np\n"
        f'try: ht.arange(12, split=0), ht.random.randn(4, 4), x.resplit_(1)'
    )

    import jax.numpy as jnp
    import numpy as np

    namespace = {"ht": ht, "jax": jax, "jnp": jnp, "np": np}
    try:
        import readline  # noqa: F401 - line editing when available
    except ImportError:  # pragma: no cover
        pass
    code.interact(banner=banner, local=namespace, exitmsg="")


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Local multi-process launcher/supervisor for the fault-tolerant runtime.

Spawns ``-n`` worker processes on this machine (each a full JAX controller
joined into one process-spanning mesh over loopback gloo), supervises
their lease files, and — when a worker dies or hangs — reforms: survivors
drain and exit ``REFORM_EXIT``, the launcher re-ranks them into a
contiguous smaller world under a bumped ``HEAT_TPU_MESH_EPOCH`` and a
fresh coordinator, and the respawned generation restores from the newest
verifying checkpoint. This is a thin CLI over
:func:`heat_tpu.core.multihost.spawn_local`; see
``doc/internals_distribution.md`` for the reform contract.

Everything after ``--`` is the worker command (run once per process with
the launcher's env applied). Without one, the default acceptance workload
``scripts/multiproc_trainer.py`` runs; trainer flags can follow ``--``
normally, e.g.::

    python scripts/launch_multiproc.py -n 2 -- \\
        python scripts/multiproc_trainer.py --steps 8 \\
            --ckpt-dir /tmp/ckpt --out /tmp/out

Deterministic chaos (for CI / bench): ``--kill-rank R --kill-at-step S``
SIGKILLs rank R from outside once its progress beacon passes step S;
``--kill-after-s T`` kills after a wall-clock delay instead. The launcher
prints the run summary as JSON and exits 0 only if the final generation
completed cleanly.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    worker_cmd = None
    if "--" in argv:
        split = argv.index("--")
        argv, worker_cmd = argv[:split], argv[split + 1 :]

    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    ap.add_argument("-n", "--num-processes", type=int, default=2)
    ap.add_argument("--mesh-dir", default=None,
                    help="shared coordination dir (default: a fresh tempdir)")
    ap.add_argument("--max-reforms", type=int, default=1)
    ap.add_argument("--devices-per-process", type=int, default=1)
    ap.add_argument("--barrier-timeout-ms", type=float, default=30_000.0)
    ap.add_argument("--heartbeat-ms", type=float, default=200.0)
    ap.add_argument("--peer-lost-ms", type=float, default=None)
    ap.add_argument("--timeout-s", type=float, default=600.0)
    ap.add_argument("--kill-rank", type=int, default=None)
    ap.add_argument("--kill-at-step", type=int, default=None)
    ap.add_argument("--kill-after-s", type=float, default=None)
    ap.add_argument("--quiet", action="store_true",
                    help="suppress worker stdout/stderr")
    args = ap.parse_args(argv)

    from heat_tpu.core import multihost

    if not worker_cmd:
        trainer = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "multiproc_trainer.py")
        scratch = args.mesh_dir or os.path.join("/tmp", f"heat_tpu_mp_{os.getpid()}")
        worker_cmd = [
            sys.executable, trainer,
            "--ckpt-dir", os.path.join(scratch, "ckpt"),
            "--out", os.path.join(scratch, "out"),
        ]

    kill = None
    if args.kill_rank is not None:
        kill = {"rank": args.kill_rank}
        if args.kill_at_step is not None:
            kill["at_step"] = args.kill_at_step
        elif args.kill_after_s is not None:
            kill["after_s"] = args.kill_after_s
        else:
            ap.error("--kill-rank needs --kill-at-step or --kill-after-s")

    result = multihost.spawn_local(
        args.num_processes,
        worker_cmd,
        mesh=args.mesh_dir,
        max_reforms=args.max_reforms,
        devices_per_process=args.devices_per_process,
        barrier_timeout_ms=args.barrier_timeout_ms,
        heartbeat_ms=args.heartbeat_ms,
        peer_lost_ms=args.peer_lost_ms,
        timeout_s=args.timeout_s,
        kill=kill,
        stdout=__import__("subprocess").DEVNULL if args.quiet else None,
    )
    json.dump(result, sys.stdout, indent=2, sort_keys=True, default=str)
    print()
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

"""Native line-coverage for heat_tpu (no coverage.py in this image).

The reference gates CI on codecov (reference codecov.yml:1-20,
Jenkinsfile:36-39); this module supplies the measurement half of that
subsystem with stdlib machinery: Python 3.12's ``sys.monitoring`` LINE
events. The callback returns ``sys.monitoring.DISABLE`` after recording a
location, so every (code object, line) fires AT MOST ONCE per process —
true line coverage at near-zero steady-state overhead (the same design
coverage.py adopts on 3.12+).

Usage (collector): set ``HEAT_TPU_COVERAGE=/path/out.json`` and run pytest —
``tests/conftest.py`` starts collection before heat_tpu is imported and
writes per-file executed-line sets at session end.

Usage (report): ``python scripts/heat_coverage.py merge out.json leg1.json
leg2.json ...`` unions executed lines across matrix legs and prints/writes
per-module percentages, flagging modules under 60%.

Executable-line sets come from compiling each source file and walking every
nested code object's ``co_lines()`` — the same line table the interpreter
reports against, so executed/executable are measured in the same units.
"""

from __future__ import annotations

import json
import os
import sys
from types import CodeType

_PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "heat_tpu")

_executed: dict = {}
_TOOL = None


def start() -> None:
    """Begin collection (idempotent). Must run before the measured package
    is imported only to catch module-level lines; functions imported earlier
    are still counted when they run."""
    global _TOOL
    if _TOOL is not None:
        return
    mon = sys.monitoring
    _TOOL = mon.COVERAGE_ID
    mon.use_tool_id(_TOOL, "heat-coverage")

    prefix = _PKG + os.sep

    def on_line(code: CodeType, line: int):
        fn = code.co_filename
        if fn.startswith(prefix) or fn == _PKG:
            _executed.setdefault(fn, set()).add(line)
        return mon.DISABLE  # each location reports once; cost amortizes out

    mon.register_callback(_TOOL, mon.events.LINE, on_line)
    mon.set_events(_TOOL, mon.events.LINE)


def dump(path: str) -> None:
    """Write the executed-line sets collected so far."""
    doc = {
        os.path.relpath(fn, os.path.dirname(_PKG)): sorted(lines)
        for fn, lines in _executed.items()
    }
    with open(path, "w") as fh:
        json.dump({"executed": doc}, fh)
        fh.write("\n")


def _executable_lines(path: str) -> set:
    """Every line number carried by the file's (nested) code objects."""
    with open(path, "r") as fh:
        src = fh.read()
    try:
        code = compile(src, path, "exec")
    except SyntaxError:
        return set()
    lines: set = set()
    stack = [code]
    while stack:
        c = stack.pop()
        for _, _, ln in c.co_lines():
            # drop the synthetic line-0 entries some 3.12 code objects carry
            # for their preamble: LINE events never report them, so keeping
            # them would inflate the denominator
            if ln:
                lines.add(ln)
        stack.extend(k for k in c.co_consts if isinstance(k, CodeType))
    return lines


def report(executed_by_file: dict) -> dict:
    """Per-module coverage over EVERY heat_tpu source file (files never
    imported count as 0%), plus the total and the <60% gap list."""
    root = os.path.dirname(_PKG)
    modules = []
    tot_exec = tot_avail = 0
    for dirpath, _, files in os.walk(_PKG):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root)
            avail = _executable_lines(full)
            hit = set(executed_by_file.get(rel, ())) & avail
            pct = round(100.0 * len(hit) / len(avail), 1) if avail else 100.0
            modules.append(
                {"module": rel, "lines": len(avail), "covered": len(hit), "pct": pct}
            )
            tot_exec += len(hit)
            tot_avail += len(avail)
    total_pct = round(100.0 * tot_exec / tot_avail, 1) if tot_avail else 100.0
    gaps = [m for m in modules if m["pct"] < 60.0]
    return {
        "total_pct": total_pct,
        "total_lines": tot_avail,
        "total_covered": tot_exec,
        "modules": modules,
        "below_60pct": [m["module"] for m in sorted(gaps, key=lambda m: m["pct"])],
    }


def merge_main(out_path: str, leg_paths: list) -> dict:
    merged: dict = {}
    legs = []
    for p in leg_paths:
        with open(p) as fh:
            doc = json.load(fh)
        legs.append(os.path.basename(p))
        for rel, lines in doc.get("executed", {}).items():
            merged.setdefault(rel, set()).update(lines)
    rep = report(merged)
    rep["legs"] = legs
    with open(out_path, "w") as fh:
        json.dump(rep, fh, indent=1)
        fh.write("\n")
    return rep


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "merge":
        argv = sys.argv[2:]
        fail_under = None
        if "--fail-under" in argv:  # the gate half of the reference's codecov
            i = argv.index("--fail-under")
            fail_under = float(argv[i + 1])
            del argv[i : i + 2]
        rep = merge_main(argv[0], argv[1:])
        print(
            f"total: {rep['total_pct']}% "
            f"({rep['total_covered']}/{rep['total_lines']} lines, "
            f"{len(rep['modules'])} modules; "
            f"{len(rep['below_60pct'])} below 60%)"
        )
        for m in rep["below_60pct"]:
            print(f"  <60%: {m}")
        if fail_under is not None and rep["total_pct"] < fail_under:
            print(f"FAIL: total {rep['total_pct']}% < --fail-under {fail_under}%")
            sys.exit(1)
    else:
        print(__doc__)
        sys.exit(2)

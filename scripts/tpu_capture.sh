#!/usr/bin/env bash
# One-shot real-TPU capture: run the moment the axon tunnel answers.
# Banks every TPU artifact the round tracks, most valuable first, so a
# tunnel that dies mid-way still leaves the earlier records on disk
# (the bench.py promotion logic then leads with them on any later run).
set -u
cd "$(dirname "$0")/.."
STAMP=$(date -u +%Y%m%dT%H%MZ)

echo "== probe =="
timeout 90 python -c "import jax; d=jax.devices(); print(d)" || {
  echo "tunnel down; aborting"; exit 1; }

echo "== 1. bench worker (full-size tracked configs; banks RESULTS_TPU_latest) =="
timeout 1500 python bench.py --_worker | tee "benchmarks/TPU_WORKER_${STAMP}.jsonl"
python - <<PYEOF
import json
import bench

lines = [l for l in open("benchmarks/TPU_WORKER_${STAMP}.jsonl") if l.strip().startswith("{")]
recs = [json.loads(l) for l in lines]
# bank only a COMPLETE record (bench.py's own invariant: a timeout-killed
# worker leaves a kmeans-only "partial" line that must never become the
# headline RESULTS_TPU_latest); the raw jsonl keeps whatever was measured
complete = [r for r in recs if not bench._is_incomplete(r)]
if complete:
    rec = complete[-1]
    bench.annotate_roofline(rec)
    bench._bank_tpu_record(rec)
    print("banked:", {k: rec.get(k) for k in ("metric", "value", "lloyd_path", "pct_hbm_roofline_kmeans")})
elif recs:
    print("worker died before a complete record; raw partial kept in the jsonl only")
else:
    print("worker produced no records")
PYEOF

echo "== 2. capability probe (roofline refinement) =="
timeout 900 python benchmarks/tpu_capability.py --out benchmarks/TPU_CAPABILITY.json || true

echo "== 3. training throughput on the chip =="
timeout 900 python benchmarks/train_throughput.py --platform default --model resnet18 \
  --batch 256 --steps 5 --out "benchmarks/TRAIN_THROUGHPUT_TPU_${STAMP}.json" || true

echo "== 4. long-context attention on the chip =="
timeout 900 python benchmarks/long_context.py --platform default --seqs 8192 32768 \
  --out "benchmarks/LONG_CONTEXT_TPU_${STAMP}.json" || true

echo "== done; git add the new benchmarks/ artifacts =="
ls -la benchmarks/ | tail -8

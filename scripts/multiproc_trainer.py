#!/usr/bin/env python
"""Deterministic multi-process trainer: the acceptance workload for the
fault-tolerant process-spanning runtime.

Launched by ``multihost.spawn_local`` / ``scripts/launch_multiproc.py``
(all world configuration arrives via the launcher environment —
``HEAT_TPU_COORDINATOR`` / ``HEAT_TPU_PROCESS_ID`` / ``HEAT_TPU_NUM_PROCESSES``
/ ``HEAT_TPU_MESH_DIR`` / ``HEAT_TPU_MESH_EPOCH``). The workload is linear
regression by full-batch gradient descent over a FIXED seeded global
dataset, rows sharded across every device of every process:

    w  <-  w - lr * X^T (X w - y) / rows

The gradient is a mean over the *global* rows, so the trajectory is
world-size invariant: a run that loses a process mid-training, restores
from the newest verifying checkpoint onto the shrunk world and replays,
must land on the same final ``w`` as an uninterrupted run (rtol 1e-5, the
kill-a-process acceptance pin). ``X^T r`` over row-sharded operands makes
XLA insert a real cross-process psum (gloo over DCN on a CPU mesh) into
the compiled step — this trainer IS the cross-process collective smoke.

Per step the worker: polls ``multihost.check_peers()`` (lease-daemon
declarations become control flow at the step boundary), publishes a
progress beacon (``multihost.note_progress`` — the launcher's chaos
injector and recovery timing read these), and commits a checkpoint through
``utils/checkpoint.py``'s cooperative manifest protocol every
``--checkpoint-every`` steps (its save/commit barriers run under the
launcher's barrier timeout).

On peer loss — a ``PeerLostError`` from the poll, a ``StallError`` from a
barrier, or a collective torn by the dying peer — the worker writes a
partial result record and exits ``multihost.REFORM_EXIT`` so the launcher
respawns the survivors into a smaller world.

Chaos hooks (deterministic, driven by the test matrix / bench):
``--die-rank R --die-at-step S`` SIGKILLs rank R from inside at step S;
``--hang-rank R --hang-at-step S`` stops beating and sleeps forever (the
zero-hang pin: survivors must surface a named error, and the launcher
reaps the hung child).

Results land as JSON at ``--out/result-epoch{E:04d}-rank{R:05d}.json``.
"""

import argparse
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
jax.config.update("jax_enable_x64", True)


def _result_path(out_dir: str, epoch: int, rank: int) -> str:
    return os.path.join(out_dir, f"result-epoch{epoch:04d}-rank{rank:05d}.json")


def _write_result(out_dir: str, doc: dict) -> None:
    os.makedirs(out_dir, exist_ok=True)
    path = _result_path(out_dir, doc["epoch"], doc["rank"])
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
    os.replace(tmp, path)


def _comm_failure(exc: BaseException) -> bool:
    msg = f"{type(exc).__name__}: {exc}".lower()
    return any(
        key in msg
        for key in (
            "gloo", "socket", "connection", "peer", "deadline", "barrier",
            "distributed", "coordination", "unavailable", "cancelled",
        )
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--checkpoint-every", type=int, default=2)
    ap.add_argument("--rows", type=int, default=64)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--out", required=True, help="result JSON directory")
    ap.add_argument("--die-rank", type=int, default=-1)
    ap.add_argument("--die-at-step", type=int, default=-1)
    ap.add_argument("--hang-rank", type=int, default=-1)
    ap.add_argument("--hang-at-step", type=int, default=-1)
    args = ap.parse_args()

    from heat_tpu.core import elastic, multihost, resilience
    from heat_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

    multihost.initialize_distributed()
    rank = multihost.process_index()
    world = multihost.process_count()
    epoch = multihost.mesh_epoch()
    lost_window_s = (
        float(os.environ.get("HEAT_TPU_PEER_LOST_MS", "1000") or 1000) / 1e3
    )

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("x",))
    row2 = NamedSharding(mesh, P("x", None))
    row1 = NamedSharding(mesh, P("x"))
    rep = NamedSharding(mesh, P())

    rows, dim, lr = args.rows, args.dim, args.lr
    rng = np.random.default_rng(7)  # identical on every process by design
    X_full = rng.standard_normal((rows, dim))
    y_full = rng.standard_normal((rows,))

    X = jax.make_array_from_callback((rows, dim), row2, lambda idx: X_full[idx])
    y = jax.make_array_from_callback((rows,), row1, lambda idx: y_full[idx])

    @jax.jit
    def train_step(w, X, y):
        r = X @ w - y
        g = X.T @ r / rows  # row-sharded contraction: the cross-process psum
        return jax.lax.with_sharding_constraint(w - lr * g, rep)

    start = 0
    resumed_from = None
    newest = elastic.newest_verified_step(args.ckpt_dir)
    if newest is not None:
        restored = load_checkpoint(args.ckpt_dir, {"w": np.zeros(dim)}, step=newest)
        w_np = np.asarray(restored["w"], dtype=np.float64)
        start = resumed_from = int(newest)
    else:
        w_np = np.zeros(dim)
    w = jax.make_array_from_callback((dim,), rep, lambda idx: w_np[idx])

    doc = {
        "rank": rank, "world": world, "epoch": epoch, "status": "reform",
        "resumed_from": resumed_from, "completed_steps": start,
        "t_first_step": None, "rate_steps_per_s": None, "final_w": None,
    }

    def exit_for_reform(exc: BaseException) -> "int":
        doc["error"] = f"{type(exc).__name__}: {exc}"
        _write_result(args.out, doc)
        # NOT sys.exit: atexit would run jax.distributed.shutdown(), whose
        # barrier blocks on the dead peer and then LOG(FATAL)s this survivor
        multihost.reform_exit()
        return multihost.REFORM_EXIT  # unreachable; keeps the signature honest

    t_after_first = None
    try:
        step = start
        while step < args.steps:
            multihost.check_peers()
            if rank == args.die_rank and step == args.die_at_step:
                os.kill(os.getpid(), signal.SIGKILL)
            if rank == args.hang_rank and step == args.hang_at_step:
                multihost.stop_heartbeat()  # go silent: peers must DETECT this
                time.sleep(3600)
            w = train_step(w, X, y)
            w.block_until_ready()
            step += 1
            doc["completed_steps"] = step
            multihost.note_progress(step)
            if doc["t_first_step"] is None:
                doc["t_first_step"] = t_after_first = time.time()
            if step % args.checkpoint_every == 0 and step < args.steps:
                try:
                    save_checkpoint(
                        args.ckpt_dir, {"w": np.asarray(w)}, step=step, keep=5
                    )
                except (multihost.PeerLostError, resilience.StallError):
                    raise
                except Exception as exc:  # noqa: BLE001 - ci-fault mix survivable
                    print(
                        f"[rank {rank}] checkpoint at step {step} skipped: {exc!r}",
                        file=sys.stderr,
                    )
    except (multihost.PeerLostError, resilience.StallError) as exc:
        return exit_for_reform(exc)
    except Exception as exc:  # noqa: BLE001 - a collective torn by a dying peer?
        deadline = time.time() + 2.0 * lost_window_s
        while time.time() < deadline and not multihost.lost_peers():
            time.sleep(0.05)
        if multihost.lost_peers() or _comm_failure(exc):
            return exit_for_reform(exc)
        raise

    doc["status"] = "done"
    if t_after_first is not None and step - start > 1:
        doc["rate_steps_per_s"] = round(
            (step - start - 1) / max(time.time() - t_after_first, 1e-9), 3
        )
    doc["final_w"] = np.asarray(w).tolist()
    _write_result(args.out, doc)
    multihost.stop_heartbeat()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""API parity check: every public name exported by the reference's __all__
lists must be reachable in heat_tpu (same top-level or submodule location).

Run:  python scripts/api_parity_check.py [/path/to/reference/heat]
Exit code 1 and a listing if anything is missing. Used by
tests/test_api_aliases.py when the reference checkout is present.
"""

import ast
import os
import sys

# Running as ``python scripts/api_parity_check.py`` puts scripts/ (not the
# repo root) on sys.path; do NOT touch PYTHONPATH for this — the axon
# backend registration rides on it.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

SUBMODULES = (
    "nn", "optim", "cluster", "spatial", "utils", "linalg", "random",
    "datasets", "classification", "naive_bayes", "regression", "graph",
)


def reference_names(ref_root):
    names = {}
    for dirpath, _, files in os.walk(ref_root):
        if "tests" in dirpath:
            continue
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                tree = ast.parse(open(path).read())
            except SyntaxError:  # pragma: no cover
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "__all__":
                            try:
                                vals = ast.literal_eval(node.value)
                            except Exception:  # pragma: no cover
                                continue
                            rel = os.path.relpath(path, ref_root)
                            for v in vals:
                                names.setdefault(v, []).append(rel)
    return names


def missing_names(ref_root):
    import heat_tpu as ht
    import heat_tpu.utils.data  # noqa: F401 - reachable data namespace

    out = []
    for name, sources in sorted(reference_names(ref_root).items()):
        if name.startswith("_"):
            continue
        found = hasattr(ht, name)
        if not found:
            for sub in SUBMODULES:
                mod = getattr(ht, sub, None)
                if mod is not None and hasattr(mod, name):
                    found = True
                    break
                if sub == "utils" and hasattr(ht.utils.data, name):
                    found = True
                    break
        if not found:
            out.append((name, sources[0]))
    return out


def main():
    ref_root = sys.argv[1] if len(sys.argv) > 1 else "/root/reference/heat"
    miss = missing_names(ref_root)
    total = len([n for n in reference_names(ref_root) if not n.startswith("_")])
    print(f"reference public names: {total}; missing in heat_tpu: {len(miss)}")
    for n, src in miss:
        print(f"  {n}  ({src})")
    sys.exit(1 if miss else 0)


if __name__ == "__main__":
    main()

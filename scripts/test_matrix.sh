#!/usr/bin/env bash
# Run the suite at mesh sizes 1/3/5/8 — the TPU-native analog of the
# reference CI's "mpirun -n 1,3,5,8 pytest heat/" matrix
# (reference Jenkinsfile:24-28; SURVEY.md §4) — with per-leg line coverage
# (the reference's codecov flags per world size, codecov.yml:1-20;
# Jenkinsfile:36-39) collected by scripts/heat_coverage.py and merged into
# one report at the end.
set -e
cd "$(dirname "$0")/.."
COV_DIR=${HEAT_TPU_COV_DIR:-/tmp/heat_cov}
mkdir -p "$COV_DIR"
legs=()
for size in ${@:-1 3 5 8}; do
  echo "=== mesh size $size ==="
  HEAT_TPU_TEST_DEVICES=$size \
  HEAT_TPU_COVERAGE="$COV_DIR/cov_mesh$size.json" \
    python -m pytest tests/ -q -x
  legs+=("$COV_DIR/cov_mesh$size.json")
done
# the coverage gate (reference codecov.yml target semantics): the merged
# matrix coverage must clear the floor or the matrix run fails
python scripts/heat_coverage.py merge "$COV_DIR/coverage_merged.json" \
  --fail-under "${HEAT_TPU_COV_MIN:-60}" "${legs[@]}"

#!/usr/bin/env bash
# Run the suite at mesh sizes 1/3/5/8 — the TPU-native analog of the
# reference CI's "mpirun -n 1,3,5,8 pytest heat/" matrix
# (reference Jenkinsfile:24-28; SURVEY.md §4).
set -e
cd "$(dirname "$0")/.."
for size in ${@:-1 3 5 8}; do
  echo "=== mesh size $size ==="
  HEAT_TPU_TEST_DEVICES=$size python -m pytest tests/ -q -x
done

#!/usr/bin/env bash
# Run the suite at mesh sizes 1/3/5/8 — the TPU-native analog of the
# reference CI's "mpirun -n 1,3,5,8 pytest heat/" matrix
# (reference Jenkinsfile:24-28; SURVEY.md §4) — with per-leg line coverage
# (the reference's codecov flags per world size, codecov.yml:1-20;
# Jenkinsfile:36-39) collected by scripts/heat_coverage.py and merged into
# one report at the end, plus a fusion-off leg that reruns the elementwise
# and eager-chain suites with HEAT_TPU_FUSION=0 so the deferred AND the
# eager engine paths both stay green.
set -e
cd "$(dirname "$0")/.."
COV_DIR=${HEAT_TPU_COV_DIR:-/tmp/heat_cov}
mkdir -p "$COV_DIR"
legs=()
for size in ${@:-1 3 5 8}; do
  echo "=== mesh size $size ==="
  HEAT_TPU_TEST_DEVICES=$size \
  HEAT_TPU_COVERAGE="$COV_DIR/cov_mesh$size.json" \
    python -m pytest tests/ -q -x -m "not slow"
  legs+=("$COV_DIR/cov_mesh$size.json")
done
# fusion leg: the eager engines (HEAT_TPU_FUSION=0 escape hatch) must match
# the recorded/fused default on the suites that exercise op chains
echo "=== fusion off (HEAT_TPU_FUSION=0) ==="
HEAT_TPU_FUSION=0 \
  python -m pytest tests/test_elementwise.py tests/test_eager_chain.py -q -x
# collective-fusion leg: HEAT_TPU_FUSION_COLLECTIVES=0 restores the
# force-at-collective behavior (resplit_/apply dispatch eagerly, no
# multi-root batching) — the escape hatch must keep the collective-spanning
# suites green and numerically identical
echo "=== collective fusion off (HEAT_TPU_FUSION_COLLECTIVES=0) ==="
HEAT_TPU_FUSION_COLLECTIVES=0 \
  python -m pytest tests/test_fused_collectives.py tests/test_eager_chain.py \
    tests/test_statistics.py tests/test_manipulations.py -q -x
# telemetry leg: the observability layer (HEAT_TPU_TELEMETRY=1) must change
# no results on the instrumented suites, and the overhead guard in
# tests/test_telemetry.py pins the enabled dispatch rate at >= 0.9x disabled
echo "=== telemetry on (HEAT_TPU_TELEMETRY=1) ==="
HEAT_TPU_TELEMETRY=1 \
  python -m pytest tests/test_telemetry.py tests/test_eager_chain.py tests/test_linalg_depth.py -q -x
# trace-timeline leg: the full verbose event log (timestamps, correlation
# ids, scoped sessions) stays green on the telemetry + trace suites, and an
# exported trace of a real reduction-chain run must parse as Chrome
# trace-event JSON (the CLI's validate-trace is the same check CI users run)
echo "=== telemetry verbose (HEAT_TPU_TELEMETRY=verbose) ==="
HEAT_TPU_TELEMETRY=verbose \
  python -m pytest tests/test_trace_timeline.py tests/test_telemetry.py -q -x
HEAT_TPU_TELEMETRY=verbose python - <<'PY'
import numpy as np, heat_tpu as ht
from heat_tpu.core import telemetry
a = ht.array(np.random.default_rng(0).standard_normal(
    (8 * ht.get_comm().size, 3)).astype(np.float32), split=0)
float(ht.mean(a)) + float(ht.std(a))  # dispatch + blocking sync on the timeline
telemetry.export_trace("/tmp/heat_tpu_matrix_trace.json")
PY
HEAT_TPU_TELEMETRY=verbose \
  python -m heat_tpu.telemetry validate-trace /tmp/heat_tpu_matrix_trace.json
# tracelens leg (ISSUE 13): the exported+validated trace runs through the
# post-hoc analyzer — the JSON output must parse with full attribution
# coverage (every bucket accounted, explicit unattributed remainder <= 5%)
# and ZERO findings on this clean workload; `analyze` itself exits nonzero
# on any warning/error finding, and the python step re-checks the shape
python -m heat_tpu.telemetry analyze /tmp/heat_tpu_matrix_trace.json --json \
  > /tmp/heat_tpu_matrix_analysis.json
python - <<'PY'
import json
doc = json.load(open("/tmp/heat_tpu_matrix_analysis.json"))
assert doc["attribution"]["overall"], "analyze produced no attribution buckets"
assert doc["attribution"]["unattributed_pct"] <= 5.0, \
    f"unattributed {doc['attribution']['unattributed_pct']}% > 5%"
assert doc["findings"] == [], f"clean workload produced findings: {doc['findings']}"
print("analyze OK:", {b: rec["pct"] for b, rec in doc["attribution"]["overall"].items()})
PY
# whole-algorithm fusion leg (ISSUE 20): the estimator suites run with
# collective fusion on (the default), then a real reduce-then-matmul
# iteration loop is traced and budget-checked — steady state must be ONE
# program dispatch and at most one blocking sync per iteration — and the
# trace goes through the post-hoc analyzer with ZERO findings; the same
# suites stay green under HEAT_TPU_FUSION_COLLECTIVES=0 and the ambient
# fault mix (deferral must not change results or swallow faults)
echo "=== whole-algorithm fusion (estimator suites + dispatch/sync budget) ==="
python -m pytest tests/test_whole_algorithm_fusion.py tests/test_lloyd_fused.py \
  tests/test_ml.py -q -x
HEAT_TPU_TELEMETRY=verbose python - <<'PY'
import numpy as np
import heat_tpu as ht
from heat_tpu.core import telemetry

p = ht.get_comm().size
rng = np.random.default_rng(0)
x = ht.array(rng.standard_normal((8 * p, 4 * p)).astype(np.float32), split=0)
w = ht.array(rng.standard_normal((4 * p, 2 * p)).astype(np.float32))

def step():
    mu = ht.mean(x)  # split-crossing psum node
    return float(ht.sum((x - mu) @ w))  # matmul node + reduction, ONE read

step(); step()  # warm: compiles land, steady state begins
telemetry.reset()
iters = 5
for _ in range(iters):
    step()
stats = telemetry.async_forcing()
assert stats["dispatches"] == iters, f"not 1 dispatch/iteration: {stats}"
assert stats["blocking_total"] <= iters, f">1 blocking sync/iteration: {stats}"
telemetry.export_trace("/tmp/heat_tpu_whole_algo_trace.json")
print("whole-algorithm budget OK:", {k: stats[k] for k in
      ("dispatches", "blocking_total", "multi_root_batches")})
PY
python -m heat_tpu.telemetry analyze /tmp/heat_tpu_whole_algo_trace.json --json \
  > /tmp/heat_tpu_whole_algo_analysis.json
python - <<'PY'
import json
doc = json.load(open("/tmp/heat_tpu_whole_algo_analysis.json"))
assert doc["findings"] == [], \
    f"whole-algorithm trace produced findings: {doc['findings']}"
print("whole-algorithm analyze OK")
PY
echo "=== whole-algorithm fusion: collectives-off + faults legs ==="
HEAT_TPU_FUSION_COLLECTIVES=0 \
  python -m pytest tests/test_whole_algorithm_fusion.py tests/test_lloyd_fused.py -q -x
HEAT_TPU_FAULTS=ci HEAT_TPU_TELEMETRY=1 \
  python -m pytest tests/test_whole_algorithm_fusion.py -q -x
# memory-observability leg: the headroom admission gate is ARMED (a generous
# fraction of host memory under the warn policy — every fused dispatch pays
# the live-ledger check without any policy actually firing) while the memory
# suite and the eager-chain suite run; the gate/ledger/forensics must change
# no results and the suite's own warn|raise|drain pins stay exact (tests
# re-arm their own budgets per test and restore the ambient one)
echo "=== memory observability (HEAT_TPU_MEMORY_BUDGET armed) ==="
HEAT_TPU_MEMORY_BUDGET=0.95 HEAT_TPU_MEMORY_POLICY=warn HEAT_TPU_TELEMETRY=1 \
  python -m pytest tests/test_memory_obs.py tests/test_eager_chain.py -q -x
# resilience leg: the suite runs under the deterministic ambient fault mix
# (core/resilience.py 'ci' preset: fused compiles/executes fail periodically
# and degrade to eager, transient io errors are retried, checkpoint
# write/commit/restore attempts absorb transient faults and gc deletions
# degrade to debris-for-the-next-sweep) — recovery is proven by the suite
# simply staying green while faults fire. Explicit inject() scopes suspend
# the ambient specs, so exact-count pins stay exact; the checkpoint suite's
# kill-mid-save resume loop runs here too (ISSUE 4 acceptance).
echo "=== faults injected (HEAT_TPU_FAULTS=ci) ==="
HEAT_TPU_FAULTS=ci HEAT_TPU_TELEMETRY=1 \
  python -m pytest tests/test_resilience.py tests/test_resilience_io.py tests/test_io_errors.py \
    tests/test_checkpoint_resilience.py tests/test_checkpoint_profiling.py \
    tests/test_fused_collectives.py tests/test_trace_timeline.py \
    tests/test_memory_obs.py tests/test_tracelens.py tests/test_numlens.py -q -x
# SDC-injection smoke: arm the numeric.sdc fault site on one device and prove
# the sentinel NAMES it — the true-positive path of the canary, end to end
# through the quarantine ledger (tests pin the MeshDegradedWarning escalation)
echo "=== SDC sentinel smoke (HEAT_TPU_FAULTS='numeric.sdc.0:every=1') ==="
HEAT_TPU_FAULTS='numeric.sdc.0:every=1' HEAT_TPU_NUMLENS=full python - <<'PY'
import numpy as np, heat_tpu as ht
from heat_tpu.core import numlens
float(ht.sum(ht.array(np.ones(8, np.float32), split=0)))  # bring the mesh up
r = numlens.run_canary()
assert r is not None and r["mismatches"], f"sentinel missed the sick device: {r}"
sdc = [f for f in numlens.findings() if f["rule"] == "numlens.sdc"]
assert sdc, "no numlens.sdc finding emitted"
print("SDC sentinel OK:", sdc[0]["device"])
PY
# numerics-lens leg: the numerics observability layer ARMED in sampling mode
# (every fused dispatch pays the hook check, sampled dispatches pay the jitted
# stats kernel + periodic shadow replay) while the numlens suite and the
# eager-chain suite run — the lens must change no results; the suite's own
# overhead/never-forces/never-initializes pins run armed too
echo "=== numerics lens (HEAT_TPU_NUMLENS=sample) ==="
HEAT_TPU_NUMLENS=sample HEAT_TPU_TELEMETRY=1 \
  python -m pytest tests/test_numlens.py tests/test_eager_chain.py -q -x
# runtime-health leg (core/health_runtime.py): flight recorder ARMED with a
# small ring and the stall watchdog live under the warn policy (every fused
# dispatch and blocking sync pays the guard arm/disarm and the ring append)
# while the health suite and the eager-chain suite run — the recorder,
# watchdog and latency histograms must change no results, and the suite's
# own trip/dump/percentile pins stay exact
echo "=== runtime health (HEAT_TPU_FLIGHT=1, watchdog armed) ==="
HEAT_TPU_FLIGHT=1 HEAT_TPU_FLIGHT_EVENTS=512 HEAT_TPU_WATCHDOG_POLICY=warn \
HEAT_TPU_TELEMETRY=1 \
  python -m pytest tests/test_health_runtime.py tests/test_eager_chain.py -q -x
# elasticity leg (core/elastic.py): the suite runs with the ambient
# elastic.preempt fault site firing periodically — every 7th poll of
# Supervisor.maybe_preempt() reports a preemption, so the drain → commit →
# reform → resume cycle executes for real while the elastic suite and the
# checkpoint-resilience suite run. Explicit inject()/suspended() scopes
# suspend the ambient spec, so the suites' exact-count pins stay exact; the
# kill-a-host DASO test (full-vs-shrunk trajectory match) runs here too.
echo "=== elasticity (HEAT_TPU_FAULTS='elastic.preempt:every=7') ==="
HEAT_TPU_FAULTS='elastic.preempt:every=7' HEAT_TPU_TELEMETRY=1 \
  python -m pytest tests/test_elastic.py tests/test_checkpoint_resilience.py -q -x
# multi-process runtime leg (core/multihost.py, ISSUE 19): REAL coordinated
# worker processes — a 2-process mesh over loopback gloo, supervised across
# reform generations. The slow-marked suite (excluded from the mesh loop
# above) runs under the ambient CI fault mix, which the launcher propagates
# into every worker's environment: cross-process collectives, world-size
# invariance, SIGKILL-mid-step reform with checkpoint-equality, the
# hung-peer drain watchdog. Then the launcher CLI drives one SIGKILL chaos
# run end to end: kill rank 1 mid-step, the survivor drains with
# REFORM_EXIT, and the reformed 1-process world restores from the newest
# verifying checkpoint and completes.
echo "=== multi-process runtime (2-proc gloo mesh, -m slow, HEAT_TPU_FAULTS=ci) ==="
HEAT_TPU_FAULTS=ci python -m pytest tests/test_multiproc.py -q -x -m slow
MP_SCRATCH=$(mktemp -d)
python scripts/launch_multiproc.py -n 2 --max-reforms 1 \
  --kill-rank 1 --kill-at-step 3 --quiet -- \
  python scripts/multiproc_trainer.py --steps 8 --checkpoint-every 2 \
    --ckpt-dir "$MP_SCRATCH/ckpt" --out "$MP_SCRATCH/out" \
  > "$MP_SCRATCH/result.json"
python - "$MP_SCRATCH" <<'PY'
import glob, json, os, sys
scratch = sys.argv[1]
r = json.load(open(os.path.join(scratch, "result.json")))
assert r["ok"] and r["reforms"] == 1, r
docs = [json.load(open(p))
        for p in glob.glob(os.path.join(scratch, "out", "result-*.json"))]
final = [d for d in docs if d["status"] == "done"]
assert final and final[0]["resumed_from"] is not None, docs
print("multiproc leg: reform OK, resumed from step", final[0]["resumed_from"])
PY
rm -rf "$MP_SCRATCH"
# serving leg (core/serving.py, ISSUE 15): the multi-tenant session layer —
# the suite drives N=8 threaded clients through session isolation, admission
# gates and cross-session batching (zero steady-state retraces, flat p99);
# then the persistent program cache's cross-process contract runs for real:
# a COLD process populates HEAT_TPU_PROGRAM_CACHE_DIR, and a second WARM
# process replaying the same chain must record ZERO compiles (disk warm
# start — ROADMAP item 4's fresh-process acceptance)
echo "=== serving (sessions + admission + persistent cache) ==="
python -m pytest tests/test_serving.py -q -x
SERVING_CACHE_DIR=$(mktemp -d)
for leg_name in cold warm; do
  echo "--- $leg_name process ---"
  HEAT_TPU_PROGRAM_CACHE_DIR="$SERVING_CACHE_DIR" SERVING_LEG=$leg_name \
  python - <<'PY'
import json, os
import numpy as np
import heat_tpu as ht
from heat_tpu.core import serving

a = ht.array(np.arange(48, dtype=np.float32), split=0)
float(ht.sum(a * 5.0 + 2.0))
stats = serving.cache_stats()
leg = os.environ["SERVING_LEG"]
print(f"{leg}: compiles={stats['compiles']} disk_hits={stats['disk_hits']} "
      f"index_keys={stats['index_keys']}")
if leg == "cold":
    assert stats["compiles"] >= 1, f"cold process compiled nothing: {stats}"
    assert stats["index_keys"] >= 1, f"cold process banked no keys: {stats}"
else:
    assert stats["compiles"] == 0, f"warm process recompiled: {stats}"
    assert stats["disk_hits"] >= 1, f"warm process missed the index: {stats}"
PY
done
rm -rf "$SERVING_CACHE_DIR"
# ops-plane leg (core/opsplane.py, ISSUE 17): the live ops endpoint ARMED
# while N=8 threaded tenants drive real traffic — mid-traffic scrapes of
# /metrics + /healthz must be thread-safe and the exposition must pass the
# strict parser check (types, HELP lines, no duplicate samples, schema'd
# names only), exactly as a sidecar Prometheus would see it
echo "=== ops plane (HEAT_TPU_OPS_PORT armed during serving traffic) ==="
HEAT_TPU_OPS_PORT=0 python -m pytest tests/test_opsplane.py -q -x
HEAT_TPU_OPS_PORT=0 python - <<'PY'
import io, threading, urllib.request
import numpy as np
import heat_tpu as ht
from heat_tpu.core import opsplane, serving
import heat_tpu.telemetry as cli

port = opsplane.status()["port"]
assert port, "HEAT_TPU_OPS_PORT=0 did not arm the ops server"

def chain(arr, k):
    return ht.sum(arr * k + 1.0)

arrs = [
    ht.array(
        np.random.default_rng(i).normal(size=(256,)).astype(np.float32), split=0
    )
    for i in range(8)
]
# prebake every batch-size signature so steady state never retraces
for k in range(1, 9):
    outs = [chain(arrs[j], 1.0 + j * 0.25) for j in range(k)]
    for o in outs:
        float(o)

barrier = threading.Barrier(9)
errors = []

def client(i):
    try:
        with serving.Session(f"matrix{i}"):
            barrier.wait(timeout=30)
            for r in range(30):
                float(chain(arrs[i], 1.0 + r * 0.25))
    except Exception as exc:
        errors.append(exc)

threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
for t in threads:
    t.start()
barrier.wait(timeout=30)
# mid-traffic: the strict check (parser-valid /metrics + /healthz 200)
out = io.StringIO()
rc = cli.main(["ops", "check", "--port", str(port)], out=out)
print(out.getvalue().rstrip())
assert rc == 0, f"mid-traffic ops check failed:\n{out.getvalue()}"
with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
    text = r.read().decode()
assert 'tenant="matrix' in text, "no per-tenant counters on /metrics mid-traffic"
for t in threads:
    t.join(timeout=120)
assert not errors, errors
with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
    problems = opsplane.validate_exposition(r.read().decode())
assert not problems, problems
print("ops leg: mid-traffic scrape clean, per-tenant labels present")
PY
# autoscale leg (core/autoscale.py, ISSUE 18): the overload controller
# ARMED while 8 bursty mixed-tier tenants (4 interactive + 4 batch) drive
# traffic through an injected SLO burn — the loop must shed batch (typed
# ShedError, chains stay pending), hold every interactive request green,
# and walk shed -> cooldown -> recover with a bounded decision count
echo "=== autoscale (controller armed under bursty mixed-tier overload) ==="
python -m pytest tests/test_autoscale.py -q -x
python - <<'PY'
import threading, time
import numpy as np
import heat_tpu as ht
from heat_tpu.core import autoscale, health_runtime, opsplane, serving

warm = ht.array(np.arange(32, dtype=np.float32), split=0)
float(ht.sum(warm * 2.0))  # mesh + program warm
health_runtime.set_slo(dispatch_ms=1.0)
opsplane.set_burn(target=0.9, fast_s=1.0, slow_s=4.0, threshold=1.0,
                  min_samples=4)
ctl = autoscale.arm(interval_s=60.0, cooldown_s=0.3, shrink_after_s=3600.0)

# injected latency fault fires the burn alert; the controller sheds batch
for _ in range(16):
    health_runtime._slo_observe("dispatch", 0.05)
opsplane.sample()
assert autoscale.poll() == "shed_on", autoscale.stats()

interactive_errors, shed_hits = [], []
barrier = threading.Barrier(8)

def interactive(i):
    try:
        barrier.wait(timeout=30)
        with serving.Session(f"fg{i}", tier="interactive", deadline_ms=100.0):
            a = ht.array(np.random.default_rng(i).normal(
                size=(64,)).astype(np.float32), split=0)
            for r in range(8):
                float(ht.sum(a * (1.0 + r)))
    except Exception as exc:
        interactive_errors.append(exc)

def batch(i):
    barrier.wait(timeout=30)
    with serving.Session(f"bg{i}", tier="batch"):
        a = ht.array(np.random.default_rng(100 + i).normal(
            size=(64,)).astype(np.float32), split=0)
        for r in range(8):
            try:
                float(ht.sum(a * (1.0 + r)))
            except serving.ShedError:
                shed_hits.append(i)

threads = [threading.Thread(target=interactive, args=(i,)) for i in range(4)]
threads += [threading.Thread(target=batch, args=(i,)) for i in range(4)]
for t in threads:
    t.start()
for t in threads:
    t.join(timeout=120)
assert not interactive_errors, f"interactive failed mid-overload: {interactive_errors}"
assert shed_hits, "no batch dispatch was shed under overload"

# burn clears + cooldown passes -> recovery; batch dispatches cleanly again
time.sleep(1.1)
opsplane.sample()
autoscale.poll()
time.sleep(0.35)
assert autoscale.poll() in ("shed_off", "recover"), autoscale.stats()
with serving.Session("bg-after", tier="batch"):
    float(ht.sum(warm * 3.0))
d = autoscale.stats()["decisions"]
assert d["shed_on"] == 1 and d["shed_off"] == 1 and d["errors"] == 0, d
autoscale.disarm()
health_runtime.set_slo(dispatch_ms=None)
print(f"autoscale leg: 0 interactive failures, {len(shed_hits)} batch "
      f"sheds, decisions={d}")
PY
# bench regression-sentinel smoke: the file-vs-file compare path (no jax,
# no measurement) must accept a banked round artifact against itself —
# exercises record loading, envelope unwrap and threshold plumbing
echo "=== bench sentinel smoke (--against/--record) ==="
python bench.py --against BENCH_r05.json --record BENCH_r05.json
# static-analysis leg (heat_tpu/analysis): the AST lint must be clean
# against the committed baseline (zero NEW findings — suppressions carry
# their justifications inline), the AOT program auditor over a cache
# warmed with the bench-shaped workloads at mesh 8 must report zero
# replication-blowup / collective-parity / budget findings, and the
# distribution-flow verifier (interprocedural split/sharding abstract
# interpretation, rules S101-S105) must verify the library + examples
# clean against the same (namespace-shared) baseline
echo "=== static analysis (heat-lint + program audit + heat-verify) ==="
python -m heat_tpu.analysis lint heat_tpu examples --baseline heat-lint-baseline.json
python -m heat_tpu.analysis audit --warm bench --devices 8
python -m heat_tpu.analysis verify heat_tpu examples --baseline heat-lint-baseline.json
# the coverage gate (reference codecov.yml target semantics): the merged
# matrix coverage must clear the floor or the matrix run fails. On runtimes
# without sys.monitoring (Python < 3.12) no cov_mesh*.json legs are produced
# — a green matrix must not then die on a FileNotFoundError, so the merge
# only runs over legs that actually exist and is skipped when there are none.
produced=()
for leg in "${legs[@]}"; do
  [ -f "$leg" ] && produced+=("$leg")
done
if [ "${#produced[@]}" -eq 0 ]; then
  if python -c 'import sys; sys.exit(0 if sys.version_info >= (3, 12) else 1)'; then
    # sys.monitoring IS available here — zero legs means the coverage
    # pipeline itself broke (e.g. the atexit dump failing); fail loudly
    echo "ERROR: no coverage legs produced although Python >= 3.12 supports sys.monitoring" >&2
    exit 1
  fi
  echo "coverage: no cov_mesh*.json legs produced (sys.monitoring needs Python >= 3.12); skipping merge/gate"
else
  python scripts/heat_coverage.py merge "$COV_DIR/coverage_merged.json" \
    --fail-under "${HEAT_TPU_COV_MIN:-60}" "${produced[@]}"
fi

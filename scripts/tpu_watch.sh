#!/bin/bash
# Tunnel watcher: waits for the axon TPU tunnel to answer, then captures the
# remaining round-4 window stages (attention marginals, cdist marginal) and
# finishes with one fresh full bench.py so the official record carries the
# dispatch-cost-cancelled roofline fields. Safe to re-run; exits after DONE.
cd "$(dirname "$0")/.." || exit 1
for i in $(seq 1 "${TPU_WATCH_TRIES:-40}"); do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "=== tunnel up, attempt $i $(date -u +%H:%M:%S) ===" >> /tmp/tpu_watch.log
    timeout 1800 python benchmarks/tpu_window.py \
      --out benchmarks/TPU_WINDOW_r04.json --force \
      --stages attention,cdist,train50,train_bf16,attention_sweep,capability,lloyd_bf16 \
      >> /tmp/tpu_watch.log 2>&1
    if python - <<'PY'
import json, sys
d = json.load(open("benchmarks/TPU_WINDOW_r04.json"))
ok = lambda s: isinstance(s, dict) and s and not any("error" in k for k in s)
sys.exit(
    0
    if ok(d.get("attention", {})) and ok(d.get("cdist", {})) and ok(d.get("train50", {}))
    else 1
)
PY
    then
      echo "=== stages banked, running fresh bench ===" >> /tmp/tpu_watch.log
      # per-attempt log: the shared append-log would let an OLD attempt's
      # record satisfy the gate for a new, failed one
      BLOG="/tmp/tpu_watch_bench_$i.log"
      timeout 2700 python bench.py > "$BLOG" 2>&1
      # DONE only when the bench actually produced a FRESH live-TPU record —
      # bench's ladder reprints the committed (stale) capture over a CPU
      # fallback, and that reprint must NOT satisfy this gate
      if BLOG="$BLOG" python - <<'PY'
import json, os, sys
rec = None
try:
    for line in open(os.environ["BLOG"]):
        line = line.strip()
        if line.startswith("{"):
            try:
                cand = json.loads(line)
            except Exception:
                continue
            if (
                isinstance(cand, dict)
                and cand.get("platform") == "tpu"
                and not any(
                    k in cand
                    for k in ("staleness", "reprinted_over_cpu_fallback", "provisional")
                )
            ):
                rec = cand
except FileNotFoundError:
    pass
sys.exit(0 if rec else 1)
PY
      then
        echo DONE >> /tmp/tpu_watch.log
        break
      fi
      echo "=== bench produced no TPU record; retrying ===" >> /tmp/tpu_watch.log
    fi
  fi
  sleep 280
done

#!/bin/bash
# Tunnel watcher (round 5): waits for the axon TPU tunnel to answer, then
# captures the FULL round-5 window ladder — including every stage the r04
# verdict flagged as never-run (lloyd_bf16, cdist, attention,
# attention_sweep, train50, train_bf16) plus the new qr_marginal and
# moments_diag diagnostics — and finishes with one fresh full bench.py so
# the official record describes the FINAL tree, live, with the
# dispatch-cost-cancelled roofline fields. Safe to re-run; exits after DONE.
cd "$(dirname "$0")/.." || exit 1
OUT=benchmarks/TPU_WINDOW_r05.json
for i in $(seq 1 "${TPU_WATCH_TRIES:-40}"); do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "=== tunnel up, attempt $i $(date -u +%H:%M:%S) ===" >> /tmp/tpu_watch.log
    timeout 3000 python benchmarks/tpu_window.py --out "$OUT" \
      >> /tmp/tpu_watch.log 2>&1
    if OUT="$OUT" python - <<'PY'
import json, os, sys
d = json.load(open(os.environ["OUT"]))
ok = lambda s: isinstance(s, dict) and s and not any("error" in k for k in s)
needed = [
    "init", "lloyd_full", "lloyd_bf16", "capability", "cholqr2", "qr_marginal",
    "cdist", "moments_diag", "attention", "attention_sweep", "train50",
    "train_bf16",
]
sys.exit(0 if all(ok(d.get(s, {})) for s in needed) else 1)
PY
    then
      echo "=== all required stages banked, running fresh bench ===" >> /tmp/tpu_watch.log
      # per-attempt log: the shared append-log would let an OLD attempt's
      # record satisfy the gate for a new, failed one
      BLOG="/tmp/tpu_watch_bench_$i.log"
      timeout 2700 python bench.py > "$BLOG" 2>&1
      # DONE only when the bench actually produced a FRESH live-TPU record —
      # bench's ladder reprints the committed (stale) capture over a CPU
      # fallback, and that reprint must NOT satisfy this gate
      if BLOG="$BLOG" python - <<'PY'
import json, os, sys
rec = None
try:
    for line in open(os.environ["BLOG"]):
        line = line.strip()
        if line.startswith("{"):
            try:
                cand = json.loads(line)
            except Exception:
                continue
            if (
                isinstance(cand, dict)
                and cand.get("platform") == "tpu"
                and not any(
                    k in cand
                    for k in ("staleness", "reprinted_over_cpu_fallback", "provisional")
                )
            ):
                rec = cand
except FileNotFoundError:
    pass
sys.exit(0 if rec else 1)
PY
      then
        echo DONE >> /tmp/tpu_watch.log
        break
      fi
      echo "=== bench produced no TPU record; retrying ===" >> /tmp/tpu_watch.log
    fi
  fi
  sleep 280
done

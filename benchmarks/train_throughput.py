"""Training-stack throughput: DP and DASO samples/s (BASELINE config 5).

The reference's training raison d'être is nn.DataParallel + optim.DASO
(reference heat/optim/dp_optimizer.py:432-475,592-650 — the skip-schedule
cadence is the whole point of DASO); BASELINE.md tracks it as config 5
(ResNet/CIFAR). This harness measures both trainers on CIFAR-shaped
synthetic data (32x32x3, 10 classes) and reports:

  * dp_samples_per_sec        — nn.DataParallel fused jitted step
  * daso_sweep                — samples/s at each (global_skip, local_skip)
                                cadence point, incl. full-sync (0, 0): the
                                ici/dcn sweep showing what skipping buys
  * step-time breakdown       — device placement vs compiled compute vs
                                host overhead, so a tunnel-RTT-dominated
                                number is diagnosable from the artifact

Defaults run on the 8-device forced-CPU mesh (the CI topology); on a live
TPU backend run with --platform default. Usage:

    python benchmarks/train_throughput.py [--devices 8] [--platform cpu]
        [--batch 64] [--steps 6] [--model resnet18|cnn] [--out FILE]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--platform", default="cpu", choices=["cpu", "default"])
    parser.add_argument("--batch", type=int, default=64, help="global batch size")
    parser.add_argument("--steps", type=int, default=6, help="timed steps per config")
    parser.add_argument("--model", default="resnet18", choices=["resnet18", "cnn"])
    parser.add_argument("--out", default=None)
    args = parser.parse_args()

    if args.platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={args.devices}".strip()
            )

    import jax

    if args.platform == "cpu":
        # the axon site hook overrides JAX_PLATFORMS; only a config update
        # after import actually selects the CPU backend
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    import optax

    import heat_tpu as ht
    from heat_tpu.nn import DataParallel, ResNet18, SimpleCNN
    from heat_tpu.optim import DASO

    comm = ht.get_comm()
    n_dev = comm.size
    doc = {
        "config": "BASELINE.md config 5 (synthetic CIFAR-shaped data)",
        "platform": comm.devices[0].platform,
        "devices": n_dev,
        "model": args.model,
        "global_batch": args.batch,
        "timed_steps": args.steps,
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }

    rng = np.random.default_rng(0)
    batch = args.batch // n_dev * n_dev or n_dev
    x_np = rng.standard_normal((batch, 32, 32, 3)).astype(np.float32)
    y_np = rng.integers(0, 10, size=batch).astype(np.int32)

    module = ResNet18(num_classes=10) if args.model == "resnet18" else SimpleCNN(num_classes=10)

    def timed_steps(step_fn, n):
        """Best-of-two mean step time over n steps (first call outside)."""
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(n):
                step_fn()
            best = min(best, (time.perf_counter() - t0) / n)
        return best

    # ---- DataParallel ----------------------------------------------------
    dp = DataParallel(module, comm=comm, optimizer=optax.sgd(0.05))
    dp.init(0, x_np[: max(n_dev, 2)])
    dp.train_step(x_np, y_np)  # compile
    dp_step_s = timed_steps(lambda: dp.train_step(x_np, y_np), args.steps)
    doc["dp_samples_per_sec"] = round(batch / dp_step_s, 1)
    doc["dp_step_ms"] = round(dp_step_s * 1e3, 2)

    # breakdown: placement cost vs compiled compute. The product step calls
    # _ensure_split (a device_put) then the jitted program; timing the jitted
    # program on pre-placed operands isolates the compute.
    from heat_tpu.core.dndarray import _ensure_split

    xb = _ensure_split(jnp.asarray(x_np), 0, comm)
    yb = _ensure_split(jnp.asarray(y_np), 0, comm)
    t_place = timed_steps(
        lambda: (_ensure_split(jnp.asarray(x_np), 0, comm), _ensure_split(jnp.asarray(y_np), 0, comm)),
        args.steps,
    )

    def compute_only():
        if dp._stateful:
            p, s, o, loss = dp._train_step(dp.params, dp.state, dp.opt_state, xb, yb)
        else:
            p, o, loss = dp._train_step(dp.params, dp.opt_state, xb, yb)
        float(loss)

    compute_only()
    t_compute = timed_steps(compute_only, args.steps)
    doc["dp_breakdown_ms"] = {
        "placement": round(t_place * 1e3, 2),
        "compiled_step": round(t_compute * 1e3, 2),
        "host_overhead": round(max(dp_step_s - t_place - t_compute, 0.0) * 1e3, 2),
    }

    # ---- DASO cadence sweep ---------------------------------------------
    # (global_skip, local_skip) points: (0,0) is full synchronization (every
    # batch: ICI grad allreduce + DCN merge); (4,1) is the reference's
    # post-warmup operating point; (8,2) the max-skip steady state.
    sweep = []
    for gs, ls in ((0, 0), (2, 1), (4, 1), (8, 2)):
        daso = DASO(
            optax.sgd(0.05),
            total_epochs=10,
            comm=comm,
            warmup_epochs=0,
            cooldown_epochs=0,
            verbose=False,
        )
        daso.add_model(module, 0, x_np[: max(n_dev, 2)])
        daso.global_skip = gs
        daso.local_skip = ls
        daso.batches_to_wait = 1 if gs else 0
        daso.step(x_np, y_np)  # compile both solo and synced programs
        daso.step(x_np, y_np)
        step_s = timed_steps(lambda: daso.step(x_np, y_np), args.steps)
        sweep.append(
            {
                "global_skip": gs,
                "local_skip": ls,
                "samples_per_sec": round(batch / step_s, 1),
                "step_ms": round(step_s * 1e3, 2),
                "solo_steps_seen": daso._solo_steps,
            }
        )
    doc["daso_sweep"] = sweep
    full_sync = sweep[0]["samples_per_sec"]
    best_pt = max(sweep, key=lambda r: r["samples_per_sec"])
    doc["daso_best"] = {
        "point": [best_pt["global_skip"], best_pt["local_skip"]],
        "samples_per_sec": best_pt["samples_per_sec"],
        "speedup_vs_full_sync": round(best_pt["samples_per_sec"] / full_sync, 2),
    }

    out = json.dumps(doc, indent=1)
    print(out)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(out + "\n")


if __name__ == "__main__":
    main()

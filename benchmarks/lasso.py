"""Lasso benchmark (reference: benchmarks/lasso/config.json protocol).

``--torch-baseline`` also runs the same coordinate-descent sweep in torch on
CPU (the reference's comparison baseline, benchmarks/lasso/torch-cpu.py) and
reports ``torch_time_s`` + ``vs_torch``."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=100_000)
    parser.add_argument("--f", type=int, default=64)
    parser.add_argument("--iterations", type=int, default=20)
    parser.add_argument("--trials", type=int, default=3)
    parser.add_argument("--torch-baseline", action="store_true")
    args = parser.parse_args()

    import os

    if os.environ.get("HEAT_TPU_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")

    import heat_tpu as ht

    ht.random.seed(0)
    x = ht.random.randn(args.n, args.f, split=0)
    y = ht.random.randn(args.n, split=0)

    times = []
    for _ in range(args.trials):
        lasso = ht.regression.Lasso(lam=0.1, max_iter=args.iterations, tol=None)
        start = time.perf_counter()
        lasso.fit(x, y)
        float(lasso.theta.larray[0, 0])
        times.append(time.perf_counter() - start)

    rec = {
        "benchmark": "lasso",
        "n": args.n,
        "f": args.f,
        "devices": ht.get_comm().size,
        "time_s": round(min(times), 4),
    }
    if args.torch_baseline:
        t = _torch_lasso_sec(args.n, args.f, args.iterations, args.trials)
        rec["torch_time_s"] = round(t, 4)
        rec["vs_torch"] = round(t / rec["time_s"], 2)
    print(json.dumps(rec))


def _torch_lasso_sec(n: int, f: int, iters: int, trials: int) -> float:
    """The same coordinate-descent sweep in torch on CPU — the reference's
    single-node comparison baseline (benchmarks/lasso/torch-cpu.py): per
    feature, rho from the current residual, soft threshold, intercept free."""
    import torch

    torch.manual_seed(0)
    X = torch.randn(n, f)
    y = torch.randn(n, 1)
    lam = 0.1

    def fit():
        theta = torch.zeros(f, 1)
        for _ in range(iters):
            for j in range(f):
                X_j = X[:, j]
                y_est = X @ theta
                rho = (X_j @ (y.ravel() - y_est.ravel() + theta[j, 0] * X_j)) / n
                if j == 0:
                    theta[j, 0] = rho
                else:
                    theta[j, 0] = torch.sign(rho) * torch.clamp(rho.abs() - lam, min=0.0)
        return theta

    fit()  # warmup
    best = float("inf")
    for _ in range(trials):
        start = time.perf_counter()
        fit()
        best = min(best, time.perf_counter() - start)
    return best


if __name__ == "__main__":
    main()

"""Lasso benchmark (reference: benchmarks/lasso/config.json protocol)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=100_000)
    parser.add_argument("--f", type=int, default=64)
    parser.add_argument("--iterations", type=int, default=20)
    parser.add_argument("--trials", type=int, default=3)
    args = parser.parse_args()

    import os

    if os.environ.get("HEAT_TPU_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")

    import heat_tpu as ht

    ht.random.seed(0)
    x = ht.random.randn(args.n, args.f, split=0)
    y = ht.random.randn(args.n, split=0)

    times = []
    for _ in range(args.trials):
        lasso = ht.regression.Lasso(lam=0.1, max_iter=args.iterations, tol=None)
        start = time.perf_counter()
        lasso.fit(x, y)
        float(lasso.theta.larray[0, 0])
        times.append(time.perf_counter() - start)
    print(
        json.dumps(
            {
                "benchmark": "lasso",
                "n": args.n,
                "f": args.f,
                "devices": ht.get_comm().size,
                "time_s": round(min(times), 4),
            }
        )
    )


if __name__ == "__main__":
    main()

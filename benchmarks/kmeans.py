"""KMeans benchmark (reference: benchmarks/kmeans/heat-cpu.py:20-26 protocol:
k=8, 30 iterations, timed over multiple trials, split=0)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import time

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=1_000_000, help="number of points")
    parser.add_argument("--f", type=int, default=16, help="features")
    parser.add_argument("--k", type=int, default=8)
    parser.add_argument("--iterations", type=int, default=30)
    parser.add_argument("--trials", type=int, default=3)
    parser.add_argument("--path", type=str, default=None, help="optional HDF5 input")
    parser.add_argument("--dataset", type=str, default="data")
    args = parser.parse_args()

    import os

    if os.environ.get("HEAT_TPU_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax

    import heat_tpu as ht

    if args.path:
        x = ht.load_hdf5(args.path, args.dataset, split=0)
    else:
        ht.random.seed(0)
        x = ht.random.randn(args.n, args.f, split=0)

    times = []
    for trial in range(args.trials):
        km = ht.cluster.KMeans(n_clusters=args.k, init="random", max_iter=args.iterations, tol=0.0, random_state=trial)
        start = time.perf_counter()
        km.fit(x)
        _ = km.inertia_  # host-read sync
        times.append(time.perf_counter() - start)
    print(
        json.dumps(
            {
                "benchmark": "kmeans",
                "n": args.n,
                "f": args.f,
                "k": args.k,
                "devices": ht.get_comm().size,
                "iters_per_sec": args.iterations / min(times),
                "times_s": [round(t, 4) for t in times],
            }
        )
    )


if __name__ == "__main__":
    main()

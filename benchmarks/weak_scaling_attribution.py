"""Attribute the virtual-mesh weak-scaling overhead (VERDICT r04 weak #6).

The r04 series showed kmeans overhead 1.167 and lasso 1.274 at 8 virtual
devices against the <= 1.11 north-star bound, with nothing attributing the
8-device jump. This experiment separates the three candidate costs for the
jnp Lloyd iteration at fixed per-device size:

  * **collective cost** — the SAME per-device work run (a) through the
    GSPMD program with its per-iteration all-reduce vs (b) through a
    shard_map program with NO collectives (each shard's centers evolve
    independently; identical local matmul/argmin/contraction work). The
    wall-time difference is what the all-reduce rendezvous costs on p
    single-core-multiplexed virtual devices.
  * **dispatch cost** — a trivial jitted op timed at each p: what one
    host->devices dispatch costs as p grows (every KMeans.fit chunk pays it).
  * **HLO collective budget** — collective-instruction counts of the
    compiled 10-iteration program at each p, proving the budget is O(1) in
    p (the jump is runtime rendezvous serialization, not extra collectives).

All measurements are single compiled programs (one dispatch per timing), so
host-side chunking effects are excluded from the collective attribution.

Usage: python benchmarks/weak_scaling_attribution.py [--out ...]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

PER_DEV_N, F, K, ITERS = 125_000, 16, 8, 10


def child(p: int) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import heat_tpu as ht
    from heat_tpu.cluster.kmeans import _lloyd_run

    comm = ht.get_comm()
    assert comm.size == p, (comm.size, p)
    n = PER_DEV_N * p
    data = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (n, F), dtype=jnp.float32),
        comm.sharding(2, 0),
    )
    centers = jax.random.normal(jax.random.PRNGKey(2), (K, F), dtype=jnp.float32) * 3

    def timeit(fn, sync, reps=3):
        sync(fn())
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            sync(fn())
            best = min(best, time.perf_counter() - t0)
        return best

    out = {"devices": p, "n": n}

    # (a) the product path: GSPMD program with its per-iteration all-reduce
    run = jax.jit(lambda d, c: _lloyd_run(d, c, K, ITERS), static_argnums=())
    lowered = jax.jit(lambda d, c: _lloyd_run(d, c, K, ITERS)).lower(data, centers)
    hlo = lowered.compile().as_text()
    out["hlo_collectives"] = len(
        re.findall(r"(?:all-gather|all-reduce|all-to-all|collective-permute)\(", hlo)
    )
    out["gspmd_s"] = round(timeit(lambda: run(data, centers), lambda r: float(r[3])), 4)

    # (b) identical local work, ZERO collectives: per-shard Lloyd iterations
    # via shard_map, each shard's centers evolving independently
    def local_kernel(xs, c0):
        def body(i, c):
            score = jnp.sum(c * c, axis=1) - 2.0 * (xs @ c.T)
            labels = jnp.argmin(score, axis=1).astype(jnp.int32)
            onehot = jax.nn.one_hot(labels, K, dtype=xs.dtype)
            counts = jnp.sum(onehot, axis=0)
            sums = onehot.T @ xs
            return jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), c)

        c = jax.lax.fori_loop(0, ITERS, body, c0)
        return jnp.sum((c - c0) ** 2)[None]

    local = jax.jit(
        jax.shard_map(
            local_kernel,
            mesh=comm.mesh,
            in_specs=(P(comm.axis_name, None), P(None, None)),
            out_specs=P(comm.axis_name),
            check_vma=False,
        )
    )
    out["local_s"] = round(
        timeit(lambda: local(data, centers), lambda r: float(r[0])), 4
    )
    out["collective_s"] = round(out["gspmd_s"] - out["local_s"], 4)

    # (c) dispatch floor at this p
    tiny = jax.jit(lambda a: a.sum())
    tv = jnp.ones(8)
    out["dispatch_ms"] = round(timeit(lambda: tiny(tv), lambda r: float(r), reps=5) * 1e3, 3)

    # (d) footprint probe: same p, HALVED/QUARTERED per-device rows. If the
    # per-row cost returns to the 1-device figure while p stays constant,
    # the overhead is aggregate working-set size on the one-core host (a
    # virtual-mesh artifact real per-chip HBM does not have), not device
    # count, threads, or collectives.
    for div in (2, 4):
        n_s = PER_DEV_N // div * p
        d_s = jax.device_put(
            jax.random.normal(jax.random.PRNGKey(1), (n_s, F), dtype=jnp.float32),
            comm.sharding(2, 0),
        )
        t = timeit(lambda: run(d_s, centers), lambda r: float(r[3]))
        out[f"ns_per_row_shard_div{div}"] = round(t / n_s / ITERS * 1e9, 2)
    out["ns_per_row"] = round(out["gspmd_s"] / n / ITERS * 1e9, 2)

    print(json.dumps(out), flush=True)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="benchmarks/WEAK_SCALING_ATTRIBUTION_r05.json")
    parser.add_argument("--sizes", type=int, nargs="+", default=[1, 2, 4, 8])
    parser.add_argument("--child", type=int, default=0)
    args = parser.parse_args()

    if args.child:
        child(args.child)
        return

    rows = []
    for p in args.sizes:
        env = dict(os.environ)
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={p}"
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", str(p)],
            capture_output=True,
            text=True,
            env=env,
            cwd=_REPO,
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
        try:
            rows.append(json.loads(line))
        except (ValueError, IndexError):
            print(proc.stdout, proc.stderr, file=sys.stderr)
            raise
        print(line, flush=True)

    base = rows[0]
    last = rows[-1]
    conclusion = (
        "The 8-device jump is NOT collectives: the HLO budget is O(1) in p "
        "(2 all-reduces per 10-iteration program) and the zero-collective "
        "shard_map program shows the same overhead. It is aggregate "
        "working-set footprint: at the same p, halving per-device rows "
        f"returns the per-row cost to the 1-device figure "
        f"({last.get('ns_per_row_shard_div2')} ns vs {last.get('ns_per_row')} ns "
        f"full-shard vs {round(base['gspmd_s'] / base['n'] / ITERS * 1e9, 2)} ns "
        "at p=1). All virtual devices share one host memory system, so total "
        "footprint grows with p — on real chips every device owns its HBM and "
        "this term does not exist."
    )
    doc = {
        "conclusion": conclusion,
        "protocol": (
            "fixed 125k rows/device, ONE compiled 10-iteration Lloyd program per "
            "timing; gspmd_s = with per-iteration all-reduce, local_s = identical "
            "per-shard work with zero collectives (shard_map), collective_s = the "
            "difference; dispatch_ms = trivial-op dispatch at that device count. "
            "All virtual devices share ONE physical core, so ideal scaling is "
            "time proportional to p."
        ),
        "rows": rows,
        "attribution": {},
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    p0 = base["devices"]
    for row in rows[1:]:
        p = row["devices"]
        ideal = base["gspmd_s"] * p / p0  # normalized to the first size
        overhead = row["gspmd_s"] / ideal
        # how much of the overhead the zero-collective program also shows
        # (= partitioning/serialization, NOT collectives)
        local_overhead = row["local_s"] / (base["local_s"] * p / p0)
        doc["attribution"][f"p{p}"] = {
            "overhead_vs_ideal_work_scaling": round(overhead, 3),
            "overhead_without_collectives": round(local_overhead, 3),
            "collective_share_of_wall_pct": round(
                100.0 * max(row["collective_s"], 0.0) / row["gspmd_s"], 1
            ),
            "hlo_collectives": row["hlo_collectives"],
        }
    with open(os.path.join(_REPO, args.out), "w") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    print(json.dumps({"written": args.out}), flush=True)


if __name__ == "__main__":
    main()

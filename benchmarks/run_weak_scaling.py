"""Weak-scaling harness (reference: benchmarks/generate_jobscripts.py:12-50
generates SLURM jobs over 1..15 nodes; on TPU the mesh is virtualized instead:
the same benchmark runs at mesh sizes 1/2/4/8 with problem size scaled
per-device, reporting parallel efficiency).

Run on CPU with forced host devices to validate scaling behavior:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 python benchmarks/run_weak_scaling.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import subprocess
import sys


BENCHMARKS = {
    "kmeans": lambda per_dev, p: ["--n", str(per_dev * p), "--iterations", "10", "--trials", "2"],
    "distance_matrix": lambda per_dev, p: ["--n", str(per_dev * p), "--trials", "2"],
    "statistical_moments": lambda per_dev, p: ["--rows", str(per_dev * p), "--trials", "3"],
    "lasso": lambda per_dev, p: ["--n", str(per_dev * p), "--iterations", "10", "--trials", "2"],
}


def _series(benchmark: str, per_device: int, sizes) -> list:
    results = []
    for p in sizes:
        import os

        env = dict(os.environ)
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={p}"
        env["HEAT_TPU_FORCE_CPU"] = "1"
        extra = []
        if benchmark == "lasso" and p == 1:
            # single-node external baseline (reference benchmarks/lasso/
            # torch-cpu.py): one torch-CPU run at the 1-device size
            extra = ["--torch-baseline"]
        out = subprocess.run(
            [sys.executable, f"benchmarks/{benchmark}.py"]
            + BENCHMARKS[benchmark](per_device, p)
            + extra,
            capture_output=True,
            text=True,
            env=env,
        )
        line = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else "{}"
        try:
            results.append(json.loads(line))
        except json.JSONDecodeError:
            print(out.stdout, out.stderr, file=sys.stderr)
            raise
        print(line)
    return results


def _overheads(results: list, sizes) -> None:
    """Attach overhead_vs_ideal_work_scaling = t(p)/(t(p0)·p/p0) per row
    (all virtual devices share the physical core, so ideal time grows with
    p; normalized to the FIRST measured size, not an assumed p0=1)."""
    p0 = sizes[0]

    def t_of(row):
        return row["time_s"] if "time_s" in row else min(row["times_s"])

    t0 = t_of(results[0])
    for row, p in zip(results, sizes):
        row["overhead_vs_ideal_work_scaling"] = round(t_of(row) / (t0 * p / p0), 3)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--benchmark", choices=BENCHMARKS, default="kmeans")
    parser.add_argument("--per-device", type=int, default=125_000)
    parser.add_argument("--sizes", type=int, nargs="+", default=[1, 2, 4, 8])
    parser.add_argument(
        "--artifact",
        default=None,
        help="run the kmeans + lasso series and write the combined weak-scaling "
        "artifact (the WEAK_SCALING_r*.json the round ships) to this path",
    )
    args = parser.parse_args()

    if args.artifact:
        # --per-device scales BOTH series (lasso keeps its 4x-smaller rows,
        # the r04 protocol's ratio) instead of being silently ignored
        kk = _series("kmeans", args.per_device, args.sizes)
        _overheads(kk, args.sizes)
        ll = _series("lasso", args.per_device // 4, args.sizes)
        _overheads(ll, args.sizes)
        import time

        doc = {
            "note": (
                "virtual-mesh weak scaling: p forced-host CPU devices share the "
                "SAME physical core, so total work grows with p while compute "
                "does not — ideal behavior is time ∝ p. "
                "'overhead_vs_ideal_work_scaling' = t(p)/(t(1)*p). The 8-device "
                "overhead is attributed (collectives exonerated, aggregate "
                "host-memory footprint identified) in "
                "WEAK_SCALING_ATTRIBUTION_r05.json; the per-program collective "
                "budgets are pinned by tests/test_mesh64_compile.py. jnp Lloyd "
                "path (the fused pallas kernel is TPU-only); lasso runs the "
                "Gram-mode CD (zero collectives per sweep)."
            ),
            "series": kk,
            "series_lasso": ll,
            "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        with open(args.artifact, "w") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")
        print(json.dumps({"written": args.artifact}))
        return

    results = _series(args.benchmark, args.per_device, args.sizes)
    if len(results) > 1 and "time_s" in results[0]:
        eff = results[0]["time_s"] / results[-1]["time_s"]
        print(json.dumps({"weak_scaling_efficiency": round(eff, 3), "sizes": args.sizes}))


if __name__ == "__main__":
    main()

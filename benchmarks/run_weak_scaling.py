"""Weak-scaling harness (reference: benchmarks/generate_jobscripts.py:12-50
generates SLURM jobs over 1..15 nodes; on TPU the mesh is virtualized instead:
the same benchmark runs at mesh sizes 1/2/4/8 with problem size scaled
per-device, reporting parallel efficiency).

Run on CPU with forced host devices to validate scaling behavior:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 python benchmarks/run_weak_scaling.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import subprocess
import sys


BENCHMARKS = {
    "kmeans": lambda per_dev, p: ["--n", str(per_dev * p), "--iterations", "10", "--trials", "2"],
    "distance_matrix": lambda per_dev, p: ["--n", str(per_dev * p), "--trials", "2"],
    "statistical_moments": lambda per_dev, p: ["--rows", str(per_dev * p), "--trials", "3"],
    "lasso": lambda per_dev, p: ["--n", str(per_dev * p), "--iterations", "10", "--trials", "2"],
}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--benchmark", choices=BENCHMARKS, default="kmeans")
    parser.add_argument("--per-device", type=int, default=125_000)
    parser.add_argument("--sizes", type=int, nargs="+", default=[1, 2, 4, 8])
    args = parser.parse_args()

    results = []
    for p in args.sizes:
        import os

        env = dict(os.environ)
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={p}"
        env["HEAT_TPU_FORCE_CPU"] = "1"
        extra = []
        if args.benchmark == "lasso" and p == 1:
            # single-node external baseline (reference benchmarks/lasso/
            # torch-cpu.py): one torch-CPU run at the 1-device size
            extra = ["--torch-baseline"]
        out = subprocess.run(
            [sys.executable, f"benchmarks/{args.benchmark}.py"]
            + BENCHMARKS[args.benchmark](args.per_device, p)
            + extra,
            capture_output=True,
            text=True,
            env=env,
        )
        line = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else "{}"
        try:
            results.append(json.loads(line))
        except json.JSONDecodeError:
            print(out.stdout, out.stderr, file=sys.stderr)
            raise
        print(line)

    if len(results) > 1 and "time_s" in results[0]:
        eff = results[0]["time_s"] / results[-1]["time_s"]
        print(json.dumps({"weak_scaling_efficiency": round(eff, 3), "sizes": args.sizes}))


if __name__ == "__main__":
    main()

"""Long-context attention throughput: ring / Ulysses / dense side by side.

The long-context story (SURVEY.md: sequence/context parallelism is
first-class) needs a measured artifact, not just oracle tests: this harness
times the attention kernels at growing sequence lengths on the mesh and
reports tokens/s plus the dense kernel's memory ceiling — the point of ring
attention is that the S x S score matrix never materializes, so it keeps
scaling after dense OOMs.

Defaults run on the 8-device forced-CPU mesh (CI topology); on a live TPU
use --platform default. Usage:

    python benchmarks/long_context.py [--devices 8] [--platform cpu]
        [--seqs 2048 8192] [--dim 256] [--heads 8] [--out FILE]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--platform", default="cpu", choices=["cpu", "default"])
    parser.add_argument("--seqs", type=int, nargs="+", default=[2048, 8192])
    parser.add_argument("--dim", type=int, default=256)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--trials", type=int, default=3)
    parser.add_argument("--out", default=None)
    args = parser.parse_args()

    if args.platform == "cpu":
        # an explicit --devices always wins: strip any pre-set count rather
        # than silently running on a different topology than requested
        import re

        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        ).strip()
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={args.devices}".strip()
        )

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    import heat_tpu as ht
    from heat_tpu.nn.attention import (
        dot_product_attention,
        ring_attention,
        ulysses_attention,
    )

    comm = ht.get_comm()
    p = comm.size
    head_dim = args.dim // args.heads
    doc = {
        "platform": comm.devices[0].platform,
        "devices": p,
        "heads": args.heads,
        "head_dim": head_dim,
        "causal": True,
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "series": [],
    }

    def timed(fn, *ops):
        out = fn(*ops)
        float(jnp.sum(out[..., 0]))  # compile + sync
        best = float("inf")
        for _ in range(args.trials):
            t0 = time.perf_counter()
            out = fn(*ops)
            float(jnp.sum(out[..., 0]))
            best = min(best, time.perf_counter() - t0)
        return best

    for S in args.seqs:
        S = S // p * p
        key = jax.random.PRNGKey(S)
        q, k, v = (
            jax.random.normal(kk, (1, S, args.heads, head_dim), jnp.float32)
            for kk in jax.random.split(key, 3)
        )
        qs, ks, vs = (jax.device_put(t, comm.sharding(4, 1)) for t in (q, k, v))
        rec = {"seq": S}

        t_ring = timed(
            lambda a, b, c: ring_attention(a, b, c, causal=True, comm=comm), qs, ks, vs
        )
        rec["ring_tokens_per_sec"] = round(S / t_ring, 1)
        rec["ring_ms"] = round(t_ring * 1e3, 2)

        t_uly = timed(
            lambda a, b, c: ulysses_attention(a, b, c, causal=True, comm=comm), qs, ks, vs
        )
        rec["ulysses_tokens_per_sec"] = round(S / t_uly, 1)
        rec["ulysses_ms"] = round(t_uly * 1e3, 2)

        # dense reference: materializes the (S, S) score matrix per head —
        # measured while it fits; recorded as the ceiling it is
        score_bytes = args.heads * S * S * 4
        if score_bytes <= 2 << 30:  # keep the CI box sane
            t_dense = timed(
                lambda a, b, c: dot_product_attention(a, b, c, causal=True), q, k, v
            )
            rec["dense_tokens_per_sec"] = round(S / t_dense, 1)
            rec["dense_ms"] = round(t_dense * 1e3, 2)
        else:
            rec["dense_tokens_per_sec"] = None
            rec["dense_skipped"] = f"score matrix would be {score_bytes / 1e9:.1f} GB"
        rec["score_matrix_gb_if_dense"] = round(score_bytes / 1e9, 3)
        doc["series"].append(rec)
        # bank incrementally: a tunnel death during the NEXT (bigger) seq
        # must not lose this one's measurements (the bench.py pattern)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(json.dumps(doc, indent=1) + "\n")

    print(json.dumps(doc, indent=1))


if __name__ == "__main__":
    main()

"""cdist benchmark (reference: benchmarks/distance_matrix/heat-gpu.py:20-34:
quadratic_expansion on/off, timed trials, split=0)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=40_000)
    parser.add_argument("--f", type=int, default=64)
    parser.add_argument("--trials", type=int, default=3)
    parser.add_argument("--quadratic-expansion", action="store_true")
    args = parser.parse_args()

    import os

    if os.environ.get("HEAT_TPU_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")

    import heat_tpu as ht

    ht.random.seed(0)
    n = (args.n // ht.get_comm().size) * ht.get_comm().size
    x = ht.random.randn(n, args.f, split=0)

    times = []
    for _ in range(args.trials):
        start = time.perf_counter()
        d = ht.spatial.cdist(x, quadratic_expansion=args.quadratic_expansion)
        float(d.larray[0, 0])  # sync
        times.append(time.perf_counter() - start)
    best = min(times)
    # bytes written for the (n, n) result per chip
    gb = (n * n * 4) / 1e9 / ht.get_comm().size
    print(
        json.dumps(
            {
                "benchmark": "distance_matrix",
                "n": n,
                "f": args.f,
                "quadratic_expansion": args.quadratic_expansion,
                "devices": ht.get_comm().size,
                "time_s": round(best, 4),
                "gb_per_sec_per_chip": round(gb / best, 2),
            }
        )
    )


if __name__ == "__main__":
    main()

"""One-chip TPU capability probe.

Measures the framework's hot kernels on the real TPU and prints one JSON
document: MXU matmul rates (bf16/f32), the pallas flash-attention kernel,
cdist (jnp quadratic expansion vs the pallas pairwise kernel), and an HBM
bandwidth probe. Every timing synchronizes via a host scalar read and is
reported both raw and with the measured dispatch round-trip subtracted
(the axon tunnel adds a fixed per-dispatch cost that a real on-host run
does not pay).

Usage: python benchmarks/tpu_capability.py [--out FILE]
"""

import argparse
import json
import time


def _timeit(fn, sync, reps=3):
    """Best-of-reps wall time of fn(); sync(result) forces completion."""
    sync(fn())  # warmup/compile
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=None)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    out = {"device": str(dev), "platform": dev.platform}

    # dispatch round-trip floor
    tiny = jax.jit(lambda a: a.sum())
    tv = jnp.ones(8)
    rtt = _timeit(lambda: tiny(tv), lambda r: float(r), reps=5)
    out["dispatch_rtt_ms"] = round(rtt * 1e3, 2)

    def corrected(best):
        return max(best - rtt, 1e-9)

    # ---- MXU matmul rates ------------------------------------------------
    for dtype, name in ((jnp.bfloat16, "bf16"), (jnp.float32, "f32")):
        n = 4096
        a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.float32).astype(dtype)
        b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32).astype(dtype)
        mm = jax.jit(lambda x, y: (x @ y).astype(jnp.float32))
        best = _timeit(lambda: mm(a, b), lambda r: float(r[0, 0]))
        flops = 2.0 * n * n * n
        out[f"matmul_{name}_{n}_tflops"] = round(flops / best / 1e12, 2)
        out[f"matmul_{name}_{n}_tflops_rtt_corrected"] = round(flops / corrected(best) / 1e12, 2)

    # ---- HBM bandwidth: big elementwise triad ----------------------------
    n = 64 * 1024 * 1024  # 256 MB per operand f32
    x = jax.random.normal(jax.random.PRNGKey(2), (n,), jnp.float32)
    y = jax.random.normal(jax.random.PRNGKey(3), (n,), jnp.float32)
    triad = jax.jit(lambda a, b: (a * 1.5 + b).sum())  # read 2n, reduce
    best = _timeit(lambda: triad(x, y), lambda r: float(r))
    bytes_moved = 2 * n * 4
    out["hbm_read_gbps"] = round(bytes_moved / best / 1e9, 1)
    out["hbm_read_gbps_rtt_corrected"] = round(bytes_moved / corrected(best) / 1e9, 1)

    # ---- flash attention (pallas) vs dense reference ---------------------
    try:
        from heat_tpu.nn.attention import dot_product_attention
        from heat_tpu.ops.flash import flash_attention_tpu as flash_attention

        B, S, H, D = 1, 4096, 8, 128
        q, k, v = (
            jax.random.normal(kk, (B, S, H, D), jnp.float32)
            for kk in jax.random.split(jax.random.PRNGKey(4), 3)
        )
        # 4 matmul-equivalent flops per (S,S,D) score+value pair, halved causal
        att_flops = 4.0 * B * H * S * S * D / 2
        fl = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
        best = _timeit(lambda: fl(q, k, v), lambda r: float(r[0, 0, 0, 0]))
        out["flash_attn_causal_4k_tflops"] = round(att_flops / best / 1e12, 2)
        out["flash_attn_causal_4k_tflops_rtt_corrected"] = round(
            att_flops / corrected(best) / 1e12, 2
        )
        dn = jax.jit(lambda q, k, v: dot_product_attention(q, k, v, causal=True))
        best_d = _timeit(lambda: dn(q, k, v), lambda r: float(r[0, 0, 0, 0]))
        out["dense_attn_causal_4k_tflops"] = round(att_flops / best_d / 1e12, 2)
        out["flash_vs_dense_speedup"] = round(best_d / best, 2)
    except Exception as exc:  # noqa: BLE001
        out["flash_attn_error"] = repr(exc)[:200]

    # ---- cdist: jnp quadratic expansion vs pallas pairwise ---------------
    try:
        from heat_tpu.spatial.distance import _euclidian_fast

        n, f = 16384, 64
        pts = jax.random.normal(jax.random.PRNGKey(5), (n, f), jnp.float32)
        cd = jax.jit(_euclidian_fast)
        best = _timeit(lambda: cd(pts, pts), lambda r: float(r[0, 0]))
        bytes_min = 2 * n * f * 4 + n * n * 4
        out["cdist_jnp_16k_gbps"] = round(bytes_min / best / 1e9, 1)
        out["cdist_jnp_16k_gbps_rtt_corrected"] = round(bytes_min / corrected(best) / 1e9, 1)
        try:
            from heat_tpu.ops.pairwise import pairwise_distance

            pd = jax.jit(pairwise_distance)
            best_p = _timeit(lambda: pd(pts, pts), lambda r: float(r[0, 0]))
            out["cdist_pallas_16k_gbps"] = round(bytes_min / best_p / 1e9, 1)
        except Exception as exc:  # noqa: BLE001
            out["cdist_pallas_error"] = repr(exc)[:200]
    except Exception as exc:  # noqa: BLE001
        out["cdist_error"] = repr(exc)[:200]

    doc = json.dumps(out, indent=1)
    print(doc)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(doc + "\n")


if __name__ == "__main__":
    main()

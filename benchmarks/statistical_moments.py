"""Statistical moments benchmark (reference:
benchmarks/statistical_moments/heat-cpu.py:21-28: mean and std over
axis in {None, 0, 1}, timed trials)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--rows", type=int, default=1000)
    parser.add_argument("--cols", type=int, default=1000)
    parser.add_argument("--trials", type=int, default=10)
    args = parser.parse_args()

    import os

    if os.environ.get("HEAT_TPU_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")

    import heat_tpu as ht

    ht.random.seed(0)
    x = ht.random.randn(args.rows, args.cols, split=0)

    results = {}
    for name, fn in (("mean", ht.mean), ("std", ht.std)):
        for axis in (None, 0, 1):
            fn(x, axis)  # warmup
            times = []
            for _ in range(args.trials):
                start = time.perf_counter()
                r = fn(x, axis)
                r.numpy() if r.ndim else float(r.larray)
                times.append(time.perf_counter() - start)
            results[f"{name}_axis{axis}"] = round(min(times) * 1000, 3)
    print(
        json.dumps(
            {
                "benchmark": "statistical_moments",
                "shape": [args.rows, args.cols],
                "devices": ht.get_comm().size,
                "ms": results,
            }
        )
    )


if __name__ == "__main__":
    main()

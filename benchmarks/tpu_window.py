"""Opportunistic one-window TPU capture.

The axon tunnel to the real chip is up for minutes at a time between long
outages, so every TPU-gated measurement in this repo must be capturable in
ONE window without supervision. This script runs a ladder of independent
stages in a single process (device bring-up paid once) and re-writes its
``--out`` JSON after EVERY stage, so a tunnel death mid-run still banks the
completed stages. Re-running MERGES, and the merge is ADDITIVE: stages that
already succeeded in the out-file are skipped, failed/missing ones retry,
and a failed re-run attempt (even under --force) can never overwrite a
banked-ok record — it lands in ``attempt_errors`` instead. An outer retry
loop makes the artifact monotone across windows.

Stages (each independently try/except'd):
  init          platform + dispatch round-trip floor
  mosaic_probe  a trivial 128-lane-aligned pallas kernel — distinguishes
                "this backend cannot compile ANY Mosaic kernel" from "a
                specific kernel is at fault" (the r04 bench saw the fused
                Lloyd kernel 500 through the remote-compile helper)
  mosaic_narrow same, but with a (block, 16) narrow-lane block — the fused
                Lloyd kernel's one unusual layout choice
  mosaic_variants one-construct-each sub-kernels ((1,1) VMEM scalar operand,
                lane argmin, narrow dot, grid accumulator, SMEM scalar) —
                maps a message-hiding remote-compile 500 to the construct
  lloyd_small   fused_lloyd_run on 64k rows: full error text if it fails
  lloyd_full    fused vs jnp Lloyd at the bench shape (10M x 16, k=8),
                wall + 10x-spread marginal + fixed_ms
  lloyd_bf16    fused Lloyd on a bfloat16 stream (half the HBM bytes)
  capability    MXU matmul bf16/f32 TFLOP/s + HBM triad GB/s, single-shot
                AND chained-marginal forms (the roofline refinement triad
                bench.py reads from TPU_CAPABILITY.json)
  cholqr2       CholeskyQR2 vs TSQR at the qr bench shape (VERDICT ask 6)
  qr_marginal   chained CholeskyQR2 (f32 + bf16-stream) marginal TFLOP/s —
                the RTT-cancelled number the r04 verdict asked for
  cdist         chained-eval marginal GB/s for the cdist tile
  moments_diag  eager ht.mean+ht.std vs one-program/one-read vs a 2048-step
                chain marginal — attributes the eager wall to host reads
  attention     pallas flash vs scan-flash vs dense at 4k causal (marginals)
  attention_sweep  (block_q, block_k) tile-schedule search, marginal rates
  train         DP ResNet18 samples/s + compiled-step breakdown (the
                BASELINE config-5 TPU leg; the DASO sweep needs a mesh)
  train50       DP ResNet-50 (BASELINE config 5's named model)
  train_bf16    DP ResNet18 with bf16 compute (MXU-native mixed precision)

Usage: python benchmarks/tpu_window.py [--out benchmarks/TPU_WINDOW_r04.json]
       [--stages init,mosaic_probe,...] [--skip-full]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time
import traceback

# Running as ``python benchmarks/tpu_window.py`` puts benchmarks/ (not the
# repo root) on sys.path; heat_tpu lives at the root. Do NOT touch
# PYTHONPATH for this — the axon backend registration rides on it.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _sz(full, smoke):
    """Stage size: the real capture shape, or a tiny one under
    HEAT_WINDOW_SMOKE=1 (CPU shakeout of the whole ladder before a tunnel
    window is spent on it — a stage that crashes on shapes/plumbing must be
    caught here, not on the chip)."""
    return smoke if os.environ.get("HEAT_WINDOW_SMOKE") == "1" else full


def _bank(out_path: str, doc: dict) -> None:
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, out_path)


def _timeit(fn, sync, reps=3):
    sync(fn())  # warmup/compile
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def _err(exc: BaseException) -> str:
    tb = traceback.format_exc()
    return (repr(exc)[:600] + " || tb-tail: " + tb[-1200:]) if tb else repr(exc)[:600]


def _marginal_sec(best1: float, bestN: float, extra_units: int):
    """Marginal seconds per unit from a (1x, Nx) two-point pair, or None
    when the spread is inside timing noise — the ONE acceptance rule for
    every marginal in this ladder and in bench.py. A near-zero delta would
    imply an unboundedly inflated rate, so the Nx run must clearly dominate
    the fixed cost before subtracting; and because the overstatement a
    noise-driven delta can bank GROWS with the work multiple (at 10x work a
    delta just above a 1.2x floor would report up to ~45x the wall rate),
    large multiples require a proportionally larger spread: 1.2x up to 16
    extra units, 1.5x beyond (advisor finding r04#1)."""
    floor = 1.2 if extra_units <= 16 else 1.5
    if bestN < floor * best1:
        return None
    return (bestN - best1) / extra_units


# ---------------------------------------------------------------------------
# stages — each returns a dict merged under its own key
# ---------------------------------------------------------------------------
def stage_init():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    tiny = jax.jit(lambda a: a.sum())
    tv = jnp.ones(8)
    rtt = _timeit(lambda: tiny(tv), lambda r: float(r), reps=5)
    return {
        "device": str(dev),
        "platform": dev.platform,
        "n_devices": len(jax.devices()),
        "dispatch_rtt_ms": round(rtt * 1e3, 2),
    }


def _probe_kernel(f_lane: int):
    """Compile+run a minimal pallas kernel whose block last-dim is f_lane."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(x_ref, o_ref):
        o_ref[:, :] = x_ref[:, :] * 2.0 + 1.0

    n = 512
    x = jnp.ones((n, f_lane), jnp.float32)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, f_lane), jnp.float32),
        grid=(n // 256,),
        in_specs=[pl.BlockSpec((256, f_lane), lambda i: (i, 0), memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((256, f_lane), lambda i: (i, 0), memory_space=pltpu.VMEM),
    )(x)
    return float(out[0, 0])


def stage_mosaic_probe():
    return {"ok": _probe_kernel(128) == 3.0}


def stage_mosaic_narrow():
    return {"ok": _probe_kernel(16) == 3.0}


def stage_mosaic_variants():
    """Bisect the fused-Lloyd kernel's constructs: each sub-kernel isolates
    ONE thing the Lloyd kernel does that a plain copy kernel does not, so a
    remote-compile 500 (which hides the Mosaic error text) maps to a
    specific construct."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, f, k, block = 512, 16, 8, 256
    x = jnp.ones((n, f), jnp.float32)
    out = {}

    def run(tag, kernel, extra_in=(), extra_specs=(), out_shape=None, out_spec=None):
        try:
            res = pl.pallas_call(
                kernel,
                out_shape=out_shape or jax.ShapeDtypeStruct((n, f), jnp.float32),
                grid=(n // block,),
                in_specs=[
                    pl.BlockSpec((block, f), lambda i: (i, 0), memory_space=pltpu.VMEM),
                    *extra_specs,
                ],
                out_specs=out_spec
                or pl.BlockSpec((block, f), lambda i: (i, 0), memory_space=pltpu.VMEM),
            )(x, *extra_in)
            jax.block_until_ready(res)
            out[tag] = "ok"
        except Exception as exc:  # noqa: BLE001 - each variant independent
            msg = _err(exc)[:300]
            if "Unable to initialize backend" in msg:
                # backend down mid-stage, not a Mosaic finding — raise so the
                # ladder retries this stage in the next window
                raise RuntimeError(msg) from exc
            out[tag] = msg

    # (b) (1,1) int32 scalar operand in VMEM + broadcasted_iota compare mask
    nv = jnp.full((1, 1), n - 3, jnp.int32)

    def k_scalar(x_ref, nv_ref, o_ref):
        i = pl.program_id(0)
        rows = i * block + jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)
        o_ref[:, :] = jnp.where(rows < nv_ref[0, 0], x_ref[:, :], 0.0)

    run(
        "scalar_vmem_mask",
        k_scalar,
        extra_in=(nv,),
        extra_specs=(pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),),
    )

    # (c) in-kernel argmin over the lane axis
    def k_argmin(x_ref, o_ref):
        labels = jnp.argmin(x_ref[:, :], axis=1).astype(jnp.int32)
        o_ref[:, :] = labels[:, None]

    run(
        "argmin_lane",
        k_argmin,
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.int32),
        out_spec=pl.BlockSpec((block, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
    )

    # (d) narrow dot: (block,16) x (16,8) with preferred f32
    ct = jnp.ones((f, k), jnp.float32)

    def k_dot(x_ref, c_ref, o_ref):
        o_ref[:, :] = jnp.dot(x_ref[:, :], c_ref[:, :], preferred_element_type=jnp.float32)

    run(
        "narrow_dot",
        k_dot,
        extra_in=(ct,),
        extra_specs=(pl.BlockSpec((f, k), lambda i: (0, 0), memory_space=pltpu.VMEM),),
        out_shape=jax.ShapeDtypeStruct((n, k), jnp.float32),
        out_spec=pl.BlockSpec((block, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
    )

    # (e) cross-grid accumulator output + @pl.when(i == 0) init
    def k_accum(x_ref, o_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            o_ref[:, :] = jnp.zeros_like(o_ref)

        o_ref[:, :] += jnp.sum(x_ref[:, :], axis=0, keepdims=True)

    run(
        "grid_accumulator",
        k_accum,
        out_shape=jax.ShapeDtypeStruct((1, f), jnp.float32),
        out_spec=pl.BlockSpec((1, f), lambda i: (0, 0), memory_space=pltpu.VMEM),
    )

    # (f) the SAME kernel with its scalar operand in SMEM (the
    # guide-recommended space) — if (b) fails and this passes, the Lloyd fix
    # is a one-line BlockSpec change
    run(
        "scalar_smem_mask",
        k_scalar,
        extra_in=(nv,),
        extra_specs=(pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),),
    )
    return out


def stage_lloyd_small():
    import jax
    import jax.numpy as jnp

    from heat_tpu.ops.lloyd import fused_lloyd_run

    n, f, k = 65536, 16, 8
    data = jax.random.normal(jax.random.PRNGKey(0), (n, f), dtype=jnp.float32)
    centers = jax.random.normal(jax.random.PRNGKey(1), (k, f), dtype=jnp.float32) * 3
    _, _, inertia, shift = fused_lloyd_run(data, centers, k, 2)
    return {"ok": True, "inertia": float(inertia), "shift": float(shift)}


def stage_lloyd_full():
    import jax
    import jax.numpy as jnp

    from heat_tpu.cluster.kmeans import _lloyd_run
    from heat_tpu.ops.lloyd import fused_lloyd_run

    n, f, k, iters = _sz(10_000_000, 20_000), 16, 8, 10
    data = jax.random.normal(jax.random.PRNGKey(1), (n, f), dtype=jnp.float32)
    centers = jax.random.normal(jax.random.PRNGKey(2), (k, f), dtype=jnp.float32) * 3
    out = {"n": n}
    for name, fn in (("fused", fused_lloyd_run), ("jnp", _lloyd_run)):
        try:
            best = _timeit(lambda: fn(data, centers, k, iters), lambda r: float(r[3]), reps=3)
            out[f"{name}_iters_per_sec"] = round(iters / best, 2)
            # two-point marginal at 10x: cancels the per-program fixed cost
            # (tunnel dispatch ~67 ms measured — it swamps a 10-iter program)
            best10 = _timeit(
                lambda: fn(data, centers, k, 10 * iters), lambda r: float(r[3]), reps=2
            )
            marg = _marginal_sec(best, best10, 9 * iters)
            if marg:
                out[f"{name}_iters_per_sec_marginal"] = round(1.0 / marg, 2)
                out[f"{name}_fixed_ms"] = round((best - iters * marg) * 1e3, 1)
        except Exception as exc:  # noqa: BLE001 - bank the other path regardless
            out[f"{name}_error"] = _err(exc)
    if out.get("fused_iters_per_sec") and out.get("jnp_iters_per_sec"):
        out["fused_vs_jnp"] = round(
            (out.get("fused_iters_per_sec_marginal") or out["fused_iters_per_sec"])
            / (out.get("jnp_iters_per_sec_marginal") or out["jnp_iters_per_sec"]),
            2,
        )
    return out


def stage_lloyd_bf16():
    """Fused Lloyd on a bfloat16 stream at the bench shape — the operand
    stays bf16 on the MXU (half the HBM bytes of the f32 stream), so a
    bandwidth-bound marginal should land near 2x lloyd_full's f32 rate."""
    import jax
    import jax.numpy as jnp

    from heat_tpu.ops.lloyd import fused_lloyd_run

    n, f, k, iters = _sz(10_000_000, 20_000), 16, 8, 10
    data = jax.random.normal(jax.random.PRNGKey(1), (n, f), dtype=jnp.float32).astype(
        jnp.bfloat16
    )
    centers = jax.random.normal(jax.random.PRNGKey(2), (k, f), dtype=jnp.float32) * 3
    best = _timeit(
        lambda: fused_lloyd_run(data, centers, k, iters), lambda r: float(r[3]), reps=3
    )
    out = {"n": n, "dtype": "bfloat16", "fused_iters_per_sec": round(iters / best, 2)}
    try:  # bank the wall rate regardless — a marginal-run hiccup must not
        best10 = _timeit(  # discard the measurement above (lloyd_full's rule)
            lambda: fused_lloyd_run(data, centers, k, 10 * iters),
            lambda r: float(r[3]),
            reps=2,
        )
        marg = _marginal_sec(best, best10, 9 * iters)
        if marg:
            out["fused_iters_per_sec_marginal"] = round(1.0 / marg, 2)
            out["hbm_gbps_effective"] = round(n * f * 2 / marg / 1e9, 1)
    except Exception as exc:  # noqa: BLE001
        out["marginal_error"] = _err(exc)
    return out


def stage_capability():
    import jax
    import jax.numpy as jnp

    out = {}
    tiny = jax.jit(lambda a: a.sum())
    tv = jnp.ones(8)
    rtt = _timeit(lambda: tiny(tv), lambda r: float(r), reps=5)

    def corrected(best):
        return max(best - rtt, 1e-9)

    for dtype, name in ((jnp.bfloat16, "bf16"), (jnp.float32, "f32")):
        n = _sz(4096, 256)
        a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.float32).astype(dtype)
        b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32).astype(dtype)
        mm = jax.jit(lambda x, y: (x @ y).astype(jnp.float32))
        best = _timeit(lambda: mm(a, b), lambda r: float(r[0, 0]))
        flops = 2.0 * n * n * n
        out[f"matmul_{name}_{n}_tflops"] = round(flops / best / 1e12, 2)
        out[f"matmul_{name}_{n}_tflops_rtt_corrected"] = round(flops / corrected(best) / 1e12, 2)

        # chained marginal: one 4k matmul is ~2.6 ms against the ~67 ms
        # tunnel RTT, so the subtraction above is noise — chain 16 dependent
        # matmuls in ONE program and difference against 1
        def chain(reps):
            @jax.jit
            def run(x, y):
                def body(i, acc):
                    return (acc @ y).astype(x.dtype)

                return jax.lax.fori_loop(0, reps, body, x)[0, 0].astype(jnp.float32)

            return run

        c1, c16 = chain(1), chain(16)
        b1 = _timeit(lambda: c1(a, b), lambda r: float(r), reps=2)
        b16 = _timeit(lambda: c16(a, b), lambda r: float(r), reps=2)
        marg = _marginal_sec(b1, b16, 15)
        if marg:
            out[f"matmul_{name}_{n}_tflops_marginal"] = round(flops / marg / 1e12, 2)

    n = _sz(64 * 1024 * 1024, 1024 * 1024)
    x = jax.random.normal(jax.random.PRNGKey(2), (n,), jnp.float32)
    y = jax.random.normal(jax.random.PRNGKey(3), (n,), jnp.float32)
    triad = jax.jit(lambda a, b: (a * 1.5 + b).sum())
    best = _timeit(lambda: triad(x, y), lambda r: float(r))
    out["hbm_read_gbps"] = round(2 * n * 4 / best / 1e9, 1)
    out["hbm_read_gbps_rtt_corrected"] = round(2 * n * 4 / corrected(best) / 1e9, 1)

    # chained triad marginal: the carry joins the operand BEFORE the triad
    # arithmetic ((a + carry) * 1.5 + b) so the whole body depends on loop
    # state — a left-associated `a * 1.5 + b + carry` would let XLA hoist
    # the invariant a*1.5+b and halve the real traffic while 2 reads/step
    # are billed, inflating the marginal (the exact rate _roofline_peaks
    # trusts to raise the assumed HBM peak)
    def tchain(reps):
        @jax.jit
        def run(a, b):
            def body(i, carry):
                s = ((a + carry) * 1.5 + b).sum()
                return s * 1e-30

            return jax.lax.fori_loop(0, reps, body, jnp.zeros((), jnp.float32))

        return run

    t1, t8 = tchain(1), tchain(8)
    b1 = _timeit(lambda: t1(x, y), lambda r: float(r), reps=2)
    b8 = _timeit(lambda: t8(x, y), lambda r: float(r), reps=2)
    marg = _marginal_sec(b1, b8, 7)
    if marg:
        out["hbm_read_gbps_marginal"] = round(2 * n * 4 / marg / 1e9, 1)
    out["dispatch_rtt_ms"] = round(rtt * 1e3, 2)
    return out


def stage_cholqr2():
    import jax
    import jax.numpy as jnp

    import heat_tpu as ht

    comm = ht.get_comm()
    m, n = _sz(1 << 21, 1 << 13), _sz(256, 32)
    a = ht.array(
        jax.device_put(
            jax.random.normal(jax.random.PRNGKey(4), (m, n), dtype=jnp.float32),
            comm.sharding(2, 0),
        ),
        is_split=0,
    )
    out = {"shape": [m, n]}
    flops = 2.0 * m * n * n
    for method in ("tsqr", "cholqr2"):
        try:
            best = _timeit(
                lambda: ht.linalg.qr(a, method=method),
                lambda qr_: float(qr_[1].larray[0, 0]),
                reps=2,
            )
            out[f"qr_{method}_tflops"] = round(flops / best / 1e12, 3)
        except Exception as exc:  # noqa: BLE001
            out[f"qr_{method}_error"] = _err(exc)
    if out.get("qr_cholqr2_tflops") and out.get("qr_tsqr_tflops"):
        out["cholqr2_vs_tsqr"] = round(out["qr_cholqr2_tflops"] / out["qr_tsqr_tflops"], 2)
    return out


def stage_qr_marginal():
    """RTT-cancelled CholeskyQR2 rate at the bench shape (2M x 256) — the r04
    verdict's ask: the cholqr2 stage's wall number carries the ~67 ms tunnel
    fixed cost AND the auto-probe's extra host read, so nobody could say how
    much of the 1.29 TFLOP/s (vs 52+ capability) was tunnel vs algorithm.
    Chains K full CholeskyQR2 evaluations in ONE program (each step's operand
    perturbed by a value derived from the previous step's full result, so
    nothing hoists or DCEs) and differences against 1."""
    import jax
    import jax.numpy as jnp

    from heat_tpu.core.linalg.qr import _cholqr2_kernel

    m, n = _sz(1 << 21, 1 << 13), _sz(256, 32)
    x = jax.random.normal(jax.random.PRNGKey(4), (m, n), dtype=jnp.float32)
    flops = 2.0 * m * n * n  # the 2mn^2 billing every qr number in this repo uses

    def chained(reps):
        @jax.jit
        def run(x):
            def body(i, carry):
                q, r, _ = _cholqr2_kernel(carry, calc_q=True)
                return carry + q * (r[0, 0] * 1e-30)

            final = jax.lax.fori_loop(0, reps, body, x)
            q, r, ok = _cholqr2_kernel(final, calc_q=True)
            return r[0, 0] + q[0, 0]

        return run

    one, five = chained(0), chained(4)
    b1 = _timeit(lambda: float(one(x)), lambda r: r, reps=2)
    b5 = _timeit(lambda: float(five(x)), lambda r: r, reps=2)
    out = {
        "shape": [m, n],
        "qr_cholqr2_wall_tflops": round(flops / b1 / 1e12, 3),
        "qr_fixed_ms": round(b1 * 1e3, 1),
    }
    marg = _marginal_sec(b1, b5, 4)
    if marg:
        out["qr_cholqr2_tflops_marginal"] = round(flops / marg / 1e12, 3)
        out["qr_ms_per_eval_marginal"] = round(marg * 1e3, 2)
    # bf16-stream variant: operand cast to bf16 once, Gram/formation matmuls
    # run bf16 x bf16 -> f32 on the MXU (half the HBM bytes, ~2.5x the MXU
    # rate); CholeskyQR2's second pass restores orthogonality lost to the
    # low-precision first pass for well-conditioned operands
    try:
        xb = x.astype(jnp.bfloat16)

        def chained_bf16(reps):
            @jax.jit
            def run(xb):
                def body(i, carry):
                    q, r, _ = _cholqr2_kernel(carry, calc_q=True)
                    return carry + (q * (r[0, 0].astype(q.dtype) * 1e-30)).astype(carry.dtype)

                final = jax.lax.fori_loop(0, reps, body, xb)
                q, r, ok = _cholqr2_kernel(final, calc_q=True)
                return (r[0, 0] + q[0, 0].astype(r.dtype)).astype(jnp.float32)

            return run

        one_b, five_b = chained_bf16(0), chained_bf16(4)
        b1b = _timeit(lambda: float(one_b(xb)), lambda r: r, reps=2)
        b5b = _timeit(lambda: float(five_b(xb)), lambda r: r, reps=2)
        out["qr_cholqr2_bf16_wall_tflops"] = round(flops / b1b / 1e12, 3)
        margb = _marginal_sec(b1b, b5b, 4)
        if margb:
            out["qr_cholqr2_bf16_tflops_marginal"] = round(flops / margb / 1e12, 3)
    except Exception as exc:  # noqa: BLE001 - bf16 variant must not cost the f32 one
        out["bf16_error"] = _err(exc)[:300]
    return out


def stage_cdist():
    """cdist marginal GB/s/chip: K chained evaluations in one program vs 1,
    cancelling the tunnel fixed cost (the official r04 record salvaged
    before bench.py's cdist diagnostics could run). The chain feeds a value
    derived from each full result back into the operand so nothing hoists."""
    import jax
    import jax.numpy as jnp

    from heat_tpu.spatial.distance import _euclidian_fast

    n, f = _sz(32768, 1024), 64
    x = jax.random.normal(jax.random.PRNGKey(5), (n, f), jnp.float32)

    def chained(reps):
        @jax.jit
        def run(x):
            def body(i, carry):
                d = _euclidian_fast(carry, carry)
                return carry + d[0, 0] * 1e-12

            return _euclidian_fast(
                jax.lax.fori_loop(0, reps, body, x), x
            )

        return run

    one, eight = chained(0), chained(7)
    best1 = _timeit(lambda: one(x), lambda r: float(r[0, 0]))
    best8 = _timeit(lambda: eight(x), lambda r: float(r[0, 0]), reps=2)
    out = {"n": n}
    # bytes per evaluation: the tile kernel reads x twice (row/col operands)
    # and writes the n^2 result; the chain's carry add fuses into the tile
    ev_bytes = (2.0 * n * f + n * n) * 4
    out["cdist_gbps"] = round(ev_bytes / best1 / 1e9, 2)
    marg = _marginal_sec(best1, best8, 7)
    if marg:
        out["cdist_gbps_marginal"] = round(ev_bytes / marg / 1e9, 2)
        out["cdist_fixed_ms"] = round((best1 - marg) * 1e3, 1)
    return out


def stage_moments_diag():
    """Attribute the moments wall time (the r04 'anomaly': 131-152 ms for a
    4 MB reduction). The ladder: eager API (2 dispatches, 2 host scalar
    reads) -> one program but still 2 host reads -> one program, ONE host
    read -> a 2048-step in-program chain whose marginal cancels the fixed
    cost entirely. Each rung isolates one suspect; r04's probe conflated the
    middle two (its 'fused' variant still did two float() reads, which is
    why it measured the same as eager and the anomaly looked unexplained)."""
    import jax
    import jax.numpy as jnp

    import heat_tpu as ht

    comm = ht.get_comm()
    n = 1_000_000
    mom = ht.array(
        jax.device_put(
            jax.random.normal(jax.random.PRNGKey(3), (n,), dtype=jnp.float32),
            comm.sharding(1, 0),
        ),
        is_split=0,
    )
    # eager API path (what bench.py's moments_ms_1M measures): 2 dispatches,
    # 2 host scalar reads
    def eager():
        float(ht.mean(mom).larray)
        float(ht.std(mom).larray)
        return 0.0

    best_eager = _timeit(lambda: eager(), lambda r: r, reps=5)

    # same arithmetic, ONE program — but still TWO host reads
    fused = jax.jit(lambda x: (x.mean(), x.std()))

    def two_reads():
        m_, s_ = fused(mom.larray)
        return float(m_) + float(s_)

    best_2read = _timeit(lambda: two_reads(), lambda r: r, reps=5)

    # one program, ONE host read (the scalars summed on device)
    fused1 = jax.jit(lambda x: x.mean() + x.std())
    best_1read = _timeit(lambda: float(fused1(mom.larray)), lambda r: r, reps=5)

    out = {
        "eager_api_ms": round(best_eager * 1e3, 3),
        "fused_two_reads_ms": round(best_2read * 1e3, 3),
        "fused_one_read_ms": round(best_1read * 1e3, 3),
    }

    # in-program chain marginal: the true device-side cost of one mean+std
    # evaluation, every per-dispatch and per-host-read cost cancelled. 2048
    # steps so the chained work dominates the ~67 ms tunnel fixed cost even
    # under the 1.5x acceptance floor for large multiples.
    def chain(steps):
        @jax.jit
        def run(t):
            def body(i, carry):
                t, acc = carry
                acc = acc + t.mean() + t.std()
                return (t + acc * 1e-30, acc)

            _, acc = jax.lax.fori_loop(0, steps, body, (t, jnp.zeros((), t.dtype)))
            return acc

        return run

    c1, cN = chain(1), chain(_sz(2048, 64))
    mop = mom.larray
    b1 = _timeit(lambda: float(c1(mop)), lambda r: r, reps=2)
    bN = _timeit(lambda: float(cN(mop)), lambda r: r, reps=2)
    marg = _marginal_sec(b1, bN, _sz(2048, 64) - 1)
    if marg:
        out["moments_device_us_marginal"] = round(marg * 1e6, 2)
        # 2 reduction passes (mean, centered squares) + the chained operand
        # update's read+write = 4 passes over the 1M f32 operand per step
        out["moments_gbps_marginal"] = round(4 * n * 4 / marg / 1e9, 2)
    # attribution: how much of the eager wall is host-read round-trips
    out["host_read_ms_each"] = round((best_2read - best_1read) * 1e3, 3)
    out["eager_attribution"] = (
        "eager wall = 2 host scalar reads (tunnel RTT each) + device compute; "
        "see fused_one_read_ms vs fused_two_reads_ms and the chain marginal"
    )
    return out


def stage_attention():
    import jax
    import jax.numpy as jnp

    from heat_tpu.nn.attention import dot_product_attention
    from heat_tpu.nn.attention import flash_attention as scan_flash
    from heat_tpu.ops.flash import flash_attention_tpu as flash_attention

    B, S, H, D = 1, _sz(4096, 512), _sz(8, 2), _sz(128, 32)
    q, k, v = (
        jax.random.normal(kk, (B, S, H, D), jnp.float32)
        for kk in jax.random.split(jax.random.PRNGKey(4), 3)
    )
    att_flops = 4.0 * B * H * S * S * D / 2
    out = {}

    # two-point marginal (1 vs 8 chained evals in ONE program, the output
    # feeding back as q so nothing hoists): the single-eval wall time is
    # dominated by the ~67 ms tunnel fixed cost — at 4k it exceeds the
    # attention compute itself, making raw TFLOP/s a tunnel metric
    def chained(att, reps):
        @jax.jit
        def run(q, k, v):
            def body(i, qq):
                return att(qq, k, v).astype(qq.dtype)

            return jax.lax.fori_loop(0, reps, body, q)

        return run

    for name, att in (
        ("flash", lambda q, k, v: flash_attention(q, k, v, causal=True)),
        ("scan", lambda q, k, v: scan_flash(q, k, v, causal=True, impl="scan")),
        ("dense", lambda q, k, v: dot_product_attention(q, k, v, causal=True)),
    ):
        try:
            one = chained(att, 1)
            eight = chained(att, 8)
            best = _timeit(lambda: one(q, k, v), lambda r: float(r[0, 0, 0, 0]))
            best8 = _timeit(lambda: eight(q, k, v), lambda r: float(r[0, 0, 0, 0]), reps=2)
            out[f"{name}_attn_causal_4k_tflops"] = round(att_flops / best / 1e12, 2)
            marg = _marginal_sec(best, best8, 7)
            if marg:
                out[f"{name}_attn_causal_4k_tflops_marginal"] = round(
                    att_flops / marg / 1e12, 2
                )
        except Exception as exc:  # noqa: BLE001 - one impl must not end the stage
            out[f"{name}_error"] = _err(exc)[:300]
    d_m = out.get("dense_attn_causal_4k_tflops_marginal")
    # '_marginal' suffix: these are marginal-RATE ratios, not wall-time ratios
    # (the r04 artifact reused the wall-ratio key name for the marginal form —
    # advisor finding r04#5; the un-suffixed key is retired)
    for name in ("flash", "scan"):
        n_m = out.get(f"{name}_attn_causal_4k_tflops_marginal")
        if n_m and d_m:
            out[f"{name}_vs_dense_speedup_marginal"] = round(n_m / d_m, 2)
    return out


def _train_one_model(model, name: str) -> dict:
    import numpy as np
    import jax.numpy as jnp
    import optax

    import heat_tpu as ht
    from heat_tpu.core.dndarray import _ensure_split
    from heat_tpu.nn import DataParallel

    comm = ht.get_comm()
    n_dev = comm.size
    batch = _sz(256, 16) // n_dev * n_dev or n_dev
    rng = np.random.default_rng(0)
    x_np = rng.standard_normal((batch, 32, 32, 3)).astype(np.float32)
    y_np = rng.integers(0, 10, size=batch).astype(np.int32)

    dp = DataParallel(model, comm=comm, optimizer=optax.sgd(0.05))
    dp.init(0, x_np[: max(n_dev, 2)])
    dp.train_step(x_np, y_np)  # compile

    def one():
        dp.train_step(x_np, y_np)
        return 0.0

    best = _timeit(lambda: one(), lambda r: r, reps=4)
    out = {
        "model": name,
        "global_batch": batch,
        "devices": n_dev,
        "dp_samples_per_sec": round(batch / best, 1),
        "dp_step_ms": round(best * 1e3, 2),
    }
    # breakdown: placement vs compiled compute (diagnosability through the
    # tunnel — a RTT-dominated step must be visible in the artifact)
    xb = _ensure_split(jnp.asarray(x_np), 0, comm)
    yb = _ensure_split(jnp.asarray(y_np), 0, comm)

    def compute_only():
        if dp._stateful:
            _, _, _, loss = dp._train_step(dp.params, dp.state, dp.opt_state, xb, yb)
        else:
            _, _, loss = dp._train_step(dp.params, dp.opt_state, xb, yb)
        return float(loss)

    t_compute = _timeit(lambda: compute_only(), lambda r: r, reps=4)
    out["dp_compiled_step_ms"] = round(t_compute * 1e3, 2)
    out["dp_samples_per_sec_compiled"] = round(batch / t_compute, 1)
    return out


def stage_attention_sweep():
    """Marginal TFLOP/s across (block_q, block_k) tilings of the pallas flash
    kernel at the 4k causal shape — picks the tile schedule the defaults
    should use. Banked opportunistically (not in the watcher's success gate)."""
    import jax
    import jax.numpy as jnp

    from heat_tpu.ops.flash import flash_attention_tpu

    B, S, H, D = 1, _sz(4096, 512), _sz(8, 2), _sz(128, 32)
    q, k, v = (
        jax.random.normal(kk, (B, S, H, D), jnp.float32)
        for kk in jax.random.split(jax.random.PRNGKey(4), 3)
    )
    att_flops = 4.0 * B * H * S * S * D / 2
    out = {}
    best_rate, best_cfg = 0.0, None
    for bq, bk in (
        (128, 128),
        (128, 512),
        (256, 256),
        (256, 512),
        (512, 512),
        # larger k tiles became legal with the r05 streamed-K grid (VMEM
        # holds one tile pair, not the sequence)
        (256, 1024),
        (512, 1024),
    ):
        def att(qq, kk_, vv, bq=bq, bk=bk):
            return flash_attention_tpu(qq, kk_, vv, causal=True, block_q=bq, block_k=bk)

        def chained(reps):
            @jax.jit
            def run(q, k, v):
                def body(i, qq):
                    return att(qq, k, v).astype(qq.dtype)

                return att(jax.lax.fori_loop(0, reps, body, q), k, v)

            return run

        try:
            one, more = chained(0), chained(7)
            b1 = _timeit(lambda: one(q, k, v), lambda r: float(r[0, 0, 0, 0]), reps=2)
            b8 = _timeit(lambda: more(q, k, v), lambda r: float(r[0, 0, 0, 0]), reps=2)
            marg = _marginal_sec(b1, b8, 7)
            if marg:
                rate = att_flops / marg / 1e12
                out[f"bq{bq}_bk{bk}_tflops_marginal"] = round(rate, 2)
                if rate > best_rate:
                    best_rate, best_cfg = rate, [bq, bk]
        except Exception as exc:  # noqa: BLE001 - one bad tiling must not end the sweep
            out[f"bq{bq}_bk{bk}_error"] = repr(exc)[:160]
    if best_cfg:
        out["best"] = {"block_q": best_cfg[0], "block_k": best_cfg[1], "tflops": round(best_rate, 2)}
    return out


def stage_train():
    """DP ResNet18 samples/s on the live chip (BASELINE config 5's TPU leg;
    the DASO cadence sweep needs a multi-device mesh and stays on the CPU
    matrix — benchmarks/TRAIN_THROUGHPUT_r04.json)."""
    from heat_tpu.nn import ResNet18

    return _train_one_model(ResNet18(num_classes=10), "resnet18")


def stage_train50():
    """DP ResNet-50 samples/s — BASELINE config 5 names ResNet-50/CIFAR
    specifically; ResNet18 (stage_train) stays for cross-round continuity."""
    from heat_tpu.nn import ResNet50

    return _train_one_model(ResNet50(num_classes=10), "resnet50")


def stage_train_bf16():
    """Mixed-precision DP ResNet18 (bf16 compute, f32 params/grads — the
    MXU-native training mode; flax keeps parameters f32 under dtype=bf16)."""
    import jax.numpy as jnp

    from heat_tpu.nn import ResNet18

    return _train_one_model(
        ResNet18(num_classes=10, dtype=jnp.bfloat16), "resnet18_bf16"
    )


# dict order IS capture order: a tunnel window can die at any minute, so the
# ladder banks the round's highest-value stages first — the primary bench
# config (lloyd), the roofline triad, then every stage the r04 verdict
# flagged as never-run; the mosaic bisection probes are failure diagnostics
# and run only after the product stages have had their chance
STAGES = {
    "init": stage_init,
    "capability": stage_capability,
    "lloyd_small": stage_lloyd_small,
    "lloyd_full": stage_lloyd_full,
    "lloyd_bf16": stage_lloyd_bf16,
    "cdist": stage_cdist,
    "moments_diag": stage_moments_diag,
    "qr_marginal": stage_qr_marginal,
    "cholqr2": stage_cholqr2,
    "attention": stage_attention,
    "attention_sweep": stage_attention_sweep,
    "train50": stage_train50,
    "train_bf16": stage_train_bf16,
    "train": stage_train,
    "mosaic_probe": stage_mosaic_probe,
    "mosaic_narrow": stage_mosaic_narrow,
    "mosaic_variants": stage_mosaic_variants,
}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="benchmarks/TPU_WINDOW_r05.json")
    parser.add_argument("--stages", default=",".join(STAGES))
    parser.add_argument(
        "--skip-full", action="store_true", help="skip the 10M-row lloyd_full stage"
    )
    parser.add_argument(
        "--force",
        action="store_true",
        help="re-run the listed stages even if already banked ok (kernel iteration)",
    )
    args = parser.parse_args()

    if os.environ.get("HEAT_BENCH_PLATFORM"):
        # CPU smoke-testing of the ladder itself (the axon site hook
        # overrides JAX_PLATFORMS, so select via jax.config like bench.py)
        import jax

        jax.config.update("jax_platforms", os.environ["HEAT_BENCH_PLATFORM"])

    doc = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as fh:
                doc = json.load(fh)
        except Exception:  # noqa: BLE001 - a corrupt artifact never blocks capture
            doc = {}

    wanted = [s for s in args.stages.split(",") if s in STAGES]
    if args.skip_full and "lloyd_full" in wanted:
        wanted.remove("lloyd_full")

    def _is_ok(rec) -> bool:
        return isinstance(rec, dict) and bool(rec) and not any("error" in k for k in rec)

    for name in wanted:
        prior = doc.get(name)
        # a stage re-runs if ANY of its keys records an error (lloyd_full /
        # cholqr2 bank per-path errors like fused_error / qr_tsqr_error)
        if not args.force and _is_ok(prior):
            print(f"[skip] {name}: already banked", flush=True)
            continue
        t0 = time.perf_counter()
        try:
            res = STAGES[name]()
            res["seconds"] = round(time.perf_counter() - t0, 1)
            doc[name] = res
            doc.get("attempt_errors", {}).pop(name, None)
            print(f"[ok]   {name}: {json.dumps(res)[:200]}", flush=True)
        except Exception as exc:  # noqa: BLE001 - every stage is independent
            failure = {"error": _err(exc), "seconds": round(time.perf_counter() - t0, 1)}
            # THE MERGE IS ADDITIVE: banked measurements must never vanish —
            # r04 lost its real-TPU attention capture exactly this way (a
            # --force re-run died with the backend mid-window and the error
            # record replaced the data). A prior record counts as data if it
            # carries ANY non-error key (a partially-ok stage like a banked
            # f32 marginal beside a bf16_error is still data); only a
            # missing or pure-error prior may be replaced.
            has_data = isinstance(prior, dict) and any(
                "error" not in k and k != "seconds" for k in prior
            )
            if has_data:
                failure["note"] = "banked result kept; this re-run attempt failed"
                doc.setdefault("attempt_errors", {})[name] = failure
                print(f"[fail] {name} (banked kept): {repr(exc)[:200]}", flush=True)
            else:
                doc[name] = failure
                print(f"[fail] {name}: {repr(exc)[:200]}", flush=True)
            # bare "UNAVAILABLE" is NOT enough: the per-kernel remote-compile
            # 500s this ladder exists to bisect also carry that status while
            # the backend stays up — only true bring-up failure aborts
            if "Unable to initialize backend" in repr(exc):
                # the backend itself is down: every later stage would burn
                # minutes hitting the same wall — end the attempt, the outer
                # retry loop re-enters when the tunnel answers again
                doc["captured_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
                _bank(args.out, doc)
                print("[abort] backend unavailable — ending this attempt", flush=True)
                return
        doc["captured_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        _bank(args.out, doc)

    # refresh the roofline-refinement artifact bench.py reads, when the triad
    # stage has real numbers
    cap = doc.get("capability")
    if isinstance(cap, dict) and cap.get("hbm_read_gbps") and doc.get("init", {}).get(
        "platform"
    ) not in (None, "cpu"):
        cap_doc = dict(cap)
        cap_doc["device"] = doc.get("init", {}).get("device")
        cap_doc["captured_utc"] = doc["captured_utc"]
        _bank(os.path.join(os.path.dirname(os.path.abspath(args.out)), "TPU_CAPABILITY.json"), cap_doc)


if __name__ == "__main__":
    main()

"""Lasso regression demo (analog of reference examples/lasso/demo.py).

Coordinate-descent lasso over the diabetes-like dataset, sweeping the
regularization strength and printing the coefficient paths.

Run (CPU mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/lasso_demo.py
"""

import numpy as np

import heat_tpu as ht
from heat_tpu.regression import Lasso


def main():
    X = ht.datasets.diabetes_like(split=0)
    # synthesize a sparse ground truth like the reference's diabetes target
    rng = np.random.default_rng(0)
    w = np.zeros(X.shape[1], np.float32)
    w[[1, 4, 7]] = [2.5, -1.5, 3.0]
    y_np = X.numpy() @ w + 0.05 * rng.standard_normal(X.shape[0]).astype(np.float32)
    y = ht.array(y_np[:, None], split=0)

    # normalize like the reference demo (demo.py:27-28)
    X = X / ht.sqrt(ht.mean(X**2, axis=0))

    print(f"{'lambda':>8} | nonzero coefficients")
    for lam in (0.001, 0.01, 0.1, 0.5, 1.0):
        estimator = Lasso(lam=lam, max_iter=200)
        estimator.fit(X, y)
        coef = np.asarray(estimator.coef_.numpy()).ravel()
        nz = np.flatnonzero(np.abs(coef) > 1e-3)
        print(f"{lam:8.3f} | {len(nz)} of {len(coef)}: {np.round(coef[nz], 3).tolist()}")


if __name__ == "__main__":
    main()

"""KNN classification demo (analog of reference examples/classification/demo_knn.py).

Train/verify split over the iris-like dataset with a leave-chunk-out loop;
reports classification accuracy per fold.

Run (CPU mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/knn_demo.py
"""

import numpy as np

import heat_tpu as ht
from heat_tpu.classification import KNeighborsClassifier


def calculate_accuracy(new_y, verification_y):
    """Fraction of matching integer labels (reference demo_knn.py:27-52)."""
    a = np.asarray(new_y.numpy()).ravel()
    b = np.asarray(verification_y.numpy()).ravel()
    return float((a == b).mean())


def main():
    X, Y = ht.datasets.iris_like(split=0, return_labels=True)
    n = X.shape[0]
    fold = n // 5

    accuracies = []
    for k in range(5):
        lo, hi = k * fold, (k + 1) * fold
        mask = np.ones(n, dtype=bool)
        mask[lo:hi] = False
        train_x = X[np.nonzero(mask)[0]]
        train_y = Y[np.nonzero(mask)[0]]
        test_x = X[np.arange(lo, hi)]
        test_y = Y[np.arange(lo, hi)]

        knn = KNeighborsClassifier(n_neighbors=5).fit(train_x, train_y)
        pred = knn.predict(test_x)
        acc = calculate_accuracy(pred, test_y)
        accuracies.append(acc)
        # heat-lint: disable=H002 — one accuracy read per CV fold is the demo's output
        print(f"fold {k}: accuracy {acc:.3f}")

    print(f"mean accuracy: {np.mean(accuracies):.3f}")


if __name__ == "__main__":
    main()

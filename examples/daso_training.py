"""DASO hierarchical training demo (reference: the DASO usage pattern in
heat/optim/dp_optimizer.py's docstring / examples).

Trains a ResNet on synthetic CIFAR-shaped data over a (dcn x ici) mesh with
the skip-scheduled global synchronization."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import heat_tpu as ht


def main():
    rng = np.random.default_rng(0)
    n, classes = 512, 10
    X = rng.standard_normal((n, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, classes, n).astype(np.int32)

    epochs = 6
    daso = ht.optim.DASO(
        local_optimizer=ht.optim.SGD(0.05),
        total_epochs=epochs,
        warmup_epochs=1,
        cooldown_epochs=1,
        verbose=True,
    )
    print(f"topology: {daso.nodes} DCN group(s) x {daso.ici_size} ICI device(s)")
    model = ht.nn.ResNet(stage_sizes=(1, 1), num_classes=classes, num_filters=16)
    daso.add_model(model, 0, X[:4])

    batch = 64
    for epoch in range(epochs):
        losses = []
        for b in range(0, n, batch):
            losses.append(daso.step(X[b : b + batch], y[b : b + batch]))
        epoch_loss = float(np.mean(losses))
        daso.epoch_loss_logic(epoch_loss)
        # heat-lint: disable=H002 — per-epoch progress line over host-side scalars
        print(f"epoch {epoch}: loss {epoch_loss:.4f} (global_skips={daso.global_skip})")


if __name__ == "__main__":
    main()

"""Clustering demo (reference: examples/ cluster demo on the iris dataset).

Runs KMeans / KMedians / KMedoids / Spectral on the bundled iris-like dataset
and prints label distributions.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import heat_tpu as ht


def main():
    x, y = ht.datasets.iris_like(split=0, return_labels=True)
    print(f"dataset: {x.shape} on {ht.get_comm().size} device(s)")
    for name, est in [
        ("KMeans", ht.cluster.KMeans(n_clusters=3, init="kmeans++", random_state=0)),
        ("KMedians", ht.cluster.KMedians(n_clusters=3, init="kmeans++", random_state=0)),
        ("KMedoids", ht.cluster.KMedoids(n_clusters=3, init="kmeans++", random_state=0)),
        ("Spectral", ht.cluster.Spectral(n_clusters=3, gamma=0.5, n_lanczos=50, random_state=0)),
    ]:
        est.fit(x)
        # heat-lint: disable=H002 — one labels read per fitted estimator IS the output
        labels = est.labels_.numpy()
        counts = np.bincount(labels, minlength=3)
        # heat-lint: disable=H002 — host-side numpy counts; one line per estimator
        print(f"{name:10s} cluster sizes: {counts.tolist()}")


if __name__ == "__main__":
    main()

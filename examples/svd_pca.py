"""PCA via the distributed TSQR-based SVD.

Projects sharded observations onto their top principal components without
any host gather: the tall factor U stays split over devices end-to-end.

Run:  python examples/svd_pca.py  (any backend; uses all visible devices)
"""

import numpy as np

import heat_tpu as ht


def main() -> None:
    ht.random.seed(0)
    n, f, k = 4000, 16, 3

    # anisotropic blob: 3 dominant directions buried in 16-D noise
    basis = ht.random.randn(f, k)
    weights = ht.random.randn(n, k, split=0)
    x = weights @ basis.T + 0.05 * ht.random.randn(n, f, split=0)

    # center, decompose, project — all sharded over the sample axis
    x = x - ht.mean(x, axis=0)
    u, s, vh = ht.linalg.svd(x, full_matrices=False)
    explained = (s * s) / float(ht.sum(s * s).item())
    scores = x @ vh.T[:, :k]  # (n, k), split preserved

    print("singular values:", np.round(np.asarray(s.larray)[:6], 2))
    print("explained variance (top 6):", np.round(np.asarray(explained.larray)[:6], 4))
    print("scores split:", scores.split, "shape:", tuple(scores.shape))
    top3 = float(ht.sum(explained[:k]).item())
    print(f"top-{k} components explain {top3:.1%}")
    assert top3 > 0.95, "anisotropic data should concentrate in 3 components"


if __name__ == "__main__":
    main()

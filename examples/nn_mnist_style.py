"""Data-parallel training demo (reference: examples/nn/mnist.py:49-66).

Trains the SimpleCNN on MNIST when torchvision is available, otherwise on a
synthetic image-classification task — through DataLoader + DataParallel, the
same pipeline shape as the reference example.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import heat_tpu as ht


def get_data():
    try:
        ds = ht.utils.data.MNISTDataset("/tmp/mnist-data", train=True)
        print("using MNIST")
        return ds
    except Exception:
        rng = np.random.default_rng(0)
        n, classes = 4096, 10
        templates = rng.standard_normal((classes, 28, 28)).astype(np.float32)
        y = rng.integers(0, classes, n)
        X = templates[y] + 0.3 * rng.standard_normal((n, 28, 28)).astype(np.float32)
        print("torchvision unavailable -> synthetic digits")
        return ht.utils.data.Dataset(
            [ht.array(X, split=0), ht.array(y.astype(np.int32), split=0)]
        )


def main():
    dataset = get_data()
    loader = ht.utils.data.DataLoader(dataset, batch_size=128, shuffle=True)

    model = ht.nn.SimpleCNN(num_classes=10)
    dp = ht.nn.DataParallel(model, optimizer=ht.optim.Adam(1e-3))
    sample, _ = dataset[0:8]
    dp.init(0, sample)

    for epoch in range(3):
        losses = [dp.train_step(xb, yb) for xb, yb in loader]
        # heat-lint: disable=H002 — per-epoch progress line over host-side losses
        print(f"epoch {epoch}: loss {np.mean(losses):.4f}")

    xb, yb = dataset[0:512]
    acc = float(np.mean(np.argmax(np.asarray(dp(xb)), axis=1) == np.asarray(yb)))
    print(f"train accuracy on 512 samples: {acc:.3f}")


if __name__ == "__main__":
    main()

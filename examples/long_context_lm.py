"""Long-context language model: train dense, run inference sequence-parallel.

The TransformerLM's attention kernel is injected, so ONE set of parameters
serves both deployments: a data-parallel training step with dense attention
(short sequences), then a sequence-parallel forward with ring attention over
the whole mesh — the attention contraction never materializes the S x S
score matrix, so per-chip attention memory is O(S/p).

Run: python examples/long_context_lm.py  (any backend; uses all devices)
"""

import functools
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import optax

    import heat_tpu as ht
    from heat_tpu.nn import DataParallel, TransformerLM
    from heat_tpu.nn.attention import ring_attention

    comm = ht.get_comm()
    p = comm.size
    vocab, dim, depth, heads = 64, 32, 2, 4
    S_train, S_long = 16, 16 * p  # inference sequence grows with the mesh

    # --- train with dense attention over the data-parallel axis ----------
    model = TransformerLM(vocab=vocab, dim=dim, depth=depth, heads=heads, max_len=S_long)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, vocab, (4 * p, S_train))

    def next_token_loss(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], labels[:, 1:]
        ).mean()

    dp = DataParallel(model, optimizer=optax.adam(1e-2), loss_fn=next_token_loss)
    dp.init(0, toks[:2])
    for step in range(8):
        loss = dp.train_step(toks, toks)
        if step % 4 == 0:
            # heat-lint: disable=H002 — progress line every 4th step; loss is a host float
            print(f"step {step}: loss {loss:.4f}")

    # --- same parameters, sequence-parallel long-context forward ---------
    sp_model = TransformerLM(
        vocab=vocab, dim=dim, depth=depth, heads=heads, max_len=S_long,
        attention_fn=functools.partial(ring_attention, comm=comm),
    )
    long_toks = jnp.asarray(rng.integers(0, vocab, (1, S_long)))
    logits = sp_model.apply(dp.params, long_toks)
    dense_logits = model.apply(dp.params, long_toks)
    drift = float(jnp.max(jnp.abs(logits - dense_logits)))
    print(f"sequence-parallel forward at S={S_long} over {p} device(s); "
          f"max drift vs dense: {drift:.2e}")
    assert drift < 1e-3
    print("ok")


if __name__ == "__main__":
    main()

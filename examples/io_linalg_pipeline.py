"""End-to-end data pipeline: sharded HDF5 I/O -> distributed linear algebra.

Demonstrates the round-3 subsystems working together (the reference's
analogous flow is load_hdf5 -> qr / solvers across MPI ranks):

1. write a feature matrix to HDF5, streamed shard by shard;
2. load it back with per-device hyperslab reads (split=0);
3. orthogonalize with distributed TSQR;
4. solve a least-squares problem via R x = Q^T b with the
   SquareDiagTiles-blocked triangular solve;
5. smooth a signal with the halo-exchange convolution.

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/io_linalg_pipeline.py
"""

import os
import tempfile

import numpy as np

import heat_tpu as ht


def main() -> None:
    rng = np.random.default_rng(0)
    p = ht.get_comm().size
    m, n = 64 * max(p, 1), 12

    # ground-truth least-squares problem
    A_np = rng.standard_normal((m, n))
    coef = rng.standard_normal(n)
    b_np = A_np @ coef + 0.01 * rng.standard_normal(m)

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "features.h5")
        ht.save_hdf5(ht.array(A_np, split=0), path, "A")  # streamed per shard
        A = ht.load_hdf5(path, "A", dtype=ht.float64, split=0)  # per-device hyperslabs

    Q, R = ht.linalg.qr(A)  # TSQR: one R-tile all-gather
    qt_b = Q.T @ ht.array(b_np, split=0)
    x = ht.linalg.solve_triangular(R, qt_b)  # blocked substitution
    rel_err = float(np.linalg.norm(x.numpy() - coef) / np.linalg.norm(coef))
    print(f"least-squares relative error: {rel_err:.2e}")

    # halo-exchange smoothing of a noisy signal
    noisy = ht.array(np.sin(np.linspace(0, 6, 16 * p)) + 0.1 * rng.standard_normal(16 * p), split=0)
    kernel = ht.array(np.ones(5) / 5.0)
    smooth = ht.convolve(noisy, kernel, mode="same")  # ppermute halos + local conv
    print(f"smoothed variance: {float(smooth.var().item()):.4f}")


if __name__ == "__main__":
    main()

"""Distribution-flow verification demo: static cost budgets before running.

The verifier (``python -m heat_tpu.analysis verify``) interprets Python
source over the ``(rank, split, device-set, pending|forced)`` lattice and
lower-bounds every region's bytes-on-wire BEFORE anything executes — so a
CI gate can refuse an algorithm whose collective bill grew, without a mesh.

This demo builds two versions of a centering pipeline, asks the verifier
for their static cost, and shows a budget that passes on the sharded
version and fails on the gather-everything version. Pure static analysis:
the target sources below are never executed.

Run:  python examples/verify_budget_demo.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from heat_tpu.analysis import dataflow

MESH = 8

SHARDED = """
import heat_tpu as ht

def center(n, f):
    x = ht.random.randn(n, f, split=0)
    return x - ht.mean(x, axis=0)   # psums one (f,) row: tiny

y = center(65536, 64)
"""

GATHERING = """
import heat_tpu as ht

def center(n, f):
    x = ht.random.randn(n, f, split=0)
    g = ht.resplit(x, None)  # heat-lint: disable=S103 -- the demo's anti-pattern
    return g - ht.mean(g, axis=0)

y = center(65536, 64)
"""


def report(tag: str, src: str, budget_bytes: int) -> None:
    findings, stats = dataflow.verify_source(
        src, f"<{tag}>", mesh_size=MESH, budgets={"*center": budget_bytes}
    )
    region = stats["regions"].get(f"<{tag}>::center", {"bytes": 0, "cost": {}})
    verdict = "OVER BUDGET" if any(f.rule == "S105" for f in findings) else "ok"
    print(f"{tag:>9}: static lower bound {region['bytes']:>10} B  "
          f"{dict(region['cost'])}  -> {verdict} (budget {budget_bytes} B)")


def main() -> None:
    print(f"static cost model at mesh {MESH} (nothing below is executed):")
    budget = 1 << 20  # 1 MiB on the wire for the centering region
    report("sharded", SHARDED, budget)
    report("gathering", GATHERING, budget)
    # the drift contract keeps the byte formulas honest against telemetry
    static = dataflow.static_workload_bytes("qr_cholqr2", MESH)
    print(f"drift workload qr_cholqr2 static estimate: {static} "
          "(bench diffs this against telemetry-observed bytes, 2x bound)")


if __name__ == "__main__":
    main()

"""Compiled ht pipelines: jax.jit / jax.grad over DNDarrays.

DNDarray is a registered JAX pytree (heat_tpu/core/dndarray.py:_tree_flatten),
so whole ``ht.*`` call chains compile into ONE XLA program — one dispatch per
pipeline call instead of one per op. The reference (torch + mpi4py,
reference heat/core/dndarray.py) is eager-only: every op pays kernel-launch
and, on a remote accelerator, a host round-trip.

The demo fits a tiny ridge regression by gradient descent where the WHOLE
update step — prediction, loss, gradient, parameter update — is a single
compiled program over distributed arrays.
"""

import time

import jax
import numpy as np

import heat_tpu as ht


def main() -> None:
    rng = np.random.default_rng(0)
    n, f = 4096, 16
    w_true = rng.standard_normal(f).astype(np.float32)
    xn = rng.standard_normal((n, f)).astype(np.float32)
    yn = xn @ w_true + 0.01 * rng.standard_normal(n).astype(np.float32)

    x = ht.array(xn, split=0)  # rows sharded over the mesh
    y = ht.array(yn, split=0)
    w = ht.zeros(f, dtype=ht.float32)  # replicated parameters

    def loss_fn(w):
        resid = ht.linalg.matmul(x, w) - y
        return (ht.mean(resid * resid) + 1e-4 * ht.sum(w * w)).larray

    @jax.jit  # ONE program: forward + backward + update, collectives included
    def step(w):
        loss, g = jax.value_and_grad(loss_fn)(w)
        return w - 0.5 * g, loss

    t0 = time.perf_counter()
    for i in range(60):
        w, loss = step(w)  # loss is evaluated at the PRE-update iterate
        # bound in-flight programs to ONE: each step's program contains a
        # cross-partition all-reduce, and on a forced-host-device CPU mesh
        # with few cores, many queued collective programs can starve XLA's
        # spin-wait rendezvous past its hard 40 s abort (observed at mesh 5
        # on a 1-core sandbox). The "whole step is one compiled program"
        # point is unaffected; real accelerator backends pipeline fine, but
        # the example must be robust where the test matrix runs it.
        # heat-lint: disable=H002 — the per-step sync is deliberate (see above)
        float(loss)
    elapsed = time.perf_counter() - t0

    err = float(np.abs(w.numpy() - w_true).max())
    print(f"compiled steps: 60 in {elapsed * 1e3:.1f} ms, loss at step 59: {float(loss):.5f}")
    print(f"max |w - w_true| error {err:.4f}")
    assert err < 0.05, "gradient descent did not converge"

    # the same pipeline runs eagerly (per-op dispatch) — identical numbers:
    # both evaluate the loss at the final iterate w_60
    resid = ht.linalg.matmul(x, w) - y
    eager_final = float((ht.mean(resid * resid) + 1e-4 * ht.sum(w * w)).larray)
    compiled_final = float(jax.jit(loss_fn)(w))
    print(f"eager vs compiled loss at w_60: {eager_final:.5f} / {compiled_final:.5f}")
    assert abs(eager_final - compiled_final) < 1e-5


if __name__ == "__main__":
    main()

"""Lasso regression (reference: heat/regression/lasso.py).

Coordinate descent with soft thresholding (reference lasso.py:90-176). Each
per-feature update's dot products are sharded reductions; the whole feature
sweep is compiled as one XLA program with ``lax.fori_loop`` instead of a
Python loop, so an iteration is a single device program rather than the
reference's per-feature Allreduce chain.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import factories, types
from ..core.base import BaseEstimator, RegressionMixin
from ..core.dndarray import DNDarray, _ensure_split

__all__ = ["Lasso"]


@partial(jax.jit, static_argnames=())
def _cd_sweep(X: jax.Array, y: jax.Array, theta: jax.Array, lam: jnp.float32):
    """One full coordinate-descent sweep over all features (feature 0 is the
    unpenalized intercept, reference lasso.py:120-141)."""
    n, m = X.shape

    def body(j, th):
        X_j = X[:, j]
        y_est = X @ th
        rho = X_j @ (y.reshape(-1) - y_est.reshape(-1) + th[j, 0] * X_j) / n
        # soft threshold for j>0; intercept updated without penalty
        new = jnp.where(
            j == 0,
            rho,
            jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - lam, 0.0),
        )
        return th.at[j, 0].set(new)

    return jax.lax.fori_loop(0, m, body, theta)


class Lasso(RegressionMixin, BaseEstimator):
    """Least absolute shrinkage and selection operator (reference lasso.py:14-89).

    Parameters
    ----------
    lam : float
        L1 penalty strength.
    max_iter : int
    tol : float
    """

    def __init__(self, lam: float = 0.1, max_iter: int = 100, tol: float = 1e-6):
        self.__lam = lam
        self.max_iter = max_iter
        self.tol = tol
        self.__theta = None
        self.n_iter = None

    @property
    def lam(self) -> float:
        return self.__lam

    @lam.setter
    def lam(self, arg: float):
        self.__lam = arg

    @property
    def coef_(self) -> Optional[DNDarray]:
        return None if self.__theta is None else self.__theta[1:]

    @property
    def intercept_(self) -> Optional[DNDarray]:
        return None if self.__theta is None else self.__theta[0]

    @property
    def theta(self):
        return self.__theta

    def fit(self, x: DNDarray, y: DNDarray) -> "Lasso":
        """Coordinate-descent fit (reference lasso.py:90-141)."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y must be DNDarrays")
        if x.ndim != 2:
            raise ValueError(f"x needs to be 2D, but was {x.ndim}D")
        if y.ndim > 2:
            raise ValueError(f"y needs to be 1D or 2D, but was {y.ndim}D")

        # as in the reference, the first column of x is treated as the
        # (unregularized) intercept feature — no ones column is prepended
        # (reference lasso.py:150-165)
        X = x.larray.astype(jnp.float32)
        yl = y.larray.astype(jnp.float32).reshape(-1, 1)
        n, m = X.shape
        theta = jnp.zeros((m, 1), jnp.float32)

        for it in range(self.max_iter):
            theta_old = theta
            theta = _cd_sweep(X, yl, theta, jnp.float32(self.__lam))
            # rmse convergence criterion, as in reference lasso.py:166-171
            diff = float(jnp.sqrt(jnp.mean((theta - theta_old) ** 2)))
            if self.tol is not None and diff < self.tol:
                break
        self.n_iter = it + 1
        arr = _ensure_split(theta, None, x.comm)
        self.__theta = DNDarray(
            arr, tuple(arr.shape), types.canonical_heat_type(arr.dtype), None, x.device, x.comm
        )
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Linear prediction with learned coefficients (reference lasso.py:142-176)."""
        if self.__theta is None:
            raise RuntimeError("fit needs to be called before predict")
        pred = x.larray.astype(jnp.float32) @ self.__theta.larray
        pred = _ensure_split(pred, x.split, x.comm)
        return DNDarray(
            pred, tuple(pred.shape), types.canonical_heat_type(pred.dtype), x.split, x.device, x.comm
        )

"""Lasso regression (reference: heat/regression/lasso.py).

Coordinate descent with soft thresholding (reference lasso.py:90-176). Each
per-feature update's dot products are sharded reductions; the whole feature
sweep is compiled as one XLA program with ``lax.fori_loop`` instead of a
Python loop, so an iteration is a single device program rather than the
reference's per-feature Allreduce chain.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import factories, types
from ..core.base import BaseEstimator, RegressionMixin
from ..core.dndarray import DNDarray, _ensure_split

__all__ = ["Lasso"]


@partial(jax.jit, static_argnames=())
def _cd_sweep(XT: jax.Array, y: jax.Array, theta: jax.Array, lam: jnp.float32):
    """One full coordinate-descent sweep over all features (feature 0 is the
    unpenalized intercept, reference lasso.py:120-141).

    The residual is computed ONCE per sweep and updated incrementally after
    each coordinate step (``r -= Δθ_j · X_j``), so a sweep costs O(n·m)
    instead of the reference's O(n·m²) full ``X @ θ`` per feature — the
    same iterates up to rounding (the residual is refreshed from scratch
    every sweep, bounding drift). The operand arrives TRANSPOSED ((m, n),
    features in rows) so each coordinate's slice is contiguous — a per-
    feature column gather out of the (n, m) layout costs ~stride-m reads
    per element and dominated the sweep. The collective budget is
    unchanged: the per-feature ``rho`` contraction is the sweep's ONE
    row-axis all-reduce (the samples stay sharded on axis 1 of the
    transpose), everything else is local to the shards."""
    m, n = XT.shape
    r = y.reshape(-1) - theta.reshape(-1) @ XT

    def body(j, carry):
        r, th = carry
        X_j = jax.lax.dynamic_slice_in_dim(XT, j, 1, axis=0)[0]  # (n,) contiguous
        th_j = th[j, 0]
        rho = X_j @ (r + th_j * X_j) / n  # psum over the sharded samples
        # soft threshold for j>0; intercept updated without penalty
        new = jnp.where(
            j == 0,
            rho,
            jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - lam, 0.0),
        )
        r = r - (new - th_j) * X_j
        return r, th.at[j, 0].set(new)

    _, theta = jax.lax.fori_loop(0, m, body, (r, theta))
    return theta


# features-squared budget for replicating the Gram matrix (the same spirit as
# linalg.qr's _REPLICATED_MAX_ELEMENTS): above this, fit falls back to the
# incremental-residual sweep
_GRAM_MAX_ELEMENTS = 1 << 22


@partial(jax.jit, static_argnames=())
def _gram_precompute(XT: jax.Array, y: jax.Array):
    """(G, cy) = (X'X, X'y) — the fit's ONLY distributed contractions in
    Gram mode: one matmul + one matvec over the sharded samples, each ending
    in a single all-reduce. Both results are (m, m)/(m,) and replicated."""
    return XT @ XT.T, (XT @ y.reshape(-1, 1)).reshape(-1)


@partial(jax.jit, static_argnames=())
def _cd_sweep_gram(G: jax.Array, cy: jax.Array, theta: jax.Array, lam: jnp.float32, n: int):
    """One coordinate-descent sweep in the covariance-update form (sklearn's
    ``precompute=True``): with ``G = X'X`` and ``cy = X'y`` replicated, the
    per-feature statistic is ``rho_j = (cy_j - Σ_{i≠j} G_ji θ_i) / n`` and a
    coordinate step only touches the m-vector ``c = cy - G @ θ`` — the sweep
    is PURELY LOCAL (zero collectives; pinned by tests/test_mesh64_compile
    style HLO counting in tests/test_ml.py). Identical iterates to the
    residual form in exact arithmetic."""
    c = cy - G @ theta.reshape(-1)

    def body(j, carry):
        c, th = carry
        th_j = th[j, 0]
        g_j = jax.lax.dynamic_slice_in_dim(G, j, 1, axis=0)[0]  # (m,)
        rho = (c[j] + th_j * g_j[j]) / n
        new = jnp.where(
            j == 0,
            rho,
            jnp.sign(rho) * jnp.maximum(jnp.abs(rho) - lam, 0.0),
        )
        c = c - (new - th_j) * g_j
        return c, th.at[j, 0].set(new)

    _, theta = jax.lax.fori_loop(0, G.shape[0], body, (c, theta))
    return theta


class Lasso(RegressionMixin, BaseEstimator):
    """Least absolute shrinkage and selection operator (reference lasso.py:14-89).

    Parameters
    ----------
    lam : float
        L1 penalty strength.
    max_iter : int
    tol : float
    """

    def __init__(self, lam: float = 0.1, max_iter: int = 100, tol: float = 1e-6):
        self.__lam = lam
        self.max_iter = max_iter
        self.tol = tol
        self.__theta = None
        self.n_iter = None

    @property
    def lam(self) -> float:
        return self.__lam

    @lam.setter
    def lam(self, arg: float):
        self.__lam = arg

    @property
    def coef_(self) -> Optional[DNDarray]:
        return None if self.__theta is None else self.__theta[1:]

    @property
    def intercept_(self) -> Optional[DNDarray]:
        return None if self.__theta is None else self.__theta[0]

    @property
    def theta(self):
        return self.__theta

    def fit(self, x: DNDarray, y: DNDarray) -> "Lasso":
        """Coordinate-descent fit (reference lasso.py:90-141)."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y must be DNDarrays")
        if x.ndim != 2:
            raise ValueError(f"x needs to be 2D, but was {x.ndim}D")
        if y.ndim > 2:
            raise ValueError(f"y needs to be 1D or 2D, but was {y.ndim}D")

        # as in the reference, the first column of x is treated as the
        # (unregularized) intercept feature — no ones column is prepended
        # (reference lasso.py:150-165)
        X = x.larray.astype(jnp.float32)
        yl = y.larray.astype(jnp.float32).reshape(-1, 1)
        n, m = X.shape
        XT = jnp.transpose(X)  # one pass; every sweep slice is contiguous
        theta = jnp.zeros((m, 1), jnp.float32)

        # Gram (covariance-update) mode whenever the (m, m) Gram replicates
        # cheaply: ALL sample-axis contractions happen once up front (one
        # matmul + one matvec, one all-reduce each) and every sweep is then
        # local m-vector work — the collective budget per fit drops from
        # m·iterations all-reduces to two. Falls back to the incremental-
        # residual sweep for very wide operands.
        gram_mode = m * m <= _GRAM_MAX_ELEMENTS and n >= m
        if gram_mode:
            G, cy = _gram_precompute(XT, yl)

        for it in range(self.max_iter):
            theta_old = theta
            if gram_mode:
                theta = _cd_sweep_gram(G, cy, theta, jnp.float32(self.__lam), n)
            else:
                theta = _cd_sweep(XT, yl, theta, jnp.float32(self.__lam))
            # rmse convergence criterion, as in reference lasso.py:166-171
            diff = float(jnp.sqrt(jnp.mean((theta - theta_old) ** 2)))
            if self.tol is not None and diff < self.tol:
                break
        self.n_iter = it + 1
        arr = _ensure_split(theta, None, x.comm)
        self.__theta = DNDarray(
            arr, tuple(arr.shape), types.canonical_heat_type(arr.dtype), None, x.device, x.comm
        )
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Linear prediction with learned coefficients (reference lasso.py:142-176)."""
        if self.__theta is None:
            raise RuntimeError("fit needs to be called before predict")
        pred = x.larray.astype(jnp.float32) @ self.__theta.larray
        pred = _ensure_split(pred, x.split, x.comm)
        return DNDarray(
            pred, tuple(pred.shape), types.canonical_heat_type(pred.dtype), x.split, x.device, x.comm
        )

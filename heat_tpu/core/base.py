"""sklearn-compatible estimator base classes (reference: heat/core/base.py:13-258)."""

from __future__ import annotations

import inspect
import json
from typing import Dict, List, Optional

__all__ = [
    "BaseEstimator",
    "ClassificationMixin",
    "ClusteringMixin",
    "RegressionMixin",
    "TransformMixin",
    "is_classifier",
    "is_clusterer",
    "is_estimator",
    "is_regressor",
    "is_transformer",
]


class BaseEstimator:
    """Base for all estimators; parameter introspection via __init__ signature
    (reference base.py:13-97)."""

    @classmethod
    def _parameter_names(cls) -> List[str]:
        init = cls.__init__
        if init is object.__init__:
            return []
        sig = inspect.signature(init)
        return [
            name
            for name, p in sig.parameters.items()
            if name != "self" and p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
        ]

    def get_params(self, deep: bool = True) -> Dict:
        """Parameter dict of this estimator (reference base.py:29)."""
        params = {}
        for key in self._parameter_names():
            value = getattr(self, key, None)
            if deep and hasattr(value, "get_params"):
                for sub_key, sub_value in value.get_params().items():
                    params[f"{key}__{sub_key}"] = sub_value
            params[key] = value
        return params

    def set_params(self, **params) -> "BaseEstimator":
        """Set estimator parameters, supporting nested `a__b` keys (reference base.py:62)."""
        if not params:
            return self
        valid = self.get_params(deep=True)
        nested = {}
        for key, value in params.items():
            key, delim, sub_key = key.partition("__")
            if key not in valid:
                raise ValueError(f"Invalid parameter {key} for estimator {self}")
            if delim:
                nested.setdefault(key, {})[sub_key] = value
            else:
                setattr(self, key, value)
                valid[key] = value
        for key, sub_params in nested.items():
            valid[key].set_params(**sub_params)
        return self

    def __repr__(self, indent: int = 1) -> str:
        params = {k: v for k, v in self.get_params(deep=False).items()}
        return f"{self.__class__.__name__}({params})"


class ClassificationMixin:
    """Mixin for classifiers (reference base.py:98-144)."""

    def fit(self, x, y):
        raise NotImplementedError()

    def fit_predict(self, x, y):
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x):
        raise NotImplementedError()


class TransformMixin:
    """Mixin for transformers (reference base.py)."""

    def fit(self, x):
        raise NotImplementedError()

    def fit_transform(self, x):
        return self.fit(x).transform(x)

    def transform(self, x):
        raise NotImplementedError()


class ClusteringMixin:
    """Mixin for clusterers (reference base.py:145-175)."""

    def fit(self, x):
        raise NotImplementedError()

    def fit_predict(self, x):
        self.fit(x)
        return self.predict(x)


class RegressionMixin:
    """Mixin for regressors (reference base.py:176-220)."""

    def fit(self, x, y):
        raise NotImplementedError()

    def fit_predict(self, x, y):
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x):
        raise NotImplementedError()


def is_classifier(estimator) -> bool:
    """(reference base.py:221)"""
    return isinstance(estimator, ClassificationMixin)


def is_estimator(estimator) -> bool:
    """(reference base.py:230)"""
    return isinstance(estimator, BaseEstimator)


def is_regressor(estimator) -> bool:
    """(reference base.py:248)"""
    return isinstance(estimator, RegressionMixin)


def is_transformer(estimator) -> bool:
    """(reference base.py:239)"""
    return isinstance(estimator, TransformMixin)


def is_clusterer(estimator) -> bool:
    """(reference base.py:245)"""
    return isinstance(estimator, ClusteringMixin)

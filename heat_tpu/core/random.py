"""Parallel random number generation.

The reference implements a counter-based Threefry-2x32/64 cipher in torch ops
(reference heat/core/random.py:876-1040) with per-rank counter intervals
(:55-200) so results are identical at any world size. JAX's native PRNG *is*
counter-based Threefry-2x32 — the exact same construction — so this module is
a stateful (seed, counter) veneer over ``jax.random`` keys: every draw folds
the call counter into the key, outputs are generated globally and sharded by
GSPMD, and the world-size-independence property holds by construction.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import devices as devices_module
from . import factories, types
from .communication import sanitize_comm
from .dndarray import DNDarray, _ensure_split
from .stride_tricks import sanitize_shape

__all__ = [
    "get_state",
    "normal",
    "permutation",
    "rand",
    "randint",
    "randn",
    "random",
    "random_integer",
    "random_sample",
    "randperm",
    "ranf",
    "sample",
    "seed",
    "set_state",
    "standard_normal",
    "uniform",
]

# global RNG state: (seed, counter) — reference random.py:39-43
__seed: int = None  # type: ignore[assignment]
__counter: int = 0


def _ensure_seeded() -> None:
    global __seed
    if __seed is None:
        seed(None)


def _next_key() -> jax.Array:
    """Fold the draw counter into the seed key (the Threefry counter step,
    reference random.py:55-200)."""
    global __counter
    _ensure_seeded()
    key = jax.random.fold_in(jax.random.PRNGKey(__seed), __counter)
    __counter += 1
    return key


def seed(new_seed: Optional[int] = None) -> None:
    """Seed the global generator (reference random.py:772-790)."""
    global __seed, __counter
    if new_seed is None:
        new_seed = int(time.time() * 1000) % (2**31)
    __seed = int(new_seed)
    __counter = 0


def get_state() -> Tuple[str, int, int, int, float]:
    """Internal state tuple, reference layout ('Threefry', seed, counter, 0, 0.0)
    (reference random.py:203-219)."""
    _ensure_seeded()
    return ("Threefry", __seed, __counter, 0, 0.0)


def set_state(state: Tuple) -> None:
    """Restore generator state (reference random.py:791-826)."""
    global __seed, __counter
    if not isinstance(state, tuple) or len(state) not in (3, 5):
        raise TypeError("state needs to be a 3- or 5-tuple")
    if state[0] != "Threefry":
        raise ValueError("algorithm must be 'Threefry'")
    __seed = int(state[1])
    __counter = int(state[2])


def _wrap(arr: jax.Array, split, device, comm) -> DNDarray:
    comm = sanitize_comm(comm)
    device = devices_module.sanitize_device(device)
    arr = _ensure_split(arr, split if arr.ndim else None, comm)
    return DNDarray(
        arr, tuple(arr.shape), types.canonical_heat_type(arr.dtype), split if arr.ndim else None,
        device, comm,
    )


def _float_dtype(dtype):
    if dtype is None:
        return types.float32
    dtype = types.canonical_heat_type(dtype)
    if dtype not in (types.float32, types.float64, types.bfloat16, types.float16):
        raise ValueError(f"Unsupported dtype {dtype} for random floats")
    return dtype


def rand(*d, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0, 1) samples (reference random.py:268-319)."""
    if len(d) == 0:
        shape = ()
    elif len(d) == 1 and isinstance(d[0], (tuple, list)):
        shape = sanitize_shape(d[0])
    else:
        shape = sanitize_shape(d)
    dtype = _float_dtype(dtype)
    arr = jax.random.uniform(_next_key(), shape, dtype=dtype.jax_type())
    if not shape:
        return _wrap(arr, None, device, comm)
    return _wrap(arr, split, device, comm)


def randint(
    low: int,
    high: Optional[int] = None,
    size=None,
    dtype=None,
    split=None,
    device=None,
    comm=None,
) -> DNDarray:
    """Uniform integers in [low, high) (reference random.py:320-421)."""
    if high is None:
        low, high = 0, low
    if size is None:
        size = ()
    shape = sanitize_shape(size)
    if high <= low:
        raise ValueError("low >= high")
    dtype = types.canonical_heat_type(dtype) if dtype is not None else types.int32
    if not types.heat_type_is_exact(dtype):
        raise ValueError("Unsupported dtype for randint")
    # draw in the widest dtype the range requires (int64 needs x64 mode)
    draw_dtype = jnp.int32
    if int(high) > np.iinfo(np.int32).max or int(low) < np.iinfo(np.int32).min:
        if not jax.config.jax_enable_x64:
            raise ValueError(
                f"randint range [{low}, {high}) exceeds int32 and 64-bit mode is "
                "disabled (enable jax_enable_x64 for int64 sampling)"
            )
        draw_dtype = jnp.int64
    arr = jax.random.randint(_next_key(), shape, low, high, dtype=draw_dtype).astype(
        dtype.jax_type()
    )
    return _wrap(arr, split, device, comm)


random_integer = randint


def randn(*d, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Standard-normal samples via the counter-based generator (reference
    random.py:422-477; the reference's Kundu transform :248-267 is replaced by
    JAX's native normal sampling on the same Threefry bits)."""
    if len(d) == 1 and isinstance(d[0], (tuple, list)):
        shape = sanitize_shape(d[0])
    else:
        shape = sanitize_shape(d)
    dtype = _float_dtype(dtype)
    arr = jax.random.normal(_next_key(), shape, dtype=dtype.jax_type())
    return _wrap(arr, split, device, comm)


def standard_normal(shape=None, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Standard normal distribution (reference random.py:827-852)."""
    if shape is None:
        shape = ()
    return randn(*sanitize_shape(shape), dtype=dtype, split=split, device=device, comm=comm)


def normal(mean=0.0, std=1.0, shape=None, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Normal distribution with given mean/std (reference random.py:478-529)."""
    if shape is None:
        shape = ()
    base = standard_normal(shape, dtype, split, device, comm)
    mean_v = mean.larray if isinstance(mean, DNDarray) else mean
    std_v = std.larray if isinstance(std, DNDarray) else std
    arr = base.larray * std_v + mean_v
    return _wrap(arr, split, device, comm)


def random(shape=None, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0, 1) samples, numpy naming (reference random.py:530-560)."""
    if shape is None:
        shape = ()
    return rand(*sanitize_shape(shape), dtype=dtype, split=split, device=device, comm=comm)


random_sample = random
ranf = random
sample = random


def uniform(low=0.0, high=1.0, size=None, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [low, high) samples (reference random.py:853-875)."""
    if size is None:
        size = ()
    shape = sanitize_shape(size)
    dtype = _float_dtype(dtype)
    arr = jax.random.uniform(
        _next_key(), shape, dtype=dtype.jax_type(), minval=low, maxval=high
    )
    return _wrap(arr, split, device, comm)


def permutation(x, split=None, device=None, comm=None) -> DNDarray:
    """Random permutation of an int range or a shuffle of the first axis
    (reference random.py:561-633)."""
    if isinstance(x, (int, np.integer)):
        arr = jax.random.permutation(_next_key(), int(x))
        return _wrap(arr.astype(types.index_dtype()), split, device, comm)
    if isinstance(x, DNDarray):
        arr = jax.random.permutation(_next_key(), x.larray, axis=0)
        return _wrap(arr, x.split if split is None else split, device or x.device, comm or x.comm)
    raise TypeError(f"x must be int or DNDarray, but was {type(x)}")


def randperm(n: int, dtype=types.int64, split=None, device=None, comm=None) -> DNDarray:
    """Random permutation of range(n) (reference random.py:634-678)."""
    if not isinstance(n, (int, np.integer)):
        raise TypeError(f"n must be an integer, got {type(n)}")
    arr = jax.random.permutation(_next_key(), int(n)).astype(
        types.canonical_heat_type(dtype).jax_type()
    )
    return _wrap(arr, split, device, comm)

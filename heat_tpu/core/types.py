"""NumPy-like dtype class hierarchy backed by JAX dtypes.

TPU-native re-design of the reference's torch-backed type system
(reference: heat/core/types.py:64-415 hierarchy, :495 canonical_heat_type,
:836 promote_types, :868 result_type, :950/:1005 finfo/iinfo).

Each concrete dtype is a *class*; calling it casts a value/array to that type
(mirroring ``ht.float32(x)``). ``.jax_type()`` returns the underlying
``jnp.dtype`` the way the reference's ``.torch_type()`` returned a torch dtype.

Note on 64-bit types: JAX canonicalizes 64-bit dtypes to 32-bit unless
``jax.config.update("jax_enable_x64", True)``. On TPU the performant types are
bfloat16/float32; float64 is honoured only under x64 mode (tests enable it on
the CPU mesh).
"""

from __future__ import annotations

import builtins
import functools
from typing import Any, Union

import jax.numpy as jnp
import numpy as np

__all__ = [
    "datatype",
    "generic",
    "number",
    "integer",
    "signedinteger",
    "unsignedinteger",
    "floating",
    "flexible",
    "complexfloating",
    "bool",
    "bool_",
    "int8",
    "byte",
    "int16",
    "short",
    "int32",
    "int",
    "int64",
    "long",
    "uint8",
    "ubyte",
    "float16",
    "half",
    "bfloat16",
    "float32",
    "float",
    "float_",
    "float64",
    "double",
    "complex",
    "complex64",
    "csingle",
    "cfloat",
    "complex128",
    "cdouble",
    "canonical_heat_type",
    "heat_type_of",
    "heat_type_is_exact",
    "heat_type_is_inexact",
    "heat_type_is_complexfloating",
    "issubdtype",
    "iscomplex",
    "isreal",
    "promote_types",
    "result_type",
    "can_cast",
    "finfo",
    "iinfo",
]


class _DatatypeMeta(type):
    """Metaclass so that calling a dtype class casts, and repr is clean."""

    def __repr__(cls) -> str:
        return f"heat_tpu.{cls.__name__}"

    def __call__(cls, *args, **kwargs):
        # Abstract types cannot be instantiated/cast-called.
        if getattr(cls, "_jax_dtype", None) is None:
            raise TypeError(f"cannot create instances of abstract type {cls.__name__}")
        return cls._cast(*args, **kwargs)


class datatype(metaclass=_DatatypeMeta):
    """Abstract base for all heat_tpu data types (reference heat/core/types.py:64)."""

    _jax_dtype: Any = None

    @classmethod
    def jax_type(cls):
        """The corresponding ``jnp.dtype`` (analog of reference ``torch_type()``)."""
        if cls._jax_dtype is None:
            raise TypeError(f"abstract type {cls.__name__} has no JAX equivalent")
        return cls._jax_dtype

    # kept name for users grepping parity with the reference API
    torch_type = jax_type

    @classmethod
    def char(cls) -> str:
        return cls.__name__

    @classmethod
    def _cast(cls, x, device=None, comm=None):
        from . import factories

        return factories.array(x, dtype=cls, device=device, comm=comm, copy=None)


class generic(datatype):
    pass


class bool(generic):  # noqa: A001 - parity with reference name
    _jax_dtype = jnp.bool_


bool_ = bool


class number(generic):
    pass


class integer(number):
    pass


class signedinteger(integer):
    pass


class unsignedinteger(integer):
    pass


class inexact(number):
    pass


class floating(inexact):
    pass


class complexfloating(inexact):
    pass


class flexible(generic):
    pass


class int8(signedinteger):
    _jax_dtype = jnp.int8


byte = int8


class int16(signedinteger):
    _jax_dtype = jnp.int16


short = int16


class int32(signedinteger):
    _jax_dtype = jnp.int32


int = int32  # noqa: A001


class int64(signedinteger):
    _jax_dtype = jnp.int64


long = int64


class uint8(unsignedinteger):
    _jax_dtype = jnp.uint8


ubyte = uint8


class float16(floating):
    _jax_dtype = jnp.float16


half = float16


class bfloat16(floating):
    """TPU-native 16-bit float — first-class here, absent in the reference."""

    _jax_dtype = jnp.bfloat16


class float32(floating):
    _jax_dtype = jnp.float32


float = float32  # noqa: A001
float_ = float32


class float64(floating):
    _jax_dtype = jnp.float64


double = float64


class complex64(complexfloating):
    _jax_dtype = jnp.complex64


cfloat = complex64


class complex128(complexfloating):
    _jax_dtype = jnp.complex128


cdouble = complex128
csingle = complex64
# reference types.py:367 names the abstract complex parent `complex`
complex = complexfloating  # noqa: A001


# ----------------------------------------------------------------------------
# lookup tables
# ----------------------------------------------------------------------------

_CONCRETE: tuple = (
    bool,
    int8,
    int16,
    int32,
    int64,
    uint8,
    float16,
    bfloat16,
    float32,
    float64,
    complex64,
    complex128,
)

# numpy dtype name -> heat type
_NAME_TO_TYPE = {np.dtype(c._jax_dtype).name: c for c in _CONCRETE}
_NAME_TO_TYPE["bfloat16"] = bfloat16

_PY_TO_TYPE = {
    builtins.bool: bool,
    builtins.int: int64,
    builtins.float: float32,
    builtins.complex: complex64,
}


def canonical_heat_type(a_type) -> type:
    """Map any dtype-like (heat type, str, numpy/jax dtype, python type) to the
    canonical heat_tpu type class (reference heat/core/types.py:495).

    Memoized for hashable inputs: this sits on the per-op hot path of the
    eager engines AND the fusion recorder, and the ``np.dtype(...).name``
    string derivation dominates its cost."""
    try:
        return _canonical_heat_type_cached(a_type)
    except TypeError:  # unhashable dtype-like: fall through uncached
        return _canonical_heat_type(a_type)


@functools.lru_cache(maxsize=None)
def _canonical_heat_type_cached(a_type) -> type:
    return _canonical_heat_type(a_type)


def _canonical_heat_type(a_type) -> type:
    if isinstance(a_type, type) and issubclass(a_type, datatype):
        if a_type._jax_dtype is None:
            raise TypeError(f"data type {a_type} is abstract")
        return a_type
    if a_type in _PY_TO_TYPE:
        return _PY_TO_TYPE[a_type]
    try:
        name = np.dtype(a_type).name
    except TypeError:
        name = getattr(a_type, "name", None) or str(a_type)
    if name in _NAME_TO_TYPE:
        return _NAME_TO_TYPE[name]
    raise TypeError(f"data type {a_type!r} is not understood")


def heat_type_of(obj) -> type:
    """Infer the heat_tpu type of an array-like (reference heat/core/types.py:556)."""
    dt = getattr(obj, "dtype", None)
    if dt is not None:
        return canonical_heat_type(dt)
    if isinstance(obj, (list, tuple)):
        return canonical_heat_type(jnp.asarray(obj).dtype)
    return canonical_heat_type(builtins.type(obj))


def issubdtype(arg1, arg2) -> builtins.bool:
    """NumPy-style abstract dtype subclass check."""
    if not (isinstance(arg1, type) and issubclass(arg1, datatype)):
        arg1 = canonical_heat_type(arg1)
    if isinstance(arg2, type) and issubclass(arg2, datatype):
        return issubclass(arg1, arg2)
    return issubclass(arg1, canonical_heat_type(arg2))


def heat_type_is_exact(ht_dtype) -> builtins.bool:
    """True if the type is an integer/bool type (reference types.py:595)."""
    return issubdtype(ht_dtype, integer) or issubdtype(ht_dtype, bool)


def heat_type_is_inexact(ht_dtype) -> builtins.bool:
    return issubdtype(ht_dtype, floating) or issubdtype(ht_dtype, complexfloating)


def heat_type_is_complexfloating(ht_dtype) -> builtins.bool:
    return issubdtype(ht_dtype, complexfloating)


def iscomplex(x):
    """Elementwise test for nonzero imaginary part (reference types.py:640)."""
    from . import complex_math, factories

    if heat_type_is_complexfloating(x.dtype):
        return complex_math.imag(x) != 0
    return factories.zeros(x.shape, dtype=bool, split=x.split, device=x.device, comm=x.comm)


def isreal(x):
    """Elementwise test for zero imaginary part (reference types.py:675)."""
    from . import complex_math, factories

    if heat_type_is_complexfloating(x.dtype):
        return complex_math.imag(x) == 0
    return factories.ones(x.shape, dtype=bool, split=x.split, device=x.device, comm=x.comm)


# Promotion scan order and cast rule (reference types.py:604-666). The
# reference's "intuitive" rule is numpy's "safe" casting plus the two
# torch-style exceptions int32->float32 and int32->complex64 — which is what
# makes ht.promote_types(int32, float32) == float32 (reference types.py:855)
# where numpy would say float64.
_PROMOTE_ORDER = [
    bool_,
    uint8,
    int8,
    int16,
    int32,
    int64,
    float32,
    float64,
    complex64,
    complex128,
]


def _intuitive_can_cast(src: np.dtype, dst: np.dtype) -> builtins.bool:
    if src == np.dtype(np.int32) and dst in (np.dtype(np.float32), np.dtype(np.complex64)):
        return True
    return np.can_cast(src, dst, casting="safe")


def _scalar_fits(value, target: np.dtype) -> builtins.bool:
    """Value-based castability: rounding allowed, overflow/truncation not."""
    if np.issubdtype(target, np.bool_):
        return isinstance(value, builtins.bool) or value in (0, 1)
    if isinstance(value, builtins.complex) and not np.issubdtype(target, np.complexfloating):
        if value.imag != 0:
            return False
        value = value.real
    if np.issubdtype(target, np.integer):
        if isinstance(value, builtins.float) and not builtins.float(value).is_integer():
            return False
        info = np.iinfo(target)
        try:
            return info.min <= value <= info.max
        except (OverflowError, ValueError):
            return False
    # float/complex target: any magnitude within range; nan/inf always fit
    v = builtins.abs(value)
    if np.isnan(v) or np.isinf(v):
        return True
    comp = target if np.issubdtype(target, np.floating) else np.dtype(
        np.float32 if target == np.dtype(np.complex64) else np.float64
    )
    return v <= builtins.float(np.finfo(comp).max)


def promote_types(type1, type2) -> type:
    """Smallest type in the reference's scan order that both inputs cast to
    under the "intuitive" rule (reference heat/core/types.py:755-761, 836).

    float16/bfloat16 (TPU extensions; absent from the reference's table)
    delegate to jax's promotion, which handles them natively. Memoized on
    the canonical pair (pure scan over a fixed table, hot-path cost)."""
    return _promote_types_cached(canonical_heat_type(type1), canonical_heat_type(type2))


@functools.lru_cache(maxsize=None)
def _promote_types_cached(h1, h2) -> type:
    if float16 in (h1, h2) or bfloat16 in (h1, h2):
        return canonical_heat_type(jnp.promote_types(h1.jax_type(), h2.jax_type()))
    t1 = np.dtype(h1.char())
    t2 = np.dtype(h2.char())
    for target in _PROMOTE_ORDER:
        td = np.dtype(target.char())
        if _intuitive_can_cast(t1, td) and _intuitive_can_cast(t2, td):
            return target
    raise TypeError(f"no promotion for {type1}, {type2}")


def _result_kind(op):
    """Hashable promotion key of one operand, or None when unclassifiable:
    the heat dtype class for arrays, a weak-scalar kind marker for Python /
    numpy scalars. Powers the memoized fast path of :func:`result_type`."""
    if isinstance(op, type) and issubclass(op, datatype):
        return op
    if hasattr(op, "split") and hasattr(op, "dtype"):
        return canonical_heat_type(op.dtype)
    if isinstance(op, (builtins.bool, np.bool_)):
        return "bool"
    if isinstance(op, (builtins.int, np.integer)):
        return "int"
    if isinstance(op, (builtins.float, np.floating)):
        return "float"
    if isinstance(op, (builtins.complex, np.complexfloating)):
        return "complex"
    return None


_KIND_SUBSTITUTES = {"bool": True, "int": 1, "float": 1.0, "complex": 1j}


@functools.lru_cache(maxsize=None)
def _result_type_keyed(keys) -> type:
    return _result_type_impl(*(_KIND_SUBSTITUTES.get(k, k) for k in keys))


def result_type(*operands) -> type:
    """Result type over arrays and scalars (reference types.py:868).

    Follows the reference's torch-style *weak scalar* rules, independent of
    JAX's x64 mode: a Python float joined with integer arrays promotes to the
    default float (float32), with float arrays it adopts their dtype; a Python
    int never widens a narrower integer array; a Python bool is neutral.

    Memoized on the operands' promotion keys (dtype classes + weak-scalar
    kinds — promotion never depends on values): this runs once per engine
    call, and the promotion scan dominates small-op dispatch otherwise.
    """
    keys = tuple(_result_kind(op) for op in operands)
    if None not in keys:
        return _result_type_keyed(keys)
    return _result_type_impl(*operands)


def _result_type_impl(*operands) -> type:
    dtypes: list = []
    scalar_kinds: list = []
    for op in operands:
        if isinstance(op, type) and issubclass(op, datatype):
            dtypes.append(op.jax_type())
        elif hasattr(op, "split") and hasattr(op, "dtype"):
            dtypes.append(canonical_heat_type(op.dtype).jax_type())
        elif isinstance(op, builtins.bool) or isinstance(op, np.bool_):
            scalar_kinds.append("bool")
        elif isinstance(op, (builtins.int, np.integer)):
            scalar_kinds.append("int")
        elif isinstance(op, (builtins.float, np.floating)):
            scalar_kinds.append("float")
        elif isinstance(op, (builtins.complex, np.complexfloating)):
            scalar_kinds.append("complex")
        elif hasattr(op, "dtype"):
            dtypes.append(np.dtype(op.dtype))
        else:
            dtypes.append(jnp.result_type(op))
    if dtypes:
        if len(dtypes) > 1:
            acc = canonical_heat_type(dtypes[0])
            for d in dtypes[1:]:
                acc = promote_types(acc, canonical_heat_type(d))
            res = np.dtype(acc.char()) if acc not in (bfloat16,) else acc.jax_type()
        else:
            res = np.dtype(dtypes[0])
        for kind in scalar_kinds:
            if kind == "complex":
                res = jnp.promote_types(res, jnp.complex64)
            elif kind == "float" and np.issubdtype(res, np.integer) or (
                kind == "float" and res == np.bool_
            ):
                res = jnp.promote_types(res, jnp.float32)
            elif kind == "int" and res == np.bool_:
                res = np.dtype(np.int64)
        return canonical_heat_type(res)
    # scalars only
    kind_map = {"bool": jnp.bool_, "int": jnp.int64, "float": jnp.float32, "complex": jnp.complex64}
    res = np.dtype(np.bool_)
    for kind in scalar_kinds:
        res = jnp.promote_types(res, kind_map[kind])
    return canonical_heat_type(res)


def can_cast(from_, to, casting: str = "intuitive") -> builtins.bool:
    """Casting feasibility check (reference heat/core/types.py:430).

    The reference's ``"intuitive"`` rule (its default, types.py:636-649) is
    numpy's ``"safe"`` plus the torch-style int32->float32 and
    int32->complex64 casts.
    """
    if isinstance(from_, type) and issubclass(from_, datatype):
        from_ = from_.jax_type()
    elif hasattr(from_, "dtype"):
        # DNDarrays, numpy/jax arrays, scalars with a dtype: cast by dtype
        from_ = canonical_heat_type(from_.dtype).jax_type()
    elif isinstance(from_, (builtins.bool, builtins.int, builtins.float, builtins.complex)):
        # value-based scalar rule (reference types.py:707-710 examples):
        # can_cast(1, float64) is True, can_cast(2.0e200, "u1") is False.
        # Precision loss is fine (3.14 -> float32); overflow and
        # int-truncation are not — numpy's classic value-based semantics.
        if casting == "unsafe":
            return True
        try:
            # normalize through the heat hierarchy: np.dtype(<heat class>)
            # would silently produce the object dtype
            target = np.dtype(canonical_heat_type(to).char())
        except (TypeError, ValueError):
            return False
        if casting == "no":
            return np.result_type(from_) == target
        return _scalar_fits(from_, target)
    if isinstance(to, type) and issubclass(to, datatype):
        to = to.jax_type()
    if casting == "intuitive":
        return _intuitive_can_cast(np.dtype(from_), np.dtype(to))
    return np.can_cast(from_, np.dtype(to), casting=casting)


class finfo:
    """Machine limits for floating point types (reference types.py:950)."""

    def __new__(cls, dtype):
        ht = canonical_heat_type(dtype)
        if not heat_type_is_inexact(ht):
            raise TypeError(f"data type {ht} not inexact")
        return super().__new__(cls)._init(ht)

    def _init(self, ht_dtype):
        info = jnp.finfo(ht_dtype.jax_type())
        self.bits = info.bits
        self.eps = builtins.float(info.eps)
        self.max = builtins.float(info.max)
        self.min = builtins.float(info.min)
        self.tiny = builtins.float(info.tiny)
        self.dtype = ht_dtype
        return self


class iinfo:
    """Machine limits for integer types (reference types.py:1005)."""

    def __new__(cls, dtype):
        ht = canonical_heat_type(dtype)
        if not heat_type_is_exact(ht):
            raise TypeError(f"data type {ht} not exact")
        return super().__new__(cls)._init(ht)

    def _init(self, ht_dtype):
        info = jnp.iinfo(ht_dtype.jax_type())
        self.bits = info.bits
        self.max = builtins.int(info.max)
        self.min = builtins.int(info.min)
        self.dtype = ht_dtype
        return self


def index_dtype():
    """int64 under x64 mode, else int32 — avoids JAX's truncation warning on
    TPU where 64-bit types are disabled."""
    import jax

    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32

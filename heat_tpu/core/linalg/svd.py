"""Distributed SVD and least squares — beyond-reference linalg.

The reference framework (heat 1.2, /root/reference/heat/core/linalg) stops
at QR + cg/lanczos; later heat versions grew hierarchical SVD because users
need it. Here both come almost for free from the TPU-native factorizations:

- ``svd``: QR-based two-stage algorithm. For a tall (or wide, via
  transpose) operand, the distributed TSQR/panel QR reduces the problem to
  an ``n x n`` replicated core, whose SVD is one XLA kernel; the tall factor
  U = Q @ U_r is a sharding-preserving MXU matmul. This is the standard
  "TSQR + small SVD" construction (the same shape as the reference's
  TSQR literature, qr.py:49-58) — numerically backward-stable and
  communication-optimal: the only collectives are those inside qr().
- ``lstsq``: min ||Ax - b||_2 via the same QR, one triangular solve.
"""

from __future__ import annotations

import collections
import functools
from typing import Optional

import jax.numpy as jnp

from .. import factories, sanitation
from ..dndarray import DNDarray
from . import basics
from .qr import qr
from .solver import solve_triangular

__all__ = ["svd", "lstsq", "pinv"]

SVD = collections.namedtuple("SVD", "U, S, Vh")


def svd(a: DNDarray, full_matrices: bool = True, compute_uv: bool = True):
    """Singular value decomposition ``a = U @ diag(S) @ Vh``.

    The default ``full_matrices=True`` matches ``numpy.linalg.svd`` (and
    torch) so code ported from either gets the shapes it expects — or a loud
    ``NotImplementedError`` rather than silently different shapes. The full
    decomposition is computed locally for replicated operands; for a *split*
    operand only the reduced form exists (the distributed TSQR construction
    produces it without an extra orthogonal completion), so pass
    ``full_matrices=False`` explicitly there.

    Split semantics (reduced form): a split-0 tall operand yields a split-0
    ``U`` and replicated ``S``/``Vh``; a split-1 wide operand the mirror
    image.
    """
    sanitation.sanitize_in(a)
    if a.ndim != 2:
        raise ValueError(f"svd requires a 2-D operand, got {a.ndim}-D")
    if full_matrices and compute_uv:
        if a.split is not None:
            raise NotImplementedError(
                "full_matrices=True (the numpy-compatible default) is only "
                "supported for replicated operands; a split operand's "
                "distributed construction produces the reduced form — pass "
                "full_matrices=False explicitly"
            )
        # ints AND half floats promote to f32 (XLA's svd has no bf16/f16
        # kernel); f32/f64/complex pass through _float_for unchanged
        local = a.larray.astype(basics._float_for(a))
        u, s, vh = jnp.linalg.svd(local, full_matrices=True)
        mk = functools.partial(factories.array, device=a.device, comm=a.comm)
        return SVD(mk(u), mk(s), mk(vh))
    m, n = a.shape

    if m < n:
        # wide: decompose the (tall) transpose and swap the factors
        res = svd(basics.transpose(a), full_matrices=False, compute_uv=compute_uv)
        if not compute_uv:
            return res
        return SVD(basics.transpose(res.Vh), res.S, basics.transpose(res.U))

    # tall (or square): distributed QR -> replicated n x n core SVD
    q, r = qr(a)
    u_r, s, vh = jnp.linalg.svd(r.larray, full_matrices=False)
    s_arr = factories.array(s, device=a.device, comm=a.comm)
    vh_arr = factories.array(vh, device=a.device, comm=a.comm)
    if not compute_uv:
        return s_arr
    u_core = factories.array(u_r, device=a.device, comm=a.comm)
    u = basics.matmul(q, u_core)  # preserves q's split
    return SVD(u, s_arr, vh_arr)


def lstsq(a: DNDarray, b: DNDarray, rcond: Optional[float] = None) -> DNDarray:
    """Least-squares solution of ``a @ x = b`` for full-rank tall ``a``.

    One distributed QR plus one triangular solve: ``x = R^-1 (Q^T b)``.
    ``rcond`` is accepted for numpy-API familiarity but only ``None``
    (no cutoff; full rank assumed) is supported.
    """
    sanitation.sanitize_in(a)
    sanitation.sanitize_in(b)
    if a.ndim != 2:
        raise ValueError(f"lstsq requires a 2-D coefficient matrix, got {a.ndim}-D")
    if rcond is not None:
        raise NotImplementedError("rcond cutoffs are not supported (full rank assumed)")
    m, n = a.shape
    if m < n:
        raise ValueError(f"lstsq requires m >= n, got shape {(m, n)}")
    if b.ndim not in (1, 2) or b.shape[0] != m:
        raise ValueError(f"b must have leading dimension {m}, got {tuple(b.shape)}")

    q, r = qr(a)
    rhs = basics.matmul(basics.transpose(q), b)  # (n,) or (n, k), replicated-sized
    squeeze = b.ndim == 1
    if squeeze:
        rhs = rhs.reshape((n, 1))
    x = solve_triangular(r, rhs, lower=False)
    if squeeze:
        x = x.reshape((n,))
    return x


def pinv(a: DNDarray, rcond: float = 1e-15) -> DNDarray:
    """Moore–Penrose pseudo-inverse via :func:`svd`.

    Singular values below ``rcond * max(S)`` are zeroed in the reciprocal
    (numpy's contract). The result has the transpose's natural split: a
    split-0 tall operand yields a split-1 ``(n, m)`` pseudo-inverse.
    """
    sanitation.sanitize_in(a)
    if a.ndim != 2:
        raise ValueError(f"pinv requires a 2-D operand, got {a.ndim}-D")
    u, s, vh = svd(a, full_matrices=False)
    s_np = s.larray
    cutoff = rcond * jnp.max(s_np)
    s_inv = jnp.where(s_np > cutoff, 1.0 / jnp.where(s_np > cutoff, s_np, 1.0), 0.0)
    s_inv_arr = factories.array(s_inv, device=a.device, comm=a.comm)
    # A⁺ = V S⁺ Uᵀ — scale Vh's rows, then one sharding-preserving matmul
    v_scaled = basics.transpose(vh) * s_inv_arr  # (n, r) * (r,) broadcast
    return basics.matmul(v_scaled, basics.transpose(u))

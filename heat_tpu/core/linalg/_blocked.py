"""Shared scaffolding for the fused blocked shard_map programs (triangular
solve in :mod:`.solver`, determinant in :mod:`.basics`).

Both programs sweep diagonal-owner stages over a split-0 operand's PHYSICAL
payload; they share two invariants that must never drift apart:

* the stage grid and diagonal ownership come from the
  :class:`~heat_tpu.core.tiling.SquareDiagTiles` decomposition (one tile per
  device — the runtime's ceil-chunk grid, tiling.py:_axis_tile_sizes), and
* each device's row slab is column-padded to the square physical extent and
  its pad rows (unspecified content per the ``dndarray.parray`` contract)
  are replaced with identity rows, so padding contributes an identity block
  (zero solution rows / determinant factor 1).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def mirror_triangle(local, uplo: str = "L"):
    """Symmetric/Hermitian completion from ONE triangle — numpy's
    convention for cholesky/eigh (JAX's kernels average the two triangles
    instead, a silent divergence for one-triangle-stored operands)."""
    if uplo == "L":
        tri, strict = jnp.tril(local), jnp.tril(local, -1)
    else:
        tri, strict = jnp.triu(local), jnp.triu(local, 1)
    mirrored = jnp.conjugate(strict).mT if jnp.iscomplexobj(local) else strict.mT
    return tri + mirrored


def stage_grid(a) -> Tuple[int, int, int, tuple]:
    """``(p, rows_loc, n_stages, owners)`` for a split-0 2-D operand.

    ``owners[t]`` is the device owning stage ``t``'s diagonal tile, read from
    the tile decomposition's ownership map; stages exist only where the
    diagonal has logical rows.
    """
    from ..tiling import SquareDiagTiles

    comm = a.comm
    p = comm.size
    n = int(a.shape[0])
    rows_loc = -(-n // p)
    tiles = SquareDiagTiles(a, tiles_per_proc=1)
    n_stages = len(tiles.row_indices)
    owners = tuple(
        int(tiles.tile_map[i, min(i, tiles.tile_columns - 1), 2]) for i in range(n_stages)
    )
    return p, rows_loc, n_stages, owners


def sanitize_slab(Al, idx, rows_loc: int, n: int, n_pad: int, dtype):
    """Column-pad a device's physical ``(rows_loc, n)`` row slab to
    ``(rows_loc, n_pad)`` and replace pad rows with identity rows.

    Returns ``(slab, rows)`` where ``rows`` are the slab's global row ids
    (callers reuse them to zero pad entries of the right-hand side).
    """
    rows = idx * rows_loc + jnp.arange(rows_loc)
    W = jnp.pad(Al.astype(dtype), ((0, 0), (0, n_pad - n)))
    eye_rows = (rows[:, None] == jnp.arange(n_pad)[None, :]).astype(dtype)
    return jnp.where((rows >= n)[:, None], eye_rows, W), rows

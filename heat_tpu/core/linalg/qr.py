"""QR decomposition.

TPU-native re-design of reference heat/core/linalg/qr.py:17-1042. The
reference implements tile-CAQR: per-tile-column local QRs with cross-process
Householder merges (qr.py:319-608 for split=0, :866-1042 for split=1) driven
by ``SquareDiagTiles``. The TPU rendering keeps a distributed schedule for
every split:

* **split=0, m >= n** — **TSQR**: each device QR-factors its row block, the
  small R factors are all-gathered and factored once more, and the final Q is
  one local matmul per device. Ragged row counts ride the runtime's pad+mask
  contract: zero row-blocks contribute zero R factors, so the padding rows of
  Q come out exactly zero and slice off (replaces reference qr.py:319-865).
* **split=1** — **blocked panel loop**: sequentially per device-panel, the
  owner QR-factors its (updated) panel, broadcasts the Q panel, and all later
  panels are orthogonalized against it with a two-pass block Gram-Schmidt
  (CGS2) update — the reference's ``__split1_qr_loop`` Bcast-per-panel
  schedule (qr.py:866-1042) with the Householder merge replaced by the
  TPU-friendlier panel-QR + reorthogonalized projection. Q and R come out
  column-split, like the reference's.
* **split=None / short-wide (m < n with ragged rows)** — one replicated XLA
  QR kernel; above ``_REPLICATED_MAX_ELEMENTS`` this emits a warning instead
  of silently gathering (the reference covers these shapes with its tile
  loops; the replicated fallback is explicit policy here, never silent).
"""

from __future__ import annotations

import collections
import functools
import warnings
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .. import factories, resilience, sanitation, telemetry, types
from ..communication import sanitize_comm
from ..dndarray import DNDarray, _ensure_split

__all__ = ["qr"]

# payload access here forces pending chains under the "collective" trigger,
# and each schedule declares its collective budget to the telemetry ledger
# (the schedule IS the algorithm — counts are per wrapper call)
_T_COLLECTIVE = telemetry.force_trigger("collective")

QR = collections.namedtuple("QR", "Q, R")

# replicated fallback (split=1 short-wide etc.) is explicit policy below this
# size and a loud warning above it — never a silent gather
_REPLICATED_MAX_ELEMENTS = 1 << 22


def qr(
    a: DNDarray,
    tiles_per_proc: int = 1,
    calc_q: bool = True,
    overwrite_a: bool = False,
    method: str = "auto",
) -> QR:
    """Reduced QR decomposition of a 2-D DNDarray (reference qr.py:17-179).

    ``tiles_per_proc``/``overwrite_a`` are accepted for API parity; the TSQR /
    panel schedules have no tile-count knob and never mutate their input.

    ``method``: ``"auto"`` (default), ``"tsqr"`` (Householder-based,
    unconditionally stable), or ``"cholqr2"``. CholeskyQR2 factors tall-skinny
    operands as R from ``chol(AᵀA)``, Q by triangular solve, repeated once
    for re-orthonormalization. Every FLOP is a matmul, so on TPU it runs on
    the MXU where Householder QR is mostly vector work; the price is a
    squared condition number in the first pass — safe for
    ``cond(A) ≲ 1/√ε`` (~3e3 f32 / ~7e7 f64). Breakdown detection is a
    single fused on-device probe (one host read): non-finite Cholesky OR
    first-pass orthogonality error ``‖Q1ᴴQ1 − I‖ >= 0.5`` — the latter
    catches operands near the ``1/√ε`` bound whose Gram Cholesky stays
    finite while Q silently degrades below Householder quality (the second
    pass only restores orthogonality while that error is < 1).
    ``method="cholqr2"`` raises on the probe rather than returning garbage.
    ``"auto"`` tries the MXU-native CholeskyQR2 first for genuinely
    tall-skinny operands (``m >= 2n``, Gram small enough to replicate,
    split != 1 — the panel path's split-1 R layout must not depend on
    conditioning) and falls back to TSQR on the same probe instead of
    raising — the all-matmul speed when conditioning allows, Householder
    stability when it does not. ``"auto"`` became the default
    once a real-TPU capture showed the margin at the benchmark shape:
    CholeskyQR2 1.29 TFLOP/s vs TSQR 0.19 — 6.7x
    (benchmarks/TPU_WINDOW_r04.json, cholqr2 stage, v5e 2M x 256 f32).
    """
    sanitation.sanitize_in(a)
    if a.ndim != 2:
        raise ValueError(f"qr requires a 2-D array, got {a.ndim}-D")
    if method not in ("tsqr", "cholqr2", "auto"):
        raise ValueError(
            f"unknown qr method {method!r}: expected 'tsqr', 'cholqr2' or 'auto'"
        )
    if not types.heat_type_is_inexact(a.dtype) or a.dtype in (
        types.bfloat16,
        types.float16,
    ):
        # ints AND half floats factor in f32: XLA's qr/cholesky lowerings
        # have no half-precision kernels (factors return as float32)
        a = a.astype(types.promote_types(a.dtype, types.float32))

    m, n = a.shape
    comm = a.comm
    p = comm.size

    q_split = a.split
    r_split: Optional[int] = None
    q_arr = r_arr = None
    if (
        method == "auto"
        # genuinely tall-skinny only: the probe factors a REPLICATED (n, n)
        # Gram, so a large square operand would silently replicate — the
        # exact degradation class warn_replicated polices. The aspect bound
        # also keeps the probe where CholeskyQR2's all-matmul profile wins.
        and m >= 2 * n
        and n * n <= _REPLICATED_MAX_ELEMENTS
        # split=1 stays on the panel path: its R is split=1 by contract, and
        # a conditioning-dependent layout flip (replicated R on probe
        # success) would break layout-dependent callers intermittently
        and a.split != 1
    ):
        # try the MXU-native CholeskyQR2, fall back to Householder on the
        # breakdown/conditioning probe (one host scalar read; the probe also
        # catches finite-but-degraded orthogonality, see _cholqr2_kernel).
        # Deferred-first: the passes record as a multi-output collective
        # node, the probe read forces Q/R/ok in ONE dispatch, and a pending
        # operand chain compiles into the same program.
        deferred = _cholqr2_deferred(a, calc_q)
        if deferred is not None:
            q_d, r_d, ok_d = deferred
            if ok_d:
                if not calc_q:
                    return QR(None, r_d)
                return QR(q_d, r_d)
        else:
            with _T_COLLECTIVE:
                q_try, r_try, ok = _cholqr2_kernel(a.larray, calc_q)
            _record_cholqr2_collectives(a)  # the Gram psums ran either way
            if bool(ok):
                q_arr, r_arr = q_try, r_try
    elif method == "cholqr2":
        if m < n:
            raise ValueError(f"cholqr2 requires a tall operand (m >= n), got {a.shape}")
        deferred = _cholqr2_deferred(a, calc_q)
        if deferred is not None:
            q_d, r_d, ok_d = deferred
            if not ok_d:
                raise ValueError(_CHOLQR2_BREAKDOWN_MSG)
            if not calc_q:
                return QR(None, r_d)
            return QR(q_d, r_d)
        with _T_COLLECTIVE:
            q_arr, r_arr, ok = _cholqr2_kernel(a.larray, calc_q)
        _record_cholqr2_collectives(a)
        if not bool(ok):
            raise ValueError(_CHOLQR2_BREAKDOWN_MSG)

    if r_arr is None:  # no CholeskyQR2 result: Householder dispatch
        # TSQR needs a full (n, n) R per block: block = ceil(m/p) >= n,
        # otherwise the R-tile all-gather would move p*block*n = the FULL
        # operand volume — exactly the silent gather the explicit fallback
        # policy exists to avoid
        if a.split == 0 and p > 1 and m >= n and -(-m // p) >= n:
            deferred = _tsqr_deferred(a, comm)
            if deferred is not None:
                q_d, r_d = deferred
                if not calc_q:
                    # the unused Q pick is never walked into a program, so
                    # XLA dead-code-eliminates the formation matmul
                    return QR(None, r_d)
                return QR(q_d, r_d)
            q_arr, r_arr = _tsqr(a, comm)
        elif a.split == 1 and p > 1 and m >= n:
            q_arr, r_arr = _panel_qr_split1(a, comm)
            r_split = 1
        else:
            # replicated or short-wide: one XLA QR kernel over the gathered
            # operand — explicit policy with a size guard, never silent (the
            # shared warn_replicated helper so callers can filter one class)
            if a.is_distributed() and a.size > _REPLICATED_MAX_ELEMENTS:
                sanitation.warn_replicated(
                    "qr",
                    f"no gather-free distributed schedule for shape {a.shape} "
                    f"split={a.split} (short-wide, or row blocks narrower than "
                    "n); consider resplit or a transpose formulation",
                )
            q_arr, r_arr = jnp.linalg.qr(a.larray, mode="reduced")
            r_split = 1 if a.split == 1 else None

    r = DNDarray(
        _ensure_split(r_arr, r_split, comm),
        tuple(r_arr.shape),
        types.canonical_heat_type(r_arr.dtype),
        r_split,
        a.device,
        comm,
    )
    if not calc_q or q_arr is None:
        return QR(None, r)
    q = DNDarray(
        _ensure_split(q_arr, q_split, comm),
        tuple(q_arr.shape),
        types.canonical_heat_type(q_arr.dtype),
        q_split,
        a.device,
        comm,
    )
    return QR(q, r)


def _record_cholqr2_collectives(a: DNDarray) -> None:
    """Declared CholeskyQR2 schedule: each of the two passes' Gram
    contractions psums one (n, n) partial over the split axis (GSPMD inserts
    it when the operand rows are sharded; replicated operands move nothing)."""
    if a.split != 0 or not a.comm.is_distributed():
        return  # replicated operand: the Gram contractions move nothing
    if resilience._ARMED:
        # the declared schedule's fault site: fires exactly when the psums
        # will actually ride the dispatch, telemetry on or off
        resilience.check("collective.allreduce")
    if not telemetry._MODE:
        return
    n = int(a.shape[1])
    acc = jnp.result_type(a.larray.dtype, jnp.float32)
    telemetry.record_collective(
        "allreduce", a.comm.axis_name, n * n * jnp.dtype(acc).itemsize, str(acc), count=2
    )


_CHOLQR2_BREAKDOWN_MSG = (
    "cholqr2 broke down (non-finite Cholesky of the Gram matrix, or "
    "first-pass orthogonality error ‖Q1ᴴQ1 − I‖ >= 0.5): the operand "
    "is rank-deficient or too ill-conditioned (cond ≳ 1/√ε) for the "
    "squared-condition first pass — use method='tsqr'"
)


def _cholqr2_deferred(a: DNDarray, calc_q: bool):
    """Record the CholeskyQR2 passes as a multi-output collective node: the
    Gram psums compile into the producing chain's program, and the breakdown
    probe's ONE host read forces Q/R/ok together (sibling batching — one
    dispatch, one blocking sync). Returns ``(q, r, ok)`` with Q/R as DNDarray
    wrappers (Q None when ``calc_q=False``), or None to decline (collectives
    off, tracer payloads, record failures → the eager jitted kernel)."""
    from .. import fusion

    if not fusion.collectives_active():
        return None
    nodes = fusion.defer_multi(_cholqr2_op, (a,), calc_q=calc_q)
    if nodes is None:
        return None
    _record_cholqr2_collectives(a)  # the Gram psums ride the dispatch
    m, n = (int(s) for s in a.shape)
    if calc_q:
        qn, rn, okn = nodes
        q = fusion.wrap_node(qn, (m, n), a.split, a)
    else:
        rn, okn = nodes
        q = None
    r = fusion.wrap_node(rn, (n, n), None, a)
    with _T_COLLECTIVE:
        ok = fusion.force(okn)
    return q, r, bool(ok)


@functools.lru_cache(maxsize=None)
def _tsqr_kernel(axis: str, block: int, n: int, p: int):
    """The TSQR reduction tree as an UNJITTED multi-output kernel for the
    deferred path — the same schedule as :func:`_tsqr_program`, handed to
    ``fusion.defer_apply`` so the R-stack all_gather compiles INTO the
    enclosing chain's program. Cached for one function identity per layout
    (one program-cache key)."""
    k1 = min(block, n)

    def kernel(xs):  # xs: (block, n) per device
        q1, r1 = jnp.linalg.qr(xs, mode="reduced")  # (block, k1), (k1, n)
        rs = jax.lax.all_gather(r1, axis)  # (p, k1, n) — the one ICI collective
        q2, r = jnp.linalg.qr(rs.reshape(p * k1, n), mode="reduced")
        idx = jax.lax.axis_index(axis)
        q2_block = jax.lax.dynamic_slice_in_dim(q2, idx * k1, k1, axis=0)
        return q1 @ q2_block, r

    kernel.__name__ = f"tsqr_b{block}_n{n}"
    return kernel


def _tsqr_deferred(a: DNDarray, comm):
    """Record TSQR as a multi-output collective node (Q row-split, R
    replicated) — pending operand chains stay pending and the allgather
    compiles into their program. Returns ``(q, r)`` DNDarray wrappers, or
    None to decline (collectives off, ragged rows → the eager pad+mask
    path, tracer payloads, record failures)."""
    from .. import fusion

    if not fusion.collectives_active() or a.padded:
        return None
    m, n = (int(s) for s in a.shape)
    p = comm.size
    block = m // p  # unpadded row split: m divides evenly
    nodes = fusion.defer_apply(
        comm,
        _tsqr_kernel(comm.axis_name, block, n, p),
        (a,),
        in_splits=(0,),
        out_split=(0, None),
        check_vma=False,
    )
    if nodes is None:
        return None
    if resilience._ARMED:
        # the declared schedule's fault site (one in-kernel all_gather) —
        # record time is dispatch time for deferred kernels
        resilience.check("collective.allgather")
    if telemetry._MODE:
        k1 = min(block, n)
        itemsize = jnp.dtype(a.dtype.jax_type()).itemsize
        telemetry.record_collective(
            "allgather", comm.axis_name, p * k1 * n * itemsize, str(a.dtype.jax_type())
        )
    return (
        fusion.wrap_node(nodes[0], (m, n), 0, a),
        fusion.wrap_node(nodes[1], (n, n), None, a),
    )


@functools.lru_cache(maxsize=None)
def _tsqr_program(mesh, axis: str, block: int, n: int, p: int, dtype_name: str):
    """Compiled TSQR kernel over the row-padded (p*block, n) operand."""
    from jax.sharding import PartitionSpec as P

    k1 = min(block, n)

    def kernel(xs):  # xs: (block, n) per device
        q1, r1 = jnp.linalg.qr(xs, mode="reduced")  # (block, k1), (k1, n)
        rs = jax.lax.all_gather(r1, axis)  # (p, k1, n) — the one ICI collective
        q2, r = jnp.linalg.qr(rs.reshape(p * k1, n), mode="reduced")
        idx = jax.lax.axis_index(axis)
        q2_block = jax.lax.dynamic_slice_in_dim(q2, idx * k1, k1, axis=0)
        return q1 @ q2_block, r

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=P(axis, None),
            out_specs=(P(axis, None), P(None, None)),
            # R is replicated by construction (every device factors the same
            # gathered stack); the varying-axis checker cannot infer that.
            check_vma=False,
        )
    )


def _tsqr(a: DNDarray, comm) -> Tuple[jax.Array, jax.Array]:
    """Tall-skinny QR over the row-sharded array (reference qr.py:319-865).

    Schedule (the TSQR reduction tree):
      1. local QR of each (block, n) row block          — compute only
      2. all_gather of the p (k1, n) R factors          — one ICI collective
      3. QR of the stacked (p*k1, n) matrix (replicated)— small, redundant
      4. local Q1 @ Q2-block                            — compute only

    Ragged m rides pad+mask: zero row-blocks yield zero R factors, so the
    padded rows of Q are exactly zero and are sliced off.
    """
    m, n = a.shape
    p = comm.size
    with _T_COLLECTIVE:
        phys = a.parray  # (p*block, n), zero rows past m
    block = int(phys.shape[0]) // p
    k1 = min(block, int(n))
    if resilience._ARMED:
        # the declared schedule's fault site (one in-kernel all_gather) —
        # fires with the dispatch below, like the communication verbs
        resilience.check("collective.allgather")
    if telemetry._MODE:
        # declared schedule: ONE all_gather of the p (k1, n) R factors
        telemetry.record_collective(
            "allgather",
            comm.axis_name,
            p * k1 * int(n) * phys.dtype.itemsize,
            str(phys.dtype),
        )
    fn = _tsqr_program(comm.mesh, comm.axis_name, block, int(n), p, str(phys.dtype))
    q_pad, r = fn(phys)
    if a.padded:
        q_pad = q_pad[:m]  # zero padding rows slice off
    return q_pad, r


@functools.lru_cache(maxsize=None)
def _panel_program(mesh, axis: str, m: int, c: int, n: int, p: int, dtype_name: str):
    """Compiled split=1 blocked panel-QR kernel (reference qr.py:866-1042)."""
    from jax.sharding import PartitionSpec as P

    def bcast(v, root):
        idx = jax.lax.axis_index(axis)
        masked = jnp.where(idx == root, v, jnp.zeros_like(v))
        return jax.lax.psum(masked, axis)

    def kernel(a_loc):  # (m, c) per device
        idx = jax.lax.axis_index(axis)

        # fori_loop over the p panels (not an unrolled chain): program size
        # stays O(1) in the mesh size — tests/test_mesh64_compile
        def panel(d, carry):
            a_cur, q_loc, r_loc = carry
            # panel owner factors its (already orthogonalized) panel; every
            # device computes a QR but only the owner's is broadcast — the
            # XLA rendering of the reference's per-panel Bcast (qr.py:907-955)
            qd_own, rd_own = jnp.linalg.qr(a_cur, mode="reduced")
            qd = bcast(qd_own, d)  # (m, c)
            rdd = bcast(rd_own, d)  # (c, c)
            later = idx > d
            # two-pass block Gram-Schmidt (CGS2) of later panels against qd
            qdh = jnp.conjugate(qd).mT  # Q^H: correct for complex panels too
            coef1 = qdh @ a_cur  # (c, c)
            a_upd = a_cur - qd @ coef1
            coef2 = qdh @ a_upd
            a_upd = a_upd - qd @ coef2
            a_cur = jnp.where(later, a_upd, a_cur)
            # R rows d*c:(d+1)*c of this device's column block
            r_rows = jnp.where(
                idx == d, rdd, jnp.where(later, coef1 + coef2, jnp.zeros_like(rdd))
            )
            r_loc = jax.lax.dynamic_update_slice(r_loc, r_rows, (d * c, 0))
            q_loc = jnp.where(idx == d, qd, q_loc)
            return a_cur, q_loc, r_loc

        _, q_loc, r_loc = jax.lax.fori_loop(
            0, p, panel, (a_loc, jnp.zeros_like(a_loc), jnp.zeros((n, c), a_loc.dtype))
        )
        return q_loc, r_loc

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=P(None, axis),
            out_specs=(P(None, axis), P(None, axis)),
            check_vma=False,
        )
    )


def _panel_qr_split1(a: DNDarray, comm) -> Tuple[jax.Array, jax.Array]:
    """Column-split blocked panel QR (reference qr.py:866-1042).

    Sequential over the p device panels: the owner QR-factors its panel,
    broadcasts the (m, c) Q panel, later panels run a CGS2 block update.
    Communication: p broadcasts of (m, c) = one full-operand volume — the
    same budget as the reference's panel Bcast schedule. Q and R come out
    column-split. Ragged n rides pad+mask: padding columns are a suffix of
    the last panel, their Q/R columns are sliced off at the end.
    """
    m, n = a.shape
    p = comm.size
    with _T_COLLECTIVE:
        phys = a.parray  # (m, p*c), zero columns past n
    c = int(phys.shape[1]) // p
    n_pad = c * p
    if resilience._ARMED:
        # the declared schedule's fault site (per-panel in-kernel bcasts)
        resilience.check("collective.bcast")
    if telemetry._MODE:
        # declared schedule: per panel, one (m, c) Q bcast + one (c, c) R bcast
        telemetry.record_collective(
            "bcast", comm.axis_name, int(m) * c * phys.dtype.itemsize, str(phys.dtype), count=p
        )
        telemetry.record_collective(
            "bcast", comm.axis_name, c * c * phys.dtype.itemsize, str(phys.dtype), count=p
        )
    fn = _panel_program(comm.mesh, comm.axis_name, int(m), c, n_pad, p, str(phys.dtype))
    q_pad, r_pad = fn(phys)
    if a.padded:
        # logical views; the DNDarray wrap re-pads along split=1
        q_pad = q_pad[:, :n]
        r_pad = r_pad[:n, :n]
    return q_pad, r_pad


def _cholqr2_body(x, calc_q: bool = True):
    """Two CholeskyQR passes returning ``(q, r, ok)`` — the UNJITTED body
    shared by the eager jitted wrapper (:func:`_cholqr2_kernel`) and the
    deferred recording (:func:`_cholqr2_op`), so both paths run the exact
    same arithmetic.

    Everything tall is a matmul: the Gram contractions run on the MXU (and
    GSPMD turns them into psums over the split axis), and Q formation is
    ``x @ R⁻¹`` — R⁻¹ computed once per pass by an (n, n) triangular solve
    against the identity — instead of an (m, n) triangular solve. XLA lowers
    a big ``triangular_solve`` on TPU to a blocked substitution sweep that
    runs at a fraction of matmul rate; inverting the SMALL factor and
    substituting a GEMM keeps the m-dimensional work entirely on the MXU
    (the r04 capture measured the solve formulation at ~1% of the chip's
    matmul capability; see benchmarks/tpu_window.py stage_qr_marginal).
    Numerically the inverse of the small triangular factor is applied to the
    same operand the solve would see, and CholeskyQR2's second pass restores
    first-pass orthogonality loss either way.

    ``ok`` is the breakdown/conditioning probe, computed on-device so the
    caller pays ONE host scalar read: finite R AND ``max|Q1ᴴQ1 − I| < 0.5``.
    The second-pass Gram is exactly the first pass's orthogonality error, so
    this rejects not just NaN breakdown (rank deficiency) but the gradual
    degradation where a near-``1/√ε``-conditioned operand keeps the Cholesky
    finite while Q drifts from orthonormal (advisor finding r04#3): CholQR2
    theory restores full orthogonality only while ``‖Q1ᴴQ1 − I‖ < 1``.
    Hermitian Gram (``xᴴx``) so complex operands factor correctly. With
    ``calc_q=False`` the second (largest) formation matmul is skipped — R
    only needs the second pass's Cholesky factor.

    Half-precision operands STREAM at their own width: a bfloat16/float16
    ``x`` keeps its dtype on the big matmul operands (half the HBM bytes;
    Q comes back in the streamed dtype) while the Gram accumulates in f32
    (``preferred_element_type``) and the small Cholesky/inverse run f32 —
    XLA has no half-precision LAPACK kernels, and bf16 accumulation would
    be numerically void. The probe inherits the arithmetic honestly: the
    ~1e-2 bf16 quantization noise bounds the accepted conditioning far
    tighter than f32's (cond ≲ a few tens), which is the correct contract
    for a squared-condition algorithm on half-precision data. The public
    ``qr()`` never routes half dtypes here (it promotes to f32); this path
    serves callers that explicitly want the half-width stream
    (benchmarks/tpu_window.py stage_qr_marginal's bf16 variant)."""
    acc_t = (
        jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else x.dtype
    )
    eye = jnp.eye(x.shape[1], dtype=acc_t)

    def gram_chol(x):
        # (n, n) — contracts the (sharded) row axis; psum under GSPMD
        g = jax.lax.dot_general(
            jnp.conjugate(x), x, (((0,), (0,)), ((), ())),
            preferred_element_type=acc_t,
        )
        return jnp.conjugate(jnp.linalg.cholesky(g)).mT, g  # upper factor

    def inv_upper(r):  # (n, n) solve against I: small, exact, off the hot path
        return jax.lax.linalg.triangular_solve(r, eye, left_side=False, lower=False)

    def form_q(x, r_inv):  # big GEMM; operands in the streamed dtype
        return jax.lax.dot_general(
            x, r_inv.astype(x.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=acc_t,
        ).astype(x.dtype)

    r1, _ = gram_chol(x)
    q1 = form_q(x, inv_upper(r1))
    r2, g2 = gram_chol(q1)  # re-orthonormalization pass
    ok = _cholqr2_probe_ok(r1, r2, g2, eye)
    q2 = form_q(q1, inv_upper(r2)) if calc_q else None
    return q2, r2 @ r1, ok


_cholqr2_kernel = functools.partial(jax.jit, static_argnames=("calc_q",))(_cholqr2_body)


def _cholqr2_op(x, *, calc_q):
    """CholeskyQR2 as a recordable multi-output DAG op: the same body, with
    the calc_q=False tuple flattened (record_multi infers one aval per
    output, so None cannot ride the tuple). Under the fused program the
    Gram contractions see the sharded operand and GSPMD inserts the same
    psums the eager jitted kernel gets."""
    q, r, ok = _cholqr2_body(x, calc_q)
    if calc_q:
        return q, r, ok
    return r, ok


def _cholqr2_probe_ok(r1, r2, g2, eye):
    """The breakdown/conditioning acceptance scalar (see _cholqr2_kernel):
    both Cholesky factors finite AND first-pass orthogonality error
    ``‖Q1ᴴQ1 − I‖_F < 0.5``. The second pass provably restores
    orthonormality when the *spectral* norm ``‖Q1ᴴQ1 − I‖₂ < 1``; the
    Frobenius norm upper-bounds the spectral norm, so gating it at 0.5
    soundly implies the recovery condition (with margin) — unlike the
    element-wise max, which *lower*-bounds the spectral norm and could
    accept a matrix whose aggregate departure already exceeds 1
    (round-5 advisor finding)."""
    ok = jnp.isfinite(r2).all() & jnp.isfinite(r1).all()
    return ok & (jnp.linalg.norm(g2 - eye) < 0.5)

"""QR decomposition.

TPU-native re-design of reference heat/core/linalg/qr.py:17-1042. The
reference implements tile-CAQR: per-tile-column local QRs with cross-process
Householder merges (qr.py:319-608) driven by ``SquareDiagTiles``. On TPU the
equivalent for the dominant (tall-skinny, split=0) case is **TSQR**: each
device QR-factors its row block, the small R factors are all-gathered and
factored once more, and the final Q is one local matmul per device — a
reduction tree whose only collective is a single ``all_gather`` of n×n tiles
(SURVEY.md §7 phase 5). Column-split (split=1) inputs take a panel-wise
blocked Householder path mirroring the reference's ``__split1_qr_loop``
(qr.py:866-1042) with XLA resharding standing in for the panel Bcasts.
"""

from __future__ import annotations

import collections
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .. import factories, sanitation, types
from ..communication import sanitize_comm
from ..dndarray import DNDarray, _ensure_split

__all__ = ["qr"]

QR = collections.namedtuple("QR", "Q, R")


def qr(
    a: DNDarray,
    tiles_per_proc: int = 1,
    calc_q: bool = True,
    overwrite_a: bool = False,
) -> QR:
    """Reduced QR decomposition of a 2-D DNDarray (reference qr.py:17-179).

    ``tiles_per_proc``/``overwrite_a`` are accepted for API parity; the TSQR
    schedule has no tile-count knob and never mutates its input.
    """
    sanitation.sanitize_in(a)
    if a.ndim != 2:
        raise ValueError(f"qr requires a 2-D array, got {a.ndim}-D")
    if not types.heat_type_is_inexact(a.dtype):
        a = a.astype(types.promote_types(a.dtype, types.float32))

    m, n = a.shape
    comm = a.comm
    p = comm.size

    if (
        a.split == 0
        and p > 1
        and m % p == 0
        and (m // p) >= n
    ):
        q_arr, r_arr = _tsqr(a.larray, comm)
    else:
        # replicated / column-split / short-wide: one XLA QR kernel over the
        # (gathered) operand — the reference's split=1 loop exists to manage
        # MPI panels, which GSPMD renders unnecessary at these shapes.
        q_arr, r_arr = jnp.linalg.qr(a.larray, mode="reduced")

    q = DNDarray(
        _ensure_split(q_arr, a.split, comm),
        tuple(q_arr.shape),
        types.canonical_heat_type(q_arr.dtype),
        a.split,
        a.device,
        comm,
    )
    r_split = 1 if a.split == 1 else None
    r = DNDarray(
        _ensure_split(r_arr, r_split, comm),
        tuple(r_arr.shape),
        types.canonical_heat_type(r_arr.dtype),
        r_split,
        a.device,
        comm,
    )
    if not calc_q:
        return QR(None, r)
    return QR(q, r)


def _tsqr(x: jax.Array, comm) -> Tuple[jax.Array, jax.Array]:
    """Tall-skinny QR over the row-sharded global array ``x``.

    Schedule (the TSQR reduction tree, replacing reference qr.py:319-865):
      1. local QR of each (m/p, n) row block            — compute only
      2. all_gather of the p (n, n) R factors           — one ICI collective
      3. QR of the stacked (p*n, n) matrix (replicated) — small, redundant
      4. local Q1 @ Q2-block                            — compute only
    """
    from jax.sharding import PartitionSpec as P

    p = comm.size
    n = x.shape[1]
    axis = comm.axis_name

    def kernel(xs):
        q1, r1 = jnp.linalg.qr(xs, mode="reduced")  # (m/p, n), (n, n)
        rs = comm.allgather(r1)  # (p, n, n) — one ICI collective
        q2, r = jnp.linalg.qr(rs.reshape(p * n, n), mode="reduced")
        idx = jax.lax.axis_index(axis)
        q2_block = jax.lax.dynamic_slice_in_dim(q2, idx * n, n, axis=0)  # (n, n)
        return q1 @ q2_block, r

    fn = jax.jit(
        jax.shard_map(
            kernel,
            mesh=comm.mesh,
            in_specs=P(axis, None),
            out_specs=(P(axis, None), P(None, None)),
            # R is replicated by construction (every device factors the same
            # gathered stack); the varying-axis checker cannot infer that.
            check_vma=False,
        )
    )
    return fn(x)

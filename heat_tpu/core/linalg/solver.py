"""Solvers (reference: heat/core/linalg/solver.py).

``cg``/``lanczos`` are compositions of matmul/dot exactly as in the
reference; the manual Allreduce dots (solver.py:13-184) are sharded
reductions here. ``cg`` is fused into ONE XLA program (``lax.while_loop``
with the convergence test on device) — the reference's Python loop
host-syncs every iteration; the fused loop dispatches once.
``solve_triangular`` is the blocked back/forward-substitution driven by the
``SquareDiagTiles`` decomposition (the tile grid the reference builds for
its tile-QR, reference tiling.py:331-1257).
"""

from __future__ import annotations

from typing import Optional

import functools

import jax
import jax.numpy as jnp

from .. import factories
from ..dndarray import DNDarray
from .basics import dot, matmul, norm, transpose

__all__ = ["cg", "lanczos", "solve_triangular"]


@jax.jit
def _cg_fused(Al, bl, x0l):
    """Whole CG run as one XLA program: the convergence test lives on device
    inside the while_loop, so there is no per-iteration host round-trip."""
    n = bl.shape[0]
    r0 = bl - Al @ x0l
    rs0 = r0 @ r0

    def cond(carry):
        _, _, _, rsold, i = carry
        return (i < n) & (jnp.sqrt(rsold) >= 1e-10)

    def body(carry):
        x, r, p, rsold, i = carry
        Ap = Al @ p
        alpha = rsold / (p @ Ap)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = r @ r
        p = r + (rsnew / rsold) * p
        return x, r, p, rsnew, i + 1

    x, _, _, _, _ = jax.lax.while_loop(cond, body, (x0l, r0, r0, rs0, jnp.int32(0)))
    return x


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients for s.p.d. ``A`` (reference solver.py:13-65)."""
    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError(f"A, b and x0 need to be of type DNDarray, but were {type(A)}, {type(b)}, {type(x0)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("c needs to be a 1D vector")

    xl = _cg_fused(A.larray, b.larray, x0.larray)
    x = factories.array(xl, is_split=None, device=x0.device, comm=x0.comm)
    x.resplit_(x0.split)
    if out is not None:
        out._replace(x.larray, x.split)
        return out
    return x


def solve_triangular(A: DNDarray, b: DNDarray, lower: bool = False) -> DNDarray:
    """Solve ``A x = b`` for triangular ``A`` by blocked substitution over
    the :class:`~heat_tpu.core.tiling.SquareDiagTiles` decomposition.

    The tile grid supplies the diagonal-aligned block bounds (the same
    decomposition the reference builds to drive tile-QR, reference
    tiling.py:331-1257); the sweep solves one diagonal tile with the XLA
    triangular kernel and folds the off-diagonal tiles into the right-hand
    side — MXU matmuls between small triangular solves.
    """
    from ..tiling import SquareDiagTiles

    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray):
        raise TypeError("A and b must be DNDarrays")
    if A.ndim != 2 or A.shape[0] != A.shape[1]:
        raise ValueError("A must be a square 2-D matrix")
    vector_rhs = b.ndim == 1
    if b.shape[0] != A.shape[0]:
        raise ValueError("b's leading dimension must match A")

    tiles = SquareDiagTiles(A, tiles_per_proc=2)
    # global tile-row boundaries from the decomposition's index arithmetic
    bounds = [0] + [int(t) for t in tiles.row_indices[1:]] + [A.shape[0]]
    bounds = sorted(set(bounds))

    Al = A.larray.astype(jnp.result_type(A.larray.dtype, jnp.float32))
    bl = b.larray.astype(Al.dtype)
    if vector_rhs:
        bl = bl[:, None]
    x = jnp.zeros_like(bl)

    spans = list(zip(bounds[:-1], bounds[1:]))
    order = spans if lower else list(reversed(spans))
    for (s, e) in order:
        rhs = bl[s:e]
        if lower:
            rhs = rhs - Al[s:e, :s] @ x[:s]
        else:
            rhs = rhs - Al[s:e, e:] @ x[e:]
        blk = jax.scipy.linalg.solve_triangular(Al[s:e, s:e], rhs, lower=lower)
        x = x.at[s:e].set(blk)

    if vector_rhs:
        x = x[:, 0]
    out = factories.array(x, device=b.device, comm=b.comm)
    out.resplit_(b.split)
    return out


@functools.partial(jax.jit, static_argnums=(2,))
def _lanczos_fused(Al, v0l, m: int, key):
    """Whole Lanczos run as ONE XLA program (reference solver.py:68-184 does
    m Python iterations with a host sync per dot/norm; here the loop, the
    full reorthogonalization, and the breakdown restart all live on device).
    V is carried row-major (m, n) so each step is one row update; rows >= i
    are masked out of the reorthogonalization."""
    n = v0l.shape[0]
    dtype = v0l.dtype
    V = jnp.zeros((m, n), dtype)
    T = jnp.zeros((m, m), dtype)
    vr = v0l
    w = Al @ vr
    alpha = w @ vr
    w = w - alpha * vr
    V = V.at[0].set(vr)
    T = T.at[0, 0].set(alpha)
    row_ids = jnp.arange(m)

    def body(i, carry):
        V, T, w, key = carry
        beta = jnp.linalg.norm(w)
        key, sub = jax.random.split(key)
        # breakdown restart: a random vector replaces the collapsed residual
        # (the subsequent reorthogonalization projects out span(V[:i]))
        vn = jax.random.normal(sub, (n,), dtype)
        cand = jnp.where(beta > 1e-10, w / jnp.maximum(beta, 1e-30), vn)
        # full reorthogonalization against the first i basis rows
        Vm = jnp.where((row_ids < i)[:, None], V, jnp.zeros_like(V))
        proj = Vm @ cand  # (m,)
        vr = cand - Vm.T @ proj
        nrm = jnp.linalg.norm(vr)
        vr = jnp.where(nrm > 1e-12, vr / jnp.maximum(nrm, 1e-30), cand)
        w2 = Al @ vr
        alpha = w2 @ vr
        w_next = w2 - alpha * vr - beta * V[i - 1]
        T = T.at[i - 1, i].set(beta).at[i, i - 1].set(beta).at[i, i].set(alpha)
        V = V.at[i].set(vr)
        return V, T, w_next, key

    V, T, _, _ = jax.lax.fori_loop(1, m, body, (V, T, w, key))
    return V.T, T


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
):
    """Lanczos tridiagonalization with full reorthogonalization (reference
    solver.py:68-184), fused into a single XLA program (no per-iteration
    host round-trips)."""
    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be of type DNDarray, but was {type(A)}")
    if not isinstance(m, (int,)):
        raise TypeError(f"m must be int, but was {type(m)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    n, column = A.shape
    if v0 is None:
        import numpy as _np

        rng = _np.random.default_rng(0)
        v0 = factories.array(
            rng.standard_normal(n).astype(_np.float32), split=A.split, comm=A.comm
        )
        v0 = v0 / norm(v0)
    else:
        if v0.split != A.split:
            v0 = factories.array(v0, split=A.split, copy=True)

    v_arr, t_arr = _lanczos_fused(
        A.larray.astype(v0.dtype.jax_type()), v0.larray, m, jax.random.PRNGKey(0)
    )
    V = factories.array(v_arr, comm=A.comm, device=A.device)
    V.resplit_(A.split)
    T = factories.array(t_arr, comm=A.comm, device=A.device)

    if V_out is not None:
        V_out._replace(V.larray, V.split)
        if T_out is not None:
            T_out._replace(T.larray, T.split)
            return V_out, T_out
        return V_out, T
    if T_out is not None:
        T_out._replace(T.larray, T.split)
        return V, T_out
    return V, T

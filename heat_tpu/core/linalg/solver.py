"""Iterative solvers (reference: heat/core/linalg/solver.py).

Both are compositions of matmul/dot exactly as in the reference; the manual
Allreduce dots (solver.py:13-184) are sharded reductions here.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .. import factories
from ..dndarray import DNDarray
from .basics import dot, matmul, norm, transpose

__all__ = ["cg", "lanczos"]


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients for s.p.d. ``A`` (reference solver.py:13-65)."""
    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError(f"A, b and x0 need to be of type DNDarray, but were {type(A)}, {type(b)}, {type(x0)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("c needs to be a 1D vector")

    r = b - matmul(A, x0)
    p = r
    rsold = dot(r, r)
    x = x0

    for i in range(len(b)):
        Ap = matmul(A, p)
        alpha = rsold / dot(p, Ap)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = dot(r, r)
        if float(jnp.sqrt(rsnew.larray)) < 1e-10:
            if out is not None:
                out._replace(x.larray, x.split)
                return out
            return x
        p = r + ((rsnew / rsold) * p)
        rsold = rsnew

    if out is not None:
        out._replace(x.larray, x.split)
        return out
    return x


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
):
    """Lanczos tridiagonalization with full reorthogonalization (reference
    solver.py:68-184)."""
    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be of type DNDarray, but was {type(A)}")
    if not isinstance(m, (int,)):
        raise TypeError(f"m must be int, but was {type(m)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    n, column = A.shape
    if v0 is None:
        import numpy as _np

        rng = _np.random.default_rng(0)
        v0 = factories.array(
            rng.standard_normal(n).astype(_np.float32), split=A.split, comm=A.comm
        )
        v0 = v0 / norm(v0)
    else:
        if v0.split != A.split:
            v0 = factories.array(v0, split=A.split, copy=True)

    T = factories.zeros((m, m), dtype=v0.dtype, comm=A.comm)
    V = factories.zeros((n, m), dtype=v0.dtype, split=A.split, comm=A.comm)

    vr = v0
    # first iteration
    w = matmul(A, vr)
    alpha = float(dot(w, vr))
    w = w - alpha * vr
    T[0, 0] = alpha
    V[:, 0] = vr

    for i in range(1, m):
        beta = float(norm(w))
        if abs(beta) < 1e-10:
            # breakdown: restart with a random orthogonal vector
            import numpy as _np

            rng = _np.random.default_rng(i)
            vn = factories.array(
                rng.standard_normal(n).astype(_np.dtype(v0.dtype.jax_type())),
                split=A.split,
                comm=A.comm,
            )
            # orthogonalize against V
            vi_loc = V.larray[:, :i]
            proj = jnp.einsum("ij,i->j", vi_loc, vn.larray)
            vn = factories.array(
                vn.larray - jnp.einsum("ij,j->i", vi_loc, proj), split=A.split, comm=A.comm
            )
            vr = vn / norm(vn)
        else:
            vr = w / beta

        # full reorthogonalization (reference solver.py:118-135)
        vi_loc = V.larray[:, :i]
        proj = jnp.einsum("ij,i->j", vi_loc, vr.larray)
        vr = factories.array(
            vr.larray - jnp.einsum("ij,j->i", vi_loc, proj), split=A.split, comm=A.comm
        )
        nrm = float(norm(vr))
        if nrm > 1e-12:
            vr = vr / nrm

        w = matmul(A, vr)
        alpha = float(dot(w, vr))
        w = w - alpha * vr - beta * V[:, i - 1]

        T[i - 1, i] = beta
        T[i, i - 1] = beta
        T[i, i] = alpha
        V[:, i] = vr

    if V_out is not None:
        V_out._replace(V.larray, V.split)
        if T_out is not None:
            T_out._replace(T.larray, T.split)
            return V_out, T_out
        return V_out, T
    if T_out is not None:
        T_out._replace(T.larray, T.split)
        return V, T_out
    return V, T

"""Solvers (reference: heat/core/linalg/solver.py).

``cg``/``lanczos`` are compositions of matmul/dot exactly as in the
reference; the manual Allreduce dots (solver.py:13-184) are sharded
reductions here. ``cg`` is fused into ONE XLA program (``lax.while_loop``
with the convergence test on device) — the reference's Python loop
host-syncs every iteration; the fused loop dispatches once.
``solve_triangular`` is the blocked back/forward-substitution driven by the
``SquareDiagTiles`` decomposition (the tile grid the reference builds for
its tile-QR, reference tiling.py:331-1257).
"""

from __future__ import annotations

from typing import Optional

import collections
import functools

import jax
import jax.numpy as jnp

from .. import factories, fusion, resilience, sanitation, telemetry
from ..dndarray import DNDarray
from .basics import dot, matmul, norm, transpose

_T_COLLECTIVE = telemetry.force_trigger("collective")

__all__ = ["cg", "eigh", "eigvalsh", "lanczos", "solve", "solve_triangular"]


def _cg_body(Al, bl, x0l):
    """Whole CG run as one XLA program: the convergence test lives on device
    inside the while_loop, so there is no per-iteration host round-trip.
    Unjitted body shared by the eager jitted wrapper (``_cg_fused``) and the
    deferred recording (``fusion.defer_op``) — a pending operand chain then
    compiles into the same program as the whole CG sweep."""
    n = bl.shape[0]
    r0 = bl - Al @ x0l
    rs0 = r0 @ r0

    def cond(carry):
        _, _, _, rsold, i = carry
        return (i < n) & (jnp.sqrt(rsold) >= 1e-10)

    def body(carry):
        x, r, p, rsold, i = carry
        Ap = Al @ p
        alpha = rsold / (p @ Ap)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = r @ r
        p = r + (rsnew / rsold) * p
        return x, r, p, rsnew, i + 1

    x, _, _, _, _ = jax.lax.while_loop(cond, body, (x0l, r0, r0, rs0, jnp.int32(0)))
    return x


_cg_fused = jax.jit(_cg_body)


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients for s.p.d. ``A`` (reference solver.py:13-65)."""
    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError(f"A, b and x0 need to be of type DNDarray, but were {type(A)}, {type(b)}, {type(x0)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("c needs to be a 1D vector")

    # deferred: record the whole CG sweep as ONE collective DAG node over the
    # operands' LOGICAL views — a pending chain producing A/b/x0 (normal
    # equations, preconditioner setup, ...) compiles into the SAME program as
    # the sweep, and the result stays pending for the consumer's read
    node = fusion.defer_op(_cg_body, (A, b, x0))
    if node is not None:
        x = fusion.wrap_node(node, tuple(b.shape), None, x0)
        x.resplit_(x0.split)
        if out is not None:
            out._adopt(x)
            return out
        return x

    xl = _cg_fused(A.larray, b.larray, x0.larray)
    x = factories.array(xl, is_split=None, device=x0.device, comm=x0.comm)
    x.resplit_(x0.split)
    if out is not None:
        out._adopt(x)
        return out
    return x


@functools.lru_cache(maxsize=None)
def _tri_solve_kernel(axis, p, n, k, rows_loc, n_stages, owners, lower, dtype_name):
    """The blocked-substitution device function, UNJITTED — shared by the
    eager jitted wrapper (:func:`_tri_solve_program`) and the deferred
    recording (``fusion.defer_apply``), so both execute the identical
    per-stage psum schedule. See :func:`_tri_solve_program` for the stage
    anatomy and the collective budget."""
    dtype = jnp.dtype(dtype_name)
    n_pad = p * rows_loc

    from ._blocked import sanitize_slab

    def device_fn(Al, bl):
        # created inside the trace — a build-time jnp constant would leak a
        # tracer into the lru_cache when the factory first runs under an
        # outer jit (see basics._det_program)
        owners_arr = jnp.asarray(owners, jnp.int32)
        idx = jax.lax.axis_index(axis)
        # pad columns so every diagonal tile is square; pad rows become
        # identity rows, so their solution is exactly b's zero padding
        Alp, rows = sanitize_slab(Al, idx, rows_loc, n, n_pad, dtype)
        rhs0 = jnp.where((rows >= n)[:, None], 0.0, bl.astype(dtype))

        def stage(i, carry):
            rhs, x_own = carry
            t = i if lower else (n_stages - 1) - i
            start = t * rows_loc
            tile = jax.lax.dynamic_slice(Alp, (0, start), (rows_loc, rows_loc))
            cand = jax.scipy.linalg.solve_triangular(tile, rhs, lower=lower)
            is_owner = idx == owners_arr[t]
            xblk = jax.lax.psum(jnp.where(is_owner, cand, 0.0), axis)
            x_own = jnp.where(is_owner, xblk, x_own)
            # off-diagonal fold: subtract this tile-column's contribution
            # from every local rhs (rows already solved are never re-read)
            rhs = rhs - tile @ xblk
            return rhs, x_own

        x0 = jnp.zeros((rows_loc, k), dtype)
        _, x_own = jax.lax.fori_loop(0, n_stages, stage, (rhs0, x0))
        return x_own

    device_fn.__name__ = f"tri_solve_p{p}_n{n}_k{k}"
    return device_fn


@functools.lru_cache(maxsize=None)
def _tri_solve_program(mesh, axis, p, n, k, rows_loc, n_stages, owners, lower, dtype_name):
    """Fused distributed blocked substitution (one jitted shard_map program).

    ``A`` arrives as the PHYSICAL split-0 payload ``(p*rows_loc, n)`` —
    rows padded per the dndarray.parray contract — and ``b`` zero-padded to
    the same leading extent. The sweep runs ``n_stages`` stages inside a
    ``fori_loop`` (program size is O(1) in ``p`` — the compile-time-scaling
    requirement); stage ``t``:

      1. the diagonal owner (``owners[t]`` — the SquareDiagTiles ownership
         grid) solves its ``(rows_loc, rows_loc)`` diagonal tile against its
         current local rhs with the XLA triangular kernel,
      2. ONE psum of the solved ``(rows_loc, k)`` block replicates it,
      3. every device folds ``A[:, tile t] @ x_t`` out of its local rhs —
         the off-diagonal update, an MXU matmul with zero communication.

    Collective budget: ``n_stages`` psums of ``rows_loc * k`` elements —
    exactly one solved block each, never the operand (asserted by
    tests/test_linalg_depth HLO budgets). Pad rows are sanitized to identity
    rows inside the kernel, so their solution is the zero pad of ``b``.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    device_fn = _tri_solve_kernel(axis, p, n, k, rows_loc, n_stages, owners, lower, dtype_name)

    sharded = NamedSharding(mesh, P(axis, None))

    @functools.partial(jax.jit, in_shardings=(sharded, sharded), out_shardings=sharded)
    def run(A_phys, b_pad):
        return jax.shard_map(
            device_fn,
            mesh=mesh,
            in_specs=(P(axis, None), P(axis, None)),
            out_specs=P(axis, None),
            check_vma=False,
        )(A_phys, b_pad)

    return run


def _colvec_op(x):
    return x[:, None]


def _squeeze_col_op(x):
    return x[:, 0]


def _tri_solve_deferred(A: DNDarray, b: DNDarray, lower: bool):
    """Record the split-0 blocked substitution as a collective DAG node; None
    declines (collectives off, ragged operands, tracer payloads) back to the
    eager jitted program. Block-aligned operands only: the physical payload
    IS the logical one, so no pad/sanitize staging is needed and a pending
    producer chain (e.g. QR's R inside ``solve``) fuses straight into the
    substitution sweep."""
    if not fusion.collectives_active():
        return None
    n = int(A.shape[0])
    comm = A.comm
    p = comm.size
    if A.padded or b.padded or n % p != 0:
        return None
    vector_rhs = b.ndim == 1
    dtype = jnp.result_type(A.dtype.jax_type(), b.dtype.jax_type(), jnp.float32)

    from ._blocked import stage_grid

    p, rows_loc, n_stages, owners = stage_grid(A)
    if p * rows_loc != n:
        return None

    bn = fusion.phys_node(b)
    if bn is None:
        return None
    k = 1 if vector_rhs else int(b.shape[1])
    kernel = _tri_solve_kernel(
        comm.axis_name, p, n, k, rows_loc, n_stages, owners, bool(lower), dtype.name
    )
    try:
        bn = fusion.cast(bn, dtype)
        if vector_rhs:
            bn = fusion.record(_colvec_op, (bn,))
        node = fusion.defer_apply(
            comm, kernel, (A, bn), in_splits=(0, 0), out_split=0, check_vma=False
        )
        if node is None:
            return None
        if vector_rhs:
            node = fusion.record(_squeeze_col_op, (node,))
    except Exception as exc:  # narrowed: ONE policy decides what falls back
        if not resilience.record_recoverable(exc):
            raise
        return None
    if resilience._ARMED:
        # the declared schedule's fault site (per-stage in-kernel psums)
        resilience.check("collective.allreduce")
    if telemetry._MODE:
        # declared schedule: one psum of one solved (rows_loc, k) block per stage
        telemetry.record_collective(
            "allreduce",
            comm.axis_name,
            rows_loc * k * jnp.dtype(dtype).itemsize,
            dtype.name,
            count=n_stages,
        )
    gshape = (n,) if vector_rhs else (n, k)
    out = fusion.wrap_node(node, gshape, 0, b)
    if b.split != 0:
        out.resplit_(b.split)
    return out


def solve_triangular(A: DNDarray, b: DNDarray, lower: bool = False) -> DNDarray:
    """Solve ``A x = b`` for triangular ``A``.

    Replicated ``A``: one XLA triangular kernel. Split ``A``: a fused
    shard_map blocked-substitution program whose stage grid and diagonal
    ownership come from the :class:`~heat_tpu.core.tiling.SquareDiagTiles`
    decomposition (one tile per device — the runtime's physical ceil-chunk
    grid; the reference drives its tile-QR from the same decomposition,
    reference tiling.py:331-1257). One psum of one solved block per stage;
    the operand is never gathered. A split-1 ``A`` is resharded to split 0
    first (one alltoall — the column schedule would pay a full-rhs psum per
    stage instead).
    """
    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray):
        raise TypeError("A and b must be DNDarrays")
    if A.ndim != 2 or A.shape[0] != A.shape[1]:
        raise ValueError("A must be a square 2-D matrix")
    vector_rhs = b.ndim == 1
    if b.shape[0] != A.shape[0]:
        raise ValueError("b's leading dimension must match A")

    n = int(A.shape[0])
    if A.split is None or A.comm.size == 1:
        dtype = jnp.result_type(A.larray.dtype, b.larray.dtype, jnp.float32)
        bl = b.larray.astype(dtype)
        if vector_rhs:
            bl = bl[:, None]
        x = jax.scipy.linalg.solve_triangular(A.larray.astype(dtype), bl, lower=lower)
        if vector_rhs:
            x = x[:, 0]
        out = factories.array(x, device=b.device, comm=b.comm)
        out.resplit_(b.split)
        return out

    if A.split == 1:
        from ..manipulations import resplit as _resplit

        A = _resplit(A, 0)

    # deferred-first: block-aligned operands record the sweep as a collective
    # DAG node (operands stay pending; decline falls through to eager)
    deferred = _tri_solve_deferred(A, b, bool(lower))
    if deferred is not None:
        return deferred

    # first payload access on the distributed eager path: pending chains
    # force here and attribute to the collective schedule below
    with _T_COLLECTIVE:
        dtype = jnp.result_type(A.larray.dtype, b.larray.dtype, jnp.float32)

    comm = A.comm
    # stage grid + diagonal ownership from the tile decomposition (one tile
    # per device: the physical grid; shared with det via _blocked.stage_grid)
    from ._blocked import stage_grid

    p, rows_loc, n_stages, owners = stage_grid(A)

    from jax.sharding import NamedSharding, PartitionSpec

    bl = b.larray.astype(dtype)
    if vector_rhs:
        bl = bl[:, None]
    k = int(bl.shape[1])
    b_pad = jax.device_put(
        jnp.pad(bl, ((0, p * rows_loc - n), (0, 0))),
        NamedSharding(comm.mesh, PartitionSpec(comm.axis_name, None)),
    )

    if resilience._ARMED:
        # the declared schedule's fault site (per-stage in-kernel psums)
        resilience.check("collective.allreduce")
    if telemetry._MODE:
        # declared schedule: one psum of one solved (rows_loc, k) block per stage
        telemetry.record_collective(
            "allreduce",
            comm.axis_name,
            rows_loc * k * jnp.dtype(dtype).itemsize,
            dtype.name,
            count=n_stages,
        )
    fn = _tri_solve_program(
        comm.mesh, comm.axis_name, p, n, k, rows_loc, n_stages, owners, bool(lower), dtype.name
    )
    with _T_COLLECTIVE:
        x_pad = fn(A.parray, b_pad)
    x = x_pad[:n]
    if vector_rhs:
        x = x[:, 0]
    out = factories.array(x, device=b.device, comm=b.comm)
    out.resplit_(b.split)
    return out


def solve(a: DNDarray, b: DNDarray) -> DNDarray:
    """Solve ``a @ x = b`` for square full-rank ``a`` (beyond the reference,
    ``numpy.linalg.solve`` parity — including ``LinAlgError`` on a singular
    operand).

    Distributed end to end through the framework's own factorizations: a
    split-0 operand reshards to the column-split panel QR (one alltoall —
    the square shape fails TSQR's ``ceil(m/p) >= n`` row-block requirement,
    and a silent gather would violate the explicit-fallback policy), then
    one fused blocked triangular solve (:func:`solve_triangular`). numpy
    uses a pivoted LU instead, but QR is unconditionally stable and both
    stages have gather-free distributed schedules. Replicated operands take
    one local XLA kernel.
    """
    import numpy as _np

    from .qr import qr

    if not isinstance(a, DNDarray) or not isinstance(b, DNDarray):
        raise TypeError("a and b must be DNDarrays")
    if a.ndim != 2 or a.shape[0] != a.shape[1]:
        raise ValueError("a must be a square 2-D matrix")
    if b.ndim not in (1, 2) or b.shape[0] != a.shape[0]:
        raise ValueError(f"b must have leading dimension {a.shape[0]}, got {tuple(b.shape)}")

    if a.split is None or a.comm.size == 1:
        dtype = jnp.result_type(a.larray.dtype, b.larray.dtype, jnp.float32)
        x = jnp.linalg.solve(a.larray.astype(dtype), b.larray.astype(dtype))
        if not bool(jnp.isfinite(x).all()):
            raise _np.linalg.LinAlgError("solve: matrix is singular")
        out = factories.array(x, device=b.device, comm=b.comm)
        out.resplit_(b.split)
        return out

    if a.split == 0:
        from ..manipulations import resplit as _resplit

        a = _resplit(a, 1)  # square split-0 has no gather-free row schedule
    q, r = qr(a)
    qh = transpose(q)
    if jnp.issubdtype(qh.larray.dtype, jnp.complexfloating):
        from ..complex_math import conjugate as _conj

        qh = _conj(qh)  # Q^H, not Q^T: the unitary inverse
    rhs = matmul(qh, b)
    vector_rhs = b.ndim == 1
    if vector_rhs:
        rhs = rhs.reshape((a.shape[0], 1))
    x = solve_triangular(r, rhs, lower=False)
    if vector_rhs:
        x = x.reshape((a.shape[0],))
    if not bool(jnp.isfinite(x.larray).all()):
        raise _np.linalg.LinAlgError("solve: matrix is singular")
    return x


EighResult = collections.namedtuple("EighResult", "eigenvalues, eigenvectors")


def _eigh_prep(a: DNDarray, UPLO: str, op: str):
    """Shared eigh/eigvalsh front end: validation, the explicit replication
    warning, and numpy's one-triangle mirroring (shared with cholesky via
    :func:`._blocked.mirror_triangle`)."""
    from ._blocked import mirror_triangle

    sanitation.sanitize_in(a)
    if a.ndim != 2 or a.shape[0] != a.shape[1]:
        raise ValueError(f"{op} requires a square 2-D matrix")
    if UPLO not in ("L", "U"):
        raise ValueError(f"UPLO must be 'L' or 'U', got {UPLO!r}")
    if a.is_distributed():
        sanitation.warn_replicated(
            op, "no gather-free distributed symmetric eigensolver exists "
            "(tridiagonalization is sequential panel work); use lanczos for "
            "the dominant spectrum of large operands"
        )
    local = a.larray.astype(jnp.result_type(a.larray.dtype, jnp.float32))
    return mirror_triangle(local, UPLO)


def eigh(a: DNDarray, UPLO: str = "L") -> EighResult:
    """Eigendecomposition of a symmetric/Hermitian matrix (beyond the
    reference, ``numpy.linalg.eigh`` parity: ascending eigenvalues, the
    ``UPLO`` triangle read).

    Executes the one-kernel XLA path on the gathered operand — there is no
    gather-free distributed symmetric eigensolver here (tridiagonalization
    is sequential-panel work; use :func:`lanczos` for the dominant part of
    the spectrum of a LARGE operand). A distributed input warns through the
    shared explicit-fallback policy rather than degrading silently.
    """
    w, v = jnp.linalg.eigh(_eigh_prep(a, UPLO, "eigh"))
    mk = functools.partial(factories.array, device=a.device, comm=a.comm)
    return EighResult(mk(w), mk(v))


def eigvalsh(a: DNDarray, UPLO: str = "L") -> DNDarray:
    """Eigenvalues of a symmetric/Hermitian matrix (``numpy.linalg.eigvalsh``
    parity; see :func:`eigh` for the replication policy). Uses the
    eigenvalues-only kernel — no discarded eigenvector matrix."""
    w = jnp.linalg.eigvalsh(_eigh_prep(a, UPLO, "eigvalsh"))
    return factories.array(w, device=a.device, comm=a.comm)


@functools.partial(jax.jit, static_argnums=(2,))
def _lanczos_fused(Al, v0l, m: int, key):
    """Whole Lanczos run as ONE XLA program (reference solver.py:68-184 does
    m Python iterations with a host sync per dot/norm; here the loop, the
    full reorthogonalization, and the breakdown restart all live on device).
    V is carried row-major (m, n) so each step is one row update; rows >= i
    are masked out of the reorthogonalization."""
    n = v0l.shape[0]
    dtype = v0l.dtype
    V = jnp.zeros((m, n), dtype)
    T = jnp.zeros((m, m), dtype)
    vr = v0l
    w = Al @ vr
    alpha = w @ vr
    w = w - alpha * vr
    V = V.at[0].set(vr)
    T = T.at[0, 0].set(alpha)
    row_ids = jnp.arange(m)

    def body(i, carry):
        V, T, w, key = carry
        beta = jnp.linalg.norm(w)
        key, sub = jax.random.split(key)
        # breakdown restart: a random vector replaces the collapsed residual
        # (the subsequent reorthogonalization projects out span(V[:i]))
        vn = jax.random.normal(sub, (n,), dtype)
        cand = jnp.where(beta > 1e-10, w / jnp.maximum(beta, 1e-30), vn)
        # full reorthogonalization against the first i basis rows
        Vm = jnp.where((row_ids < i)[:, None], V, jnp.zeros_like(V))
        proj = Vm @ cand  # (m,)
        vr = cand - Vm.T @ proj
        nrm = jnp.linalg.norm(vr)
        vr = jnp.where(nrm > 1e-12, vr / jnp.maximum(nrm, 1e-30), cand)
        w2 = Al @ vr
        alpha = w2 @ vr
        w_next = w2 - alpha * vr - beta * V[i - 1]
        T = T.at[i - 1, i].set(beta).at[i, i - 1].set(beta).at[i, i].set(alpha)
        V = V.at[i].set(vr)
        return V, T, w_next, key

    V, T, _, _ = jax.lax.fori_loop(1, m, body, (V, T, w, key))
    return V.T, T


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
):
    """Lanczos tridiagonalization with full reorthogonalization (reference
    solver.py:68-184), fused into a single XLA program (no per-iteration
    host round-trips)."""
    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be of type DNDarray, but was {type(A)}")
    if not isinstance(m, (int,)):
        raise TypeError(f"m must be int, but was {type(m)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    n, column = A.shape
    if v0 is None:
        import numpy as _np

        rng = _np.random.default_rng(0)
        v0 = factories.array(
            rng.standard_normal(n).astype(_np.float32), split=A.split, comm=A.comm
        )
        v0 = v0 / norm(v0)
    else:
        if v0.split != A.split:
            v0 = factories.array(v0, split=A.split, copy=True)

    v_arr, t_arr = _lanczos_fused(
        A.larray.astype(v0.dtype.jax_type()), v0.larray, m, jax.random.PRNGKey(0)
    )
    V = factories.array(v_arr, comm=A.comm, device=A.device)
    V.resplit_(A.split)
    T = factories.array(t_arr, comm=A.comm, device=A.device)

    if V_out is not None:
        V_out._replace(V.larray, V.split)
        if T_out is not None:
            T_out._replace(T.larray, T.split)
            return V_out, T_out
        return V_out, T
    if T_out is not None:
        T_out._replace(T.larray, T.split)
        return V, T_out
    return V, T

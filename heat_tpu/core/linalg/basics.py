"""Basic linear algebra (reference: heat/core/linalg/basics.py).

The reference implements matmul as a hand-scheduled block-cyclic SUMMA with
Isend/Ibcast pipelines over a case table of split combinations
(basics.py:424-1050) and transpose via Alltoallw with derived MPI datatypes
(basics.py:2051-2120). On TPU these are *sharding problems, not scheduling
problems*: ``jnp.matmul`` over GSPMD-sharded operands lowers to the same
blockwise schedule (XLA picks SUMMA-style collectives on the MXU), and
transpose is a metadata permutation plus one resharding collective.
"""

from __future__ import annotations

import collections
import functools
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from .. import factories, fusion, resilience, sanitation, types
from .._operations import __binary_op as _binary_op
from ..communication import sanitize_comm
from ..dndarray import DNDarray, _ensure_split
from ..stride_tricks import sanitize_axis

__all__ = [
    "cholesky",
    "cross",
    "det",
    "dot",
    "einsum",
    "matrix_rank",
    "slogdet",
    "inv",
    "matmul",
    "matrix_norm",
    "norm",
    "outer",
    "projection",
    "trace",
    "transpose",
    "tril",
    "triu",
    "vdot",
    "vecdot",
    "vector_norm",
]


def _wrap_like(result: jax.Array, split: Optional[int], ref: DNDarray) -> DNDarray:
    if split is not None and (result.ndim == 0 or split >= result.ndim):
        split = None
    result = _ensure_split(result, split, ref.comm)
    return DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype), split, ref.device, ref.comm
    )


@functools.lru_cache(maxsize=None)
def _matmul_program(mesh, axis: str, a_split, b_split, out_split):
    """Cached 2-D matmul program with explicit operand/output shardings.

    Pinning the shardings hands GSPMD the whole case table of reference
    basics.py:513-629 as one lowering problem; the emitted schedule (asserted
    by tests/test_matmul_schedule.py) matches the reference's by case:
    contraction-split operands -> local partials + one all-reduce of the
    (m, n) product; split0xsplit0 / split0xsplit1 -> one all-gather of the
    SMALLER operand (the (k, n) factor), never the row-split operand;
    split1xsplit1 -> one all-gather of the left factor. No schedule gathers
    more than one operand's volume.
    """

    def spec(sp):
        if sp is None:
            return PartitionSpec()
        ent = [None, None]
        ent[sp] = axis
        return PartitionSpec(*ent)

    return jax.jit(
        jnp.matmul,
        in_shardings=(NamedSharding(mesh, spec(a_split)), NamedSharding(mesh, spec(b_split))),
        out_shardings=NamedSharding(mesh, spec(out_split)),
    )


def matmul(a: DNDarray, b: DNDarray, allow_resplit: bool = False) -> DNDarray:
    """Matrix product of two DNDarrays (reference basics.py:424-1050).

    Output distribution follows the reference's case table
    (basics.py:513-629): a row-split left operand yields a row-split product,
    a column-split right operand a column-split product; contraction-axis
    splits reduce via an XLA psum. The 2-D divisible case runs under a cached
    program with pinned in/out shardings so the collective schedule is
    deterministic and asserted (tests/test_matmul_schedule.py); ragged
    operands go through the logical view (padding must not enter the
    contraction).
    """
    sanitation.sanitize_in(a)
    sanitation.sanitize_in(b)
    if a.ndim == 1 and b.ndim == 1:
        return dot(a, b)

    if a.ndim == 2 and b.ndim == 2 and not a.padded and not b.padded:
        # the collective.matmul fault site fires before EITHER path
        # dispatches (the resplit_ precedent): guarded forcing can
        # degrade/replay the contraction like any other collective
        if resilience._ARMED:
            resilience.check("collective.matmul")
        # deferred-first: the case table records as a collective DAG node
        # (pending operands stay pending; the contraction's psum/allgather
        # compiles into the enclosing chain's program). None → the
        # schedule-pinned eager program (collectives off, tracers, ...).
        deferred = fusion.defer_matmul(a, b)
        if deferred is not None:
            return deferred
        # schedule-pinned path: out split per the case table; unpadded
        # operands guarantee the out dim is divisible whenever it inherits
        # a split from an operand
        if a.split == 0:
            out_split: Optional[int] = 0
        elif b.split == 1:
            out_split = 1
        else:
            out_split = None
        fn = _matmul_program(a.comm.mesh, a.comm.axis_name, a.split, b.split, out_split)
        result = fn(a.larray, b.larray)
        return DNDarray(
            result,
            tuple(result.shape),
            types.canonical_heat_type(result.dtype),
            out_split,
            a.device,
            a.comm,
        )

    result = jnp.matmul(a.larray, b.larray)
    # split bookkeeping over the matmul dimension map
    split: Optional[int] = None
    if a.ndim >= 2 and a.split is not None:
        if a.split == a.ndim - 2 or a.split < a.ndim - 2:
            # row split or batch split carries through
            split = a.split if result.ndim == a.ndim else None
    if split is None and b.ndim >= 2 and b.split == b.ndim - 1:
        split = result.ndim - 1
    return _wrap_like(result, split, a)


def dot(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None) -> Union[DNDarray, float]:
    """Dot product (reference basics.py:246-309): 1-D·1-D inner product (local
    dot + Allreduce in the reference, a sharded reduction here), otherwise
    matmul semantics."""
    if isinstance(a, DNDarray) and isinstance(b, DNDarray) and a.ndim == 1 and b.ndim == 1:
        result = jnp.dot(a.larray, b.larray)
        ret = _wrap_like(result, None, a)
        if out is not None:
            out._adopt(ret)
            return out
        return ret
    if a.ndim <= 2 and b.ndim <= 2:
        ret = matmul(a, b)
        if out is not None:
            # adopt, don't force: a deferred matmul stays one pending chain
            # through the out= seam
            out._adopt(ret)
            return out
        return ret
    raise NotImplementedError("ht.dot not implemented for N-D dot M-D arrays")


def vdot(x1: DNDarray, x2: DNDarray) -> DNDarray:
    """Conjugated dot product over flattened inputs (reference basics.py:2236)."""
    result = jnp.vdot(x1.larray, x2.larray)
    return _wrap_like(result, None, x1)


def vecdot(
    x1: DNDarray, x2: DNDarray, axis: Optional[int] = None, keepdims: bool = False, keepdim=None
) -> DNDarray:
    """Vector dot along an axis (reference basics.py:2301). ``keepdim`` is the
    reference's torch-style alias for ``keepdims``."""
    if keepdim is not None:
        keepdims = keepdim
    if axis is None:
        axis = -1
    a, b = x1.larray, x2.larray
    result = jnp.sum(jnp.conj(a) * b, axis=axis, keepdims=keepdims)
    split = x1.split if x1.split is not None else x2.split
    if split is not None:
        ax = axis % max(x1.ndim, x2.ndim)
        if split == ax:
            split = None
        elif not keepdims and split > ax:
            split -= 1
    return _wrap_like(result, split, x1)


def einsum(subscripts: str, *operands, optimize: Union[bool, str] = "optimal", out=None) -> DNDarray:
    """Einstein summation over the subscripts-string form of
    ``numpy.einsum`` (beyond the reference).

    Executes one sharded ``jnp.einsum`` over the logical global views — XLA
    GSPMD schedules the contraction collectives, exactly as for
    :func:`matmul`. The output split is inferred by following each operand's
    split-axis LABEL into the output subscript: a surviving label keeps the
    distribution, a contracted one yields a replicated result (the psum
    case). Ellipsis subscripts compute correctly but return replicated
    (batch-label tracking through ``...`` is not implemented), and numpy's
    interleaved sublist calling form is not supported. ``optimize`` is
    accepted for source compatibility (contraction-path search is XLA's job
    under jit; the value is forwarded to ``jnp.einsum``).
    """
    if out is not None:
        raise NotImplementedError("einsum does not support out= buffers")
    if not isinstance(subscripts, str):
        raise TypeError(
            "einsum requires the subscripts string as the first argument "
            "(the interleaved operand/sublist form is not supported)"
        )
    arrays = []
    ref = None
    for op in operands:
        if isinstance(op, DNDarray):
            arrays.append(op.larray)
            ref = ref if ref is not None else op
        else:
            arrays.append(jnp.asarray(op))
    if ref is None:
        raise TypeError("einsum requires at least one DNDarray operand")
    result = jnp.einsum(subscripts, *arrays, optimize=optimize)

    split: Optional[int] = None
    spec = subscripts.replace(" ", "")
    if "..." not in spec:
        if "->" in spec:
            in_spec, out_spec = spec.split("->")
        else:
            in_spec = spec
            # numpy's implicit-output rule: labels appearing exactly once,
            # alphabetically
            labels = in_spec.replace(",", "")
            out_spec = "".join(sorted(c for c in set(labels) if labels.count(c) == 1))
        in_specs = in_spec.split(",")
        if len(in_specs) == len(operands):
            for op, labels in zip(operands, in_specs):
                if (
                    isinstance(op, DNDarray)
                    and op.split is not None
                    and op.split < len(labels)
                ):
                    lbl = labels[op.split]
                    if lbl in out_spec:
                        split = out_spec.index(lbl)
                        break
    if split is not None and (result.ndim == 0 or split >= result.ndim):
        split = None
    return _wrap_like(result, split, ref)


def cross(
    x1: DNDarray, x2: DNDarray, axisa: int = -1, axisb: int = -1, axisc: int = -1, axis: int = -1
) -> DNDarray:
    """Cross product of 3-vectors (reference basics.py:46-159)."""
    result = jnp.cross(x1.larray, x2.larray, axisa=axisa, axisb=axisb, axisc=axisc, axis=axis)
    split = x1.split if result.ndim == x1.ndim else None
    return _wrap_like(result, split, x1)


@functools.lru_cache(maxsize=None)
def _det_program(mesh, axis, p, n, rows_loc, n_stages, owners, dtype_name):
    """Fused distributed determinant: blocked forward elimination (Schur
    recursion) as ONE shard_map program (the TPU analog of the reference's
    distributed elimination, reference basics.py:160-245).

    Stage ``t``: the diagonal owner LU-factors its ``(rows_loc, rows_loc)``
    diagonal tile (``slogdet`` — partial pivoting WITHIN the tile),
    accumulates sign/log|det|, and broadcasts ``D^-1 @ W_owner`` with one
    psum; every later device folds the block column out of its rows with an
    MXU matmul. No cross-tile pivoting — a singular-to-working-precision
    diagonal tile surfaces as a non-finite result, which the caller catches
    and retries on the replicated path (with a warning).

    Collective budget per stage: one ``(rows_loc, n_pad)`` psum (one row
    slab) + the scalar accumulators — never the whole operand at once.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    dtype = jnp.dtype(dtype_name)
    n_pad = p * rows_loc

    # sign handling: each device counts its own stages' negative pivot
    # signs; one scalar psum at the end turns the count's parity into the
    # global sign (a product of ±1 is not psum-able, its negative count is)
    from ._blocked import sanitize_slab

    def device_fn(Al):
        # constants must be created INSIDE the traced fn: this factory can
        # first run during an outer jit trace (det under a user's jax.jit),
        # and a build-time jnp constant would cache that trace's tracer
        owners_arr = jnp.asarray(owners, jnp.int32)
        idx = jax.lax.axis_index(axis)
        W, _ = sanitize_slab(Al, idx, rows_loc, n, n_pad, dtype)  # pad rows: det 1

        def stage(i, carry):
            W, neg, zero, logabs = carry
            start = i * rows_loc
            is_owner = idx == owners_arr[i]
            D = jax.lax.dynamic_slice(W, (0, start), (rows_loc, rows_loc))
            s, la = jnp.linalg.slogdet(D)
            neg = neg + jnp.where(is_owner & (s < 0), 1.0, 0.0)
            zero = zero + jnp.where(is_owner & (s == 0), 1.0, 0.0)
            logabs = logabs + jnp.where(is_owner, la, 0.0)
            B = jnp.linalg.solve(D, W)
            B = jax.lax.psum(jnp.where(is_owner, B, 0.0), axis)
            C = jax.lax.dynamic_slice(W, (0, start), (rows_loc, rows_loc))
            W = jnp.where(is_owner, W, W - C @ B)
            return W, neg, zero, logabs

        z = jnp.zeros((), dtype)
        _, neg, zero, logabs = jax.lax.fori_loop(0, n_stages, stage, (W, z, z, z))
        neg = jax.lax.psum(neg, axis)  # total count of negative pivot-signs
        zero = jax.lax.psum(zero, axis)  # any exactly-singular pivot => sign 0
        logabs = jax.lax.psum(logabs, axis)
        sign = jnp.where(jnp.mod(neg, 2.0) > 0.5, -1.0, 1.0).astype(dtype)
        sign = jnp.where(zero > 0, 0.0, sign)
        return sign, logabs

    sharded = NamedSharding(mesh, P(axis, None))
    rep = NamedSharding(mesh, P())

    @functools.partial(jax.jit, in_shardings=(sharded,), out_shardings=(rep, rep))
    def run(A_phys):
        return jax.shard_map(
            device_fn,
            mesh=mesh,
            in_specs=(P(axis, None),),
            out_specs=(P(), P()),
            check_vma=False,
        )(A_phys)

    return run


@functools.lru_cache(maxsize=None)
def _cholesky_program(mesh, axis, p, n, rows_loc, n_stages, owners, dtype_name):
    """Fused distributed Cholesky (beyond the reference): right-looking
    blocked factorization as ONE shard_map program, sharing the det/solve
    scaffolding (stage grid from SquareDiagTiles, pad rows sanitized to
    identity — their factor column is the identity block, sliced off).

    Stage ``t``: the diagonal owner factors its updated ``(b, b)`` tile; ONE
    psum replicates ``L_tt``; every device forms its panel block
    ``C_i = W_i[:, t] @ L_tt^-T`` (zero above the diagonal), ONE all_gather
    assembles the block column, and the trailing matrix update
    ``W -= C @ col^T`` is a local MXU matmul — already-factored columns see
    only zero contributions, so no masking of the update is needed.

    Collective budget per stage: one ``(b, b)`` psum + one block-column
    all_gather (``p*b*b`` elements) — never the operand.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    dtype = jnp.dtype(dtype_name)
    n_pad = p * rows_loc

    def device_fn(Al):
        from ._blocked import sanitize_slab

        # created inside the trace — a build-time jnp constant would leak a
        # tracer into the lru_cache when the factory first runs under an
        # outer jit (see _det_program)
        owners_arr = jnp.asarray(owners, jnp.int32)
        idx = jax.lax.axis_index(axis)
        W, _ = sanitize_slab(Al, idx, rows_loc, n, n_pad, dtype)
        L = jnp.zeros_like(W)

        def stage(t, carry):
            W, L = carry
            start = t * rows_loc
            D = jax.lax.dynamic_slice(W, (0, start), (rows_loc, rows_loc))
            is_owner = idx == owners_arr[t]
            # numpy convention: ONLY the lower triangle is read. The owner's
            # diagonal tile may carry arbitrary upper-triangle content (and,
            # after stage updates, modified upper garbage) — mirror its
            # lower triangle before factoring. Subdiagonal tiles are pure
            # lower-triangle data and are used as-is.
            Dsym = jnp.tril(D) + jnp.tril(D, -1).T
            Ltt = jnp.linalg.cholesky(Dsym)
            Ltt = jax.lax.psum(jnp.where(is_owner, Ltt, 0.0), axis)
            # panel: C_i = W_i[:, t] L_tt^-T for subdiagonal devices; the
            # owner's block is L_tt itself (no solve against its own tile,
            # whose upper triangle is unspecified)
            C = jax.lax.linalg.triangular_solve(
                Ltt, D, left_side=False, lower=True, transpose_a=True
            )
            C = jnp.where(is_owner, Ltt, C)
            C = jnp.where(idx >= owners_arr[t], C, 0.0)
            L = jax.lax.dynamic_update_slice(L, C, (0, start))
            col = jax.lax.all_gather(C, axis).reshape(n_pad, rows_loc)
            W = W - C @ col.T
            return W, L

        _, L = jax.lax.fori_loop(0, n_stages, stage, (W, L))
        return L

    sharded = NamedSharding(mesh, P(axis, None))

    @functools.partial(jax.jit, in_shardings=(sharded,), out_shardings=sharded)
    def run(A_phys):
        return jax.shard_map(
            device_fn,
            mesh=mesh,
            in_specs=(P(axis, None),),
            out_specs=P(axis, None),
            check_vma=False,
        )(A_phys)

    return run


def cholesky(a: DNDarray) -> DNDarray:
    """Lower-triangular Cholesky factor of an SPD matrix, ``a = L @ L^T``
    (beyond the reference).

    Follows numpy exactly: ONLY the lower triangle is read (a matrix stored
    lower-triangle-only factors identically to its symmetric completion —
    note JAX's own ``jnp.linalg.cholesky`` instead symmetrizes the full
    input), and a non-positive-definite operand raises
    ``numpy.linalg.LinAlgError``. Distributed split operands run a fused
    right-looking blocked program (:func:`_cholesky_program` — one small
    psum + one block-column all_gather per stage, operand never gathered);
    replicated operands take the local XLA kernel.
    """
    sanitation.sanitize_in(a)
    if a.ndim != 2 or a.shape[0] != a.shape[1]:
        raise ValueError("cholesky requires a square 2-D matrix")
    is_complex = jnp.issubdtype(a.larray.dtype, jnp.complexfloating)
    if a.split is not None and a.comm.size > 1 and is_complex:
        sanitation.warn_replicated(
            "cholesky", "the blocked program's panel solve is real-only; "
            "computing the Hermitian factorization on the gathered operand"
        )
    if a.split is not None and a.comm.size > 1 and not is_complex:
        from ._blocked import stage_grid

        if a.split == 1:
            from ..manipulations import resplit as _resplit

            af = _resplit(a, 0)
        else:
            af = a
        comm = af.comm
        n = int(af.shape[0])
        p, rows_loc, n_stages, owners = stage_grid(af)
        fn = _cholesky_program(
            comm.mesh, comm.axis_name, p, n, rows_loc, n_stages, owners,
            jnp.dtype(_float_for(af)).name,
        )
        L_pad = fn(af.parray)
        # pad rows factor as identity; slice the logical block
        L = L_pad[:n, :n]
        # the LinAlgError probe is a host read — impossible under a jit
        # trace, where a non-SPD operand propagates nan instead (numpy's
        # exception contract holds eagerly)
        if sanitation.is_concrete(L) and not bool(jnp.isfinite(jnp.diagonal(L)).all()):
            raise np.linalg.LinAlgError("cholesky: matrix is not positive definite")
        out = _wrap_like(L, a.split, a)
        return out
    # numpy reads only the lower triangle; mirror it explicitly because the
    # XLA kernel would symmetrize the FULL input instead
    from ._blocked import mirror_triangle

    sym = mirror_triangle(a.larray.astype(_float_for(a)), "L")
    result = jnp.linalg.cholesky(sym)
    if sanitation.is_concrete(result) and not bool(jnp.isfinite(result).all()):
        raise np.linalg.LinAlgError("cholesky: matrix is not positive definite")
    return _wrap_like(result, a.split, a)


def _slogdet_core(a: DNDarray, op: str):
    """Shared det/slogdet dispatch: run the fused blocked-elimination
    program when a distributed real path exists, else None (caller takes
    the replicated kernel). Every fallback announces itself:
    complex split operands (the sign-parity accumulator is real-only — a
    complex slogdet sign is a phase, not ±1) and garbage from a singular
    non-final diagonal tile both warn through the shared policy.
    Returns ``(sign, logabs)`` — an exactly-singular final Schur block is
    the valid ``(0, -inf)``."""
    sanitation.sanitize_in(a)
    if a.ndim < 2 or a.shape[-1] != a.shape[-2]:
        raise ValueError("Last two dimensions of the array must be square")
    if not (a.ndim == 2 and a.split is not None and a.comm.size > 1):
        return None
    if jnp.issubdtype(a.larray.dtype, jnp.complexfloating):
        sanitation.warn_replicated(
            op, "complex determinants have no sign-parity encoding in the "
            "blocked-elimination program; computing on the gathered operand"
        )
        return None
    from ._blocked import stage_grid

    if a.split == 1:
        from ..manipulations import resplit as _resplit

        af = _resplit(a, 0)
    else:
        af = a
    comm = af.comm
    n = int(af.shape[0])
    p, rows_loc, n_stages, owners = stage_grid(af)
    fn = _det_program(
        comm.mesh, comm.axis_name, p, n, rows_loc, n_stages, owners,
        jnp.dtype(_float_for(af)).name,
    )
    sign, logabs = fn(af.parray)
    if not sanitation.is_concrete(logabs):
        # under a jit trace the singular-tile probe (a host read) cannot
        # run: return the program's result directly. An exactly-singular
        # final block is still the valid (0, -inf); a singular NON-final
        # diagonal tile — which the eager path would catch and retry on the
        # replicated kernel — propagates nan, as numpy's LU would overflow
        return sign, logabs
    singular_exact = bool((sign == 0) & (logabs == -jnp.inf))
    if bool(jnp.isfinite(logabs)) or singular_exact:
        return sign, logabs
    sanitation.warn_replicated(
        op, "a diagonal tile was singular under blocked elimination "
        "(no cross-tile pivoting); falling back to the replicated LU kernel"
    )
    return None


def det(a: DNDarray) -> DNDarray:
    """Determinant (reference basics.py:160-245: distributed elimination).

    Distributed 2-D split operands run the fused blocked-elimination program
    (:func:`_det_program` via the :func:`_slogdet_core` dispatch shared with
    :func:`slogdet` — one psum'd pivot-slab broadcast per stage, the operand
    never gathered); complex/singular-tile/batched/replicated cases take the
    local XLA kernel, warning where a split operand degrades.
    """
    core = _slogdet_core(a, "det")
    if core is not None:
        sign, logabs = core
        return _wrap_like(sign * jnp.exp(logabs), None, a)
    result = jnp.linalg.det(a.larray.astype(_float_for(a)))
    return _wrap_like(result, None, a)


SlogdetResult = collections.namedtuple("SlogdetResult", "sign, logabsdet")


def slogdet(a: DNDarray) -> "SlogdetResult":
    """Sign and log|det| (beyond the reference, ``numpy.linalg.slogdet``
    parity) — the overflow-free determinant for large operands.

    Distributed real 2-D operands read (sign, log|det|) straight out of the
    blocked-elimination program's accumulators (the :func:`_slogdet_core`
    dispatch shared with :func:`det`, which is exactly
    ``sign * exp(logabsdet)`` of this); complex and fallback cases take the
    local XLA kernel, warning for split operands per the explicit policy.
    """
    core = _slogdet_core(a, "slogdet")
    if core is not None:
        sign, logabs = core
        return SlogdetResult(_wrap_like(sign, None, a), _wrap_like(logabs, None, a))
    sign, logabs = jnp.linalg.slogdet(a.larray.astype(_float_for(a)))
    return SlogdetResult(_wrap_like(sign, None, a), _wrap_like(logabs, None, a))


def matrix_rank(a: DNDarray, tol=None, hermitian: bool = False, rtol=None) -> DNDarray:
    """Rank of a 2-D operand from its singular values (the
    ``numpy.linalg.matrix_rank`` contract for single matrices: default
    ``tol = max(m, n) * eps * max(S)``; ``rtol`` scales ``max(S)``
    directly; numpy's STACKED ndim>2 form is not supported — the singular
    values come from the distributed 2-D construction).

    Singular values come from the framework's own construction — the
    distributed TSQR-based :func:`~heat_tpu.core.linalg.svd.svd` for split
    2-D operands (``hermitian=True`` uses the replicated symmetric
    eigensolver with the shared replication policy).
    """
    sanitation.sanitize_in(a)
    if a.ndim != 2:
        raise ValueError(
            "matrix_rank requires a 2-D operand (numpy's stacked ndim>2 form "
            "is not supported)"
        )
    if tol is not None and rtol is not None:
        raise ValueError("tol and rtol cannot both be given")
    if hermitian:
        from .solver import eigvalsh

        s_arr = jnp.abs(eigvalsh(a).larray)
    else:
        from .svd import svd as _svd

        s_arr = _svd(a, compute_uv=False).larray
    if tol is None and rtol is not None:
        tol = rtol * jnp.max(s_arr)
    elif tol is None:
        eps = jnp.finfo(s_arr.dtype).eps
        tol = max(int(a.shape[0]), int(a.shape[1])) * eps * jnp.max(s_arr)
    rank = jnp.sum(s_arr > tol).astype(jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)
    return _wrap_like(rank, None, a)


def inv(a: DNDarray) -> DNDarray:
    """Matrix inverse (reference basics.py:312-421: distributed Gauss-Jordan
    with pivoting).

    Distributed 2-D operands invert via the framework's own distributed
    factorizations: ``A = QR`` (TSQR / blocked panel loop, linalg/qr.py) and
    ``A^-1 = R^-1 Q^T`` through the SquareDiagTiles-blocked triangular solve
    — numerically stable without the pivoting choreography the reference's
    Gauss-Jordan needs. Small/replicated/batched operands take one XLA LU
    kernel.
    """
    sanitation.sanitize_in(a)
    if a.ndim < 2 or a.shape[-1] != a.shape[-2]:
        raise ValueError("Last two dimensions of the array must be square")
    if a.ndim == 2 and a.split is not None and a.comm.size > 1:
        import jax

        from .qr import qr as _qr
        from .solver import solve_triangular

        af = a if types.heat_type_is_inexact(a.dtype) else a.astype(
            types.promote_types(a.dtype, types.float32)
        )
        Q, R = _qr(af)
        qt = transpose(Q, (1, 0))
        if R.split is None:
            # TSQR leaves R replicated: solve in ONE local kernel against the
            # global view of Q^T and place the result at a's split directly —
            # a single device_put, no intermediate replicated hop
            xl = jax.scipy.linalg.solve_triangular(
                R.larray.astype(_float_for(af)), qt.larray.astype(_float_for(af)), lower=False
            )
            return _wrap_like(xl, a.split, a)
        out = solve_triangular(R, qt, lower=False)
        if out.split != a.split:
            out.resplit_(a.split)
        return out
    result = jnp.linalg.inv(a.larray.astype(_float_for(a)))
    return _wrap_like(result, a.split, a)


def _float_for(a: DNDarray):
    """Compute dtype for factorization-class kernels: ints promote to f32,
    and so do the half floats (bfloat16/float16) — XLA's LAPACK-class
    lowerings (lu, cholesky, qr, triangular_solve) have no half-precision
    kernels and raise 'Unsupported dtype bfloat16'. f64/complex pass
    through."""
    if types.heat_type_is_inexact(a.dtype) and a.dtype not in (
        types.bfloat16,
        types.float16,
    ):
        return a.dtype.jax_type()
    return types.promote_types(a.dtype, types.float32).jax_type()


def matrix_norm(
    x: DNDarray, axis=None, keepdims: bool = False, ord=None
) -> DNDarray:
    """Matrix norm over a 2-axis pair (reference basics.py:1095-1224)."""
    sanitation.sanitize_in(x)
    if axis is None:
        if x.ndim != 2:
            raise ValueError("dimensions do not match, axis must be given for ndim != 2")
        axis = (0, 1)
    if not (isinstance(axis, tuple) and len(axis) == 2):
        raise TypeError(f"axis must be a 2-tuple, got {axis}")
    row_axis, col_axis = (sanitize_axis(x.shape, ax) for ax in axis)
    if ord in (None, "fro"):
        result = jnp.sqrt(
            jnp.sum(jnp.abs(x.larray.astype(_float_for(x))) ** 2, axis=(row_axis, col_axis), keepdims=keepdims)
        )
    elif ord == "nuc":
        result = jnp.sum(
            jnp.linalg.svd(x.larray.astype(_float_for(x)), compute_uv=False), axis=-1, keepdims=False
        )
        if keepdims:
            result = jnp.expand_dims(jnp.expand_dims(result, row_axis), col_axis)
    elif ord in (1, -1, np.inf, -np.inf):
        sum_axis = col_axis if ord in (np.inf, -np.inf) else row_axis
        red = jnp.max if ord in (1, np.inf) else jnp.min
        sums = jnp.sum(jnp.abs(x.larray.astype(_float_for(x))), axis=sum_axis, keepdims=True)
        other = row_axis if sum_axis == col_axis else col_axis
        result = red(sums, axis=(row_axis, col_axis), keepdims=keepdims)
    elif ord in (2, -2):
        sv = jnp.linalg.svd(x.larray.astype(_float_for(x)), compute_uv=False)
        result = jnp.max(sv, axis=-1) if ord == 2 else jnp.min(sv, axis=-1)
        if keepdims:
            result = jnp.expand_dims(jnp.expand_dims(result, row_axis), col_axis)
    else:
        raise ValueError(f"Invalid norm order {ord} for matrices")
    out_split = None
    if x.split is not None and x.split not in (row_axis, col_axis):
        out_split = x.split if keepdims else x.split - sum(1 for ax in (row_axis, col_axis) if ax < x.split)
    return _wrap_like(result, out_split, x)


def vector_norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Vector norm over an axis (reference basics.py:1225-1330)."""
    sanitation.sanitize_in(x)
    if axis is None and ord is not None and x.ndim > 1:
        axis = tuple(range(x.ndim))
    if isinstance(axis, (list, tuple)) and len(axis) > 1 and ord is not None and ord not in (2,):
        pass
    result = jnp.linalg.norm(
        x.larray.astype(_float_for(x)),
        ord=ord,
        axis=axis if axis is None or isinstance(axis, int) else tuple(axis),
        keepdims=keepdims,
    )
    out_split = None
    if x.split is not None and axis is not None:
        axes = (axis,) if isinstance(axis, int) else tuple(sanitize_axis(x.shape, a) for a in axis)
        axes = tuple(sanitize_axis(x.shape, a) for a in axes)
        if x.split not in axes:
            out_split = x.split if keepdims else x.split - sum(1 for a in axes if a < x.split)
    return _wrap_like(result, out_split, x)


def norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Matrix or vector norm (reference basics.py:1331-1371)."""
    if axis is None and ord is None:
        # frobenius over everything
        return vector_norm(x, axis=None, keepdims=keepdims, ord=None)
    if axis is None:
        axis = (0, 1) if x.ndim == 2 else None
    if isinstance(axis, tuple) and len(axis) == 2:
        return matrix_norm(x, axis=axis, keepdims=keepdims, ord=ord)
    return vector_norm(x, axis=axis, keepdims=keepdims, ord=ord)


def outer(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None, split: Optional[int] = None) -> DNDarray:
    """Outer product of two 1-D arrays (reference basics.py:1372-1604: a ring
    pass of shards; here one sharded jnp.outer whose collectives XLA derives)."""
    sanitation.sanitize_in(a)
    sanitation.sanitize_in(b)
    result = jnp.outer(a.larray.reshape(-1), b.larray.reshape(-1))
    if split is None:
        split = 0 if a.split is not None else (1 if b.split is not None else None)
    ret = _wrap_like(result, split, a)
    if out is not None:
        out._replace(ret.larray.astype(out.dtype.jax_type()), ret.split)
        return out
    return ret


def projection(a: DNDarray, b: DNDarray) -> DNDarray:
    """Project vector a onto vector b (reference basics.py:1605-1628)."""
    if a.ndim != 1 or b.ndim != 1:
        raise RuntimeError(f"a, b must be vectors of length 1, but were {a.ndim}, {b.ndim}")
    return (dot(a, b) / dot(b, b)) * b


def trace(
    a, offset: int = 0, axis1: int = 0, axis2: int = 1, dtype=None, out: Optional[DNDarray] = None
):
    """Sum of diagonal elements (reference basics.py:1629-1965)."""
    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if a.ndim < 2:
        raise ValueError(f"x must be at least two-dimensional, but was {a.ndim}-dimensional")
    axis1 = sanitize_axis(a.shape, axis1)
    axis2 = sanitize_axis(a.shape, axis2)
    if axis1 == axis2:
        raise ValueError(f"axis1 and axis2 cannot be the same, but were {axis1}, {axis2}")
    result = jnp.trace(a.larray, offset=offset, axis1=axis1, axis2=axis2)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype).jax_type())
    ret = _wrap_like(result, None, a)
    if a.ndim == 2:
        # the reference rejects out= for the scalar 2-D case (reference
        # basics.py:1756-1762); the scalar return mirrors its behavior —
        # except under a jit trace, where the host read is impossible and
        # the 0-d DNDarray is returned instead
        if out is not None:
            raise ValueError(
                "`out` is not applicable if result is a scalar / input `a` is 2-dimensional"
            )
        if ret.ndim == 0 and sanitation.is_concrete(result):
            return ret.item()
        return ret
    if out is not None:
        out._replace(ret.larray, ret.split)
        return out
    return ret


def transpose(a: DNDarray, axes: Optional[Sequence[int]] = None) -> DNDarray:
    """Permute dimensions (reference basics.py:2051-2120: Alltoallw with
    derived datatypes; here a lazy permutation + one resharding)."""
    sanitation.sanitize_in(a)
    if axes is None:
        axes = tuple(reversed(range(a.ndim)))
    else:
        axes = tuple(int(ax) if not hasattr(ax, "item") else int(ax.item()) for ax in axes)
        if len(axes) != a.ndim:
            raise ValueError("axes do not match tensor shape")
        axes = tuple(sanitize_axis(a.shape, ax) for ax in axes)
    result = jnp.transpose(a.larray, axes)
    split = axes.index(a.split) if a.split is not None else None
    return _wrap_like(result, split, a)


def tril(m: DNDarray, k: int = 0) -> DNDarray:
    """Lower-triangular part (reference basics.py:2121-2177)."""
    return _tri(m, k, jnp.tril)


def triu(m: DNDarray, k: int = 0) -> DNDarray:
    """Upper-triangular part (reference basics.py:2178-2235)."""
    return _tri(m, k, jnp.triu)


def _tri(m: DNDarray, k: int, fn) -> DNDarray:
    sanitation.sanitize_in(m)
    arr = m.larray
    expanded = False
    if arr.ndim == 1:
        # the reference expands vectors to (n, n) (basics.py:2121)
        arr = jnp.broadcast_to(arr, (arr.shape[0], arr.shape[0]))
        expanded = True
    result = fn(arr, k=k)
    split = m.split if not expanded else (0 if m.split is not None else None)
    return _wrap_like(result, split, m)

"""Index finding operations (reference: heat/core/indexing.py)."""

from __future__ import annotations

from typing import Union

import jax.numpy as jnp

from . import sanitation, types
from ._operations import __binary_op as _binary_op
from .dndarray import DNDarray, _ensure_split

__all__ = ["nonzero", "where"]


def nonzero(x: DNDarray) -> DNDarray:
    """Indices of non-zero elements as an (nnz, ndim) array (reference
    indexing.py:16-90; the reference corrects local indices by lshape offsets —
    global indexing makes that moot)."""
    sanitation.sanitize_in(x)
    idx = jnp.nonzero(x.larray)
    result = jnp.stack(idx, axis=1) if x.ndim > 1 else idx[0]
    result = result.astype(types.index_dtype())
    split = 0 if x.split is not None else None
    result = _ensure_split(result, split, x.comm)
    return DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype), split, x.device, x.comm
    )


def where(cond: DNDarray, x=None, y=None) -> DNDarray:
    """Ternary where / nonzero (reference indexing.py:91-151)."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y should be given")
    if not isinstance(x, DNDarray) and not isinstance(y, DNDarray):
        # both scalars/arrays: ht.where(a < 0, 0, 1) — the reference's
        # canonical usage (indexing.py:120-135)
        result = jnp.where(cond.larray.astype(bool), x, y)
        result = _ensure_split(result, cond.split, cond.comm)
        return DNDarray(
            result,
            tuple(result.shape),
            types.canonical_heat_type(result.dtype),
            cond.split,
            cond.device,
            cond.comm,
        )
    x_ref = x if isinstance(x, DNDarray) else y

    def op(a, b):
        # the engine's pad-aware fast path hands us PHYSICAL (padded) payloads
        # (in either operand slot — the other may be a scalar); align cond to
        # the same layout (garbage selected in the padding region stays in
        # the padding region)
        c = cond.larray
        a_sh = tuple(getattr(a, "shape", ()))
        b_sh = tuple(getattr(b, "shape", ()))
        if (
            isinstance(x_ref, DNDarray)
            and x_ref.padded
            and tuple(x_ref.parray.shape) in (a_sh, b_sh)
            and cond.ndim == x_ref.ndim
            and cond.shape[x_ref.split] == x_ref.shape[x_ref.split]
        ):
            if cond.split == x_ref.split:
                c = cond.parray
            else:
                widths = [(0, 0)] * cond.ndim
                widths[x_ref.split] = (
                    0,
                    int(x_ref.parray.shape[x_ref.split]) - cond.shape[x_ref.split],
                )
                c = jnp.pad(c, widths)
        return jnp.where(c.astype(bool), a, b)

    # the closure reads cond/x_ref state: it must run in the eager engine,
    # never be abstractly traced or cached by the fusion recorder
    op._no_fusion = True
    return _binary_op(op, x, y)

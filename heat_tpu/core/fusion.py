"""Eager op-chain fusion engine with a sharded-program cache.

The reference HeAT design (and our port until this module) is eager op-by-op:
every operator call dispatches its own XLA program, so a 10-op elementwise
chain pays 10 dispatches and every reduction ends in its own device sync.
This module makes the L3 engines (``core/_operations.py``) *deferred*: chains
of elementwise / broadcast / cast / reduce ops are recorded as a small
expression DAG (:class:`LazyArray` nodes stored as the ``DNDarray`` payload)
instead of being executed, and the whole chain is materialized as ONE jitted,
sharding-aware XLA program at a *forcing point*:

* ``DNDarray.parray`` / ``.larray`` access (and everything built on them:
  ``numpy()``, ``item()``, printing, I/O, indexing, collectives, linalg,
  ``out=`` buffers, mixed eager fallbacks),
* flattening for ``jax.jit`` pytree pipelines (``_tree_flatten``).

Compiled programs live in an LRU keyed on (DAG structure — op identities,
topology, static kwargs — leaf logical shapes, dtypes, shardings i.e. split
axes + mesh), so steady-state loops hit compiled code with zero retraces.
Reductions record lazily too: a chain of k reductions feeding one consumer
costs ONE device sync at the forcing point instead of k.

Correctness stance
------------------
* *Always-correct, transparently-forced*: any access to the physical payload
  forces the chain; no user-visible API returns unmaterialized state.
* The pad+mask ragged contract is preserved: identical-layout chains compute
  on the *physical* (padded) payloads, so padding garbage stays in padding
  through fused programs; reductions across the split axis record an explicit
  un-pad slice so padding never enters the reduction (exactly the eager
  engines' rule).
* Anything the recorder cannot prove deferrable (``out=``/``where=`` buffers,
  unhashable kwargs, tracer payloads under an enclosing ``jax.jit``, padded
  broadcasts, shape-changing "local" ops) falls back to the eager engine
  unchanged.

``HEAT_TPU_FUSION=0`` is the escape hatch: it disables recording (the eager
engines run exactly as before); forcing of already-recorded nodes keeps
working regardless of the flag.

Guarded forcing (``core/resilience.py``): a fused program that fails to
trace/compile/execute does NOT abort the chain — ``force()`` degrades to
per-op eager dispatch (bitwise the eager engines' result), records a
``degraded`` telemetry event, and quarantines the DAG key so steady-state
loops skip the doomed compile from then on. The
``fusion.record``/``fusion.compile``/``fusion.execute`` injection sites
exist so tests can trigger exactly these failures deterministically.

Collective-aware fusion + asynchronous forcing
----------------------------------------------
The DAG also records **collective nodes**, so chains spanning communication
compile into the SAME cached program instead of fencing at each collective
(the GSPMD lesson: let XLA schedule the psums inside one partitioned
program):

* split-axis reductions already record through ``defer_reduce`` — the psum
  is GSPMD-inserted when the fused program compiles;
* ``defer_reshard`` records a redistribution (``resplit_`` / out-of-place
  ``resplit`` of a pending chain) as a ``with_sharding_constraint`` node
  (plus explicit un-pad/re-pad nodes for ragged splits);
* ``defer_apply`` records a ``MeshCommunication.apply``-style shard_map
  kernel (single-output) as a DAG node, so record→kernel→record chains stay
  one program.

Forcing is **asynchronous**: ``force()`` dispatches the fused program and
installs the resulting ``jax.Array`` futures without blocking or reading
device data — only genuine host boundaries (``float()``/``item()``,
``numpy()``, printing, I/O shard reads) synchronize. Independent DAG roots
alive at a forcing point (tracked in a weak registry of pending wrappers)
are batched into ONE multi-output jitted program when their results are
small (``HEAT_TPU_FUSION_BATCH_BYTES``), so e.g. ``mean``/``var``/``std``
of one operand cost one dispatch and at most one blocking sync.

Fault-site contract: ``collective.reshard``/``collective.apply`` fire at
*record* time (every deferral, before any metadata mutates); the in-kernel
``collective.<verb>`` sites fire whenever the kernel is actually traced
(first record of a signature, and the fused program's compile). Telemetry's
``collective_counts()`` therefore only sees collectives at trace time once
they ride fused programs — ``record_fused_collective`` counts the recorded
collective nodes and :func:`program_hlo` + ``telemetry.hlo_collective_counts``
cross-check the compiled program.

``HEAT_TPU_FUSION_COLLECTIVES=0`` is the escape hatch restoring
force-at-collective behavior (no collective nodes, no multi-root batching);
``HEAT_TPU_FUSION=0`` still disables recording entirely.

Memory observability (``core/memledger.py``)
--------------------------------------------
The dispatch seam here is also the memory seam: every force's results are
tagged into the live-buffer ledger (``fusion`` owner until a wrapper claims
them), ``_estimate_cost`` banks XLA's ``memory_analysis`` static peaks per
program, ``HEAT_TPU_MEMORY_BUDGET`` is checked before each dispatch
(``warn``/``raise``/``drain`` policies — drain blocking-syncs the other
outstanding async roots first), and a dispatch that dies of memory
exhaustion (injectable at the ``memory.exhausted`` site) produces a ranked
OOM forensic before degrading through the guarded path.
"""

from __future__ import annotations

import functools
import hashlib
import itertools
import os
import threading
import time
import weakref
from collections import OrderedDict
from contextlib import contextmanager
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import health_runtime, memledger, resilience, telemetry

__all__ = [
    "LazyArray",
    "ProgramCostWarning",
    "active",
    "collectives_active",
    "collectives_disabled",
    "cost_error_count",
    "disabled",
    "defer_apply",
    "defer_matmul",
    "defer_multi",
    "defer_op",
    "defer_reshard",
    "force",
    "phys_node",
    "record_multi",
    "is_deferred",
    "cache_stats",
    "clear_cache",
    "clear_quarantine",
    "program_audit_info",
    "program_costs",
    "program_hlo",
    "programs",
    "register_root",
    "wrap_node",
]


class ProgramCostWarning(UserWarning):
    """A cached program's cost estimate failed in the backend (the estimate
    carries ``cost["error"]``); failures are counted into
    ``report()["programs"]["cost_errors"]`` and warned once per session —
    never buried silently."""

_OFF_VALUES = ("0", "false", "off", "no")

# recording beyond this chain depth force-materializes the sub-chain first:
# unbounded deferral would otherwise grow the DAG (and the compiled program)
# without limit in loops that never hit a forcing point
_MAX_CHAIN = int(os.environ.get("HEAT_TPU_FUSION_MAX_CHAIN", "128"))
_CACHE_SIZE = int(os.environ.get("HEAT_TPU_FUSION_CACHE", "512"))
# DAG keys whose fused program failed once stay quarantined (replayed per-op
# eagerly, never re-jitted) up to this many keys — steady-state loops must
# not pay a doomed compile attempt every step
_QUARANTINE_SIZE = int(os.environ.get("HEAT_TPU_FUSION_QUARANTINE", "256"))


# the escape hatch is read ONCE at import (a per-op os.environ lookup is
# measurable on the record hot path); in-process toggling goes through
# set_enabled()/disabled(), cross-process through the env var
_ENABLED = os.environ.get("HEAT_TPU_FUSION", "1").lower() not in _OFF_VALUES

# collective-aware fusion escape hatch: with it off, collectives force the
# chain exactly as before this layer existed (resplit_/apply dispatch
# eagerly) and forcing points never batch independent roots
_COLLECTIVES = (
    os.environ.get("HEAT_TPU_FUSION_COLLECTIVES", "1").lower() not in _OFF_VALUES
)

# async multi-root forcing: at a forcing point, other live pending roots are
# dispatched in the SAME multi-output program when their results are small
# (batching a big intermediate would materialize an extra HBM write the
# chain's consumer never asked for); count-capped for program-size sanity
_BATCH_MAX = int(os.environ.get("HEAT_TPU_FUSION_BATCH", "16"))
_BATCH_BYTES = int(os.environ.get("HEAT_TPU_FUSION_BATCH_BYTES", "16384"))


def active() -> bool:
    """Whether the recorder is on (``HEAT_TPU_FUSION`` escape hatch, read at
    import; see :func:`set_enabled`/:func:`disabled` for in-process control)."""
    return _ENABLED


def set_enabled(flag: bool) -> bool:
    """Flip the recorder in-process; returns the previous state."""
    global _ENABLED
    prev, _ENABLED = _ENABLED, bool(flag)
    return prev


@contextmanager
def disabled():
    """Context manager running with fusion recording off (used by the parity
    tests and the fused-vs-unfused benchmark legs)."""
    prev = set_enabled(False)
    try:
        yield
    finally:
        set_enabled(prev)


def collectives_active() -> bool:
    """Whether collective nodes record into the DAG and forcing batches
    independent roots (``HEAT_TPU_FUSION_COLLECTIVES`` escape hatch)."""
    return _ENABLED and _COLLECTIVES


def set_collectives_enabled(flag: bool) -> bool:
    """Flip collective-aware fusion in-process; returns the previous state."""
    global _COLLECTIVES
    prev, _COLLECTIVES = _COLLECTIVES, bool(flag)
    return prev


@contextmanager
def collectives_disabled():
    """Context manager restoring force-at-collective behavior (the parity
    tests and the ``HEAT_TPU_FUSION_COLLECTIVES=0`` matrix leg)."""
    prev = set_collectives_enabled(False)
    try:
        yield
    finally:
        set_collectives_enabled(prev)


#: correlation-id source: every fresh chain takes the next id at record time;
#: nodes recorded onto a pending chain inherit it, so one fused DAG's whole
#: lifecycle (record -> dispatch -> blocking sync) shares a cid the trace
#: timeline can join on (doc/internals_distribution.md: the cid contract)
_CID_SEQ = itertools.count(1)


class LazyArray:
    """One recorded expression-DAG node.

    ``children`` entries are other ``LazyArray`` nodes, concrete arrays
    (``jax.Array`` / ``np.ndarray``) or Python scalars; ``kw`` is the sorted
    tuple of static keyword arguments baked into the program. ``shape`` /
    ``dtype`` describe the *physical* result (inferred abstractly at record
    time, never by executing the op). ``cid`` is the chain's correlation id
    (inherited from the first still-pending child, else fresh). ``program``
    is stamped at force time with the key of the fused program that
    produced the value (None while pending, or when the chain degraded to
    eager replay) — the provenance ``ht.errstate`` and the numerics lens
    report for a value gone bad.
    """

    __slots__ = ("fn", "children", "kw", "shape", "dtype", "depth", "cid",
                 "program", "session", "_value")

    def __init__(self, fn, children, kw, shape, dtype, depth, cid=0):
        self.fn = fn
        self.children = children
        self.kw = kw
        self.shape = shape
        self.dtype = dtype
        self.depth = depth
        self.cid = cid
        self.program = None
        # the serving session (name) this node was recorded under, or None —
        # cross-session batching preserves it per root so tracelens/SLO
        # histograms bill the right tenant even inside a shared dispatch
        self.session = None
        self._value = None

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def astype(self, dtype) -> "LazyArray":
        """Deferred cast — keeps ``DNDarray.astype`` chains recorded."""
        return cast(self, dtype)

    def __repr__(self) -> str:  # debugging aid only
        state = "forced" if self._value is not None else f"depth={self.depth}"
        return f"LazyArray({getattr(self.fn, '__name__', self.fn)}, shape={self.shape}, dtype={self.dtype}, {state})"


# ----------------------------------------------------------------------
# recording
# ----------------------------------------------------------------------
def _astype_op(x, *, dtype):
    return jnp.asarray(x).astype(dtype)


def _unpad_op(x, *, axis, size):
    # the mask step of pad+mask: slice the suffix padding off the split dim
    # INSIDE the fused program, so cross-split reductions never see padding
    return jax.lax.slice_in_dim(x, 0, size, axis=axis)


def _pad_split_op(x, *, axis, pad):
    # the pad step of pad+mask, as a DAG node: zero-pad the new split dim to
    # p*ceil(n/p) inside the fused program (deferred reshard of ragged dims)
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _reshard_op(x, *, sharding):
    # a recorded redistribution: under a trace (the fused program, or the
    # record-time eval_shape) it is a sharding constraint GSPMD satisfies
    # with its own collective schedule; in the eager replay (guarded
    # forcing's degraded arm) it is the same device_put resplit_ used to do
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sharding)
    return jax.device_put(x, sharding)


def _matmul_op(a, b, *, a_sharding, b_sharding, out_sharding):
    # the matmul case table as a DAG node: under a trace the operand/result
    # sharding constraints pin the same schedule _matmul_program's in/out
    # shardings pin (split-0 @ *: local contraction; * @ split-1: local;
    # both-split-1/split-0-rhs: GSPMD's psum/allgather), compiled INTO the
    # enclosing chain's program; the eager replay (guarded forcing's
    # degraded arm) is device_put + matmul — the same placement the pinned
    # program produces
    if isinstance(a, jax.core.Tracer):
        a = jax.lax.with_sharding_constraint(a, a_sharding)
    elif isinstance(a, jax.Array):
        a = jax.device_put(a, a_sharding)
    if isinstance(b, jax.core.Tracer):
        b = jax.lax.with_sharding_constraint(b, b_sharding)
    elif isinstance(b, jax.Array):
        b = jax.device_put(b, b_sharding)
    out = jnp.matmul(a, b)
    if isinstance(out, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(out, out_sharding)
    return jax.device_put(out, out_sharding)


def _pick_op(t, *, i):
    # selector over a multi-output kernel node's result tuple: each output of
    # a record_multi parent is one _pick_op node, so the DAG stays
    # single-value per node while the kernel itself runs once per program
    return t[i]


def _aval(c) -> Tuple[Tuple[int, ...], np.dtype]:
    if isinstance(c, LazyArray):
        return c.shape, c.dtype
    if isinstance(c, (jax.Array, np.ndarray)):
        return tuple(c.shape), np.dtype(c.dtype)
    # python scalar: only ever feeds an _astype_op node, whose output aval
    # does not depend on the input dtype
    return (), np.result_type(type(c))


@functools.lru_cache(maxsize=8192)
def _infer_cached(fn, child_avals, kw):
    """Abstract (shape, dtype) of ``fn(*children, **kw)`` via one cached
    ``jax.eval_shape`` — the op is never executed at record time."""
    args = [jax.ShapeDtypeStruct(s, d) for s, d in child_avals]
    # kwargs are baked into the closure: eval_shape abstracts every argument
    # it is passed, and ops like jnp.sum need keepdims/axis static
    kw_d = dict(kw)
    out = jax.eval_shape(lambda *a: fn(*a, **kw_d), *args)
    return tuple(out.shape), np.dtype(out.dtype)


def record(fn, children, **kw) -> LazyArray:
    """Record ``fn(*children, **kw)`` as a DAG node without dispatching it.

    ``kw`` values must be hashable (callers pre-check); shape/dtype are
    inferred abstractly. Raises on inference failure — callers route the
    exception through ``resilience.record_recoverable`` and fall back to the
    eager engine, which reproduces the error eagerly.
    """
    if resilience._ARMED:
        resilience.check("fusion.record")
    kw_t = tuple(sorted(kw.items()))
    depth = 1 + max(
        (c.depth for c in children if isinstance(c, LazyArray) and c._value is None),
        default=0,
    )
    if depth > _MAX_CHAIN:
        children = tuple(
            force(c) if isinstance(c, LazyArray) and c._value is None else c
            for c in children
        )
        depth = 1
    cid = 0
    for c in children:
        if isinstance(c, LazyArray) and c._value is None:
            cid = c.cid  # join the pending chain's lifecycle
            break
    if not cid:
        cid = next(_CID_SEQ)
    if fn is _astype_op:
        shape = _aval(children[0])[0]
        dtype = np.dtype(kw["dtype"])
    elif fn is _unpad_op:
        shape = list(_aval(children[0])[0])
        shape[kw["axis"]] = kw["size"]
        shape = tuple(shape)
        dtype = _aval(children[0])[1]
    else:
        shape, dtype = _infer_cached(fn, tuple(_aval(c) for c in children), kw_t)
    if telemetry._MODE >= 2:
        telemetry.record_event(
            "record", op=getattr(fn, "__name__", str(fn)), cid=cid, depth=depth
        )
    node = LazyArray(fn, tuple(children), kw_t, shape, dtype, depth, cid)
    if _SESSION_OF is not None:
        node.session = _SESSION_OF()
    return node


@functools.lru_cache(maxsize=8192)
def _infer_multi_cached(fn, child_avals, kw):
    """Abstract per-output (shape, dtype) of a tuple-returning ``fn`` via one
    cached ``jax.eval_shape`` — the multi-output analog of ``_infer_cached``."""
    args = [jax.ShapeDtypeStruct(s, d) for s, d in child_avals]
    kw_d = dict(kw)
    outs = jax.eval_shape(lambda *a: fn(*a, **kw_d), *args)
    return tuple((tuple(o.shape), np.dtype(o.dtype)) for o in outs)


def record_multi(fn, children, **kw) -> Tuple[LazyArray, ...]:
    """Record a tuple-returning kernel as ONE parent node plus one
    ``_pick_op`` selector node per output, and return the selector tuple.

    The parent stays interior to the DAG — forcing any selector runs the
    kernel once inside the fused program, and ``_gather_batch``'s sibling
    rule pulls the other selectors into the same dispatch so every output
    lands together (TSQR's Q/R, CholQR2's Q/R/ok, the halo pair)."""
    if resilience._ARMED:
        resilience.check("fusion.record")
    kw_t = tuple(sorted(kw.items()))
    depth = 1 + max(
        (c.depth for c in children if isinstance(c, LazyArray) and c._value is None),
        default=0,
    )
    if depth > _MAX_CHAIN:
        children = tuple(
            force(c) if isinstance(c, LazyArray) and c._value is None else c
            for c in children
        )
        depth = 1
    cid = 0
    for c in children:
        if isinstance(c, LazyArray) and c._value is None:
            cid = c.cid  # join the pending chain's lifecycle
            break
    if not cid:
        cid = next(_CID_SEQ)
    avals = _infer_multi_cached(fn, tuple(_aval(c) for c in children), kw_t)
    if telemetry._MODE >= 2:
        telemetry.record_event(
            "record", op=getattr(fn, "__name__", str(fn)), cid=cid, depth=depth
        )
    # the parent's own aval is never consumed (only _pick_op children refer
    # to it, with their own hand-assigned avals) — stamp the first output's
    session = _SESSION_OF() if _SESSION_OF is not None else None
    parent = LazyArray(fn, tuple(children), kw_t, avals[0][0], avals[0][1], depth, cid)
    parent.session = session
    picks = []
    for i, (shape, dtype) in enumerate(avals):
        pick = LazyArray(_pick_op, (parent,), (("i", i),), shape, dtype, depth + 1, cid)
        pick.session = session
        picks.append(pick)
    return tuple(picks)


def cast(c, jax_dtype) -> LazyArray:
    """A deferred dtype cast node (no-op passthrough when already right)."""
    dt = np.dtype(jax_dtype)
    if isinstance(c, (LazyArray, jax.Array, np.ndarray)) and np.dtype(_aval(c)[1]) == dt:
        return c
    return record(_astype_op, (c,), dtype=dt.name)


# ----------------------------------------------------------------------
# the sharded-program cache + materialization
# ----------------------------------------------------------------------
_PROGRAMS: "OrderedDict[tuple, callable]" = OrderedDict()
# quarantined DAG keys: signatures whose fused program failed to build or
# execute; forced via per-op eager replay from then on (guarded forcing)
_QUARANTINE: "OrderedDict[tuple, None]" = OrderedDict()
# per-program accounting: sig -> {key (stable digest), family, compiles,
# dispatches, roots}. Kept alongside _PROGRAMS (same LRU bound) so the
# telemetry trace can name the program a dispatch launched and the cost
# estimator can re-lower the signature on demand without holding operands.
_PROGRAM_INFO: "OrderedDict[tuple, dict]" = OrderedDict()
# memoized cost estimates keyed by program key (program_costs())
_COSTS: dict = {}
# program keys whose cost estimate failed in the backend: the failure used
# to be buried silently in cost["error"] — now it is counted into
# report()["programs"]["cost_errors"] and warned once per session
_COST_ERROR_KEYS: set = set()
_COST_ERROR_WARNED = False
# memoized everything-replicated cost estimates keyed by program key — the
# audit baseline: "what would this program cost per host if nothing were
# sharded" (heat_tpu/analysis/audit.py divides by the mesh size to get the
# sharded lower bound a replication blowup is measured against)
_REPL_COSTS: dict = {}
_STATS = {
    "compiles": 0,
    "hits": 0,
    "disk_hits": 0,
    "forces": 0,
    "evictions": 0,
    "degraded": 0,
    "quarantine_hits": 0,
}

# serving seams (core/serving.py installs these as module attributes — the
# telemetry ``_MEM_HOOK`` set-attribute pattern; each costs one ``is None``
# check per force when the serving layer is not in use):
_DISK_INDEX = None  # persistent program-key index: disk warm-start accounting
# token-bucket admission gate, composed BEFORE memledger's. Fires in force()
# BEFORE _FORCE_LOCK is taken: the `wait` policy sleeps until refill, and a
# rate-limited tenant sleeping under the force lock would convoy every other
# session's dispatches behind it. Called as _ADMIT_HOOK(cid) -> refund|None;
# the refund is invoked when the admitted dispatch never runs (a neighbour's
# batch materialized the node during the wait).
_ADMIT_HOOK = None
_SERVING_NOTE = None  # per-session incident/billing notes
_SESSION_OF = None  # resolves the calling thread's active Session id
# dispatch-ordering seam: maps a root's recording session name to a sort
# key (serving installs (tier_rank, deadline_ms)). When set, _gather_batch
# considers candidates in (priority, registration) order instead of pure
# registration order, so interactive/deadline-near roots win batch slots
# over batch-tier chains. Must be deterministic — candidate order feeds the
# program signature, and nondeterminism would churn the program cache.
# The hook may also return _BATCH_EXCLUDED to keep a root OUT of other
# sessions' gathered batches entirely (serving: shed-tier roots must not
# free-ride an interactive neighbour's dispatch while shedding is active).
_ROOT_PRIORITY = None
_BATCH_EXCLUDED = object()

# micro batch window (seconds): when serving arms this (>= 2 concurrent
# sessions), a top-level force sleeps this long BEFORE taking _FORCE_LOCK.
# The sleep releases the GIL so other client threads can register their own
# pending roots; the first thread to wake gathers every root registered in
# the window into ONE multi-output dispatch, and the absorbed threads find
# their value already installed without ever contending on the lock.
_BATCH_WINDOW_S = 0.0
_FORCE_TLS = threading.local()  # .held: this thread is inside force already

# force() serializes its walk/cache/dispatch critical section under ONE
# reentrant lock so concurrent serving clients never interleave signature
# accumulators or the program-cache LRU. RLock, not Lock: the memledger
# drain policy recursively forces OTHER roots from inside the gate.
_FORCE_LOCK = threading.RLock()


def _program_key(sig) -> str:
    """Stable short digest of a program signature: op names + topology +
    static kwargs + leaf shape/dtype/sharding — the *program key* correlating
    the trace timeline's ``dispatch`` events, ``cache_stats()["program_keys"]``
    and :func:`program_costs`. Function identities hash by name (not id), so
    the key is reproducible within and across processes up to sharding repr."""
    parts = []
    for e in sig:
        tag = e[0]
        if tag == "L":
            parts.append(f"L:{e[1]}:{e[2]}:{e[3]}")
        elif tag == "Ls":
            parts.append(f"Ls:{getattr(e[1], '__name__', e[1])}")
        elif tag == "R":
            parts.append(f"R:{e[1]}")
        else:
            fn, idxs, kw = e
            parts.append(f"O:{getattr(fn, '__name__', fn)}:{idxs}:{kw}")
    return hashlib.sha1("|".join(parts).encode()).hexdigest()[:16]


def _program_info(sig) -> dict:
    info = _PROGRAM_INFO.get(sig)
    if info is None:
        info = _PROGRAM_INFO[sig] = {
            "key": _program_key(sig),
            "family": "/".join(_family(sig)) or "<leaf>",
            "compiles": 0,
            "dispatches": 0,
            "roots": 0,
        }
        while len(_PROGRAM_INFO) > _CACHE_SIZE:
            _PROGRAM_INFO.popitem(last=False)
    else:
        # LRU like _PROGRAMS itself: a hot program's accounting must never
        # be the insertion-order eviction victim while its program stays
        # cached (the counters would silently restart from zero)
        _PROGRAM_INFO.move_to_end(sig)
    return info


def _leaf_sig(v):
    if isinstance(v, jax.Array):
        # the sharding carries both the split axes and the mesh, so a layout
        # or mesh-size change keys a fresh program (shardings and np dtypes
        # are hashable; no string derivation on the hot path)
        return ("L", v.shape, v.dtype, getattr(v, "sharding", None))
    if isinstance(v, np.ndarray):
        return ("L", v.shape, v.dtype, None)
    return ("Ls", type(v))


def _walk(root, entries, leaves, memo) -> None:
    """Postorder walk of one DAG root into the shared (entries, leaves,
    memo) accumulators — a subexpression shared with an earlier walk appears
    once and is referenced by index."""
    stack = [(root, False)]
    while stack:
        obj, expanded = stack.pop()
        oid = id(obj)
        if oid in memo:
            continue
        if not (isinstance(obj, LazyArray) and obj._value is None):
            val = obj._value if isinstance(obj, LazyArray) else obj
            memo[oid] = len(entries)
            leaves.append(val)
            entries.append(_leaf_sig(val))
            continue
        if not expanded:
            stack.append((obj, True))
            for c in obj.children:
                stack.append((c, False))
        else:
            memo[oid] = len(entries)
            entries.append((obj.fn, tuple(memo[id(c)] for c in obj.children), obj.kw))


def _signature(roots):
    """Structural signature + the leaf operands of one or more DAG roots.
    The final ``("R", ...)`` entry records each root's position —
    multi-output forcing's return order."""
    entries = []
    leaves = []
    memo = {}
    for root in roots:
        _walk(root, entries, leaves, memo)
    entries.append(("R", tuple(memo[id(r)] for r in roots)))
    return tuple(entries), leaves


def _build(sig):
    """The executable for a structural signature: replays the DAG from the
    leaf operands and returns the tuple of root values (one per ``("R",...)``
    position). One instance per signature, jitted once — steady-state calls
    with fresh same-shaped inputs reuse the compiled program."""

    def run(*leaves):
        vals = []
        li = 0
        for e in sig:
            if e[0] == "L" or e[0] == "Ls":
                vals.append(leaves[li])
                li += 1
            elif e[0] == "R":
                return tuple(vals[i] for i in e[1])
            else:
                fn, idxs, kw = e
                vals.append(fn(*(vals[i] for i in idxs), **dict(kw)))
        raise AssertionError("signature missing its root entry")  # pragma: no cover

    return run


def _family(sig) -> tuple:
    """The op identities of a signature, ignoring leaf shapes — the retrace
    detector's key: the same family missing under churning shapes is the
    recompile pathology worth warning about."""
    return tuple(
        getattr(e[0], "__name__", str(e[0]))
        for e in sig
        if e[0] not in ("L", "Ls", "R")
    )


def _leaf_key(sig) -> tuple:
    """The leaf (shape/dtype/sharding) part of a signature."""
    return tuple(e for e in sig if e[0] in ("L", "Ls"))


# ----------------------------------------------------------------------
# live-root registry: async multi-root forcing
# ----------------------------------------------------------------------
# weakrefs to DNDarray wrappers whose payload is (was) a pending LazyArray,
# in registration order: a forcing point batches the still-pending ones into
# one multi-output program. Entries die with their wrappers automatically;
# already-forced survivors are pruned during gathering.
_ROOT_SEQ = itertools.count()
_LIVE_ROOTS: "weakref.WeakValueDictionary[int, object]" = weakref.WeakValueDictionary()
# guards registry MUTATION and key snapshots: record()/register_root runs on
# client threads WITHOUT _FORCE_LOCK (the batch window exists precisely so
# other threads can register roots while a force is in flight), so an
# unsynchronized sorted(_LIVE_ROOTS.keys()) in the gather/drain loops could
# raise "dictionary changed size during iteration" mid-force
_ROOTS_LOCK = threading.Lock()


def register_root(wrapper) -> None:
    """Track a DNDarray whose payload is a pending recorded chain as an
    async-forcing batch candidate (every deferral site calls this). No-op
    with collective-aware fusion off — forcing then never batches."""
    if _COLLECTIVES:
        with _ROOTS_LOCK:
            _LIVE_ROOTS[next(_ROOT_SEQ)] = wrapper


def _live_root_keys() -> list:
    """Stable snapshot of the registry's keys, safe against concurrent
    ``register_root`` inserts from other client threads."""
    with _ROOTS_LOCK:
        return sorted(_LIVE_ROOTS.keys())


def _node_nbytes(node: LazyArray) -> int:
    size = 1
    for s in node.shape:
        size *= int(s)
    return size * np.dtype(node.dtype).itemsize


#: pending-node ids of the signature currently held at the admission gate:
#: while the drain policy recursively forces OTHER roots, neither the drain
#: loop nor those forces' own _gather_batch may touch any node of the gated
#: chain — batching it would dispatch the chain a second time when admit()
#: returns and the original force runs its already-built program
_DRAIN_EXCLUDE: frozenset = frozenset()


def _gather_batch(entries, leaves, memo, roots):
    """Select other live pending roots to dispatch alongside the triggering
    root, in stable registration order (nondeterministic ordering would
    churn the program cache), walking each selection into the shared
    signature accumulators as it is taken. ``memo`` is the signature walk so
    far: candidates already INTERIOR to a selected DAG are skipped — they
    would add an output write nothing asked for, and whether a caller
    happens to hold an intermediate must not change the program's cache key
    (a later read finds the whole-chain force already materialized the
    ancestor, and forces the held node with its own small program). Only
    small results batch (a big disjoint root keeps its own dispatch), and
    only candidates living on the SAME device set as the triggering root's
    leaves — one jitted program cannot span two meshes, and a mixed batch
    would dispatch-fail and spuriously degrade a perfectly valid chain.
    Roots recorded under DIFFERENT serving sessions batch together freely
    (the registry is global and the rules above are session-blind): each
    root carries its ``session`` stamp, so the shared dispatch still bills
    per tenant through the serving note and the timeline's ``sessions``."""
    device_set = None
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None:
                device_set = sharding.device_set
                break
    if device_set is None:
        return  # no placed operand to anchor the mesh: skip batching
    keys = _live_root_keys()
    prio = _ROOT_PRIORITY
    if prio is not None:
        # deadline/tier-aware ordering (serving's seam): candidates sort by
        # (priority, registration key) — deterministic for a given session
        # mix, so repeated steady-state batches keep one program signature.
        # _BATCH_EXCLUDED candidates (shed tiers) drop out entirely: a shed
        # root riding a neighbour's batch would dispatch work the overload
        # controller just refused.
        ranked = []
        for key in keys:
            wrapper = _LIVE_ROOTS.get(key)
            payload = getattr(wrapper, "_payload", None)
            try:
                p = prio(getattr(payload, "session", None))
            except Exception:  # noqa: BLE001 - ordering must never break a force
                p = None
            if p is _BATCH_EXCLUDED:
                continue
            ranked.append(((1, float("inf")) if p is None else p, key))
        ranked.sort()
        keys = [key for _, key in ranked]
    stale = []
    for key in keys:
        if len(roots) >= _BATCH_MAX:
            break
        wrapper = _LIVE_ROOTS.get(key)
        if wrapper is None:
            continue
        payload = wrapper._payload
        if not (isinstance(payload, LazyArray) and payload._value is None):
            stale.append(key)  # forced since registration: stop tracking
            continue
        if id(payload) in memo:
            continue  # interior to (or already selected by) this batch
        if _DRAIN_EXCLUDE and id(payload) in _DRAIN_EXCLUDE:
            continue  # part of the chain held at the admission gate
        if _node_nbytes(payload) > _BATCH_BYTES:
            # sibling outputs of one multi-output kernel ride along
            # regardless of size: their shared parent is already interior to
            # this batch's walk, so the kernel runs once either way and the
            # extra output write is free — leaving the sibling behind would
            # re-run the whole kernel at its own later force
            if not (
                payload.fn is _pick_op
                and payload.children
                and id(payload.children[0]) in memo
            ):
                continue
        if getattr(wrapper.comm, "device_set", None) != device_set:
            continue  # different comm/mesh: never fuse across device sets
        _walk(payload, entries, leaves, memo)
        roots.append(payload)
    with _ROOTS_LOCK:
        for key in stale:
            _LIVE_ROOTS.pop(key, None)


def _static_peak(key: str, leaves, roots) -> Tuple[int, str]:
    """The failing/candidate program's static per-host memory peak for the
    admission gate and OOM forensics: XLA's memoized ``memory_analysis``
    peak when :func:`program_costs` has computed it (``"static"``), else the
    cheap operand+result estimate (``"estimate"``) — the gate must never
    compile at dispatch time."""
    cost = _COSTS.get(key)
    if cost:
        peak = (cost.get("memory") or {}).get("peak_bytes")
        if peak:
            return int(peak), "static"
    est = 0
    for leaf in leaves:
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            est += int(nbytes)
    for r in roots:
        est += _node_nbytes(r)
    return est, "estimate"


def _drain_pending_roots(exclude=()):
    """The ``drain`` admission policy's arm: force every OTHER live pending
    root and block until its value is on device — outstanding async futures
    stop being "outstanding", and their operand chains become collectable.
    ``exclude`` holds the ids of EVERY pending node of the gated signature
    (roots and interior nodes); it is also published as ``_DRAIN_EXCLUDE``
    so the recursive forces' own ``_gather_batch`` cannot pull the gated
    chain into another root's program — that would dispatch the chain twice
    once the gate admits the original force. Returns how many roots were
    drained; counted as ``drain`` blocking syncs so the async-forcing
    report shows what the gate cost."""
    global _DRAIN_EXCLUDE
    prev, _DRAIN_EXCLUDE = _DRAIN_EXCLUDE, _DRAIN_EXCLUDE | frozenset(exclude)
    drained = 0
    try:
        for key in _live_root_keys():
            wrapper = _LIVE_ROOTS.get(key)
            if wrapper is None:
                continue
            payload = wrapper._payload
            if not isinstance(payload, LazyArray) or id(payload) in _DRAIN_EXCLUDE:
                continue
            if payload._value is None:
                force(payload)
            value = payload._value
            if isinstance(value, jax.Array):
                token = None
                if telemetry._MODE:
                    token = telemetry.record_blocking_sync("drain", cid=payload.cid)
                with health_runtime.watch("sync:drain", cid=payload.cid):
                    value.block_until_ready()
                # close the event so the trace shows the drain's true host
                # wait as a duration, not a zero-width instant
                telemetry.end_blocking_sync(token)
                drained += 1
    finally:
        _DRAIN_EXCLUDE = prev
    return drained


def _quarantine(sig) -> None:
    _QUARANTINE[sig] = None
    while len(_QUARANTINE) > _QUARANTINE_SIZE:
        _QUARANTINE.popitem(last=False)


def _degrade(sig, leaves, exc, missed):
    """Guarded forcing's recovery arm: the fused program for ``sig`` failed
    to build (``missed``) or execute — drop it from the cache, quarantine the
    DAG key (later forces skip the doomed compile and replay eagerly), record
    a ``degraded`` telemetry event, warn once, and re-run the chain as per-op
    eager dispatch. The replay produces the exact eager result; if IT fails,
    the error surfaces with per-op locality — the reference's error model."""
    import warnings

    _PROGRAMS.pop(sig, None)
    _PROGRAM_INFO.pop(sig, None)  # a quarantined key is not a live program
    _quarantine(sig)
    _STATS["degraded"] += 1
    stage = "compile" if missed else "execute"
    family = _family(sig)
    if _SERVING_NOTE is not None:
        # contained per-session: only the tripping tenant's quarantine view
        # records the incident — a neighbor's programs stay undegraded
        _SERVING_NOTE("degraded", program=_program_key(sig), stage=stage)
    if telemetry._MODE:
        telemetry.record_degraded(family, stage, repr(exc))
    warnings.warn(
        resilience.DegradedDispatchWarning(
            f"fused program for op chain {'/'.join(family) or '<leaf>'} failed at "
            f"{stage} ({exc!r}); degraded to per-op eager dispatch and quarantined "
            "the DAG key (correct result, slower — fusion.clear_cache() lifts the "
            "quarantine)"
        ),
        stacklevel=4,
    )
    # black-box the failure: the ring holds the dispatches/collectives that
    # led here (throttled; no-op when the recorder is disarmed)
    health_runtime.auto_dump("degrade")
    return _build(sig)(*leaves)


def force(node):
    """Materialize a recorded DAG as one cached, jitted XLA program.

    ASYNCHRONOUS: the dispatch installs the resulting ``jax.Array`` futures
    and returns immediately — nothing here blocks on or reads device data,
    so only genuine host boundaries (``item()``, ``numpy()``, printing, I/O
    shard reads) synchronize. Under collective-aware fusion
    (:func:`collectives_active`), other live pending roots with small
    results (see :func:`_gather_batch`) ride the SAME multi-output program,
    so a later read of theirs finds the value already in flight.

    Under an active trace (an enclosing ``jax.jit``/``eval_shape``) the
    program executes into that trace, so the results may be tracers — they
    are then returned WITHOUT being cached on the nodes (caching a tracer
    would leak it past the trace's lifetime).

    GUARDED: a program that fails to trace/compile/execute (injectable at
    the ``fusion.compile``/``fusion.execute`` sites) degrades to per-op
    eager dispatch through :func:`_degrade` — one policy
    (``resilience.force_recoverable``) decides what degrades, the DAG key is
    quarantined, and the active ``ht.errstate`` policy is applied to the
    materialized value either way."""
    if not isinstance(node, LazyArray):
        return node
    if node._value is not None:
        return node._value
    # micro batch window (serving arms it): sleep with the GIL released so
    # concurrent clients can register their roots, then re-check — a
    # neighbour's batch may have materialized this node during the window.
    # Skipped on recursive forces (drain policy) which already hold the lock.
    if _BATCH_WINDOW_S > 0.0 and not getattr(_FORCE_TLS, "held", 0):
        time.sleep(_BATCH_WINDOW_S)
        if node._value is not None:
            return node._value
    # local capture: a concurrent last-session exit may uninstall the hook
    # between the None check and the call
    admit = _ADMIT_HOOK
    if admit is not None and not getattr(_FORCE_TLS, "held", 0):
        # serving admission gate (core/serving.py): per-session + global
        # token buckets, BEFORE the force lock so a tenant blocked on refill
        # (`wait` policy) never convoys other sessions' dispatches behind
        # _FORCE_LOCK, and before memledger's headroom gate — cheap rate
        # math before ledger walks. A refusal surfaces AdmissionError with
        # the chain intact: still pending, never degraded, dispatchable once
        # tokens refill — exactly the admission_hold contract. Recursive
        # forces (the drain policy, already holding the lock) are exempt:
        # they dispatch on behalf of an already-admitted force, and gating
        # them would sleep under the lock.
        refund = admit(node.cid)
        if node._value is not None:
            # a neighbour's batch landed this node while we waited for
            # tokens: no dispatch happens, so the token goes back
            if refund is not None:
                refund()
            return node._value
    # one force at a time: concurrent serving clients serialize here (the
    # lock is reentrant for the drain policy's recursive forces). Re-check
    # after acquiring — another thread's batch may have materialized us.
    with _FORCE_LOCK:
        _FORCE_TLS.held = getattr(_FORCE_TLS, "held", 0) + 1
        try:
            return _force_locked(node)
        finally:
            _FORCE_TLS.held -= 1


def _force_locked(node):
    if node._value is not None:
        return node._value
    roots = [node]
    entries = []
    leaves = []
    memo = {}
    _walk(node, entries, leaves, memo)
    if _COLLECTIVES and _ENABLED and _LIVE_ROOTS and jax.core.trace_state_clean():
        # never batch while executing into an enclosing jit/eval_shape
        # trace: the extra roots would come back as tracers (uncacheable —
        # see below), tracing their subgraphs and baking their operands
        # into the user's compiled program as outputs nothing reads
        _gather_batch(entries, leaves, memo, roots)
    entries.append(("R", tuple(memo[id(r)] for r in roots)))
    sig = tuple(entries)
    _STATS["forces"] += 1
    info = None  # per-program accounting; stays None for eager replays
    disk_warm = False  # this force's miss was served by the persistent index
    if _QUARANTINE and sig in _QUARANTINE:
        # known-bad DAG key: skip the failing compile, replay per-op
        _STATS["quarantine_hits"] += 1
        if _SERVING_NOTE is not None:
            _SERVING_NOTE(
                "quarantine_hit", program=_program_key(sig), cid=node.cid,
                sessions=[getattr(r, "session", None) for r in roots],
            )
        if telemetry._MODE:
            telemetry.record_force(
                telemetry.current_trigger(), node.depth, compiled=False, cid=node.cid
            )
        values = _build(sig)(*leaves)
    else:
        prog = _PROGRAMS.get(sig)
        missed = prog is None
        info = _program_info(sig)
        if missed:
            prog = jax.jit(_build(sig))
            _PROGRAMS[sig] = prog
            # a key already in the persistent index is a DISK hit, not a
            # recompile: jax's compilation cache (wired to the same
            # HEAT_TPU_PROGRAM_CACHE_DIR) serves the compiled binary, so a
            # warm-started process records zero compiles for seen signatures
            disk_warm = _DISK_INDEX is not None and _DISK_INDEX.has(info["key"])
            if disk_warm:
                _STATS["disk_hits"] += 1
            else:
                _STATS["compiles"] += 1
                info["compiles"] += 1
            if _DISK_INDEX is not None:
                _DISK_INDEX.note(info["key"], info["family"])
            while len(_PROGRAMS) > _CACHE_SIZE:
                _PROGRAMS.popitem(last=False)
                _STATS["evictions"] += 1
            if telemetry._MODE and not disk_warm:
                telemetry.record_retrace(_family(sig), _leaf_key(sig))
                # lands on the verbose timeline AND the flight ring (the
                # black box wants compiles next to the dispatches they cost)
                telemetry.record_event(
                    "compile",
                    program=info["key"], family=info["family"], cid=node.cid,
                )
        else:
            _PROGRAMS.move_to_end(sig)
            _STATS["hits"] += 1
        if telemetry._MODE:
            telemetry.record_force(
                telemetry.current_trigger(), node.depth, compiled=missed, cid=node.cid
            )
        if memledger._BUDGET_RAW is not None or memledger._HOLD is not None:
            # headroom admission gate (core/memledger.py): live ledger bytes
            # + this program's static peak against HEAT_TPU_MEMORY_BUDGET.
            # An elastic admission hold routes through the same seam even
            # with no budget armed — the supervisor's stop-the-world window.
            # Sits BEFORE the guarded try, so the `raise` policy surfaces to
            # the caller with the chain intact instead of degrading to an
            # eager replay that would dispatch the same bytes anyway.
            peak, peak_src = _static_peak(info["key"], leaves, roots)
            # every pending node of THIS signature (roots + interior): the
            # drain policy must not let another force's batch absorb any of
            # them — the program below is already built over this walk
            exclude = frozenset(memo)
            try:
                memledger.admit(
                    info["key"], info["family"], peak, peak_src,
                    drain_fn=lambda: _drain_pending_roots(exclude),
                )
            except memledger.MemoryBudgetExceeded:
                if _SERVING_NOTE is not None:
                    # the refusal is billed to the refused tenant only —
                    # containment: neighbors never see this session's gate
                    _SERVING_NOTE(
                        "mem_refused", program=info["key"], cid=node.cid,
                        sessions=[getattr(r, "session", None) for r in roots],
                    )
                raise
            if node._value is not None:  # pragma: no cover - belt and braces
                # some recursive path materialized this very chain while the
                # gate held it: the dispatch is done, do not run it again
                return node._value
        try:
            if resilience._ARMED:
                # jax.jit builds lazily, so the XLA compile happens inside the
                # first call — the injection sites model that split
                resilience.check("fusion.compile" if missed else "fusion.execute")
                # device OOM at dispatch time, as an injectable failure mode
                # (ISSUE 8): fires the same seam a real RESOURCE_EXHAUSTED
                # would, so the forensic + degrade path is testable
                resilience.check("memory.exhausted")
            if telemetry._MODE or health_runtime._WD_ACTIVE:
                # the fused-dispatch arming point: the watchdog knows the
                # in-flight program key + batched root cids at arm time, and
                # the health layer starts the dispatch→done clock (a fresh
                # build's call duration is the compile-time sample)
                cids = [r.cid for r in roots]
                t_disp = time.perf_counter()
                with health_runtime.watch(
                    "dispatch", program=info["key"], cid=node.cid, cids=cids
                ):
                    values = prog(*leaves)
                if telemetry._MODE:
                    health_runtime.note_dispatch(
                        info["key"], cids, missed, time.perf_counter() - t_disp
                    )
            else:
                values = prog(*leaves)
            info["dispatches"] += 1
            info["roots"] += len(roots)
        except Exception as exc:  # noqa: BLE001 - routed through ONE policy
            if memledger.is_oom(exc):
                # forensics BEFORE the degrade: rank the live buffers by
                # owner and name the failing program while the evidence is
                # still live — the eager replay below will churn it
                peak, _src = _static_peak(info["key"], leaves, roots)
                memledger.record_oom(
                    exc, program=info["key"], family=info["family"],
                    static_peak=peak,
                )
            if not resilience.force_recoverable(exc):
                raise
            values = _degrade(sig, leaves, exc, missed)
            info = None  # the eager replay is not a program dispatch
    # under an enclosing trace the jit bind joins that trace and the values
    # are tracers even though every leaf is concrete (verified on jax
    # 0.4.37); caching is gated on each value's actual concreteness, not
    # ambient state (the errstate non-finite policy is applied at the
    # DNDarray.parray seam, which knows the logical extent — the padding
    # suffix of a ragged split holds unspecified garbage, never checked)
    for root, value in zip(roots, values):
        if not isinstance(value, jax.core.Tracer):
            root._value = value
            # provenance stamp: which fused program produced this value
            # (None on the degraded eager path) — read back at the errstate
            # seam so a nonfinite finding can name its producer
            root.program = None if info is None else info["key"]
            # drop the recorded graph: later forces of ancestors treat this
            # node as a leaf, and the chain's operand buffers become
            # collectable
            root.children = ()
            # ledger attribution: a dispatched-but-unclaimed async future is
            # "fusion" until a wrapper claims it at the parray seam. Tagged
            # BEFORE the dispatch event below, whose ledger sample must see
            # the in-flight futures attributed, not "unattributed"
            memledger.tag(value, "fusion")
    if telemetry._NUMLENS_HOOK is not None and info is not None:
        # numerics lens (core/numlens.py, HEAT_TPU_NUMLENS): sampled tensor
        # statistics + shadow-replay drift audit over the landed root
        # values — one attribute check when disarmed, and the hook itself
        # never raises and skips tracer values
        telemetry._NUMLENS_HOOK(sig, leaves, roots, values, info)
    sessions = None
    if _SESSION_OF is not None or any(
        getattr(r, "session", None) is not None for r in roots
    ):
        sessions = [getattr(r, "session", None) for r in roots]
    if _SERVING_NOTE is not None and info is not None:
        # per-tenant billing for a (possibly cross-session) shared dispatch:
        # each session is charged for ITS roots, and the compile (if any)
        # for the triggering session only. A disk warm-start is NOT billed
        # as a compile — session reports must agree with the global retrace
        # counter, which disk hits leave untouched.
        _SERVING_NOTE(
            "dispatch", program=info["key"], sessions=sessions,
            compiled=missed and not disk_warm,
            trigger=getattr(node, "session", None),
        )
    if telemetry._MODE:
        telemetry.record_async_dispatch(
            len(roots),
            cid=node.cid,
            cids=[r.cid for r in roots],
            program=None if info is None else info["key"],
            sessions=sessions,
        )
    return values[0]


def is_deferred(x) -> bool:
    """Whether a DNDarray currently carries an unmaterialized recorded chain."""
    payload = getattr(x, "_payload", x)
    return isinstance(payload, LazyArray) and payload._value is None


def cache_stats() -> dict:
    """Program-cache counters: ``compiles`` (the retrace count the
    compile-count tests pin — a disk warm-start is NOT a compile), ``hits``
    (in-memory), ``disk_hits`` (first force of a signature whose key was in
    the persistent ``HEAT_TPU_PROGRAM_CACHE_DIR`` index — the compiled
    binary comes from jax's compilation cache), ``forces``, ``misses``
    (``compiles + disk_hits`` — every cache-structure miss, however the
    binary was then obtained), ``evictions`` (LRU drops past
    ``HEAT_TPU_FUSION_CACHE``), the current cache ``size``, the
    ``program_keys`` of every cached program (the digests the trace
    timeline's ``dispatch`` events correlate to), plus the guarded-forcing
    counters: ``degraded`` (programs that failed and were replayed per-op),
    ``quarantine_hits`` (forces that skipped a known-bad compile) and
    ``quarantined`` (currently quarantined keys)."""
    return dict(
        _STATS,
        misses=_STATS["compiles"] + _STATS["disk_hits"],
        size=len(_PROGRAMS),
        quarantined=len(_QUARANTINE),
        program_keys=[info["key"] for info in _PROGRAM_INFO.values()],
    )


def clear_cache() -> None:
    """Drop every compiled program (and its accounting/cost memo), lift
    every quarantine, forget the live async-forcing root registry, and zero
    ALL counters coherently."""
    _PROGRAMS.clear()
    _PROGRAM_INFO.clear()
    _COSTS.clear()
    _REPL_COSTS.clear()
    _COST_ERROR_KEYS.clear()  # the once-per-session warn flag survives
    _QUARANTINE.clear()
    with _ROOTS_LOCK:
        _LIVE_ROOTS.clear()
    _STATS.update(
        compiles=0, hits=0, disk_hits=0, forces=0, evictions=0, degraded=0,
        quarantine_hits=0,
    )


def clear_quarantine() -> None:
    """Lift the quarantine only (keep compiled programs and counters): the
    next force of a previously-failing DAG key retries the fused compile."""
    _QUARANTINE.clear()


# ----------------------------------------------------------------------
# deferral front-ends for the L3 engines
# ----------------------------------------------------------------------
_SCALARS = (int, float, bool, complex, np.number, np.bool_)

# sibling-module handles resolved on first use (function-level `from . import`
# costs ~1µs of import machinery per call on the record hot path; a module
# top-level import would be circular — dndarray imports fusion)
DNDarray = None
_types = None
_broadcast_shape = None


def _resolve_siblings():
    global DNDarray, _types, _broadcast_shape
    from . import types as types_mod
    from .dndarray import DNDarray as dnd_cls
    from .stride_tricks import broadcast_shape as bshape

    DNDarray, _types, _broadcast_shape = dnd_cls, types_mod, bshape


def hashable_kwargs(kw: dict) -> bool:
    """Whether ``kw`` can be baked into a program-cache key. Only the
    ``hash()`` itself is guarded — the sort runs outside the ``try`` so a
    genuinely broken kwargs dict (unorderable keys) raises instead of being
    silently classified as "unhashable, use the eager engine"."""
    items = tuple(sorted(kw.items()))
    try:
        hash(items)
        return True
    except TypeError:  # an unhashable VALUE (list/array kwarg): eager path
        return False


def _unfused(engine: str, reason: str):
    """The one-line telemetry breadcrumb every eager-fallback site leaves,
    so ``telemetry.report()`` shows *why* a chain wasn't fused. Returns
    None — callers ``return _unfused(...)`` to decline deferral."""
    if telemetry._MODE:
        telemetry.record_unfused(engine, reason)
    return None


def _phys_node(x):
    """The DNDarray's physical payload as a recordable child, or None when it
    is a tracer (inside an enclosing jit/vmap trace, where deferral would
    nest programs — the enclosing trace already fuses)."""
    arr = x._payload
    if isinstance(arr, LazyArray):
        return arr if arr._value is None else arr._value
    if isinstance(arr, jax.core.Tracer):
        return None
    return arr


def _logical_node(x):
    n = _phys_node(x)
    if n is not None and x.padded:
        n = record(_unpad_op, (n,), axis=x.split, size=x.shape[x.split])
    return n


def _wrap(node, gshape, split, ref):
    """Wrap a recorded node as a DNDarray. Direct slot assembly (the
    ``_tree_unflatten`` pattern): the constructor's pad/validation logic is
    metadata-only for a LazyArray payload, and this sits on the per-op path."""
    if split is not None and (len(node.shape) == 0 or split >= len(gshape)):
        split = None
    obj = DNDarray.__new__(DNDarray)
    obj._DNDarray__gshape = gshape
    obj._DNDarray__dtype = _types.canonical_heat_type(node.dtype)
    obj._DNDarray__split = split
    obj._DNDarray__device = ref.device
    obj._DNDarray__comm = ref.comm
    obj._DNDarray__balanced = True
    obj._DNDarray__array = node
    if _COLLECTIVES:
        register_root(obj)
    return obj


def wrap_node(node: LazyArray, gshape, split, ref):
    """Public form of :func:`_wrap` for collective-node call sites outside
    this module (deferred ``apply`` consumers wrap their own metadata)."""
    if DNDarray is None:
        _resolve_siblings()
    return _wrap(node, tuple(int(s) for s in gshape), split, ref)


def defer_binary(operation, t1, t2, jt, fn_kwargs):
    """Record a binary elementwise/broadcast op; None = use the eager engine.

    Mirrors the eager engine's layout rules: identical-layout operands (or
    array⊗scalar) chain on the *physical* payloads so ragged padding stays in
    the padding; unpadded broadcasts follow split dominance. Padded operands
    with mismatched shapes are left to the eager engine.
    """
    if DNDarray is None:
        _resolve_siblings()
    if getattr(operation, "_no_fusion", False):
        # impure engine ops (closures reading other DNDarrays, e.g. where's
        # cond-alignment op) must not be traced abstractly or cached
        return _unfused("binary", "no_fusion_op")
    d1, d2 = isinstance(t1, DNDarray), isinstance(t2, DNDarray)
    ref = t1 if d1 else t2
    if d1 and d2:
        if t1.comm is not t2.comm:
            return _unfused("binary", "mixed_comm")
        if t1.split == t2.split and t1.shape == t2.shape:
            a, b = _phys_node(t1), _phys_node(t2)
            if a is None or b is None:
                return _unfused("binary", "tracer_payload")
            out_shape, out_split = t1.shape, t1.split
            expected_phys = _aval(a)[0]
        elif not t1.padded and not t2.padded:
            a, b = _phys_node(t1), _phys_node(t2)
            if a is None or b is None:
                return _unfused("binary", "tracer_payload")
            # shape check stays eager-identical (error parity)
            out_shape = _broadcast_shape(t1.shape, t2.shape)
            expected_phys = out_shape

            def _bcast_split(split, shape):
                return None if split is None else split + (len(out_shape) - len(shape))

            out_split = _bcast_split(t1.split, t1.shape)
            if out_split is None:
                out_split = _bcast_split(t2.split, t2.shape)
        else:
            return _unfused("binary", "padded_broadcast")
    elif d1 and isinstance(t2, _SCALARS):
        a, b = _phys_node(t1), t2
        if a is None:
            return _unfused("binary", "tracer_payload")
        out_shape, out_split = t1.shape, t1.split
        expected_phys = _aval(a)[0]
    elif d2 and isinstance(t1, _SCALARS):
        a, b = t1, _phys_node(t2)
        if b is None:
            return _unfused("binary", "tracer_payload")
        out_shape, out_split = t2.shape, t2.split
        expected_phys = _aval(b)[0]
    else:
        return _unfused("binary", "foreign_operand")  # np.ndarray / list / ...
    try:
        node = record(operation, (cast(a, jt), cast(b, jt)), **fn_kwargs)
    except Exception as exc:  # narrowed: ONE policy decides what falls back
        if not resilience.record_recoverable(exc):
            raise
        return _unfused("binary", "record_failed:" + type(exc).__name__)
    if node.shape != tuple(expected_phys):
        return _unfused("binary", "shape_changed")  # not elementwise after all
    return _wrap(node, out_shape, out_split, ref)


def defer_local(operation, x, promote_jt, kwargs):
    """Record a unary elementwise op on the physical payload (padding garbage
    stays in padding); None = use the eager engine."""
    if DNDarray is None:
        _resolve_siblings()
    if getattr(operation, "_no_fusion", False):
        return _unfused("local", "no_fusion_op")
    if not hashable_kwargs(kwargs):
        return _unfused("local", "unhashable_kwargs")
    n = _phys_node(x)
    if n is None:
        return _unfused("local", "tracer_payload")
    phys_shape = _aval(n)[0]
    try:
        # the promote cast records a node too — keep it inside the guard
        if promote_jt is not None:
            n = cast(n, promote_jt)
        node = record(operation, (n,), **kwargs)
    except Exception as exc:  # narrowed: ONE policy decides what falls back
        if not resilience.record_recoverable(exc):
            raise
        return _unfused("local", "record_failed:" + type(exc).__name__)
    if node.shape != phys_shape:
        return _unfused("local", "shape_changed")  # the eager engine's rare branch
    return _wrap(node, x.shape, x.split, x)


def defer_reduce(partial_op, x, axis, keepdims, out_split, dtype, kwargs):
    """Record a reduction; the chain (including the reduction) then costs one
    program + one sync at the forcing point. None = use the eager engine."""
    if DNDarray is None:
        _resolve_siblings()
    if getattr(partial_op, "_no_fusion", False):
        return _unfused("reduce", "no_fusion_op")
    if not hashable_kwargs(kwargs):
        return _unfused("reduce", "unhashable_kwargs")
    axes = None if axis is None else ((axis,) if isinstance(axis, int) else tuple(axis))
    padded_fast = x.padded and axes is not None and x.split not in axes
    ax_kw = axis if (axis is None or isinstance(axis, int)) else tuple(axis)
    try:
        # _logical_node records the un-pad slice, so it must sit INSIDE the
        # guarded region: a record-time failure there (including an injected
        # fusion.record fault) falls back to eager like any other
        child = _phys_node(x) if (padded_fast or not x.padded) else _logical_node(x)
        if child is None:
            return _unfused("reduce", "tracer_payload")
        node = record(partial_op, (child,), axis=ax_kw, keepdims=keepdims, **kwargs)
        if dtype is not None:
            node = cast(node, _types.canonical_heat_type(dtype).jax_type())
    except Exception as exc:  # narrowed: ONE policy decides what falls back
        if not resilience.record_recoverable(exc):
            raise
        return _unfused("reduce", "record_failed:" + type(exc).__name__)
    if padded_fast:
        gshape = list(x.shape)
        for a in sorted(axes, reverse=True):
            if keepdims:
                gshape[a] = 1
            else:
                del gshape[a]
        gshape = tuple(gshape)
    else:
        gshape = node.shape
    return _wrap(node, gshape, out_split, x)


def defer_cum(operation, x, axis, dtype):
    """Record a cumulative op (padding is a suffix, so any-axis scans leave
    the data region untouched); None = use the eager engine."""
    if DNDarray is None:
        _resolve_siblings()
    if getattr(operation, "_no_fusion", False):
        return _unfused("cum", "no_fusion_op")
    n = _phys_node(x)
    if n is None:
        return _unfused("cum", "tracer_payload")
    phys_shape = _aval(n)[0]
    try:
        node = record(operation, (n,), axis=axis)
        if dtype is not None:
            node = cast(node, _types.canonical_heat_type(dtype).jax_type())
    except Exception as exc:  # narrowed: ONE policy decides what falls back
        if not resilience.record_recoverable(exc):
            raise
        return _unfused("cum", "record_failed:" + type(exc).__name__)
    if node.shape != phys_shape:
        return _unfused("cum", "shape_changed")
    return _wrap(node, x.shape, x.split, x)


# ----------------------------------------------------------------------
# collective nodes: deferred reshard + deferred shard_map kernels
# ----------------------------------------------------------------------
def defer_reshard(payload: LazyArray, gshape, split, padded, axis, comm):
    """Record a redistribution of a pending chain to split ``axis`` as DAG
    nodes (un-pad the old split if ragged, re-pad the new one if ragged,
    then a ``with_sharding_constraint`` the fused program's partitioner
    satisfies with its own collective schedule). Returns the new payload
    node, or None when recording fails recoverably (callers then force and
    reshard eagerly — today's behavior).

    The ``collective.reshard`` fault site is the CALLER's to check before
    any metadata mutates (``resplit_`` does); this function only records.
    """
    if DNDarray is None:
        _resolve_siblings()
    try:
        node = payload
        if padded:
            node = record(_unpad_op, (node,), axis=split, size=int(gshape[split]))
        if axis is not None:
            n = int(gshape[axis])
            p = comm.size
            block = -(-n // p) if n else 0
            pad = block * p - n
            if pad:
                node = record(_pad_split_op, (node,), axis=axis, pad=pad)
        target = comm.sharding(len(node.shape), axis)
        node = record(_reshard_op, (node,), sharding=target)
    except Exception as exc:  # narrowed: ONE policy decides what falls back
        if not resilience.record_recoverable(exc):
            raise
        return _unfused("reshard", "record_failed:" + type(exc).__name__)
    if telemetry._MODE:
        detail = "replicated" if axis is None else f"split={int(axis)}"
        telemetry.record_fused_collective("reshard", cid=node.cid, detail=detail)
    return node


@functools.lru_cache(maxsize=512)
def _apply_fn(mesh, axis_name, kernel, in_splits, ndims, out_split, check_vma):
    """Cached shard_map rendering of a ``MeshCommunication.apply`` call — one
    stable function object per (mesh, kernel, layout) so the program cache
    and the retrace ledger key deferred kernels exactly like any other op."""
    from jax.sharding import PartitionSpec

    def spec(ndim, split):
        if split is None:
            return PartitionSpec()
        entries = [None] * ndim
        entries[split] = axis_name
        return PartitionSpec(*entries)

    def ospec(split):
        if split is None:
            return PartitionSpec()
        return PartitionSpec(*([None] * split), axis_name)

    if isinstance(out_split, tuple):
        # multi-output kernel: one spec per output (record_multi's picks)
        out_spec = tuple(ospec(s) for s in out_split)
    else:
        out_spec = ospec(out_split)
    fn = jax.shard_map(
        kernel,
        mesh=mesh,
        in_specs=tuple(spec(nd, s) for nd, s in zip(ndims, in_splits)),
        out_specs=out_spec,
        check_vma=check_vma,
    )

    def run(*args):
        return fn(*args)

    run.__name__ = "apply:" + getattr(kernel, "__name__", "kernel")
    return run


def defer_apply(comm, kernel, xs, in_splits, out_split, check_vma: bool = False):
    """Record a ``shard_map`` kernel over ``comm``'s mesh as DAG node(s), so
    record→kernel→record chains compile into ONE program (the deferred form
    of ``MeshCommunication.apply``). ``xs`` entries are DNDarrays (pending
    chains stay pending), already-recorded LazyArray nodes, or concrete
    arrays. With a scalar ``out_split`` the kernel is single-output and ONE
    LazyArray node is returned; a tuple/list ``out_split`` declares a
    multi-output kernel (one split entry per output) and a TUPLE of selector
    nodes comes back — one per output, all landing in the same dispatch via
    :func:`record_multi`'s sibling batching. Callers wrap their own global
    metadata via :func:`wrap_node`; None means declined (padded or tracer
    operands, record failures → the eager ``comm.apply`` path).

    The ``collective.apply`` fault site fires here at record time, every
    call; the in-kernel ``collective.<verb>`` sites and their telemetry
    fire whenever the kernel is actually traced (first record of a
    signature, and the fused program's compile) — steady-state collective
    accounting for deferred kernels lives in the compiled program
    (:func:`program_hlo` + ``telemetry.hlo_collective_counts``)."""
    if DNDarray is None:
        _resolve_siblings()
    if not (_ENABLED and _COLLECTIVES):
        return None
    multi = isinstance(out_split, (tuple, list))
    if multi:
        out_split = tuple(out_split)
    if getattr(kernel, "_no_fusion", False):
        return _unfused("apply", "no_fusion_op")
    children = []
    ndims = []
    for x in xs:
        if isinstance(x, DNDarray):
            if x.padded:
                return _unfused("apply", "padded_operand")
            child = _phys_node(x)
            if child is None:
                return _unfused("apply", "tracer_payload")
        elif isinstance(x, LazyArray):
            # a pre-recorded operand node (a cast/reshape the caller staged)
            child = x if x._value is None else x._value
        elif isinstance(x, (jax.Array, np.ndarray)):
            child = x
        else:
            return _unfused("apply", "foreign_operand")
        children.append(child)
        ndims.append(len(_aval(child)[0]))
    if resilience._ARMED:
        # record time IS dispatch time for the fault contract: the site
        # fires per call, exactly like the eager apply, and propagates
        resilience.check("collective.apply")
    try:
        fn = _apply_fn(
            comm.mesh,
            comm.axis_name,
            kernel,
            tuple(in_splits),
            tuple(ndims),
            out_split,
            check_vma,
        )
        if multi:
            nodes = record_multi(fn, tuple(children))
        else:
            nodes = record(fn, tuple(children))
    except Exception as exc:  # narrowed: ONE policy decides what falls back
        if not resilience.record_recoverable(exc):
            raise
        return _unfused("apply", "record_failed:" + type(exc).__name__)
    if telemetry._MODE:
        cid = nodes[0].cid if multi else nodes.cid
        telemetry.record_fused_collective(
            "apply:" + getattr(kernel, "__name__", "kernel"), cid=cid
        )
    return nodes


def phys_node(x):
    """Public :func:`_phys_node`: a DNDarray's physical payload as a
    recordable child (pending node or concrete array), or None for tracer
    payloads — deferral call sites stage casts/reshapes on it with
    :func:`record`/:func:`cast` before handing it to :func:`defer_apply`."""
    if DNDarray is None:
        _resolve_siblings()
    return _phys_node(x)


def _operand_children(engine, xs):
    """Shared operand intake for the global-view deferral front-ends: the
    LOGICAL node of each DNDarray (padding sliced off inside the program —
    global-view kernels see exactly ``larray``), pre-recorded nodes and
    concrete arrays as-is. Returns None (after the ``_unfused`` breadcrumb)
    when any operand cannot be recorded."""
    children = []
    for x in xs:
        if isinstance(x, DNDarray):
            child = _logical_node(x)
            if child is None:
                return _unfused(engine, "tracer_payload")
        elif isinstance(x, LazyArray):
            child = x if x._value is None else x._value
        elif isinstance(x, (jax.Array, np.ndarray)):
            child = x
        else:
            return _unfused(engine, "foreign_operand")
        children.append(child)
    return children


def defer_op(fn, xs, **kw):
    """Record a single-output global-view op over DNDarray/array operands
    (their LOGICAL views — padding is sliced off inside the program, GSPMD
    schedules any collectives the op implies). Returns the LazyArray node,
    or None to decline — the eager path's exact global-view semantics make
    this the deferral seam for jit-level kernels like CG's fused step."""
    if DNDarray is None:
        _resolve_siblings()
    if not (_ENABLED and _COLLECTIVES):
        return None
    if getattr(fn, "_no_fusion", False):
        return _unfused("op", "no_fusion_op")
    if not hashable_kwargs(kw):
        return _unfused("op", "unhashable_kwargs")
    try:
        # _logical_node records the un-pad slice: inside the guard, like
        # defer_reduce — a record-time failure there falls back to eager
        children = _operand_children("op", xs)
        if children is None:
            return None
        node = record(fn, tuple(children), **kw)
    except Exception as exc:  # narrowed: ONE policy decides what falls back
        if not resilience.record_recoverable(exc):
            raise
        return _unfused("op", "record_failed:" + type(exc).__name__)
    if telemetry._MODE:
        telemetry.record_fused_collective(
            "op:" + getattr(fn, "__name__", "op"), cid=node.cid
        )
    return node


def defer_multi(fn, xs, **kw):
    """Record a multi-output global-view op (a tuple-returning kernel like
    CholQR2's (Q, R, ok)) over DNDarray/array operands as selector nodes —
    :func:`defer_op`'s :func:`record_multi` form. Returns the tuple of
    LazyArray selectors, or None to decline."""
    if DNDarray is None:
        _resolve_siblings()
    if not (_ENABLED and _COLLECTIVES):
        return None
    if getattr(fn, "_no_fusion", False):
        return _unfused("multi", "no_fusion_op")
    if not hashable_kwargs(kw):
        return _unfused("multi", "unhashable_kwargs")
    try:
        # _logical_node records the un-pad slice: inside the guard, like
        # defer_reduce — a record-time failure there falls back to eager
        children = _operand_children("multi", xs)
        if children is None:
            return None
        nodes = record_multi(fn, tuple(children), **kw)
    except Exception as exc:  # narrowed: ONE policy decides what falls back
        if not resilience.record_recoverable(exc):
            raise
        return _unfused("multi", "record_failed:" + type(exc).__name__)
    if telemetry._MODE:
        telemetry.record_fused_collective(
            "multi:" + getattr(fn, "__name__", "op"), cid=nodes[0].cid
        )
    return nodes


def defer_matmul(a, b):
    """Record a 2-D ``a @ b`` as a collective DAG node: the nine
    split-combination schedules of the matmul case table
    (``linalg.basics._matmul_program``) become sharding constraints on a
    ``jnp.matmul`` node, so the contraction's psum/allgather compiles INTO
    the enclosing chain's program instead of forcing it. Pending operands
    stay pending. Returns the wrapped DNDarray at the case table's output
    split, or None to decline (N-D, padded or tracer operands, mixed comms,
    record failures → the eager pinned-program path).

    The ``collective.matmul`` fault site is the CALLER's (``matmul`` checks
    it before either path dispatches, like ``resplit_`` does for
    ``collective.reshard``); this function only records."""
    if DNDarray is None:
        _resolve_siblings()
    if not (_ENABLED and _COLLECTIVES):
        return None
    if a.ndim != 2 or b.ndim != 2:
        return _unfused("matmul", "non_2d")
    if a.comm is not b.comm:
        return _unfused("matmul", "mixed_comm")
    if a.padded or b.padded:
        return _unfused("matmul", "padded_operand")
    an, bn = _phys_node(a), _phys_node(b)
    if an is None or bn is None:
        return _unfused("matmul", "tracer_payload")
    # the case table: split-0 lhs keeps rows local (out split 0); split-1
    # rhs keeps cols local (out split 1); everything else contracts into a
    # replicated result
    if a.split == 0:
        out_split = 0
    elif b.split == 1:
        out_split = 1
    else:
        out_split = None
    comm = a.comm
    try:
        node = record(
            _matmul_op,
            (an, bn),
            a_sharding=comm.sharding(2, a.split),
            b_sharding=comm.sharding(2, b.split),
            out_sharding=comm.sharding(2, out_split),
        )
    except Exception as exc:  # narrowed: ONE policy decides what falls back
        if not resilience.record_recoverable(exc):
            raise
        return _unfused("matmul", "record_failed:" + type(exc).__name__)
    if telemetry._MODE:
        telemetry.record_fused_collective(
            "matmul",
            cid=node.cid,
            detail=f"{a.split}x{b.split}->{out_split}",
        )
    return _wrap(node, (int(a.shape[0]), int(b.shape[1])), out_split, a)


def programs() -> dict:
    """Per-cached-program accounting keyed by program key: op ``family``,
    ``compiles``, ``dispatches`` and total ``roots`` dispatched, with any
    memoized :func:`program_costs` estimate merged in as ``cost``. The
    record side of telemetry's ``report()["programs"]`` top-N block."""
    out = {}
    for info in _PROGRAM_INFO.values():
        rec = {k: v for k, v in info.items() if k != "key"}
        cost = _COSTS.get(info["key"])
        if cost is not None:
            # the raw HLO instruction lines are audit-only detail: merged
            # into report()/the metrics sink they would bloat every flush
            # with multi-hundred-char strings per program
            rec["cost"] = {k: v for k, v in cost.items() if k != "collective_lines"}
        out[info["key"]] = rec
    return out


def _replicated_like(sharding):
    """The fully-replicated sharding over the SAME mesh as ``sharding`` (the
    audit's everything-replicated lowering keeps per-host semantics exact by
    replicating over the identical device set), or None when the sharding
    type cannot express one."""
    if sharding is None:
        return None
    try:
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(sharding.mesh, PartitionSpec())
    except Exception:  # noqa: BLE001 - non-Named shardings degrade to unsharded
        return None


def _leaf_placeholder(entry, replicated: bool = False):
    """An abstract stand-in for one signature leaf: sharded
    ``ShapeDtypeStruct`` for arrays, a zero of the recorded type for python
    scalars — enough to AOT-lower the program without any live operand.
    ``replicated=True`` swaps the recorded sharding for its fully-replicated
    form on the same mesh (the audit baseline)."""
    if entry[0] == "L":
        _, shape, dtype, sharding = entry
        if replicated:
            sharding = _replicated_like(sharding)
        try:
            return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
        except Exception:  # noqa: BLE001 - sharding kwarg availability varies
            return jax.ShapeDtypeStruct(shape, dtype)
    try:
        return entry[1](0)
    except Exception:  # noqa: BLE001 - exotic scalar types degrade to int
        return 0


def _estimate_cost(sig, replicated: bool = False) -> dict:
    """Best-effort cost estimate of one cached program, from its signature
    alone: logical operand/result bytes from the recorded avals, flops and
    bytes-accessed from XLA's post-compile cost analysis, and the in-program
    collective instruction counts parsed from the optimized HLO
    (``telemetry.hlo_collective_counts``). Re-lowers the signature from
    abstract specs — an extra compile, which is why callers memoize.
    ``replicated=True`` lowers with every array leaf fully replicated over
    its mesh instead — the denominator of the audit's replication check."""
    leaves = [e for e in sig if e[0] in ("L", "Ls")]
    specs = [_leaf_placeholder(e, replicated) for e in leaves]
    cost: dict = {
        "operand_bytes": 0,
        "result_bytes": None,
        "flops": None,
        "bytes_accessed": None,
        "collectives": {},
    }
    for e in leaves:
        if e[0] == "L":
            size = 1
            for s in e[1]:
                size *= int(s)
            cost["operand_bytes"] += size * np.dtype(e[2]).itemsize
    try:
        outs = jax.eval_shape(_build(sig), *specs)
        total = 0
        for o in jax.tree_util.tree_leaves(outs):
            size = 1
            for s in o.shape:
                size *= int(s)
            total += size * np.dtype(o.dtype).itemsize
        cost["result_bytes"] = total
    except Exception as exc:  # noqa: BLE001 - best-effort estimate
        cost["error"] = repr(exc)
        return cost
    try:
        compiled = jax.jit(_build(sig)).lower(*specs).compile()
        try:
            # XLA's post-compile memory accounting: the static per-host
            # peak (arguments + outputs + temps) the admission gate and
            # the resplit O(n/p) assertion surface read. Best-effort —
            # some backends return None or omit the analysis entirely.
            ma = compiled.memory_analysis()
            if ma is not None:
                arg_b = int(getattr(ma, "argument_size_in_bytes", 0))
                out_b = int(getattr(ma, "output_size_in_bytes", 0))
                tmp_b = int(getattr(ma, "temp_size_in_bytes", 0))
                cost["memory"] = {
                    "argument_bytes": arg_b,
                    "output_bytes": out_b,
                    "temp_bytes": tmp_b,
                    "alias_bytes": int(getattr(ma, "alias_size_in_bytes", 0)),
                    "generated_code_bytes": int(
                        getattr(ma, "generated_code_size_in_bytes", 0)
                    ),
                    "peak_bytes": arg_b + out_b + tmp_b,
                }
        except (AttributeError, RuntimeError, TypeError, ValueError):
            pass  # no memory analysis on this backend: flops/HLO still bank
        hlo_text = compiled.as_text()
        entries = telemetry.hlo_collectives(hlo_text)
        cost["collectives"] = {}
        for entry in entries:
            cost["collectives"][entry["op"]] = cost["collectives"].get(entry["op"], 0) + 1
        # the raw instruction lines carry the payload shapes — the audit's
        # bytes-on-wire estimate parses them (analysis/audit.py)
        cost["collective_lines"] = [entry["line"] for entry in entries]
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        if isinstance(analysis, dict):
            if "flops" in analysis:
                cost["flops"] = float(analysis["flops"])
            if "bytes accessed" in analysis:
                cost["bytes_accessed"] = float(analysis["bytes accessed"])
    except Exception as exc:  # noqa: BLE001 - cost analysis is backend-dependent
        cost["error"] = repr(exc)
    return cost


def _note_cost_error(key: str, cost: dict) -> None:
    """Count a failed cost estimate (``cost["error"]``) into the per-key
    error ledger and warn once per session — a backend that cannot analyze
    programs must be visible, not silently averaged away."""
    global _COST_ERROR_WARNED
    if "error" not in cost:
        _COST_ERROR_KEYS.discard(key)
        return
    _COST_ERROR_KEYS.add(key)
    if not _COST_ERROR_WARNED:
        _COST_ERROR_WARNED = True
        import warnings

        warnings.warn(
            ProgramCostWarning(
                f"cost estimate failed for cached program {key} "
                f"({cost['error']}); further failures are counted into "
                "report()['programs']['cost_errors'] without re-warning"
            ),
            stacklevel=4,
        )


def cost_error_count() -> int:
    """How many cached programs currently hold a failed cost estimate
    (``report()["programs"]["cost_errors"]``)."""
    return len(_COST_ERROR_KEYS)


def program_costs(top: Optional[int] = None, refresh: bool = False) -> dict:
    """Cost estimates for the cached sharded programs, keyed by program key
    and ranked by dispatch count (``top`` limits how many are analyzed).
    Estimates come from :func:`_estimate_cost` and are memoized per key
    (``refresh=True`` recomputes); each entry also carries the program's
    ``family`` and ``dispatches`` so flops×dispatches ranks total spend,
    and (where the backend exposes ``memory_analysis``) a ``memory`` block
    with the static argument/output/temp/peak bytes per host. Backend
    estimate failures are never silent: they count into
    :func:`cost_error_count` and warn once per session.
    Never touches live data or forces a pending chain."""
    ranked = sorted(
        _PROGRAM_INFO.items(), key=lambda kv: kv[1]["dispatches"], reverse=True
    )
    if top is not None:
        ranked = ranked[:top]
    out = {}
    for sig, info in ranked:
        key = info["key"]
        cost = None if refresh else _COSTS.get(key)
        if cost is None:
            cost = _COSTS[key] = _estimate_cost(sig)
            _note_cost_error(key, cost)
        public = {k: v for k, v in cost.items() if k != "collective_lines"}
        out[key] = dict(
            public, family=info["family"], dispatches=info["dispatches"]
        )
    return out


def program_audit_info(top: Optional[int] = None, refresh: bool = False) -> dict:
    """Audit-grade introspection of every cached sharded program, keyed by
    program key (the AOT seam ``heat_tpu/analysis/audit.py`` reasons over):
    the op ``family``, ``dispatches``, the recorded ``leaves`` (shape/dtype/
    replicated flag), the leaf ``mesh_size`` and ``split_leaves`` count, the
    memoized :func:`_estimate_cost` under the recorded shardings (``cost``)
    and under everything-replicated shardings (``replicated_cost``) — the
    pair whose ratio exposes a replication blowup without depending on the
    chain's depth. Lowers from abstract specs only: never touches live data
    or forces a pending chain."""
    ranked = sorted(
        _PROGRAM_INFO.items(), key=lambda kv: kv[1]["dispatches"], reverse=True
    )
    if top is not None:
        ranked = ranked[:top]
    out = {}
    for sig, info in ranked:
        key = info["key"]
        cost = None if refresh else _COSTS.get(key)
        if cost is None:
            cost = _COSTS[key] = _estimate_cost(sig)
            _note_cost_error(key, cost)
        rcost = None if refresh else _REPL_COSTS.get(key)
        if rcost is None:
            rcost = _REPL_COSTS[key] = _estimate_cost(sig, replicated=True)
        leaves = []
        mesh_size = 1
        split_leaves = 0
        for e in sig:
            if e[0] != "L":
                continue
            sharding = e[3]
            replicated = True
            if sharding is not None:
                try:
                    mesh_size = max(mesh_size, len(sharding.device_set))
                    replicated = bool(sharding.is_fully_replicated)
                except Exception:  # noqa: BLE001 - sharding APIs vary by type
                    pass
            if not replicated:
                split_leaves += 1
            size = 1
            for s in e[1]:
                size *= int(s)
            leaves.append(
                {
                    "shape": tuple(int(s) for s in e[1]),
                    "dtype": str(np.dtype(e[2])),
                    "nbytes": size * np.dtype(e[2]).itemsize,
                    "replicated": replicated,
                }
            )
        out[key] = {
            "family": info["family"],
            "dispatches": info["dispatches"],
            "mesh_size": mesh_size,
            "split_leaves": split_leaves,
            "leaves": leaves,
            "cost": dict(cost),
            "replicated_cost": dict(rcost),
        }
    return out


def program_hlo(x, optimized: bool = True) -> str:
    """The (post-partitioning) HLO text of the program that would force
    ``x``'s pending chain — the compiled-side cross-check for collective
    accounting (``telemetry.hlo_collective_counts`` parses it). ``x`` is a
    DNDarray with a pending payload or a pending LazyArray; lowering here
    neither forces nor caches anything. ``optimized=False`` returns the
    pre-optimization StableHLO instead."""
    node = getattr(x, "_payload", x)
    if not (isinstance(node, LazyArray) and node._value is None):
        raise ValueError("program_hlo needs a pending recorded chain")
    sig, leaves = _signature([node])
    lowered = jax.jit(_build(sig)).lower(*leaves)
    if not optimized:
        return lowered.as_text()
    return lowered.compile().as_text()

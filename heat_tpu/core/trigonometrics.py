"""Trigonometric and hyperbolic functions (reference: heat/core/trigonometrics.py).

Every function routes through the L3 engines with stable ``jnp`` callables,
so under the eager fusion recorder (``core/fusion.py``) these ops defer into
the surrounding chain and key stably into the sharded-program cache (the
``_f`` integer-promotion pre-cast records as a fusion cast node).
"""

from __future__ import annotations

import jax.numpy as jnp

from ._operations import __binary_op as _binary_op
from ._operations import __local_op as _local_op
from .dndarray import DNDarray

__all__ = [
    "arccos",
    "acos",
    "arccosh",
    "acosh",
    "arcsin",
    "asin",
    "arcsinh",
    "asinh",
    "arctan",
    "atan",
    "arctan2",
    "atan2",
    "arctanh",
    "atanh",
    "cos",
    "cosh",
    "deg2rad",
    "degrees",
    "rad2deg",
    "radians",
    "sin",
    "sinh",
    "tan",
    "tanh",
]


def arccos(x, out=None) -> DNDarray:
    """Inverse cosine (reference trigonometrics.py:18)."""
    return _local_op(jnp.arccos, x, out=out)


acos = arccos


def arccosh(x, out=None) -> DNDarray:
    """Inverse hyperbolic cosine (reference trigonometrics.py:46)."""
    return _local_op(jnp.arccosh, x, out=out)


acosh = arccosh


def arcsin(x, out=None) -> DNDarray:
    """Inverse sine (reference trigonometrics.py:74)."""
    return _local_op(jnp.arcsin, x, out=out)


asin = arcsin


def arcsinh(x, out=None) -> DNDarray:
    """Inverse hyperbolic sine (reference trigonometrics.py:102)."""
    return _local_op(jnp.arcsinh, x, out=out)


asinh = arcsinh


def arctan(x, out=None) -> DNDarray:
    """Inverse tangent (reference trigonometrics.py:130)."""
    return _local_op(jnp.arctan, x, out=out)


atan = arctan


def arctan2(x1, x2) -> DNDarray:
    """Quadrant-aware arctan(x1/x2) (reference trigonometrics.py:158)."""
    return _binary_op(jnp.arctan2, _f(x1), _f(x2))


atan2 = arctan2


def _f(x):
    from . import types

    if isinstance(x, DNDarray) and types.heat_type_is_exact(x.dtype):
        return x.astype(types.promote_types(x.dtype, types.float32))
    return x


def arctanh(x, out=None) -> DNDarray:
    """Inverse hyperbolic tangent (reference trigonometrics.py:197)."""
    return _local_op(jnp.arctanh, x, out=out)


atanh = arctanh


def cos(x, out=None) -> DNDarray:
    """Cosine (reference trigonometrics.py:225)."""
    return _local_op(jnp.cos, x, out=out)


def cosh(x, out=None) -> DNDarray:
    """Hyperbolic cosine (reference trigonometrics.py:253)."""
    return _local_op(jnp.cosh, x, out=out)


def deg2rad(x, out=None) -> DNDarray:
    """Degrees to radians (reference trigonometrics.py:281)."""
    return _local_op(jnp.deg2rad, x, out=out)


radians = deg2rad


def rad2deg(x, out=None) -> DNDarray:
    """Radians to degrees (reference trigonometrics.py:333)."""
    return _local_op(jnp.rad2deg, x, out=out)


degrees = rad2deg


def sin(x, out=None) -> DNDarray:
    """Sine (reference trigonometrics.py:385)."""
    return _local_op(jnp.sin, x, out=out)


def sinh(x, out=None) -> DNDarray:
    """Hyperbolic sine (reference trigonometrics.py:413)."""
    return _local_op(jnp.sinh, x, out=out)


def tan(x, out=None) -> DNDarray:
    """Tangent (reference trigonometrics.py:441)."""
    return _local_op(jnp.tan, x, out=out)


def tanh(x, out=None) -> DNDarray:
    """Hyperbolic tangent (reference trigonometrics.py:469)."""
    return _local_op(jnp.tanh, x, out=out)

"""Live elasticity: the preemption-tolerant supervisor (``ht.elastic``).

The resilience stack below this module guarantees "a crash leaves a valid
checkpoint" (the manifest commit point of utils/checkpoint.py, fusion's
quarantine/degrade, the memory gate); ROADMAP item 5 demands the production
end state — *traffic keeps flowing* when a host is preempted or a device
goes flaky. The reference framework inherits a fixed MPI world and dies on
rank loss; here the supervisor closes the detect→drain→checkpoint→re-form→
resume loop on a *running* job:

1. **Detect** — five triggers feed one poll (:meth:`Supervisor.maybe_preempt`):
   a SIGTERM/signal hook (``HEAT_TPU_ELASTIC_SIGNALS``, default ``SIGTERM``),
   the ``elastic.preempt`` fault site (so ``HEAT_TPU_FAULTS`` kills a host
   deterministically), :func:`probe_devices` health probes on collective
   failure, escalation from resilience's per-device fault ledger —
   N repeated ``collective.*``/dispatch faults attributable to one device
   degrade the *mesh*, not the job (``resilience.note_device_fault``) —
   and ``multihost``'s lease daemon declaring a peer *process* lost. A lost
   peer takes the cross-process path: drain → best-effort commit →
   :class:`multihost.PeerLostError`, handing the worker back to the local
   launcher (``multihost.spawn_local``) for a respawn into a smaller world
   under a new mesh epoch (in-process reform across processes is impossible:
   XLA's coordination service hard-kills the survivors of a dead peer).
2. **Drain + commit** — stop admitting new fused dispatches
   (``memledger.admission_hold``, the same gate seam the memory budget
   uses), drain live fusion roots under a watchdog-guarded deadline
   (``HEAT_TPU_ELASTIC_DRAIN_MS``), and commit a checkpoint through the
   manifest commit point — a preemption racing the save is safe by
   construction, torn saves already fall back.
3. **Re-form** — rebuild the world on the surviving devices
   (``communication.reform``), which invalidates every mesh-keyed cache
   (fusion program cache + ``_PROGRAM_INFO``, the shard_map program memo,
   memledger's resolved budget denominator); drop stale watchdog guards
   (``health_runtime.reset_guards``); restore from the newest checkpoint
   that *verifies* via the elastic restore path; resume the step function.
4. **Drive** — :func:`run` for a generic step function over (DNDarray)
   state, :func:`fit` for DASO/DataParallel trainers (mesh-shape-
   independent ``elastic_state_dict`` + ``rebind``). Every reform is
   forensically visible: ``report()["elastic"]`` counts preemptions
   survived, reform downtime and steps replayed; ``elastic_preempt`` /
   ``elastic_reformed`` / ``elastic_reform_failed`` land on the flight
   ring and trigger auto-dumps, so a reform that *fails* leaves a bundle.
"""

from __future__ import annotations

import os
import signal as _signal_mod
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from . import (
    communication, fusion, health_runtime, memledger, multihost, resilience,
    telemetry,
)

__all__ = [
    "ElasticError",
    "Preempted",
    "Supervisor",
    "fit",
    "newest_verified_step",
    "probe_devices",
    "request_preempt",
    "reset",
    "run",
    "stats",
]

_OFF_VALUES = ("", "0", "false", "off", "no")


class Preempted(RuntimeError):
    """A preemption notice: why the world must shrink and which devices (if
    any) are known-sick. Returned by :meth:`Supervisor.maybe_preempt` as a
    signal object rather than raised — the supervisor turns it into a
    reform, not a crash."""

    def __init__(self, reason: str, devices: Tuple = ()):
        super().__init__(reason)
        self.reason = reason
        self.devices = tuple(devices)


class ElasticError(RuntimeError):
    """The supervisor cannot keep the job alive: reforms exhausted, the
    surviving world would fall below ``min_devices``, or no checkpoint
    verifies. Propagates to the caller — this is the "job is lost" signal,
    everything recoverable is handled internally."""


# ----------------------------------------------------------------------
# the observability surface: report()["elastic"]
# ----------------------------------------------------------------------
_STATS: Dict[str, Any] = {
    "preemptions": 0,       # preemption notices the supervisor consumed
    "reforms": 0,           # successful mesh re-forms
    "failed_reforms": 0,    # ElasticError exits (forensics bundle dumped)
    "steps_replayed": 0,    # steps re-run after restores (≤ checkpoint_every each)
    "downtime_ms": 0.0,     # cumulative drain→restore wall time
    "drained_roots": 0,     # live fusion roots forced during drains
    "checkpoints": 0,       # commits through the supervisor
    "peer_losses": 0,       # cross-process losses handed to the launcher
    "last_reform": None,    # {"step","mesh","downtime_ms","reason"} of the newest
}


def stats() -> Dict[str, Any]:
    """Snapshot of the supervisor counters (joined into ``report()`` as the
    ``elastic`` block via telemetry's set-attribute hook)."""
    doc = dict(_STATS)
    if doc["last_reform"] is not None:
        doc["last_reform"] = dict(doc["last_reform"])
    return doc


def reset() -> None:
    """Zero the supervisor counters (part of the ``telemetry.reset()``
    cascade, so a bench scope never reports the previous run's reforms)."""
    _STATS.update(
        preemptions=0, reforms=0, failed_reforms=0, steps_replayed=0,
        downtime_ms=0.0, drained_roots=0, checkpoints=0, peer_losses=0,
        last_reform=None,
    )


# ----------------------------------------------------------------------
# detection: signals, the fault site, health probes, ledger escalation
# ----------------------------------------------------------------------
#: the out-of-band preemption notice (signal handlers land here; the
#: supervisor pops it at its next poll — handlers must not run the drain)
_PENDING: Optional[Preempted] = None


def request_preempt(reason: str, devices: Sequence = ()) -> None:
    """File a preemption notice for the next supervisor poll. Safe from
    signal handlers and foreign threads: nothing heavier than an attribute
    store happens here."""
    global _PENDING
    _PENDING = Preempted(reason, tuple(devices))


def _parse_signals() -> List[int]:
    """``HEAT_TPU_ELASTIC_SIGNALS``: comma-separated signal names the
    supervisor hooks (default ``SIGTERM`` — what every cloud scheduler sends
    ahead of a preemption). ``off``/empty disables; unknown names warn and
    are skipped."""
    raw = os.environ.get("HEAT_TPU_ELASTIC_SIGNALS", "SIGTERM").strip()
    if raw.lower() in _OFF_VALUES:
        return []
    out: List[int] = []
    for name in raw.split(","):
        name = name.strip().upper()
        if not name:
            continue
        if not name.startswith("SIG"):
            name = "SIG" + name
        num = getattr(_signal_mod, name, None)
        if num is None:
            warnings.warn(
                f"HEAT_TPU_ELASTIC_SIGNALS: unknown signal {name!r}; skipped",
                stacklevel=2,
            )
            continue
        out.append(int(num))
    return out


def _parse_drain_ms() -> float:
    """``HEAT_TPU_ELASTIC_DRAIN_MS``: the watchdog deadline on the drain
    (default 10s). Malformed values warn and keep the default — a broken
    knob must not unbound the drain."""
    raw = os.environ.get("HEAT_TPU_ELASTIC_DRAIN_MS", "").strip()
    if not raw:
        return 10_000.0
    try:
        value = float(raw)
    except ValueError:
        warnings.warn(
            f"HEAT_TPU_ELASTIC_DRAIN_MS={raw!r} is not a number; using 10000",
            stacklevel=2,
        )
        return 10_000.0
    return value if value > 0 else 10_000.0


def probe_devices(devices: Sequence) -> List:
    """Health-probe ``devices`` with a tiny transfer each; unresponsive ones
    are attributed a fault in resilience's per-device ledger (three strikes
    degrade them) and dropped from the returned healthy list. The elastic
    reform path probes its survivor set so a sick-but-unreported device
    does not make it into the new world."""
    healthy = []
    for d in devices:
        try:
            jax.device_put(np.ones((1,), dtype=np.float32), d).block_until_ready()
            healthy.append(d)
        # ANY probe failure means "sick"; the fault is not swallowed, it is
        # attributed to the device's ledger (three strikes degrade it)
        # heat-lint: disable=H003 — sick-device attribution is the contract
        except Exception:  # noqa: BLE001
            resilience.note_device_fault(d, site="elastic.probe")
    return healthy


def newest_verified_step(directory: str) -> Optional[int]:
    """The newest checkpoint step in ``directory`` that passes
    verification, or None. The restore side of the reform: a preemption may
    have raced the last save, so the supervisor restores the newest step
    whose manifest + payload hashes check out — never a torn hybrid."""
    from ..utils import checkpoint as ckpt

    for s in sorted(ckpt.all_steps(directory), reverse=True):
        if not ckpt.verify_checkpoint(directory, s):
            return int(s)
    return None


def _retarget(tree, comm) -> Any:
    """Template for the elastic restore: every DNDarray leaf re-targeted
    onto ``comm`` (fresh zeros with the same shape/split/dtype — the restore
    fills the values, the template only carries the destination layout);
    everything else passes through for checkpoint's shape validation."""
    from . import factories
    from .dndarray import DNDarray

    def remap(leaf):
        if isinstance(leaf, DNDarray):
            return factories.zeros(
                tuple(leaf.shape), dtype=leaf.dtype, split=leaf.split, comm=comm
            )
        return leaf

    return jax.tree.map(remap, tree, is_leaf=lambda x: isinstance(x, DNDarray))


# ----------------------------------------------------------------------
# the supervisor
# ----------------------------------------------------------------------
class Supervisor:
    """Owns one job's preemption tolerance: polls the detection seams,
    drains and commits on a trigger, re-forms the world on the survivors
    and restores. :func:`run`/:func:`fit` are the drivers; the class is the
    building block for custom loops.

    Parameters
    ----------
    directory : str
        Checkpoint directory (the manifest commit point lives here).
    checkpoint_every : int
        Commit cadence in steps — also the replay bound after a reform.
    max_reforms : int
        Reforms before the supervisor gives the job up (ElasticError).
    keep : int
        Checkpoint retention.
    drain_ms : float, optional
        Watchdog deadline on the drain (default the
        ``HEAT_TPU_ELASTIC_DRAIN_MS`` knob, 10s).
    lose : int
        Devices shed per reform when no specific device is known-sick
        (the deterministic kill-a-host contract; clamps so the world never
        drops below ``min_devices`` — at one device the reform is a
        restart-in-place).
    min_devices : int
        Floor under the surviving world.
    comm : MeshCommunication, optional
        Starting world (default the global default comm).
    install_signals : bool
        Hook ``HEAT_TPU_ELASTIC_SIGNALS`` for the supervisor's lifetime
        (previous handlers restored by :meth:`close`; skipped silently off
        the main thread, where Python forbids signal handlers).
    """

    def __init__(
        self,
        directory: str,
        *,
        checkpoint_every: int = 5,
        max_reforms: int = 2,
        keep: int = 3,
        drain_ms: Optional[float] = None,
        lose: int = 1,
        min_devices: int = 1,
        comm=None,
        install_signals: bool = True,
    ):
        self.directory = str(directory)
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.max_reforms = int(max_reforms)
        self.keep = int(keep)
        self.drain_ms = _parse_drain_ms() if drain_ms is None else float(drain_ms)
        self.lose = max(0, int(lose))
        self.min_devices = max(1, int(min_devices))
        self.comm = communication.sanitize_comm(comm)
        self.reforms = 0
        self._seen_degraded: set = set()
        self._seen_lost: set = set()
        self._prev_handlers: List[Tuple[int, Any]] = []
        if install_signals:
            self._install_signals()

    # -- detection ------------------------------------------------------
    def _install_signals(self) -> None:
        for num in _parse_signals():
            try:
                prev = _signal_mod.signal(
                    num,
                    lambda signum, frame: request_preempt(
                        f"signal {_signal_mod.Signals(signum).name}"
                    ),
                )
            except ValueError:  # not the main thread: signals are not ours
                return
            self._prev_handlers.append((num, prev))

    def close(self) -> None:
        """Restore hooked signal handlers (idempotent)."""
        while self._prev_handlers:
            num, prev = self._prev_handlers.pop()
            try:
                _signal_mod.signal(num, prev)
            except (ValueError, TypeError):  # pragma: no cover - teardown race
                pass

    def maybe_preempt(self) -> Optional[Preempted]:
        """One detection poll (call between steps): the ``elastic.preempt``
        fault site, fresh ledger degradations, then any out-of-band notice
        (signal / :func:`request_preempt`). Returns the notice to act on, or
        None — never raises."""
        global _PENDING
        if resilience._ARMED:
            try:
                resilience.check("elastic.preempt")
            except Exception as exc:  # noqa: BLE001 - the fault IS the notice
                return Preempted(f"injected: {exc}")
        lost = multihost.lost_peers() - self._seen_lost
        if lost:
            self._seen_lost |= lost
            pre = Preempted(f"peer process(es) {sorted(lost)} lost mid-run")
            pre.peers = tuple(sorted(lost))
            return pre
        degraded = resilience.degraded_devices() - self._seen_degraded
        if degraded:
            self._seen_degraded |= degraded
            current = {str(d): d for d in self.comm.devices}
            sick = [current[k] for k in sorted(degraded) if k in current]
            if sick:
                return Preempted(
                    f"{len(sick)} device(s) crossed the fault threshold",
                    devices=sick,
                )
        if _PENDING is not None:
            pre, _PENDING = _PENDING, None
            return pre
        return None

    # -- drain + commit -------------------------------------------------
    def drain(self) -> int:
        """Force every live fusion root to a device value under the drain
        deadline, so nothing is mid-flight when the world is torn down.
        Runs gate-exempt: the drain's own forces must pass the admission
        hold — they ARE the draining."""
        with memledger.gate_exempt():
            with health_runtime.watch("elastic:drain", deadline_ms=self.drain_ms):
                drained = fusion._drain_pending_roots(())
        _STATS["drained_roots"] += drained
        return drained

    def commit(self, tree, step: int) -> None:
        """Checkpoint ``tree`` through the manifest commit point."""
        from ..utils.checkpoint import save_checkpoint

        with memledger.gate_exempt():
            save_checkpoint(self.directory, tree, step=int(step), keep=self.keep)
        _STATS["checkpoints"] += 1

    # -- re-form --------------------------------------------------------
    def reform(self, sick: Sequence = ()) -> "communication.MeshCommunication":
        """Rebuild the default world on the survivors: current devices minus
        known-sick ones, minus ``lose`` tail devices when none are named.
        Installs the new world (invalidating every mesh-keyed cache), drops
        stale watchdog guards and wipes the device-fault ledger — the
        re-formed mesh starts with a clean bill of health."""
        if self.reforms >= self.max_reforms:
            raise ElasticError(
                f"preempted again after {self.reforms} reform(s) "
                f"(max_reforms={self.max_reforms}): giving the job up"
            )
        devices = list(self.comm.devices)
        sick_keys = {str(d) for d in sick}
        survivors = [d for d in devices if str(d) not in sick_keys]
        if len(survivors) == len(devices):
            # nothing named sick: shed the tail (the deterministic
            # kill-a-host semantics), never dropping below min_devices —
            # a 1-device world re-forms in place
            lose_n = max(0, min(self.lose, len(devices) - self.min_devices))
            if lose_n:
                survivors = devices[: len(devices) - lose_n]
        survivors = probe_devices(survivors)
        if len(survivors) < self.min_devices:
            raise ElasticError(
                f"only {len(survivors)} healthy device(s) would survive the "
                f"reform (min_devices={self.min_devices}): giving the job up"
            )
        new_comm = communication.reform(survivors)
        health_runtime.reset_guards()
        resilience.reset_device_faults()
        self._seen_degraded.clear()
        self.comm = new_comm
        self.reforms += 1
        _STATS["reforms"] += 1
        return new_comm

    # -- the closed loop ------------------------------------------------
    def handle(
        self,
        pre: Preempted,
        *,
        step: int,
        get_state: Optional[Callable[[], Any]] = None,
        template_fn: Optional[Callable[[Any], Any]] = None,
    ) -> Tuple[Any, int]:
        """One full preemption: drain → best-effort commit → reform →
        restore. Returns ``(restored_state, restored_step)`` — the caller
        resumes its loop there. ``template_fn(new_comm)`` builds the restore
        template against the NEW world (and is the rebind point for trainer
        integrations); without one the state is not restored (restored_state
        is None) and the caller owns the resume."""
        t0 = time.perf_counter()
        _STATS["preemptions"] += 1
        if telemetry._MODE:
            telemetry.record_event(
                "elastic_preempt", reason=pre.reason, step=step, mesh=self.comm.size
            )
        health_runtime.auto_dump("elastic_preempt")
        peers = tuple(getattr(pre, "peers", ()))
        if peers:
            # a lost PEER (not a lost device) cannot be reformed from inside
            # this process: XLA's coordination service hard-kills the
            # survivors of a dead peer, and the shrunk process world needs a
            # fresh coordinator epoch. Drain, attempt a best-effort local
            # commit (expected to fail fast once the cooperative barrier is
            # unreachable), then hand the process back to the launcher: the
            # raised PeerLostError is the worker's cue to exit REFORM_EXIT
            # and be respawned into the smaller world.
            _STATS["peer_losses"] += 1
            with memledger.admission_hold(f"peer lost at step {step}: {pre.reason}"):
                self.drain()
                if get_state is not None:
                    try:
                        self.commit(get_state(), step)
                    except Exception as exc:  # noqa: BLE001 - best-effort only
                        warnings.warn(
                            f"post-loss checkpoint at step {step} failed "
                            f"({exc!r}); the reformed world restores from the "
                            "newest verified step",
                            stacklevel=2,
                        )
            if telemetry._MODE:
                telemetry.record_event(
                    "elastic_peer_lost", peers=list(peers), step=step,
                    mesh=self.comm.size,
                )
            health_runtime.auto_dump("elastic_peer_lost")
            raise multihost.PeerLostError(
                f"peer process(es) {sorted(peers)} lost at step {step}: "
                "drained; exit for launcher-mediated reform "
                f"(exit code {multihost.REFORM_EXIT})", peers=peers,
            )
        try:
            with memledger.admission_hold(f"preempted at step {step}: {pre.reason}"):
                self.drain()
                if get_state is not None:
                    try:
                        self.commit(get_state(), step)
                    except Exception as exc:  # noqa: BLE001 - racing save is safe
                        warnings.warn(
                            f"pre-reform checkpoint at step {step} failed "
                            f"({exc!r}); restoring from the newest verified step",
                            stacklevel=2,
                        )
                new_comm = self.reform(sick=pre.devices)
                restored_step = newest_verified_step(self.directory)
                if restored_step is None:
                    raise ElasticError(
                        f"no checkpoint in {self.directory!r} verifies: "
                        "nothing to resume from"
                    )
                restored = None
                if template_fn is not None:
                    from ..utils.checkpoint import load_checkpoint

                    template = template_fn(new_comm)
                    with memledger.gate_exempt():
                        restored = load_checkpoint(
                            self.directory, template, step=restored_step
                        )
        except ElasticError:
            _STATS["failed_reforms"] += 1
            if telemetry._MODE:
                telemetry.record_event(
                    "elastic_reform_failed", reason=pre.reason, step=step
                )
            health_runtime.auto_dump("elastic_reform_failed")
            raise
        downtime_ms = (time.perf_counter() - t0) * 1e3
        replayed = max(0, int(step) - int(restored_step))
        _STATS["steps_replayed"] += replayed
        _STATS["downtime_ms"] += downtime_ms
        _STATS["last_reform"] = {
            "step": int(restored_step),
            "mesh": self.comm.size,
            "downtime_ms": downtime_ms,
            "reason": pre.reason,
        }
        if telemetry._MODE:
            telemetry.record_event(
                "elastic_reformed",
                step=int(restored_step), mesh=self.comm.size,
                downtime_ms=downtime_ms, replayed=replayed,
            )
        health_runtime.auto_dump("elastic_reformed")
        return restored, int(restored_step)


# ----------------------------------------------------------------------
# drivers
# ----------------------------------------------------------------------
def run(
    step_fn: Callable[[Any, int], Any],
    state: Any,
    *,
    steps: int,
    directory: str,
    checkpoint_every: int = 5,
    max_reforms: int = 2,
    keep: int = 3,
    drain_ms: Optional[float] = None,
    lose: int = 1,
    min_devices: int = 1,
    template_fn: Optional[Callable[[Any], Any]] = None,
    on_reform: Optional[Callable[[Any, Any], Any]] = None,
    comm=None,
    install_signals: bool = True,
) -> Any:
    """Drive ``state = step_fn(state, step)`` for ``steps`` steps with
    preemption tolerance: periodic commits every ``checkpoint_every`` steps,
    and on any preemption trigger the full drain→commit→reform→restore→
    resume loop (≤ ``checkpoint_every`` steps replayed). ``state`` is a
    pytree whose DNDarray leaves re-target onto each re-formed world
    (override with ``template_fn(new_comm)``); ``on_reform(new_comm, state)``
    may return a replacement state (e.g. re-jit against the new mesh).
    Returns the final state."""
    sup = Supervisor(
        directory,
        checkpoint_every=checkpoint_every, max_reforms=max_reforms, keep=keep,
        drain_ms=drain_ms, lose=lose, min_devices=min_devices, comm=comm,
        install_signals=install_signals,
    )
    step = 0
    try:
        sup.commit(state, 0)
        while step < steps:
            pre = sup.maybe_preempt()
            if pre is None:
                state = step_fn(state, step)
                step += 1
                if step % sup.checkpoint_every == 0 and step < steps:
                    sup.commit(state, step)
                continue
            tf = template_fn if template_fn is not None else (
                lambda new_comm: _retarget(state, new_comm)
            )
            state, step = sup.handle(
                pre, step=step, get_state=lambda: state, template_fn=tf
            )
            if on_reform is not None:
                replacement = on_reform(sup.comm, state)
                if replacement is not None:
                    state = replacement
        sup.commit(state, step)
        return state
    finally:
        sup.close()


def fit(
    trainer,
    batches: Sequence,
    *,
    directory: str,
    steps: Optional[int] = None,
    checkpoint_every: int = 5,
    max_reforms: int = 2,
    keep: int = 3,
    drain_ms: Optional[float] = None,
    lose: int = 1,
    min_devices: int = 1,
    install_signals: bool = True,
) -> Dict[str, Any]:
    """Preemption-tolerant training: one ``trainer.step(x, y)`` (DASO /
    DataParallelMultiGPU) or ``trainer.train_step(x, y)`` (DataParallel) per
    ``(x, y)`` batch, commits every ``checkpoint_every`` steps, and the full
    reform loop on preemption — the trainer is rebound onto the shrunk
    world (``rebind``) and restored from its mesh-shape-independent elastic
    state, replaying at most ``checkpoint_every`` batches. Returns
    ``{"losses", "steps", "elastic"}`` (losses truncated to the restore
    point before replay, so the list matches an uninterrupted run's
    step count)."""
    core = getattr(trainer, "daso", None) or trainer
    get_state = getattr(core, "elastic_state_dict", None) or core.state_dict
    load_state = getattr(core, "load_elastic_state_dict", None) or core.load_state_dict
    step_call = getattr(trainer, "step", None) or trainer.train_step
    batches = list(batches)
    total = len(batches) if steps is None else min(int(steps), len(batches))
    sup = Supervisor(
        directory,
        checkpoint_every=checkpoint_every, max_reforms=max_reforms, keep=keep,
        drain_ms=drain_ms, lose=lose, min_devices=min_devices,
        comm=getattr(core, "comm", None), install_signals=install_signals,
    )
    losses: List[float] = []
    step = 0
    try:
        sup.commit(get_state(), 0)
        while step < total:
            pre = sup.maybe_preempt()
            if pre is None:
                x, y = batches[step]
                losses.append(float(step_call(x, y)))
                step += 1
                if step % sup.checkpoint_every == 0 and step < total:
                    sup.commit(get_state(), step)
                continue

            def template_fn(new_comm):
                # the rebind point: re-target the trainer onto the new
                # world FIRST, then let its own (mesh-shape-independent)
                # state dict shape the restore template
                trainer.rebind(new_comm)
                return get_state()

            restored, step = sup.handle(
                pre, step=step, get_state=get_state, template_fn=template_fn
            )
            load_state(restored)
            del losses[step:]
        sup.commit(get_state(), step)
        return {"losses": losses, "steps": step, "elastic": stats()}
    finally:
        sup.close()


# report()["elastic"]: the set-attribute hook pattern (telemetry stays
# dependency-free; this module may never be imported in a run that still
# wants a report)
telemetry._ELASTIC_HOOK = stats

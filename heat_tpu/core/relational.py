"""Elementwise comparison operations (reference: heat/core/relational.py:35-420).

Comparisons defer under the eager fusion recorder like any other binary op;
the trailing bool cast in ``_cmp`` records as a fusion cast node, so an
``(a < b).astype(bool)`` chain stays a single program at the forcing point.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import types
from ._operations import __binary_op as _binary_op
from .dndarray import DNDarray

__all__ = ["eq", "equal", "ge", "greater", "greater_equal", "gt", "le", "less", "less_equal", "lt", "ne", "not_equal"]


def _cmp(op, t1, t2, out=None, where=None) -> DNDarray:
    res = _binary_op(op, t1, t2, out=out, where=where)
    if out is None and res.dtype is not types.bool:
        return res.astype(types.bool, copy=False)
    return res


def eq(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise == (reference relational.py:35)."""
    return _cmp(jnp.equal, t1, t2, out, where)


def equal(t1, t2) -> bool:
    """True if ALL elements equal (reference relational.py:82: reduce over eq)."""
    from . import logical

    try:
        res = eq(t1, t2)
    except ValueError:
        return False
    return bool(logical.all(res).item())


def ge(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise >= (reference relational.py:130)."""
    return _cmp(jnp.greater_equal, t1, t2, out, where)


greater_equal = ge


def gt(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise > (reference relational.py:177)."""
    return _cmp(jnp.greater, t1, t2, out, where)


greater = gt


def le(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise <= (reference relational.py:225)."""
    return _cmp(jnp.less_equal, t1, t2, out, where)


less_equal = le


def lt(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise < (reference relational.py:272)."""
    return _cmp(jnp.less, t1, t2, out, where)


less = lt


def ne(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise != (reference relational.py:320)."""
    return _cmp(jnp.not_equal, t1, t2, out, where)


not_equal = ne

"""Numerics observability — the value-plane companion to the time/memory/
health planes (``telemetry``/``memledger``/``health_runtime``).

The observability stack watches *when* things run, *what* they allocate and
*whether* the runtime is healthy — but nothing watches the **values**: a
bf16 underflow collapsing a gradient, a fused-reduction reorder drifting
past tolerance, or a flaky accelerator returning silently-wrong bits is
invisible until a model diverges. This module closes that gap with four
pillars, all off by default (``HEAT_TPU_NUMLENS={0,sample,full}``) and
near-zero when disabled (the fused-dispatch seam pays exactly one ``is
None`` check on ``telemetry._NUMLENS_HOOK``):

1. **Streaming tensor statistics** — every Nth fused dispatch
   (``HEAT_TPU_NUMLENS_SAMPLE_EVERY``; ``full`` samples every dispatch)
   runs a tiny jitted stats program over each DAG-root value: rms, absmax,
   nonfinite count, subnormal fraction and a fixed 16-bucket exponent
   histogram spanning the value dtype's exponent range (the edge buckets
   are the underflow/overflow saturation gauges EQuARX per-block scales
   will pin against). Aggregated per program key + root into
   ``report()["numerics"]``, emitted as ``numeric`` timeline events and
   exported as Perfetto counter tracks alongside memledger's.

2. **Shadow-replay drift audit** — every ``HEAT_TPU_NUMLENS_SHADOW_EVERY``
   sampled dispatch is re-executed through the fusion engine's bitwise
   eager replay path (``fusion._build(sig)`` — the exact unjitted op chain
   the degrade path runs) and compared ULP-aware against the fused jitted
   output. The per-program drift ledger (p50/max ULP, worst op family)
   machine-checks the "fused reorder stays within float tolerance"
   contract the tests otherwise pin only at fixed shapes.

3. **Cross-device determinism + SDC sentinel** — :func:`run_canary` runs a
   fixed jitted program with replicated inputs on every mesh device,
   twice per device: repeated executions must be bitwise self-consistent
   and all devices must agree with the majority (across hosts, process
   rank disambiguates the device names). A mismatch emits a
   ``numlens.sdc`` finding naming the sick device and feeds
   ``resilience.note_device_fault``, escalating through the quarantine
   ledger into a mesh shrink — silent data corruption becomes a
   diagnosed, self-healing event. The ``numeric.sdc.<index>`` fault site
   gives every detector a true-positive test.

4. **Training-signal telemetry** — DASO and ``nn.data_parallel`` call
   :func:`note_training` around each gradient sync: per-merge
   gradient/update norms, the update ratio ``|Δp|/|p|``, and plateau /
   overflow detectors over the loss stream (the assertable surface
   EQuARX error-feedback parity pins against).

Contracts shared with the other observability planes: never force a
pending chain, never initialize a JAX backend from a pure-state read
(:func:`numerics_block` is module state only; :func:`run_canary` returns
``None`` until the mesh singleton exists), never raise out of the hook.
"""

from __future__ import annotations

import math
import os
import threading
import time
from collections import Counter, OrderedDict, deque
from typing import Any, Dict, List, Optional

import numpy as np

from . import resilience, telemetry

__all__ = [
    "set_mode",
    "mode",
    "active",
    "reset",
    "numerics_block",
    "tensor_stats",
    "drift_ledger",
    "run_canary",
    "note_training",
    "training_stats",
    "findings",
    "ulp_diff",
]

# ----------------------------------------------------------------------
# knobs
# ----------------------------------------------------------------------
_MODE_NAMES = {0: "off", 1: "sample", 2: "full"}


def _parse_mode(raw) -> int:
    if isinstance(raw, int):
        return max(0, min(2, raw))
    s = str(raw or "").strip().lower()
    if s in ("", "0", "off", "false", "no"):
        return 0
    if s in ("2", "full"):
        return 2
    return 1  # "sample", "1", "on", anything truthy


def _int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


_MODE = 0
#: sample every Nth fused dispatch in ``sample`` mode (``full`` ignores it)
_SAMPLE_EVERY = _int_env("HEAT_TPU_NUMLENS_SAMPLE_EVERY", 16)
#: shadow-replay every Nth SAMPLED dispatch (0 disables the drift audit)
_SHADOW_EVERY = _int_env("HEAT_TPU_NUMLENS_SHADOW_EVERY", 4)
#: run the SDC canary every Nth sampled dispatch (0 = manual run_canary only)
_CANARY_EVERY = _int_env("HEAT_TPU_NUMLENS_CANARY_EVERY", 0)
#: drift above this many ULPs raises a ``numlens.drift`` finding
_MAX_ULP = _int_env("HEAT_TPU_NUMLENS_MAX_ULP", 16)

#: fixed exponent-histogram width — the bf16/f16 saturation tests and the
#: CLI renderer assume this is stable
N_BUCKETS = 16

_FINDING_CAP = 64
_PROGRAM_CAP = 64
_TRAIN_WINDOW = 128
_PLATEAU_WINDOW = 12

# ----------------------------------------------------------------------
# session state (cleared by reset(); the mode survives, like memledger's
# budget arming — arming is configuration, counters are session data)
# ----------------------------------------------------------------------
_LOCK = threading.Lock()
_SEEN = 0  # fused dispatches observed while armed
_SAMPLED = 0  # dispatches that actually paid for stats
_STATS: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
_DRIFT: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
_CANARY: Dict[str, Any] = {}
_TRAINING: Dict[str, Dict[str, Any]] = {}
_FINDINGS: deque = deque(maxlen=_FINDING_CAP)
_IN_HOOK = False

# Per-session sampling frames (the serving ``Session`` seam): each frame is
# thread-local and carries its own mode override + seen/sampled counters so
# concurrent sessions sample independently — one session in ``full`` mode
# never changes a neighbor's cadence, and per-session counters report which
# tenant paid for stats. ``_SESSION_ARMED`` counts armed frames process-wide
# so the dispatch hook stays installed while any session needs it.
_NL_TLS = threading.local()
_SESSION_ARMED = 0


def _push_session(mode_override=None) -> Dict[str, Any]:
    """Push a thread-local sampling frame. ``mode_override`` of ``None``
    inherits the global mode (but still gets isolated counters); otherwise
    it shadows ``_MODE`` for dispatches on the pushing thread."""
    global _SESSION_ARMED
    stack = getattr(_NL_TLS, "frames", None)
    if stack is None:
        stack = _NL_TLS.frames = []
    frame: Dict[str, Any] = {
        "mode": None if mode_override is None else _parse_mode(mode_override),
        "seen": 0,
        "sampled": 0,
    }
    stack.append(frame)
    if frame["mode"]:
        with _LOCK:
            _SESSION_ARMED += 1
            if telemetry._NUMLENS_HOOK is None:
                telemetry._NUMLENS_HOOK = _on_dispatch
    return frame


def _pop_session() -> Optional[Dict[str, Any]]:
    global _SESSION_ARMED
    stack = getattr(_NL_TLS, "frames", None)
    if not stack:
        return None
    frame = stack.pop()
    if frame["mode"]:
        with _LOCK:
            _SESSION_ARMED -= 1
            if not _SESSION_ARMED and not _MODE:
                telemetry._NUMLENS_HOOK = None
    return frame


def mode() -> str:
    """Current mode name: ``off`` / ``sample`` / ``full``."""
    return _MODE_NAMES[_MODE]


def active() -> bool:
    """Whether the numerics lens is armed (sampling hook installed)."""
    return _MODE > 0


def set_mode(new_mode) -> int:
    """Arm or disarm the lens; accepts ``0/"off"``, ``1/"sample"``,
    ``2/"full"`` (or a previous return value). Installs the fused-dispatch
    hook as a telemetry module attribute (the ``_MEM_HOOK`` set-attribute
    pattern) so the disabled hot path costs one ``is None`` check. Returns
    the previous mode as an int so callers can restore it."""
    global _MODE
    prev = _MODE
    _MODE = _parse_mode(new_mode)
    telemetry._NUMLENS_HOOK = _on_dispatch if (_MODE or _SESSION_ARMED) else None
    return prev


def reset() -> None:
    """Clear the session state (counters, stats, drift ledger, canary,
    training streams, findings). The mode/knobs survive — arming is
    configuration, mirrored on ``memledger.reset``. Called from
    ``telemetry.reset()`` so the joined report surfaces clear together."""
    global _SEEN, _SAMPLED
    with _LOCK:
        _SEEN = 0
        _SAMPLED = 0
        _STATS.clear()
        _DRIFT.clear()
        _CANARY.clear()
        _TRAINING.clear()
        _FINDINGS.clear()


def _add_finding(rule: str, severity: str, message: str, **data) -> Dict[str, Any]:
    f = {"rule": rule, "severity": severity, "message": message}
    f.update(data)
    _FINDINGS.append(f)
    return f


def findings() -> List[Dict[str, Any]]:
    """The capped list of numeric findings (drift breaches, SDC hits,
    training overflow/plateau), oldest first."""
    return list(_FINDINGS)


# ----------------------------------------------------------------------
# pillar 1: streaming tensor statistics
# ----------------------------------------------------------------------
_STATS_FNS: Dict[str, Any] = {}


#: IEEE exponent-field widths per dtype — the count/histogram pillar works
#: on raw bit patterns: float comparisons on subnormal operands are
#: flushed to zero by some XLA CPU pipelines (FTZ varies per compiled
#: program, observed on jax 0.4.37), so only the bits are trustworthy
_EXP_BITS = {"bfloat16": 8, "float16": 5, "float32": 8, "float64": 11}


def _stats_fn(dtype):
    """One tiny jitted stats program per float dtype: (rms, absmax,
    nonfinite count, subnormal count, exponent histogram). The counts and
    the histogram are computed from the integer bit pattern (exponent /
    mantissa fields) — exact and flush-proof; rms/absmax use float math
    with the sum-of-squares scaled by absmax so bf16-range maxima do not
    overflow the f32 accumulator."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    key = str(np.dtype(dtype))
    fn = _STATS_FNS.get(key)
    if fn is not None:
        return fn
    nbits = np.dtype(dtype).itemsize * 8
    uint = {16: jnp.uint16, 32: jnp.uint32, 64: jnp.uint64}[nbits]
    ebits = _EXP_BITS[key]
    mbits = nbits - 1 - ebits
    bias = (1 << (ebits - 1)) - 1
    efield_max = (1 << ebits) - 1
    minexp = 1 - bias  # floor(log2) of the smallest normal
    span = max(1, efield_max - 1)  # number of normal exponent codes

    def stats(x):
        xr = jnp.ravel(x)
        bits = lax.bitcast_convert_type(xr, uint)
        expf = ((bits >> mbits) & efield_max).astype(jnp.int32)
        mant = bits & ((1 << mbits) - 1)
        is_nonfinite = expf == efield_max  # inf and nan: exponent all-ones
        is_zero = (expf == 0) & (mant == 0)
        nonfinite = jnp.sum(is_nonfinite)
        subnormal = jnp.sum((expf == 0) & (mant != 0))
        # bucket by floor(log2|x|) = biased exponent - bias, over the
        # dtype's own exponent range; subnormals fall below minexp and
        # clip into bucket 0 (the underflow-saturation gauge)
        e = expf - bias
        b = jnp.clip(((e - minexp) * N_BUCKETS) // span, 0, N_BUCKETS - 1)
        counted = (~is_nonfinite) & (~is_zero)
        hist = jnp.bincount(b, weights=counted.astype(jnp.int32), length=N_BUCKETS)
        acc = jnp.float64 if x.dtype == jnp.float64 else jnp.float32
        xf = jnp.where(is_nonfinite, 0, xr).astype(acc)
        ax = jnp.abs(xf)
        absmax = jnp.max(ax)
        scale = jnp.maximum(absmax, jnp.asarray(1e-30, acc))
        rms = scale * jnp.sqrt(jnp.mean(jnp.square(xf / scale)))
        return rms, absmax, nonfinite, subnormal, hist

    fn = jax.jit(stats)
    _STATS_FNS[key] = fn
    return fn


def _record_stats(key: str, family: str, values, roots) -> None:
    import jax
    import jax.numpy as jnp

    rec = _STATS.get(key)
    if rec is None:
        while len(_STATS) >= _PROGRAM_CAP:
            _STATS.popitem(last=False)
        rec = _STATS[key] = {"family": family, "samples": 0, "roots": {}}
    rec["samples"] += 1
    for i, v in enumerate(values):
        dt = getattr(v, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.floating):
            continue
        rms, absmax, nonfinite, subnormal, hist = jax.device_get(_stats_fn(dt)(v))
        n = int(v.size)
        hist = np.asarray(hist, dtype=np.int64)
        rr = rec["roots"].get(i)
        if rr is None:
            rr = rec["roots"][i] = {
                "shape": tuple(getattr(v, "shape", ())),
                "dtype": str(np.dtype(dt)),
                "samples": 0,
                "elems": 0,
                "rms": 0.0,
                "absmax": 0.0,
                "nonfinite": 0,
                "subnormal": 0,
                "subnormal_pct": 0.0,
                "hist": [0] * N_BUCKETS,
                "edge_low": 0,
                "edge_high": 0,
            }
        rr["samples"] += 1
        rr["elems"] += n
        rr["rms"] = float(rms)
        rr["absmax"] = max(rr["absmax"], float(absmax))
        rr["nonfinite"] += int(nonfinite)
        rr["subnormal"] += int(subnormal)
        rr["subnormal_pct"] = round(100.0 * rr["subnormal"] / max(1, rr["elems"]), 4)
        rr["hist"] = [a + int(b) for a, b in zip(rr["hist"], hist)]
        rr["edge_low"] = rr["hist"][0]
        rr["edge_high"] = rr["hist"][-1]
        telemetry.record_event(
            "numeric",
            event="stats",
            program=key,
            root=i,
            dtype=rr["dtype"],
            rms=float(rms),
            absmax=float(absmax),
            nonfinite=int(nonfinite),
            subnormal_pct=rr["subnormal_pct"],
            edge_low=int(hist[0]),
            edge_high=int(hist[-1]),
        )


def tensor_stats() -> Dict[str, Dict[str, Any]]:
    """Per-program-key streaming statistics (per DAG root: rms, absmax,
    nonfinite/subnormal counts, exponent histogram + edge saturation)."""
    with _LOCK:
        return {k: _copy_stats(v) for k, v in _STATS.items()}


def _copy_stats(rec: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(rec)
    out["roots"] = {i: dict(rr) for i, rr in rec["roots"].items()}
    return out


# ----------------------------------------------------------------------
# pillar 2: ULP-aware shadow-replay drift audit
# ----------------------------------------------------------------------
_INT_FOR_SIZE = {2: np.int16, 4: np.int32, 8: np.int64}
_ULP_SENTINEL = 2**62  # stands in for "finiteness disagrees"


def ulp_diff(a, b) -> np.ndarray:
    """Elementwise ULP distance between two same-dtype float arrays (bf16 /
    f16 / f32 / f64), as int64. Bit patterns are mapped through the
    monotone signed-magnitude transform (``i if i >= 0 else INT_MIN - i``,
    so -0.0 and +0.0 coincide) and differenced in float64 (exact for any
    drift small enough to matter, overflow-safe at the extremes). Elements
    where both sides are nonfinite count as 0; where finiteness disagrees
    the distance saturates at ``2**62``."""
    a = np.atleast_1d(np.asarray(a))
    b = np.atleast_1d(np.asarray(b))
    if a.dtype != b.dtype:
        raise TypeError(f"ulp_diff needs matching dtypes, got {a.dtype} vs {b.dtype}")
    itype = _INT_FOR_SIZE.get(a.dtype.itemsize)
    if itype is None or a.dtype.kind in "iub?":
        raise TypeError(f"ulp_diff: unsupported dtype {a.dtype}")
    ia = a.view(itype).astype(np.int64)
    ib = b.view(itype).astype(np.int64)
    mn = -(2 ** (8 * a.dtype.itemsize - 1))
    oa = np.where(ia >= 0, ia, mn - ia).astype(np.float64)
    ob = np.where(ib >= 0, ib, mn - ib).astype(np.float64)
    d = np.minimum(np.abs(oa - ob), float(_ULP_SENTINEL)).astype(np.int64)
    fa = np.isfinite(a.astype(np.float64))
    fb = np.isfinite(b.astype(np.float64))
    d = np.where(fa & fb, d, np.where(fa == fb, 0, _ULP_SENTINEL))
    return d


def _shadow_audit(sig, leaves, values, info) -> None:
    """Re-execute the fused program through the eager bitwise replay path
    (``fusion._build`` — exactly what the degrade path runs op by op,
    outside jit) and compare each root ULP-aware against the fused jitted
    output. This prices one eager chain execution per
    ``sample_every * shadow_every`` dispatches."""
    import jax.numpy as jnp

    from . import fusion

    replay = fusion._build(sig)(*leaves)
    key, family = info["key"], info.get("family", "?")
    diffs: List[np.ndarray] = []
    mismatched = 0
    for v, r in zip(values, replay):
        dt = getattr(v, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.floating):
            continue
        try:
            d = ulp_diff(np.asarray(v), np.asarray(r))
        except TypeError:
            continue
        diffs.append(d.ravel())
        mismatched += int(np.count_nonzero(d >= _ULP_SENTINEL))
    if not diffs:
        return
    flat = np.concatenate(diffs)
    p50 = int(np.median(flat))
    worst = int(flat.max())
    rec = _DRIFT.get(key)
    if rec is None:
        while len(_DRIFT) >= _PROGRAM_CAP:
            _DRIFT.popitem(last=False)
        rec = _DRIFT[key] = {
            "family": family,
            "samples": 0,
            "p50_ulp": 0,
            "max_ulp": 0,
            "nonfinite_mismatch": 0,
        }
    rec["samples"] += 1
    rec["p50_ulp"] = max(rec["p50_ulp"], p50)
    rec["max_ulp"] = max(rec["max_ulp"], worst)
    rec["nonfinite_mismatch"] += mismatched
    telemetry.record_event(
        "numeric", event="drift", program=key, family=family,
        p50_ulp=p50, max_ulp=worst,
    )
    if worst > _MAX_ULP:
        _add_finding(
            "numlens.drift",
            "warning",
            f"shadow replay of program {key} (family {family}) drifted "
            f"{worst} ULP from the fused output (p50 {p50}, threshold "
            f"{_MAX_ULP}) — the fused reorder left float tolerance",
            program=key,
            family=family,
            max_ulp=worst,
        )


def drift_ledger() -> Dict[str, Any]:
    """The shadow-replay drift picture: per-program (samples, p50/max ULP,
    op family) plus the global worst offender."""
    with _LOCK:
        programs = {k: dict(v) for k, v in _DRIFT.items()}
    worst_key, worst = None, -1
    for k, v in programs.items():
        if v["max_ulp"] > worst:
            worst_key, worst = k, v["max_ulp"]
    return {
        "programs": programs,
        "max_ulp": max(worst, 0),
        "worst_program": worst_key,
        "worst_family": programs[worst_key]["family"] if worst_key else None,
    }


# ----------------------------------------------------------------------
# pillar 3: cross-device determinism canary / SDC sentinel
# ----------------------------------------------------------------------
_CANARY_FN = None


def _canary_fn():
    global _CANARY_FN
    if _CANARY_FN is None:
        import jax
        import jax.numpy as jnp

        # reorder-sensitive enough to catch a sick FPU, tiny enough to be
        # microseconds per device: transcendental chain + full reduction
        _CANARY_FN = jax.jit(
            lambda x: jnp.sum(jnp.exp(jnp.sin(x) * 1.5) * x + jnp.sqrt(jnp.abs(x)))
        )
    return _CANARY_FN


def run_canary(repeats: int = 2) -> Optional[Dict[str, Any]]:
    """Run the determinism canary on every device of the live mesh:
    replicated inputs, ``repeats`` executions per device. Each device must
    be bitwise self-consistent across repeats and must agree with the
    majority answer across devices (on a multi-host mesh the addressable
    devices of every process run the same check against the same constant
    input, so a cross-host divergence surfaces as a majority mismatch on
    the host that owns the sick chip). A mismatch emits a ``numlens.sdc``
    finding naming the device and feeds ``resilience.note_device_fault`` —
    three strikes quarantines the device and the elastic supervisor
    shrinks the mesh around it. Returns ``None`` without touching JAX when
    no mesh exists yet (the never-initialize contract); the
    ``numeric.sdc.<index>`` fault site injects a corruption per device
    index for true-positive tests."""
    from . import communication

    comm = communication.MESH_WORLD
    if comm is None:
        return None
    import jax

    t0 = time.perf_counter()
    x = (np.arange(96, dtype=np.float32) * 0.37) - 11.5
    prog = _canary_fn()
    outs: Dict[int, Optional[bytes]] = {}
    sick: Dict[int, str] = {}
    for idx, dev in enumerate(comm.devices):
        try:
            resilience.check(f"numeric.sdc.{idx}")
            got = []
            for _ in range(max(2, int(repeats))):
                y = jax.device_get(prog(jax.device_put(x, dev)))
                got.append(np.atleast_1d(np.asarray(y)).tobytes())
            if any(g != got[0] for g in got[1:]):
                sick[idx] = "self-inconsistent across repeats"
                outs[idx] = None
            else:
                outs[idx] = got[0]
        except resilience.FaultInjected:
            sick[idx] = "injected numeric.sdc corruption"
            outs[idx] = None
    majority = None
    votes = Counter(v for v in outs.values() if v is not None)
    if votes:
        majority = votes.most_common(1)[0][0]
        for idx, v in outs.items():
            if v is not None and v != majority and idx not in sick:
                sick[idx] = "bitwise mismatch vs device majority"
    ms = (time.perf_counter() - t0) * 1e3
    mismatches = []
    devs = list(comm.devices)
    for idx in sorted(sick):
        dev = devs[idx]
        why = sick[idx]
        mismatches.append(str(dev))
        _add_finding(
            "numlens.sdc",
            "error",
            f"SDC canary: device {dev} (index {idx}) returned wrong bits "
            f"({why}) — replicated input must agree bitwise; reporting to "
            f"the resilience quarantine ledger",
            device=str(dev),
            index=idx,
            why=why,
        )
        telemetry.record_event(
            "numeric", event="sdc", device=str(dev), index=idx, why=why
        )
        try:
            resilience.note_device_fault(dev, site="numlens.sdc")
        except Exception:  # pragma: no cover - ledger must not kill the canary
            pass
    with _LOCK:
        _CANARY["runs"] = _CANARY.get("runs", 0) + 1
        _CANARY["devices"] = len(devs)
        _CANARY["mismatches"] = _CANARY.get("mismatches", 0) + len(mismatches)
        _CANARY["last_ms"] = round(ms, 3)
        _CANARY["last_sick"] = mismatches
    return {"devices": len(devs), "mismatches": mismatches, "ms": ms}


# ----------------------------------------------------------------------
# pillar 4: training-signal telemetry (DASO / nn.data_parallel seam)
# ----------------------------------------------------------------------
def _tree_norm(tree) -> float:
    import jax
    import jax.numpy as jnp

    leaves = [l for l in jax.tree_util.tree_leaves(tree) if hasattr(l, "dtype")]
    if not leaves:
        return 0.0
    total = sum(jnp.sum(jnp.square(jnp.asarray(l, jnp.float32))) for l in leaves)
    return float(jnp.sqrt(total))


def note_training(
    tag: str,
    *,
    loss=None,
    params=None,
    prev_params=None,
    grads=None,
) -> Optional[Dict[str, Any]]:
    """Record one gradient-sync / merge step for stream ``tag``: loss,
    gradient norm (when ``grads`` given), parameter norm, update norm
    ``|params - prev_params|`` and the update ratio ``|Δp| / |p|``. Flags
    ``numlens.overflow`` (nonfinite loss or update) and ``numlens.plateau``
    (loss flat over the last window). No-op returning None when the lens is
    off — callers gate on a single module-attr read."""
    if not _MODE:
        return None
    try:
        import jax

        rec = _TRAINING.get(tag)
        if rec is None:
            rec = _TRAINING[tag] = {
                "steps": 0,
                "losses": deque(maxlen=_TRAIN_WINDOW),
                "grad_norms": deque(maxlen=_TRAIN_WINDOW),
                "update_ratios": deque(maxlen=_TRAIN_WINDOW),
                "overflows": 0,
                "plateau": False,
            }
        rec["steps"] += 1
        out: Dict[str, Any] = {"tag": tag, "step": rec["steps"]}
        loss_f = None
        if loss is not None:
            try:
                loss_f = float(jax.device_get(loss))
            except Exception:
                loss_f = None
        if loss_f is not None:
            rec["losses"].append(loss_f)
            out["loss"] = loss_f
        if grads is not None:
            gn = _tree_norm(grads)
            rec["grad_norms"].append(gn)
            out["grad_norm"] = gn
        if params is not None and prev_params is not None:
            import jax.numpy as jnp

            delta = jax.tree_util.tree_map(
                lambda a, b: jnp.asarray(a, jnp.float32) - jnp.asarray(b, jnp.float32),
                params,
                prev_params,
            )
            un = _tree_norm(delta)
            pn = _tree_norm(params)
            ratio = un / (pn + 1e-12)
            rec["update_ratios"].append(ratio)
            out["update_norm"] = un
            out["param_norm"] = pn
            out["update_ratio"] = ratio
        # overflow: a nonfinite loss or update is an error finding per hit
        bad_loss = loss_f is not None and not math.isfinite(loss_f)
        bad_update = "update_norm" in out and not math.isfinite(out["update_norm"])
        if bad_loss or bad_update:
            rec["overflows"] += 1
            what = "loss" if bad_loss else "parameter update"
            _add_finding(
                "numlens.overflow",
                "error",
                f"training stream '{tag}' step {rec['steps']}: nonfinite "
                f"{what} — gradients have overflowed",
                tag=tag,
                step=rec["steps"],
            )
        # plateau: loss flat (relative) over the detection window; flagged
        # once, rearmed when the loss moves again
        losses = list(rec["losses"])
        if len(losses) >= _PLATEAU_WINDOW:
            win = losses[-_PLATEAU_WINDOW:]
            if all(math.isfinite(v) for v in win):
                spread = max(win) - min(win)
                scale = max(1e-12, abs(sum(win) / len(win)))
                flat = spread <= 1e-9 + 1e-6 * scale
                if flat and not rec["plateau"]:
                    rec["plateau"] = True
                    _add_finding(
                        "numlens.plateau",
                        "info",
                        f"training stream '{tag}' loss has been flat for "
                        f"{_PLATEAU_WINDOW} merges (spread {spread:.3e}) — "
                        f"plateau or dead gradients",
                        tag=tag,
                        step=rec["steps"],
                    )
                elif not flat:
                    rec["plateau"] = False
        telemetry.record_event(
            "numeric",
            event="train",
            tag=tag,
            step=rec["steps"],
            **{
                k: out[k]
                for k in ("loss", "grad_norm", "update_ratio")
                if k in out
            },
        )
        return out
    except Exception:  # pragma: no cover - observability must not break training
        return None


def training_stats() -> Dict[str, Dict[str, Any]]:
    """Per-tag training streams as plain scalars (steps, last/min loss,
    last update ratio, overflow count, plateau flag)."""
    out = {}
    with _LOCK:
        items = list(_TRAINING.items())
    for tag, rec in items:
        losses = list(rec["losses"])
        ratios = list(rec["update_ratios"])
        gnorms = list(rec["grad_norms"])
        out[tag] = {
            "steps": rec["steps"],
            "last_loss": losses[-1] if losses else None,
            "min_loss": min(losses) if losses else None,
            "last_grad_norm": gnorms[-1] if gnorms else None,
            "last_update_ratio": ratios[-1] if ratios else None,
            "overflows": rec["overflows"],
            "plateau": rec["plateau"],
        }
    return out


# ----------------------------------------------------------------------
# the fused-dispatch hook (installed on telemetry as _NUMLENS_HOOK)
# ----------------------------------------------------------------------
def _on_dispatch(sig, leaves, roots, values, info) -> None:
    """Called by ``fusion.force`` after a fused program's root values land
    (cache-stamped, concrete). Pays one counter increment per dispatch;
    stats/shadow work only on sampled dispatches. Never raises, never
    forces (the values are already concrete), skips under an active jax
    trace, and guards against re-entrancy (the canary dispatches its own
    jitted program)."""
    global _SEEN, _SAMPLED, _IN_HOOK
    frames = getattr(_NL_TLS, "frames", None)
    frame = frames[-1] if frames else None
    # the innermost session frame's mode shadows the global one for
    # dispatches on this thread; a frame without an override inherits it
    eff_mode = _MODE
    if frame is not None and frame["mode"] is not None:
        eff_mode = frame["mode"]
    if not eff_mode or _IN_HOOK or info is None:
        return
    _SEEN += 1
    if frame is not None:
        frame["seen"] += 1
    every = 1 if eff_mode >= 2 else max(1, _SAMPLE_EVERY)
    # sessions with their own mode sample on their own cadence — a
    # neighbor's traffic never advances (or stalls) this tenant's stride
    seen = frame["seen"] if (frame is not None and frame["mode"] is not None) else _SEEN
    if (seen - 1) % every:
        return
    _IN_HOOK = True
    try:
        import jax

        for v in values:
            if isinstance(v, jax.core.Tracer):
                return
        _SAMPLED += 1
        if frame is not None:
            frame["sampled"] += 1
        _record_stats(info["key"], info.get("family", "?"), values, roots)
        if _SHADOW_EVERY > 0 and _SAMPLED % _SHADOW_EVERY == 0:
            _shadow_audit(sig, leaves, values, info)
        if _CANARY_EVERY > 0 and _SAMPLED % _CANARY_EVERY == 0:
            run_canary()
    except Exception:  # pragma: no cover - the lens never breaks a dispatch
        pass
    finally:
        _IN_HOOK = False


# ----------------------------------------------------------------------
# report block (pure module state — never forces, never initializes)
# ----------------------------------------------------------------------
def sampling_stats() -> Dict[str, int]:
    return {"dispatches_seen": _SEEN, "dispatches_sampled": _SAMPLED}


def numerics_block() -> Dict[str, Any]:
    """The ``report()["numerics"]`` payload: mode + knobs, sampling
    counters, tensor stats, drift ledger, canary summary, training streams
    and findings. Pure module state — safe before any backend exists."""
    return {
        "mode": mode(),
        "sample_every": _SAMPLE_EVERY,
        "shadow_every": _SHADOW_EVERY,
        "dispatches_seen": _SEEN,
        "dispatches_sampled": _SAMPLED,
        "tensor_stats": tensor_stats(),
        "drift": drift_ledger(),
        "canary": dict(_CANARY),
        "training": training_stats(),
        "findings": findings(),
    }


# arm from the environment at import (the hook is a set-attribute on
# telemetry, so an unarmed import leaves the hot path untouched)
set_mode(os.environ.get("HEAT_TPU_NUMLENS", "0"))

"""Shape and data manipulations (reference: heat/core/manipulations.py — the
largest module in the reference at 4028 LoC).

The reference's heavyweights — reshape's Alltoallv repartition (:1821-1988),
the distributed sample-sort (:2267-2520), topk's custom MPI merge op
(:3834-4028), resplit's SplitTiles P2P (:3329-3425) — are all expressible as
single sharded XLA ops here: the data movement the reference schedules by hand
is exactly what GSPMD derives from the output sharding constraint. Because
the library API executes eagerly (only internal algorithms are jitted),
data-dependent output shapes (unique, nonzero) are allowed without the
bounded-size+mask contortions jit would require.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import factories, fusion, resilience, sanitation, types
from .communication import sanitize_comm
from .dndarray import DNDarray, _ensure_split
from .stride_tricks import broadcast_shape, sanitize_axis, sanitize_shape

__all__ = [
    "balance",
    "broadcast_arrays",
    "broadcast_to",
    "collect",
    "column_stack",
    "concatenate",
    "diag",
    "diagonal",
    "dsplit",
    "expand_dims",
    "flatten",
    "flip",
    "fliplr",
    "flipud",
    "hsplit",
    "hstack",
    "moveaxis",
    "pad",
    "ravel",
    "redistribute",
    "repeat",
    "reshape",
    "resplit",
    "roll",
    "rot90",
    "row_stack",
    "shape",
    "sort",
    "split",
    "squeeze",
    "stack",
    "swapaxes",
    "tile",
    "topk",
    "mpi_topk",
    "unique",
    "vsplit",
    "vstack",
]


def _wrap(result: jax.Array, split, ref: DNDarray) -> DNDarray:
    if result.ndim == 0 or (split is not None and split >= result.ndim):
        split = None
    result = _ensure_split(result, split, ref.comm)
    return DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype), split, ref.device, ref.comm
    )


def balance(array: DNDarray, copy: bool = False) -> DNDarray:
    """Balanced copy (reference manipulations.py:70-110). GSPMD arrays are
    always balanced; returns the input (or a copy)."""
    from . import memory

    return memory.copy(array) if copy else array


def broadcast_arrays(*arrays: DNDarray) -> List[DNDarray]:
    """Broadcast arrays against each other (reference manipulations.py:111-158)."""
    shapes = [a.shape for a in arrays]
    target = broadcast_shape(*shapes) if len(shapes) > 1 else shapes[0]
    return [broadcast_to(a, target) for a in arrays]


def broadcast_to(x: DNDarray, shape) -> DNDarray:
    """Broadcast to a new shape (reference manipulations.py:159-187)."""
    sanitation.sanitize_in(x)
    shape = sanitize_shape(shape)
    result = jnp.broadcast_to(x.larray, shape)
    split = None
    if x.split is not None:
        split = x.split + (len(shape) - x.ndim)
    return _wrap(result, split, x)


def collect(arr: DNDarray, target_rank: int = 0) -> DNDarray:
    """Reference collect gathers to one process; here: replicate (split=None)."""
    return resplit(arr, None)


def column_stack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack 1-D/2-D arrays as columns (reference manipulations.py:188-246)."""
    arrs = [a.reshape((a.shape[0], 1)) if a.ndim == 1 else a for a in arrays]
    return concatenate(arrs, axis=1)


def row_stack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack arrays as rows (reference manipulations.py:3426-3483)."""
    arrs = [a.reshape((1, a.shape[0])) if a.ndim == 1 else a for a in arrays]
    return concatenate(arrs, axis=0)


def vstack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Vertical stack (reference manipulations.py:4000ish / vstack)."""
    return row_stack(arrays)


def hstack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Horizontal stack (reference manipulations.py:1053-1127)."""
    arrays = list(arrays)
    if all(a.ndim == 1 for a in arrays):
        return concatenate(arrays, axis=0)
    return concatenate(arrays, axis=1)


def concatenate(arrays: Sequence[DNDarray], axis: int = 0) -> DNDarray:
    """Join arrays along an existing axis (reference manipulations.py:247-511,
    whose case analysis + Send/Recv chains reduce to one sharded jnp op)."""
    if not isinstance(arrays, (tuple, list)):
        raise TypeError(f"arrays must be a list or a tuple, got {type(arrays)}")
    arrays = list(arrays)
    if len(arrays) == 0:
        raise ValueError("need at least one array to concatenate")
    for a in arrays:
        sanitation.sanitize_in(a)
    axis = sanitize_axis(arrays[0].shape, axis)
    out_type = arrays[0].dtype
    for a in arrays[1:]:
        out_type = types.promote_types(out_type, a.dtype)
    result = jnp.concatenate([a.larray.astype(out_type.jax_type()) for a in arrays], axis=axis)
    split = next((a.split for a in arrays if a.split is not None), None)
    return _wrap(result, split, arrays[0])


def diag(a: DNDarray, offset: int = 0) -> DNDarray:
    """Extract or construct a diagonal (reference manipulations.py:512-594)."""
    sanitation.sanitize_in(a)
    if a.ndim == 1:
        result = jnp.diag(a.larray, k=offset)
        return _wrap(result, 0 if a.split is not None else None, a)
    return diagonal(a, offset=offset)


def diagonal(a: DNDarray, offset: int = 0, dim1: int = 0, dim2: int = 1) -> DNDarray:
    """Extract diagonal entries (reference manipulations.py:595-684)."""
    sanitation.sanitize_in(a)
    dim1 = sanitize_axis(a.shape, dim1)
    dim2 = sanitize_axis(a.shape, dim2)
    if dim1 == dim2:
        raise ValueError(f"Dim1 and dim2 need to be different, got {dim1}, {dim2}")
    result = jnp.diagonal(a.larray, offset=offset, axis1=dim1, axis2=dim2)
    split = None
    if a.split is not None and a.split not in (dim1, dim2):
        split = a.split - sum(1 for d in (dim1, dim2) if d < a.split)
    elif a.split is not None:
        split = result.ndim - 1
    return _wrap(result, split, a)


def dsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split along axis 2 (reference manipulations.py:685-741)."""
    return split(x, indices_or_sections, axis=2)


def hsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split along axis 1 (0 for 1-D) (reference manipulations.py:1000-1052)."""
    if x.ndim < 2:
        return split(x, indices_or_sections, axis=0)
    return split(x, indices_or_sections, axis=1)


def vsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split along axis 0 (reference manipulations.py:3942-3999)."""
    return split(x, indices_or_sections, axis=0)


def split(x: DNDarray, indices_or_sections, axis: int = 0) -> List[DNDarray]:
    """Split into sub-arrays (reference manipulations.py:2156-2266)."""
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if isinstance(indices_or_sections, DNDarray):
        indices_or_sections = indices_or_sections.tolist()
    if isinstance(indices_or_sections, (int, np.integer)):
        if x.shape[axis] % int(indices_or_sections) != 0:
            raise ValueError("array split does not result in an equal division")
    parts = jnp.split(x.larray, indices_or_sections, axis=axis)
    out = []
    for p in parts:
        split_ax = x.split
        out.append(_wrap(p, split_ax, x))
    return out


def expand_dims(a: DNDarray, axis: int) -> DNDarray:
    """Insert a new axis (reference manipulations.py:742-795)."""
    sanitation.sanitize_in(a)
    axis = sanitize_axis(tuple(a.shape) + (1,), axis)
    result = jnp.expand_dims(a.larray, axis)
    split = a.split
    if split is not None and axis <= split:
        split += 1
    return _wrap(result, split, a)


def flatten(a: DNDarray) -> DNDarray:
    """Flatten to 1-D (reference manipulations.py:796-827)."""
    sanitation.sanitize_in(a)
    result = jnp.ravel(a.larray)
    return _wrap(result, 0 if a.split is not None else None, a)


def ravel(a: DNDarray) -> DNDarray:
    """Flatten to 1-D (view semantics where possible) (reference
    manipulations.py:1459-1501)."""
    return flatten(a)


def flip(a: DNDarray, axis=None) -> DNDarray:
    """Reverse element order along axes (reference manipulations.py:828-887:
    sends shards to mirrored ranks; one sharded jnp.flip here)."""
    sanitation.sanitize_in(a)
    if axis is not None:
        axis = sanitize_axis(a.shape, axis)
    result = jnp.flip(a.larray, axis=axis)
    return _wrap(result, a.split, a)


def fliplr(a: DNDarray) -> DNDarray:
    """Flip along axis 1 (reference manipulations.py:888-931)."""
    if a.ndim < 2:
        raise IndexError("Input must be >= 2-d.")
    return flip(a, 1)


def flipud(a: DNDarray) -> DNDarray:
    """Flip along axis 0 (reference manipulations.py:932-974)."""
    return flip(a, 0)


def moveaxis(x: DNDarray, source, destination) -> DNDarray:
    """Move axes to new positions (reference manipulations.py:1075-1127)."""
    from .linalg import basics

    if isinstance(source, int):
        source = (source,)
    if isinstance(destination, int):
        destination = (destination,)
    try:
        source = tuple(sanitize_axis(x.shape, s) for s in source)
    except TypeError:
        raise TypeError("source must be int or sequence of ints")
    try:
        destination = tuple(sanitize_axis(x.shape, d) for d in destination)
    except TypeError:
        raise TypeError("destination must be int or sequence of ints")
    if len(source) != len(destination):
        raise ValueError("source and destination arguments must have the same number of elements")
    order = [n for n in range(x.ndim) if n not in source]
    for dest, src in sorted(zip(destination, source)):
        order.insert(dest, src)
    return basics.transpose(x, order)


def pad(array: DNDarray, pad_width, mode: str = "constant", constant_values=0) -> DNDarray:
    """Pad an array (reference manipulations.py:1128-1458). Supports numpy's
    pad modes ('constant', 'edge', 'reflect', 'symmetric', 'wrap', ...) via
    the XLA pad/gather kernels."""
    sanitation.sanitize_in(array)
    if isinstance(pad_width, DNDarray):
        pad_width = pad_width.tolist()
    if mode == "constant":
        result = jnp.pad(array.larray, pad_width, mode=mode, constant_values=constant_values)
    else:
        result = jnp.pad(array.larray, pad_width, mode=mode)
    return _wrap(result, array.split, array)


def redistribute(arr: DNDarray, lshape_map=None, target_map=None) -> DNDarray:
    """Out-of-place redistribute (reference manipulations.py:1502-1540)."""
    from . import memory

    out = memory.copy(arr)
    out.redistribute_(lshape_map=lshape_map, target_map=target_map)
    return out


def repeat(a, repeats, axis: Optional[int] = None) -> DNDarray:
    """Repeat elements (reference manipulations.py:1541-1820)."""
    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if isinstance(repeats, DNDarray):
        repeats = repeats.larray
    elif isinstance(repeats, (list, tuple, np.ndarray)):
        repeats = jnp.asarray(repeats)
    elif not isinstance(repeats, (int, np.integer)):
        raise TypeError(f"repeats must be int, list, tuple or DNDarray, got {type(repeats)}")
    if axis is not None:
        axis = sanitize_axis(a.shape, axis)
    result = jnp.repeat(a.larray, repeats, axis=axis)
    split = a.split if axis is not None else (0 if a.split is not None else None)
    return _wrap(result, split, a)


def reshape(a: DNDarray, *shape, new_split: Optional[int] = None) -> DNDarray:
    """Reshape to a new global shape (reference manipulations.py:1821-1988:
    Alltoallv repartition; here the resharding falls out of the output
    constraint)."""
    sanitation.sanitize_in(a)
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    shape = list(shape)
    neg = [i for i, s in enumerate(shape) if s == -1]
    if len(neg) > 1:
        raise ValueError("can only specify one unknown dimension")
    if neg:
        known = int(np.prod([s for s in shape if s != -1])) or 1
        shape[neg[0]] = a.size // known
    shape = sanitize_shape(shape)
    if int(np.prod(shape)) != a.size:
        raise ValueError(f"cannot reshape array of size {a.size} into shape {tuple(shape)}")
    result = jnp.reshape(a.larray, shape)
    if new_split is None:
        new_split = a.split if (a.split is not None and a.split < len(shape)) else (
            0 if a.split is not None and len(shape) else None
        )
    else:
        new_split = sanitize_axis(shape, new_split)
    return _wrap(result, new_split, a)


def resplit(arr: DNDarray, axis: Optional[int] = None) -> DNDarray:
    """Out-of-place redistribution to a new split axis (reference
    manipulations.py:3329-3425: Allgatherv / SplitTiles P2P; one resharding
    collective here). A PENDING recorded chain stays recorded: the reshard
    becomes a collective node in the new wrapper's DAG
    (``fusion.defer_reshard``) while ``arr``'s own chain is untouched, so
    the redistribution compiles inside the chain's one fused program."""
    sanitation.sanitize_in(arr)
    axis = sanitize_axis(arr.shape, axis)
    if axis == arr.split:
        from . import memory

        return memory.copy(arr)
    if resilience._ARMED:
        # same contract as resplit_: the collective.reshard site fires at
        # record-or-dispatch time, before any wrapper is produced — an
        # injected reshard fault must not vanish into the deferred path
        resilience.check("collective.reshard")
    payload = arr._payload
    if (
        isinstance(payload, fusion.LazyArray)
        and payload._value is None
        and fusion.collectives_active()
    ):
        node = fusion.defer_reshard(
            payload, arr.gshape, arr.split, arr.padded, axis, arr.comm
        )
        if node is not None:
            return fusion.wrap_node(node, arr.gshape, axis, arr)
        # recording declined (breadcrumb left): force + reshard eagerly
    result = _ensure_split(arr.larray, axis, arr.comm)
    return DNDarray(result, arr.gshape, arr.dtype, axis, arr.device, arr.comm)


def roll(x: DNDarray, shift, axis=None) -> DNDarray:
    """Roll elements along axes (reference manipulations.py:1989-2155: an
    Isend ring; jnp.roll's collective permute here)."""
    sanitation.sanitize_in(x)
    if axis is not None:
        if isinstance(axis, (list, tuple)):
            axis = tuple(sanitize_axis(x.shape, ax) for ax in axis)
        else:
            axis = sanitize_axis(x.shape, axis)
    if isinstance(shift, DNDarray):
        shift = tuple(shift.tolist())
    result = jnp.roll(x.larray, shift, axis=axis)
    return _wrap(result, x.split, x)


def rot90(m: DNDarray, k: int = 1, axes=(0, 1)) -> DNDarray:
    """Rotate by 90 degrees in a plane (reference manipulations.py:3484-3576)."""
    sanitation.sanitize_in(m)
    if len(axes) != 2:
        raise ValueError("len(axes) must be 2")
    axes = tuple(sanitize_axis(m.shape, ax) for ax in axes)
    if axes[0] == axes[1]:
        raise ValueError("axes must be different")
    result = jnp.rot90(m.larray, k=k, axes=axes)
    split = m.split
    if split is not None and k % 2 != 0:
        if split == axes[0]:
            split = axes[1]
        elif split == axes[1]:
            split = axes[0]
    return _wrap(result, split, m)


def shape(a: DNDarray) -> Tuple[int, ...]:
    """Global shape (reference manipulations.py:3577-3601)."""
    sanitation.sanitize_in(a)
    return a.shape


def sort(a: DNDarray, axis: int = -1, descending: bool = False, out=None):
    """Sort along an axis, returning (values, indices) (reference
    manipulations.py:2267-2520: distributed sample-sort with Bcast pivots and
    Alltoallv exchange).

    Along a non-split axis this is one sharded XLA sort (no communication).
    Along the *split* axis it runs a distributed merge-exchange sort
    (odd-even transposition on sorted blocks, see :func:`_dist_sort`) so no
    device ever materializes more than two blocks — the reference's
    sample-sort role with a static-shape schedule.
    """
    sanitation.sanitize_in(a)
    axis = sanitize_axis(a.shape, axis)
    # complex sorts lexicographically through the gather path (no total-order
    # sentinel exists for the ragged pad slots)
    is_complex = jnp.issubdtype(a.parray.dtype, jnp.complexfloating)
    use_dist = a.split == axis and a.comm.size > 1 and not is_complex
    if is_complex and a.split == axis and a.comm.size > 1:
        sanitation.warn_replicated(
            "sort",
            "complex dtypes have no total-order pad sentinel for the "
            "distributed merge-exchange network; sorting on the gathered view",
        )
    if use_dist:
        sv, sg = _dist_sort(a, axis, descending)
        # sv/sg leave the program at the padded physical shape, correctly
        # sharded — the constructor stores such payloads as-is (no re-pad)
        v = DNDarray(
            sv, tuple(a.shape), types.canonical_heat_type(sv.dtype), a.split, a.device, a.comm
        )
        i = DNDarray(
            sg.astype(types.index_dtype()),
            tuple(a.shape),
            types.canonical_heat_type(types.index_dtype()),
            a.split,
            a.device,
            a.comm,
        )
    else:
        arr = a.larray
        indices = jnp.argsort(arr, axis=axis, descending=descending, stable=True)
        values = jnp.take_along_axis(arr, indices, axis=axis)
        v = _wrap(values, a.split, a)
        i = _wrap(indices.astype(types.index_dtype()), a.split, a)
    if out is not None:
        # store the (possibly padded) physical payload as-is — _replace with
        # gshape accepts it; going through larray would pad+place again
        out._replace(v.parray, v.split, tuple(v.shape))
        return out, i
    return v, i


def _sort_sentinel(dtype, descending: bool):
    """A value that sorts to the global TAIL for pad slots.

    XLA float sort follows the total order ``-NaN < -inf < … < +inf < NaN``.
    Ascending, real NaNs land at the global tail, so a +inf sentinel would
    sort *before* them and leak into the logical result — the sentinel must
    be NaN itself; stability (pads carry the highest global positions) keeps
    real NaNs ahead of pads. Descending, NaNs go to the *head*, the tail is
    the -inf side, and -NaN cannot be used as a sentinel anyway (XLA
    canonicalizes NaN signs) — -inf is correct there, with stability again
    ordering pads after any real -inf. Ints/bools use the dtype extreme with
    the same stability argument.
    """
    if jnp.issubdtype(dtype, jnp.floating):
        return -jnp.inf if descending else jnp.nan
    if jnp.issubdtype(dtype, jnp.bool_):
        return not descending
    info = jnp.iinfo(dtype)
    return info.min if descending else info.max


@functools.lru_cache(maxsize=None)
def _dist_sort_program(
    mesh, axis_name: str, p: int, axis: int, ndim: int, descending: bool, with_indices: bool = True
):
    """Compiled odd-even merge-exchange sort over the block-sharded payload.

    Each device keeps its (block, …) slice sorted; p rounds of pairwise
    block merges (even pairs, then odd pairs, alternating) provably sort any
    sequence of p blocks. Per round a device holds at most TWO blocks —
    O(n/p) memory — and total exchange volume is p·(n/p) = n, the same bytes
    one all-gather moves but without its O(n)-per-device memory. Global
    indices ride along so ``sort`` can return the reference's (values,
    indices) pair; ties keep ascending-global-position order (stable).
    """
    from jax.sharding import PartitionSpec as P

    spec_entries: list = [None] * ndim
    spec_entries[axis] = axis_name
    spec = P(*spec_entries)

    def local_sort(v, g):
        order = jnp.argsort(v, axis=axis, stable=True, descending=descending)
        v = jnp.take_along_axis(v, order, axis)
        return v, (jnp.take_along_axis(g, order, axis) if g is not None else None)

    # only TWO pairings exist (even rounds pair (0,1)(2,3)…, odd rounds
    # (1,2)(3,4)…), so the p rounds run as a fori_loop choosing between two
    # static-perm branches with lax.cond — program size is O(1) in p (the
    # 64-chip compile-scaling requirement, tests/test_mesh64_compile)
    def _pairing(parity: int):
        partner = list(range(p))
        for lo in range(parity, p - 1, 2):
            partner[lo], partner[lo + 1] = lo + 1, lo
        perm = [(d, partner[d]) for d in range(p)]
        is_lower = jnp.asarray([partner[d] > d for d in range(p)])
        is_paired = jnp.asarray([partner[d] != d for d in range(p)])
        return perm, is_lower, is_paired

    pairings = (_pairing(0), _pairing(1))

    def body(v, g):
        idx = jax.lax.axis_index(axis_name)
        block = v.shape[axis]
        has_g = g is not None
        v, g = local_sort(v, g)

        def merge_round(carry, perm, lower_vec, paired_vec):
            v, g = carry  # g is a 0-d dummy when has_g is False
            is_lower = lower_vec[idx]
            is_paired = paired_vec[idx]
            pv = jax.lax.ppermute(v, axis_name, perm)
            # concatenate in global order (lower device's block first) so the
            # stable merge keeps equal keys in global-position order
            cat_v = jnp.concatenate(
                [jnp.where(is_lower, v, pv), jnp.where(is_lower, pv, v)], axis=axis
            )
            order = jnp.argsort(cat_v, axis=axis, stable=True, descending=descending)
            sv = jnp.take_along_axis(cat_v, order, axis)
            lo_v = jax.lax.slice_in_dim(sv, 0, block, axis=axis)
            hi_v = jax.lax.slice_in_dim(sv, block, 2 * block, axis=axis)
            new_v = jnp.where(is_paired, jnp.where(is_lower, lo_v, hi_v), v)
            if has_g:
                pg = jax.lax.ppermute(g, axis_name, perm)
                cat_g = jnp.concatenate(
                    [jnp.where(is_lower, g, pg), jnp.where(is_lower, pg, g)], axis=axis
                )
                sg = jnp.take_along_axis(cat_g, order, axis)
                lo_g = jax.lax.slice_in_dim(sg, 0, block, axis=axis)
                hi_g = jax.lax.slice_in_dim(sg, block, 2 * block, axis=axis)
                g = jnp.where(is_paired, jnp.where(is_lower, lo_g, hi_g), g)
            return new_v, g

        carry0 = (v, g if has_g else jnp.zeros((), v.dtype))

        def round_fn(r, carry):
            return jax.lax.cond(
                r % 2 == 0,
                lambda c: merge_round(c, *pairings[0]),
                lambda c: merge_round(c, *pairings[1]),
                carry,
            )

        v, g_out = jax.lax.fori_loop(0, p, round_fn, carry0)
        return v, (g_out if has_g else None)

    if with_indices:
        kernel = body
        in_specs = (spec, spec)
        out_specs = (spec, spec)
    else:
        def kernel(v):
            return body(v, None)[0]

        in_specs = (spec,)
        out_specs = spec

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )
    )


def _dist_sort(a: DNDarray, axis: int, descending: bool, with_indices: bool = True):
    """Driver for the split-axis distributed sort: sentinel the pad slots,
    run the merge-exchange program. Returns the sorted values and global
    indices at the PADDED physical shape (sentinels occupy the global tail,
    exactly the pad+mask layout the DNDarray constructor stores as-is).
    ``with_indices=False`` (e.g. unique) skips the companion index payload,
    halving the network's exchange volume."""
    comm = a.comm
    p = comm.size
    phys = a.parray
    n = a.shape[axis]
    pos = jnp.arange(phys.shape[axis])
    shape_1 = [1] * phys.ndim
    shape_1[axis] = phys.shape[axis]
    pos_b = pos.reshape(shape_1)
    if phys.shape[axis] != n:  # ragged: pad slots must sort to the global tail
        sentinel = _sort_sentinel(phys.dtype, descending)
        phys = jnp.where(pos_b < n, phys, jnp.asarray(sentinel, phys.dtype))
    phys = _ensure_split(phys, axis, comm)
    fn = _dist_sort_program(
        comm.mesh, comm.axis_name, p, axis, phys.ndim, bool(descending), bool(with_indices)
    )
    if not with_indices:
        return fn(phys)
    gidx = jnp.broadcast_to(pos_b, phys.shape).astype(types.index_dtype())
    gidx = _ensure_split(gidx, axis, comm)
    return fn(phys, gidx)


def squeeze(x: DNDarray, axis=None) -> DNDarray:
    """Remove size-1 axes (reference manipulations.py:3602-3713)."""
    sanitation.sanitize_in(x)
    if axis is not None:
        axis = sanitize_axis(x.shape, axis)
        axes = (axis,) if isinstance(axis, int) else axis
        for ax in axes:
            if x.shape[ax] != 1:
                raise ValueError(
                    f"Dimension along axis {ax} is not 1 for shape {x.shape}"
                )
    else:
        axes = tuple(i for i, s in enumerate(x.shape) if s == 1)
    result = jnp.squeeze(x.larray, axis=axes)
    split = x.split
    if split is not None:
        if split in axes:
            split = None
        else:
            split -= sum(1 for ax in axes if ax < split)
    return _wrap(result, split, x)


def stack(arrays: Sequence[DNDarray], axis: int = 0, out=None) -> DNDarray:
    """Join along a NEW axis (reference manipulations.py:3714-3833)."""
    if not isinstance(arrays, (tuple, list)):
        raise TypeError(f"arrays must be a list or a tuple, got {type(arrays)}")
    arrays = list(arrays)
    if len(arrays) < 2:
        raise ValueError("stack expects at least two arrays")
    for a in arrays:
        sanitation.sanitize_in(a)
        if a.shape != arrays[0].shape:
            raise ValueError(
                f"all input arrays must have the same shape, got {[tuple(x.shape) for x in arrays]}"
            )
    axis = sanitize_axis(tuple(arrays[0].shape) + (1,), axis)
    result = jnp.stack([a.larray for a in arrays], axis=axis)
    split = arrays[0].split
    if split is not None and axis <= split:
        split += 1
    ret = _wrap(result, split, arrays[0])
    if out is not None:
        out._replace(ret.larray.astype(out.dtype.jax_type()), ret.split)
        return out
    return ret


def swapaxes(x: DNDarray, axis1: int, axis2: int) -> DNDarray:
    """Interchange two axes (reference manipulations.py:3834-3880 region)."""
    from .linalg import basics

    axis1 = sanitize_axis(x.shape, axis1)
    axis2 = sanitize_axis(x.shape, axis2)
    order = list(range(x.ndim))
    order[axis1], order[axis2] = order[axis2], order[axis1]
    return basics.transpose(x, order)


def tile(x: DNDarray, reps) -> DNDarray:
    """Tile an array (reference manipulations.py:3881-3941)."""
    sanitation.sanitize_in(x)
    if isinstance(reps, DNDarray):
        reps = reps.tolist()
    if isinstance(reps, (int, np.integer)):
        reps = (int(reps),)
    reps = tuple(int(r) for r in reps)
    result = jnp.tile(x.larray, reps)
    split = x.split
    if split is not None:
        split += result.ndim - x.ndim
    return _wrap(result, split, x)


@functools.lru_cache(maxsize=256)
def _topk_kernel(axis_name: str, size: int, dim: int, k: int, block: int, largest: bool):
    """One stable top-k merge kernel per (mesh axis+size, dim, k, block,
    largest) so ``comm.apply``'s program cache and the retrace ledger key it
    like any other op — a per-call closure here retraced every ``topk`` call
    (the H004 bug class; cf. ``fusion._apply_fn`` /
    ``statistics._arg_reduce_kernel``). ``size`` is the static mesh-axis
    size the custom-combiner allreduce folds over."""
    from . import communication

    def kernel(xs):
        x_last = jnp.moveaxis(xs, dim, -1)
        order = jnp.argsort(x_last, axis=-1, descending=largest, stable=True)
        order = jnp.take(order, jnp.arange(k), axis=-1)
        lv = jnp.take_along_axis(x_last, order, axis=-1)
        li = order + jax.lax.axis_index(axis_name) * block
        gv, gi = communication.allreduce(
            (lv, li),
            axis_name,
            op=lambda p1, p2: mpi_topk(p1, p2, k, largest),
            size=size,
        )
        return jnp.moveaxis(gv, -1, dim), jnp.moveaxis(gi, -1, dim)

    kernel.__name__ = f"topk_merge_d{dim}_k{k}"
    return kernel


def topk(a: DNDarray, k: int, dim: int = -1, largest: bool = True, sorted: bool = True, out=None):
    """Top-k values and indices along a dimension (reference
    manipulations.py:3834-3984 + the custom mpi_topk merge :3985-4028; XLA's
    sharded sort/slice here)."""
    sanitation.sanitize_in(a)
    dim = sanitize_axis(a.shape, dim)
    if k > a.shape[dim]:
        raise ValueError(f"k={k} out of range for dimension of size {a.shape[dim]}")
    # distributed schedule for top-k ACROSS the split axis: per-device local
    # top-k with global indices, merged by the mpi_topk combiner through one
    # allreduce — the reference's custom MPI merge op
    # (reference manipulations.py:3985-4028) riding MeshCommunication.allreduce.
    # Moves O(k) per device instead of sorting the global axis.
    if (
        a.split == dim
        and not a.padded
        and a.comm.size > 1
        and k <= a.shape[dim] // a.comm.size
    ):
        comm = a.comm
        block = a.shape[dim] // comm.size
        kernel = _topk_kernel(comm.axis_name, comm.size, dim, k, block, largest)
        val, idx = comm.apply(kernel, a.larray, in_splits=[dim], out_splits=(None, None))
        v = _wrap(val, None, a)
        i = _wrap(idx.astype(types.index_dtype()), None, a)
        if out is not None:
            out[0]._replace(v.larray, v.split)
            out[1]._replace(i.larray, i.split)
            return out
        return v, i
    arr = a.larray
    idx = jnp.argsort(arr, axis=dim, descending=largest, stable=True)
    idx = jnp.take(idx, jnp.arange(k), axis=dim)
    val = jnp.take_along_axis(arr, idx, axis=dim)
    split = a.split if a.split != dim else None
    v = _wrap(val, split, a)
    i = _wrap(idx.astype(types.index_dtype()), split, a)
    if out is not None:
        out[0]._replace(v.larray, v.split)
        out[1]._replace(i.larray, i.split)
        return out
    return v, i


def unique(a: DNDarray, sorted: bool = False, return_inverse: bool = False, axis: Optional[int] = None):
    """Unique elements (reference manipulations.py:3055-3264: local uniques,
    then a reduced exchange). Eager execution permits the data-dependent
    output shape directly.

    Flat unique of a split array rides the distributed merge-exchange sort:
    sort in place over the mesh (O(n/p) per-device memory), mark
    first-occurrence flags with one global shift, and gather only the k
    unique values — the full operand is never gathered. ``return_inverse``,
    axis-unique, and complex dtypes use the dense path.
    """
    sanitation.sanitize_in(a)
    if axis is not None:
        axis = sanitize_axis(a.shape, axis)
    is_complex = jnp.issubdtype(a.parray.dtype, jnp.complexfloating)
    use_dist = (
        axis is None
        and not return_inverse
        and a.split is not None
        and a.comm.size > 1
        and a.ndim >= 1
        and a.size > 0
        and not is_complex
    )
    if is_complex and axis is None and a.split is not None and a.comm.size > 1:
        sanitation.warn_replicated(
            "unique",
            "complex dtypes have no total-order pad sentinel for the "
            "distributed sort network; deduplicating on the gathered view",
        )
    if use_dist:
        flat = ravel(a) if a.ndim > 1 else a
        sv = _dist_sort(flat, 0, False, with_indices=False)
        n = flat.shape[0]
        svl = sv[:n] if sv.shape[0] != n else sv  # logical view (drops sentinels)
        flags = svl[1:] != svl[:-1]
        if jnp.issubdtype(svl.dtype, jnp.floating):
            # collapse NaN runs like jnp.unique (equal_nan): NaN != NaN is
            # True elementwise, but consecutive NaNs are not new values
            both_nan = jnp.isnan(svl[1:]) & jnp.isnan(svl[:-1])
            flags = flags & ~both_nan
        flags = jnp.concatenate([jnp.ones((1,), bool), flags])
        k = int(flags.sum())  # host sync — eager API, data-dependent shape
        idxs = jnp.nonzero(flags, size=k)[0]
        res = jnp.take(svl, idxs)  # gathers k elements, not n
        return _wrap(res, 0, a)
    if return_inverse:
        res, inverse = jnp.unique(a.larray, return_inverse=True, axis=axis)
        split = 0 if a.split is not None else None
        return _wrap(res, split, a), _wrap(inverse.astype(types.index_dtype()), None, a)
    res = jnp.unique(a.larray, axis=axis)
    split = 0 if a.split is not None else None
    return _wrap(res, split, a)


def mpi_topk(a, b, k: int, largest: bool = True):
    """Merge two ``(values, indices)`` top-k partials into the combined top-k
    — the pure-JAX equivalent of the reference's custom MPI merge op
    (reference manipulations.py:3985-4028). Operates along the last axis."""
    av, ai = a
    bv, bi = b
    vals = jnp.concatenate([av, bv], axis=-1)
    inds = jnp.concatenate([ai, bi], axis=-1)
    if k > vals.shape[-1]:
        raise ValueError(f"k={k} out of range for combined partials of size {vals.shape[-1]}")
    order = jnp.argsort(vals, axis=-1, descending=largest, stable=True)
    order = jnp.take(order, jnp.arange(k), axis=-1)
    return (
        jnp.take_along_axis(vals, order, axis=-1),
        jnp.take_along_axis(inds, order, axis=-1),
    )

"""Distributed-aware printing (reference: heat/core/printing.py).

The reference gathers only the edge items of large arrays to rank 0
(printing.py:208-264). Under the global-view runtime a repr materializes the
(summarized) global array on host; for very large arrays only the edge slabs
are fetched, mirroring the reference's balanced gather.
"""

from __future__ import annotations

import numpy as np

from . import health_runtime, telemetry
from .communication import get_comm

_T_PRINT = telemetry.force_trigger("print")

__all__ = [
    "get_printoptions",
    "global_printing",
    "local_printing",
    "print0",
    "set_printoptions",
]

# default print options (reference printing.py:14-27 via torch defaults)
__PRINT_OPTIONS = dict(precision=4, threshold=1000, edgeitems=3, linewidth=120, sci_mode=None)
__LOCAL_PRINTING = False


def set_printoptions(precision=None, threshold=None, edgeitems=None, linewidth=None, profile=None, sci_mode=None):
    """Configure printing options (reference printing.py:150-207)."""
    if profile == "default":
        __PRINT_OPTIONS.update(precision=4, threshold=1000, edgeitems=3, linewidth=120)
    elif profile == "short":
        __PRINT_OPTIONS.update(precision=2, threshold=1000, edgeitems=2, linewidth=120)
    elif profile == "full":
        __PRINT_OPTIONS.update(precision=4, threshold=float("inf"), edgeitems=3, linewidth=120)
    for key, val in dict(
        precision=precision, threshold=threshold, edgeitems=edgeitems, linewidth=linewidth, sci_mode=sci_mode
    ).items():
        if val is not None:
            __PRINT_OPTIONS[key] = val


def get_printoptions() -> dict:
    """View of current printing options (reference printing.py:31)."""
    return dict(__PRINT_OPTIONS)


def local_printing() -> None:
    """Print only the local shard on each process (reference printing.py:30-60)."""
    global __LOCAL_PRINTING
    __LOCAL_PRINTING = True


def global_printing() -> None:
    """Restore global (gathered) printing (reference printing.py:61-99)."""
    global __LOCAL_PRINTING
    __LOCAL_PRINTING = False


def print0(*args, **kwargs) -> None:
    """Print once (process 0) (reference printing.py:100-126)."""
    if get_comm().rank == 0:
        print(*args, **kwargs)


def __str__(dndarray) -> str:
    """Global string representation (reference printing.py:208-264)."""
    opts = __PRINT_OPTIONS
    token = None
    if telemetry._MODE:
        from . import fusion

        if fusion.is_deferred(dndarray):  # printing a pending chain blocks
            token = telemetry.record_blocking_sync(
                "print", cid=dndarray._payload.cid
            )
    with _T_PRINT:  # a repr that forces a pending chain reads as "print"
        with health_runtime.watch(
            "sync:print", cid=None if token is None else token.get("cid")
        ):
            body = _format_data(dndarray, opts)
    telemetry.end_blocking_sync(token)
    return (
        f"DNDarray({body}, dtype=heat_tpu.{dndarray.dtype.__name__}, "
        f"device={dndarray.device}, split={dndarray.split})"
    )


# above this many elements, only edge slabs are fetched from the devices
_FULL_FETCH_LIMIT = 65536


def _format_data(dndarray, opts) -> str:
    """Format (the printable portion of) the array. Arrays beyond the fetch
    limit only move their first-axis edge slabs to host — the analog of the
    reference's balanced edge-item gather (printing.py:208-264)."""
    threshold = opts["threshold"]
    edge = opts["edgeitems"]
    np_opts = dict(
        precision=opts["precision"],
        threshold=int(threshold) if np.isfinite(threshold) else np.iinfo(np.int64).max,
        edgeitems=edge,
        linewidth=opts["linewidth"],
    )
    arr = dndarray.larray
    summarize_slabs = (
        np.isfinite(threshold)
        and dndarray.ndim >= 1
        and dndarray.size > max(threshold, _FULL_FETCH_LIMIT)
        and dndarray.shape[0] > 2 * edge + 1
    )
    with np.printoptions(**np_opts):
        if not summarize_slabs:
            return np.array2string(np.asarray(arr), separator=", ", prefix="DNDarray(")
        top = np.asarray(arr[:edge])
        bot = np.asarray(arr[-edge:])
        if dndarray.ndim == 1:
            items = [np.array2string(v)[:] for v in top] + ["..."] + [
                np.array2string(v) for v in bot
            ]
            return "[" + ", ".join(items) + "]"
        head = np.array2string(top, separator=", ", prefix="DNDarray(")[1:-1]
        tail = np.array2string(bot, separator=", ", prefix="DNDarray(")[1:-1]
        return "[" + head + ",\n ...,\n " + tail + "]"

"""Shape/axis sanitation helpers (reference: heat/core/stride_tricks.py)."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

__all__ = ["broadcast_shape", "broadcast_shapes", "sanitize_axis", "sanitize_shape", "sanitize_slice"]


def broadcast_shape(shape_a: Sequence[int], shape_b: Sequence[int]) -> Tuple[int, ...]:
    """Broadcast output shape of two operands, NumPy rules
    (reference stride_tricks.py:12-69)."""
    try:
        return tuple(np.broadcast_shapes(tuple(shape_a), tuple(shape_b)))
    except ValueError:
        raise ValueError(
            f"operands could not be broadcast, input shapes {tuple(shape_a)} {tuple(shape_b)}"
        )


def broadcast_shapes(*shapes: Sequence[int]) -> Tuple[int, ...]:
    """N-ary broadcast shape."""
    try:
        return tuple(np.broadcast_shapes(*[tuple(s) for s in shapes]))
    except ValueError:
        raise ValueError(f"operands could not be broadcast, input shapes {shapes}")


def sanitize_axis(
    shape: Sequence[int], axis: Optional[Union[int, Sequence[int]]]
) -> Optional[Union[int, Tuple[int, ...]]]:
    """Normalize (possibly negative, possibly tuple) axis arguments against a
    shape; raise for out-of-bounds (reference stride_tricks.py:72-132)."""
    ndim = len(shape)
    if axis is None:
        return None
    if isinstance(axis, (list, tuple, np.ndarray)):
        axes = []
        for ax in axis:
            if not isinstance(ax, (int, np.integer)):
                raise TypeError(f"axis must be None or int or tuple of ints, got {type(ax)}")
            ax = int(ax)
            if ax < 0:
                ax += ndim
            if ax < 0 or ax >= max(ndim, 1):
                raise ValueError(f"axis {ax - ndim} is out of bounds for {ndim}-dimensional array")
            axes.append(ax)
        if len(set(axes)) != len(axes):
            raise ValueError("duplicate value in axis")
        return tuple(axes)
    if not isinstance(axis, (int, np.integer)):
        raise TypeError(f"axis must be None or int or tuple of ints, got {type(axis)}")
    axis = int(axis)
    if axis < 0:
        axis += ndim
    if ndim == 0 and axis in (0, -1):
        return 0
    if axis < 0 or axis >= max(ndim, 1):
        raise ValueError(f"axis {axis - ndim if axis < 0 else axis} is out of bounds for {ndim}-dimensional array")
    return axis


def sanitize_shape(shape, lval: int = 0) -> Tuple[int, ...]:
    """Normalize a shape argument to a tuple of non-negative ints
    (reference stride_tricks.py:135-177)."""
    if isinstance(shape, (int, np.integer)):
        shape = (int(shape),)
    try:
        shape = tuple(shape)
    except TypeError:
        raise TypeError(f"expected sequence object with length >= 0 or a single integer")
    out = []
    for dim in shape:
        if hasattr(dim, "item") and not isinstance(dim, (int, np.integer)):
            dim = dim.item()
        if not isinstance(dim, (int, np.integer)):
            raise TypeError(f"expected int dimension, got {type(dim)}")
        dim = int(dim)
        if dim < lval:
            raise ValueError(f"negative dimensions are not allowed, got {dim}")
        out.append(dim)
    return tuple(out)


def sanitize_slice(s: slice, max_dim: int) -> slice:
    """Resolve a slice against a dimension extent into non-negative
    start/stop/step (reference stride_tricks.py:180-210)."""
    if not isinstance(s, slice):
        raise TypeError("can only be used for slices")
    return slice(*s.indices(max_dim))

"""Runtime health layer (``ht.flight``): always-on flight recorder, stall
watchdog, and streaming latency histograms with rolling SLO gauges.

Telemetry (``core/telemetry.py``) explains a run after the fact; this module
watches the runtime WHILE it serves — the black box a production job is
examined by when it hangs, OOMs, or quietly misses its latency target:

* **Flight recorder** — a fixed-size ring of the compact typed events
  (dispatches, blocking syncs, collectives, compiles, faults, degradations,
  OOMs) captured even at plain ``HEAT_TPU_TELEMETRY=1``, where the verbose
  per-state timelines stay empty: ``telemetry._note_event`` hands every
  event to ``_FLIGHT_HOOK`` (the set-attribute seam, same pattern as the
  memledger's ``_MEM_HOOK``) and the ring costs one deque append. On OOM,
  fault-degrade, a watchdog trip, or an explicit :func:`dump_flight`, the
  ring is exported as a Chrome/Perfetto trace (validated through
  ``telemetry.validate_trace``) next to a JSON forensics bundle: watchdog
  state, stall diagnoses, latency histograms, fusion-cache metadata, memory
  watermark and the stored OOM report. Knobs: ``HEAT_TPU_FLIGHT={0,1}``,
  ``HEAT_TPU_FLIGHT_EVENTS=N``, ``HEAT_TPU_FLIGHT_DIR``,
  ``HEAT_TPU_FLIGHT_DUMP_EVERY_S`` (per-reason auto-dump throttle).

* **Stall watchdog** — a lazily started daemon thread monitoring every
  armed :func:`watch` guard (fused dispatches in ``fusion.force``, the
  blocking host boundaries in ``dndarray.numpy/item``, deferred prints, and
  the admission gate's drain loop). A guard still in flight past its
  deadline (``HEAT_TPU_WATCHDOG_MS``) produces a structured stall diagnosis:
  the in-flight program key, the pending DAG root cids, the recent
  collective trail from the flight ring (this host's half of the cross-host
  parity comparison — the runtime complement of lint rules H001/S104), and
  the blocked thread's stack. Policies (``HEAT_TPU_WATCHDOG_POLICY``):
  ``warn`` emits a :class:`~heat_tpu.core.resilience.StallWarning`;
  ``dump`` also auto-dumps the flight ring; ``raise`` additionally raises
  :class:`~heat_tpu.core.resilience.StallError` at the guarded call site
  once it returns (a policy signal — ``force_recoverable`` never degrades
  it). The ``watchdog.stall`` fault site lets tests inject REAL stalls: an
  armed guard converts the injected fault into sleeping past its own
  deadline, so the daemon trips on its own clock.

* **Latency histograms + SLO gauges** — log-bucketed (HDR-style, ~9%
  relative error) streaming histograms for blocking-sync host wait (per
  trigger), dispatch→done (per program key), and fused-program compile time
  (per program key), kept per scope exactly like telemetry's counter states
  (record to every state on the stack, query the innermost) and surfaced
  with p50/p90/p99 in ``report()["health"]``, the metrics sink, and
  ``python -m heat_tpu.telemetry health``. Optional SLO limits
  (``HEAT_TPU_SLO_SYNC_MS`` / ``_DISPATCH_MS`` / ``_COMPILE_MS``) turn each
  observation into a pass/breach sample over a rolling window
  (``HEAT_TPU_SLO_WINDOW_S``); breaches land on the flight ring.

Contracts inherited from the rest of the observability stack: nothing here
ever forces a pending chain or initializes a JAX backend (pure module state
plus metadata reads), everything stays inside the ≥0.9x dispatch-rate
overhead guard, and ``telemetry.reset()``/``scope()`` reset/scope this
module's session state too (the joined-surface rule PR 8 established for
the memory ledger).
"""
from __future__ import annotations

import itertools
import json
import math
import os
import sys
import tempfile
import threading
import time
import traceback
import warnings
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from . import resilience, telemetry
from .resilience import StallError, StallWarning

__all__ = [
    "StallError",
    "StallWarning",
    "auto_dump",
    "breach_fraction",
    "dump_flight",
    "flight_events",
    "flight_stats",
    "health_block",
    "last_dump",
    "last_stall",
    "note_dispatch",
    "reset",
    "reset_guards",
    "set_dump_dir",
    "set_flight",
    "set_slo",
    "set_watchdog",
    "stalls",
    "watch",
    "watchdog_stats",
]

_OFF_VALUES = ("", "0", "false", "off", "no")
_UNSET = object()


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw.strip())
    except ValueError:
        warnings.warn(
            f"{name}: malformed value {raw!r}; using {default}", stacklevel=2
        )
        return default


def _env_ms(name: str) -> Optional[float]:
    """Optional millisecond knob returned in SECONDS (None = unset)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return None
    try:
        return float(raw.strip()) / 1e3
    except ValueError:
        warnings.warn(f"{name}: malformed value {raw!r}; ignored", stacklevel=2)
        return None


# ----------------------------------------------------------------------
# flight recorder: the always-on event ring
# ----------------------------------------------------------------------
_ENABLED = os.environ.get("HEAT_TPU_FLIGHT", "1").strip().lower() not in _OFF_VALUES
_RING_CAP = max(16, int(_env_float("HEAT_TPU_FLIGHT_EVENTS", 2048)))
_RING: deque = deque(maxlen=_RING_CAP)
_RING_DROPPED = 0
_DUMP_DIR = os.environ.get("HEAT_TPU_FLIGHT_DIR", "").strip() or tempfile.gettempdir()
_DUMP_EVERY_S = max(0.0, _env_float("HEAT_TPU_FLIGHT_DUMP_EVERY_S", 60.0))
_DUMP_COUNT = 0
_LAST_DUMP: Optional[Dict[str, Any]] = None
_LAST_AUTO_DUMP_TS: Dict[str, float] = {}


def _flight_note(ev: dict) -> None:
    """The ``telemetry._FLIGHT_HOOK``: one bounded append per typed event.
    The ring shares the event dict with the verbose timeline, so late
    ``dur`` stamps (closed blocking syncs) show up in dumps too."""
    global _RING_DROPPED
    if len(_RING) == _RING.maxlen:
        _RING_DROPPED += 1
    _RING.append(ev)


def _install_hook() -> None:
    telemetry._FLIGHT_HOOK = _flight_note if _ENABLED else None


def set_flight(enabled: Optional[bool] = None, events: Optional[int] = None):
    """Toggle the flight recorder / resize its ring in-process (tests, bench
    legs). Returns the previous ``(enabled, ring_cap)`` pair. Resizing keeps
    the newest events."""
    global _ENABLED, _RING_CAP, _RING
    prev = (_ENABLED, _RING_CAP)
    if enabled is not None:
        _ENABLED = bool(enabled)
    if events is not None:
        _RING_CAP = max(1, int(events))
        _RING = deque(_RING, maxlen=_RING_CAP)
    _install_hook()
    return prev


def flight_events() -> List[dict]:
    """The current ring contents, oldest first."""
    return list(_RING)


def flight_stats() -> Dict[str, Any]:
    """Ring occupancy + dump accounting — the report()/CLI surface."""
    return {
        "enabled": _ENABLED,
        "events": len(_RING),
        "cap": _RING_CAP,
        "dropped": _RING_DROPPED,
        "dumps": _DUMP_COUNT,
        "last_dump": (_LAST_DUMP or {}).get("path"),
    }


def set_dump_dir(path: str) -> str:
    """Redirect auto-dumps (tests, servers with a scratch volume); returns
    the previous directory."""
    global _DUMP_DIR
    prev, _DUMP_DIR = _DUMP_DIR, str(path)
    return prev


def last_dump() -> Optional[Dict[str, Any]]:
    """The most recent dump's ``{"path", "trace_path", "problems"}``."""
    return _LAST_DUMP


def dump_flight(path: Optional[str] = None, reason: str = "manual") -> Dict[str, Any]:
    """Export the flight ring as a validated Chrome/Perfetto trace
    (``<base>.trace.json``) plus a JSON forensics bundle (``<base>.json``):
    watchdog state and stall diagnoses, the latency/SLO picture, fusion
    program-cache metadata, and the memory watermark + stored OOM report.
    Pure module state and metadata reads — never forces a pending chain,
    never initializes a backend. Returns ``{"path", "trace_path",
    "problems"}`` (``problems`` is ``validate_trace``'s finding list —
    empty for a well-formed dump)."""
    global _DUMP_COUNT, _LAST_DUMP
    evs = list(_RING)
    _DUMP_COUNT += 1
    if path is None:
        base = os.path.join(
            _DUMP_DIR,
            f"heat_flight_h{telemetry._host_index()}_{reason}_{_DUMP_COUNT:03d}",
        )
    else:
        base = path[:-5] if path.endswith(".json") else path
    trace_path = base + ".trace.json"
    bundle_path = base + ".json"
    doc = telemetry.export_trace(trace_path, events=evs)
    problems = [str(p) for p in telemetry.validate_trace(doc)]
    bundle: Dict[str, Any] = {
        "reason": reason,
        "captured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host": telemetry._host_index(),
        "telemetry_mode": telemetry._MODE,
        "events": len(evs),
        "events_dropped": _RING_DROPPED,
        "ring_cap": _RING_CAP,
        "trace_path": trace_path,
        "trace_problems": problems,
        "collective_parity_problems": [
            str(p) for p in telemetry.trace_collective_parity(doc)
        ],
        "watchdog": watchdog_stats(),
        "stalls": list(_STALLS),
        "health": health_block(global_view=True),
    }
    try:
        from . import fusion

        bundle["programs"] = fusion.cache_stats()
    except Exception as exc:  # half-imported runtime: dump what exists
        bundle["programs"] = {"error": repr(exc)}
    try:
        from . import memledger

        bundle["memory"] = {
            "watermark": memledger.watermark(),
            "budget": memledger.budget_info(),
            "last_oom": memledger.last_oom(),
        }
    except Exception as exc:
        bundle["memory"] = {"error": repr(exc)}
    try:
        from . import tracelens

        # the one-page verdict over the ring window: forensics bundles ship
        # a diagnosis, not just raw events (a ring is partial by construction)
        bundle["diagnosis"] = tracelens.diagnose(evs)
    except Exception as exc:
        bundle["diagnosis"] = {"error": repr(exc)}
    try:
        from . import numlens

        # the value-plane evidence rides next to the diagnosis: the last
        # numeric findings (SDC hits, drift breaches, nonfinite provenance)
        # plus the drift ledger, so a post-mortem can tell "the runtime
        # stalled" apart from "the numbers went bad first"
        bundle["numerics"] = {
            "findings": numlens.findings(),
            "drift": numlens.drift_ledger(),
            "canary": numlens.numerics_block()["canary"],
        }
    except Exception as exc:
        bundle["numerics"] = {"error": repr(exc)}
    with open(bundle_path, "w") as fh:
        json.dump(telemetry._jsonable(bundle), fh, indent=1, default=str)
        fh.write("\n")
    _LAST_DUMP = {"path": bundle_path, "trace_path": trace_path, "problems": problems}
    telemetry.record_event(
        "flight_dump", reason=reason, path=bundle_path, events=len(evs)
    )
    return dict(_LAST_DUMP)


def auto_dump(reason: str) -> Optional[Dict[str, Any]]:
    """The crash-dump trigger wired at the failure seams (memledger OOM,
    fusion degrade, watchdog trip, and the elastic supervisor's
    ``elastic_preempt`` / ``elastic_reformed`` / ``elastic_reform_failed``
    milestones — a reform that fails still leaves a forensics bundle).
    Throttled per reason
    (``HEAT_TPU_FLIGHT_DUMP_EVERY_S``) so a degrade storm writes one bundle,
    not thousands; a no-op unless the recorder is enabled and telemetry is
    active (an empty ring has nothing to explain)."""
    if not _ENABLED or not telemetry._MODE:
        return None
    now = time.perf_counter()
    last = _LAST_AUTO_DUMP_TS.get(reason)
    if last is not None and _DUMP_EVERY_S > 0 and now - last < _DUMP_EVERY_S:
        return None
    _LAST_AUTO_DUMP_TS[reason] = now
    try:
        return dump_flight(reason=reason)
    except Exception as exc:  # the black box must never take down recovery
        warnings.warn(f"flight auto-dump ({reason}) failed: {exc!r}", stacklevel=2)
        return None


# ----------------------------------------------------------------------
# streaming latency histograms (log-bucketed, HDR-style)
# ----------------------------------------------------------------------
#: bucket growth factor: 2**(1/8) ≈ 1.09 — ≤ ~9% relative quantile error
_HIST_BASE = 2.0 ** 0.125
_HIST_LOG = math.log(_HIST_BASE)
_HIST_FLOOR = 1e-9  # sub-nanosecond waits clamp into the first bucket


class _Hist:
    """One streaming histogram over seconds: sparse log-spaced buckets plus
    exact count/total/min/max. Quantiles walk the cumulative counts and
    return the bucket's geometric midpoint clamped to the observed range —
    bounded relative error at O(1) memory, however long the run."""

    __slots__ = ("count", "total", "vmin", "vmax", "buckets")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = 0.0
        self.buckets: Dict[int, int] = {}

    def observe(self, v: float) -> None:
        v = _HIST_FLOOR if v <= _HIST_FLOOR else float(v)
        idx = int(math.floor(math.log(v) / _HIST_LOG))
        self.buckets[idx] = self.buckets.get(idx, 0) + 1
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v

    def merge(self, other: "_Hist") -> None:
        for idx, n in other.buckets.items():
            self.buckets[idx] = self.buckets.get(idx, 0) + n
        self.count += other.count
        self.total += other.total
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)

    def percentile(self, q: float) -> float:
        if not self.count:
            return 0.0
        target = q / 100.0 * self.count
        cum = 0
        for idx in sorted(self.buckets):
            cum += self.buckets[idx]
            if cum >= target:
                rep = _HIST_BASE ** (idx + 0.5)
                return min(max(rep, self.vmin), self.vmax)
        return self.vmax

    def snapshot(self) -> Dict[str, Any]:
        if not self.count:
            return {"count": 0}
        return {
            "count": self.count,
            "total_s": round(self.total, 6),
            "mean_s": round(self.total / self.count, 6),
            "min_s": round(self.vmin, 9),
            "max_s": round(self.vmax, 6),
            "p50_s": round(self.percentile(50.0), 6),
            "p90_s": round(self.percentile(90.0), 6),
            "p99_s": round(self.percentile(99.0), 6),
        }


#: distinct per-program rows kept per table (LRU; the "*" overall row is a
#: separate slot and never evicted)
_PROGRAM_CAP = 64
_METRICS = ("sync", "dispatch", "compile")


class _HState:
    """One scope's histogram tables — mirrors ``telemetry._State``: record
    to every state on the stack, query the innermost."""

    __slots__ = ("path", "overall", "sync", "dispatch", "compile")

    def __init__(self, path: str = ""):
        self.path = path
        self.clear()

    def clear(self) -> None:
        self.overall: Dict[str, _Hist] = {m: _Hist() for m in _METRICS}
        self.sync: Dict[str, _Hist] = {}
        self.dispatch: "OrderedDict[str, _Hist]" = OrderedDict()
        self.compile: "OrderedDict[str, _Hist]" = OrderedDict()


def _merge_hstate(dst: _HState, src: _HState) -> None:
    for m in _METRICS:
        dst.overall[m].merge(src.overall[m])
        table, out = getattr(src, m), getattr(dst, m)
        for key, h in table.items():
            acc = out.get(key)
            if acc is None:
                acc = out[key] = _Hist()
            acc.merge(h)


_H_GLOBAL = _HState()
#: completed-scope accumulators, keyed by scope path (re-entry accumulates)
_H_SCOPES: Dict[str, _HState] = {}

# The scope stack is THREAD-LOCAL, mirroring telemetry's: concurrent serving
# sessions each scope their own histograms, records roll up into the shared
# global tables, and the archive merge runs under _H_LOCK.
_H_TLS = threading.local()
_H_GLOBAL_ONLY = (_H_GLOBAL,)
#: every scope state active on ANY thread (reset() must clear them all)
_H_ACTIVE: List[_HState] = []
_H_LOCK = threading.Lock()


def _h_stack() -> List[_HState]:
    stack = getattr(_H_TLS, "scopes", None)
    if stack is None:
        stack = _H_TLS.scopes = []
    return stack


def _h_states():
    stack = getattr(_H_TLS, "scopes", None)
    if not stack:
        return _H_GLOBAL_ONLY
    return [_H_GLOBAL] + stack


def _push_scope(path: str) -> None:
    """``telemetry.scope`` seam: scope the histograms alongside the counters."""
    st = _HState(path)
    _h_stack().append(st)
    with _H_LOCK:
        _H_ACTIVE.append(st)


def _pop_scope(path: str) -> None:
    stack = _h_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i].path == path:
            st = stack.pop(i)
            with _H_LOCK:
                for j in range(len(_H_ACTIVE) - 1, -1, -1):
                    if _H_ACTIVE[j] is st:
                        del _H_ACTIVE[j]
                        break
                acc = _H_SCOPES.get(path)
                if acc is None:
                    acc = _H_SCOPES[path] = _HState(path)
                _merge_hstate(acc, st)
            return


def _observe(metric: str, key: Optional[str], v: float) -> None:
    """Fold one latency sample into every active state ('*' overall row +
    the per-key row) and the SLO window. ``key`` is the sync trigger or the
    program key (None observes the overall row only)."""
    for st in _h_states():
        st.overall[metric].observe(v)
        if key is None:
            continue
        table = getattr(st, metric)
        h = table.get(key)
        if h is None:
            h = table[key] = _Hist()
            if metric != "sync":
                while len(table) > _PROGRAM_CAP:
                    table.popitem(last=False)
        elif metric != "sync":
            table.move_to_end(key)
        h.observe(v)
    _slo_observe(metric, v)


def _render_hists(st: _HState, metric: str) -> Dict[str, Any]:
    out = {"*": st.overall[metric].snapshot()}
    for key, h in getattr(st, metric).items():
        out[str(key)] = h.snapshot()
    return out


# ----------------------------------------------------------------------
# SLO gauges: rolling pass/breach windows per metric
# ----------------------------------------------------------------------
_SLO_LIMITS: Dict[str, Optional[float]] = {  # seconds; None = no SLO set
    "sync": _env_ms("HEAT_TPU_SLO_SYNC_MS"),
    "dispatch": _env_ms("HEAT_TPU_SLO_DISPATCH_MS"),
    "compile": _env_ms("HEAT_TPU_SLO_COMPILE_MS"),
}
_SLO_WINDOW_S = max(1.0, _env_float("HEAT_TPU_SLO_WINDOW_S", 300.0))
#: samples are ``(perf_counter_ts, seconds, tenant)`` — the tenant tag is
#: the serving session name active on the recording thread (None outside a
#: session), the label opsplane's per-tenant burn-rate windows group by
_SLO_SAMPLES: Dict[str, deque] = {m: deque(maxlen=2048) for m in _METRICS}
_SLO_BREACHES: Dict[str, int] = {m: 0 for m in _METRICS}

#: set-attribute seam (the memledger ``_MEM_HOOK`` pattern): serving
#: installs its ``_current_session_name`` here so SLO samples carry the
#: tenant without this module importing the serving layer
_TENANT_HOOK = None


def _slo_observe(metric: str, v: float) -> None:
    now = time.perf_counter()
    tenant = None
    if _TENANT_HOOK is not None:
        try:
            tenant = _TENANT_HOOK()
        # the tag is best-effort; a latency sample must never fail to land
        except Exception:  # noqa: BLE001
            tenant = None
    _SLO_SAMPLES[metric].append((now, v, tenant))
    limit = _SLO_LIMITS.get(metric)
    if limit is not None and v > limit:
        _SLO_BREACHES[metric] += 1
        telemetry.record_event(
            "slo_breach",
            metric=metric,
            value_ms=round(v * 1e3, 3),
            limit_ms=round(limit * 1e3, 3),
            tenant=tenant,
        )


def set_slo(
    sync_ms=_UNSET, dispatch_ms=_UNSET, compile_ms=_UNSET, window_s=None
) -> Dict[str, Optional[float]]:
    """Set SLO limits in-process (None clears one); returns the previous
    limits in seconds keyed by metric."""
    prev = dict(_SLO_LIMITS)
    for metric, value in (
        ("sync", sync_ms), ("dispatch", dispatch_ms), ("compile", compile_ms)
    ):
        if value is not _UNSET:
            _SLO_LIMITS[metric] = None if value is None else float(value) / 1e3
    if window_s is not None:
        global _SLO_WINDOW_S
        _SLO_WINDOW_S = max(1.0, float(window_s))
    return prev


def breach_fraction(
    metric: str,
    window_s: Optional[float] = None,
    tenant: Optional[str] = None,
) -> Optional[float]:
    """The fraction of SLO samples for ``metric`` inside the trailing
    ``window_s`` (default: the rolling SLO window) that breached the
    configured limit, optionally restricted to one ``tenant``. Returns
    ``None`` when no SLO is set or no samples land in the window — the
    autoscaler's direct p99-pressure read, cheaper than a full
    ``_slo_block`` and tenant-selective where the block is not. Pure
    module state: never forces, never initializes a backend."""
    limit = _SLO_LIMITS.get(metric)
    if limit is None:
        return None
    window = _SLO_WINDOW_S if window_s is None else max(0.0, float(window_s))
    now = time.perf_counter()
    n = bad = 0
    for item in list(_SLO_SAMPLES.get(metric, ())):
        if now - item[0] > window:
            continue
        if tenant is not None and (item[2] if len(item) > 2 else None) != tenant:
            continue
        n += 1
        if item[1] > limit:
            bad += 1
    return (bad / n) if n else None


def _slo_block() -> Dict[str, Any]:
    now = time.perf_counter()
    out: Dict[str, Any] = {"window_s": _SLO_WINDOW_S}
    for metric, dq in _SLO_SAMPLES.items():
        limit = _SLO_LIMITS[metric]
        vals = sorted(s[1] for s in dq if now - s[0] <= _SLO_WINDOW_S)
        entry: Dict[str, Any] = {
            "limit_ms": None if limit is None else round(limit * 1e3, 3),
            "recent": len(vals),
            "breaches_total": _SLO_BREACHES[metric],
        }
        if vals:
            def pct(q):
                return vals[min(len(vals) - 1, int(q / 100.0 * len(vals)))]

            entry["window_p50_ms"] = round(pct(50) * 1e3, 3)
            entry["window_p99_ms"] = round(pct(99) * 1e3, 3)
            if limit is not None:
                bad = sum(1 for v in vals if v > limit)
                entry["window_breaches"] = bad
                entry["ok_ratio"] = round(1.0 - bad / len(vals), 4)
        out[metric] = entry
    return out


# ----------------------------------------------------------------------
# dispatch→done resolution
# ----------------------------------------------------------------------
#: in-flight dispatches: cid -> (dispatch perf_counter ts, program key).
#: A blocking sync closing on one of these cids resolves the sample; the
#: LRU cap bounds fire-and-forget dispatches whose reads never block.
_DISPATCHED: "OrderedDict[int, Tuple[float, str]]" = OrderedDict()
_DISPATCHED_CAP = 512


def note_dispatch(program: str, cids, compiled: bool, dur_s: float) -> None:
    """``fusion.force`` seam, called right after the program call returns:
    start the dispatch→done clock for every batched root cid, and fold the
    call's duration into the per-program compile histogram when this force
    paid a fresh build (on a cache hit the call is an async enqueue — its
    duration measures nothing worth keeping)."""
    now = time.perf_counter()
    for cid in cids:
        if cid is not None:
            _DISPATCHED[cid] = (now - dur_s, str(program))
            _DISPATCHED.move_to_end(cid)
    while len(_DISPATCHED) > _DISPATCHED_CAP:
        _DISPATCHED.popitem(last=False)
    if compiled:
        _observe("compile", str(program), dur_s)


def _on_sync_end(kind: str, cid: Optional[int], dur: float) -> None:
    """``telemetry._SYNC_HOOK``: every closed blocking sync feeds the host
    wait histogram, and — when its cid matches an in-flight dispatch — the
    dispatch→done histogram under that program key."""
    now = time.perf_counter()
    _observe("sync", kind, dur)
    if cid is not None:
        rec = _DISPATCHED.pop(cid, None)
        if rec is not None:
            _observe("dispatch", rec[1], max(0.0, now - rec[0]))


# ----------------------------------------------------------------------
# stall watchdog
# ----------------------------------------------------------------------
_WD_ENABLED = (
    os.environ.get("HEAT_TPU_WATCHDOG", "1").strip().lower() not in _OFF_VALUES
)
_WD_DEADLINE_S = max(0.0, _env_float("HEAT_TPU_WATCHDOG_MS", 30000.0)) / 1e3
_WD_POLICIES = ("warn", "dump", "raise")
_WD_POLICY = os.environ.get("HEAT_TPU_WATCHDOG_POLICY", "warn").strip().lower() or "warn"
if _WD_POLICY not in _WD_POLICIES:
    warnings.warn(
        f"HEAT_TPU_WATCHDOG_POLICY: unknown policy {_WD_POLICY!r}; using 'warn'",
        stacklevel=2,
    )
    _WD_POLICY = "warn"
#: one attribute read decides the disarmed hot path
_WD_ACTIVE = _WD_ENABLED and _WD_DEADLINE_S > 0

_WD_COND = threading.Condition(threading.Lock())
_WD_GUARDS: Dict[int, "_Guard"] = {}
_WD_SEQ = itertools.count(1)  # atomic idents without a lock on the arm path
_WD_THREAD: Optional[threading.Thread] = None
_WD_STATS = {"arms": 0, "trips": 0}
_STALLS: deque = deque(maxlen=16)


class _NullGuard:
    """The disarmed fast path: a shared no-op context manager."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_GUARD = _NullGuard()


class _Guard:
    """One armed watch over a blocking region. The daemon trips it past its
    deadline; the guarded thread raises at exit under the ``raise`` policy
    (the daemon itself can only warn/dump — it must never throw into code
    it does not own)."""

    __slots__ = (
        "ident", "site", "deadline_s", "t0", "program", "cid", "cids",
        "thread_ident", "tripped",
    )

    def __init__(self, site, deadline_s, program, cid, cids):
        self.ident = next(_WD_SEQ)
        self.site = site
        self.deadline_s = deadline_s
        self.program = program
        self.cid = cid
        self.cids = tuple(cids)
        self.t0 = 0.0
        self.thread_ident = 0
        self.tripped = False

    def __enter__(self) -> "_Guard":
        self.t0 = time.perf_counter()
        self.thread_ident = threading.get_ident()
        # lock-free arm: dict set/pop are GIL-atomic and the daemon scans a
        # snapshot — the condition lock is only paid on the rare paths
        # (thread bring-up, short test-scale deadlines that must not ride
        # the 0.5s poll)
        _WD_GUARDS[self.ident] = self
        _WD_STATS["arms"] += 1
        if _WD_THREAD is None or not _WD_THREAD.is_alive():
            with _WD_COND:
                _ensure_thread()
        if self.deadline_s < 2.0:
            # short (test-scale) deadlines wake the daemon immediately;
            # production-scale ones ride its 0.5s poll granularity
            with _WD_COND:
                _WD_COND.notify()
        if resilience._ARMED:
            # the injectable stall: convert the fault into REALLY blocking
            # past this guard's deadline, so the daemon trips on its own
            # clock — tests exercise detection, not a mock of it. The bare
            # site stalls whichever guard arms first; the site-qualified one
            # (e.g. ``watchdog.stall:dispatch``) targets one arming point
            try:
                resilience.check("watchdog.stall")
                resilience.check("watchdog.stall:" + str(self.site))
            except resilience.FaultInjected:
                time.sleep(self.deadline_s * 1.5 + 0.05)
        return self

    def __exit__(self, exc_type, exc, tb):
        _WD_GUARDS.pop(self.ident, None)
        if self.tripped and exc_type is None and _WD_POLICY == "raise":
            raise StallError(
                f"{self.site} blocked past its {self.deadline_s:.3f}s watchdog "
                f"deadline (program {self.program or '<unknown>'}); full "
                "diagnosis via health_runtime.last_stall()"
            )
        return False


def watch(site: str, program=None, cid=None, cids=(), deadline_ms=None):
    """Arm the watchdog around a blocking region::

        with health_runtime.watch("dispatch", program=key, cids=cids):
            values = prog(*leaves)

    Returns a shared no-op guard when the watchdog is disarmed (one
    attribute read + one call on the hot path). ``deadline_ms`` overrides
    the ambient deadline for this region only — and arms the guard even
    when the ambient watchdog is disabled."""
    if deadline_ms is None:
        if not _WD_ACTIVE:
            return _NULL_GUARD
        deadline_s = _WD_DEADLINE_S
    else:
        deadline_s = max(0.001, float(deadline_ms) / 1e3)
    return _Guard(site, deadline_s, program, cid, cids)


def reset_guards() -> int:
    """Drop every armed watchdog guard, returning how many were dropped.

    The elastic reform path (core/elastic.py): guards armed over regions
    that the drain abandoned (a collective that will never complete on the
    lost device) must not trip minutes later against the re-formed world.
    The owning threads' ``_Guard.__exit__`` still runs and pops a missing
    ident harmlessly; with ``tripped`` never set, no stale ``StallError``
    surfaces."""
    dropped = len(_WD_GUARDS)
    _WD_GUARDS.clear()
    return dropped


def _ensure_thread() -> None:
    global _WD_THREAD
    if _WD_THREAD is None or not _WD_THREAD.is_alive():
        _WD_THREAD = threading.Thread(
            target=_wd_loop, name="heat-tpu-watchdog", daemon=True
        )
        _WD_THREAD.start()


def _wd_loop() -> None:
    while True:
        due: List[_Guard] = []
        now = time.perf_counter()
        wait_s = 0.5
        # snapshot: guards arm/disarm lock-free, so never iterate the live
        # dict (list() of a dict is one GIL-atomic C call)
        for g in list(_WD_GUARDS.values()):
            if g.tripped or g.t0 == 0.0:
                continue
            remaining = g.t0 + g.deadline_s - now
            if remaining <= 0:
                g.tripped = True
                due.append(g)
            elif remaining < wait_s:
                wait_s = remaining
        if not due:
            with _WD_COND:
                _WD_COND.wait(timeout=max(0.005, wait_s))
            continue
        for g in due:  # diagnose OUTSIDE the lock: arms must never block
            try:
                _trip(g)
            except Exception as exc:  # the monitor survives its own diagnosis
                warnings.warn(f"watchdog diagnosis failed: {exc!r}", stacklevel=1)


def _program_of_cid(cid) -> Optional[str]:
    if cid is None:
        return None
    rec = _DISPATCHED.get(cid)
    if rec is not None:
        return rec[1]
    for ev in reversed(_RING):
        if ev.get("kind") == "dispatch" and (
            ev.get("cid") == cid or cid in (ev.get("cids") or ())
        ):
            return ev.get("program")
    return None


def _pending_roots() -> List[dict]:
    """Snapshot the still-pending DAG roots (cheap metadata walk over
    fusion's live-root registry — never forces anything)."""
    try:
        from . import fusion

        out = []
        for key in fusion._live_root_keys():
            wrapper = fusion._LIVE_ROOTS.get(key)
            payload = getattr(wrapper, "_payload", None)
            if isinstance(payload, fusion.LazyArray) and payload._value is None:
                out.append({"cid": payload.cid, "depth": payload.depth})
            if len(out) >= 32:
                break
        return out
    except Exception as exc:  # half-imported runtime: diagnose what exists
        return [{"error": repr(exc)}]


def _collective_trail(limit: int = 16) -> Dict[str, Any]:
    """This host's recent collective activity from the flight ring — the
    local half of the cross-host parity comparison (a stalled collective
    shows hosts whose ``counts`` diverge; ``trace_collective_parity`` makes
    the same comparison over merged dumps)."""
    recent: List[list] = []
    counts: Dict[str, int] = {}
    for ev in _RING:
        kind = ev.get("kind")
        if kind in ("collective", "fused_collective"):
            op = str(ev.get("op"))
            recent.append([kind, op, ev.get("cid")])
            counts[op] = counts.get(op, 0) + int(ev.get("count", 1) or 1)
    return {"recent": recent[-limit:], "counts": counts}


def _stack_of(thread_ident: int) -> List[str]:
    try:
        frame = sys._current_frames().get(thread_ident)
        if frame is None:
            return []
        return [ln.rstrip() for ln in traceback.format_stack(frame)][-12:]
    except Exception:  # pragma: no cover - interpreter-dependent
        return []


def _trip(g: _Guard) -> None:
    waited = time.perf_counter() - g.t0
    diag = {
        "ts": time.time(),
        "site": g.site,
        "waited_s": round(waited, 4),
        "deadline_s": g.deadline_s,
        "policy": _WD_POLICY,
        "program": g.program or _program_of_cid(g.cid),
        "cid": g.cid,
        "cids": list(g.cids),
        "pending_roots": _pending_roots(),
        "collective_trail": _collective_trail(),
        "stack": _stack_of(g.thread_ident),
    }
    _STALLS.append(diag)
    _WD_STATS["trips"] += 1
    telemetry.record_event(
        "stall",
        site=g.site,
        program=diag["program"],
        cid=g.cid,
        waited_s=diag["waited_s"],
    )
    pending = ", ".join(str(r.get("cid")) for r in diag["pending_roots"][:6]) or "none"
    warnings.warn(
        StallWarning(
            f"watchdog: {g.site} has been blocked {waited:.2f}s (deadline "
            f"{g.deadline_s:.2f}s); in-flight program "
            f"{diag['program'] or '<unknown>'}, pending root cids [{pending}]. "
            "Full diagnosis via health_runtime.last_stall()"
        ),
        stacklevel=2,
    )
    if _WD_POLICY in ("dump", "raise"):
        auto_dump("stall")


def set_watchdog(deadline_ms=_UNSET, policy=_UNSET, enabled=_UNSET):
    """Configure the watchdog in-process; returns the previous
    ``(deadline_ms, policy, enabled)`` triple (pass it back to restore)."""
    global _WD_DEADLINE_S, _WD_POLICY, _WD_ENABLED, _WD_ACTIVE
    prev = (_WD_DEADLINE_S * 1e3, _WD_POLICY, _WD_ENABLED)
    if deadline_ms is not _UNSET:
        _WD_DEADLINE_S = max(0.0, float(deadline_ms)) / 1e3
    if policy is not _UNSET:
        if policy not in _WD_POLICIES:
            raise ValueError(f"watchdog policy must be one of {_WD_POLICIES}")
        _WD_POLICY = policy
    if enabled is not _UNSET:
        _WD_ENABLED = bool(enabled)
    _WD_ACTIVE = _WD_ENABLED and _WD_DEADLINE_S > 0
    return prev


def watchdog_stats() -> Dict[str, Any]:
    return {
        "enabled": _WD_ENABLED,
        "deadline_ms": round(_WD_DEADLINE_S * 1e3, 3),
        "policy": _WD_POLICY,
        "armed": len(_WD_GUARDS),
        "arms": _WD_STATS["arms"],
        "trips": _WD_STATS["trips"],
    }


def stalls() -> List[dict]:
    """Every stall diagnosis this session (bounded, newest last)."""
    return list(_STALLS)


def last_stall() -> Optional[dict]:
    """The most recent stall diagnosis, or None."""
    return _STALLS[-1] if _STALLS else None


# ----------------------------------------------------------------------
# the report surface
# ----------------------------------------------------------------------
def health_block(global_view: bool = False) -> Dict[str, Any]:
    """The ``report()["health"]`` block: flight-ring occupancy, watchdog
    state + last stall, the three latency histogram tables ('*' = overall
    row; ``dispatch``/``compile`` keyed by program key, ``sync`` by
    trigger), and the rolling SLO gauges. Inside a ``telemetry.scope`` the
    histograms are the scope's own isolated view unless ``global_view``."""
    st = _H_GLOBAL if global_view else _h_states()[-1]
    return {
        "flight": flight_stats(),
        "watchdog": dict(watchdog_stats(), last_stall=last_stall()),
        "sync": _render_hists(st, "sync"),
        "dispatch": _render_hists(st, "dispatch"),
        "compile": _render_hists(st, "compile"),
        "slo": _slo_block(),
    }


def reset() -> None:
    """Zero the session state — ring, drop/dump counters, histograms
    (every active scope state + archives), SLO windows, stall log, watchdog
    arm/trip counters. Configuration (flight enablement, ring cap, watchdog
    deadline/policy, SLO limits, dump dir) survives — the same
    config-vs-session split as ``memledger.reset``."""
    global _RING_DROPPED, _DUMP_COUNT, _LAST_DUMP
    _RING.clear()
    _RING_DROPPED = 0
    _DUMP_COUNT = 0
    _LAST_DUMP = None
    _LAST_AUTO_DUMP_TS.clear()
    _DISPATCHED.clear()
    _H_GLOBAL.clear()
    with _H_LOCK:
        for st in list(_H_ACTIVE):
            st.clear()
        _H_SCOPES.clear()
    for dq in _SLO_SAMPLES.values():
        dq.clear()
    for m in _SLO_BREACHES:
        _SLO_BREACHES[m] = 0
    _STALLS.clear()
    for k in _WD_STATS:
        _WD_STATS[k] = 0


# joined-surface wiring (set-attribute, like memledger's _MEM_HOOK): the
# telemetry module stays import-order independent of the health layer
telemetry._SYNC_HOOK = _on_sync_end
_install_hook()

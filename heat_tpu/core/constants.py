"""Mathematical constants (reference: heat/core/constants.py)."""

import math

INF = float("inf")
NAN = float("nan")
NINF = -float("inf")
PI = math.pi
E = math.e

inf = INF
nan = NAN
pi = PI
e = E

# capitalized aliases (reference constants.py:7,26,38)
Inf = INF
Infty = INF
Infinity = INF
NaN = NAN
Euler = E

__all__ = [
    "e",
    "Euler",
    "inf",
    "Inf",
    "Infty",
    "Infinity",
    "nan",
    "NaN",
    "pi",
    "E",
    "INF",
    "NAN",
    "NINF",
    "PI",
]

"""Mathematical constants (reference: heat/core/constants.py)."""

import math

INF = float("inf")
NAN = float("nan")
NINF = -float("inf")
PI = math.pi
E = math.e

inf = INF
nan = NAN
pi = PI
e = E

__all__ = ["e", "inf", "nan", "pi", "E", "INF", "NAN", "NINF", "PI"]

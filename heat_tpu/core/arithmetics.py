"""Arithmetic operations (reference: heat/core/arithmetics.py:63-1003).

Every function dispatches through the §L3 engines; distributed behavior
(cumsum Exscan, diff neighbor exchange in the reference :224-429) is a single
sharded ``jnp`` call here.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp

from . import types
from ._operations import __binary_op as _binary_op
from ._operations import __cum_op as _cum_op
from ._operations import __local_op as _local_op
from ._operations import __reduce_op as _reduce_op
from .dndarray import DNDarray

__all__ = [
    "add",
    "bitwise_and",
    "bitwise_not",
    "bitwise_or",
    "bitwise_xor",
    "copysign",
    "cumprod",
    "cumproduct",
    "cumsum",
    "diff",
    "div",
    "divide",
    "divmod",
    "floordiv",
    "floor_divide",
    "fmod",
    "gcd",
    "hypot",
    "invert",
    "lcm",
    "left_shift",
    "mod",
    "mul",
    "multiply",
    "nan_to_num",
    "nanprod",
    "nansum",
    "neg",
    "negative",
    "pos",
    "positive",
    "pow",
    "power",
    "prod",
    "remainder",
    "right_shift",
    "sub",
    "subtract",
    "sum",
]


def add(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise addition (reference arithmetics.py:63)."""
    return _binary_op(jnp.add, t1, t2, out=out, where=where)


def sub(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise subtraction (reference arithmetics.py:905)."""
    return _binary_op(jnp.subtract, t1, t2, out=out, where=where)


subtract = sub


def mul(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise multiplication (reference arithmetics.py:679)."""
    return _binary_op(jnp.multiply, t1, t2, out=out, where=where)


multiply = mul


def div(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise true division (reference arithmetics.py:295)."""
    return _binary_op(jnp.true_divide, t1, t2, out=out, where=where)


divide = div


def divmod(t1, t2):
    """Simultaneous floordiv and mod (reference arithmetics.py:345)."""
    return (floordiv(t1, t2), mod(t1, t2))


def floordiv(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise floor division (reference arithmetics.py:430)."""
    return _binary_op(jnp.floor_divide, t1, t2, out=out, where=where)


floor_divide = floordiv


def fmod(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise C-style remainder (sign of dividend) (reference arithmetics.py:470)."""
    return _binary_op(jnp.fmod, t1, t2, out=out, where=where)


def mod(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise Python-style modulo (sign of divisor) (reference arithmetics.py:639)."""
    return _binary_op(jnp.mod, t1, t2, out=out, where=where)


remainder = mod


def pow(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise power (reference arithmetics.py:759)."""
    return _binary_op(jnp.power, t1, t2, out=out, where=where)


power = pow


def neg(a, out=None) -> DNDarray:
    """Elementwise negation (reference arithmetics.py:714)."""
    return _local_op(jnp.negative, a, out=out, no_cast=True)


negative = neg


def pos(a, out=None) -> DNDarray:
    """Elementwise unary plus (reference arithmetics.py:736)."""
    return _local_op(jnp.positive, a, out=out, no_cast=True)


positive = pos


def bitwise_and(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise bitwise AND (reference arithmetics.py:103)."""
    _check_bitwise(t1, t2)
    return _binary_op(jnp.bitwise_and, t1, t2, out=out, where=where)


def bitwise_or(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise bitwise OR (reference arithmetics.py:141)."""
    _check_bitwise(t1, t2)
    return _binary_op(jnp.bitwise_or, t1, t2, out=out, where=where)


def bitwise_xor(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise bitwise XOR (reference arithmetics.py:179)."""
    _check_bitwise(t1, t2)
    return _binary_op(jnp.bitwise_xor, t1, t2, out=out, where=where)


def _check_bitwise(*ops):
    for op in ops:
        dt = op.dtype if isinstance(op, DNDarray) else types.heat_type_of(op)
        if not (types.issubdtype(dt, types.integer) or types.issubdtype(dt, types.bool)):
            raise TypeError("Operation is not supported for float types")


def invert(a, out=None) -> DNDarray:
    """Elementwise bitwise NOT (reference arithmetics.py:521)."""
    _check_bitwise(a)
    if a.dtype is types.bool:
        return _local_op(jnp.logical_not, a, out=out, no_cast=True)
    return _local_op(jnp.invert, a, out=out, no_cast=True)


bitwise_not = invert


def left_shift(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise left bit-shift (reference arithmetics.py:558)."""
    _check_shift(t1, t2)
    return _binary_op(jnp.left_shift, t1, t2, out=out, where=where)


def right_shift(t1, t2, out=None, where=None) -> DNDarray:
    """Elementwise right bit-shift (reference arithmetics.py:855)."""
    _check_shift(t1, t2)
    return _binary_op(jnp.right_shift, t1, t2, out=out, where=where)


def _check_shift(t1, t2):
    for op in (t1, t2):
        dt = op.dtype if isinstance(op, DNDarray) else types.heat_type_of(op)
        if types.issubdtype(dt, types.bool):
            raise TypeError("Operation is not supported for boolean types")
        if not types.issubdtype(dt, types.integer):
            raise TypeError("Operation is only supported for integer types")


def copysign(t1, t2, out=None, where=None) -> DNDarray:
    """Magnitude of t1 with sign of t2 (reference arithmetics.py:219)."""
    dt1 = t1.dtype if isinstance(t1, DNDarray) else types.heat_type_of(t1)
    if types.issubdtype(dt1, types.complexfloating):
        raise TypeError("copysign is not defined for complex types")
    return _binary_op(jnp.copysign, t1, t2, out=out, where=where)


def cumprod(a, axis: int, dtype=None, out=None) -> DNDarray:
    """Cumulative product along axis (reference arithmetics.py:253; the
    reference Exscans partial products — XLA decomposes the sharded scan)."""
    return _cum_op(jnp.cumprod, a, axis, out=out, dtype=dtype)


cumproduct = cumprod


def cumsum(a, axis: int, dtype=None, out=None) -> DNDarray:
    """Cumulative sum along axis (reference arithmetics.py:274)."""
    return _cum_op(jnp.cumsum, a, axis, out=out, dtype=dtype)


def diff(a, n: int = 1, axis: int = -1, prepend=None, append=None) -> DNDarray:
    """n-th discrete difference (reference arithmetics.py:293-429: one-row
    neighbor exchange over MPI; here the shifted subtraction's boundary comms
    are XLA's). ``prepend``/``append`` extend the array along ``axis`` before
    differencing, numpy-style."""
    if n == 0:
        return a
    if n < 0:
        raise ValueError(f"diff requires that n be a positive number, got {n}")
    from . import sanitation
    from .dndarray import _ensure_split

    kw = {}
    for key, val in (("prepend", prepend), ("append", append)):
        if val is not None:
            kw[key] = val.larray if isinstance(val, DNDarray) else jnp.asarray(val)

    # diff is a STENCIL, not an elementwise op: it must never see the padding
    # of a ragged payload (with prepend/append the result shape can coincide
    # with the padded shape, defeating the engine's shape heuristic), so it
    # computes on the logical view explicitly
    sanitation.sanitize_in(a)
    result = jnp.diff(a.larray, n=n, axis=axis, **kw)
    split = a.split  # diff never changes rank
    result = _ensure_split(result, split, a.comm)
    return DNDarray(
        result,
        tuple(result.shape),
        types.canonical_heat_type(result.dtype),
        split,
        a.device,
        a.comm,
    )


def gcd(t1, t2, out=None, where=None) -> DNDarray:
    """Greatest common divisor (reference arithmetics.py:498)."""
    _check_shift(t1, t2)
    return _binary_op(jnp.gcd, t1, t2, out=out, where=where)


def hypot(t1, t2, out=None, where=None) -> DNDarray:
    """sqrt(t1^2 + t2^2) (reference arithmetics.py:514)."""
    dt1 = t1.dtype if isinstance(t1, DNDarray) else types.heat_type_of(t1)
    dt2 = t2.dtype if isinstance(t2, DNDarray) else types.heat_type_of(t2)
    for dt in (dt1, dt2):
        if types.issubdtype(dt, types.integer) or types.issubdtype(dt, types.bool):
            raise TypeError("hypot is not supported for integer types")
    return _binary_op(jnp.hypot, t1, t2, out=out, where=where)


def lcm(t1, t2, out=None, where=None) -> DNDarray:
    """Least common multiple (reference arithmetics.py:540)."""
    _check_shift(t1, t2)
    return _binary_op(jnp.lcm, t1, t2, out=out, where=where)


def nan_to_num(a, nan=0.0, posinf=None, neginf=None, out=None) -> DNDarray:
    """Replace NaN/Inf with finite numbers (reference arithmetics.py:702).
    The replacement values ride as static kwargs (cacheable under fusion)."""
    return _local_op(
        jnp.nan_to_num, a, out=out, no_cast=True, nan=nan, posinf=posinf, neginf=neginf
    )


def nanprod(a, axis=None, out=None, keepdims=False) -> DNDarray:
    """Product ignoring NaN (reference arithmetics.py:726)."""
    return _reduce_op(jnp.nanprod, a, axis, out=out, keepdims=keepdims)


def nansum(a, axis=None, out=None, keepdims=False) -> DNDarray:
    """Sum ignoring NaN (reference arithmetics.py:745)."""
    return _reduce_op(jnp.nansum, a, axis, out=out, keepdims=keepdims)


def prod(a, axis=None, out=None, keepdims=False, keepdim=None) -> DNDarray:
    """Product of elements over axis (reference arithmetics.py:803).
    ``keepdim`` is the reference's torch-style alias for ``keepdims``."""
    return _reduce_op(jnp.prod, a, axis, out=out, keepdims=keepdims if keepdim is None else keepdim)


def sum(a, axis=None, out=None, keepdims=False, keepdim=None) -> DNDarray:
    """Sum of elements over axis (reference arithmetics.py:946; cross-split
    reduction is the reference's Allreduce, here an XLA psum). ``keepdim``
    is the reference's torch-style alias for ``keepdims``."""
    return _reduce_op(jnp.sum, a, axis, out=out, keepdims=keepdims if keepdim is None else keepdim)

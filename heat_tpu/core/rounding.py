"""Rounding and sign operations (reference: heat/core/rounding.py)."""

from __future__ import annotations

import jax.numpy as jnp

from . import types
from ._operations import __binary_op as _binary_op
from ._operations import __local_op as _local_op
from .dndarray import DNDarray

__all__ = ["abs", "absolute", "ceil", "clip", "fabs", "floor", "modf", "round", "sgn", "sign", "trunc"]


def abs(x, out=None, dtype=None) -> DNDarray:
    """Elementwise absolute value (reference rounding.py:23)."""
    if dtype is not None and not issubclass(types.canonical_heat_type(dtype), types.generic):
        raise TypeError("dtype must be a heat data type")
    res = _local_op(jnp.abs, x, out=out, no_cast=True)
    if dtype is not None and out is None:
        res = res.astype(dtype)
    return res


absolute = abs


def fabs(x, out=None) -> DNDarray:
    """Elementwise absolute value, float result (reference rounding.py:92)."""
    return _local_op(jnp.abs, x, out=out, no_cast=False)


def ceil(x, out=None) -> DNDarray:
    """Elementwise ceiling (reference rounding.py:59)."""
    return _local_op(jnp.ceil, x, out=out)


def clip(x, min=None, max=None, out=None) -> DNDarray:
    """Clip values to [min, max] (reference rounding.py:118). Scalar bounds
    ride as static kwargs (cacheable under the fusion engine); array bounds
    make the kwargs unhashable, which routes to the eager engine unchanged."""
    if min is None and max is None:
        raise ValueError("either min or max must be set")
    lo = min.larray if isinstance(min, DNDarray) else min
    hi = max.larray if isinstance(max, DNDarray) else max
    return _local_op(jnp.clip, x, out=out, no_cast=True, min=lo, max=hi)


def floor(x, out=None) -> DNDarray:
    """Elementwise floor (reference rounding.py:151)."""
    return _local_op(jnp.floor, x, out=out)


def modf(x, out=None):
    """Fractional and integral parts (reference rounding.py:177)."""
    from .dndarray import DNDarray as D

    if not isinstance(x, D):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    frac = _local_op(_modf_frac, x)
    whole = _local_op(_modf_whole, x)
    if out is not None:
        if not isinstance(out, tuple) or len(out) != 2:
            raise TypeError(f"expected out to be None or a tuple of two DNDarrays, but was {type(out)}")
        out[0].larray = frac.larray
        out[1].larray = whole.larray
        return out
    return (frac, whole)


def _modf_frac(a):
    return jnp.modf(a)[0]


def _modf_whole(a):
    return jnp.modf(a)[1]


def round(x, decimals: int = 0, out=None, dtype=None) -> DNDarray:
    """Round to given decimals (reference rounding.py:220)."""
    res = _local_op(jnp.round, x, out=out, decimals=decimals)
    if dtype is not None and out is None:
        res = res.astype(dtype)
    return res


def sgn(x, out=None) -> DNDarray:
    """Sign, complex-aware (reference rounding.py:266)."""
    return _local_op(jnp.sign, x, out=out, no_cast=True)


def _sign_of_real(a):
    return jnp.sign(a.real).astype(a.dtype)


def sign(x, out=None) -> DNDarray:
    """Sign of elements; for complex, sign of the real part (reference rounding.py:290)."""
    if types.heat_type_is_complexfloating(x.dtype):
        return _local_op(_sign_of_real, x, out=out, no_cast=True)
    return _local_op(jnp.sign, x, out=out, no_cast=True)


def trunc(x, out=None) -> DNDarray:
    """Truncate toward zero (reference rounding.py:321)."""
    return _local_op(jnp.trunc, x, out=out)

"""Exponential and logarithmic functions (reference: heat/core/exponential.py).

Every function routes through the L3 engines with stable ``jnp`` callables,
so under the eager fusion recorder (``core/fusion.py``) these ops defer into
the surrounding chain and key stably into the sharded-program cache.
"""

from __future__ import annotations

import jax.numpy as jnp

from ._operations import __binary_op as _binary_op
from ._operations import __local_op as _local_op
from .dndarray import DNDarray

__all__ = [
    "exp",
    "exp2",
    "expm1",
    "log",
    "log10",
    "log1p",
    "log2",
    "logaddexp",
    "logaddexp2",
    "sqrt",
    "square",
]


def exp(x, out=None) -> DNDarray:
    """Elementwise e**x (reference exponential.py:14)."""
    return _local_op(jnp.exp, x, out=out)


def exp2(x, out=None) -> DNDarray:
    """Elementwise 2**x (reference exponential.py:64)."""
    return _local_op(jnp.exp2, x, out=out)


def expm1(x, out=None) -> DNDarray:
    """Elementwise e**x - 1 (reference exponential.py:39)."""
    return _local_op(jnp.expm1, x, out=out)


def log(x, out=None) -> DNDarray:
    """Natural logarithm (reference exponential.py:89)."""
    return _local_op(jnp.log, x, out=out)


def log2(x, out=None) -> DNDarray:
    """Base-2 logarithm (reference exponential.py:142)."""
    return _local_op(jnp.log2, x, out=out)


def log10(x, out=None) -> DNDarray:
    """Base-10 logarithm (reference exponential.py:116)."""
    return _local_op(jnp.log10, x, out=out)


def log1p(x, out=None) -> DNDarray:
    """log(1 + x) (reference exponential.py:168)."""
    return _local_op(jnp.log1p, x, out=out)


def logaddexp(x1, x2, out=None) -> DNDarray:
    """log(exp(x1) + exp(x2)) (reference exponential.py:193)."""
    return _binary_op(jnp.logaddexp, x1, x2, out=out)


def logaddexp2(x1, x2, out=None) -> DNDarray:
    """log2(2**x1 + 2**x2) (reference exponential.py:223)."""
    return _binary_op(jnp.logaddexp2, x1, x2, out=out)


def sqrt(x, out=None) -> DNDarray:
    """Elementwise square root (reference exponential.py:253)."""
    return _local_op(jnp.sqrt, x, out=out)


def square(x, out=None) -> DNDarray:
    """Elementwise square (reference exponential.py:278)."""
    return _local_op(jnp.square, x, out=out, no_cast=True)

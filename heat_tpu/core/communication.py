"""Communication layer: a JAX device-mesh in place of the reference's MPI wrapper.

The reference funnels every byte through ``MPICommunication`` (reference
heat/core/communication.py:88-1891): torch-tensor-aware Send/Recv, Allreduce,
Allgatherv, Alltoallw with derived datatypes, etc. On TPU none of that
choreography is user-visible — a single-controller JAX program owns *all*
devices, arrays are globally addressed ``jax.Array``s under a
``NamedSharding``, and XLA/GSPMD inserts the collectives over ICI/DCN.

What remains for this layer to own:

* the :class:`jax.sharding.Mesh` (1-D, axis name ``"split"``) and the mapping
  ``split: int|None -> NamedSharding`` that realises the reference's single
  split-axis model (reference dndarray.py:51-52);
* ``chunk()`` — the block-distribution rule (reference communication.py:161-209).
  GSPMD shards a dimension of size ``n`` over ``k`` devices in blocks of
  ``ceil(n/k)`` with the tail device(s) short (vs. the reference's
  remainder-on-lowest-ranks rule); ``chunk`` reports the *actual* GSPMD layout
  so ``lshape_map`` is truthful;
* explicit collective *helpers* (`allreduce`, `exscan`, ...) used by the few
  algorithms whose schedule is the algorithm (ring cdist, TSQR, DASO) — these
  are thin shims over ``jax.lax`` collectives inside ``shard_map``.

``rank``/``size``: single-controller JAX has one Python process; ``rank`` is
the process index (0 on a single host, ``jax.process_index()`` multi-host) and
``size`` is the number of mesh devices — the parallelism degree, which is what
reference scripts branch on.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# NOTE: the lazy singletons (MESH_WORLD/MPI_WORLD/...) are deliberately NOT in
# __all__ — a star import would force backend initialization at import time.
# They are reachable as module attributes (heat_tpu.MPI_WORLD works via the
# package-level __getattr__).
__all__ = [
    "Communication",
    "MeshCommunication",
    "get_comm",
    "sanitize_comm",
    "use_comm",
]

SPLIT_AXIS = "split"


class Communication:
    """Base class for communication contexts (reference communication.py:88-101)."""

    @staticmethod
    def is_distributed() -> bool:
        raise NotImplementedError()

    def chunk(self, shape, split, rank=None):
        raise NotImplementedError()


class MeshCommunication(Communication):
    """A communication context backed by a 1-D JAX device mesh.

    Parameters
    ----------
    devices : sequence of jax.Device, optional
        Devices forming the mesh. Defaults to all devices of the default
        backend (every TPU chip in the slice / every forced-host CPU device).
    axis_name : str
        Mesh axis name the ``split`` dimension of every DNDarray maps onto.
    """

    def __init__(self, devices: Optional[Sequence] = None, axis_name: str = SPLIT_AXIS):
        if devices is None:
            devices = jax.devices()
        self._devices = tuple(devices)
        self.axis_name = axis_name
        self.mesh = Mesh(np.asarray(self._devices), (axis_name,))
        try:
            self.rank = jax.process_index()
        except Exception:  # pragma: no cover
            self.rank = 0

    # ------------------------------------------------------------------
    # topology
    # ------------------------------------------------------------------
    @property
    def size(self) -> int:
        """Parallelism degree: number of devices along the split axis."""
        return len(self._devices)

    @property
    def devices(self):
        return self._devices

    def is_distributed(self) -> bool:
        return self.size > 1

    # ------------------------------------------------------------------
    # sharding construction
    # ------------------------------------------------------------------
    def spec(self, ndim: int, split: Optional[int]) -> PartitionSpec:
        """PartitionSpec placing mesh axis on dimension ``split``."""
        if split is None:
            return PartitionSpec()
        entries: List[Optional[str]] = [None] * ndim
        entries[split] = self.axis_name
        return PartitionSpec(*entries)

    def sharding(self, ndim: int, split: Optional[int]) -> NamedSharding:
        """NamedSharding realizing a 1-D block distribution along ``split``
        (the TPU equivalent of the reference's split attribute semantics,
        reference communication.py:193-203)."""
        return NamedSharding(self.mesh, self.spec(ndim, split))

    # ------------------------------------------------------------------
    # block-distribution arithmetic (reference communication.py:161-209)
    # ------------------------------------------------------------------
    def counts_displs_shape(
        self, shape: Sequence[int], split: int
    ) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Per-device counts and displacements along ``split`` under GSPMD's
        ceil-division block rule."""
        n = shape[split]
        k = self.size
        block = -(-n // k) if n else 0
        counts = tuple(max(0, min(block, n - i * block)) for i in range(k))
        displs = tuple(min(i * block, n) for i in range(k))
        return counts, displs

    def chunk(
        self, shape: Sequence[int], split: Optional[int], rank: Optional[int] = None
    ) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """Offset, local shape and slices of device ``rank``'s shard.

        Mirrors reference communication.py:161-209 but reports GSPMD's actual
        ceil-division layout. With ``split=None`` the full array is returned.
        """
        shape = tuple(int(s) for s in shape)
        if split is None:
            return 0, shape, tuple(slice(0, s) for s in shape)
        rank = 0 if rank is None else rank
        counts, displs = self.counts_displs_shape(shape, split)
        start, count = displs[rank], counts[rank]
        lshape = list(shape)
        lshape[split] = count
        slices = [slice(0, s) for s in shape]
        slices[split] = slice(start, start + count)
        return start, tuple(lshape), tuple(slices)

    def lshape_map(self, shape: Sequence[int], split: Optional[int]) -> np.ndarray:
        """(size, ndim) array of each device's local shape (reference
        dndarray.py:569-600 computes this with an Allreduce; here it is pure
        arithmetic because the layout is deterministic)."""
        out = np.empty((self.size, len(shape)), dtype=np.int64)
        for r in range(self.size):
            _, lshape, _ = self.chunk(shape, split, rank=r)
            out[r] = lshape
        return out

    # ------------------------------------------------------------------
    # group creation (reference communication.py:445-456)
    # ------------------------------------------------------------------
    def split_comm(self, n_groups: int) -> "MeshCommunication":
        """Return a communication context over the first ``size // n_groups``
        devices — the analog of MPI ``Split`` for simple subgrouping."""
        group = max(1, self.size // n_groups)
        return MeshCommunication(self._devices[:group], axis_name=self.axis_name)

    def __repr__(self) -> str:
        plat = self._devices[0].platform if self._devices else "?"
        return f"MeshCommunication({self.size} {plat} device(s), axis={self.axis_name!r})"


def _world() -> MeshCommunication:
    return MeshCommunication()


# Lazily constructed singletons: jax.devices() initializes the backend, which
# must not happen at import time (tests flip the platform first).
MESH_WORLD: Optional[MeshCommunication] = None
MESH_SELF: Optional[MeshCommunication] = None

__default_comm: Optional[MeshCommunication] = None


def get_comm() -> MeshCommunication:
    """The current global default communication context (reference
    communication.py:1919-1925)."""
    global __default_comm, MESH_WORLD, MESH_SELF
    if __default_comm is None:
        if MESH_WORLD is None:
            MESH_WORLD = _world()
            MESH_SELF = MeshCommunication(jax.devices()[:1])
        __default_comm = MESH_WORLD
    return __default_comm


def sanitize_comm(comm: Optional[Communication]) -> MeshCommunication:
    """Validate/normalize a communication context (reference communication.py:1900)."""
    if comm is None:
        return get_comm()
    if isinstance(comm, MeshCommunication):
        return comm
    raise TypeError(f"Given communication object is not valid: {comm!r}")


def use_comm(comm: Optional[Communication] = None) -> None:
    """Set the globally-used default communication context (reference
    communication.py:1927-1937)."""
    global __default_comm
    __default_comm = sanitize_comm(comm)


def __getattr__(name: str):
    # MPI_WORLD/MPI_SELF exist for reference-API compatibility; build lazily.
    if name in ("MPI_WORLD",):
        return get_comm()
    if name in ("MPI_SELF",):
        get_comm()
        return MESH_SELF
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""Communication layer: a JAX device-mesh in place of the reference's MPI wrapper.

The reference funnels every byte through ``MPICommunication`` (reference
heat/core/communication.py:88-1891): torch-tensor-aware Send/Recv, Allreduce,
Allgatherv, Alltoallw with derived datatypes, etc. On TPU none of that
choreography is user-visible — a single-controller JAX program owns *all*
devices, arrays are globally addressed ``jax.Array``s under a
``NamedSharding``, and XLA/GSPMD inserts the collectives over ICI/DCN.

What remains for this layer to own:

* the :class:`jax.sharding.Mesh` (1-D, axis name ``"split"``) and the mapping
  ``split: int|None -> NamedSharding`` that realises the reference's single
  split-axis model (reference dndarray.py:51-52);
* ``chunk()`` — the block-distribution rule (reference communication.py:161-209).
  GSPMD shards a dimension of size ``n`` over ``k`` devices in blocks of
  ``ceil(n/k)`` with the tail device(s) short (vs. the reference's
  remainder-on-lowest-ranks rule); ``chunk`` reports the *actual* GSPMD layout
  so ``lshape_map`` is truthful;
* explicit collective *helpers* (`allreduce`, `exscan`, ...) used by the few
  algorithms whose schedule is the algorithm (ring cdist, TSQR, DASO) — these
  are thin shims over ``jax.lax`` collectives inside ``shard_map``.

``rank``/``size``: single-controller JAX has one Python process; ``rank`` is
the process index (0 on a single host, ``jax.process_index()`` multi-host) and
``size`` is the number of mesh devices — the parallelism degree, which is what
reference scripts branch on.
"""

from __future__ import annotations

import functools
import os
from typing import Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from . import resilience, telemetry

# NOTE: the lazy singletons (MESH_WORLD/MPI_WORLD/...) are deliberately NOT in
# __all__ — a star import would force backend initialization at import time.
# They are reachable as module attributes (heat_tpu.MPI_WORLD works via the
# package-level __getattr__).
__all__ = [
    "Communication",
    "MeshCommunication",
    "get_comm",
    "initialize",
    "reform",
    "sanitize_comm",
    "use_comm",
]

SPLIT_AXIS = "split"

# cap on the per-instance pure-metadata memos (counts/displs, lshape maps):
# workloads with data-dependent shapes must not grow them for the process
# lifetime — past the cap the memo resets (recompute is cheap arithmetic)
_METADATA_CACHE_SIZE = 1024


def _type_min(dtype):
    """Most-negative representable value (neutral element of max)."""
    if jnp.issubdtype(dtype, jnp.floating):
        return -jnp.inf
    if jnp.issubdtype(dtype, jnp.bool_):
        return False
    return jnp.iinfo(dtype).min


def _type_max(dtype):
    """Most-positive representable value (neutral element of min)."""
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf
    if jnp.issubdtype(dtype, jnp.bool_):
        return True
    return jnp.iinfo(dtype).max


# ----------------------------------------------------------------------------
# in-kernel collective functions (call inside shard_map over a mesh axis)
#
# The XLA rendering of the reference's MPI collective set
# (reference communication.py:88-1891): string ops lower to hardware
# collectives over ICI; a callable ``op`` — the analog of a custom MPI reduce
# op (reference statistics.py:1335-1405, manipulations.py:3985-4028) — is an
# associative pytree combiner evaluated as an all_gather + static fold.
# ----------------------------------------------------------------------------
def _neutral(op: str, x):
    makers = {
        "sum": lambda l: jnp.zeros_like(l),
        "prod": lambda l: jnp.ones_like(l),
        "max": lambda l: jnp.full_like(l, _type_min(l.dtype)),
        "min": lambda l: jnp.full_like(l, _type_max(l.dtype)),
        "land": lambda l: jnp.ones_like(l),
        "lor": lambda l: jnp.zeros_like(l),
    }
    return jax.tree.map(makers[op], x)


def _combine(op: Union[str, Callable]) -> Callable:
    """Binary pytree combiner for a string or callable ``op``."""
    if callable(op):
        return op
    fns = {
        "sum": jnp.add,
        "prod": jnp.multiply,
        "max": jnp.maximum,
        "min": jnp.minimum,
        "land": jnp.logical_and,
        "lor": jnp.logical_or,
    }
    fn = fns[op]
    return lambda a, b: jax.tree.map(fn, a, b)


def allreduce(x, axis: str, op: Union[str, Callable] = "sum", size: Optional[int] = None):
    """All-reduce ``x`` over mesh axis ``axis`` (reference Allreduce)."""
    telemetry.record_collective_operand("allreduce", axis, x)
    if resilience._ARMED:
        resilience.check("collective.allreduce")
    if op == "sum":
        return jax.tree.map(lambda l: jax.lax.psum(l, axis), x)
    if op == "mean":
        return jax.tree.map(lambda l: jax.lax.pmean(l, axis), x)
    if op == "max":
        return jax.tree.map(lambda l: jax.lax.pmax(l, axis), x)
    if op == "min":
        return jax.tree.map(lambda l: jax.lax.pmin(l, axis), x)
    if op == "land":
        return jax.tree.map(lambda l: jax.lax.pmin(l.astype(jnp.uint8), axis).astype(jnp.bool_), x)
    if op == "lor":
        return jax.tree.map(lambda l: jax.lax.pmax(l.astype(jnp.uint8), axis).astype(jnp.bool_), x)
    # prod / custom combiner: gather the contributions (size is static) and
    # fold — the XLA rendering of an arbitrary MPI reduce op. The fold is a
    # fori_loop, not an unrolled chain: program size stays O(1) in the mesh
    # size (the 64-chip compile-scaling requirement, tests/test_mesh64_compile)
    if size is None:
        raise ValueError("custom/prod allreduce needs the static axis size")
    combine = _combine(op)
    gathered = jax.tree.map(lambda l: jax.lax.all_gather(l, axis), x)
    acc0 = jax.tree.map(lambda g: g[0], gathered)

    def fold(i, acc):
        return combine(acc, jax.tree.map(lambda g: g[i], gathered))

    return jax.lax.fori_loop(1, size, fold, acc0)


def allgather(x, axis: str, gather_axis: int = 0, tiled: bool = False):
    """All-gather over the mesh axis (reference Allgather(v)).
    ``tiled=False`` stacks a new axis at position ``gather_axis``;
    ``tiled=True`` concatenates along it."""
    telemetry.record_collective_operand("allgather", axis, x)
    if resilience._ARMED:
        resilience.check("collective.allgather")
    return jax.tree.map(lambda l: jax.lax.all_gather(l, axis, axis=gather_axis, tiled=tiled), x)


def alltoall(x, axis: str, split_axis: int = 0, concat_axis: int = 0):
    """All-to-all over the mesh axis (reference Alltoall(v/w)): scatter
    ``split_axis``, concatenate received pieces along ``concat_axis``."""
    telemetry.record_collective_operand("alltoall", axis, x)
    if resilience._ARMED:
        resilience.check("collective.alltoall")
    return jax.tree.map(
        lambda l: jax.lax.all_to_all(l, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=True),
        x,
    )


def ppermute(
    x,
    axis: str,
    size: int,
    shift: int = 1,
    perm: Optional[Sequence[Tuple[int, int]]] = None,
):
    """Ring rotation: device ``d`` receives device ``(d + shift) % size``'s
    value; an explicit ``perm`` of (src, dst) pairs overrides ``shift``."""
    telemetry.record_collective_operand("ppermute", axis, x)
    if resilience._ARMED:
        resilience.check("collective.ppermute")
    if perm is None:
        perm = [(j, (j - shift) % size) for j in range(size)]
    return jax.tree.map(lambda l: jax.lax.ppermute(l, axis, perm), x)


def bcast(x, axis: str, root: int = 0):
    """Every device gets ``root``'s value — a masked psum: O(1) memory, no
    gather (reference Bcast, communication.py:544-600)."""
    telemetry.record_collective_operand("bcast", axis, x)
    if resilience._ARMED:
        resilience.check("collective.bcast")
    idx = jax.lax.axis_index(axis)

    def pick(l):
        numeric = l if jnp.issubdtype(l.dtype, jnp.number) else l.astype(jnp.uint8)
        masked = jnp.where(idx == root, numeric, jnp.zeros_like(numeric))
        out = jax.lax.psum(masked, axis)
        return out if numeric.dtype == l.dtype else out.astype(l.dtype)

    return jax.tree.map(pick, x)


def exscan(x, axis: str, size: int, op: Union[str, Callable] = "sum", neutral=None):
    """Exclusive prefix combine over the device axis (reference Exscan,
    the cumsum/cumprod workhorse _operations.py:268-295). Device 0 gets the
    neutral element."""
    telemetry.record_collective_operand("exscan", axis, x)
    if resilience._ARMED:
        resilience.check("collective.exscan")
    return _exscan_impl(x, axis, size, op, neutral)


def _exscan_impl(x, axis: str, size: int, op: Union[str, Callable], neutral):
    idx = jax.lax.axis_index(axis)
    if neutral is None:
        if callable(op):
            raise ValueError("a callable op requires an explicit neutral element")
        neutral = _neutral(op, x)
    combine = _combine(op)
    gathered = jax.tree.map(lambda l: jax.lax.all_gather(l, axis), x)

    # fori_loop fold (O(1) program size in the mesh size): device d keeps
    # the prefix of shards < d
    def fold(i, carry):
        out, acc = carry
        acc = combine(acc, jax.tree.map(lambda g: g[i], gathered))
        out = jax.tree.map(lambda o, a: jnp.where(idx > i, a, o), out, acc)
        return out, acc

    out, _ = jax.lax.fori_loop(0, size - 1, fold, (neutral, neutral))
    return out


def pscan(x, axis: str, size: int, op: Union[str, Callable] = "sum", neutral=None):
    """Inclusive prefix combine over the device axis (reference Scan)."""
    telemetry.record_collective_operand("scan", axis, x)
    if resilience._ARMED:
        resilience.check("collective.scan")
    return _combine(op)(_exscan_impl(x, axis, size, op, neutral), x)


class Communication:
    """Base class for communication contexts (reference communication.py:88-101)."""

    @staticmethod
    def is_distributed() -> bool:
        raise NotImplementedError()

    def chunk(self, shape, split, rank=None):
        raise NotImplementedError()


@functools.lru_cache(maxsize=512)
def _apply_program(mesh, kernel, in_specs, out_specs, check_vma):
    """One jitted shard_map program per (mesh, kernel identity, layout) —
    ``MeshCommunication.apply`` used to build a fresh ``jax.jit(shard_map)``
    wrapper per call, which retraced even for a module-level kernel. With
    the program memoized, a STABLE kernel identity (module-level function or
    lru-cached factory — the H004 lint contract) makes repeat applies hit
    compiled code; a per-call closure still misses every time, which is
    exactly what the retrace ledger (``record_compile``) now counts."""
    if telemetry._MODE:
        telemetry.record_compile("apply:" + getattr(kernel, "__name__", "kernel"))
    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )
    )


class MeshCommunication(Communication):
    """A communication context backed by a 1-D JAX device mesh.

    Parameters
    ----------
    devices : sequence of jax.Device, optional
        Devices forming the mesh. Defaults to all devices of the default
        backend (every TPU chip in the slice / every forced-host CPU device).
    axis_name : str
        Mesh axis name the ``split`` dimension of every DNDarray maps onto.
    """

    def __init__(self, devices: Optional[Sequence] = None, axis_name: str = SPLIT_AXIS):
        if devices is None:
            devices = jax.devices()
        self._devices = tuple(devices)
        self.device_set = frozenset(self._devices)  # fusion batch-mesh gate
        self.axis_name = axis_name
        self.mesh = Mesh(np.asarray(self._devices), (axis_name,))
        self.__sharding_cache = {}
        # pure-metadata memos: counts/displs and lshape maps are recomputed
        # on EVERY distributed op (chunk(), counts_displs(), the io/ckpt
        # shard protocols) from nothing but (shape, split, size) — cache per
        # instance since the layout is deterministic
        self.__counts_cache = {}
        self.__lshape_cache = {}
        try:
            self.rank = jax.process_index()
        except Exception:  # pragma: no cover
            self.rank = 0

    # ------------------------------------------------------------------
    # topology
    # ------------------------------------------------------------------
    @property
    def size(self) -> int:
        """Parallelism degree: number of devices along the split axis."""
        return len(self._devices)

    @property
    def devices(self):
        return self._devices

    def is_distributed(self) -> bool:
        return self.size > 1

    # ------------------------------------------------------------------
    # sharding construction
    # ------------------------------------------------------------------
    def spec(self, ndim: int, split: Optional[int]) -> PartitionSpec:
        """PartitionSpec placing mesh axis on dimension ``split``."""
        if split is None:
            return PartitionSpec()
        entries: List[Optional[str]] = [None] * ndim
        entries[split] = self.axis_name
        return PartitionSpec(*entries)

    def sharding(self, ndim: int, split: Optional[int]) -> NamedSharding:
        """NamedSharding realizing a 1-D block distribution along ``split``
        (the TPU equivalent of the reference's split attribute semantics,
        reference communication.py:193-203). Memoized per (ndim, split):
        every engine call and fusion forcing point asks for one."""
        key = (ndim, split)
        cached = self.__sharding_cache.get(key)
        if cached is None:
            cached = NamedSharding(self.mesh, self.spec(ndim, split))
            self.__sharding_cache[key] = cached
        return cached

    # ------------------------------------------------------------------
    # block-distribution arithmetic (reference communication.py:161-209)
    # ------------------------------------------------------------------
    def counts_displs_shape(
        self, shape: Sequence[int], split: int
    ) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Per-device counts and displacements along ``split`` under GSPMD's
        ceil-division block rule. Memoized per (split size, axis): this is
        the hot pure-metadata path every distributed op, ``counts_displs()``
        call and shard-protocol walk recomputes."""
        n = int(shape[split])
        cached = self.__counts_cache.get((n, split))
        if cached is not None:
            return cached
        k = self.size
        block = -(-n // k) if n else 0
        counts = tuple(max(0, min(block, n - i * block)) for i in range(k))
        displs = tuple(min(i * block, n) for i in range(k))
        if len(self.__counts_cache) >= _METADATA_CACHE_SIZE:
            self.__counts_cache.clear()  # churning shapes: recompute > grow
        self.__counts_cache[(n, split)] = (counts, displs)
        return counts, displs

    def chunk(
        self, shape: Sequence[int], split: Optional[int], rank: Optional[int] = None
    ) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """Offset, local shape and slices of device ``rank``'s shard.

        Mirrors reference communication.py:161-209 but reports GSPMD's actual
        ceil-division layout. With ``split=None`` the full array is returned.
        """
        shape = tuple(int(s) for s in shape)
        if split is None:
            return 0, shape, tuple(slice(0, s) for s in shape)
        rank = 0 if rank is None else rank
        counts, displs = self.counts_displs_shape(shape, split)
        start, count = displs[rank], counts[rank]
        lshape = list(shape)
        lshape[split] = count
        slices = [slice(0, s) for s in shape]
        slices[split] = slice(start, start + count)
        return start, tuple(lshape), tuple(slices)

    def lshape_map(self, shape: Sequence[int], split: Optional[int]) -> np.ndarray:
        """(size, ndim) array of each device's local shape (reference
        dndarray.py:569-600 computes this with an Allreduce; here it is pure
        arithmetic because the layout is deterministic). Memoized per
        (shape, split); callers receive a fresh copy, so mutating a returned
        map can never poison the cache."""
        key = (tuple(int(s) for s in shape), split)
        cached = self.__lshape_cache.get(key)
        if cached is None:
            cached = np.empty((self.size, len(shape)), dtype=np.int64)
            for r in range(self.size):
                _, lshape, _ = self.chunk(shape, split, rank=r)
                cached[r] = lshape
            if len(self.__lshape_cache) >= _METADATA_CACHE_SIZE:
                self.__lshape_cache.clear()  # churning shapes: recompute > grow
            self.__lshape_cache[key] = cached
        return cached.copy()

    # ------------------------------------------------------------------
    # collective helpers (reference communication.py:88-1891)
    #
    # These are the chokepoint the reference's MPICommunication provides:
    # every explicitly-scheduled algorithm (ring cdist, TSQR, DASO, ring/
    # Ulysses attention, pipeline) routes its bytes through them. They are
    # *in-kernel* helpers — call them inside a ``shard_map`` over ``.mesh``
    # (use :meth:`apply` to enter one); each receives the per-device shard
    # view and lowers to a single XLA collective over the mesh axis. The
    # implementations are the module-level functions below, which take an
    # explicit (axis, size) so kernels on other meshes (DASO's 2-axis
    # dcn×ici, the tp/pp/ep meshes) share the same code path.
    # ------------------------------------------------------------------
    def allreduce(self, x, op: Union[str, Callable] = "sum"):
        """Combine ``x`` across all devices; every device gets the result
        (reference Allreduce, communication.py:712-760). ``op`` ∈
        {'sum','mean','prod','max','min','land','lor'} or an associative
        callable combining two pytrees (custom-MPI-op analog, reference
        statistics.py:1335-1405)."""
        return allreduce(x, self.axis_name, op, self.size)

    def allgather(self, x, gather_axis: int = 0, tiled: bool = False):
        """Gather every device's shard to all devices (reference Allgather(v),
        communication.py:790-900). ``tiled=False`` stacks a new device axis at
        ``gather_axis``; ``tiled=True`` concatenates along it."""
        return allgather(x, self.axis_name, gather_axis=gather_axis, tiled=tiled)

    def alltoall(self, x, split_axis: int = 0, concat_axis: int = 0):
        """Transpose the device axis against a data axis (reference
        Alltoall(v/w), communication.py:336-437)."""
        return alltoall(x, self.axis_name, split_axis=split_axis, concat_axis=concat_axis)

    def ppermute(self, x, shift: int = 1, perm: Optional[Sequence[Tuple[int, int]]] = None):
        """Ring rotation: device ``d`` receives the shard of device
        ``(d + shift) % p`` (the Send-to-neighbor schedule of reference
        distance.py:272-327 / get_halo dndarray.py:360-441)."""
        return ppermute(x, self.axis_name, self.size, shift=shift, perm=perm)

    def bcast(self, x, root: int = 0):
        """Every device gets ``root``'s shard (reference Bcast,
        communication.py:544-600)."""
        return bcast(x, self.axis_name, root)

    def exscan(self, x, op: Union[str, Callable] = "sum", neutral=None):
        """Exclusive prefix combine over the device axis (reference Exscan,
        communication.py:1160-1220); device 0 gets the neutral element."""
        return exscan(x, self.axis_name, self.size, op, neutral)

    def scan(self, x, op: Union[str, Callable] = "sum", neutral=None):
        """Inclusive prefix combine over the device axis (reference Scan)."""
        return pscan(x, self.axis_name, self.size, op, neutral)

    def apply(
        self,
        kernel: Callable,
        *arrays,
        in_splits: Sequence[Optional[int]],
        out_splits: Union[Optional[int], Sequence[Optional[int]]],
        check_vma: bool = False,
    ):
        """Run ``kernel`` as a jitted ``shard_map`` over this mesh.

        ``kernel`` sees per-device shards and may call the collective helpers
        above. ``in_splits[i]``/``out_splits[j]`` give the dimension each
        array is block-split along (None = replicated) — the same vocabulary
        as ``DNDarray.split``.
        """
        def prefix_spec(split):
            # PartitionSpec may be shorter than the array rank (trailing dims
            # are implicitly unsharded), so the split position suffices
            if split is None:
                return PartitionSpec()
            return PartitionSpec(*([None] * split), self.axis_name)

        in_specs = tuple(self.spec(a.ndim, s) for a, s in zip(arrays, in_splits))
        if isinstance(out_splits, (tuple, list)):
            out_specs = tuple(prefix_spec(s) for s in out_splits)
        else:
            out_specs = prefix_spec(out_splits)
        if resilience._ARMED:
            resilience.check("collective.apply")
        fn = _apply_program(self.mesh, kernel, in_specs, out_specs, check_vma)
        if telemetry._MODE >= 2:
            # time the dispatch wall (build+trace+first-execute on a program
            # cache miss) on the timeline: eager apply kernels are exactly
            # the dispatches the fused path avoids, so their cost should be
            # visible next to the fused programs'
            # (lazy import: utils depends on core, never the other way)
            from ..utils.profiling import Timer

            with Timer("apply:" + getattr(kernel, "__name__", "kernel"), sync=False):
                return fn(*arrays)
        return fn(*arrays)

    # ------------------------------------------------------------------
    # group creation (reference communication.py:445-456)
    # ------------------------------------------------------------------
    def split_comm(self, n_groups: int) -> "MeshCommunication":
        """Return a communication context over the first ``size // n_groups``
        devices — the analog of MPI ``Split`` for simple subgrouping."""
        group = max(1, self.size // n_groups)
        return MeshCommunication(self._devices[:group], axis_name=self.axis_name)

    def __repr__(self) -> str:
        plat = self._devices[0].platform if self._devices else "?"
        return f"MeshCommunication({self.size} {plat} device(s), axis={self.axis_name!r})"


def _distributed_client_live() -> bool:
    """Whether ``jax.distributed`` is already connected, probed from runtime
    state rather than by parsing exception wording (which changes across JAX
    versions). Conservative: any probe failure reads as "not connected"."""
    try:
        state = jax._src.distributed.global_state
        return getattr(state, "client", None) is not None
    except (AttributeError, ImportError):
        return False  # private-module layout changed: read as "not connected"


def _refresh_world_state() -> None:
    """Invalidate every mesh-keyed cache after the world changed.

    A refreshed/re-formed world makes three kinds of stale state dangerous:
    compiled shard_map programs hold shardings naming the *old* devices
    (dispatching one against a lost device is a runtime crash, not a cache
    miss), fusion's program cache and ``_PROGRAM_INFO`` are keyed the same
    way, and memledger's resolved budget is a fraction of the old world's
    per-device capacity. Per-instance metadata memos (sharding/counts/lshape
    caches) die with their ``MeshCommunication`` instance and need no help.
    Each teardown is individually best-effort: a subsystem that was never
    imported has nothing to clear."""
    _apply_program.cache_clear()
    try:
        from . import fusion

        fusion.clear_cache()
    except Exception:  # pragma: no cover - fusion unavailable/uninitialized
        pass
    try:
        from . import memledger

        memledger.invalidate_resolved_budget()
    except Exception:  # pragma: no cover - memledger unavailable
        pass


def reform(devices: Optional[Sequence] = None) -> MeshCommunication:
    """Re-form the default world on ``devices`` (all live devices if None).

    The elastic supervisor's world-rebuild step (core/elastic.py): installs a
    fresh ``MeshCommunication`` over the surviving device set as
    ``MESH_WORLD``/default comm and invalidates every mesh-keyed cache via
    :func:`_refresh_world_state`. Also the test-suite idiom for restoring the
    full world after an elasticity test: ``reform()`` with no arguments."""
    global MESH_WORLD, MESH_SELF, __default_comm
    comm = MeshCommunication(devices)
    MESH_WORLD = comm
    MESH_SELF = MeshCommunication(comm.devices[:1])
    __default_comm = comm
    _refresh_world_state()
    return comm


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> MeshCommunication:
    """Multi-host bring-up: connect this controller to the cluster and make
    the default communication context span every device in it.

    The reference framework inherits its world from ``mpirun`` (MPI_WORLD,
    reference communication.py:1886-1891); the single-controller analog is
    ``jax.distributed.initialize`` — one Python process per host, all hosts'
    devices visible globally afterwards. On TPU pods the coordinator is
    auto-detected, so ``initialize()`` with no arguments suffices; elsewhere
    pass ``coordinator_address``/``num_processes``/``process_id``.

    Idempotent: re-initialization errors from an already-connected runtime
    are swallowed. Returns the refreshed default comm (and installs it via
    :func:`use_comm`).
    """
    if _distributed_client_live():
        # state probe, not message parsing: the runtime is already connected,
        # so re-initialization is a no-op regardless of how a second
        # ``jax.distributed.initialize`` would word its complaint
        # a re-entry after device loss must not leave compiled programs /
        # fusion caches holding shardings keyed on the pre-refresh devices
        return reform()
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
    except (RuntimeError, ValueError) as exc:
        msg = str(exc).lower()
        # cluster launchers advertise the world size in the environment; if
        # one says we are multi-process, a failed bring-up must surface
        hinted_world = max(
            int(os.environ.get("SLURM_NTASKS", "1") or 1),
            int(os.environ.get("OMPI_COMM_WORLD_SIZE", "1") or 1),
            int(os.environ.get("PMI_SIZE", "1") or 1),
            int(os.environ.get("WORLD_SIZE", "1") or 1),  # torchrun et al.
        )
        single = (num_processes is None or num_processes == 1) and hinted_world == 1
        if _distributed_client_live() or (
            ("already" in msg or "once" in msg) and "in use" not in msg
        ):
            pass  # connected earlier: keep the live service (idempotent)
        elif single and ("must be called before" in msg or "coordinator_address" in msg):
            # backend already up, or no cluster to auto-detect, in a genuinely
            # single-process world: the service adds nothing — refreshing the
            # default comm is all that's needed
            import warnings

            warnings.warn(
                f"heat_tpu.initialize(): no cluster to join ({exc}); "
                "continuing as a single-host world",
                stacklevel=2,
            )
        else:
            raise
    return reform()


def _world() -> MeshCommunication:
    return MeshCommunication()


# Lazily constructed singletons: jax.devices() initializes the backend, which
# must not happen at import time (tests flip the platform first).
MESH_WORLD: Optional[MeshCommunication] = None
MESH_SELF: Optional[MeshCommunication] = None

__default_comm: Optional[MeshCommunication] = None


def get_comm() -> MeshCommunication:
    """The current global default communication context (reference
    communication.py:1919-1925)."""
    global __default_comm, MESH_WORLD, MESH_SELF
    if __default_comm is None:
        if MESH_WORLD is None:
            MESH_WORLD = _world()
            MESH_SELF = MeshCommunication(jax.devices()[:1])
        __default_comm = MESH_WORLD
    return __default_comm


def sanitize_comm(comm: Optional[Communication]) -> MeshCommunication:
    """Validate/normalize a communication context (reference communication.py:1900)."""
    if comm is None:
        return get_comm()
    if isinstance(comm, MeshCommunication):
        return comm
    raise TypeError(f"Given communication object is not valid: {comm!r}")


def use_comm(comm: Optional[Communication] = None) -> None:
    """Set the globally-used default communication context (reference
    communication.py:1927-1937)."""
    global __default_comm
    __default_comm = sanitize_comm(comm)


def __getattr__(name: str):
    # MPI_WORLD/MPI_SELF exist for reference-API compatibility; build lazily.
    if name in ("MPI_WORLD",):
        return get_comm()
    if name in ("MPI_SELF",):
        get_comm()
        return MESH_SELF
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""The multi-host seam: every per-process decision in one place.

A 64-chip slice is multi-host: each controller process addresses only its
own host's devices, and the single-controller idioms ("rank 0's chunk",
"read every block") become per-host filters. The reference framework gets
this from MPI ranks (reference communication.py:1886-1891); here the facts
come from ``jax.process_index``/``device.process_index``, and the handful
of call sites that must care (sharded ingest, ``lshape``, per-shard saves)
route through these helpers so the contract is testable against a mocked
process topology (tests/test_multihost_seam.py) without owning two hosts.

Contract (documented in doc/internals_distribution.md):

* ``process_index()`` — this controller's process id (0 on a single host).
* ``is_addressable(device)`` — whether this process may transfer to/from
  the device. Ingest loops skip non-addressable ranks; the global array is
  assembled with ``make_array_from_single_device_arrays``, which accepts
  per-host partial shard lists.
* ``ranks_to_read(devices)`` — the (rank, device) pairs THIS process must
  populate when ingesting a split array, in rank order.
* ``representative_rank(devices)`` — the mesh rank whose chunk stands in
  for "the local shard" in single-array views (``lshape``): the first
  rank addressable by this process, so every host reports a shard it
  actually holds.
* ``io_owner()`` — whether this process performs the temp→target rename
  publishing an atomic file write (``resilience.atomic_write``): exactly
  one process may win the rename when every controller runs the same
  ``save_*`` call.
* ``process_count()`` / ``sync_processes(tag)`` — how many controllers the
  runtime has, and a named barrier across them. The checkpoint subsystem
  (``utils/checkpoint.py``) syncs after every host has published its shard
  files and before the owner hashes them into the manifest, so the commit
  point never references files still in flight. ``HEAT_TPU_BARRIER_TIMEOUT_MS``
  (default off) bounds the wait: a peer dead mid-barrier surfaces as a
  ``resilience.StallError`` naming the tag instead of deadlocking.
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import List, Optional, Sequence, Tuple

import jax

__all__ = [
    "process_index",
    "process_count",
    "io_owner",
    "is_addressable",
    "ranks_to_read",
    "representative_rank",
    "sync_processes",
]

#: values that read as "knob off" (the shared env-knob convention)
_OFF_VALUES = ("", "0", "false", "off", "no")


def _barrier_timeout_ms() -> Optional[float]:
    """The ``HEAT_TPU_BARRIER_TIMEOUT_MS`` knob: off by default (an infinite
    barrier is the correct production default — a slow peer is not a dead
    peer), a positive millisecond bound otherwise. Malformed values warn and
    read as off, never take the process down."""
    raw = os.environ.get("HEAT_TPU_BARRIER_TIMEOUT_MS", "").strip().lower()
    if raw in _OFF_VALUES:
        return None
    try:
        value = float(raw)
    except ValueError:
        warnings.warn(
            f"HEAT_TPU_BARRIER_TIMEOUT_MS={raw!r} is not a number; barrier "
            "timeout stays off",
            stacklevel=2,
        )
        return None
    return value if value > 0 else None


def process_index() -> int:
    """This controller process's id; 0 when the backend has no notion of
    processes (single host, or an unstarted distributed runtime)."""
    try:
        return int(jax.process_index())
    except Exception:  # pragma: no cover - backend-dependent
        return 0


def process_count() -> int:
    """How many controller processes the runtime has; 1 when the backend has
    no notion of processes (single host, or an unstarted distributed
    runtime)."""
    try:
        return int(jax.process_count())
    except Exception:  # pragma: no cover - backend-dependent
        return 1


def sync_processes(tag: str, timeout_ms: Optional[float] = None) -> None:
    """Named barrier across controller processes (no-op on a single host).

    Cooperative multi-file protocols (the sharded checkpoint writer) need
    one ordering guarantee the per-file atomic renames cannot give: every
    host's files are on the shared filesystem before the owner publishes the
    manifest that references them. ``tag`` names the barrier so mismatched
    call sites fail loudly instead of deadlocking silently.

    A peer that died mid-barrier would hang the survivors forever —
    ``jax``'s barrier has no timeout parameter. ``timeout_ms`` (or the
    ambient ``HEAT_TPU_BARRIER_TIMEOUT_MS`` knob; off by default) bounds the
    wait: the barrier runs on a daemon worker thread, and when the bound
    expires a ``resilience.StallError`` naming the barrier tag surfaces at
    the call site instead of a deadlock. The checkpoint subsystem's save and
    commit barriers route through here, so an elastic supervisor can treat
    "peer lost during checkpoint" as a preemption rather than a hang."""
    if process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    if timeout_ms is None:
        timeout_ms = _barrier_timeout_ms()
    if timeout_ms is None:
        multihost_utils.sync_global_devices(tag)  # pragma: no cover - multi-host only
        return
    failure: List[BaseException] = []
    done = threading.Event()

    def _barrier() -> None:
        try:
            multihost_utils.sync_global_devices(tag)
        except BaseException as exc:  # noqa: BLE001 - relayed to the caller
            failure.append(exc)
        finally:
            done.set()

    worker = threading.Thread(
        target=_barrier, name=f"heat-tpu-barrier:{tag}", daemon=True
    )
    worker.start()
    if not done.wait(float(timeout_ms) / 1e3):
        from . import resilience

        raise resilience.StallError(
            f"barrier {tag!r} still waiting after {timeout_ms:g}ms "
            "(HEAT_TPU_BARRIER_TIMEOUT_MS): a peer process likely died "
            "mid-barrier; the hung sync is abandoned on its daemon thread"
        )
    if failure:
        raise failure[0]


def io_owner(proc: int | None = None) -> bool:
    """Whether this process owns the *publication* step of a cooperative
    file write (the temp→target rename of ``resilience.atomic_write``).

    Under multi-controller SPMD every process runs the same ``save_*`` call
    against the same target path; each writes a private temp, and exactly
    one rename may win — process 0's, the same convention as the reference's
    rank-0 responsibilities (reference io.py:198-226 token ring head). On a
    single host this is always True."""
    return (process_index() if proc is None else proc) == 0


def is_addressable(device, proc: int | None = None) -> bool:
    """Whether ``device`` belongs to this process (may be transferred
    to/from). Devices without a ``process_index`` attribute are treated as
    addressable — the single-host CPU/TPU cases."""
    if proc is None:
        proc = process_index()
    return getattr(device, "process_index", proc) == proc


def ranks_to_read(devices: Sequence, proc: int | None = None) -> List[Tuple[int, object]]:
    """The (mesh_rank, device) pairs this process must populate when
    ingesting a split array — its addressable ranks, in rank order."""
    if proc is None:
        proc = process_index()
    return [(r, d) for r, d in enumerate(devices) if is_addressable(d, proc)]


def representative_rank(devices: Sequence, proc: int | None = None) -> int:
    """The mesh rank whose chunk this process reports as "the local shard"
    (``DNDarray.lshape``): the first addressable rank, falling back to 0
    when none is (defensive — a controller always owns at least one)."""
    if proc is None:
        proc = process_index()
    for r, d in enumerate(devices):
        if is_addressable(d, proc):
            return r
    return 0  # pragma: no cover - a controller always addresses a device

"""The multi-host seam: every per-process decision in one place.

A 64-chip slice is multi-host: each controller process addresses only its
own host's devices, and the single-controller idioms ("rank 0's chunk",
"read every block") become per-host filters. The reference framework gets
this from MPI ranks (reference communication.py:1886-1891); here the facts
come from ``jax.process_index``/``device.process_index``, and the handful
of call sites that must care (sharded ingest, ``lshape``, per-shard saves)
route through these helpers so the contract is testable against a mocked
process topology (tests/test_multihost_seam.py) without owning two hosts.

Contract (documented in doc/internals_distribution.md):

* ``process_index()`` — this controller's process id (0 on a single host).
* ``is_addressable(device)`` — whether this process may transfer to/from
  the device. Ingest loops skip non-addressable ranks; the global array is
  assembled with ``make_array_from_single_device_arrays``, which accepts
  per-host partial shard lists.
* ``ranks_to_read(devices)`` — the (rank, device) pairs THIS process must
  populate when ingesting a split array, in rank order.
* ``representative_rank(devices)`` — the mesh rank whose chunk stands in
  for "the local shard" in single-array views (``lshape``): the first
  rank addressable by this process, so every host reports a shard it
  actually holds.
* ``io_owner()`` — whether this process performs the temp→target rename
  publishing an atomic file write (``resilience.atomic_write``): exactly
  one process may win the rename when every controller runs the same
  ``save_*`` call.
* ``process_count()`` / ``sync_processes(tag)`` — how many controllers the
  runtime has, and a named barrier across them. The checkpoint subsystem
  (``utils/checkpoint.py``) syncs after every host has published its shard
  files and before the owner hashes them into the manifest, so the commit
  point never references files still in flight. ``HEAT_TPU_BARRIER_TIMEOUT_MS``
  (default off; launcher-managed runs turn it on) bounds the wait: a peer
  dead mid-barrier surfaces as a ``resilience.StallError`` naming the tag
  instead of deadlocking.

Multi-process runtime (ROADMAP item 4)
--------------------------------------
Beyond the read-side seam, this module owns the process-world lifecycle:

* :func:`initialize_distributed` — the guarded ``jax.distributed`` bring-up:
  coordinator address / process id / world size from arguments or the
  ``HEAT_TPU_COORDINATOR`` / ``HEAT_TPU_NUM_PROCESSES`` /
  ``HEAT_TPU_PROCESS_ID`` environment (what :func:`spawn_local` exports),
  retry-with-backoff on *transient* coordinator connect faults
  (``resilience.retry_policy`` shapes the backoff), gloo CPU collectives so
  a CPU dev mesh runs real cross-process collectives, and the
  ``multihost.init`` fault site checked before every connect attempt.
* the **lease heartbeat daemon** (:func:`start_heartbeat`) — each process
  beats a lease file under ``$HEAT_TPU_MESH_DIR/lease/`` every
  ``HEAT_TPU_HEARTBEAT_MS``; a peer whose lease goes stale past
  ``HEAT_TPU_PEER_LOST_MS`` becomes a named :class:`PeerLostError` /
  ``peer_lost`` telemetry event instead of a hang. Detection must race
  ahead of XLA's coordination service, which hard-kills the *survivors* of
  a dead peer (``LOG(FATAL)`` in the client) — by the time XLA notices, a
  leased process has already drained and exited for reform.
* :func:`spawn_local` — the local launcher: N coordinated worker processes
  on one machine (CI's stand-in for N hosts), supervised across
  **generations**: when a worker dies, survivors detect the loss, drain,
  and exit with :data:`REFORM_EXIT`; the launcher re-ranks the survivors
  contiguously, bumps the mesh epoch, picks a fresh coordinator port and
  respawns them into a smaller world that restores from the newest
  verifying checkpoint. In-process reform across processes is impossible
  on this jax/jaxlib (the coordination service kills survivors), so the
  reform *ritual* (drain → checkpoint → re-init → restore) spans a process
  generation instead of a function call.

Observability: ``telemetry.report()["multihost"]`` (the set-attribute hook
pattern) carries heartbeat/barrier/abandoned-thread counters, and the ops
plane exports them as ``heat_tpu_peers_*`` / ``heat_tpu_barrier_*`` gauges
with a ``/readyz`` check that flips unready while a peer is lost.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
import warnings
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Sequence, Tuple

import jax

__all__ = [
    "PeerLostError",
    "REFORM_EXIT",
    "check_peers",
    "heartbeat_stats",
    "initialize_distributed",
    "io_owner",
    "is_addressable",
    "lost_peers",
    "reform_exit",
    "mesh_dir",
    "mesh_epoch",
    "note_progress",
    "process_count",
    "process_index",
    "ranks_to_read",
    "report_stats",
    "representative_rank",
    "reset_peers",
    "spawn_local",
    "start_heartbeat",
    "stop_heartbeat",
    "sync_processes",
]

#: values that read as "knob off" (the shared env-knob convention)
_OFF_VALUES = ("", "0", "false", "off", "no")

#: worker exit code meaning "peer lost: I drained; respawn me into a smaller
#: world" — the launcher's reform signal (sysexits leaves 64-78 user-defined;
#: 77 avoids every shell/signal convention)
REFORM_EXIT = 77


class PeerLostError(RuntimeError):
    """A peer controller process stopped beating its lease: the process
    world is degraded and every cross-process interaction (barriers,
    collectives, cooperative checkpoints) would hang or die. ``peers`` names
    the lost process ids. Supervisor-managed workers drain and exit with
    :data:`REFORM_EXIT` on this; the launcher respawns the survivors into a
    smaller world."""

    def __init__(self, message: str, peers: Sequence[int] = ()):
        super().__init__(message)
        self.peers = tuple(int(p) for p in peers)


# ----------------------------------------------------------------------
# observability: report()["multihost"] (set-attribute hook at module bottom)
# ----------------------------------------------------------------------
_LOCK = threading.Lock()
_STATS: Dict[str, Any] = {
    "world": 1,              # processes in the current world (init-time fact)
    "epoch": 0,              # mesh generation (the launcher bumps per reform)
    "barriers": 0,           # sync_processes waits entered (multi-process)
    "barrier_timeouts": 0,   # barriers abandoned on StallError
    "abandoned_threads": 0,  # cumulative daemon barrier threads abandoned
    "heartbeats": 0,         # lease beats written
    "heartbeat_errors": 0,   # beats that failed to write (missed beats)
    "init_retries": 0,       # transient coordinator connect faults retried
}
#: peers declared lost by the lease daemon (process ids); module-level so
#: ``lost_peers()`` stays a lock-and-read even after the daemon stops
_LOST: set = set()
#: abandoned barrier daemon threads, pruned of finished ones on read — the
#: "still alive" gauge a flapping peer would otherwise grow without bound
_ABANDONED: List[threading.Thread] = []


def _abandoned_alive() -> int:
    with _LOCK:
        _ABANDONED[:] = [t for t in _ABANDONED if t.is_alive()]
        return len(_ABANDONED)


def _abandoned_cap() -> int:
    """``HEAT_TPU_ABANDONED_BARRIER_CAP``: warn once the number of abandoned
    barrier threads crosses this (default 8) — a flapping peer turning every
    barrier into a leaked thread deserves a loud signal before it turns into
    thread exhaustion."""
    raw = os.environ.get("HEAT_TPU_ABANDONED_BARRIER_CAP", "").strip()
    try:
        return max(1, int(raw)) if raw else 8
    except ValueError:
        return 8


def report_stats() -> Dict[str, Any]:
    """Snapshot of the multi-process runtime counters (joined into
    ``telemetry.report()`` as the ``multihost`` block; the ops plane exports
    the same numbers as ``heat_tpu_peers_*`` / ``heat_tpu_barrier_*``)."""
    with _LOCK:
        doc = dict(_STATS)
        doc["peers_lost"] = sorted(_LOST)
    hb = _HEARTBEAT
    doc["heartbeat_running"] = hb is not None and hb.is_alive()
    if hb is not None:
        doc["world"] = hb.world
        doc["epoch"] = hb.epoch
    doc["abandoned_alive"] = _abandoned_alive()
    return doc


def reset_peers() -> None:
    """Forget every lost-peer declaration (test isolation, and the first act
    of a fresh epoch: a respawned generation starts with a clean world)."""
    with _LOCK:
        _LOST.clear()


# ----------------------------------------------------------------------
# env knobs
# ----------------------------------------------------------------------
def _barrier_timeout_ms() -> Optional[float]:
    """The ``HEAT_TPU_BARRIER_TIMEOUT_MS`` knob: off by default (an infinite
    barrier is the correct production default — a slow peer is not a dead
    peer), a positive millisecond bound otherwise. Supervisor-managed runs
    (:func:`spawn_local`) export it, so barrier timeouts default ON there.
    Malformed values warn and read as off, never take the process down."""
    raw = os.environ.get("HEAT_TPU_BARRIER_TIMEOUT_MS", "").strip().lower()
    if raw in _OFF_VALUES:
        return None
    try:
        value = float(raw)
    except ValueError:
        warnings.warn(
            f"HEAT_TPU_BARRIER_TIMEOUT_MS={raw!r} is not a number; barrier "
            "timeout stays off",
            stacklevel=2,
        )
        return None
    return value if value > 0 else None


def _env_ms(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError:
        warnings.warn(f"{name}={raw!r} is not a number; using {default:g}", stacklevel=2)
        return default
    return value if value > 0 else default


def mesh_dir() -> Optional[str]:
    """``HEAT_TPU_MESH_DIR``: the shared directory the process world
    coordinates through (lease files, lost-peer markers, progress beacons).
    None when unset — single-process, or a deployment without shared
    storage, in which case the lease daemon simply never starts."""
    raw = os.environ.get("HEAT_TPU_MESH_DIR", "").strip()
    return raw or None


def mesh_epoch() -> int:
    """``HEAT_TPU_MESH_EPOCH``: which generation of the process world this
    is (0 for a first launch; the launcher bumps it on every reform so a
    stale lease from a previous generation can never read as a live peer)."""
    raw = os.environ.get("HEAT_TPU_MESH_EPOCH", "").strip()
    try:
        return int(raw) if raw else 0
    except ValueError:
        return 0


def _lease_path(mesh: str, epoch: int, proc: int) -> str:
    return os.path.join(mesh, "lease", f"epoch-{epoch:04d}", f"proc-{proc:05d}")


def _lost_dir(mesh: str, epoch: int) -> str:
    return os.path.join(mesh, "lost", f"epoch-{epoch:04d}")


def _progress_path(mesh: str, epoch: int, proc: int) -> str:
    return os.path.join(mesh, "progress", f"epoch-{epoch:04d}", f"proc-{proc:05d}")


def _write_atomic(path: str, payload: str) -> None:
    """Tiny single-file publication (lease beats, progress beacons): a
    reader never sees a torn write because the rename is atomic."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as fh:
        fh.write(payload)
    os.replace(tmp, path)


# ----------------------------------------------------------------------
# the per-process facts
# ----------------------------------------------------------------------
def _distributed_client_live() -> bool:
    """Whether ``jax.distributed`` is connected, probed from runtime state
    (same probe as ``communication._distributed_client_live``, duplicated
    here because communication imports would be cyclic at this layer)."""
    try:
        state = jax._src.distributed.global_state
        return getattr(state, "client", None) is not None
    except (AttributeError, ImportError):
        return False  # private-module layout changed: read as "not connected"


def process_index() -> int:
    """This controller process's id; 0 when the backend has no notion of
    processes (single host, or an unstarted distributed runtime).

    Only the *backend-unavailable* error reads as "single host"
    (``RuntimeError`` from an uninitialized/unsupported backend). With a
    live distributed runtime the same error is a real fault and propagates:
    a misconfigured 8-process job silently running as 8 independent
    single-host jobs is the worst failure mode this seam can produce."""
    try:
        return int(jax.process_index())
    except RuntimeError:
        if _distributed_client_live():  # pragma: no cover - needs a live cluster
            raise
        return 0


def process_count() -> int:
    """How many controller processes the runtime has; 1 when the backend has
    no notion of processes. Error narrowing as :func:`process_index`: only a
    backend-unavailable ``RuntimeError`` with no live distributed client
    reads as a single-host world."""
    try:
        return int(jax.process_count())
    except RuntimeError:
        if _distributed_client_live():  # pragma: no cover - needs a live cluster
            raise
        return 1


def sync_processes(tag: str, timeout_ms: Optional[float] = None) -> None:
    """Named barrier across controller processes (no-op on a single host).

    Cooperative multi-file protocols (the sharded checkpoint writer) need
    one ordering guarantee the per-file atomic renames cannot give: every
    host's files are on the shared filesystem before the owner publishes the
    manifest that references them. ``tag`` names the barrier so mismatched
    call sites fail loudly instead of deadlocking silently.

    A peer that died mid-barrier would hang the survivors forever —
    ``jax``'s barrier has no timeout parameter. ``timeout_ms`` (or the
    ambient ``HEAT_TPU_BARRIER_TIMEOUT_MS`` knob; off by default, exported
    ON by :func:`spawn_local`) bounds the wait: the barrier runs on a daemon
    worker thread, and when the bound expires a ``resilience.StallError``
    naming the barrier tag surfaces at the call site instead of a deadlock.
    Abandoned barrier threads are counted (``report()["multihost"]``) and
    warn past ``HEAT_TPU_ABANDONED_BARRIER_CAP``, so a flapping peer cannot
    leak threads silently. The ``multihost.barrier`` fault site fires at
    entry, so chaos runs exercise exactly the blocked-barrier paths."""
    from . import resilience

    if resilience._ARMED:
        resilience.check("multihost.barrier")
    if process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    with _LOCK:
        _STATS["barriers"] += 1
    if timeout_ms is None:
        timeout_ms = _barrier_timeout_ms()
    if timeout_ms is None:
        multihost_utils.sync_global_devices(tag)  # pragma: no cover - multi-host only
        return
    failure: List[BaseException] = []
    done = threading.Event()

    def _barrier() -> None:
        try:
            multihost_utils.sync_global_devices(tag)
        except BaseException as exc:  # noqa: BLE001 - relayed to the caller
            failure.append(exc)
        finally:
            done.set()

    worker = threading.Thread(
        target=_barrier, name=f"heat-tpu-barrier:{tag}", daemon=True
    )
    worker.start()
    if not done.wait(float(timeout_ms) / 1e3):
        with _LOCK:
            _STATS["barrier_timeouts"] += 1
            _STATS["abandoned_threads"] += 1
            abandoned_total = _STATS["abandoned_threads"]
            _ABANDONED.append(worker)
        cap = _abandoned_cap()
        if abandoned_total >= cap:
            warnings.warn(
                f"{abandoned_total} barrier daemon thread(s) abandoned on "
                f"timeout (cap {cap}, HEAT_TPU_ABANDONED_BARRIER_CAP): a "
                "flapping peer is leaking threads; reform the world or raise "
                "the barrier timeout",
                resilience.StallWarning,
                stacklevel=2,
            )
        raise resilience.StallError(
            f"barrier {tag!r} still waiting after {timeout_ms:g}ms "
            "(HEAT_TPU_BARRIER_TIMEOUT_MS): a peer process likely died "
            "mid-barrier; the hung sync is abandoned on its daemon thread"
        )
    if failure:
        raise failure[0]


def io_owner(proc: int | None = None) -> bool:
    """Whether this process owns the *publication* step of a cooperative
    file write (the temp→target rename of ``resilience.atomic_write``).

    Under multi-controller SPMD every process runs the same ``save_*`` call
    against the same target path; each writes a private temp, and exactly
    one rename may win — process 0's, the same convention as the reference's
    rank-0 responsibilities (reference io.py:198-226 token ring head). On a
    single host this is always True.

    When process 0 is the *dead* peer, no survivor owns publication in the
    degraded world — by design: cooperative saves fail fast with
    :class:`PeerLostError` there, and the launcher's re-rank makes the next
    generation's process ids contiguous again, so a process 0 (and therefore
    an owner) always exists in any world that commits."""
    return (process_index() if proc is None else proc) == 0


def is_addressable(device, proc: int | None = None) -> bool:
    """Whether ``device`` belongs to this process (may be transferred
    to/from). Devices without a ``process_index`` attribute are treated as
    addressable — the single-host CPU/TPU cases."""
    if proc is None:
        proc = process_index()
    return getattr(device, "process_index", proc) == proc


def ranks_to_read(devices: Sequence, proc: int | None = None) -> List[Tuple[int, object]]:
    """The (mesh_rank, device) pairs this process must populate when
    ingesting a split array — its addressable ranks, in rank order."""
    if proc is None:
        proc = process_index()
    return [(r, d) for r, d in enumerate(devices) if is_addressable(d, proc)]


def representative_rank(devices: Sequence, proc: int | None = None) -> int:
    """The mesh rank whose chunk this process reports as "the local shard"
    (``DNDarray.lshape``): the first addressable rank, falling back to 0
    when none is (defensive — a controller always owns at least one)."""
    if proc is None:
        proc = process_index()
    for r, d in enumerate(devices):
        if is_addressable(d, proc):
            return r
    return 0  # pragma: no cover - a controller always addresses a device


# ----------------------------------------------------------------------
# the lease heartbeat daemon: peer loss as a named event, not a hang
# ----------------------------------------------------------------------
class _HeartbeatDaemon(threading.Thread):
    """Beats this process's lease file and watches the peers'.

    One file per process per epoch under ``{mesh_dir}/lease/``; staleness is
    judged by file mtime against the watcher's clock — on one machine (the
    launcher case) that is the same clock, and on shared network storage the
    server stamps both sides. A peer is declared lost when its lease is
    older than ``lost_after_s`` (missing files get the same grace from
    daemon start, covering slow starters). Declarations are sticky until
    :func:`reset_peers`: a peer that comes *back* after being declared lost
    belongs to a previous world and must rejoin through a new epoch."""

    def __init__(
        self,
        mesh: str,
        process: int,
        world: int,
        epoch: int,
        interval_s: float,
        lost_after_s: float,
        on_peer_lost: Optional[Callable[[int], None]] = None,
    ):
        super().__init__(name="heat-tpu-heartbeat", daemon=True)
        self.mesh = mesh
        self.process = int(process)
        self.world = int(world)
        self.epoch = int(epoch)
        self.interval_s = float(interval_s)
        self.lost_after_s = float(lost_after_s)
        self.on_peer_lost = on_peer_lost
        self._watchdog_armed = False
        self._halt = threading.Event()
        self._started_at = time.time()
        self._lease = _lease_path(mesh, self.epoch, self.process)
        os.makedirs(os.path.dirname(self._lease), exist_ok=True)

    def stop(self) -> None:
        self._halt.set()

    def run(self) -> None:  # pragma: no branch - trivial loop shape
        beat = 0
        while not self._halt.is_set():
            beat += 1
            self._beat(beat)
            self._scan()
            self._halt.wait(self.interval_s)

    def _beat(self, beat: int) -> None:
        from . import resilience

        try:
            if resilience._ARMED:
                resilience.check("multihost.heartbeat")
            _write_atomic(
                self._lease,
                json.dumps(
                    {
                        "process": self.process,
                        "epoch": self.epoch,
                        "beat": beat,
                        "pid": os.getpid(),
                        "time": time.time(),
                    }
                ),
            )
            with _LOCK:
                _STATS["heartbeats"] += 1
        # a failed beat is a MISSED beat (counted, survivable), never a
        # daemon crash: the process is alive even when the mesh dir flakes
        except Exception:  # noqa: BLE001
            with _LOCK:
                _STATS["heartbeat_errors"] += 1

    def _scan(self) -> None:
        now = time.time()
        for peer in range(self.world):
            if peer == self.process:
                continue
            with _LOCK:
                if peer in _LOST:
                    continue
            try:
                age = now - os.stat(_lease_path(self.mesh, self.epoch, peer)).st_mtime
            except OSError:
                # never beaten: grace from daemon start covers slow starters
                age = now - self._started_at
            if age > self.lost_after_s:
                self._declare_lost(peer, age)

    def _declare_lost(self, peer: int, age_s: float) -> None:
        with _LOCK:
            if peer in _LOST:
                return
            _LOST.add(peer)
        # the marker is the launcher's evidence of WHO died, whatever exit
        # codes the generation ends with (collateral crashes included)
        try:
            lost_dir = _lost_dir(self.mesh, self.epoch)
            os.makedirs(lost_dir, exist_ok=True)
            _write_atomic(
                os.path.join(lost_dir, f"proc-{peer:05d}"),
                json.dumps(
                    {"peer": peer, "by": self.process, "age_s": round(age_s, 3),
                     "time": time.time()}
                ),
            )
        except OSError:  # pragma: no cover - marker is best-effort evidence
            pass
        from . import telemetry

        if telemetry._MODE:
            telemetry.record_event(
                "peer_lost", peer=peer, epoch=self.epoch,
                age_ms=round(age_s * 1e3, 1), world=self.world,
            )
        warnings.warn(
            f"peer process {peer} lost (lease silent {age_s * 1e3:.0f}ms > "
            f"{self.lost_after_s * 1e3:.0f}ms, epoch {self.epoch}): the "
            "process world is degraded",
            stacklevel=2,
        )
        if self.on_peer_lost is not None:
            try:
                self.on_peer_lost(peer)
            except Exception:  # noqa: BLE001 - callback must not kill the daemon
                pass
        self._maybe_arm_drain_watchdog()

    def _maybe_arm_drain_watchdog(self) -> None:
        """The zero-hang backstop for a peer that HANGS instead of dying.

        ``check_peers()`` only runs at step boundaries — a worker already
        blocked inside a cross-process collective when its peer went silent
        (a SIGSTOP'd or wedged process keeps its sockets open, so gloo never
        errors) would wait there forever. Once a loss is declared, this arms
        a one-shot timer: if the worker is still running after the grace, it
        is forced through :func:`reform_exit` so the launcher can reform.

        Strictly opt-in via ``HEAT_TPU_DRAIN_GRACE_MS`` — :func:`spawn_local`
        exports it for its workers; a bare process (tests driving the daemon
        in-process, notebooks) must never be ``os._exit``'d by a timer it
        did not ask for."""
        raw = os.environ.get("HEAT_TPU_DRAIN_GRACE_MS", "").strip().lower()
        if raw in _OFF_VALUES:
            return
        try:
            grace_ms = float(raw)
        except ValueError:
            return
        if grace_ms <= 0 or self._watchdog_armed:
            return
        self._watchdog_armed = True

        def _watch() -> None:  # pragma: no cover - exercised in the slow suite
            time.sleep(grace_ms / 1e3)
            with _LOCK:
                lost = sorted(_LOST)
            warnings.warn(
                f"peer(s) {lost} lost and this worker is still running after "
                f"the {grace_ms:g}ms drain grace (HEAT_TPU_DRAIN_GRACE_MS): "
                "likely blocked in a collective with a dead peer; forcing "
                "reform exit",
                stacklevel=2,
            )
            from . import telemetry

            if telemetry._MODE:
                telemetry.record_event(
                    "drain_watchdog_fired", peers=lost, epoch=self.epoch
                )
            reform_exit()

        threading.Thread(
            target=_watch, name="heat-tpu-drain-watchdog", daemon=True
        ).start()


_HEARTBEAT: Optional[_HeartbeatDaemon] = None


def start_heartbeat(
    *,
    mesh: Optional[str] = None,
    process: Optional[int] = None,
    world: Optional[int] = None,
    epoch: Optional[int] = None,
    interval_ms: Optional[float] = None,
    lost_ms: Optional[float] = None,
    on_peer_lost: Optional[Callable[[int], None]] = None,
) -> bool:
    """Start (or restart) the lease heartbeat daemon. Defaults come from the
    environment (``HEAT_TPU_MESH_DIR`` / ``HEAT_TPU_HEARTBEAT_MS`` /
    ``HEAT_TPU_PEER_LOST_MS`` / ``HEAT_TPU_MESH_EPOCH``) and the live
    backend (process index/count). Returns False — without starting — when
    no mesh dir is configured or the world is trivially single-process;
    True once the daemon is beating."""
    global _HEARTBEAT
    mesh = mesh if mesh is not None else mesh_dir()
    if not mesh:
        return False
    world = int(world) if world is not None else process_count()
    if world <= 1:
        return False
    process = int(process) if process is not None else process_index()
    epoch = int(epoch) if epoch is not None else mesh_epoch()
    interval_ms = (
        float(interval_ms) if interval_ms is not None
        else _env_ms("HEAT_TPU_HEARTBEAT_MS", 500.0)
    )
    lost_ms = (
        float(lost_ms) if lost_ms is not None
        else _env_ms("HEAT_TPU_PEER_LOST_MS", 5.0 * interval_ms)
    )
    stop_heartbeat()
    daemon = _HeartbeatDaemon(
        mesh, process, world, epoch,
        interval_s=interval_ms / 1e3, lost_after_s=lost_ms / 1e3,
        on_peer_lost=on_peer_lost,
    )
    with _LOCK:
        _STATS["world"] = world
        _STATS["epoch"] = epoch
    daemon.start()
    _HEARTBEAT = daemon
    return True


def stop_heartbeat() -> None:
    """Stop the lease daemon (idempotent). Lost-peer declarations persist —
    they describe the world, not the daemon; :func:`reset_peers` clears."""
    global _HEARTBEAT
    daemon, _HEARTBEAT = _HEARTBEAT, None
    if daemon is not None:
        daemon.stop()
        daemon.join(timeout=2.0)


def lost_peers() -> FrozenSet[int]:
    """The peer process ids currently declared lost (empty = healthy)."""
    with _LOCK:
        return frozenset(_LOST)


def check_peers() -> None:
    """Raise :class:`PeerLostError` if any peer is declared lost — the
    between-steps poll point of a supervisor-managed worker: turning the
    daemon's background declaration into control flow at a safe boundary,
    *before* the next cross-process collective can block on a dead peer."""
    with _LOCK:
        lost = sorted(_LOST)
    if lost:
        raise PeerLostError(
            f"peer process(es) {lost} lost (missed lease beats in epoch "
            f"{_STATS['epoch']}): drain and exit for reform", peers=lost,
        )


def heartbeat_stats() -> Dict[str, Any]:
    """Alias of :func:`report_stats` under the name tests/operators expect
    next to :func:`start_heartbeat`."""
    return report_stats()


def reform_exit() -> None:
    """Drain this worker with :data:`REFORM_EXIT`, bypassing atexit.

    A survivor of a lost peer must NOT run the interpreter's normal exit
    path: JAX's atexit handler calls ``jax.distributed.shutdown()``, whose
    coordination-service shutdown barrier blocks on the dead peer (~100 s
    at the default client heartbeat timeout) and then LOG(FATAL)s the
    survivor (SIGABRT) — turning a clean drain into a second casualty.
    ``os._exit`` skips all of that; callers must have flushed any results
    to disk first (the lease daemon is stopped here)."""
    stop_heartbeat()
    try:
        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:  # pragma: no cover - broken pipes must not block exit
        pass
    os._exit(REFORM_EXIT)


def note_progress(step: int) -> None:
    """Publish this process's training progress (a step-number beacon under
    ``{mesh_dir}/progress/``). The launcher's chaos injector keys SIGKILLs
    off these ("kill rank 1 once it passes step 3"), and recovery timing
    reads the first beacon of a respawned generation. Best-effort: no mesh
    dir, no beacon."""
    mesh = mesh_dir()
    if not mesh:
        return
    path = _progress_path(mesh, mesh_epoch(), process_index())
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        _write_atomic(path, json.dumps({"step": int(step), "time": time.time()}))
    except OSError:  # pragma: no cover - beacon is best-effort
        pass


# ----------------------------------------------------------------------
# guarded bring-up: jax.distributed with retry, fault site, heartbeat
# ----------------------------------------------------------------------
def _transient_init_fault(exc: BaseException, policy) -> bool:
    """Whether a coordinator connect failure is worth a retry: connection
    errors and the transient-errno OSErrors always are; RuntimeErrors only
    when the message carries the coordination-service transient signatures
    (DEADLINE_EXCEEDED / UNAVAILABLE / refused / timed out)."""
    if isinstance(exc, ConnectionError):
        return True
    if isinstance(exc, OSError):
        return policy.is_transient(exc)
    if isinstance(exc, RuntimeError):
        msg = str(exc).lower()
        return any(
            key in msg
            for key in (
                "deadline", "unavailable", "timed out", "timeout",
                "connection refused", "failed to connect", "connection reset",
            )
        )
    return False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    heartbeat: bool = True,
    retries: Optional[int] = None,
    backoff_s: Optional[float] = None,
    **kwargs,
):
    """Bring up the multi-process runtime, guarded.

    Wraps ``communication.initialize`` (→ ``jax.distributed.initialize``)
    with the pieces a production bring-up needs:

    * **Configuration from the launcher env** — ``HEAT_TPU_COORDINATOR`` /
      ``HEAT_TPU_NUM_PROCESSES`` / ``HEAT_TPU_PROCESS_ID`` fill any argument
      left None, so a :func:`spawn_local` worker calls this with no
      arguments.
    * **CPU collectives** — a multi-process CPU world needs the gloo
      cross-process collective implementation; it is configured before the
      backend exists (the only time it can be).
    * **Retry with backoff** — transient coordinator connect faults (the
      coordinator's port not up yet, a connection reset mid-handshake) are
      retried with ``resilience.retry_policy``'s capped exponential backoff
      (``retries``/``backoff_s`` override). Non-transient faults propagate
      on the first attempt — error parity with the bare call.
    * **The ``multihost.init`` fault site** — checked before every connect
      attempt, so an injected ``ConnectionResetError`` exercises exactly the
      retry path a flaky coordinator would.
    * **Liveness** — with a mesh dir configured and a real multi-process
      world, the lease heartbeat daemon starts beating before this returns.

    Returns the refreshed default ``MeshCommunication`` spanning every
    process's devices. Idempotent like ``communication.initialize``."""
    from . import communication, resilience, telemetry

    env = os.environ
    if coordinator_address is None:
        coordinator_address = env.get("HEAT_TPU_COORDINATOR", "").strip() or None
    if num_processes is None:
        raw = env.get("HEAT_TPU_NUM_PROCESSES", "").strip()
        num_processes = int(raw) if raw else None
    if process_id is None:
        raw = env.get("HEAT_TPU_PROCESS_ID", "").strip()
        process_id = int(raw) if raw else None
    if (num_processes or 1) > 1:
        platforms = (env.get("JAX_PLATFORMS") or "").strip().lower()
        if platforms in ("", "cpu"):
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception:  # noqa: BLE001 - jaxlib without gloo: single-host only
                warnings.warn(
                    "gloo CPU collectives unavailable in this jaxlib; a "
                    "multi-process CPU mesh cannot run cross-process "
                    "collectives",
                    stacklevel=2,
                )
    policy = resilience.retry_policy
    attempts_left = policy.retries if retries is None else int(retries)
    delay = policy.base_delay if backoff_s is None else float(backoff_s)
    while True:
        try:
            if resilience._ARMED:
                resilience.check("multihost.init")
            comm = communication.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                **kwargs,
            )
            break
        except Exception as exc:  # noqa: BLE001 - classified below
            if attempts_left <= 0 or not _transient_init_fault(exc, policy):
                raise
            attempts_left -= 1
            with _LOCK:
                _STATS["init_retries"] += 1
            if telemetry._MODE:
                telemetry.record_event(
                    "distributed_init_retry", error=repr(exc), delay_s=delay
                )
            time.sleep(min(delay, policy.max_delay))
            delay *= 2.0
    world = process_count()
    with _LOCK:
        _STATS["world"] = world
        _STATS["epoch"] = mesh_epoch()
    if telemetry._MODE:
        telemetry.record_event(
            "distributed_init",
            world=world, process=process_index(), epoch=mesh_epoch(),
        )
    if heartbeat and world > 1:
        start_heartbeat(world=world)
    return comm


# ----------------------------------------------------------------------
# the local launcher: N coordinated processes, supervised across generations
# ----------------------------------------------------------------------
def _free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return int(s.getsockname()[1])


def _read_progress_step(mesh: str, epoch: int, proc: int) -> Optional[int]:
    try:
        with open(_progress_path(mesh, epoch, proc)) as fh:
            return int(json.load(fh).get("step"))
    except (OSError, ValueError, TypeError):
        return None


def _first_progress_time(mesh: str, epoch: int, world: int) -> Optional[float]:
    times = []
    for proc in range(world):
        try:
            times.append(os.stat(_progress_path(mesh, epoch, proc)).st_mtime)
        except OSError:
            pass
    return min(times) if times else None


def _read_lost_markers(mesh: str, epoch: int) -> FrozenSet[int]:
    lost = set()
    try:
        for name in os.listdir(_lost_dir(mesh, epoch)):
            if name.startswith("proc-"):
                try:
                    lost.add(int(name.split("-", 1)[1]))
                except ValueError:
                    pass
    except OSError:
        pass
    return frozenset(lost)


def _strip_device_count(flags: str) -> str:
    import re

    return re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags).strip()


def spawn_local(
    n: int,
    command: Sequence[str],
    *,
    mesh: Optional[str] = None,
    max_reforms: int = 1,
    devices_per_process: int = 1,
    barrier_timeout_ms: float = 30_000.0,
    heartbeat_ms: float = 200.0,
    peer_lost_ms: Optional[float] = None,
    drain_grace_ms: Optional[float] = None,
    env: Optional[Dict[str, str]] = None,
    timeout_s: float = 600.0,
    kill: Optional[Dict[str, Any]] = None,
    stdout=None,
) -> Dict[str, Any]:
    """Launch ``command`` as ``n`` coordinated worker processes on this
    machine and supervise them across reform generations.

    Every worker gets the launcher contract in its environment:
    ``HEAT_TPU_COORDINATOR`` (a fresh localhost port per generation),
    ``HEAT_TPU_PROCESS_ID`` / ``HEAT_TPU_NUM_PROCESSES`` (contiguous,
    re-ranked each generation), ``HEAT_TPU_MESH_DIR`` / ``HEAT_TPU_MESH_EPOCH``
    (the shared lease/marker dir and the generation number), barrier
    timeouts ON (``HEAT_TPU_BARRIER_TIMEOUT_MS``), and fast lease cadence
    (``HEAT_TPU_HEARTBEAT_MS`` / ``HEAT_TPU_PEER_LOST_MS``) — a worker that
    calls :func:`initialize_distributed` needs nothing else.

    Generation protocol: a generation ends when every child has exited. All
    zero → done. Otherwise the lost set is read from the lease daemon's
    markers (``{mesh}/lost/epoch-*/``) — detection evidence, robust to
    collateral crashes — falling back to "non-zero, non-reform exits" when
    no survivor lived long enough to write one. If at least one worker asked
    for reform (exit :data:`REFORM_EXIT`) and reforms remain, the survivors
    respawn as a smaller world under the next epoch (fresh port, re-ranked
    ids); workers are expected to restore from the newest verifying
    checkpoint themselves. Children still alive after every non-lost member
    exited (a SIGSTOP'd or hung lost peer) are SIGKILLed — the launcher
    guarantees a generation cannot hang.

    Chaos injection (the process-level fault injector): ``kill={"rank": r,
    "at_step": s}`` SIGKILLs rank ``r`` once its progress beacon
    (:func:`note_progress`) reaches step ``s`` (or ``{"rank": r,
    "after_s": t}`` on a timer) in epoch 0.

    Returns ``{"ok", "reforms", "generations": [...], "t_kill", "mesh"}``;
    each generation records its epoch, world size, exit codes, lost set and
    timing (``t_spawn`` / ``t_first_progress``)."""
    import tempfile

    if mesh is None:
        mesh = tempfile.mkdtemp(prefix="heat-tpu-mesh-")
    os.makedirs(mesh, exist_ok=True)
    command = [str(c) for c in command]
    generations: List[Dict[str, Any]] = []
    result: Dict[str, Any] = {
        "ok": False, "reforms": 0, "generations": generations,
        "t_kill": None, "mesh": mesh,
    }
    world = int(n)
    epoch = 0
    kill_pending = dict(kill) if kill else None
    while True:
        port = _free_port()
        base_env = dict(os.environ)
        if env:
            base_env.update(env)
        flags = _strip_device_count(base_env.get("XLA_FLAGS", ""))
        base_env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={devices_per_process}"
        ).strip()
        base_env.setdefault("JAX_PLATFORMS", "cpu")
        overrides = env or {}
        if "HEAT_TPU_BARRIER_TIMEOUT_MS" not in overrides:
            base_env["HEAT_TPU_BARRIER_TIMEOUT_MS"] = f"{barrier_timeout_ms:g}"
        if "HEAT_TPU_HEARTBEAT_MS" not in overrides:
            base_env["HEAT_TPU_HEARTBEAT_MS"] = f"{heartbeat_ms:g}"
        if "HEAT_TPU_PEER_LOST_MS" not in overrides:
            base_env["HEAT_TPU_PEER_LOST_MS"] = (
                f"{peer_lost_ms if peer_lost_ms is not None else 5.0 * heartbeat_ms:g}"
            )
        if "HEAT_TPU_DRAIN_GRACE_MS" not in overrides:
            # the zero-hang backstop: a survivor stuck in a collective with
            # a hung (not dead) peer forces reform_exit after this grace
            base_env["HEAT_TPU_DRAIN_GRACE_MS"] = (
                f"{drain_grace_ms if drain_grace_ms is not None else max(2_000.0, 10.0 * heartbeat_ms):g}"
            )
        base_env["HEAT_TPU_MESH_DIR"] = mesh
        base_env["HEAT_TPU_MESH_EPOCH"] = str(epoch)
        base_env["HEAT_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
        base_env["HEAT_TPU_NUM_PROCESSES"] = str(world)
        procs: List[subprocess.Popen] = []
        t_spawn = time.time()
        for rank in range(world):
            penv = dict(base_env)
            penv["HEAT_TPU_PROCESS_ID"] = str(rank)
            procs.append(
                subprocess.Popen(command, env=penv, stdout=stdout, stderr=stdout)
            )
        gen: Dict[str, Any] = {
            "epoch": epoch, "world": world, "exits": [None] * world,
            "lost": [], "t_spawn": t_spawn, "t_first_progress": None,
            "timed_out": False,
        }
        generations.append(gen)
        deadline = time.monotonic() + float(timeout_s)
        kill_fired_at = None
        while any(p.poll() is None for p in procs):
            if gen["t_first_progress"] is None:
                gen["t_first_progress"] = _first_progress_time(mesh, epoch, world)
            if kill_pending is not None and epoch == 0:
                rank = int(kill_pending.get("rank", world - 1))
                due = False
                if "at_step" in kill_pending:
                    step = _read_progress_step(mesh, epoch, rank)
                    due = step is not None and step >= int(kill_pending["at_step"])
                if "after_s" in kill_pending:
                    due = due or (time.time() - t_spawn) >= float(kill_pending["after_s"])
                if due and rank < world and procs[rank].poll() is None:
                    procs[rank].kill()
                    result["t_kill"] = kill_fired_at = time.time()
                    kill_pending = None
            # a lost-but-still-running child (SIGSTOP'd, hung in a dead
            # collective) must not hold the generation open once every live
            # member has exited: the launcher is the hang backstop
            marked = _read_lost_markers(mesh, epoch)
            if marked and all(
                procs[r].poll() is not None for r in range(world) if r not in marked
            ):
                for r in sorted(marked):
                    if r < world and procs[r].poll() is None:
                        procs[r].kill()
            if time.monotonic() > deadline:
                gen["timed_out"] = True
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                break
            time.sleep(0.05)
        for p in procs:
            p.wait()
        gen["exits"] = [p.returncode for p in procs]
        if gen["t_first_progress"] is None:
            gen["t_first_progress"] = _first_progress_time(mesh, epoch, world)
        gen["duration_s"] = round(time.time() - t_spawn, 3)
        if gen["timed_out"]:
            return result
        if all(rc == 0 for rc in gen["exits"]):
            result["ok"] = True
            return result
        lost = set(_read_lost_markers(mesh, epoch))
        if kill_fired_at is not None and kill is not None:
            lost.add(int(kill.get("rank", world - 1)))
        # detection evidence first; exit-code forensics only as fallback
        lost = {r for r in lost if r < world and gen["exits"][r] != 0} or {
            r for r, rc in enumerate(gen["exits"]) if rc not in (0, REFORM_EXIT)
        }
        gen["lost"] = sorted(lost)
        asked_reform = any(rc == REFORM_EXIT for rc in gen["exits"])
        survivors = world - len(lost)
        if not asked_reform or survivors < 1 or result["reforms"] >= int(max_reforms):
            return result
        world = survivors
        epoch += 1
        result["reforms"] += 1


# report()["multihost"]: the set-attribute hook pattern (telemetry stays
# dependency-free; the ops plane reads the same hook for its gauges)
from . import telemetry as _telemetry  # noqa: E402

_telemetry._MULTIHOST_HOOK = report_stats

"""Logical operations (reference: heat/core/logical.py:38-531).

``all``/``any`` are reductions — the reference uses MPI.LAND/LOR Allreduces
(logical.py:38-209); here they are sharded ``jnp`` reductions.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np

from . import types
from ._operations import __binary_op as _binary_op
from ._operations import __local_op as _local_op
from ._operations import __reduce_op as _reduce_op
from .dndarray import DNDarray

__all__ = [
    "all",
    "allclose",
    "any",
    "isclose",
    "isfinite",
    "isinf",
    "isnan",
    "isneginf",
    "isposinf",
    "logical_and",
    "logical_not",
    "logical_or",
    "logical_xor",
    "signbit",
]


def all(x, axis=None, out=None, keepdims=False, keepdim=None) -> DNDarray:
    """True where all elements (over axis) are truthy (reference logical.py:38).
    ``keepdim`` is the reference's torch-style alias for ``keepdims``."""
    res = _reduce_op(jnp.all, x, axis, out=out, keepdims=keepdims if keepdim is None else keepdim)
    return res


def allclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> bool:
    """Scalar closeness check (reference logical.py:96)."""
    close = isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)
    return bool(jnp.all(close.larray).item())


def any(x, axis=None, out=None, keepdims=False, keepdim=None) -> DNDarray:
    """True where any element (over axis) is truthy (reference logical.py:145).
    ``keepdim`` is the reference's torch-style alias for ``keepdims``."""
    return _reduce_op(jnp.any, x, axis, out=out, keepdims=keepdims if keepdim is None else keepdim)


def isclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> DNDarray:
    """Elementwise closeness (reference logical.py:212). The tolerances ride
    as static ``fn_kwargs`` (a per-call lambda would defeat the fusion
    engine's program cache)."""
    res = _binary_op(
        jnp.isclose, x, y, fn_kwargs=dict(rtol=rtol, atol=atol, equal_nan=equal_nan)
    )
    return res.astype(types.bool, copy=False) if res.dtype is not types.bool else res


def isfinite(x) -> DNDarray:
    """Elementwise finiteness test (reference logical.py:249)."""
    return _local_op(jnp.isfinite, x, no_cast=True)


def isinf(x) -> DNDarray:
    """Elementwise infinity test (reference logical.py:275)."""
    return _local_op(jnp.isinf, x, no_cast=True)


def isnan(x) -> DNDarray:
    """Elementwise NaN test (reference logical.py:301)."""
    return _local_op(jnp.isnan, x, no_cast=True)


def isneginf(x, out=None) -> DNDarray:
    """Elementwise -inf test (reference logical.py:327)."""
    return _local_op(jnp.isneginf, x, out=out, no_cast=True)


def isposinf(x, out=None) -> DNDarray:
    """Elementwise +inf test (reference logical.py:353)."""
    return _local_op(jnp.isposinf, x, out=out, no_cast=True)


def logical_and(t1, t2) -> DNDarray:
    """Elementwise logical AND (reference logical.py:379)."""
    return _binary_op(jnp.logical_and, _bool(t1), _bool(t2))


def logical_not(t, out=None) -> DNDarray:
    """Elementwise logical NOT (reference logical.py:409)."""
    return _local_op(jnp.logical_not, t, out=out, no_cast=True)


def logical_or(t1, t2) -> DNDarray:
    """Elementwise logical OR (reference logical.py:435)."""
    return _binary_op(jnp.logical_or, _bool(t1), _bool(t2))


def logical_xor(t1, t2) -> DNDarray:
    """Elementwise logical XOR (reference logical.py:465)."""
    return _binary_op(jnp.logical_xor, t1, t2)


def _bool(t):
    if isinstance(t, DNDarray) and t.dtype is not types.bool:
        return t.astype(types.bool)
    return t


def signbit(x, out=None) -> DNDarray:
    """True where the sign bit is set (reference logical.py:495)."""
    return _local_op(jnp.signbit, x, out=out, no_cast=True)

"""Complex number operations (reference: heat/core/complex_math.py)."""

from __future__ import annotations

import jax.numpy as jnp

from . import types
from ._operations import __local_op as _local_op
from .dndarray import DNDarray

__all__ = ["angle", "conj", "conjugate", "imag", "real"]


def angle(x, deg: bool = False, out=None) -> DNDarray:
    """Argument of complex values (reference complex_math.py:14). ``deg``
    rides as a static kwarg (a per-call lambda would defeat the fusion
    engine's program cache)."""
    return _local_op(jnp.angle, x, out=out, no_cast=True, deg=deg)


def conjugate(x, out=None) -> DNDarray:
    """Elementwise complex conjugate (reference complex_math.py:58)."""
    return _local_op(jnp.conjugate, x, out=out, no_cast=True)


conj = conjugate


def imag(x) -> DNDarray:
    """Imaginary part (reference complex_math.py:96)."""
    if not types.heat_type_is_complexfloating(x.dtype):
        from . import factories

        return factories.zeros_like(x)
    return _local_op(jnp.imag, x, no_cast=True)


def real(x) -> DNDarray:
    """Real part (reference complex_math.py:124)."""
    if not types.heat_type_is_complexfloating(x.dtype):
        return x
    return _local_op(jnp.real, x, no_cast=True)

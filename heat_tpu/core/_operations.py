"""Generic operator engines.

TPU-native re-design of reference heat/core/_operations.py: the four private
generics every operator routes through. The reference implements split
dominance + redistribution (:151-176), neutral-element fills for empty shards
(:425-434), Allreduce for cross-split reductions (:463-468) and Exscan-based
cumops (:268-295) by hand; here local compute *and* collectives are a single
``jnp`` call on globally-sharded arrays — GSPMD inserts the psum/resharding —
and the engine's job shrinks to dtype promotion, split bookkeeping and output
sharding constraints.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import devices, fusion, resilience, sanitation, telemetry, types
from .communication import sanitize_comm
from .dndarray import DNDarray, _ensure_split
from .stride_tricks import broadcast_shape, sanitize_axis

__all__ = []  # private module, mirrors the reference


def _nonfinite_checked(res: DNDarray) -> DNDarray:
    """Numeric error policy (``ht.errstate``) for the eager engines: an op
    that does not defer (``out=``/``where=``, foreign operands, fusion off)
    never reaches a forcing point, so the check runs on the op's own logical
    result — per-op error locality, exactly the reference's model. One
    module-attribute read when the policy is off."""
    if resilience._ERRSTATE is not None or resilience._TLS_ARMED:
        resilience.check_nonfinite(res.larray, "eager")
    return res


def _as_operand(x, comm, device):
    """Normalize scalars / numpy / DNDarray to (jax_array_or_scalar, split)."""
    if isinstance(x, DNDarray):
        return x.larray, x.split
    if isinstance(x, (int, float, bool, complex, np.number, np.bool_)):
        return x, None
    if isinstance(x, (np.ndarray, list, tuple)):
        return jnp.asarray(x), None
    if isinstance(x, jax.Array):
        return x, None
    raise TypeError(f"unsupported operand type: {type(x)}")


def __binary_op(
    operation: Callable,
    t1,
    t2,
    out: Optional[DNDarray] = None,
    where=None,
    fn_kwargs: Optional[dict] = None,
) -> DNDarray:
    """Generic distributed binary operation (reference _operations.py:24-205).

    Split dominance: the result is distributed along the first operand's split
    if set, else the second's (:151-172); operands under other layouts are
    resharded by XLA during the op itself.
    """
    fn_kwargs = fn_kwargs or {}
    if not isinstance(t1, DNDarray) and not isinstance(t2, DNDarray):
        raise TypeError(f"Only DNDarrays and numeric scalars are supported, but input was {type(t1)}, {type(t2)}")
    ref = t1 if isinstance(t1, DNDarray) else t2
    comm, device = ref.comm, ref.device

    # implicit-reshard-made-explicit (heat-verify S101): identical-shape
    # operands on DIFFERENT split axes would otherwise be resharded by XLA
    # inside the op itself — a collective invisible to telemetry, the fault
    # registry and the fusion DAG. Route the non-dominant operand through
    # the explicit resplit seam instead: the redistribution records as a
    # DAG node (fusion.defer_reshard) or an eager reshard, fires its
    # collective.reshard fault site, and banks its logical bytes in the
    # collective ledger as a "reshard". Broadcasted (different-shape)
    # combinations keep XLA's behavior — the static verifier still flags
    # both.
    if (
        isinstance(t1, DNDarray)
        and isinstance(t2, DNDarray)
        and t1.split is not None
        and t2.split is not None
        and t1.split != t2.split
        and t1.shape == t2.shape
    ):
        from .manipulations import resplit as _explicit_resplit

        if telemetry._MODE:
            # the collective.reshard fault site fires inside _explicit_resplit
            # heat-lint: disable=H005 — one dispatch, one site (in resplit below)
            telemetry.record_collective(
                "reshard",
                comm.axis_name,
                int(np.prod(t2.shape, dtype=np.int64))
                * np.dtype(t2.dtype.jax_type()).itemsize,
                str(t2.dtype),
            )
        t2 = _explicit_resplit(t2, t1.split)

    # dtype promotion (reference _operations.py:87): operands are cast to the
    # promoted type BEFORE the op so op-induced promotion (e.g. true_divide of
    # integers -> float) is preserved rather than clobbered afterwards.
    out_dtype = types.result_type(t1, t2)
    jt = out_dtype.jax_type()

    # fusion recorder (core/fusion.py): defer the op into the expression DAG
    # instead of dispatching it; the whole chain becomes one cached jitted
    # program at the next forcing point. Ineligible combinations (out=/where=
    # buffers, padded broadcasts, tracer payloads, unhashable kwargs) fall
    # through to the eager engine below unchanged.
    if out is None and where is None and fusion.active() and fusion.hashable_kwargs(fn_kwargs):
        lazy = fusion.defer_binary(operation, t1, t2, jt, fn_kwargs)
        if lazy is not None:
            if telemetry._MODE:
                telemetry.record_dispatch("binary", fused=True)
            return lazy
        # defer_binary left its own (detailed) unfused breadcrumb
    elif telemetry._MODE:
        telemetry.record_unfused(
            "binary",
            "out="
            if out is not None
            else "where=" if where is not None
            else "fusion_off" if not fusion.active()
            else "unhashable_kwargs",
        )
    if telemetry._MODE:
        telemetry.record_dispatch("binary", fused=False)

    # pad-aware fast path: identical-layout ragged operands (or ragged⊗scalar)
    # compute directly on the physical payloads — the padding suffix computes
    # garbage that stays in the padding, no reshard/gather happens, and the
    # result keeps the block layout (SURVEY.md §7 pad+mask).
    scalar_types = (int, float, bool, complex, np.number, np.bool_)
    if where is None:
        phys = None
        if (
            isinstance(t1, DNDarray)
            and isinstance(t2, DNDarray)
            and t1.split == t2.split
            and t1.shape == t2.shape
            and t1.padded
        ):
            phys = (t1.parray.astype(jt), t2.parray.astype(jt))
            out_shape, out_split = t1.shape, t1.split
        elif isinstance(t1, DNDarray) and t1.padded and isinstance(t2, scalar_types):
            phys = (t1.parray.astype(jt), jnp.asarray(t2, dtype=jt))
            out_shape, out_split = t1.shape, t1.split
        elif isinstance(t2, DNDarray) and t2.padded and isinstance(t1, scalar_types):
            phys = (jnp.asarray(t1, dtype=jt), t2.parray.astype(jt))
            out_shape, out_split = t2.shape, t2.split
        if phys is not None:
            result = operation(phys[0], phys[1], **fn_kwargs)
            wrapped = DNDarray(
                result, out_shape, types.canonical_heat_type(result.dtype), out_split, device, comm
            )
            if out is not None:
                sanitation.sanitize_out(out, out_shape, out_split, device)
                out._replace(
                    result.astype(out.dtype.jax_type()), out_split, gshape=out_shape
                )
                return _nonfinite_checked(out)
            return _nonfinite_checked(wrapped)

    a, s1 = _as_operand(t1, comm, device)
    b, s2 = _as_operand(t2, comm, device)
    a = jnp.asarray(a, dtype=jt)
    b = jnp.asarray(b, dtype=jt)

    # shape check for error parity (reference _operations.py:110-122)
    sh1 = tuple(getattr(t1, "shape", np.shape(a) if not np.isscalar(a) else ()))
    sh2 = tuple(getattr(t2, "shape", np.shape(b) if not np.isscalar(b) else ()))
    out_shape = broadcast_shape(sh1, sh2)

    # split dominance (reference _operations.py:151-172), adjusted for broadcast offset
    def _bcast_split(split, shape):
        if split is None:
            return None
        return split + (len(out_shape) - len(shape))

    out_split = _bcast_split(s1, sh1)
    if out_split is None:
        out_split = _bcast_split(s2, sh2)

    result = operation(a, b, **fn_kwargs)
    if where is not None:
        w = where.larray if isinstance(where, DNDarray) else jnp.asarray(where)
        base = out.larray if out is not None else jnp.zeros(out_shape, result.dtype)
        result = jnp.where(w, result, base)

    if out_split is not None and out_split >= result.ndim:
        out_split = None
    result = _ensure_split(result, out_split, comm)
    wrapped = DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype), out_split, device, comm
    )
    if out is not None:
        sanitation.sanitize_out(out, out_shape, out_split, device)
        out._replace(
            wrapped.parray.astype(out.dtype.jax_type()), out_split, gshape=wrapped.shape
        )
        return _nonfinite_checked(out)
    return _nonfinite_checked(wrapped)


def __local_op(
    operation: Callable,
    x: DNDarray,
    out: Optional[DNDarray] = None,
    no_cast: bool = False,
    **kwargs,
) -> DNDarray:
    """Generic elementwise operation with no communication (reference
    _operations.py:305-376). Promotes exact types to floating unless
    ``no_cast``."""
    sanitation.sanitize_in(x)
    # fusion recorder: shape-preserving unary ops defer into the chain DAG
    if out is None and fusion.active():
        promote = None
        if not no_cast and types.heat_type_is_exact(x.dtype):
            promote = types.promote_types(x.dtype, types.float32).jax_type()
        lazy = fusion.defer_local(operation, x, promote, kwargs)
        if lazy is not None:
            if telemetry._MODE:
                telemetry.record_dispatch("local", fused=True)
            return lazy
        # defer_local left its own (detailed) unfused breadcrumb
    elif telemetry._MODE:
        telemetry.record_unfused("local", "out=" if out is not None else "fusion_off")
    if telemetry._MODE:
        telemetry.record_dispatch("local", fused=False)
    padded = x.padded
    # pad-aware fast path: elementwise on the physical payload; the padding
    # suffix computes garbage that stays in the padding (SURVEY.md §7)
    arr = x.parray if padded else x.larray
    if not no_cast and types.heat_type_is_exact(x.dtype):
        target = types.promote_types(x.dtype, types.float32)
        arr = arr.astype(target.jax_type())
    result = operation(arr, **kwargs)
    if padded and result.shape != arr.shape:
        # shape-changing op: redo from the logical view (rare; elementwise
        # ops — the whole local_op clientele — never take this branch)
        arr = x.larray
        if not no_cast and types.heat_type_is_exact(x.dtype):
            arr = arr.astype(types.promote_types(x.dtype, types.float32).jax_type())
        result = operation(arr, **kwargs)
        padded = False
    if padded:
        wrapped = DNDarray(
            result,
            x.shape,
            types.canonical_heat_type(result.dtype),
            x.split,
            x.device,
            x.comm,
        )
    else:
        result = _ensure_split(result, x.split if result.ndim == x.ndim else None, x.comm)
        wrapped = DNDarray(
            result,
            tuple(result.shape),
            types.canonical_heat_type(result.dtype),
            x.split if result.ndim == x.ndim else None,
            x.device,
            x.comm,
        )
    if out is not None:
        sanitation.sanitize_out(out, wrapped.shape, wrapped.split, wrapped.device)
        out._replace(
            wrapped.parray.astype(out.dtype.jax_type()), wrapped.split, gshape=wrapped.shape
        )
        return _nonfinite_checked(out)
    return _nonfinite_checked(wrapped)


def __reduce_op(
    partial_op: Callable,
    x: DNDarray,
    axis: Optional[Union[int, Tuple[int, ...]]],
    out: Optional[DNDarray] = None,
    keepdims: bool = False,
    dtype=None,
    initial=None,
    **kwargs,
) -> DNDarray:
    """Generic distributed reduction (reference _operations.py:393-505).

    The reference runs the local partial op then an Allreduce when the split
    axis is reduced (:463-468); here one ``jnp`` reduction over the sharded
    global array lets XLA choose the partial-reduce + psum schedule.
    """
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)

    # split bookkeeping (reference _operations.py:470-490)
    split = x.split
    axes = None if axis is None else ((axis,) if isinstance(axis, int) else tuple(axis))
    if split is None or axes is None:
        out_split = None
    elif split in axes:
        out_split = None
    elif keepdims:
        out_split = split
    else:
        out_split = split - sum(1 for a in axes if a < split)

    # fusion recorder: reductions defer too, so a chain ending in (or mixing)
    # k reductions costs one program + one device sync at the forcing point
    # instead of k dispatches (``initial`` is accepted-and-ignored exactly as
    # in the eager path below). A deferred reduction ACROSS the split axis is
    # a collective NODE: its psum is GSPMD-inserted inside the fused program
    # (no dispatch-time verb call), so it is counted in the fused-collective
    # ledger and cross-checked against the program HLO, not collective_counts
    if out is None and fusion.active():
        lazy = fusion.defer_reduce(partial_op, x, axis, keepdims, out_split, dtype, kwargs)
        if lazy is not None:
            if telemetry._MODE:
                telemetry.record_dispatch("reduce", fused=True)
                if (
                    split is not None
                    and (axes is None or split in axes)
                    and x.comm.is_distributed()
                ):
                    telemetry.record_fused_collective("reduce.psum")
            return lazy
        # defer_reduce left its own (detailed) unfused breadcrumb
    elif telemetry._MODE:
        telemetry.record_unfused("reduce", "out=" if out is not None else "fusion_off")
    if telemetry._MODE:
        telemetry.record_dispatch("reduce", fused=False)

    # pad-aware fast path: reducing only non-split axes of a ragged array —
    # the padding suffix reduces into the (shifted) padding suffix of the
    # result, so the physical payload can be reduced directly with no
    # reshard/gather. Reductions ACROSS the split axis take the logical view
    # (the mask step of pad+mask: padding must not enter the reduction).
    padded_fast = x.padded and axes is not None and split not in axes
    src = x.parray if padded_fast else x.larray
    result = partial_op(src, axis=axis, keepdims=keepdims, **kwargs)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype).jax_type())

    if out_split is not None and (result.ndim == 0 or out_split >= result.ndim):
        out_split = None
    if padded_fast:
        gshape = list(x.shape)
        for a in sorted(axes, reverse=True):
            if keepdims:
                gshape[a] = 1
            else:
                del gshape[a]
        wrapped = DNDarray(
            result,
            tuple(gshape),
            types.canonical_heat_type(result.dtype),
            out_split,
            x.device,
            x.comm,
        )
    else:
        result = _ensure_split(result, out_split, x.comm)
        wrapped = DNDarray(
            result,
            tuple(result.shape),
            types.canonical_heat_type(result.dtype),
            out_split,
            x.device,
            x.comm,
        )
    if out is not None:
        sanitation.sanitize_out(out, wrapped.shape, wrapped.split, wrapped.device)
        out._replace(
            wrapped.parray.astype(out.dtype.jax_type()), wrapped.split, gshape=wrapped.shape
        )
        return _nonfinite_checked(out)
    return _nonfinite_checked(wrapped)


def __cum_op(
    operation: Callable,
    x: DNDarray,
    axis: int,
    out: Optional[DNDarray] = None,
    dtype=None,
) -> DNDarray:
    """Generic cumulative operation (reference _operations.py:208-302: local
    cumop + Exscan + final combine; here XLA decomposes the sharded-axis scan)."""
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if not isinstance(axis, int):
        raise TypeError("axis must be a single integer for cumulative operations")
    # fusion recorder: cumulative ops are shape-preserving and pad-safe
    if out is None and fusion.active():
        lazy = fusion.defer_cum(operation, x, axis, dtype)
        if lazy is not None:
            if telemetry._MODE:
                telemetry.record_dispatch("cum", fused=True)
            return lazy
        # defer_cum left its own (detailed) unfused breadcrumb
    elif telemetry._MODE:
        telemetry.record_unfused("cum", "out=" if out is not None else "fusion_off")
    if telemetry._MODE:
        telemetry.record_dispatch("cum", fused=False)
    # pad-aware fast path: the padding is a *suffix* of the global split dim,
    # so a cumulative op along ANY axis leaves the data region untouched —
    # along the split axis the garbage only accumulates past position n,
    # along other axes padding rows stay padding rows.
    padded = x.padded
    result = operation(x.parray if padded else x.larray, axis=axis)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype).jax_type())
    if padded:
        wrapped = DNDarray(
            result, x.shape, types.canonical_heat_type(result.dtype), x.split, x.device, x.comm
        )
    else:
        result = _ensure_split(result, x.split, x.comm)
        wrapped = DNDarray(
            result, tuple(result.shape), types.canonical_heat_type(result.dtype), x.split, x.device, x.comm
        )
    if out is not None:
        sanitation.sanitize_out(out, wrapped.shape, wrapped.split, wrapped.device)
        out._replace(
            wrapped.parray.astype(out.dtype.jax_type()), wrapped.split, gshape=wrapped.shape
        )
        return _nonfinite_checked(out)
    return _nonfinite_checked(wrapped)

"""SLO-driven autoscaling: the control loop that closes the serving ×
elastic × opsplane triangle (ROADMAP item 6).

The runtime *measures* overload in three places — serving's admission
buckets, opsplane's multi-window SLO burn-rate alerts, health_runtime's
breach windows — but none of them *acts*. This module is the supervisor
that does: a daemon poll (the ``elastic.Supervisor`` idiom) observes those
gauges and drives three actuators:

1. **Tiered load shedding** — under overload, dispatches from shed-tier
   (``batch``/``preemptible``) sessions are refused with a typed
   :class:`~heat_tpu.core.serving.ShedError` while interactive traffic
   keeps its admission tokens. Refused chains stay PENDING (the
   ``_DRAIN_EXCLUDE`` contract): never degraded, never double-dispatched —
   they dispatch cleanly once shedding lifts.
2. **Deadline-aware dispatch ordering** — serving installs
   ``fusion._ROOT_PRIORITY`` so the cross-session batch window orders
   roots by (tier, session deadline) instead of arrival; a
   latency-sensitive root is never convoyed behind a batch tenant.
3. **Mesh shrink/recover through the elastic seams** — sustained burn
   triggers a shrink-style reform (drain under admission hold → probe →
   ``communication.reform`` → guard/fault reset, a forensics bundle on
   every action); after the burn clears and a cooldown passes, the
   controller re-forms back to the full device set.

The state machine is deliberately conservative: hysteresis (a burn must
*persist* before the mesh moves, and must stay *clear* through a cooldown
before recovery), a ``max_actions`` budget on mesh moves and a
``min_devices`` floor mean the controller can never flap or scale the
mesh out from under itself. Failure is bounded-and-loud: an
:class:`~heat_tpu.core.elastic.ElasticError` from an actuator is counted,
dumped and warned — the loop survives, the mesh stays where it was.

Every decision lands as a telemetry event and on the
``heat_tpu_autoscale_*`` opsplane families (via the
``telemetry._AUTOSCALE_HOOK`` set-attribute seam installed at the bottom
of this module); ``/readyz`` flips unready while shedding is active.

Arming::

    ht.autoscale.arm()                       # defaults, daemon poll
    ht.autoscale.arm(cooldown_s=5.0, max_actions=2)
    ht.autoscale.disarm()                    # stop + un-shed + recover

or via the environment: ``HEAT_TPU_AUTOSCALE=1`` arms at import with the
``HEAT_TPU_AUTOSCALE_*`` knobs below (malformed values warn and keep
defaults — a broken knob never kills an import).
"""

import os
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import communication, health_runtime, memledger, resilience, telemetry
from .elastic import ElasticError, probe_devices

__all__ = [
    "Controller",
    "arm",
    "disarm",
    "poll",
    "armed",
    "stats",
    "status",
    "reset",
]


# ----------------------------------------------------------------------
# env knobs (warn-and-keep-default: observability/control config must
# never crash an import)
# ----------------------------------------------------------------------
def _env_float(name: str, default: float, minimum: float = 0.0) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError:
        warnings.warn(
            f"{name}={raw!r} is not a number; using {default}", stacklevel=2
        )
        return default
    if value < minimum:
        warnings.warn(
            f"{name}={raw!r} is below the floor {minimum}; using {default}",
            stacklevel=2,
        )
        return default
    return value


def _env_int(name: str, default: int, minimum: int = 0) -> int:
    return int(_env_float(name, float(default), float(minimum)))


def _env_tiers(name: str, default: Tuple[str, ...]) -> Tuple[str, ...]:
    from . import serving

    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    tiers = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        resolved = serving._TIER_ALIASES.get(part, part)
        if resolved not in serving._TIERS:
            warnings.warn(
                f"{name}={raw!r}: unknown tier {part!r}; using {default}",
                stacklevel=2,
            )
            return default
        tiers.append(resolved)
    return tuple(tiers) or default


def _defaults() -> Dict[str, Any]:
    return {
        "interval_s": _env_float("HEAT_TPU_AUTOSCALE_INTERVAL_S", 1.0, 0.01),
        "cooldown_s": _env_float("HEAT_TPU_AUTOSCALE_COOLDOWN_S", 30.0),
        "shrink_after_s": _env_float("HEAT_TPU_AUTOSCALE_SHRINK_AFTER_S", 10.0),
        "max_actions": _env_int("HEAT_TPU_AUTOSCALE_MAX_ACTIONS", 4),
        "min_devices": _env_int("HEAT_TPU_AUTOSCALE_MIN_DEVICES", 1, 1),
        "shrink_n": _env_int("HEAT_TPU_AUTOSCALE_SHRINK", 1),
        "shed_tiers": _env_tiers("HEAT_TPU_AUTOSCALE_SHED_TIERS", ("batch",)),
    }


# ----------------------------------------------------------------------
# the controller
# ----------------------------------------------------------------------
class Controller:
    """One process's overload supervisor: observe → decide → act.

    States: ``ok`` → (burn rising edge) → ``shedding`` → (burn persists
    ``shrink_after_s``) → ``shrunk`` → (burn clear for ``cooldown_s``) →
    ``ok`` (shed lifted, mesh re-formed to the full set). The burn
    re-rising during the cooldown restarts it — the only path back to
    ``ok`` is a *sustained* clear.

    Parameters
    ----------
    interval_s : float
        Daemon poll cadence; :func:`opsplane.on_burn` edges wake the loop
        early so reaction is event-driven, the poll is the fallback.
    cooldown_s : float
        How long the burn must stay clear before shedding lifts and the
        mesh recovers (the anti-flap hysteresis on the way down).
    shrink_after_s : float
        How long shedding alone must fail to clear the burn before the
        controller moves the mesh (the hysteresis on the way up).
    max_actions : int
        Budget on mesh moves (shrink + recover both count). When spent,
        the controller keeps shedding but the mesh holds still — a
        ``bound`` decision is recorded once per saturation.
    min_devices : int
        Floor under the shrunken mesh; a shrink that would cross it is
        refused (``ElasticError``, counted + dumped, loop survives).
    shrink_n : int
        Tail devices shed per shrink action.
    shed_tiers : sequence of str
        Tiers flipped to shed under overload (default ``("batch",)``).
    """

    def __init__(
        self,
        interval_s: float = 1.0,
        cooldown_s: float = 30.0,
        shrink_after_s: float = 10.0,
        max_actions: int = 4,
        min_devices: int = 1,
        shrink_n: int = 1,
        shed_tiers: Sequence[str] = ("batch",),
    ) -> None:
        from . import elastic, serving

        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        if min_devices < 1:
            raise ValueError(f"min_devices must be >= 1, got {min_devices}")
        self.interval_s = float(interval_s)
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.shrink_after_s = max(0.0, float(shrink_after_s))
        self.max_actions = max(0, int(max_actions))
        self.min_devices = int(min_devices)
        self.shrink_n = max(0, int(shrink_n))
        self.drain_ms = elastic._parse_drain_ms()
        resolved = []
        for t in shed_tiers:
            t = serving._TIER_ALIASES.get(t, t)
            if t not in serving._TIERS:
                raise ValueError(
                    f"unknown tier {t!r}: tiers are {serving._TIERS} "
                    f"(alias {tuple(serving._TIER_ALIASES)})"
                )
            resolved.append(t)
        self.shed_tiers: Tuple[str, ...] = tuple(resolved)

        self._lock = threading.RLock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._unsubscribe: Optional[Callable[[], None]] = None

        self.state = "ok"
        self._burn_since: Optional[float] = None  # monotonic, rising edge
        self._clear_since: Optional[float] = None  # monotonic, falling edge
        self._baseline: Optional[int] = None  # mesh size before first shrink
        self._shrunk = False
        self._bound_noted = False
        self.mesh_actions = 0
        self.ticks = 0
        self.burn_edges = 0
        self.decisions: Dict[str, int] = {
            "shed_on": 0,
            "shed_off": 0,
            "shrink": 0,
            "recover": 0,
            "bound": 0,
            "errors": 0,
        }
        self.last_decision: Optional[Dict[str, Any]] = None

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        """Arm: subscribe to burn edges and start the daemon poll."""
        from . import opsplane

        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._unsubscribe = opsplane.on_burn(self._on_burn)
            self._thread = threading.Thread(
                target=self._run, name="heat-tpu-autoscale", daemon=True
            )
            self._thread.start()

    def stop(self, restore: bool = True) -> None:
        """Disarm: stop the poll, unsubscribe, lift shedding and (with
        ``restore``) re-form a shrunken mesh back to the full set. A
        failing restore is bounded-and-loud (counted + warned), never
        raised out of the disarm."""
        with self._lock:
            thread, self._thread = self._thread, None
            unsub, self._unsubscribe = self._unsubscribe, None
        self._stop.set()
        self._wake.set()
        if unsub is not None:
            unsub()
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=max(5.0, 2 * self.interval_s))
        with self._lock:
            obs = {"reason": "disarm"}
            if self.state != "ok":
                self._act_shed_off(obs)
            if restore and self._shrunk:
                try:
                    self._act_recover(obs)
                except ElasticError as exc:
                    self._note_error("recover", exc, obs)
            self.state = "ok"
            self._burn_since = None
            self._clear_since = None

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.tick()
            # the loop is the protection: it must survive anything an
            # actuator or observer throws
            # heat-lint: disable=H003 — supervisor loops never die
            except Exception as exc:  # noqa: BLE001
                self.decisions["errors"] += 1
                warnings.warn(f"autoscale tick failed: {exc!r}", stacklevel=2)

    def _on_burn(self, metric: str, tenant: str, rising: bool, snapshot) -> None:
        """:func:`opsplane.on_burn` subscriber — runs on the ticking
        thread after the burn lock released; just wakes the loop so the
        decision (which may drain + reform) happens on our own thread."""
        self.burn_edges += 1
        self._wake.set()

    # -- observe --------------------------------------------------------
    def _observe(self) -> Dict[str, Any]:
        """Snapshot the three gauge families the controller consumes:
        active burn alerts, SLO breach fractions and the projected global
        admission tokens. Each read is independently fault-isolated."""
        from . import opsplane, serving

        obs: Dict[str, Any] = {"burn": [], "breach": {}, "tokens": None}
        try:
            alerts = opsplane.burn_report()["alerts"]
            obs["burn"] = sorted(
                key for key, row in alerts.items() if row.get("active")
            )
        except Exception:  # noqa: BLE001 - a broken gauge never stops the loop
            pass
        for metric in ("dispatch", "sync", "compile"):
            try:
                frac = health_runtime.breach_fraction(metric)
            except Exception:  # noqa: BLE001
                frac = None
            if frac is not None:
                obs["breach"][metric] = round(frac, 4)
        try:
            with serving._LOCK:
                bucket = serving._GLOBAL_BUCKET
            if bucket is not None:
                from .opsplane import _bucket_tokens

                obs["tokens"] = round(_bucket_tokens(bucket), 3)
        except Exception:  # noqa: BLE001
            pass
        return obs

    # -- decide ---------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One observe → decide → act pass. Returns the action taken (or
        None). Thread-safe; tests drive it directly via :func:`poll`."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self.ticks += 1
            obs = self._observe()
            overloaded = bool(obs["burn"])
            if overloaded:
                self._clear_since = None
                if self._burn_since is None:
                    self._burn_since = now
                if self.state == "ok":
                    self._act_shed_on(obs)
                    self.state = "shedding"
                    return "shed_on"
                if (
                    self.state == "shedding"
                    and now - self._burn_since >= self.shrink_after_s
                ):
                    return self._try_shrink(obs)
                return None
            # burn clear: hysteresis on the way down
            self._burn_since = None
            if self.state == "ok":
                return None
            if self._clear_since is None:
                self._clear_since = now
            if now - self._clear_since < self.cooldown_s:
                return None
            action = None
            if self._shrunk:
                try:
                    self._act_recover(obs)
                    action = "recover"
                except ElasticError as exc:
                    self._note_error("recover", exc, obs)
                    return None  # keep shedding; retry next cooldown tick
            self._act_shed_off(obs)
            self.state = "ok"
            self._clear_since = None
            return action or "shed_off"

    def _try_shrink(self, obs: Dict[str, Any]) -> Optional[str]:
        comm = communication.MESH_WORLD
        if comm is None:
            # the controller never initializes the backend itself
            return None
        devices = list(comm.devices)
        lose = max(0, min(self.shrink_n, len(devices) - self.min_devices))
        if lose == 0:
            return None  # already at (or below) the floor
        if self.mesh_actions >= self.max_actions:
            if not self._bound_noted:
                self._bound_noted = True
                self._decide(
                    "bound", obs, mesh_actions=self.mesh_actions,
                    max_actions=self.max_actions,
                )
            return None
        try:
            self._act_shrink(obs, devices, lose)
        except ElasticError as exc:
            self._note_error("shrink", exc, obs)
            return None
        self.state = "shrunk"
        return "shrink"

    # -- act ------------------------------------------------------------
    def _act_shed_on(self, obs: Dict[str, Any]) -> None:
        from . import serving

        serving.shed(self.shed_tiers)
        self._decide("shed_on", obs, tiers=list(self.shed_tiers))

    def _act_shed_off(self, obs: Dict[str, Any]) -> None:
        from . import serving

        serving.shed(())
        self._decide("shed_off", obs)

    def _reform(self, survivors: Optional[List], reason: str) -> None:
        """The elastic reform ritual under an admission hold: drain every
        pending root (gate-exempt, watchdog-bounded), probe, re-form,
        reset guards + the device-fault ledger. ``survivors=None``
        restores the full live device set."""
        from . import fusion

        with memledger.admission_hold(reason):
            with memledger.gate_exempt():
                with health_runtime.watch(
                    "autoscale:drain", deadline_ms=self.drain_ms
                ):
                    fusion._drain_pending_roots(())
            if survivors is not None:
                survivors = probe_devices(survivors)
                if len(survivors) < self.min_devices:
                    raise ElasticError(
                        f"only {len(survivors)} healthy device(s) would "
                        f"survive the shrink (min_devices={self.min_devices})"
                    )
            communication.reform(survivors)
            health_runtime.reset_guards()
            resilience.reset_device_faults()

    def _act_shrink(self, obs: Dict[str, Any], devices: List, lose: int) -> None:
        if self._baseline is None:
            self._baseline = len(devices)
        self._reform(
            devices[: len(devices) - lose],
            f"autoscale shrink: sustained SLO burn ({obs['burn']})",
        )
        self._shrunk = True
        self.mesh_actions += 1
        self._decide(
            "shrink", obs, lose=lose, devices=len(devices) - lose,
            baseline=self._baseline,
        )
        health_runtime.auto_dump("autoscale_shrink")

    def _act_recover(self, obs: Dict[str, Any]) -> None:
        self._reform(None, "autoscale recover: burn clear through cooldown")
        self._shrunk = False
        self._bound_noted = False
        self.mesh_actions += 1
        comm = communication.MESH_WORLD
        self._decide(
            "recover", obs,
            devices=0 if comm is None else len(comm.devices),
            baseline=self._baseline,
        )
        health_runtime.auto_dump("autoscale_recover")

    # -- bookkeeping ----------------------------------------------------
    def _decide(self, action: str, obs: Dict[str, Any], **fields) -> None:
        self.decisions[action] = self.decisions.get(action, 0) + 1
        rec = {"action": action, "ts": time.time(), "obs": dict(obs)}
        rec.update(fields)
        self.last_decision = rec
        telemetry.record_event("autoscale_" + action, **{
            k: v for k, v in rec.items() if k not in ("ts",)
        })

    def _note_error(self, action: str, exc: Exception, obs: Dict[str, Any]) -> None:
        """Bounded-and-loud actuator failure: counted, evented, dumped,
        warned — never raised out of the loop."""
        self.decisions["errors"] += 1
        telemetry.record_event(
            "autoscale_error", action=action, error=str(exc), obs=dict(obs)
        )
        health_runtime.auto_dump("autoscale_" + action + "_failed")
        warnings.warn(f"autoscale {action} failed: {exc}", stacklevel=3)

    def snapshot(self) -> Dict[str, Any]:
        from . import serving

        comm = communication.MESH_WORLD  # never initialize the backend here
        with self._lock:
            return {
                "armed": self._thread is not None,
                "state": self.state,
                "shedding": self.state != "ok",
                "shed_tiers": sorted(serving._SHED_TIERS),
                "decisions": dict(self.decisions),
                "mesh_actions": self.mesh_actions,
                "max_actions": self.max_actions,
                "min_devices": self.min_devices,
                "mesh": {
                    "devices": 0 if comm is None else len(comm.devices),
                    "baseline": self._baseline,
                },
                "shed_refusals": serving._SHED_STATS["refusals"],
                "ticks": self.ticks,
                "burn_edges": self.burn_edges,
                "last_decision": self.last_decision,
            }


# ----------------------------------------------------------------------
# the module-level singleton
# ----------------------------------------------------------------------
_CONTROLLER: Optional[Controller] = None
_LOCK = threading.Lock()


def arm(**overrides) -> Controller:
    """Arm the autoscale controller (idempotent per-process singleton:
    re-arming replaces the previous controller, stopping it first). Any
    :class:`Controller` kwarg overrides its ``HEAT_TPU_AUTOSCALE_*``
    default. Returns the armed controller."""
    global _CONTROLLER
    cfg = _defaults()
    cfg.update(overrides)
    ctl = Controller(**cfg)
    with _LOCK:
        prev, _CONTROLLER = _CONTROLLER, ctl
    if prev is not None:
        prev.stop()
    ctl.start()
    return ctl


def disarm(restore: bool = True) -> None:
    """Stop the controller: lift shedding, re-form a shrunken mesh back
    to the full set (unless ``restore=False``), drop the subscription."""
    global _CONTROLLER
    with _LOCK:
        ctl, _CONTROLLER = _CONTROLLER, None
    if ctl is not None:
        ctl.stop(restore=restore)


def armed() -> bool:
    """True while a controller is armed."""
    ctl = _CONTROLLER
    return ctl is not None and ctl._thread is not None


def poll() -> Optional[str]:
    """Run one controller tick on the calling thread (tests and manual
    drivers; the armed daemon keeps its own cadence). Returns the action
    taken, or None — also None when nothing is armed."""
    ctl = _CONTROLLER
    return None if ctl is None else ctl.tick()


def stats() -> Dict[str, Any]:
    """The controller snapshot — the shape behind
    ``telemetry._AUTOSCALE_HOOK`` (so ``report()["autoscale"]`` and the
    ``heat_tpu_autoscale_*`` opsplane families read one source). Armed or
    not, this never initializes the backend."""
    ctl = _CONTROLLER
    if ctl is not None:
        return ctl.snapshot()
    from . import serving

    comm = communication.MESH_WORLD
    return {
        "armed": False,
        "state": "disarmed",
        "shedding": bool(serving._SHED_TIERS),
        "shed_tiers": sorted(serving._SHED_TIERS),
        "decisions": {},
        "mesh_actions": 0,
        "max_actions": 0,
        "min_devices": 1,
        "mesh": {"devices": 0 if comm is None else len(comm.devices),
                 "baseline": None},
        "shed_refusals": serving._SHED_STATS["refusals"],
        "ticks": 0,
        "burn_edges": 0,
        "last_decision": None,
    }


def status() -> Dict[str, Any]:
    """Operator view: the :func:`stats` snapshot plus the armed
    controller's configuration."""
    doc = stats()
    ctl = _CONTROLLER
    if ctl is not None:
        doc["config"] = {
            "interval_s": ctl.interval_s,
            "cooldown_s": ctl.cooldown_s,
            "shrink_after_s": ctl.shrink_after_s,
            "max_actions": ctl.max_actions,
            "min_devices": ctl.min_devices,
            "shrink_n": ctl.shrink_n,
            "shed_tiers": list(ctl.shed_tiers),
        }
    return doc


def reset() -> None:
    """Disarm and forget the controller (``telemetry.reset()`` cascades
    here so no test leaks a daemon poll or an armed shed set into the
    next). The mesh is NOT re-formed — reset is bookkeeping, not an
    actuator; a shrunken mesh recovers via :func:`disarm` or the next
    armed controller."""
    global _CONTROLLER
    with _LOCK:
        ctl, _CONTROLLER = _CONTROLLER, None
    if ctl is not None:
        ctl.stop(restore=False)


# report()["autoscale"] + the opsplane collector read this one snapshot
# (set-attribute seam: telemetry never imports this module)
telemetry._AUTOSCALE_HOOK = stats


# env arming: HEAT_TPU_AUTOSCALE truthy -> the controller comes up with
# the process (warn-and-disarm: a bad knob combination must never die at
# import)
_raw = os.environ.get("HEAT_TPU_AUTOSCALE", "").strip().lower()
if _raw not in ("", "0", "false", "off", "no"):  # pragma: no cover - env path
    try:
        arm()
    # heat-lint: disable=H003 — env arming is best-effort by contract
    except Exception as _exc:  # noqa: BLE001
        warnings.warn(
            f"HEAT_TPU_AUTOSCALE={_raw!r}: arming failed ({_exc}); "
            "the autoscaler stays disarmed",
            stacklevel=2,
        )
del _raw

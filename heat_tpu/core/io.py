"""Parallel I/O (reference: heat/core/io.py).

The reference's parallel HDF5 path has every MPI rank slice its own chunk
(io.py:119-147) and write through the mpio driver or a token-ring of
serialized writes (:198-226); CSV reads are split by byte ranges (:713-925).
Under a single controller the device shards come from one host-side read that
is then scattered by ``device_put`` — on a multi-host deployment each host
reads its addressable slice (the same per-chunk slicing, via
``jax.make_array_from_callback``). netCDF support is gated on the library's
presence (absent in this environment).
"""

from __future__ import annotations

import csv as csv_module
import os
from typing import List, Optional, Tuple, Union

import numpy as np

from . import devices as devices_module
from . import factories, types
from .communication import sanitize_comm
from .dndarray import DNDarray

try:
    import h5py

    __HDF5_EXTENSIONS = frozenset([".h5", ".hdf5"])
    __HAS_HDF5 = True
except ImportError:  # pragma: no cover
    __HAS_HDF5 = False
    __HDF5_EXTENSIONS = frozenset()

try:  # pragma: no cover - netCDF4 absent in this environment
    import netCDF4 as nc

    __HAS_NETCDF = True
except ImportError:
    __HAS_NETCDF = False

__CSV_EXTENSION = frozenset([".csv"])
__NETCDF_EXTENSIONS = frozenset([".nc", ".nc4", ".netcdf"])

__all__ = [
    "load",
    "load_csv",
    "load_hdf5",
    "load_netcdf",
    "save",
    "save_csv",
    "save_hdf5",
    "save_netcdf",
    "supports_hdf5",
    "supports_netcdf",
]


def supports_hdf5() -> bool:
    """True if HDF5 I/O is available (reference io.py:40-48)."""
    return __HAS_HDF5


def supports_netcdf() -> bool:
    """True if netCDF I/O is available (reference io.py:49-57)."""
    return __HAS_NETCDF


def load(path: str, *args, **kwargs) -> DNDarray:
    """Load by file extension (reference io.py:662-712)."""
    if not isinstance(path, str):
        raise TypeError(f"Expected path to be str, but was {type(path)}")
    extension = os.path.splitext(path)[-1].strip().lower()
    if extension in __CSV_EXTENSION:
        return load_csv(path, *args, **kwargs)
    if extension in __HDF5_EXTENSIONS:
        if not supports_hdf5():
            raise RuntimeError("hdf5 is required for file extension {}".format(extension))
        return load_hdf5(path, *args, **kwargs)
    if extension in __NETCDF_EXTENSIONS:
        if not supports_netcdf():
            raise RuntimeError("netcdf is required for file extension {}".format(extension))
        return load_netcdf(path, *args, **kwargs)
    raise ValueError(f"Unsupported file extension {extension}")


def save(data: DNDarray, path: str, *args, **kwargs) -> None:
    """Save by file extension (reference io.py:1060-1110)."""
    if not isinstance(path, str):
        raise TypeError(f"Expected path to be str, but was {type(path)}")
    extension = os.path.splitext(path)[-1].strip().lower()
    if extension in __CSV_EXTENSION:
        return save_csv(data, path, *args, **kwargs)
    if extension in __HDF5_EXTENSIONS:
        if not supports_hdf5():
            raise RuntimeError("hdf5 is required for file extension {}".format(extension))
        return save_hdf5(data, path, *args, **kwargs)
    if extension in __NETCDF_EXTENSIONS:
        if not supports_netcdf():
            raise RuntimeError("netcdf is required for file extension {}".format(extension))
        return save_netcdf(data, path, *args, **kwargs)
    raise ValueError(f"Unsupported file extension {extension}")


# ----------------------------------------------------------------------------
# HDF5 (reference io.py:58-245)
# ----------------------------------------------------------------------------
def load_hdf5(
    path: str,
    dataset: str,
    dtype=types.float32,
    load_fraction: float = 1.0,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Load an HDF5 dataset (reference io.py:58-147)."""
    if not isinstance(path, str):
        raise TypeError(f"path must be str, but was {type(path)}")
    if not isinstance(dataset, str):
        raise TypeError(f"dataset must be str, but was {type(dataset)}")
    if not isinstance(load_fraction, float):
        raise TypeError(f"load_fraction must be float, but was {type(load_fraction)}")
    if load_fraction <= 0.0 or load_fraction > 1.0:
        raise ValueError(f"load_fraction must be in (0, 1], but was {load_fraction}")
    with h5py.File(path, "r") as handle:
        data = handle[dataset]
        if load_fraction < 1.0 and split == 0:
            n = int(data.shape[0] * load_fraction)
            arr = np.asarray(data[:n])
        else:
            arr = np.asarray(data)
    return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)


def save_hdf5(data: DNDarray, path: str, dataset: str, mode: str = "w", **kwargs) -> None:
    """Save to an HDF5 dataset (reference io.py:148-245)."""
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be heat tensor, but was {type(data)}")
    if not isinstance(path, str):
        raise TypeError(f"path must be str, but was {type(path)}")
    if not isinstance(dataset, str):
        raise TypeError(f"dataset must be str, but was {type(dataset)}")
    if mode not in ("w", "a", "r+"):
        raise ValueError(f"mode was {mode}, not in possible modes ('w', 'a', 'r+')")
    with h5py.File(path, mode) as handle:
        handle.create_dataset(dataset, data=data.numpy(), **kwargs)


# ----------------------------------------------------------------------------
# netCDF (reference io.py:246-661) — gated
# ----------------------------------------------------------------------------
def load_netcdf(
    path: str, variable: str, dtype=types.float32, split: Optional[int] = None, device=None, comm=None
) -> DNDarray:
    """Load a netCDF variable (reference io.py:246-414)."""
    if not supports_netcdf():
        raise RuntimeError("netCDF4 is not available in this environment")
    with nc.Dataset(path, "r") as handle:  # pragma: no cover
        arr = np.asarray(handle[variable][:])
    return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)  # pragma: no cover


def save_netcdf(data: DNDarray, path: str, variable: str, mode: str = "w", **kwargs) -> None:
    """Save to a netCDF variable (reference io.py:415-661)."""
    if not supports_netcdf():
        raise RuntimeError("netCDF4 is not available in this environment")
    with nc.Dataset(path, mode) as handle:  # pragma: no cover
        arr = data.numpy()
        dims = []
        for i, s in enumerate(arr.shape):
            name = f"dim_{variable}_{i}"
            handle.createDimension(name, s)
            dims.append(name)
        var = handle.createVariable(variable, arr.dtype, tuple(dims))
        var[:] = arr


# ----------------------------------------------------------------------------
# CSV (reference io.py:713-1059)
# ----------------------------------------------------------------------------
def load_csv(
    path: str,
    header_lines: int = 0,
    sep: str = ",",
    dtype=types.float32,
    encoding: str = "utf-8",
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Load a CSV file (reference io.py:713-925: byte-range splitting per rank;
    one host read here, sharded on ingest)."""
    if not isinstance(path, str):
        raise TypeError(f"path must be str, but was {type(path)}")
    if not isinstance(sep, str):
        raise TypeError(f"separator must be str, but was {type(sep)}")
    if not isinstance(header_lines, int):
        raise TypeError(f"header_lines must be int, but was {type(header_lines)}")
    npdtype = np.dtype(types.canonical_heat_type(dtype).jax_type())
    arr = None
    if len(sep) == 1 and encoding.lower().replace("-", "") in ("utf8", "ascii"):
        # native path: multithreaded C++ byte-range parser (heat_tpu/_native)
        try:
            from .. import _native

            if _native.native_available():
                arr = _native.csv_parse(path, sep, header_lines).astype(npdtype, copy=False)
        except Exception:
            arr = None  # malformed for the strict parser or toolchain issue
    if arr is None:
        rows: List[List[float]] = []
        with open(path, "r", encoding=encoding) as f:
            for i, line in enumerate(f):
                if i < header_lines:
                    continue
                line = line.strip()
                if not line:
                    continue
                rows.append([float(v) for v in line.split(sep)])
        arr = np.asarray(rows, dtype=npdtype)
    return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)


def save_csv(
    data: DNDarray,
    path: str,
    header_lines: Optional[List[str]] = None,
    sep: str = ",",
    decimals: int = -1,
    encoding: str = "utf-8",
    **kwargs,
) -> None:
    """Save to CSV (reference io.py:926-1059)."""
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, but was {type(data)}")
    if not isinstance(path, str):
        raise TypeError(f"path must be str, but was {type(path)}")
    if data.ndim > 2:
        raise ValueError("CSV can only store 1-D or 2-D arrays")
    arr = data.numpy()
    if arr.ndim == 1:
        arr = arr[:, None]

    def write_header(f):
        for line in header_lines or ():
            f.write(line if line.endswith("\n") else line + "\n")

    # float payloads go through the native multithreaded writer
    # (heat_tpu/_native/csv_writer.cpp). Integers stay on the exact python
    # path (float64 transport would corrupt int64 > 2^53); the sep/encoding
    # guards mirror load_csv's native gate, and like load_csv any native
    # failure falls back to the python writer.
    if (
        np.issubdtype(arr.dtype, np.floating)
        and len(sep) == 1
        and ord(sep) < 128
        and encoding.replace("-", "").lower() in ("utf8", "ascii")
    ):
        try:
            from .. import _native

            if _native.native_available():
                with open(path, "w", encoding=encoding, newline="") as f:
                    write_header(f)
                _native.csv_write(path, arr, sep=sep, decimals=decimals, append=True)
                return
        except Exception:
            pass  # fall through to the python writer (rewrites from scratch)
    fmt = f"%.{decimals}f" if decimals >= 0 else "%s"
    with open(path, "w", encoding=encoding, newline="") as f:
        write_header(f)
        # match the native writer's row terminator (csv defaults to \r\n)
        writer = csv_module.writer(f, delimiter=sep, lineterminator="\n")
        for row in arr:
            writer.writerow([fmt % v if decimals >= 0 else v for v in row])

"""Parallel I/O (reference: heat/core/io.py).

The reference's parallel HDF5 path has every MPI rank slice its own chunk
(io.py:119-147) and write through the mpio driver or a token-ring of
serialized writes (:198-226); CSV reads are split by byte ranges (:713-925).
The TPU rendering keeps the per-chunk protocol:

* **HDF5 load**: each device's block is read *directly from the file* as its
  own ``h5py`` slice (true partial I/O — HDF5 reads only the requested
  hyperslab) and placed on its device; the global array is stitched with
  ``jax.make_array_from_single_device_arrays``. No host allocation ever
  equals the global array.
* **HDF5 save**: streamed per physical shard — each block is written as its
  own hyperslab, never gathering the global array to the host.
* **CSV load (split=0)**: the file is memory-mapped, newline offsets are
  scanned in bounded chunks, and each device's row range is parsed from its
  own byte range — the reference's byte-range splitting (io.py:713-925).
* **netCDF**: netCDF4 files *are* HDF5 files; load/save are implemented over
  ``h5py`` with netCDF dimension-scale conventions (reference io.py:246-660
  uses the netCDF4 library; this environment ships h5py only). Classic
  NETCDF3 (CDF magic) is detected and read via scipy.io.netcdf_file's mmap.

Resilience contract (``core/resilience.py``): every ``save_*`` writes
temp-then-rename (``resilience.atomic_write`` — a crash or fault never
leaves a partial file under the target name; only the owning process
renames, via the ``multihost`` seam), and block reads/whole-file writes
retry transient ``OSError``s with capped exponential backoff
(``resilience.retry_policy``; injectable at ``io.read``/``io.write``/
``io.rename``).
"""

from __future__ import annotations

import csv as csv_module
import mmap
import os
from io import BytesIO
from typing import List, Optional, Tuple, Union

import numpy as np

from . import devices as devices_module
from . import factories, memledger, resilience, telemetry, types
from .communication import sanitize_comm
from .dndarray import DNDarray

# every writer forces a pending recorded chain under the "io" trigger so the
# blocking host read attributes to I/O in the telemetry forcing histogram
_T_IO = telemetry.force_trigger("io")

try:
    import h5py

    __HDF5_EXTENSIONS = frozenset([".h5", ".hdf5"])
    __HAS_HDF5 = True
except ImportError:  # pragma: no cover
    __HAS_HDF5 = False
    __HDF5_EXTENSIONS = frozenset()

__CSV_EXTENSION = frozenset([".csv"])
__NETCDF_EXTENSIONS = frozenset([".nc", ".nc4", ".netcdf"])
__NPY_EXTENSION = frozenset([".npy"])

__all__ = [
    "load",
    "load_csv",
    "load_hdf5",
    "load_netcdf",
    "load_npy",
    "save",
    "save_csv",
    "save_hdf5",
    "save_netcdf",
    "save_npy",
    "supports_hdf5",
    "supports_netcdf",
]


def supports_hdf5() -> bool:
    """True if HDF5 I/O is available (reference io.py:40-48)."""
    return __HAS_HDF5


def supports_netcdf() -> bool:
    """True if netCDF I/O is available (reference io.py:49-57). netCDF4 files
    are HDF5 containers, so support rides on h5py."""
    return __HAS_HDF5


def _unsupported_extension(extension: str) -> ValueError:
    """A ValueError that *teaches*: which formats this build supports, and
    which optional dependency would unlock the rest (satellite of ISSUE 3 —
    an unknown extension must name the menu, not just refuse)."""
    supported = [".csv", ".npy"]
    missing = []
    if supports_hdf5():
        supported += [".h5", ".hdf5"]
    else:
        missing.append(".h5/.hdf5 need h5py")
    if supports_netcdf():
        supported += [".nc", ".nc4", ".netcdf"]
    else:
        missing.append(".nc/.nc4/.netcdf need h5py (and scipy for classic NETCDF3)")
    msg = (
        f"Unsupported file extension {extension!r}; "
        f"supported extensions: {', '.join(supported)}"
    )
    if missing:
        msg += f" (missing optional dependencies: {'; '.join(missing)})"
    return ValueError(msg)


def load(path: str, *args, **kwargs) -> DNDarray:
    """Load by file extension (reference io.py:662-712)."""
    if not isinstance(path, str):
        raise TypeError(f"Expected path to be str, but was {type(path)}")
    extension = os.path.splitext(path)[-1].strip().lower()
    if extension in __CSV_EXTENSION:
        return load_csv(path, *args, **kwargs)
    if extension in __NPY_EXTENSION:
        return load_npy(path, *args, **kwargs)
    if extension in __HDF5_EXTENSIONS:
        return load_hdf5(path, *args, **kwargs)
    if extension in __NETCDF_EXTENSIONS and supports_netcdf():
        return load_netcdf(path, *args, **kwargs)
    raise _unsupported_extension(extension)


def save(data: DNDarray, path: str, *args, **kwargs) -> None:
    """Save by file extension (reference io.py:1060-1110)."""
    if not isinstance(path, str):
        raise TypeError(f"Expected path to be str, but was {type(path)}")
    extension = os.path.splitext(path)[-1].strip().lower()
    if extension in __CSV_EXTENSION:
        return save_csv(data, path, *args, **kwargs)
    if extension in __NPY_EXTENSION:
        return save_npy(data, path, *args, **kwargs)
    if extension in __HDF5_EXTENSIONS:
        return save_hdf5(data, path, *args, **kwargs)
    if extension in __NETCDF_EXTENSIONS and supports_netcdf():
        return save_netcdf(data, path, *args, **kwargs)
    raise _unsupported_extension(extension)


# ----------------------------------------------------------------------------
# sharded ingest core
# ----------------------------------------------------------------------------
def _sharded_ingest(read_block, gshape, dtype, split, device, comm) -> DNDarray:
    """Assemble a split DNDarray by reading each device's block separately.

    ``read_block(slices) -> np.ndarray`` reads one hyperslab from the source
    (HDF5 dataset, CSV byte range, ...). Each block is padded to the uniform
    block size (pad+mask contract) and placed on its device; the global array
    is stitched with ``jax.make_array_from_single_device_arrays`` — the TPU
    rendering of the reference's every-rank-slices-its-own-chunk protocol
    (reference io.py:119-147). No host allocation equals the global array.
    """
    import jax

    jdt = np.dtype(types.canonical_heat_type(dtype).jax_type())
    p = comm.size
    n = gshape[split]
    block = -(-n // p) if n else 0
    pshape = list(gshape)
    pshape[split] = block * p
    counts, displs = comm.counts_displs_shape(gshape, split)
    sharding = comm.sharding(len(gshape), split)
    from .multihost import ranks_to_read

    arrays = []
    # multi-host: each host reads only its addressable blocks (the seam is
    # unit-tested against a mocked 2-process topology)
    def _read(sl):
        # the np.asarray sits INSIDE the retried callable: read_block may
        # return a lazy mmap view whose actual page-in (the part a flaky
        # NFS/GCS mount fails) only happens during the copy
        return np.asarray(read_block(sl), dtype=jdt)

    read_bytes = 0
    for r, d in ranks_to_read(comm.devices):
        sl = [slice(None)] * len(gshape)
        sl[split] = slice(displs[r], displs[r] + counts[r])
        # per-block reads retry transient OSErrors (flaky NFS/GCS model;
        # injectable at "io.read") with capped exponential backoff
        local = resilience.call_with_retries("io.read", _read, tuple(sl))
        read_bytes += local.nbytes
        if counts[r] < block:
            widths = [(0, 0)] * len(gshape)
            widths[split] = (0, block - counts[r])
            local = np.pad(local, widths)
        piece = jax.device_put(local, d)
        # ledger attribution for the staging pieces: "io" by default,
        # "checkpoint" when the restore path's owner_scope is active — a
        # watermark sample taken mid-ingest names the subsystem holding
        # the bytes, not "unattributed"
        memledger.tag(piece, memledger.current_owner() or "io")
        arrays.append(piece)
    if telemetry._MODE >= 2:
        # one timeline milestone per sharded ingest: block reads done, bytes
        # on host, about to stitch (the trace shows I/O next to the programs
        # that consume it)
        telemetry.record_event(
            "io", op="sharded_ingest", bytes=int(read_bytes),
            blocks=len(arrays), split=split,
        )
    arr = jax.make_array_from_single_device_arrays(tuple(pshape), sharding, arrays)
    return DNDarray(
        arr, tuple(gshape), types.canonical_heat_type(dtype), split, device, comm
    )


# ----------------------------------------------------------------------------
# HDF5 (reference io.py:58-245)
# ----------------------------------------------------------------------------
def load_hdf5(
    path: str,
    dataset: str,
    dtype=types.float32,
    load_fraction: float = 1.0,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Load an HDF5 dataset (reference io.py:58-147: every rank reads its own
    chunk). With ``split`` given, each device's block is read as its own h5py
    hyperslab — HDF5 performs true partial I/O, so no host-side allocation
    ever holds the full dataset."""
    if not isinstance(path, str):
        raise TypeError(f"path must be str, but was {type(path)}")
    if not isinstance(dataset, str):
        raise TypeError(f"dataset must be str, but was {type(dataset)}")
    if not isinstance(load_fraction, float):
        raise TypeError(f"load_fraction must be float, but was {type(load_fraction)}")
    if load_fraction <= 0.0 or load_fraction > 1.0:
        raise ValueError(f"load_fraction must be in (0, 1], but was {load_fraction}")
    comm = sanitize_comm(comm)
    device = devices_module.sanitize_device(device)
    # whole-file opens and bulk reads retry like the per-block ingest: the
    # flaky-mount failure mode is the same whichever branch pages the bytes
    with resilience.call_with_retries("io.read", h5py.File, path, "r") as handle:
        data = handle[dataset]
        gshape = list(data.shape)
        if load_fraction < 1.0 and split == 0:
            gshape[0] = int(gshape[0] * load_fraction)
        gshape = tuple(gshape)
        if split is None or len(gshape) == 0:
            sl = tuple(slice(0, s) for s in gshape)
            arr = resilience.call_with_retries(
                "io.read", lambda: np.asarray(data[sl] if gshape else data[()])
            )
            return factories.array(arr, dtype=dtype, split=None, device=device, comm=comm)
        split = split % len(gshape)
        return _sharded_ingest(
            lambda sl: data[sl], gshape, dtype, split, device, comm
        )


def save_hdf5(data: DNDarray, path: str, dataset: str, mode: str = "w", **kwargs) -> None:
    """Save to an HDF5 dataset (reference io.py:148-245: parallel mpio write /
    token-ring). Split arrays are streamed per physical shard — each block is
    written as its own hyperslab, never gathering the global array."""
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be heat tensor, but was {type(data)}")
    if not isinstance(path, str):
        raise TypeError(f"path must be str, but was {type(path)}")
    if not isinstance(dataset, str):
        raise TypeError(f"dataset must be str, but was {type(dataset)}")
    if mode not in ("w", "a", "r+"):
        raise ValueError(f"mode was {mode}, not in possible modes ('w', 'a', 'r+')")
    if mode == "r+" and not os.path.exists(path):
        # fail on the USER's path: without the seed file the error would
        # otherwise name the hidden temp the atomic write stages into
        raise FileNotFoundError(f"mode 'r+' requires an existing file: {path}")
    data._force_payload(_T_IO)

    # atomic + retrying: each attempt writes a fresh private temp (append
    # modes seed it with a copy of the target — atomicity costs one full-file
    # copy per attempt; prefer mode='w' to a fresh path for very large files)
    # and only a completed write is renamed into place — a crash or injected
    # fault never leaves a partial file, and transient OSErrors re-run the
    # whole attempt
    def _write():
        with resilience.atomic_write(path, preserve=mode in ("a", "r+")) as tmp:
            with h5py.File(tmp, mode) as handle:
                _write_h5_dataset(handle, dataset, data, **kwargs)

    resilience.call_with_retries("io.write", _write)


def _rank_ordered_blocks(data: DNDarray):
    """Yield ``(rank, trimmed_block)`` for every addressable shard of a SPLIT
    array, in rank order — ``DNDarray.ranked_shards`` (the shard/trim
    protocol shared with the checkpoint writer) behind the single-file
    writers' multi-controller guard.

    Multi-controller guard: when this process cannot address every mesh
    device, streaming the addressable shards would publish a file whose
    header declares the global shape but whose payload holds only this
    host's blocks — refuse loudly instead of writing a short file. (The
    atomic-publication seam, ``multihost.io_owner``, is still correct for
    replicated operands: every controller holds the full copy. The sharded
    checkpoint writer, ``utils/checkpoint.py``, has no such guard — per-host
    shard FILES are exactly the remedy this refusal names.)"""
    from .multihost import is_addressable, process_index

    proc = process_index()
    if not all(is_addressable(d, proc) for d in data.comm.devices):
        raise NotImplementedError(
            "streaming save of a split array under a multi-controller mesh: "
            "this process addresses only part of the array, so a single-file "
            "write would be incomplete. Gather first (resplit_(None)), save "
            "per-host files, or use ht.checkpoint (per-host shard files)."
        )
    written = blocks = 0
    for r, arr in data.ranked_shards():
        written += arr.nbytes
        blocks += 1
        yield r, arr
    if telemetry._MODE >= 2:
        # one timeline milestone per streamed save: every shard handed to
        # the writer (the write/rename seams stamp their own retries/faults)
        telemetry.record_event(
            "io", op="stream_blocks", bytes=int(written), blocks=blocks
        )


def _write_h5_dataset(handle, dataset: str, data: DNDarray, **kwargs):
    """Create ``dataset`` and stream ``data`` into it shard by shard."""
    jdt = np.dtype(data.dtype.jax_type())
    dset = handle.create_dataset(dataset, shape=data.shape, dtype=jdt, **kwargs)
    split = data.split
    if split is None or data.ndim == 0:
        dset[...] = data.numpy()
        return dset
    counts, displs = data.comm.counts_displs_shape(data.shape, split)
    for r, arr in _rank_ordered_blocks(data):
        tgt = [slice(None)] * data.ndim
        tgt[split] = slice(displs[r], displs[r] + counts[r])
        dset[tuple(tgt)] = arr
    return dset


# ----------------------------------------------------------------------------
# netCDF over h5py (reference io.py:246-661)
#
# netCDF4 files are HDF5 containers; the reference drives them through the
# netCDF4 library (absent in this environment). Variables are plain HDF5
# datasets carrying dimension scales, which h5py manipulates natively — so
# load/save speak the netCDF4 enhanced-model conventions directly and reuse
# the sharded HDF5 machinery above. Classic NETCDF3 files (magic b"CDF") are
# a different on-disk format, read through scipy.io.netcdf_file (write stays
# netCDF4 — the reference's own default output format).
# ----------------------------------------------------------------------------
def _is_netcdf3(path: str) -> bool:
    with open(path, "rb") as f:
        return f.read(3) == b"CDF"


def _load_netcdf3(path, variable, dtype, split, device, comm) -> DNDarray:
    """Classic NETCDF3 read via ``scipy.io.netcdf_file`` (dependency-free —
    the reference reaches this format through the netCDF4 library, absent
    here). The file is memory-mapped, so each device's block is a lazy
    per-range read through the same per-shard ingest as HDF5."""
    try:
        import scipy.io as _sio
    except ImportError as exc:  # pragma: no cover - scipy present in CI
        raise RuntimeError(
            "classic NETCDF3 files require scipy (pip install 'heat-tpu[io]' "
            "or scipy>=1.8); netCDF4 (HDF5-based) files need only h5py"
        ) from exc

    comm = sanitize_comm(comm)
    device = devices_module.sanitize_device(device)
    nc = resilience.call_with_retries("io.read", _sio.netcdf_file, path, "r", mmap=True)
    var = None
    try:
        if variable not in nc.variables:
            raise KeyError(f"variable {variable!r} not in {sorted(nc.variables)}")
        var = nc.variables[variable]
        gshape = tuple(int(s) for s in var.shape)
        if split is None or len(gshape) == 0:
            arr = resilience.call_with_retries(
                "io.read", lambda: np.array(var[...] if gshape else var.getValue())
            )
            return factories.array(arr, dtype=dtype, split=None, device=device, comm=comm)
        split = split % len(gshape)
        # copy each block out of the mmap before the file closes
        return _sharded_ingest(
            lambda sl: np.array(var[sl]), gshape, dtype, split, device, comm
        )
    finally:
        # every np.array() above copied; drop the mmap-backed variable ref so
        # close() does not warn about live views into the unmapped file
        del var
        nc.close()


def load_netcdf(
    path: str, variable: str, dtype=types.float32, split: Optional[int] = None, device=None, comm=None
) -> DNDarray:
    """Load a netCDF variable (reference io.py:246-414: every rank slices
    its own chunk). netCDF4 (HDF5-based) files use the same per-device
    hyperslab protocol as :func:`load_hdf5`; classic NETCDF3 files (magic
    ``CDF``) are read through ``scipy.io.netcdf_file``'s mmap — both formats
    the reference covers via the netCDF4 library."""
    if not isinstance(path, str):
        raise TypeError(f"path must be str, but was {type(path)}")
    if not isinstance(variable, str):
        raise TypeError(f"variable must be str, but was {type(variable)}")
    if _is_netcdf3(path):
        return _load_netcdf3(path, variable, dtype, split, device, comm)
    return load_hdf5(path, variable, dtype=dtype, split=split, device=device, comm=comm)


def save_netcdf(
    data: DNDarray, path: str, variable: str, mode: str = "w", dimension_names=None, **kwargs
) -> None:
    """Save to a netCDF4 (HDF5-based) variable (reference io.py:415-661).

    Writes the variable with netCDF dimension-scale conventions: one
    dimension-scale dataset per axis (named ``dimension_names[i]`` or
    ``<variable>_dim_<i>``) attached via the HDF5 dimension-scales API, so the
    file round-trips through the netCDF4 library. Data is streamed per shard
    like :func:`save_hdf5`."""
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be heat tensor, but was {type(data)}")
    if not isinstance(path, str):
        raise TypeError(f"path must be str, but was {type(path)}")
    if not isinstance(variable, str):
        raise TypeError(f"variable must be str, but was {type(variable)}")
    if mode not in ("w", "a", "r+"):
        raise ValueError(f"mode was {mode}, not in possible modes ('w', 'a', 'r+')")
    if mode == "r+" and not os.path.exists(path):
        raise FileNotFoundError(f"mode 'r+' requires an existing file: {path}")
    data._force_payload(_T_IO)
    if dimension_names is None:
        dimension_names = [f"{variable}_dim_{i}" for i in range(data.ndim)]
    elif len(dimension_names) != data.ndim:
        raise ValueError(
            f"{len(dimension_names)} names given for {data.ndim} dimensions"
        )
    # atomic + retrying publication, exactly like save_hdf5
    def _write():
        with resilience.atomic_write(path, preserve=mode in ("a", "r+")) as tmp:
            with h5py.File(tmp, mode) as handle:
                dset = _write_h5_dataset(handle, variable, data, **kwargs)
                for i, name in enumerate(dimension_names):
                    if name not in handle:
                        scale = handle.create_dataset(
                            name, shape=(data.shape[i],), dtype=np.float64
                        )
                        scale.make_scale(name)
                    dset.dims[i].attach_scale(handle[name])

    resilience.call_with_retries("io.write", _write)


# ----------------------------------------------------------------------------
# CSV (reference io.py:713-1059)
# ----------------------------------------------------------------------------
def _scan_line_offsets(path: str, header_lines: int) -> Tuple[np.ndarray, int]:
    """Byte offsets of each data line start (plus the end offset), scanning
    the memory-mapped file in bounded chunks — the offset table is O(rows),
    never the file payload (reference io.py:713-790 splits by byte ranges)."""
    size = os.path.getsize(path)
    offsets = [0]
    with open(path, "rb") as f:
        pos = 0
        while True:
            buf = f.read(1 << 24)
            if not buf:
                break
            nl = np.flatnonzero(np.frombuffer(buf, dtype=np.uint8) == ord("\n"))
            offsets.extend((nl + pos + 1).tolist())
            pos += len(buf)
    if offsets[-1] != size:
        offsets.append(size)  # file without trailing newline
    # drop header lines and empty trailing line starts
    starts = offsets[header_lines:-1]
    return np.asarray(starts + [offsets[-1]], dtype=np.int64), size


# ----------------------------------------------------------------------------
# npy (beyond the reference: numpy's native format, streamed per shard)
# ----------------------------------------------------------------------------
def load_npy(
    path: str,
    dtype=None,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Load a ``.npy`` file (beyond the reference — numpy's native format).

    The file is memory-mapped (``np.load(mmap_mode="r")``), so with ``split``
    given each device's block is a lazy per-range read: no host allocation
    ever holds the full array. The dtype defaults to the file's own.
    """
    if not isinstance(path, str):
        raise TypeError(f"path must be str, but was {type(path)}")
    comm = sanitize_comm(comm)
    device = devices_module.sanitize_device(device)
    mm = resilience.call_with_retries("io.read", np.load, path, mmap_mode="r")
    if dtype is None:
        dtype = types.canonical_heat_type(mm.dtype)
    if split is None or mm.ndim == 0:
        # the copy out of the mmap is the actual disk read — retried too
        arr = resilience.call_with_retries("io.read", np.asarray, mm)
        return factories.array(arr, dtype=dtype, split=None, device=device, comm=comm)
    split = split % mm.ndim
    return _sharded_ingest(lambda sl: mm[sl], tuple(mm.shape), dtype, split, device, comm)


def save_npy(data: DNDarray, path: str) -> None:
    """Save to ``.npy`` (beyond the reference), streaming shard blocks.

    The npy format is a fixed header plus the C-order buffer, so a split-0
    array appends one shard block at a time in rank order — the same
    no-global-gather contract as :func:`save_csv`/:func:`save_hdf5`. A
    split-1 operand reshards to rows first (one alltoall); replicated
    operands write their local payload directly.
    """
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, but was {type(data)}")
    if not isinstance(path, str):
        raise TypeError(f"path must be str, but was {type(path)}")

    data._force_payload(_T_IO)
    npdtype = np.dtype(data.dtype.jax_type())
    if data.split is None or data.comm.size == 1 or data.ndim == 0:

        def _write_replicated():
            with resilience.atomic_write(path) as tmp:
                # file-object form: np.save(str_path) would append a '.npy'
                # suffix, making the output filename depend on the split state
                with open(tmp, "wb") as fh:
                    np.save(fh, np.asarray(data.larray))

        resilience.call_with_retries("io.write", _write_replicated)
        return
    if data.split != 0:
        from .manipulations import resplit as _resplit

        data = _resplit(data, 0)

    header = {
        "descr": np.lib.format.dtype_to_descr(npdtype),
        "fortran_order": False,
        "shape": tuple(int(s) for s in data.shape),
    }

    def _write():
        with resilience.atomic_write(path) as tmp:
            with open(tmp, "wb") as fh:
                # version 1.0: these headers always fit it, and it has the
                # widest third-party reader support (numpy's own choice)
                np.lib.format.write_array_header_1_0(fh, header)
                for _, arr in _rank_ordered_blocks(data):
                    np.ascontiguousarray(arr.astype(npdtype, copy=False)).tofile(fh)

    resilience.call_with_retries("io.write", _write)


def load_csv(
    path: str,
    header_lines: int = 0,
    sep: str = ",",
    dtype=types.float32,
    encoding: str = "utf-8",
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Load a CSV file (reference io.py:713-925: byte-range splitting per
    rank). With ``split=0`` each device's row range is parsed from its own
    byte range of the memory-mapped file; otherwise the native multithreaded
    C++ parser (heat_tpu/_native) reads the whole file."""
    if not isinstance(path, str):
        raise TypeError(f"path must be str, but was {type(path)}")
    if not isinstance(sep, str):
        raise TypeError(f"separator must be str, but was {type(sep)}")
    if not isinstance(header_lines, int):
        raise TypeError(f"header_lines must be int, but was {type(header_lines)}")
    npdtype = np.dtype(types.canonical_heat_type(dtype).jax_type())
    comm_obj = sanitize_comm(comm)
    device_obj = devices_module.sanitize_device(device)

    if split == 0 and encoding.lower().replace("-", "") in ("utf8", "ascii") and len(sep) == 1:
        offs, size = resilience.call_with_retries(
            "io.read", _scan_line_offsets, path, header_lines
        )
        # offs has one entry per data-line start + the end offset; blank
        # trailing lines produce zero-width ranges that parse to no rows
        with open(path, "rb") as f:
            with mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ) as mm:
                # non-empty data lines from offset arithmetic alone (no
                # payload copies): a content line is longer than its line
                # terminator. CRLF files have 2-byte terminators.
                lengths = np.diff(offs)
                crlf = bool(len(offs) > 1 and offs[1] >= 2 and mm[offs[1] - 2 : offs[1]] == b"\r\n")
                term = 2 if crlf else 1
                rows = np.flatnonzero(lengths > term).tolist()
                # the (unterminated) final line has no newline to discount
                if lengths.size and lengths[-1] in (1, 2) and (len(offs) - 2) not in rows:
                    if bytes(mm[offs[-2]:offs[-1]]).strip():
                        rows.append(len(offs) - 2)
                if not rows:
                    return factories.array(
                        np.empty((0, 0), dtype=npdtype), dtype=dtype, split=0,
                        device=device, comm=comm,
                    )
                # column count from the first data line only
                first = bytes(mm[offs[rows[0]]:offs[rows[0] + 1]]).strip()
                ncols = first.count(sep.encode()) + 1
                gshape = (len(rows), ncols)

                def read_block(sl):
                    r0, r1 = sl[0].start, sl[0].stop
                    if r1 <= r0:
                        return np.empty((0, ncols), dtype=npdtype)
                    lo, hi = offs[rows[r0]], offs[rows[r1 - 1] + 1]
                    payload = bytes(mm[lo:hi])
                    out = np.loadtxt(
                        BytesIO(payload), delimiter=sep, dtype=np.float64, ndmin=2
                    )
                    return out.astype(npdtype, copy=False)

                return _sharded_ingest(
                    read_block, gshape, dtype, 0, device_obj, comm_obj
                )

    arr = None
    if len(sep) == 1 and encoding.lower().replace("-", "") in ("utf8", "ascii"):
        # native path: multithreaded C++ byte-range parser (heat_tpu/_native)
        try:
            from .. import _native

            if _native.native_available():
                arr = _native.csv_parse(path, sep, header_lines).astype(npdtype, copy=False)
        except (ImportError, OSError, ValueError, RuntimeError):
            arr = None  # malformed for the strict parser or toolchain issue
    if arr is None:

        def _parse_python():
            rows: List[List[float]] = []
            with open(path, "r", encoding=encoding) as f:
                for i, line in enumerate(f):
                    if i < header_lines:
                        continue
                    line = line.strip()
                    if not line:
                        continue
                    rows.append([float(v) for v in line.split(sep)])
            return np.asarray(rows, dtype=npdtype)

        arr = resilience.call_with_retries("io.read", _parse_python)
    return factories.array(arr, dtype=dtype, split=split, device=device, comm=comm)


def save_csv(
    data: DNDarray,
    path: str,
    header_lines: Optional[List[str]] = None,
    sep: str = ",",
    decimals: int = -1,
    encoding: str = "utf-8",
    **kwargs,
) -> None:
    """Save to CSV, streaming shard blocks in rank order without a gather.

    The reference serializes rank-by-rank over its token ring
    (reference io.py:926-1059); split arrays here stream shard by shard — each device's block is
    brought to host and appended on its own (the single-controller edition of
    the reference's token ring); the global array is NEVER materialized. A
    split-1 operand is resharded to rows first (one alltoall — CSV is a
    row-major format), and a replicated operand's local payload already is
    the data.
    """
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, but was {type(data)}")
    if not isinstance(path, str):
        raise TypeError(f"path must be str, but was {type(path)}")
    if data.ndim > 2:
        raise ValueError("CSV can only store 1-D or 2-D arrays")

    data._force_payload(_T_IO)
    if data.split == 1:
        from .manipulations import resplit as _resplit

        data = _resplit(data, 0)

    def row_blocks():
        """Logical row blocks in rank order, one host transfer each."""
        if data.split is None or data.comm.size == 1:
            arr = np.asarray(data.larray)  # local payload, not a gather
            yield arr if arr.ndim == 2 else arr[:, None]
            return
        for _, arr in _rank_ordered_blocks(data):
            yield arr if arr.ndim == 2 else arr[:, None]

    def write_header(f):
        for line in header_lines or ():
            f.write(line if line.endswith("\n") else line + "\n")

    # float payloads go through the native multithreaded writer
    # (heat_tpu/_native/csv_writer.cpp). Integers stay on the exact python
    # path (float64 transport would corrupt int64 > 2^53); the sep/encoding
    # guards mirror load_csv's native gate, and like load_csv any native
    # failure falls back to the python writer.
    npdtype = np.dtype(data.dtype.jax_type())
    if (
        np.issubdtype(npdtype, np.floating)
        and len(sep) == 1
        and ord(sep) < 128
        and encoding.replace("-", "").lower() in ("utf8", "ascii")
    ):
        try:
            from .. import _native

            native_ok = _native.native_available()
        except (ImportError, OSError, RuntimeError):
            native_ok = False  # toolchain issue: python writer owns the save
        if native_ok:

            def _write_native():
                # atomic: header + native blocks land in a private temp
                # and only a completed file is renamed onto the target
                with resilience.atomic_write(path) as tmp:
                    with open(tmp, "w", encoding=encoding, newline="") as f:
                        write_header(f)
                    for block_arr in row_blocks():
                        _native.csv_write(tmp, block_arr, sep=sep, decimals=decimals, append=True)

            try:
                resilience.call_with_retries("io.write", _write_native)
                return
            except (OSError, NotImplementedError, MemoryError, resilience.FaultInjected):
                # an exhausted-retry I/O failure, the multihost refusal, OOM,
                # or an injected fault is REAL — re-running the whole save
                # through the python writer would hide it and pay a second
                # retry cycle
                raise
            # swallowing IS the contract here: the clause above already
            # re-raised every REAL fault (OSError after retries, the
            # multihost refusal, OOM, injected faults); whatever remains is
            # the native writer rejecting the payload shape/ctypes
            # marshalling, and the python writer below owns the save bitwise
            # heat-lint: disable=H003 — real faults re-raised above; rest falls back
            except Exception:
                pass  # native writer rejected the payload: python fallback
    fmt = f"%.{decimals}f" if decimals >= 0 else "%s"

    def _write():
        with resilience.atomic_write(path) as tmp:
            with open(tmp, "w", encoding=encoding, newline="") as f:
                write_header(f)
                # match the native writer's row terminator (csv defaults \r\n)
                writer = csv_module.writer(f, delimiter=sep, lineterminator="\n")
                for block_arr in row_blocks():
                    for row in block_arr:
                        writer.writerow([fmt % v if decimals >= 0 else v for v in row])

    resilience.call_with_retries("io.write", _write)

"""Array creation routines (reference: heat/core/factories.py).

The reference's ``array(..., split=k)`` has each MPI rank slice its own block
locally (factories.py:378-381) and ``is_split`` runs a neighbor-probe +
Allreduce protocol to infer the global shape (factories.py:383-426). Under
single-controller JAX the global array is materialized once and sharded with a
single ``device_put`` — GSPMD scatters the blocks; ``is_split`` degenerates to
``split`` because there is one process (documented deviation).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple, Type, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import devices, types
from .communication import Communication, sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "arange",
    "array",
    "asarray",
    "empty",
    "empty_like",
    "eye",
    "from_partitioned",
    "full",
    "full_like",
    "linspace",
    "logspace",
    "meshgrid",
    "ones",
    "ones_like",
    "zeros",
    "zeros_like",
]


def _wrap(
    jarr: jax.Array,
    split: Optional[int],
    device,
    comm: Communication,
) -> DNDarray:
    """Place a global jax array under the split sharding and wrap it."""
    from .dndarray import _ensure_split

    jarr = _ensure_split(jarr, split if jarr.ndim else None, comm)
    return DNDarray(
        jarr,
        tuple(jarr.shape),
        types.canonical_heat_type(jarr.dtype),
        split if jarr.ndim else None,
        device,
        comm,
    )


def _resolve(device, comm, split: Optional[int], ndim: int):
    device = devices.sanitize_device(device)
    comm = sanitize_comm(comm)
    if split is not None:
        split = sanitize_axis((0,) * max(ndim, 1), split)
    return device, comm, split


def array(
    obj,
    dtype=None,
    copy: Optional[bool] = True,
    ndmin: int = 0,
    order: str = "C",
    split: Optional[int] = None,
    is_split: Optional[int] = None,
    device=None,
    comm: Optional[Communication] = None,
) -> DNDarray:
    """Create a DNDarray (reference factories.py:150-431).

    ``is_split`` is accepted for compatibility; with a single controller the
    local shard *is* the global array, so it behaves like ``split``.
    """
    if split is not None and is_split is not None:
        raise ValueError(f"split and is_split are mutually exclusive parameters")
    if is_split is not None:
        split = is_split
    if isinstance(obj, DNDarray):
        if dtype is None and split == obj.split and copy is not True:
            return obj
        jarr = obj.larray
        if dtype is not None:
            jarr = jarr.astype(types.canonical_heat_type(dtype).jax_type())
        device = obj.device if device is None else devices.sanitize_device(device)
        comm = obj.comm if comm is None else sanitize_comm(comm)
        if split is None and is_split is None:
            split = obj.split
        split = sanitize_axis(jarr.shape, split) if split is not None else None
        return _wrap(jarr, split, device, comm)

    jdtype = types.canonical_heat_type(dtype).jax_type() if dtype is not None else None
    if isinstance(obj, jax.Array):
        jarr = obj.astype(jdtype) if jdtype is not None else obj
    else:
        try:
            nparr = np.asarray(obj, order=order)
        except ValueError as e:
            raise ValueError(f"invalid data: {e}")
        if nparr.dtype == object:
            raise TypeError("invalid data of type object")
        if jdtype is None and nparr.dtype == np.float64 and not isinstance(obj, np.ndarray):
            # python floats default to the framework's working precision
            # (reference factories.py:334-340 via torch's float32 default)
            nparr = nparr.astype(np.float32)
        jarr = jnp.asarray(nparr, dtype=jdtype)
    while jarr.ndim < ndmin:
        jarr = jarr[None]
    split = sanitize_axis(jarr.shape, split) if split is not None else None
    device, comm, _ = _resolve(device, comm, None, jarr.ndim)
    return _wrap(jarr, split, device, comm)


def asarray(obj, dtype=None, copy=None, order="C", is_split=None, device=None) -> DNDarray:
    """Convert input to a DNDarray without copying when possible
    (reference factories.py:520)."""
    if isinstance(obj, DNDarray) and dtype is None and copy is not True:
        return obj
    return array(obj, dtype=dtype, copy=copy, order=order, is_split=is_split, device=device)


def __factory(shape, dtype, split, fill, device, comm, order="C") -> DNDarray:
    shape = sanitize_shape(shape)
    dtype = types.canonical_heat_type(dtype)
    split = sanitize_axis(shape, split)
    device, comm, _ = _resolve(device, comm, None, len(shape))
    eff_split = split if len(shape) else None
    if eff_split is not None and shape[eff_split] % comm.size == 0:
        sharding = comm.sharding(len(shape), eff_split)
        jarr = jax.jit(lambda: fill(shape, dtype.jax_type()), out_shardings=sharding)()
    else:
        from .dndarray import _ensure_split

        jarr = _ensure_split(fill(shape, dtype.jax_type()), eff_split, comm)
    return DNDarray(jarr, shape, dtype, eff_split, device, comm)


def empty(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Uninitialized (here: zero-filled — XLA has no uninitialized alloc)
    array (reference factories.py:558)."""
    return __factory(shape, dtype, split, jnp.zeros, device, comm, order)


def zeros(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Array of zeros (reference factories.py:1244)."""
    return __factory(shape, dtype, split, jnp.zeros, device, comm, order)


def ones(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Array of ones (reference factories.py:1072)."""
    return __factory(shape, dtype, split, jnp.ones, device, comm, order)


def full(shape, fill_value, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Constant-filled array (reference factories.py:820)."""
    if dtype is None:
        dtype = types.heat_type_of(fill_value)
        if isinstance(fill_value, (int, np.integer)):
            dtype = types.float32  # numpy full semantics in the reference use float default
    value = np.asarray(fill_value)
    return __factory(
        shape, dtype, split, lambda s, dt: jnp.full(s, value, dtype=dt), device, comm, order
    )


def __factory_like(a, dtype, split, factory, device, comm, **kwargs) -> DNDarray:
    shape = a.shape if hasattr(a, "shape") else np.asarray(a).shape
    if dtype is None:
        dtype = a.dtype if isinstance(a, DNDarray) else types.heat_type_of(a)
    if split is None:
        split = a.split if isinstance(a, DNDarray) else None
    if device is None and isinstance(a, DNDarray):
        device = a.device
    if comm is None and isinstance(a, DNDarray):
        comm = a.comm
    return factory(shape, dtype=dtype, split=split, device=device, comm=comm, **kwargs)


def empty_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, dtype, split, empty, device, comm, order=order)


def zeros_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, dtype, split, zeros, device, comm, order=order)


def ones_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, dtype, split, ones, device, comm, order=order)


def full_like(a, fill_value, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, dtype, split, full, device, comm, fill_value=fill_value, order=order)


def arange(*args, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Evenly spaced values in [start, stop) (reference factories.py:40-138)."""
    num_of_param = len(args)
    if num_of_param == 1:
        start, stop, step = 0, args[0], 1
    elif num_of_param == 2:
        start, stop, step = args[0], args[1], 1
    elif num_of_param == 3:
        start, stop, step = args
    else:
        raise TypeError(f"function takes minimum one and at most 3 positional arguments ({num_of_param} given)")
    if dtype is None:
        if all(isinstance(a, (int, np.integer)) for a in (start, stop, step)):
            dtype = types.int32
        else:
            dtype = types.float32
    dtype = types.canonical_heat_type(dtype)
    jarr = jnp.arange(start, stop, step, dtype=dtype.jax_type())
    device, comm, _ = _resolve(device, comm, None, 1)
    split = sanitize_axis(jarr.shape, split) if split is not None else None
    return _wrap(jarr, split, device, comm)


def linspace(
    start,
    stop,
    num: int = 50,
    endpoint: bool = True,
    retstep: bool = False,
    dtype=None,
    split=None,
    device=None,
    comm=None,
):
    """num evenly spaced samples over [start, stop] (reference factories.py:873)."""
    num = int(num)
    if num <= 0:
        raise ValueError(f"number of samples 'num' must be non-negative integer, but was {num}")
    start = float(start.item() if isinstance(start, DNDarray) else start)
    stop = float(stop.item() if isinstance(stop, DNDarray) else stop)
    jdt = types.canonical_heat_type(dtype).jax_type() if dtype is not None else None
    jarr = jnp.linspace(start, stop, num, endpoint=endpoint, dtype=jdt)
    device, comm, _ = _resolve(device, comm, None, 1)
    split = sanitize_axis(jarr.shape, split) if split is not None else None
    out = _wrap(jarr, split, device, comm)
    if retstep:
        # max-guard mirrors reference factories.py (num=1 would divide by zero)
        step = (stop - start) / max(1, num - 1 if endpoint else num)
        return out, step
    return out


def logspace(
    start, stop, num=50, endpoint=True, base=10.0, dtype=None, split=None, device=None, comm=None
) -> DNDarray:
    """Samples on a log scale (reference factories.py:975)."""
    y = linspace(start, stop, num=num, endpoint=endpoint, split=split, device=device, comm=comm)
    from . import exponential

    out = exponential.exp(y * float(np.log(base)))
    if dtype is not None:
        return out.astype(dtype)
    return out


def eye(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """2-D identity-like array (reference factories.py:735)."""
    if isinstance(shape, (int, np.integer)):
        n, m = int(shape), int(shape)
    else:
        shape = sanitize_shape(shape)
        if len(shape) == 1:
            n = m = shape[0]
        else:
            n, m = shape[0], shape[1]
    dtype = types.canonical_heat_type(dtype)
    device, comm, _ = _resolve(device, comm, None, 2)
    split = sanitize_axis((n, m), split) if split is not None else None
    return _wrap(jnp.eye(n, m, dtype=dtype.jax_type()), split, device, comm)


def meshgrid(*arrays, indexing: str = "xy") -> List[DNDarray]:
    """Coordinate matrices from coordinate vectors (reference factories.py:1039).

    The reference splits the output along the first/second axis according to
    the inputs' splits; here the outputs inherit split=0 if any input is split.
    """
    if indexing not in ("xy", "ij"):
        raise ValueError(f"indexing must be 'xy' or 'ij', got {indexing}")
    if not arrays:
        return []
    split = None
    comm = None
    device = None
    jarrs = []
    for a in arrays:
        if isinstance(a, DNDarray):
            comm = comm or a.comm
            device = device or a.device
            if a.split is not None:
                split = 0
            jarrs.append(a.larray)
        else:
            jarrs.append(jnp.asarray(a))
    comm = sanitize_comm(comm)
    device = devices.sanitize_device(device)
    outs = jnp.meshgrid(*jarrs, indexing=indexing)
    return [_wrap(o, split, device, comm) for o in outs]


def from_partitioned(x, comm=None) -> DNDarray:
    """Reference factories.py supports the dask-style __partitioned__ protocol;
    here any object exposing ``__partitioned__`` or an array interface is
    ingested as a global array."""
    return array(x, comm=comm)

"""Version information (reference: heat/core/version.py:3-9)."""

major: int = 0
"""Major version number."""
minor: int = 1
"""Minor version number."""
micro: int = 0
"""Micro version number."""
extension: str = "dev"
"""Version extension marker."""

if not extension:
    __version__ = f"{major}.{minor}.{micro}"
    __pep440__ = __version__
else:
    __version__ = f"{major}.{minor}.{micro}-{extension}"
    # packaging needs a PEP 440 rendering ("-dev" is not one)
    __pep440__ = f"{major}.{minor}.{micro}.{extension}0"

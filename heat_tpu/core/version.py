"""Version information (reference: heat/core/version.py:3-9)."""

major: int = 0
"""Major version number."""
minor: int = 1
"""Minor version number."""
micro: int = 0
"""Micro version number."""
extension: str = "dev"
"""Version extension marker."""

if not extension:
    __version__ = f"{major}.{minor}.{micro}"
    __pep440__ = __version__
else:
    __version__ = f"{major}.{minor}.{micro}-{extension}"
    # packaging needs a PEP 440 rendering ("-dev" is not one). Only markers
    # with an unambiguous mapping get a release-segment rendering; anything
    # else becomes a local version label rather than silently meaning
    # something different (e.g. "rc1" + "0" would read as rc10).
    import re as _re

    if extension == "dev":
        __pep440__ = f"{major}.{minor}.{micro}.dev0"
    elif _re.fullmatch(r"(?:rc|a|b)\d+", extension):
        __pep440__ = f"{major}.{minor}.{micro}{extension}"
    else:
        __pep440__ = f"{major}.{minor}.{micro}+{extension}"

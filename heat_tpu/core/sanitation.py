"""Input validation helpers (reference: heat/core/sanitation.py).

The reference's ``sanitize_distribution`` (sanitation.py:31-158) physically
redistributes operands to a target lshape map over MPI; under GSPMD matching
layouts is a sharding-constraint no-op, so the helpers here focus on type and
shape validation.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

from . import types
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "is_concrete",
    "sanitize_in",
    "sanitize_infinity",
    "sanitize_in_tensor",
    "sanitize_distribution",
    "sanitize_lshape",
    "sanitize_out",
    "sanitize_sequence",
    "scalar_to_1d",
    "warn_replicated",
]


def is_concrete(x) -> bool:
    """Whether ``x`` is a concrete array (host reads allowed) rather than a
    jit-trace tracer. The shared probe behind every "defer the host read
    under a trace" guard (det's singular-tile retry, cholesky's LinAlgError
    probe, trace's scalar return), kept in one place so a jax relocation of
    the Tracer type is a one-line fix."""
    import jax

    return not isinstance(x, jax.core.Tracer)


class ReplicationWarning(UserWarning):
    """A distributed operand degraded to a replicated/gathered execution."""


def warn_replicated(op: str, reason: str) -> None:
    """Warn that a distributed operand degraded to replicated execution.

    The explicit-fallback policy (the qr.py pattern, qr.py:106-113), shared
    by every path where a *distributed* operand would otherwise silently
    gather: say so, loudly. Filterable via :class:`ReplicationWarning`."""
    import warnings

    warnings.warn(
        f"heat_tpu.{op}: executing on a REPLICATED operand — {reason}",
        ReplicationWarning,
        stacklevel=3,
    )


def sanitize_in(x) -> None:
    """Raise TypeError unless ``x`` is a DNDarray (reference sanitation.py:161)."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")


def sanitize_in_tensor(x) -> None:
    """Raise unless x is a backend (jax) array (reference sanitation.py:178)."""
    import jax

    if not isinstance(x, jax.Array):
        raise TypeError(f"input needs to be a jax.Array, but was {type(x)}")


def sanitize_infinity(x) -> Union[int, float]:
    """Largest representable value for x's dtype (reference sanitation.py:194)."""
    dtype = x.dtype if isinstance(x, DNDarray) else types.heat_type_of(x)
    if types.heat_type_is_exact(dtype):
        return types.iinfo(dtype).max
    return float("inf")


def sanitize_distribution(*args, target: DNDarray, diff_map=None):
    """Match operands' distribution to ``target`` (reference sanitation.py:31-158).

    Under GSPMD this resplits each operand to the target's split axis; the
    data movement is one XLA resharding collective per mismatched operand.
    """
    out = []
    for x in args:
        sanitize_in(x)
        if x.split != target.split and x.shape == target.shape:
            x = _resplit_copy(x, target.split)
        out.append(x)
    return out[0] if len(out) == 1 else tuple(out)


def _resplit_copy(x: DNDarray, split: Optional[int]) -> DNDarray:
    from . import factories

    return factories.array(x, split=split, copy=True)


def sanitize_lshape(array: DNDarray, tensor) -> None:
    """Validate a local-shard shape against the global array (reference
    sanitation.py:226). Balanced GSPMD layouts make this a metadata check."""
    if tuple(tensor.shape) != tuple(array.lshape):
        raise ValueError(f"local shape {tuple(tensor.shape)} does not match expected {array.lshape}")


def sanitize_out(
    out: DNDarray,
    output_shape: Sequence[int],
    output_split: Optional[int],
    output_device,
    output_comm=None,
) -> None:
    """Validate an ``out=`` buffer (reference sanitation.py:259)."""
    if not isinstance(out, DNDarray):
        raise TypeError(f"expected out to be None or a DNDarray, but was {type(out)}")
    if tuple(out.shape) != tuple(output_shape):
        raise ValueError(f"Expecting output buffer of shape {tuple(output_shape)}, got {out.shape}")


def sanitize_sequence(seq) -> list:
    """Normalize a sequence argument to a list (reference sanitation.py:310)."""
    if isinstance(seq, list):
        return seq
    if isinstance(seq, tuple):
        return list(seq)
    if isinstance(seq, DNDarray):
        return seq.tolist()
    raise TypeError(f"seq must be a list, tuple or DNDarray, got {type(seq)}")


def scalar_to_1d(x: DNDarray) -> DNDarray:
    """Turn a scalar DNDarray into a 1-element 1-D one (reference sanitation.py:334)."""
    from . import manipulations

    if x.ndim == 0:
        return manipulations.expand_dims(x, 0)
    return x

"""Resilience layer: fault injection, recovery policy, numeric error policy
and atomic/retrying I/O primitives.

The reference framework (HeAT, arXiv:2007.13552) is eager op-by-op over MPI,
so every failure is *local*: a bad op raises at its own call site, a dead
rank kills the job loudly. Our fused engine (``core/fusion.py``) and
streaming I/O gave that locality up for speed — a fused-program compile
error would abort a whole 10-op chain, a mid-write exception would leave a
truncated file on disk, and non-finite values ride silently through k-op
chains. This module wins the locality back deliberately, and — because the
only way to *trust* recovery paths on a CPU dev mesh is to trigger them —
ships the deterministic fault-injection harness that produces the failures a
real TPU pod does (preempted compiles, OOM, flaky NFS/GCS).

Fault injection
---------------
Named **injection sites** are wired into the three seams:

====================  =====================================================
site                  where it fires
====================  =====================================================
``collective.<verb>`` each ``MeshCommunication`` verb at Python call time
                      (``collective.allreduce``, ``collective.bcast``, ...)
``collective.apply``  every ``MeshCommunication.apply`` shard_map build
``collective.reshard``  redistribution (``DNDarray.resplit_``; halo builds
                      fire ``collective.ppermute``/``collective.apply``)
``fusion.record``     recording an op into the expression DAG
``fusion.compile``    first execution (= XLA build) of a fused program
``fusion.execute``    every execution of an already-cached fused program
``memory.exhausted``  the fused-program dispatch seam, modelling device OOM
                      (``RESOURCE_EXHAUSTED``) at execute time — fires the
                      OOM forensics (``core/memledger.py``) before the
                      guarded degrade path absorbs the failure
``io.read``           each per-device block read of the sharded ingest
``io.write``          each (whole-file) write attempt of a ``save_*``
``io.rename``         the temp-then-rename publication step
``checkpoint.write``  each per-leaf / per-shard payload-file write of a
                      checkpoint save (``utils/checkpoint.py``)
``checkpoint.commit`` the manifest publication — the checkpoint's single
                      commit point (rides ``atomic_write``)
``checkpoint.restore``  manifest/payload reads during checkpoint
                      verification and restore
``checkpoint.gc``     each retention / debris deletion of checkpoint GC
                      (failures degrade to a warning; debris waits for the
                      next sweep)
``multihost.init``    each ``multihost.initialize_distributed`` connect
                      attempt — an injected ``ConnectionResetError``
                      exercises exactly the retry/backoff path a flaky
                      coordinator would
``multihost.barrier`` every ``multihost.sync_processes`` entry (before the
                      single-host early-out, so the blocked-barrier paths
                      are testable without two processes)
``multihost.heartbeat``  each lease-beat write of the peer-liveness daemon
                      — a fired fault reads as a *missed* beat (counted in
                      ``report()["multihost"]``), never a daemon crash
``elastic.preempt``   the elastic supervisor's per-step preemption poll
                      (``core/elastic.py``) — arming it kills-a-host
                      deterministically: the supervisor converts the fault
                      into a drain → checkpoint → mesh-reform cycle
``numeric.sdc.<i>``   the SDC sentinel's per-device canary execution
                      (``core/numlens.py`` ``run_canary``), device index
                      ``<i>`` — arming it makes exactly that device return
                      corrupt bits, so the sentinel's name-the-sick-device
                      → ``note_device_fault`` → quarantine → mesh-shrink
                      escalation has a deterministic true-positive test
====================  =====================================================

:func:`inject` arms a site from a test or an experiment::

    with ht.resilience.inject("fusion.compile", times=1):
        y.larray     # the compile "fails"; force() degrades to eager

``HEAT_TPU_FAULTS`` arms sites for a whole process — either the ``ci``
preset (a deterministic background fault mix that every *recoverable* seam
must absorb while the suite stays green) or an explicit spec list::

    HEAT_TPU_FAULTS="io.write:exc=OSError:every=5,fusion.execute:every=11"

Specs are deterministic: ``every=N`` is counter-based, ``p=<float>`` draws
from a ``seed``-ed private RNG. While an :func:`inject` context is active,
env/background specs are suspended, so tests asserting exact fault counts
stay exact even under ``HEAT_TPU_FAULTS=ci``.

Recovery policy
---------------
* :func:`record_recoverable` — the ONE policy for the fusion recorder's
  record-time fallbacks (shape/tracing errors fall back to the eager engine,
  real faults like ``MemoryError`` propagate).
* :func:`force_recoverable` — the policy for fused-program build/execute
  failures: ``fusion.force`` degrades the chain to per-op eager dispatch
  (correct result, slower), records a ``degraded`` telemetry event and
  quarantines the DAG key (see ``fusion.py``).
* :class:`errstate` — the numeric error policy,
  ``ht.errstate(nonfinite="warn"|"raise"|"ignore")``: forcing points run a
  cheap jitted ``isfinite`` reduction on the materialized value and warn /
  raise :class:`NonFiniteError` on inf/NaN. Off (``"ignore"``) by default;
  disabled cost is one module-attribute read per force.

Atomic + retrying I/O
---------------------
* :func:`atomic_write` — temp-then-rename publication: a crash mid-write
  can never leave a partial file under the target name. Multihost-safe via
  the ``multihost.py`` seam: every process writes a private temp, only the
  owning process (``multihost.io_owner()``) renames, others discard.
* :func:`call_with_retries` — capped exponential backoff over transient
  ``OSError``s (:data:`retry_policy` is the knob; ``HEAT_TPU_IO_RETRIES`` /
  ``HEAT_TPU_IO_RETRY_DELAY`` seed it). Non-transient errnos (``ENOENT``,
  ``EACCES``...) never retry — error parity with the bare call.
"""

from __future__ import annotations

import errno as errno_module
import fnmatch
import os
import random
import re
import shutil
import threading
import time
import warnings
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

import numpy as np

from . import telemetry

__all__ = [
    "DegradedDispatchWarning",
    "FaultInjected",
    "MeshDegradedWarning",
    "NonFiniteError",
    "NonFiniteWarning",
    "RetryPolicy",
    "atomic_write",
    "call_with_retries",
    "check",
    "check_nonfinite",
    "degraded_devices",
    "device_fault_counts",
    "errstate",
    "fault_counts",
    "force_recoverable",
    "inject",
    "note_device_fault",
    "record_recoverable",
    "reset",
    "reset_device_faults",
    "retry_policy",
    "suspended",
    "StallError",
    "StallWarning",
]


class FaultInjected(RuntimeError):
    """Default exception raised at an armed injection site."""


class DegradedDispatchWarning(UserWarning):
    """A fused program failed to build/execute and the chain was re-run as
    per-op eager dispatch (correct result, slower); the DAG key is
    quarantined so steady-state loops do not re-fail every step."""


class NonFiniteError(FloatingPointError):
    """Non-finite values detected at a forcing point under
    ``ht.errstate(nonfinite="raise")``."""


class NonFiniteWarning(RuntimeWarning):
    """Non-finite values detected at a forcing point under
    ``ht.errstate(nonfinite="warn")``."""


class StallWarning(UserWarning):
    """The health-runtime watchdog found a blocking sync or fused dispatch
    still in flight past its deadline; the structured diagnosis (in-flight
    program key, pending DAG root cids, collective trail) is retrievable via
    ``health_runtime.last_stall()``."""


class StallError(TimeoutError):
    """Raised at the guarded call site once it finally returns, when the
    watchdog tripped during the wait and the policy is ``raise``. Like
    NonFiniteError and MemoryBudgetExceeded this is a policy signal raised by
    the health layer, not an XLA failure — it must propagate, never degrade
    the chain to eager (see :func:`force_recoverable`)."""


class MeshDegradedWarning(UserWarning):
    """Repeated ``collective.*``/dispatch faults attributable to ONE device
    crossed the per-device threshold (``HEAT_TPU_DEVICE_FAULT_THRESHOLD``):
    the device is marked degraded in the ledger so the elastic supervisor
    (``core/elastic.py``) shrinks the *mesh* around it at the next reform —
    the fault pattern degrades the topology, not the job."""


# ----------------------------------------------------------------------
# fault-injection harness
# ----------------------------------------------------------------------
_EXC_BY_NAME = {
    "FaultInjected": FaultInjected,
    "OSError": OSError,
    "IOError": OSError,
    "TimeoutError": TimeoutError,
    "RuntimeError": RuntimeError,
    "MemoryError": MemoryError,
    "ValueError": ValueError,
}


class FaultSpec:
    """One armed fault: a site pattern plus a deterministic firing rule.

    ``times`` caps how often it fires (``times=0`` arms the machinery —
    every site pays the check — but never fires: the "guards on, no faults"
    overhead configuration). ``every=N`` fires on every Nth matching check;
    ``p`` draws from a private ``seed``-ed RNG so runs are reproducible.
    """

    __slots__ = ("pattern", "exc", "times", "every", "p", "rng", "seen", "fired", "_regex")

    def __init__(self, pattern, exc=FaultInjected, times=None, every=None, p=1.0, seed=0):
        self.pattern = pattern
        self.exc = exc
        self.times = times
        self.every = every
        self.p = float(p)
        self.rng = random.Random(seed)
        self.seen = 0
        self.fired = 0
        self._regex = re.compile(fnmatch.translate(pattern))

    def matches(self, site: str) -> bool:
        return self._regex.match(site) is not None

    def should_fire(self) -> bool:
        self.seen += 1
        if self.times is not None and self.fired >= self.times:
            return False
        if self.every is not None and self.seen % self.every != 0:
            return False
        if self.p < 1.0 and self.rng.random() >= self.p:
            return False
        return True

    def make(self, site: str) -> BaseException:
        if isinstance(self.exc, BaseException):
            return self.exc
        if issubclass(self.exc, OSError):
            # a *transient* I/O fault by construction (ETIMEDOUT/EIO are in
            # the retry policy's transient set) — the failure mode worth
            # injecting; TimeoutError IS an OSError and carries its errno
            err = (
                errno_module.ETIMEDOUT
                if issubclass(self.exc, TimeoutError)
                else errno_module.EIO
            )
            return self.exc(err, f"injected fault at {site}")
        return self.exc(f"injected fault at {site}")

    def __repr__(self) -> str:
        return (
            f"FaultSpec({self.pattern!r}, exc={getattr(self.exc, '__name__', self.exc)},"
            f" times={self.times}, every={self.every}, p={self.p}, fired={self.fired})"
        )


def _parse_specs(text: str) -> List[FaultSpec]:
    """``site:key=val:key=val, site2:...`` -> FaultSpecs (bad entries warn and
    are skipped — a typo in an env knob must not take the process down)."""
    specs: List[FaultSpec] = []
    for entry in text.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        kwargs: dict = {}
        try:
            for part in parts[1:]:
                key, _, val = part.partition("=")
                key = key.strip()
                if key == "exc":
                    kwargs["exc"] = _EXC_BY_NAME[val.strip()]
                elif key in ("times", "every", "seed"):
                    kwargs[key] = int(val)
                elif key == "p":
                    kwargs["p"] = float(val)
                else:
                    raise KeyError(key)
            specs.append(FaultSpec(parts[0].strip(), **kwargs))
        except Exception as exc:  # noqa: BLE001 - env knob parsing only
            warnings.warn(
                f"HEAT_TPU_FAULTS: ignoring malformed entry {entry!r} ({exc!r})",
                stacklevel=2,
            )
    return specs


#: the CI background mix: only *recoverable-by-design* seams — fused programs
#: degrade to eager, transient io errors are retried — so the matrix leg
#: proves recovery by the suite simply staying green under ambient faults.
#: Primes keep the sites' firing patterns from locking phase with each other.
_PRESETS = {
    "ci": (
        "fusion.compile:every=13,"
        "fusion.execute:every=11,"
        "fusion.record:every=17,"
        "io.write:exc=OSError:every=5,"
        "io.read:exc=OSError:every=7,"
        # the checkpoint seams are recoverable by design: write/commit/
        # restore attempts retry transient OSErrors (call_with_retries), and
        # a failed GC deletion degrades to a warning + debris for the next
        # sweep — the kill-mid-save suite must stay green under this mix
        "checkpoint.write:exc=OSError:every=3,"
        "checkpoint.commit:exc=OSError:every=3,"
        "checkpoint.restore:exc=OSError:every=5,"
        "checkpoint.gc:exc=OSError:every=2"
    ),
}


def _parse_env(value: str) -> List[FaultSpec]:
    value = (value or "").strip()
    if not value or value.lower() in ("0", "off", "false", "no"):
        return []
    return _parse_specs(_PRESETS.get(value.lower(), value))


#: background specs from the env knob; suspended while an inject() is active
_BACKGROUND: List[FaultSpec] = _parse_env(os.environ.get("HEAT_TPU_FAULTS", ""))
#: specs from nested inject() contexts (innermost last; ALL active ones fire)
_OVERLAY: List[FaultSpec] = []
#: per-site fired counters (assertable surface; survives context exit)
_FIRED: Dict[str, int] = {}

#: module attribute so the instrumented hot paths gate with ONE attribute
#: read when the harness is disarmed — the same near-zero-cost contract as
#: ``telemetry._MODE``. True whenever any spec (background or overlay) is
#: armed, including exhausted/never-firing ones: "guards on, no faults".
_ARMED = bool(_BACKGROUND)


def check(site: str) -> None:
    """Raise the armed fault for ``site``, if any. Call sites gate on
    ``resilience._ARMED`` so the disarmed cost is one attribute read."""
    if not _ARMED:
        return
    for spec in _OVERLAY if _OVERLAY else _BACKGROUND:
        if spec.matches(site) and spec.should_fire():
            spec.fired += 1
            _FIRED[site] = _FIRED.get(site, 0) + 1
            if telemetry._MODE:
                # faults are first-class trace-timeline events: the exported
                # trace shows the degradation/retry right next to its cause
                telemetry.record_fault(site, spec.pattern)
            raise spec.make(site)


@contextmanager
def inject(
    site: str,
    exc=FaultInjected,
    times: Optional[int] = 1,
    every: Optional[int] = None,
    p: float = 1.0,
    seed: int = 0,
):
    """Arm a fault at ``site`` (an fnmatch pattern) for the scope's duration.

    Yields the :class:`FaultSpec` (inspect ``.fired`` afterwards). While any
    ``inject`` scope is active the ``HEAT_TPU_FAULTS`` background specs are
    suspended, so explicit tests stay exact under the CI fault mix. Nested
    scopes compose: every active overlay spec is consulted.
    """
    global _ARMED
    spec = FaultSpec(site, exc=exc, times=times, every=every, p=p, seed=seed)
    _OVERLAY.append(spec)
    _ARMED = True
    try:
        yield spec
    finally:
        _OVERLAY.remove(spec)
        _ARMED = bool(_BACKGROUND) or bool(_OVERLAY)


@contextmanager
def suspended():
    """Run with the ``HEAT_TPU_FAULTS`` background specs suspended (an
    armed-but-never-firing overlay): tests that pin exact fault/degradation
    counts shield themselves with this so they stay exact under the CI
    ambient fault mix, while still paying the armed-site checks."""
    with inject("__suspend__", times=0):
        yield


def fault_counts() -> Dict[str, int]:
    """Per-site injected-fault counts (``collective_counts()``-style)."""
    return dict(_FIRED)


def reset() -> None:
    """Zero the per-site fired counters (armed specs keep their own state)."""
    _FIRED.clear()


# ----------------------------------------------------------------------
# quarantine escalation: the per-device fault ledger
# ----------------------------------------------------------------------
# fusion's quarantine (fusion.py) contains a failure to ONE program; when
# the failures cluster on one DEVICE the right containment is topological —
# drop the device from the mesh, keep the job. The ledger below is the
# accounting that decides when a fault pattern is "one flaky device":
# callers (the elastic supervisor's health probes, collective failure
# handlers) attribute each fault to a device; crossing the threshold marks
# the device degraded, which core/elastic.py consumes as a mesh-shrink
# trigger at its next preemption poll.

def _parse_device_fault_threshold() -> int:
    raw = os.environ.get("HEAT_TPU_DEVICE_FAULT_THRESHOLD", "").strip()
    if not raw:
        return 3
    try:
        return max(1, int(raw))
    except ValueError:
        warnings.warn(
            f"HEAT_TPU_DEVICE_FAULT_THRESHOLD={raw!r} is not an int; using 3",
            stacklevel=1,
        )
        return 3


#: faults-per-device before the device is marked degraded
_DEVICE_FAULT_THRESHOLD = _parse_device_fault_threshold()

#: per-device attributed-fault counts (assertable surface, str(device) keys)
_DEVICE_FAULTS: Dict[str, int] = {}
#: devices past the threshold — the elastic supervisor's shrink set
_DEGRADED_DEVICES: set = set()


def note_device_fault(device, site: str = "collective") -> bool:
    """Attribute one ``collective.*``/dispatch fault to ``device`` in the
    per-device ledger. Crossing ``HEAT_TPU_DEVICE_FAULT_THRESHOLD`` (default
    3) marks the device degraded, emits a ``mesh_degraded`` telemetry event
    and warns :class:`MeshDegradedWarning`; returns True exactly when this
    call crossed the threshold. Faults *spread* across devices never trip it
    — only a per-device cluster reads as "this device is flaky"."""
    key = str(device)
    count = _DEVICE_FAULTS.get(key, 0) + 1
    _DEVICE_FAULTS[key] = count
    if key in _DEGRADED_DEVICES or count < _DEVICE_FAULT_THRESHOLD:
        return False
    _DEGRADED_DEVICES.add(key)
    if telemetry._MODE:
        telemetry.record_event(
            "mesh_degraded", device=key, faults=count, site=site
        )
    warnings.warn(
        MeshDegradedWarning(
            f"device {key} accumulated {count} attributed fault(s) at {site} "
            f"(threshold {_DEVICE_FAULT_THRESHOLD}): marked degraded — an "
            "elastic supervisor will re-form the mesh without it"
        ),
        stacklevel=2,
    )
    return True


def device_fault_counts() -> Dict[str, int]:
    """The per-device attributed-fault ledger (``fault_counts()``-style)."""
    return dict(_DEVICE_FAULTS)


def degraded_devices() -> set:
    """``str(device)`` keys currently past the degradation threshold."""
    return set(_DEGRADED_DEVICES)


def reset_device_faults() -> None:
    """Clear the per-device ledger and the degraded set (a reformed mesh
    starts with a clean bill of health)."""
    _DEVICE_FAULTS.clear()
    _DEGRADED_DEVICES.clear()


# ----------------------------------------------------------------------
# recovery policies — THE one place that decides what falls back
# ----------------------------------------------------------------------
#: record-time failures that legitimately mean "this op/operand combination
#: cannot be recorded abstractly" — the eager engine reproduces them (or
#: handles the operands) with per-op locality
_RECORD_FALLBACK_TYPES = (
    TypeError,
    ValueError,
    NotImplementedError,
    IndexError,
    ArithmeticError,  # Overflow/ZeroDivision from abstract eval of scalars
)


def record_recoverable(exc: BaseException) -> bool:
    """Whether a failure while *recording* an op into the fusion DAG should
    fall back to the eager engine (True) or propagate (False).

    Shape/dtype/tracing rejections fall back — the eager path either handles
    the operands or raises the same error with per-op locality. Injected
    record faults fall back too (that IS the recovery under test). Anything
    else — ``MemoryError``, arbitrary internal errors — propagates: the old
    bare ``except Exception`` would have silently swallowed a real fault
    into a confusing second failure from the eager retry.
    """
    if isinstance(exc, FaultInjected):
        return True
    if isinstance(exc, _RECORD_FALLBACK_TYPES):
        return True
    # jax's own tracing machinery errors (ConcretizationTypeError & friends
    # subclass neither of the above on every jax version)
    return (type(exc).__module__ or "").startswith("jax")


def force_recoverable(exc: BaseException) -> bool:
    """Whether a fused-program build/execute failure should degrade the
    chain to per-op eager dispatch. Everything a compile/runtime can throw —
    including ``MemoryError`` (OOM compiles are exactly the TPU failure mode
    worth surviving) — degrades; only our own policy signals propagate,
    since they are raised *by* the forcing point, not by XLA: the errstate
    non-finite error, the memory admission gate's refusal (which fires
    before the dispatch precisely so the pending chain stays intact), and
    the watchdog's stall escalation under the ``raise`` policy."""
    if isinstance(exc, (NonFiniteError, StallError)):
        return False
    from .memledger import MemoryBudgetExceeded

    return not isinstance(exc, MemoryBudgetExceeded)


# ----------------------------------------------------------------------
# numeric error policy: ht.errstate(nonfinite=...)
# ----------------------------------------------------------------------
_NONFINITE_MODES = ("ignore", "warn", "raise")

#: None = ignore (default, zero-cost gate); "warn"/"raise" otherwise. The
#: env knob seeds the initial state for whole-process runs (CI legs).
_ERRSTATE: Optional[str] = None
_env_nonfinite = os.environ.get("HEAT_TPU_NONFINITE", "ignore").strip().lower()
if _env_nonfinite in ("warn", "raise"):
    _ERRSTATE = _env_nonfinite

# Per-session (thread-local) overrides: a serving ``Session`` pushes a policy
# that applies only to its own thread, layered over the global ``_ERRSTATE``.
# ``_TLS_ARMED`` counts pushed overrides process-wide so the hot-path gates
# (``_operations._nonfinite_checked``, ``DNDarray.larray``) stay a single
# module-attribute read when no session override is active anywhere.
_ERR_TLS = threading.local()
_TLS_ARMED = 0
_TLS_LOCK = threading.Lock()


def _push_errstate(mode: Optional[str]) -> None:
    """Push a thread-local nonfinite policy (the serving ``Session`` seam).

    ``mode`` is ``None`` (= "ignore"), ``"warn"`` or ``"raise"`` and applies
    to the calling thread ONLY, shadowing the global policy until popped."""
    global _TLS_ARMED
    stack = getattr(_ERR_TLS, "stack", None)
    if stack is None:
        stack = _ERR_TLS.stack = []
    stack.append(mode)
    with _TLS_LOCK:
        _TLS_ARMED += 1


def _pop_errstate() -> None:
    global _TLS_ARMED
    stack = getattr(_ERR_TLS, "stack", None)
    if stack:
        stack.pop()
        with _TLS_LOCK:
            _TLS_ARMED -= 1


def _effective_errstate() -> Optional[str]:
    """The policy in force for the calling thread: the innermost session
    override when one is pushed, else the global ``ht.errstate`` policy."""
    stack = getattr(_ERR_TLS, "stack", None)
    if stack:
        return stack[-1]
    return _ERRSTATE


_isfinite_prog = None


class errstate:
    """Numeric error policy scope: ``ht.errstate(nonfinite="warn")``.

    ``nonfinite`` ∈ {"ignore", "warn", "raise"} controls what happens when a
    value materialized at a forcing point contains inf/NaN: nothing (the
    default — IEEE semantics, like the reference), a :class:`NonFiniteWarning`,
    or a :class:`NonFiniteError`. The check is one cheap jitted
    ``all(isfinite(x))`` reduction per force, runs only for inexact dtypes,
    and composes with telemetry (each hit is recorded when telemetry is on).
    ``numpy.errstate`` semantics: the policy applies on ``__enter__`` and the
    previous one is restored on exit, so scopes nest and an instance can be
    reused across ``with`` blocks. Process-wide configuration goes through
    the ``HEAT_TPU_NONFINITE`` env knob.
    """

    def __init__(self, nonfinite: str = "ignore"):
        if nonfinite not in _NONFINITE_MODES:
            raise ValueError(
                f"nonfinite must be one of {_NONFINITE_MODES}, got {nonfinite!r}"
            )
        self._mode = None if nonfinite == "ignore" else nonfinite
        # a stack, not a slot: one instance may be entered reentrantly
        # (with e: with e: ...) without leaking its policy on exit
        self._prev_stack: List[Optional[str]] = []

    def __enter__(self) -> "errstate":
        global _ERRSTATE
        self._prev_stack.append(_ERRSTATE)
        _ERRSTATE = self._mode
        return self

    def __exit__(self, *exc) -> None:
        global _ERRSTATE
        _ERRSTATE = self._prev_stack.pop()


def check_nonfinite(value, where: str = "force", *, program=None, cid=None) -> None:
    """Apply the active ``errstate`` policy to a materialized array.

    Call sites gate on ``resilience._ERRSTATE``/``_TLS_ARMED`` (two module
    attribute reads when no policy is active anywhere). Inexact dtypes only; the reduction is one jitted
    ``all(isfinite(x))`` — jit caches one tiny program per shape/sharding,
    and the scalar read is the only sync added. ``program``/``cid`` carry
    the provenance of the producing fused dispatch (the program key stamped
    on the root at force time and the chain's correlation id) so the
    warning/raise names WHICH program manufactured the inf/NaN instead of
    just where it was caught."""
    mode = _effective_errstate()
    if mode is None:
        return
    dtype = getattr(value, "dtype", None)
    if dtype is None:
        return
    import jax
    import jax.numpy as jnp

    if isinstance(value, jax.core.Tracer):
        return  # inside an enclosing trace: nothing concrete to check

    # jnp.issubdtype, not np: bfloat16 (the native TPU dtype) is inexact to
    # the ml_dtypes hierarchy but NOT to numpy's — the np gate would silently
    # exempt bf16 chains from the policy
    if not jnp.issubdtype(dtype, jnp.inexact):
        return
    global _isfinite_prog
    if _isfinite_prog is None:
        _isfinite_prog = jax.jit(lambda x: jnp.all(jnp.isfinite(x)))
    if bool(_isfinite_prog(value)):
        return
    if telemetry._MODE:
        telemetry.record_nonfinite(where)
    origin = ""
    if program is not None or cid is not None:
        origin = (
            f" produced by fused program {program or '<eager>'}"
            f" (chain cid {cid if cid is not None else '?'})"
        )
    msg = (
        f"non-finite values (inf/NaN) detected at {where} point "
        f"(shape {tuple(getattr(value, 'shape', ()))}, dtype {np.dtype(dtype).name})"
        f"{origin} under ht.errstate"
    )
    try:
        from . import numlens

        if numlens.active():
            numlens._add_finding(
                "numlens.nonfinite", "error", msg,
                where=where, program=program, cid=cid,
            )
    except Exception:  # pragma: no cover - import-order safety only
        pass
    if mode == "raise":
        raise NonFiniteError(msg)
    warnings.warn(NonFiniteWarning(msg), stacklevel=3)


# ----------------------------------------------------------------------
# retrying I/O
# ----------------------------------------------------------------------
#: errnos worth retrying: scheduler blips, interrupted syscalls, and the
#: device/network errors flaky NFS/GCS mounts produce. ENOENT/EACCES/ENOSPC
#: and friends are NOT here — retrying them only delays the real error.
_TRANSIENT_ERRNOS = frozenset(
    getattr(errno_module, name)
    for name in (
        "EAGAIN", "EWOULDBLOCK", "EINTR", "EBUSY", "EIO", "ETIMEDOUT",
        "ESTALE", "ECONNRESET", "ENETDOWN", "ENETUNREACH", "ENOBUFS",
    )
    if hasattr(errno_module, name)
)


class RetryPolicy:
    """Capped exponential backoff over transient ``OSError``s.

    ``retries`` extra attempts after the first (so ``retries=2`` = up to 3
    calls), ``base_delay`` seconds before the first retry, doubling up to
    ``max_delay``. :meth:`is_transient` is the classification seam."""

    __slots__ = ("retries", "base_delay", "max_delay", "transient_errnos")

    def __init__(
        self,
        retries: int = 2,
        base_delay: float = 0.05,
        max_delay: float = 1.0,
        transient_errnos: frozenset = _TRANSIENT_ERRNOS,
    ):
        self.retries = int(retries)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.transient_errnos = transient_errnos

    def is_transient(self, exc: BaseException) -> bool:
        return isinstance(exc, OSError) and exc.errno in self.transient_errnos

    def __repr__(self) -> str:
        return (
            f"RetryPolicy(retries={self.retries}, base_delay={self.base_delay},"
            f" max_delay={self.max_delay})"
        )


#: the module-level knob every retrying I/O path consults (swap it, or set
#: HEAT_TPU_IO_RETRIES / HEAT_TPU_IO_RETRY_DELAY before import)
retry_policy = RetryPolicy(
    retries=int(os.environ.get("HEAT_TPU_IO_RETRIES", "2")),
    base_delay=float(os.environ.get("HEAT_TPU_IO_RETRY_DELAY", "0.05")),
)


def call_with_retries(site: str, fn: Callable, *args, policy: Optional[RetryPolicy] = None, **kwargs):
    """Run ``fn(*args, **kwargs)``, retrying transient ``OSError``s with the
    active :class:`RetryPolicy`'s capped exponential backoff. ``site`` names
    the injection point checked before every attempt (so an injected
    ``OSError`` exercises exactly the retry path a flaky mount would)."""
    pol = policy if policy is not None else retry_policy
    delay = pol.base_delay
    attempt = 0
    while True:
        try:
            if _ARMED:
                check(site)
            return fn(*args, **kwargs)
        except OSError as exc:
            if attempt >= pol.retries or not pol.is_transient(exc):
                raise
            attempt += 1
            if telemetry._MODE:
                telemetry.record_io_retry(site)
            time.sleep(min(delay, pol.max_delay))
            delay *= 2.0


# ----------------------------------------------------------------------
# atomic writes (temp-then-rename), multihost-safe
# ----------------------------------------------------------------------
def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


@contextmanager
def atomic_write(path: str, preserve: bool = False):
    """Yield a private temp path next to ``path``; publish atomically on
    success, leave NOTHING behind on failure.

    The temp name embeds pid and process index so concurrent writers never
    collide. On clean exit the *owning* process (``multihost.io_owner()`` —
    process 0, the single-controller seam) renames temp→target via
    ``os.replace`` (atomic on POSIX); non-owning processes discard their
    temp, since under multi-controller SPMD every process calls ``save_*``
    with the same target path and only one rename may win. On any exception
    the temp is unlinked and the error propagates — a crashed save never
    leaves a partial file under either name.

    Scope: there is NO cross-process completion barrier — a non-owning
    controller's ``save_*`` may return before (or without) the owner's
    rename landing, so multi-controller read-after-save requires the
    caller's own synchronization. What the protocol guarantees is that the
    target path only ever holds a complete file (the old one, or the new
    one); split streaming saves on a partially-addressable mesh refuse
    outright (``io._rank_ordered_blocks``).

    ``preserve=True`` seeds the temp with a copy of the existing target
    (HDF5/netCDF append modes mutate in place; the copy keeps the append
    atomic too).
    """
    from . import multihost

    directory = os.path.dirname(os.path.abspath(path)) or "."
    proc = multihost.process_index()
    tmp = os.path.join(
        directory, f".{os.path.basename(path)}.tmp-{os.getpid()}-{proc}"
    )
    if preserve and os.path.exists(path):
        try:
            shutil.copy2(path, tmp)
        except BaseException:
            # a failed seed copy (ENOSPC, transient EIO) must not orphan a
            # partial temp: same leave-nothing-behind contract as the body
            _unlink_quiet(tmp)
            raise
    try:
        yield tmp
    except BaseException:
        _unlink_quiet(tmp)
        raise
    if not os.path.exists(tmp):
        # writer wrote nothing (e.g. zero addressable shards): nothing to publish
        return
    if multihost.io_owner(proc):
        try:
            if _ARMED:
                check("io.rename")
            os.replace(tmp, path)
        except BaseException:
            _unlink_quiet(tmp)
            raise
    else:
        _unlink_quiet(tmp)

"""Device abstraction over JAX backends.

TPU-native re-design of reference heat/core/devices.py:17-167: the reference
exposes ``cpu``/``gpu`` singletons (GPU chosen round-robin by MPI rank,
devices.py:98-102) plus a mutable global default. Here a :class:`Device` names
a JAX *backend* ("cpu" or "tpu"); actual placement of every array is governed
by the mesh/sharding in :mod:`heat_tpu.core.communication`, not per-rank device
ids — single-controller JAX drives all chips of the backend at once.
"""

from __future__ import annotations

from typing import Optional, Union

import jax

__all__ = ["Device", "cpu", "tpu", "gpu", "get_device", "sanitize_device", "use_device"]


class Device:
    """Represents a compute backend on which arrays live.

    Parameters
    ----------
    device_type : str
        "cpu" or "tpu" (``"gpu"`` is accepted as an alias for the accelerator
        backend for reference-API compatibility).
    device_id : int
        Kept for API parity; placement is mesh-driven, so this is always 0.
    """

    def __init__(self, device_type: str, device_id: int = 0):
        self.__device_type = device_type
        self.__device_id = device_id

    @property
    def device_type(self) -> str:
        return self.__device_type

    @property
    def device_id(self) -> int:
        return self.__device_id

    def jax_devices(self):
        """All JAX devices of this backend (may raise if backend missing)."""
        return jax.devices(self.__device_type)

    def __repr__(self) -> str:
        return f"device({self.__str__()!r})"

    def __str__(self) -> str:
        return f"{self.device_type}:{self.device_id}"

    def __eq__(self, other) -> bool:
        if isinstance(other, Device):
            return self.device_type == other.device_type
        if isinstance(other, str):
            try:
                return self.device_type == sanitize_device(other).device_type
            except ValueError:
                return NotImplemented
        return NotImplemented

    def __hash__(self):
        return hash(self.device_type)


cpu = Device("cpu")
"""The host CPU backend."""


def _accelerator_type() -> Optional[str]:
    try:
        backend = jax.default_backend()
    except Exception:  # pragma: no cover - backend probing must never crash import
        return None
    return backend if backend != "cpu" else None


tpu = Device("tpu")
"""The TPU backend (driven as a whole mesh, not per-rank round-robin as in
reference devices.py:98-102)."""

# Reference-API alias: scripts written against the reference say ht.gpu.
gpu = tpu

__default_device: Optional[Device] = None


def get_device() -> Device:
    """The currently-selected default device (reference devices.py:139)."""
    global __default_device
    if __default_device is None:
        __default_device = tpu if _accelerator_type() else cpu
    return __default_device


def sanitize_device(device: Optional[Union[str, Device]]) -> Device:
    """Normalize a device spec to a :class:`Device` (reference devices.py:146)."""
    if device is None:
        return get_device()
    if isinstance(device, Device):
        return device
    name = str(device).strip().lower().split(":")[0]
    if name == "cpu":
        return cpu
    if name in ("tpu", "gpu", "cuda", "axon"):
        return tpu
    raise ValueError(f"Unknown device, must be 'cpu' or 'tpu', got {device!r}")


def use_device(device: Optional[Union[str, Device]] = None) -> None:
    """Set the globally-used default device (reference devices.py:157-167)."""
    global __default_device
    __default_device = sanitize_device(device)

"""Runtime telemetry layer: collective accounting, forcing-point attribution
and retrace detection across the engines.

The reference framework ships no profiling subsystem (SURVEY.md §5) and — per
the Dask-MPI communication study (arxiv 2101.08878) and the array
redistribution work (arxiv 2112.01075) — per-collective counts and bytes
moved are the load-bearing metrics for diagnosing distributed array
performance. This module measures them natively at the three hot seams
instead of leaving tests and benches to infer them from HLO dumps:

* **Collectives** — every ``MeshCommunication`` verb (``allreduce`` /
  ``allgather`` / ``alltoall`` / ``ppermute`` / ``bcast`` / ``exscan`` /
  ``scan``) records op type, mesh axis, dtype and logical bytes moved
  (:func:`record_collective`, queried via :func:`collective_counts`).
  The explicitly-scheduled linalg kernels (TSQR, panel QR, blocked
  substitution) declare their schedule the same way. Counts are recorded at
  *Python call time*: for in-kernel (``shard_map``) use that is once per
  program trace; for the linalg wrappers it is once per wrapper call with
  the schedule's declared multiplicity.
* **Forcing points** — every ``fusion.force()`` is attributed to *what*
  triggered it (``parray``/``larray`` access, ``print``, ``indexing``,
  ``io``, ``collective``, ``pytree`` flatten) together with the chain depth
  forced, so blocking host reads become attributable instead of invisible
  (:func:`forcing_points`). Call sites scope themselves with
  :func:`force_trigger`; the outermost scope wins.
* **Compile/retrace tracking** — fusion-cache misses are keyed by *op
  family* (the DAG's op identities, ignoring shapes); when the same family
  keeps missing under different leaf shapes — shape churn defeating the
  sharded-program cache — a :class:`RetraceWarning` fires exactly once per
  family (:func:`record_retrace`). ``MeshCommunication.apply`` jit builds
  are counted per kernel name (:func:`record_compile`).

``HEAT_TPU_TELEMETRY={0,1,verbose}`` is the knob (read at import; in-process
control via :func:`set_mode`/:func:`enabled`). Disabled is the default and
costs one module-attribute check per instrumented site — the overhead guard
in tests/test_telemetry.py pins the telemetry-enabled eager-chain dispatch
rate at >= 0.9x the disabled rate. ``verbose`` additionally keeps a capped
event log (:func:`events`).

:func:`span` scopes all counters to a named region (spans nest —
``"fit/iter"`` paths) and integrates with ``utils/profiling.Timer``: timers
closing inside an active span are attributed to it, and every span records
its own wall time into the Timer registry under ``span:<path>``.

:func:`report` returns the whole picture as one structured dict;
:func:`report_json` serializes it (optionally to a file).

The module also owns the *compiled-program* side of collective accounting:
:func:`hlo_collectives` / :func:`hlo_collective_counts` parse an XLA HLO
dump into per-type collective instruction counts, and
:func:`collective_budget_excess` diffs them against a named budget — the
readable replacement for the hand-pinned ``len(coll) <= 7`` assertions the
linalg suites used to carry.
"""

from __future__ import annotations

import json
import os
import re
import time
import warnings
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

__all__ = [
    "RetraceWarning",
    "active",
    "async_forcing",
    "checkpoint_events",
    "collective_budget_excess",
    "collective_counts",
    "collectives",
    "current_trigger",
    "degraded",
    "degraded_counts",
    "dispatches",
    "enabled",
    "events",
    "force_trigger",
    "forcing_points",
    "fused_collectives",
    "hlo_collective_counts",
    "hlo_collectives",
    "io_retries",
    "nonfinite_counts",
    "on_timer",
    "operand_bytes",
    "record_async_dispatch",
    "record_blocking_sync",
    "record_checkpoint",
    "record_collective",
    "record_collective_operand",
    "record_compile",
    "record_degraded",
    "record_dispatch",
    "record_force",
    "record_fused_collective",
    "record_io_retry",
    "record_nonfinite",
    "record_retrace",
    "record_unfused",
    "report",
    "report_json",
    "reset",
    "retraces",
    "set_mode",
    "span",
    "spans",
    "unfused_reasons",
    "verbose",
]


class RetraceWarning(UserWarning):
    """An op family keeps missing the fusion program cache under different
    leaf shapes — shape churn is defeating the sharded-program cache and
    every miss pays a fresh XLA compile."""


_OFF_VALUES = ("", "0", "false", "off", "no")


def _parse_mode(value) -> int:
    if isinstance(value, bool):
        return 1 if value else 0
    if isinstance(value, int):
        return max(0, min(2, value))
    v = str(value).strip().lower()
    if v in _OFF_VALUES:
        return 0
    if v in ("2", "verbose", "debug"):
        return 2
    return 1


#: 0 = off, 1 = on, 2 = verbose. A module attribute (not a function) so the
#: instrumented hot paths can gate on ``telemetry._MODE`` with one attribute
#: read — the near-zero-overhead-when-disabled contract.
_MODE = _parse_mode(os.environ.get("HEAT_TPU_TELEMETRY", "0"))

#: distinct leaf-shape signatures a family may miss with before the one-shot
#: shape-churn warning fires. First-time compiles of a handful of fixed
#: shapes are normal warmup (each program stays cached afterwards); only a
#: family that keeps producing NEW shapes — paying a fresh XLA compile per
#: step — is the churn pathology, so the default sits well above warmup.
_RETRACE_WARN_AFTER = int(os.environ.get("HEAT_TPU_TELEMETRY_RETRACE_WARN", "8"))

_EVENT_CAP = 1024


def active() -> bool:
    """Whether telemetry is recording (``HEAT_TPU_TELEMETRY`` knob)."""
    return _MODE > 0


def verbose() -> bool:
    """Whether the capped per-event log is kept (``HEAT_TPU_TELEMETRY=verbose``)."""
    return _MODE >= 2


def set_mode(mode) -> int:
    """Set the telemetry mode in-process (0/off, 1/on, 2/'verbose');
    returns the previous mode. Accepts the same spellings as the env knob."""
    global _MODE
    prev, _MODE = _MODE, _parse_mode(mode)
    return prev


@contextmanager
def enabled(mode=1):
    """Context manager running with telemetry on (tests, bench legs)."""
    prev = set_mode(mode)
    try:
        yield
    finally:
        set_mode(prev)


# ----------------------------------------------------------------------
# counter state
# ----------------------------------------------------------------------
_COLLECTIVES: Dict[str, Dict[str, Any]] = {}
_FORCES: Dict[str, Dict[str, Any]] = {}
_RETRACES: Dict[tuple, Dict[str, Any]] = {}
_COMPILES: Dict[str, int] = {}
_DISPATCHES: Dict[str, Dict[str, int]] = {}
_DEGRADED: Dict[str, Dict[str, Any]] = {}
_UNFUSED: Dict[str, Dict[str, int]] = {}
_NONFINITE: Dict[str, int] = {}
_IO_RETRIES: Dict[str, int] = {}
_CHECKPOINT: Dict[str, int] = {}
_FUSED_COLLECTIVES: Dict[str, int] = {}
_ASYNC = {"dispatches": 0, "roots": 0, "multi_root_batches": 0}
_BLOCKING: Dict[str, int] = {}
_EVENTS: deque = deque(maxlen=_EVENT_CAP)

_TRIGGER_STACK: List[str] = []
_SPAN_STACK: list = []
_SPANS: Dict[str, Dict[str, Any]] = {}


def reset() -> None:
    """Clear every counter, span and event (the mode is left untouched)."""
    _COLLECTIVES.clear()
    _FORCES.clear()
    _RETRACES.clear()
    _COMPILES.clear()
    _DISPATCHES.clear()
    _DEGRADED.clear()
    _UNFUSED.clear()
    _NONFINITE.clear()
    _IO_RETRIES.clear()
    _CHECKPOINT.clear()
    _FUSED_COLLECTIVES.clear()
    _ASYNC.update(dispatches=0, roots=0, multi_root_batches=0)
    _BLOCKING.clear()
    _EVENTS.clear()
    _SPANS.clear()


# ----------------------------------------------------------------------
# collectives
# ----------------------------------------------------------------------
def operand_bytes(x) -> int:
    """Logical payload bytes of a pytree of arrays as seen at the call site
    (per-participant shard bytes inside a ``shard_map`` kernel, global bytes
    outside). Tracers count via their abstract shape; shapeless leaves
    (python scalars) count zero."""
    import numpy as np

    total = 0
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(x)
    except Exception:  # pragma: no cover - jax always importable here
        leaves = [x]
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return total


def record_collective_operand(op: str, axis: Optional[str], x, count: int = 1) -> None:
    """Record a collective whose payload is the pytree ``x``: one leaf walk
    derives both the logical bytes and the dtype (the communication verbs'
    single call site). No-op when telemetry is off."""
    if not _MODE:
        return
    import numpy as np

    try:
        import jax

        leaves = jax.tree_util.tree_leaves(x)
    except Exception:  # pragma: no cover - jax always importable here
        leaves = [x]
    total = 0
    dtype = None
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dt = getattr(leaf, "dtype", None)
        if shape is None or dt is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
        if dtype is None:
            dtype = str(dt)
    record_collective(op, axis, total, dtype, count)


def record_collective(
    op: str,
    axis: Optional[str] = None,
    nbytes: int = 0,
    dtype: Optional[str] = None,
    count: int = 1,
) -> None:
    """Record ``count`` logical collectives of type ``op`` moving ``nbytes``
    over mesh axis ``axis``. Called by the communication verbs and the
    declared linalg schedules; no-op when telemetry is off."""
    if not _MODE:
        return
    rec = _COLLECTIVES.get(op)
    if rec is None:
        rec = _COLLECTIVES[op] = {"count": 0, "bytes": 0, "axes": {}, "dtypes": {}}
    rec["count"] += count
    rec["bytes"] += int(nbytes) * count
    if axis is not None:
        rec["axes"][axis] = rec["axes"].get(axis, 0) + count
    if dtype is not None:
        rec["dtypes"][dtype] = rec["dtypes"].get(dtype, 0) + count
    if _MODE >= 2:
        _EVENTS.append(
            {"kind": "collective", "op": op, "axis": axis, "bytes": int(nbytes), "dtype": dtype, "count": count}
        )
    if _SPAN_STACK:
        for frame in _SPAN_STACK:
            frame.collectives[op] = frame.collectives.get(op, 0) + count


def collective_counts() -> Dict[str, int]:
    """Per-type logical collective counts — the assertable surface for tests
    and benches: ``{"allreduce": 3, "allgather": 1, ...}``."""
    return {op: rec["count"] for op, rec in _COLLECTIVES.items()}


def collectives() -> Dict[str, Dict[str, Any]]:
    """Full per-type accounting: count, bytes moved, per-axis and per-dtype
    breakdowns."""
    return {
        op: {
            "count": rec["count"],
            "bytes": rec["bytes"],
            "axes": dict(rec["axes"]),
            "dtypes": dict(rec["dtypes"]),
        }
        for op, rec in _COLLECTIVES.items()
    }


def record_fused_collective(kind: str) -> None:
    """Count one collective NODE recorded into the fusion DAG (a deferred
    split-crossing reduction's psum, a deferred ``reshard``, a deferred
    ``apply:<kernel>``). These collectives execute INSIDE fused programs, so
    :func:`collective_counts` does not see them at dispatch time — this
    ledger counts them at record time, and ``fusion.program_hlo`` +
    :func:`hlo_collective_counts` cross-check the compiled side."""
    if not _MODE:
        return
    _FUSED_COLLECTIVES[kind] = _FUSED_COLLECTIVES.get(kind, 0) + 1
    if _MODE >= 2:
        _EVENTS.append({"kind": "fused_collective", "op": kind})


def fused_collectives() -> Dict[str, int]:
    """Per-kind counts of collective nodes recorded into fusion DAGs."""
    return dict(_FUSED_COLLECTIVES)


# ----------------------------------------------------------------------
# asynchronous forcing: dispatches vs blocking syncs
# ----------------------------------------------------------------------
def record_async_dispatch(n_roots: int) -> None:
    """Count one asynchronous ``fusion.force`` dispatch covering ``n_roots``
    DAG roots (>1 = independent live roots batched into one multi-output
    program). Dispatches install device futures without blocking."""
    if not _MODE:
        return
    _ASYNC["dispatches"] += 1
    _ASYNC["roots"] += int(n_roots)
    if n_roots > 1:
        _ASYNC["multi_root_batches"] += 1
    if _MODE >= 2:
        _EVENTS.append({"kind": "dispatch", "roots": int(n_roots)})


def record_blocking_sync(kind: str) -> None:
    """Count one host boundary (``item``/``numpy``/``print``/``shards``)
    that had to synchronously materialize a PENDING chain — reads of values
    already dispatched (in flight or done) are free and never counted. The
    assertable surface for "this chain cost one sync"."""
    if not _MODE:
        return
    _BLOCKING[kind] = _BLOCKING.get(kind, 0) + 1
    if _MODE >= 2:
        _EVENTS.append({"kind": "blocking_sync", "where": kind})


def async_forcing() -> Dict[str, Any]:
    """The async-forcing picture: program ``dispatches`` (with total
    ``roots_dispatched`` and how many dispatches batched multiple roots)
    versus ``blocking_syncs`` — host boundaries that synchronously forced a
    pending chain, by kind, with their total."""
    return {
        "dispatches": _ASYNC["dispatches"],
        "roots_dispatched": _ASYNC["roots"],
        "multi_root_batches": _ASYNC["multi_root_batches"],
        "blocking_syncs": dict(_BLOCKING),
        "blocking_total": sum(_BLOCKING.values()),
    }


# ----------------------------------------------------------------------
# forcing-point attribution
# ----------------------------------------------------------------------
class _TriggerScope:
    """Reentrant scope naming the forcing point for any ``fusion.force``
    that fires inside it; the OUTERMOST scope wins (a print that forces via
    ``larray`` is attributed to print, not larray)."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __enter__(self) -> "_TriggerScope":
        _TRIGGER_STACK.append(self.name)
        return self

    def __exit__(self, *exc) -> None:
        _TRIGGER_STACK.pop()


_TRIGGER_SCOPES: Dict[str, _TriggerScope] = {}


def force_trigger(name: str) -> _TriggerScope:
    """The (cached, reusable) attribution scope for forcing trigger ``name``."""
    scope = _TRIGGER_SCOPES.get(name)
    if scope is None:
        scope = _TRIGGER_SCOPES[name] = _TriggerScope(name)
    return scope


def current_trigger() -> str:
    """The attribution for a force firing right now (outermost scope, or the
    bare-``parray``-access default)."""
    return _TRIGGER_STACK[0] if _TRIGGER_STACK else "parray"


def record_force(trigger: str, depth: int, compiled: bool = False) -> None:
    """Record one materialized chain: ``trigger`` names the forcing point,
    ``depth`` the recorded chain depth dispatched, ``compiled`` whether this
    force paid a fresh XLA compile (cache miss)."""
    if not _MODE:
        return
    rec = _FORCES.get(trigger)
    if rec is None:
        rec = _FORCES[trigger] = {"count": 0, "depth_total": 0, "max_depth": 0, "compiles": 0}
    rec["count"] += 1
    rec["depth_total"] += int(depth)
    if depth > rec["max_depth"]:
        rec["max_depth"] = int(depth)
    if compiled:
        rec["compiles"] += 1
    if _MODE >= 2:
        _EVENTS.append({"kind": "force", "trigger": trigger, "depth": int(depth), "compiled": compiled})
    if _SPAN_STACK:
        for frame in _SPAN_STACK:
            frame.forces += 1


def forcing_points() -> Dict[str, Dict[str, Any]]:
    """Per-trigger forcing histogram: count, mean/max chain depth forced,
    and how many of those forces paid a compile."""
    out = {}
    for trigger, rec in _FORCES.items():
        out[trigger] = {
            "count": rec["count"],
            "mean_depth": round(rec["depth_total"] / rec["count"], 2) if rec["count"] else 0.0,
            "max_depth": rec["max_depth"],
            "compiles": rec["compiles"],
        }
    return out


# ----------------------------------------------------------------------
# compile / retrace tracking
# ----------------------------------------------------------------------
def record_retrace(family: tuple, shape_key) -> None:
    """Record a fusion-cache miss for op ``family`` (the DAG's op identities)
    under leaf-shape signature ``shape_key``. When one family accumulates
    ``_RETRACE_WARN_AFTER`` distinct shape signatures, a
    :class:`RetraceWarning` fires — exactly once per family."""
    if not _MODE:
        return
    rec = _RETRACES.get(family)
    if rec is None:
        rec = _RETRACES[family] = {"misses": 0, "keys": set(), "warned": False}
    rec["misses"] += 1
    if not rec["warned"]:
        # the key set only exists to cross the warn threshold; once warned,
        # ``misses`` tracks volume and the set stops growing (shape churn is
        # exactly the case that would otherwise accumulate keys unboundedly)
        rec["keys"].add(shape_key)
    if _SPAN_STACK:
        for frame in _SPAN_STACK:
            frame.retraces += 1
    if not rec["warned"] and len(rec["keys"]) >= _RETRACE_WARN_AFTER:
        rec["warned"] = True
        warnings.warn(
            RetraceWarning(
                f"op family {'/'.join(family) or '<leaf>'} recompiled under "
                f"{len(rec['keys'])} distinct input shapes ({rec['misses']} cache "
                "misses): shape churn is defeating the fusion program cache — pad "
                "or bucket the varying dimension, or force the chain before the "
                "shape-dependent step"
            ),
            stacklevel=3,
        )


def retraces() -> Dict[str, Dict[str, Any]]:
    """Per-op-family fusion-cache miss accounting."""
    return {
        "/".join(family) or "<leaf>": {
            "misses": rec["misses"],
            "distinct_shapes": len(rec["keys"]),
            "warned": rec["warned"],
        }
        for family, rec in _RETRACES.items()
    }


def record_compile(label: str) -> None:
    """Count a jit program build outside the fusion cache (e.g. one
    ``MeshCommunication.apply`` kernel), keyed by kernel label."""
    if not _MODE:
        return
    _COMPILES[label] = _COMPILES.get(label, 0) + 1


# ----------------------------------------------------------------------
# engine dispatch accounting
# ----------------------------------------------------------------------
def record_dispatch(engine: str, fused: bool) -> None:
    """Count one L3-engine dispatch (``binary``/``local``/``reduce``/``cum``)
    as deferred-into-the-DAG (``fused``) or eager."""
    if not _MODE:
        return
    rec = _DISPATCHES.get(engine)
    if rec is None:
        rec = _DISPATCHES[engine] = {"fused": 0, "eager": 0}
    rec["fused" if fused else "eager"] += 1


def dispatches() -> Dict[str, Dict[str, int]]:
    """Per-engine fused-vs-eager dispatch counts."""
    return {k: dict(v) for k, v in _DISPATCHES.items()}


def record_unfused(engine: str, reason: str) -> None:
    """One breadcrumb per eager-fallback site: ``engine`` declined to defer
    an op for ``reason`` (``out=``, ``where=``, ``padded_broadcast``,
    ``tracer_payload``, ``record_failed:<Type>``, ...) — so ``report()``
    shows *why* a chain wasn't fused, not just that it wasn't."""
    if not _MODE:
        return
    rec = _UNFUSED.get(engine)
    if rec is None:
        rec = _UNFUSED[engine] = {}
    rec[reason] = rec.get(reason, 0) + 1


def unfused_reasons() -> Dict[str, Dict[str, int]]:
    """Per-engine reasons ops fell back to the eager engine instead of
    deferring into the fusion DAG."""
    return {k: dict(v) for k, v in _UNFUSED.items()}


# ----------------------------------------------------------------------
# resilience accounting (core/resilience.py)
# ----------------------------------------------------------------------
def record_degraded(family: tuple, stage: str, error: str = "") -> None:
    """Record one guarded-forcing degradation: the fused program for op
    ``family`` failed at ``stage`` (``compile``/``execute``) and the chain
    was re-run as per-op eager dispatch (fusion quarantines the DAG key)."""
    if not _MODE:
        return
    key = "/".join(family) or "<leaf>"
    rec = _DEGRADED.get(key)
    if rec is None:
        rec = _DEGRADED[key] = {"count": 0, "stages": {}, "last_error": ""}
    rec["count"] += 1
    rec["stages"][stage] = rec["stages"].get(stage, 0) + 1
    if error:
        rec["last_error"] = error
    if _MODE >= 2:
        _EVENTS.append({"kind": "degraded", "family": key, "stage": stage, "error": error})


def degraded_counts() -> Dict[str, int]:
    """Per-op-family guarded-forcing degradation counts — the assertable
    surface (``collective_counts()``-style) the resilience suite pins."""
    return {key: rec["count"] for key, rec in _DEGRADED.items()}


def degraded() -> Dict[str, Dict[str, Any]]:
    """Full degradation accounting: count, per-stage breakdown, last error."""
    return {
        key: {
            "count": rec["count"],
            "stages": dict(rec["stages"]),
            "last_error": rec["last_error"],
        }
        for key, rec in _DEGRADED.items()
    }


def record_nonfinite(where: str) -> None:
    """Count one errstate non-finite detection at forcing point ``where``."""
    if not _MODE:
        return
    _NONFINITE[where] = _NONFINITE.get(where, 0) + 1
    if _MODE >= 2:
        _EVENTS.append({"kind": "nonfinite", "where": where})


def nonfinite_counts() -> Dict[str, int]:
    """Per-forcing-point errstate non-finite detections."""
    return dict(_NONFINITE)


def record_io_retry(site: str) -> None:
    """Count one transient-``OSError`` retry at I/O injection site ``site``."""
    if not _MODE:
        return
    _IO_RETRIES[site] = _IO_RETRIES.get(site, 0) + 1
    if _MODE >= 2:
        _EVENTS.append({"kind": "io_retry", "site": site})


def io_retries() -> Dict[str, int]:
    """Per-site transient I/O retry counts."""
    return dict(_IO_RETRIES)


def record_checkpoint(event: str, step: Optional[int] = None, detail: str = "") -> None:
    """Count one checkpoint lifecycle event (``utils/checkpoint.py``):
    ``save`` (manifest committed), ``restore`` (verified restore completed),
    ``corrupt`` (a checkpoint failed verification), ``fallback`` (restore
    skipped unverifiable newer checkpoints), ``gc`` (retention/debris sweep
    removed something). The assertable surface the checkpoint suite pins."""
    if not _MODE:
        return
    _CHECKPOINT[event] = _CHECKPOINT.get(event, 0) + 1
    if _MODE >= 2:
        _EVENTS.append({"kind": "checkpoint", "event": event, "step": step, "detail": detail})


def checkpoint_events() -> Dict[str, int]:
    """Per-event checkpoint lifecycle counts (``save``/``restore``/
    ``corrupt``/``fallback``/``gc``)."""
    return dict(_CHECKPOINT)


# ----------------------------------------------------------------------
# spans
# ----------------------------------------------------------------------
class _SpanFrame:
    __slots__ = ("path", "t0", "collectives", "forces", "retraces", "timers")

    def __init__(self, path: str):
        self.path = path
        self.t0 = time.perf_counter()
        self.collectives: Dict[str, int] = {}
        self.forces = 0
        self.retraces = 0
        self.timers: Dict[str, float] = {}


@contextmanager
def span(name: str):
    """Scope all counters to a named region. Spans nest (``"fit"`` containing
    ``"fit/iter"``), attribute the collective / forcing / retrace deltas that
    occur inside them, absorb ``utils/profiling.Timer`` records closing
    within them, and mirror their own wall time into the Timer registry as
    ``span:<path>`` so the two report surfaces stay joined. Yields the full
    span path (or None when telemetry is off)."""
    if not _MODE:
        yield None
        return
    path = (_SPAN_STACK[-1].path + "/" + name) if _SPAN_STACK else name
    frame = _SpanFrame(path)
    _SPAN_STACK.append(frame)
    try:
        yield path
    finally:
        _SPAN_STACK.pop()
        elapsed = time.perf_counter() - frame.t0
        rec = _SPANS.get(path)
        if rec is None:
            rec = _SPANS[path] = {
                "calls": 0,
                "total_s": 0.0,
                "collectives": {},
                "forces": 0,
                "retraces": 0,
                "timers": {},
            }
        rec["calls"] += 1
        rec["total_s"] += elapsed
        rec["forces"] += frame.forces
        rec["retraces"] += frame.retraces
        for op, cnt in frame.collectives.items():
            rec["collectives"][op] = rec["collectives"].get(op, 0) + cnt
        for tname, secs in frame.timers.items():
            rec["timers"][tname] = rec["timers"].get(tname, 0.0) + secs
        try:  # mirror into the Timer registry (utils/profiling nesting contract)
            from ..utils import profiling

            profiling.record_timing("span:" + path, elapsed)
        except Exception:  # pragma: no cover - report must not die on import order
            pass


def on_timer(name: str, elapsed: float) -> None:
    """Called by ``utils/profiling.Timer`` on every record so timers closing
    inside an active span are attributed to EVERY enclosing span — the same
    roll-up rule as collectives/forces (``span:`` mirrors excluded)."""
    if not _SPAN_STACK or name.startswith("span:"):
        return
    for frame in _SPAN_STACK:
        frame.timers[name] = frame.timers.get(name, 0.0) + elapsed


def spans() -> Dict[str, Dict[str, Any]]:
    """Per-span aggregates: calls, wall seconds, attributed collective
    counts, forces, retraces and nested timer seconds."""
    return {
        path: {
            "calls": rec["calls"],
            "total_s": rec["total_s"],
            "collectives": dict(rec["collectives"]),
            "forces": rec["forces"],
            "retraces": rec["retraces"],
            "timers": dict(rec["timers"]),
        }
        for path, rec in _SPANS.items()
    }


# ----------------------------------------------------------------------
# report
# ----------------------------------------------------------------------
def report() -> Dict[str, Any]:
    """The whole telemetry picture as one structured dict (JSON-ready via
    :func:`report_json`). Includes the fusion program-cache counters and the
    ``utils/profiling`` timer registry so one call answers "where did the
    time, the bytes and the compiles go"."""
    doc: Dict[str, Any] = {
        "enabled": active(),
        "mode": {0: "off", 1: "on", 2: "verbose"}[_MODE],
        "collectives": collectives(),
        "collective_counts": collective_counts(),
        "fused_collectives": fused_collectives(),
        "async_forcing": async_forcing(),
        "forcing_points": forcing_points(),
        "dispatches": dispatches(),
        "unfused_reasons": unfused_reasons(),
        "retraces": retraces(),
        "degraded": degraded(),
        "nonfinite": nonfinite_counts(),
        "io_retries": io_retries(),
        "checkpoint": checkpoint_events(),
        "jit_compiles": dict(_COMPILES),
        "spans": spans(),
    }
    try:
        from . import fusion

        doc["fusion_cache"] = fusion.cache_stats()
    except Exception:  # pragma: no cover
        pass
    try:
        from ..utils import profiling

        doc["timers"] = profiling.report()
    except Exception:  # pragma: no cover
        pass
    if _MODE >= 2:
        doc["events"] = list(_EVENTS)
    return doc


def report_json(path: Optional[str] = None, indent: int = 2) -> str:
    """:func:`report` serialized to JSON; written to ``path`` when given."""
    text = json.dumps(report(), indent=indent, default=str)
    if path is not None:
        with open(path, "w") as fh:
            fh.write(text)
            fh.write("\n")
    return text


def events() -> List[dict]:
    """The capped verbose event log (empty unless ``HEAT_TPU_TELEMETRY=verbose``)."""
    return list(_EVENTS)


# ----------------------------------------------------------------------
# compiled-program (HLO) collective accounting
# ----------------------------------------------------------------------
#: collective opcodes as they appear in HLO text, in call position
#: (``all-reduce(...)`` / async ``all-reduce-start(...)``). Order matters:
#: longest-prefix alternatives first so ``all-to-all`` never half-matches.
_HLO_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce-scatter|reduce-scatter|all-gather|all-reduce|all-to-all|"
    r"collective-permute|collective-broadcast)(?:-start)?\("
)


def hlo_collectives(hlo_text: str) -> List[Dict[str, str]]:
    """Collective *instructions* in an HLO dump: one entry per collective op
    in call position (async ``-start``/``-done`` pairs count once, via the
    start; instruction names and operand references never match). Each entry
    carries the op type and its source line for byte-budget checks."""
    out = []
    for line in hlo_text.splitlines():
        if "(" not in line or "=" not in line:
            continue
        # the regex requires "(" (or "-start(") right after the opcode, so
        # async "-done(" companions and name/operand references never match
        m = _HLO_COLLECTIVE_RE.search(line)
        if m:
            out.append({"op": m.group(1), "line": line.strip()})
    return out


def hlo_collective_counts(hlo_text: str) -> Dict[str, int]:
    """Per-type collective instruction counts of a compiled HLO dump —
    ``{"all-reduce": 3, "all-gather": 1}``. The readable replacement for
    counting regex hits against a single magic number."""
    counts: Dict[str, int] = {}
    for entry in hlo_collectives(hlo_text):
        counts[entry["op"]] = counts.get(entry["op"], 0) + 1
    return counts


def collective_budget_excess(
    counts: Dict[str, int], budget: Dict[str, int]
) -> Dict[str, str]:
    """Violations of a named per-type collective budget: any type over its
    allowance, or present but absent from the budget. Empty dict = within
    budget. Asserting ``collective_budget_excess(...) == {}`` fails with a
    diff that names the collective type instead of a magic total."""
    excess = {}
    for op, count in counts.items():
        allowed = budget.get(op)
        if allowed is None:
            excess[op] = f"{count} present but not budgeted"
        elif count > allowed:
            excess[op] = f"{count} > budget {allowed}"
    return excess

"""Runtime telemetry layer: collective accounting, forcing-point attribution,
retrace detection, scoped sessions and the trace timeline.

The reference framework ships no profiling subsystem (SURVEY.md §5) and — per
the Dask-MPI communication study (arxiv 2101.08878) and the array
redistribution work (arxiv 2112.01075) — per-collective counts and bytes
moved are the load-bearing metrics for diagnosing distributed array
performance. This module measures them natively at the three hot seams
instead of leaving tests and benches to infer them from HLO dumps:

* **Collectives** — every ``MeshCommunication`` verb (``allreduce`` /
  ``allgather`` / ``alltoall`` / ``ppermute`` / ``bcast`` / ``exscan`` /
  ``scan``) records op type, mesh axis, dtype and logical bytes moved
  (:func:`record_collective`, queried via :func:`collective_counts`).
  The explicitly-scheduled linalg kernels (TSQR, panel QR, blocked
  substitution) declare their schedule the same way. Counts are recorded at
  *Python call time*: for in-kernel (``shard_map``) use that is once per
  program trace; for the linalg wrappers it is once per wrapper call with
  the schedule's declared multiplicity.
* **Forcing points** — every ``fusion.force()`` is attributed to *what*
  triggered it (``parray``/``larray`` access, ``print``, ``indexing``,
  ``io``, ``collective``, ``pytree`` flatten) together with the chain depth
  forced, so blocking host reads become attributable instead of invisible
  (:func:`forcing_points`). Call sites scope themselves with
  :func:`force_trigger`; the outermost scope wins.
* **Compile/retrace tracking** — fusion-cache misses are keyed by *op
  family* (the DAG's op identities, ignoring shapes); when the same family
  keeps missing under different leaf shapes — shape churn defeating the
  sharded-program cache — a :class:`RetraceWarning` fires exactly once per
  family (:func:`record_retrace`). ``MeshCommunication.apply`` jit builds
  are counted per kernel name (:func:`record_compile`).

``HEAT_TPU_TELEMETRY={0,1,verbose}`` is the knob (read at import; in-process
control via :func:`set_mode`/:func:`enabled`). Disabled is the default and
costs one module-attribute check per instrumented site — the overhead guard
in tests/test_telemetry.py pins the telemetry-enabled eager-chain dispatch
rate at >= 0.9x the disabled rate.

The trace timeline
------------------
``verbose`` keeps a capped, monotonic-timestamped **event log** of typed
events (:func:`events`): ``record`` / ``compile`` / ``dispatch`` /
``blocking_sync`` / ``collective`` / ``fused_collective`` / ``force`` /
``degraded`` / ``fault`` / ``io_retry`` / ``io`` / ``checkpoint`` /
``checkpoint_phase`` / ``timer`` / ``span_begin`` / ``span_end`` /
``memory`` / ``memory_gate`` / ``memory_oom`` (the live-buffer ledger's
samples, gate decisions and OOM forensics — ``core/memledger.py``; the
exporter renders ``memory`` samples as per-host Perfetto counter tracks).
Events of
one fused chain's lifecycle share a **correlation id** (``cid``, assigned at
record time by ``core/fusion.py`` and inherited along the chain): the
``dispatch`` event lists every batched root's cid plus the sharded-program
key it launched (``fusion.cache_stats()["program_keys"]``), and the
``blocking_sync`` event that waited on it carries the same cid — see
doc/internals_distribution.md for the schema and the cid contract. The log
is a bounded deque: truncation is *visible* as ``events_dropped`` in
``report()["timeline"]`` (cap via ``HEAT_TPU_TELEMETRY_EVENTS``).

:func:`export_trace` renders the timeline as Chrome/Perfetto trace-event
JSON (load in ``ui.perfetto.dev`` or ``chrome://tracing``): spans and timers
as B/E duration pairs, dispatch→blocking-sync as async (``b``/``e``) pairs
keyed by cid, everything else as instants — one process row per host
(``multihost.process_index()``), with :func:`merge_traces` stitching
per-host files of a multihost run into one. ``python -m heat_tpu.telemetry``
pretty-prints / diffs ``report_json`` artifacts and validates trace files.

Scoped sessions
---------------
:func:`scope` opens a reentrant **telemetry session**: counters, spans and
events recorded inside are visible *isolated* through the query functions
(the innermost scope wins) while still rolling up into the enclosing scopes
and the global state live — the per-session surface ROADMAP item 4's
multi-tenant serving layer attaches to. Completed scopes are archived under
``report()["scopes"]`` (re-entering a path accumulates).

:func:`span` scopes all counters to a named region (spans nest —
``"fit/iter"`` paths) and integrates with ``utils/profiling.Timer``: timers
closing inside an active span are attributed to it, and every span records
its own wall time into the Timer registry under ``span:<path>``.

:func:`report` returns the whole picture as one structured dict — including
a ``memory`` block (``profiling.device_memory_stats``, live-buffer bytes,
the owner-attributed ledger + high watermark and the admission-gate state
from ``core/memledger.py``; best-effort, device stats empty off-TPU) and a
top-N ``programs`` block (per-cached-program dispatch counts with a
``cost_errors`` tally; :func:`program_costs` adds flops / bytes-accessed /
in-program collective estimates *and static memory peaks* from each
program's HLO, on demand because the estimate compiles). :func:`report_json` serializes it deterministically
(tuple keys are joined, sets sorted — never ``default=str`` drift);
``HEAT_TPU_METRICS=<path>`` streams it as JSON-lines periodically and at
exit (:func:`set_metrics_sink`) so long jobs are observable externally.

Event emission stays near-zero-cost when ``HEAT_TPU_TELEMETRY=0`` and never
forces a pending chain or adds a blocking sync of its own.

The module also owns the *compiled-program* side of collective accounting:
:func:`hlo_collectives` / :func:`hlo_collective_counts` parse an XLA HLO
dump into per-type collective instruction counts, and
:func:`collective_budget_excess` diffs them against a named budget — the
readable replacement for the hand-pinned ``len(coll) <= 7`` assertions the
linalg suites used to carry.
"""

from __future__ import annotations

import atexit
import json
import os
import re
import sys
import threading
import time
import warnings
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

__all__ = [
    "RetraceWarning",
    "TimelineDroppedWarning",
    "active",
    "async_forcing",
    "checkpoint_events",
    "collective_budget_excess",
    "collective_counts",
    "collectives",
    "current_trigger",
    "degraded",
    "degraded_counts",
    "dispatches",
    "enabled",
    "end_blocking_sync",
    "events",
    "export_trace",
    "fault_events",
    "force_trigger",
    "forcing_points",
    "fused_collectives",
    "hlo_collective_counts",
    "hlo_collectives",
    "io_retries",
    "merge_traces",
    "nonfinite_counts",
    "on_timer",
    "operand_bytes",
    "program_costs",
    "record_async_dispatch",
    "record_blocking_sync",
    "record_checkpoint",
    "record_collective",
    "record_collective_operand",
    "record_compile",
    "record_degraded",
    "record_dispatch",
    "record_event",
    "record_fault",
    "record_force",
    "record_fused_collective",
    "record_io_retry",
    "record_nonfinite",
    "record_retrace",
    "record_unfused",
    "report",
    "report_json",
    "reset",
    "retraces",
    "scope",
    "scope_reports",
    "set_metrics_sink",
    "set_mode",
    "span",
    "spans",
    "trace_collective_parity",
    "trace_events",
    "unfused_reasons",
    "validate_trace",
    "verbose",
]


class RetraceWarning(UserWarning):
    """An op family keeps missing the fusion program cache under different
    leaf shapes — shape churn is defeating the sharded-program cache and
    every miss pays a fresh XLA compile."""


class TimelineDroppedWarning(UserWarning):
    """The trace timeline hit its event cap and evicted the oldest events —
    the recorded window is now TRUNCATED, and any analysis over it
    (``tracelens.analyze``, exported traces) undercounts whatever happened
    before the surviving suffix. One-shot per :func:`reset`; raise
    ``HEAT_TPU_TELEMETRY_EVENTS`` to keep the whole window."""


_OFF_VALUES = ("", "0", "false", "off", "no")


def _parse_mode(value) -> int:
    if isinstance(value, bool):
        return 1 if value else 0
    if isinstance(value, int):
        return max(0, min(2, value))
    v = str(value).strip().lower()
    if v in _OFF_VALUES:
        return 0
    if v in ("2", "verbose", "debug"):
        return 2
    return 1


#: 0 = off, 1 = on, 2 = verbose. A module attribute (not a function) so the
#: instrumented hot paths can gate on ``telemetry._MODE`` with one attribute
#: read — the near-zero-overhead-when-disabled contract.
_MODE = _parse_mode(os.environ.get("HEAT_TPU_TELEMETRY", "0"))

#: distinct leaf-shape signatures a family may miss with before the one-shot
#: shape-churn warning fires. First-time compiles of a handful of fixed
#: shapes are normal warmup (each program stays cached afterwards); only a
#: family that keeps producing NEW shapes — paying a fresh XLA compile per
#: step — is the churn pathology, so the default sits well above warmup.
_RETRACE_WARN_AFTER = int(os.environ.get("HEAT_TPU_TELEMETRY_RETRACE_WARN", "8"))

#: trace-timeline event cap per state (global and per scope). Overflow drops
#: the OLDEST events and counts them (``report()["timeline"]["events_dropped"]``)
#: — truncation is visible, never silent.
_EVENT_CAP = int(os.environ.get("HEAT_TPU_TELEMETRY_EVENTS", "8192"))

#: one-shot latch for :class:`TimelineDroppedWarning` — the first cap
#: eviction after a :func:`reset` warns loudly; subsequent drops only count
_DROP_WARNED = False

#: programs shown in ``report()["programs"]`` (ranked by dispatch count)
_TOP_PROGRAMS = int(os.environ.get("HEAT_TPU_TELEMETRY_TOP_PROGRAMS", "5"))

#: memory-ledger sampling hook (``core/memledger.py`` installs its ``note``
#: here at import — set-attribute, not import, so this module stays
#: dependency-free). Called at the dispatch/force/collective/checkpoint
#: record seams so the live-buffer high watermark tracks the events that
#: change memory; None until the ledger module loads.
_MEM_HOOK = None

#: flight-recorder hook (``core/health_runtime.py`` installs its ring-buffer
#: append here at import — same set-attribute pattern as ``_MEM_HOOK``).
#: Called with every typed event dict from :func:`_note_event`, including at
#: plain ``HEAT_TPU_TELEMETRY=1`` where the verbose timelines stay empty —
#: the always-on black box costs one deque append per event. None until the
#: health module loads or when ``HEAT_TPU_FLIGHT=0``.
_FLIGHT_HOOK = None

#: blocking-sync completion hook (``core/health_runtime.py``): called as
#: ``_SYNC_HOOK(kind, cid, dur_s)`` whenever :func:`end_blocking_sync`
#: closes a token — feeds the host-wait latency histograms and resolves
#: dispatch→done durations without this module importing the health layer.
_SYNC_HOOK = None

#: elastic-supervisor stats hook (``core/elastic.py`` installs its ``stats``
#: snapshot here at import — same set-attribute pattern). ``report()`` calls
#: it to populate ``report()["elastic"]`` (preemptions survived, reforms,
#: downtime, steps replayed); None until the elastic module loads.
_ELASTIC_HOOK = None

#: autoscale-controller stats hook (``core/autoscale.py`` installs its
#: ``stats`` snapshot here at import — same set-attribute pattern).
#: ``report()`` joins it as ``report()["autoscale"]`` (controller state,
#: shed tiers, decision counters, mesh devices vs. baseline) and the
#: opsplane collector reads it for the ``heat_tpu_autoscale_*`` families;
#: None until the autoscale module loads.
_AUTOSCALE_HOOK = None

#: multi-process runtime stats hook (``core/multihost.py`` installs its
#: ``report_stats`` snapshot here at import — same set-attribute pattern).
#: ``report()`` joins it as ``report()["multihost"]`` (heartbeats, lost
#: peers, barrier waits/timeouts, abandoned barrier threads) and the
#: opsplane collector reads it for the ``heat_tpu_peers_*`` /
#: ``heat_tpu_barrier_*`` families; None until the multihost module loads.
_MULTIHOST_HOOK = None

#: numerics-lens sampling hook (``core/numlens.py`` installs its
#: ``_on_dispatch`` here via ``numlens.set_mode`` — same set-attribute
#: pattern). Called by ``fusion.force`` as ``_NUMLENS_HOOK(sig, leaves,
#: roots, values, info)`` after a fused program's root values land, so the
#: lens can sample streaming tensor statistics and shadow-replay drift
#: audits; None whenever ``HEAT_TPU_NUMLENS`` is off — the disabled hot
#: path pays exactly this one ``is None`` check.
_NUMLENS_HOOK = None


def active() -> bool:
    """Whether telemetry is recording (``HEAT_TPU_TELEMETRY`` knob)."""
    return _MODE > 0


def verbose() -> bool:
    """Whether the trace timeline is kept (``HEAT_TPU_TELEMETRY=verbose``)."""
    return _MODE >= 2


def set_mode(mode) -> int:
    """Set the telemetry mode in-process (0/off, 1/on, 2/'verbose');
    returns the previous mode. Accepts the same spellings as the env knob."""
    global _MODE
    prev, _MODE = _MODE, _parse_mode(mode)
    return prev


@contextmanager
def enabled(mode=1):
    """Context manager running with telemetry on (tests, bench legs)."""
    prev = set_mode(mode)
    try:
        yield
    finally:
        set_mode(prev)


# ----------------------------------------------------------------------
# counter state: one _State per telemetry session
# ----------------------------------------------------------------------
class _State:
    """One isolated set of telemetry counters + an event deque.

    The module keeps one shared global state (``_GLOBAL``) plus a
    THREAD-LOCAL stack of scope states: every active :func:`scope` pushes
    its own onto the entering thread's stack. Record functions write to the
    global state and every scope on the calling thread's stack (so scopes
    roll up live); query functions read the calling thread's INNERMOST
    scope (so concurrent sessions are isolated)."""

    __slots__ = (
        "path", "t0", "wall_s", "calls", "collectives", "forces", "retraces",
        "compiles", "dispatches", "degraded", "unfused", "nonfinite",
        "io_retries", "checkpoint", "fused_collectives", "async_", "blocking",
        "sync_wait", "faults", "spans", "events", "events_dropped",
    )

    def __init__(self, path: str = ""):
        self.path = path
        self.calls = 1
        self.wall_s = 0.0
        self.clear()

    def clear(self) -> None:
        self.t0 = time.perf_counter()
        self.collectives: Dict[str, Dict[str, Any]] = {}
        self.forces: Dict[str, Dict[str, Any]] = {}
        self.retraces: Dict[tuple, Dict[str, Any]] = {}
        self.compiles: Dict[str, int] = {}
        self.dispatches: Dict[str, Dict[str, int]] = {}
        self.degraded: Dict[str, Dict[str, Any]] = {}
        self.unfused: Dict[str, Dict[str, int]] = {}
        self.nonfinite: Dict[str, int] = {}
        self.io_retries: Dict[str, int] = {}
        self.checkpoint: Dict[str, int] = {}
        self.fused_collectives: Dict[str, int] = {}
        self.async_ = {"dispatches": 0, "roots": 0, "multi_root_batches": 0}
        self.blocking: Dict[str, int] = {}
        # true host-wait durations per trigger, aggregated in NON-verbose
        # mode too (the verbose timeline carries the per-event stamps)
        self.sync_wait: Dict[str, Dict[str, float]] = {}
        self.faults: Dict[str, int] = {}
        self.spans: Dict[str, Dict[str, Any]] = {}
        self.events: deque = deque(maxlen=_EVENT_CAP)
        self.events_dropped = 0

    def append_event(self, ev: dict) -> None:
        if self.events.maxlen is not None and len(self.events) == self.events.maxlen:
            self.events_dropped += 1
            global _DROP_WARNED
            if not _DROP_WARNED:
                _DROP_WARNED = True
                warnings.warn(
                    "trace timeline hit its event cap "
                    f"({self.events.maxlen}): oldest events are being dropped "
                    "and the recorded window is truncated — raise "
                    "HEAT_TPU_TELEMETRY_EVENTS to keep the whole window "
                    "(tracelens refuses truncated windows without "
                    "allow_partial)",
                    TimelineDroppedWarning,
                    stacklevel=3,
                )
        self.events.append(ev)


def _add_int(dst: Dict[str, int], src: Dict[str, int]) -> None:
    for k, v in src.items():
        dst[k] = dst.get(k, 0) + v


def _merge_state(dst: _State, src: _State) -> None:
    """Accumulate ``src`` into ``dst`` (the completed-scope rollup)."""
    for op, rec in src.collectives.items():
        d = dst.collectives.setdefault(op, {"count": 0, "bytes": 0, "axes": {}, "dtypes": {}})
        d["count"] += rec["count"]
        d["bytes"] += rec["bytes"]
        _add_int(d["axes"], rec["axes"])
        _add_int(d["dtypes"], rec["dtypes"])
    for trig, rec in src.forces.items():
        d = dst.forces.setdefault(trig, {"count": 0, "depth_total": 0, "max_depth": 0, "compiles": 0})
        d["count"] += rec["count"]
        d["depth_total"] += rec["depth_total"]
        d["max_depth"] = max(d["max_depth"], rec["max_depth"])
        d["compiles"] += rec["compiles"]
    for fam, rec in src.retraces.items():
        d = dst.retraces.setdefault(fam, {"misses": 0, "keys": set(), "warned": False})
        d["misses"] += rec["misses"]
        if not d["warned"]:
            # bounded union: the set exists to cross the warn threshold, so
            # archived scopes never need (and must never hold) more keys —
            # re-entered scopes under shape churn would otherwise leak
            for key in rec["keys"]:
                if len(d["keys"]) >= _RETRACE_WARN_AFTER:
                    d["warned"] = True
                    break
                d["keys"].add(key)
        d["warned"] = d["warned"] or rec["warned"]
    _add_int(dst.compiles, src.compiles)
    for eng, rec in src.dispatches.items():
        _add_int(dst.dispatches.setdefault(eng, {}), rec)
    for key, rec in src.degraded.items():
        d = dst.degraded.setdefault(key, {"count": 0, "stages": {}, "last_error": ""})
        d["count"] += rec["count"]
        _add_int(d["stages"], rec["stages"])
        d["last_error"] = rec["last_error"] or d["last_error"]
    for eng, rec in src.unfused.items():
        _add_int(dst.unfused.setdefault(eng, {}), rec)
    _add_int(dst.nonfinite, src.nonfinite)
    _add_int(dst.io_retries, src.io_retries)
    _add_int(dst.checkpoint, src.checkpoint)
    _add_int(dst.fused_collectives, src.fused_collectives)
    _add_int(dst.async_, src.async_)
    _add_int(dst.blocking, src.blocking)
    for kind, rec in src.sync_wait.items():
        d = dst.sync_wait.setdefault(kind, {"count": 0, "total_s": 0.0, "max_s": 0.0})
        d["count"] += rec["count"]
        d["total_s"] += rec["total_s"]
        d["max_s"] = max(d["max_s"], rec["max_s"])
    _add_int(dst.faults, src.faults)
    for path, rec in src.spans.items():
        d = dst.spans.setdefault(
            path, {"calls": 0, "total_s": 0.0, "collectives": {}, "forces": 0, "retraces": 0, "timers": {}}
        )
        d["calls"] += rec["calls"]
        d["total_s"] += rec["total_s"]
        d["forces"] += rec["forces"]
        d["retraces"] += rec["retraces"]
        _add_int(d["collectives"], rec["collectives"])
        for t, s in rec["timers"].items():
            d["timers"][t] = d["timers"].get(t, 0.0) + s
    for ev in src.events:
        dst.append_event(ev)
    dst.events_dropped += src.events_dropped
    dst.wall_s += src.wall_s
    dst.calls += src.calls


_GLOBAL = _State()
#: completed-scope accumulators, keyed by scope path (re-entry accumulates)
_SCOPES: Dict[str, _State] = {}

# Scope/span/trigger stacks are THREAD-LOCAL: each thread (a serving
# session, a client of `ht.serving`) resolves its own innermost scope, so a
# second thread entering a scope can never interleave with the first's
# stack. Records still roll up into the shared _GLOBAL state, queries read
# the calling thread's innermost scope, and the completed-scope archive is
# merged under _SCOPE_LOCK.
_TLS = threading.local()
#: the common fast path (no scope active on this thread) — one cached tuple,
#: no per-record allocation
_GLOBAL_ONLY = (_GLOBAL,)
#: every scope state currently active on ANY thread (reset() must clear all)
_ACTIVE_SCOPE_STATES: List[_State] = []
_SCOPE_LOCK = threading.Lock()


def _scope_stack() -> List[_State]:
    """This thread's active scope states, innermost last (created lazily)."""
    stack = getattr(_TLS, "scopes", None)
    if stack is None:
        stack = _TLS.scopes = []
    return stack


def _states():
    """Every state the calling thread records into: the shared global state
    plus this thread's own scope stack."""
    stack = getattr(_TLS, "scopes", None)
    if not stack:
        return _GLOBAL_ONLY
    return [_GLOBAL] + stack


def _span_stack() -> list:
    stack = getattr(_TLS, "spans", None)
    if stack is None:
        stack = _TLS.spans = []
    return stack


def _trigger_stack() -> List[str]:
    stack = getattr(_TLS, "triggers", None)
    if stack is None:
        stack = _TLS.triggers = []
    return stack


def _cur() -> _State:
    stack = getattr(_TLS, "scopes", None)
    return stack[-1] if stack else _GLOBAL


def reset() -> None:
    """Clear every counter, span, event and completed scope of every active
    state, and reset the ``utils/profiling`` timer registry, the
    ``core/memledger`` session state (watermark, gate counters, stored OOM
    report — the budget arming itself is configuration and survives) and the
    ``core/health_runtime`` session state (flight ring, latency histograms,
    SLO windows, stall log — knobs and watchdog arming survive) and the
    ``core/numlens`` session state (tensor stats, drift ledger, canary and
    training streams — the lens mode survives) with them:
    the report surfaces are joined — ``report()`` merges timers, the memory
    block and the health block in, so a reset that left any stale would
    mislabel the next bench's report. The mode is left untouched; active
    :func:`scope`/:func:`span` stacks keep recording."""
    global _DROP_WARNED
    _DROP_WARNED = False
    _GLOBAL.clear()
    with _SCOPE_LOCK:
        for st in list(_ACTIVE_SCOPE_STATES):  # every thread's active scopes
            st.clear()
        _SCOPES.clear()
    try:
        from ..utils import profiling

        profiling.reset()
    except Exception:  # pragma: no cover - import-order safety only
        pass
    try:
        from . import memledger

        memledger.reset()
    except Exception:  # pragma: no cover - import-order safety only
        pass
    try:
        from . import health_runtime

        health_runtime.reset()
    except Exception:  # pragma: no cover - import-order safety only
        pass
    try:
        from . import elastic

        elastic.reset()
    except Exception:  # pragma: no cover - import-order safety only
        pass
    try:
        from . import numlens

        numlens.reset()
    except Exception:  # pragma: no cover - import-order safety only
        pass
    try:
        from . import serving

        serving.reset()
    except Exception:  # pragma: no cover - import-order safety only
        pass
    try:
        from . import opsplane

        opsplane.reset()
    except Exception:  # pragma: no cover - import-order safety only
        pass
    try:
        from . import autoscale

        autoscale.reset()
    except Exception:  # pragma: no cover - import-order safety only
        pass


# ----------------------------------------------------------------------
# the trace timeline: typed, monotonic-timestamped events
# ----------------------------------------------------------------------
def _emit(kind: str, **fields) -> dict:
    """Append one typed event to every active state's timeline. Callers gate
    on ``_MODE >= 2``; the event carries a monotonic ``ts`` (perf_counter
    seconds — the exporter converts to trace microseconds) and the innermost
    scope path when a scope is active."""
    ev: Dict[str, Any] = {"kind": kind, "ts": time.perf_counter()}
    ev.update(fields)
    stack = getattr(_TLS, "scopes", None)
    if stack:
        ev["scope"] = stack[-1].path
    for st in _states():
        st.append_event(ev)
    return ev


def _note_event(kind: str, **fields) -> Optional[dict]:
    """Record one typed event on the joined timeline surfaces: the verbose
    per-state timelines (``_MODE >= 2``) and — even at plain
    ``HEAT_TPU_TELEMETRY=1`` — the always-on flight ring when
    ``core/health_runtime.py`` has installed ``_FLIGHT_HOOK``. Returns the
    (shared, mutable) event dict when anything recorded it, else None."""
    if _MODE >= 2:
        ev = _emit(kind, **fields)
        if _FLIGHT_HOOK is not None:
            _FLIGHT_HOOK(ev)
        return ev
    if _MODE and _FLIGHT_HOOK is not None:
        ev = {"kind": kind, "ts": time.perf_counter()}
        ev.update(fields)
        stack = getattr(_TLS, "scopes", None)
        if stack:
            ev["scope"] = stack[-1].path
        _FLIGHT_HOOK(ev)
        return ev
    return None


def record_event(kind: str, **fields) -> Optional[dict]:
    """Emit one typed trace-timeline event (no counter side effects). The
    public seam for subsystems with lifecycle phases worth a timestamp but
    no counter (checkpoint phases, io ingest milestones). Lands on the
    verbose timeline (``HEAT_TPU_TELEMETRY=verbose``) and on the flight ring
    (any active mode, flight armed); returns the (mutable) event dict, or
    None when nothing recorded it."""
    if not _MODE:
        return None
    return _note_event(kind, **fields)


def events() -> List[dict]:
    """The capped timeline of the innermost active state (empty unless
    ``HEAT_TPU_TELEMETRY=verbose``)."""
    return list(_cur().events)


# ----------------------------------------------------------------------
# scoped telemetry sessions
# ----------------------------------------------------------------------
@contextmanager
def scope(name: str):
    """Open an isolated telemetry session named ``name``.

    Counters/spans/events recorded inside are visible through the query
    functions as the scope's OWN (isolation) while also recording into every
    enclosing scope and the global state live (rollup) — so a multi-tenant
    server can meter one session without losing the fleet-wide picture.
    Scopes are reentrant and nest (paths join as ``outer/inner``); on exit
    the session is archived under ``report()["scopes"][path]``, re-entering
    the same path accumulates (``calls`` counts entries). The stack is
    THREAD-LOCAL: concurrent threads each resolve their own innermost scope
    (two threads entering scopes never interleave stacks), while the global
    rollup and the completed-scope archive stay shared. Yields the scope
    path, or None when telemetry is off."""
    if not _MODE:
        yield None
        return
    stack = _scope_stack()
    path = (stack[-1].path + "/" + str(name)) if stack else str(name)
    st = _State(path)
    stack.append(st)
    with _SCOPE_LOCK:
        _ACTIVE_SCOPE_STATES.append(st)
    try:  # the health layer scopes its histograms alongside (joined surface)
        from . import health_runtime

        health_runtime._push_scope(path)
    except Exception:  # pragma: no cover - import-order safety only
        pass
    try:
        yield path
    finally:
        st.wall_s = time.perf_counter() - st.t0
        # remove by identity: reset()/nesting must never pop the wrong frame
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is st:
                del stack[i]
                break
        with _SCOPE_LOCK:
            for i in range(len(_ACTIVE_SCOPE_STATES) - 1, -1, -1):
                if _ACTIVE_SCOPE_STATES[i] is st:
                    del _ACTIVE_SCOPE_STATES[i]
                    break
            acc = _SCOPES.get(path)
            if acc is None:
                acc = _SCOPES[path] = _State(path)
                acc.calls = 0
                acc.wall_s = 0.0
            _merge_state(acc, st)
        try:
            from . import health_runtime

            health_runtime._pop_scope(path)
        except Exception:  # pragma: no cover - import-order safety only
            pass


def _scope_doc(st: _State) -> Dict[str, Any]:
    """One archived scope rendered report-shaped."""
    return {
        "calls": st.calls,
        "wall_s": st.wall_s,
        "collectives": _render_collectives(st),
        "collective_counts": {op: rec["count"] for op, rec in st.collectives.items()},
        "fused_collectives": dict(st.fused_collectives),
        "async_forcing": _render_async(st),
        "forcing_points": _render_forces(st),
        "dispatches": {k: dict(v) for k, v in st.dispatches.items()},
        "unfused_reasons": {k: dict(v) for k, v in st.unfused.items()},
        "retraces": _render_retraces(st),
        "degraded": _render_degraded(st),
        "nonfinite": dict(st.nonfinite),
        "io_retries": dict(st.io_retries),
        "checkpoint": dict(st.checkpoint),
        "faults": dict(st.faults),
        "jit_compiles": dict(st.compiles),
        "spans": _render_spans(st),
        "timeline": {
            "events": len(st.events),
            "events_dropped": st.events_dropped,
            "cap": _EVENT_CAP,
        },
    }


def scope_reports() -> Dict[str, Dict[str, Any]]:
    """Every completed scope's archived counters, keyed by scope path."""
    return {path: _scope_doc(acc) for path, acc in _SCOPES.items()}


# ----------------------------------------------------------------------
# collectives
# ----------------------------------------------------------------------
def operand_bytes(x) -> int:
    """Logical payload bytes of a pytree of arrays as seen at the call site
    (per-participant shard bytes inside a ``shard_map`` kernel, global bytes
    outside). Tracers count via their abstract shape; shapeless leaves
    (python scalars) count zero."""
    import numpy as np

    total = 0
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(x)
    except Exception:  # pragma: no cover - jax always importable here
        leaves = [x]
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    return total


def record_collective_operand(op: str, axis: Optional[str], x, count: int = 1) -> None:
    """Record a collective whose payload is the pytree ``x``: one leaf walk
    derives both the logical bytes and the dtype (the communication verbs'
    single call site). No-op when telemetry is off."""
    if not _MODE:
        return
    import numpy as np

    try:
        import jax

        leaves = jax.tree_util.tree_leaves(x)
    except Exception:  # pragma: no cover - jax always importable here
        leaves = [x]
    total = 0
    dtype = None
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dt = getattr(leaf, "dtype", None)
        if shape is None or dt is None:
            continue
        total += int(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
        if dtype is None:
            dtype = str(dt)
    record_collective(op, axis, total, dtype, count)


def _in_trace() -> bool:
    """Whether a jax trace is active right now (verbose events only: a
    collective recorded from inside a ``shard_map`` kernel is stamped at
    TRACE time, not execution time — the timeline marks it so)."""
    try:
        import jax

        return not jax.core.trace_state_clean()
    except Exception:  # pragma: no cover - jax-version safety
        return False


#: sleep per recorded collective when the ``trace.hostdelay`` fault site is
#: armed on this host — the straggler-attribution test seam
_TRACE_DELAY_S = float(os.environ.get("HEAT_TPU_TRACE_DELAY_MS", "20")) / 1e3


def _maybe_host_delay() -> None:
    """Straggler test seam: when fault injection has armed the
    ``trace.hostdelay`` site on THIS host (``resilience.inject``), every
    collective record sleeps ``HEAT_TPU_TRACE_DELAY_MS`` before stamping its
    event — one simulated slow worker whose cumulative lag the tracelens
    straggler attribution must name. Looked up via ``sys.modules`` (never an
    import: resilience imports this module) and gated on its armed flag, so
    the cost is one dict probe when fault injection is idle."""
    res = sys.modules.get("heat_tpu.core.resilience")
    if res is None or not getattr(res, "_ARMED", False):
        return
    try:
        res.check("trace.hostdelay")
    except Exception:  # noqa: BLE001 - the raised fault IS the trigger
        time.sleep(_TRACE_DELAY_S)


def record_collective(
    op: str,
    axis: Optional[str] = None,
    nbytes: int = 0,
    dtype: Optional[str] = None,
    count: int = 1,
) -> None:
    """Record ``count`` logical collectives of type ``op`` moving ``nbytes``
    over mesh axis ``axis``. Called by the communication verbs and the
    declared linalg schedules; no-op when telemetry is off."""
    if not _MODE:
        return
    _maybe_host_delay()
    for st in _states():
        rec = st.collectives.get(op)
        if rec is None:
            rec = st.collectives[op] = {"count": 0, "bytes": 0, "axes": {}, "dtypes": {}}
        rec["count"] += count
        rec["bytes"] += int(nbytes) * count
        if axis is not None:
            rec["axes"][axis] = rec["axes"].get(axis, 0) + count
        if dtype is not None:
            rec["dtypes"][dtype] = rec["dtypes"].get(dtype, 0) + count
    if _MODE >= 2 or _FLIGHT_HOOK is not None:
        _note_event(
            "collective",
            op=op, axis=axis, bytes=int(nbytes), dtype=dtype, count=count,
            traced=_in_trace(),
        )
    for frame in _span_stack():
            frame.collectives[op] = frame.collectives.get(op, 0) + count
    if _MEM_HOOK is not None:
        _MEM_HOOK("collective")


def _render_collectives(st: _State) -> Dict[str, Dict[str, Any]]:
    return {
        op: {
            "count": rec["count"],
            "bytes": rec["bytes"],
            "axes": dict(rec["axes"]),
            "dtypes": dict(rec["dtypes"]),
        }
        for op, rec in st.collectives.items()
    }


def collective_counts() -> Dict[str, int]:
    """Per-type logical collective counts — the assertable surface for tests
    and benches: ``{"allreduce": 3, "allgather": 1, ...}``. Inside a
    :func:`scope` this is the scope's own isolated view."""
    return {op: rec["count"] for op, rec in _cur().collectives.items()}


def collectives() -> Dict[str, Dict[str, Any]]:
    """Full per-type accounting: count, bytes moved, per-axis and per-dtype
    breakdowns."""
    return _render_collectives(_cur())


def record_fused_collective(
    kind: str, cid: Optional[int] = None, detail: Optional[str] = None
) -> None:
    """Count one collective NODE recorded into the fusion DAG (a deferred
    split-crossing reduction's psum, a deferred ``reshard``, a deferred
    ``apply:<kernel>``). These collectives execute INSIDE fused programs, so
    :func:`collective_counts` does not see them at dispatch time — this
    ledger counts them at record time, and ``fusion.program_hlo`` +
    :func:`hlo_collective_counts` cross-check the compiled side. ``detail``
    rides the timeline event only (e.g. a reshard's target split axis — what
    the tracelens ping-pong detector keys on)."""
    if not _MODE:
        return
    _maybe_host_delay()
    for st in _states():
        st.fused_collectives[kind] = st.fused_collectives.get(kind, 0) + 1
    _note_event("fused_collective", op=kind, cid=cid, detail=detail)


def fused_collectives() -> Dict[str, int]:
    """Per-kind counts of collective nodes recorded into fusion DAGs."""
    return dict(_cur().fused_collectives)


# ----------------------------------------------------------------------
# asynchronous forcing: dispatches vs blocking syncs
# ----------------------------------------------------------------------
def record_async_dispatch(
    n_roots: int,
    cid: Optional[int] = None,
    cids=(),
    program: Optional[str] = None,
    sessions=None,
) -> None:
    """Count one asynchronous ``fusion.force`` dispatch covering ``n_roots``
    DAG roots (>1 = independent live roots batched into one multi-output
    program). Dispatches install device futures without blocking. ``cid`` is
    the triggering chain's correlation id, ``cids`` every batched root's,
    ``program`` the sharded-program key launched (None for degraded/
    quarantined replays) — the timeline event links the whole lifecycle.
    ``sessions`` (aligned with ``cids``) names each batched root's serving
    session when cross-session batching grouped tenants into one dispatch,
    so tracelens/SLO attribution can bill the right tenant per cid."""
    if not _MODE:
        return
    for st in _states():
        st.async_["dispatches"] += 1
        st.async_["roots"] += int(n_roots)
        if n_roots > 1:
            st.async_["multi_root_batches"] += 1
    if sessions is not None and any(s is not None for s in sessions):
        _note_event(
            "dispatch", roots=int(n_roots), cid=cid, cids=list(cids),
            program=program, sessions=list(sessions),
        )
    else:
        _note_event("dispatch", roots=int(n_roots), cid=cid, cids=list(cids), program=program)
    if _MEM_HOOK is not None:
        _MEM_HOOK("dispatch")


def record_blocking_sync(kind: str, cid: Optional[int] = None) -> Optional[dict]:
    """Count one host boundary (``item``/``numpy``/``print``/``shards``)
    that had to synchronously materialize a PENDING chain — reads of values
    already dispatched (in flight or done) are free and never counted. The
    assertable surface for "this chain cost one sync".

    ``cid`` is the pending chain's correlation id. Returns the timeline
    event token (any active mode) so the call site can close it with
    :func:`end_blocking_sync` once the host actually holds the value — the
    event then carries the true wall duration of the sync. In verbose mode
    the token lives on the per-state timelines; at plain mode it feeds the
    flight ring and the ``sync_wait`` aggregate (the non-verbose answer to
    "how long did we wait")."""
    if not _MODE:
        return None
    for st in _states():
        st.blocking[kind] = st.blocking.get(kind, 0) + 1
    ev = _note_event("blocking_sync", where=kind, cid=cid)
    if ev is not None:
        return ev
    # mode 1, flight disarmed: a plain token still feeds the wait aggregate
    return {"kind": "blocking_sync", "ts": time.perf_counter(), "where": kind, "cid": cid}


def end_blocking_sync(token: Optional[dict]) -> None:
    """Close a blocking-sync timeline event returned by
    :func:`record_blocking_sync`: stamps the wall ``dur`` the host boundary
    spent from noting the pending chain to holding the materialized value,
    folds it into every active state's ``sync_wait`` aggregate (count /
    total / max per trigger — reported in non-verbose mode too), and feeds
    the health layer's latency histograms via ``_SYNC_HOOK``."""
    if token is None:
        return
    dur = time.perf_counter() - token["ts"]
    token["dur"] = dur
    kind = str(token.get("where"))
    for st in _states():
        rec = st.sync_wait.get(kind)
        if rec is None:
            rec = st.sync_wait[kind] = {"count": 0, "total_s": 0.0, "max_s": 0.0}
        rec["count"] += 1
        rec["total_s"] += dur
        if dur > rec["max_s"]:
            rec["max_s"] = dur
    if _SYNC_HOOK is not None:
        _SYNC_HOOK(kind, token.get("cid"), dur)


def _render_async(st: _State) -> Dict[str, Any]:
    return {
        "dispatches": st.async_["dispatches"],
        "roots_dispatched": st.async_["roots"],
        "multi_root_batches": st.async_["multi_root_batches"],
        "blocking_syncs": dict(st.blocking),
        "blocking_total": sum(st.blocking.values()),
        "sync_wait": {
            kind: {
                "count": rec["count"],
                "total_s": round(rec["total_s"], 6),
                "max_s": round(rec["max_s"], 6),
            }
            for kind, rec in st.sync_wait.items()
        },
    }


def async_forcing() -> Dict[str, Any]:
    """The async-forcing picture: program ``dispatches`` (with total
    ``roots_dispatched`` and how many dispatches batched multiple roots)
    versus ``blocking_syncs`` — host boundaries that synchronously forced a
    pending chain, by kind, with their total."""
    return _render_async(_cur())


# ----------------------------------------------------------------------
# forcing-point attribution
# ----------------------------------------------------------------------
class _TriggerScope:
    """Reentrant scope naming the forcing point for any ``fusion.force``
    that fires inside it; the OUTERMOST scope wins (a print that forces via
    ``larray`` is attributed to print, not larray)."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __enter__(self) -> "_TriggerScope":
        _trigger_stack().append(self.name)
        return self

    def __exit__(self, *exc) -> None:
        _trigger_stack().pop()


_TRIGGER_SCOPES: Dict[str, _TriggerScope] = {}


def force_trigger(name: str) -> _TriggerScope:
    """The (cached, reusable) attribution scope for forcing trigger ``name``."""
    scope_ = _TRIGGER_SCOPES.get(name)
    if scope_ is None:
        scope_ = _TRIGGER_SCOPES[name] = _TriggerScope(name)
    return scope_


def current_trigger() -> str:
    """The attribution for a force firing right now (outermost scope, or the
    bare-``parray``-access default)."""
    stack = getattr(_TLS, "triggers", None)
    return stack[0] if stack else "parray"


def record_force(trigger: str, depth: int, compiled: bool = False, cid: Optional[int] = None) -> None:
    """Record one materialized chain: ``trigger`` names the forcing point,
    ``depth`` the recorded chain depth dispatched, ``compiled`` whether this
    force paid a fresh XLA compile (cache miss), ``cid`` the chain's
    correlation id."""
    if not _MODE:
        return
    for st in _states():
        rec = st.forces.get(trigger)
        if rec is None:
            rec = st.forces[trigger] = {"count": 0, "depth_total": 0, "max_depth": 0, "compiles": 0}
        rec["count"] += 1
        rec["depth_total"] += int(depth)
        if depth > rec["max_depth"]:
            rec["max_depth"] = int(depth)
        if compiled:
            rec["compiles"] += 1
    _note_event("force", trigger=trigger, depth=int(depth), compiled=compiled, cid=cid)
    for frame in _span_stack():
            frame.forces += 1
    if _MEM_HOOK is not None:
        _MEM_HOOK("force")


def _render_forces(st: _State) -> Dict[str, Dict[str, Any]]:
    out = {}
    for trigger, rec in st.forces.items():
        out[trigger] = {
            "count": rec["count"],
            "mean_depth": round(rec["depth_total"] / rec["count"], 2) if rec["count"] else 0.0,
            "max_depth": rec["max_depth"],
            "compiles": rec["compiles"],
        }
    return out


def forcing_points() -> Dict[str, Dict[str, Any]]:
    """Per-trigger forcing histogram: count, mean/max chain depth forced,
    and how many of those forces paid a compile."""
    return _render_forces(_cur())


# ----------------------------------------------------------------------
# compile / retrace tracking
# ----------------------------------------------------------------------
def record_retrace(family: tuple, shape_key) -> None:
    """Record a fusion-cache miss for op ``family`` (the DAG's op identities)
    under leaf-shape signature ``shape_key``. When one family accumulates
    ``_RETRACE_WARN_AFTER`` distinct shape signatures, a
    :class:`RetraceWarning` fires — exactly once per family (the warn
    decision reads the GLOBAL ledger, so scopes never re-warn)."""
    if not _MODE:
        return
    grec0 = _GLOBAL.retraces.get(family)
    already_warned = grec0 is not None and grec0["warned"]
    for st in _states():
        rec = st.retraces.get(family)
        if rec is None:
            # a family the GLOBAL ledger already warned on starts warned in
            # every fresh scope state too — otherwise per-request scopes
            # under shape churn would re-accumulate keys forever
            rec = st.retraces[family] = {"misses": 0, "keys": set(), "warned": already_warned}
        rec["misses"] += 1
        if not rec["warned"] and not already_warned:
            # the key set only exists to cross the warn threshold; once warned,
            # ``misses`` tracks volume and the set stops growing (shape churn is
            # exactly the case that would otherwise accumulate keys unboundedly)
            rec["keys"].add(shape_key)
    for frame in _span_stack():
            frame.retraces += 1
    grec = _GLOBAL.retraces.get(family)
    if grec is None:  # reset() raced the loop above; nothing to warn on
        return
    if not grec["warned"] and len(grec["keys"]) >= _RETRACE_WARN_AFTER:
        for st in _states():
            rec = st.retraces.get(family)
            if rec is not None:
                rec["warned"] = True
        warnings.warn(
            RetraceWarning(
                f"op family {'/'.join(family) or '<leaf>'} recompiled under "
                f"{len(grec['keys'])} distinct input shapes ({grec['misses']} cache "
                "misses): shape churn is defeating the fusion program cache — pad "
                "or bucket the varying dimension, or force the chain before the "
                "shape-dependent step"
            ),
            stacklevel=3,
        )


def _render_retraces(st: _State) -> Dict[str, Dict[str, Any]]:
    return {
        "/".join(family) or "<leaf>": {
            "misses": rec["misses"],
            "distinct_shapes": len(rec["keys"]),
            "warned": rec["warned"],
        }
        for family, rec in st.retraces.items()
    }


def retraces() -> Dict[str, Dict[str, Any]]:
    """Per-op-family fusion-cache miss accounting."""
    return _render_retraces(_cur())


def record_compile(label: str, cid: Optional[int] = None) -> None:
    """Count a jit program build outside the fusion cache (e.g. one
    ``MeshCommunication.apply`` kernel), keyed by kernel label."""
    if not _MODE:
        return
    for st in _states():
        st.compiles[label] = st.compiles.get(label, 0) + 1
    _note_event("compile", label=label, cid=cid)


# ----------------------------------------------------------------------
# engine dispatch accounting
# ----------------------------------------------------------------------
def record_dispatch(engine: str, fused: bool) -> None:
    """Count one L3-engine dispatch (``binary``/``local``/``reduce``/``cum``)
    as deferred-into-the-DAG (``fused``) or eager."""
    if not _MODE:
        return
    key = "fused" if fused else "eager"
    for st in _states():
        rec = st.dispatches.get(engine)
        if rec is None:
            rec = st.dispatches[engine] = {"fused": 0, "eager": 0}
        rec[key] += 1


def dispatches() -> Dict[str, Dict[str, int]]:
    """Per-engine fused-vs-eager dispatch counts."""
    return {k: dict(v) for k, v in _cur().dispatches.items()}


def record_unfused(engine: str, reason: str) -> None:
    """One breadcrumb per eager-fallback site: ``engine`` declined to defer
    an op for ``reason`` (``out=``, ``where=``, ``padded_broadcast``,
    ``tracer_payload``, ``record_failed:<Type>``, ...) — so ``report()``
    shows *why* a chain wasn't fused, not just that it wasn't."""
    if not _MODE:
        return
    for st in _states():
        rec = st.unfused.get(engine)
        if rec is None:
            rec = st.unfused[engine] = {}
        rec[reason] = rec.get(reason, 0) + 1


def unfused_reasons() -> Dict[str, Dict[str, int]]:
    """Per-engine reasons ops fell back to the eager engine instead of
    deferring into the fusion DAG."""
    return {k: dict(v) for k, v in _cur().unfused.items()}


# ----------------------------------------------------------------------
# resilience accounting (core/resilience.py)
# ----------------------------------------------------------------------
def record_degraded(family: tuple, stage: str, error: str = "") -> None:
    """Record one guarded-forcing degradation: the fused program for op
    ``family`` failed at ``stage`` (``compile``/``execute``) and the chain
    was re-run as per-op eager dispatch (fusion quarantines the DAG key)."""
    if not _MODE:
        return
    key = "/".join(family) or "<leaf>"
    for st in _states():
        rec = st.degraded.get(key)
        if rec is None:
            rec = st.degraded[key] = {"count": 0, "stages": {}, "last_error": ""}
        rec["count"] += 1
        rec["stages"][stage] = rec["stages"].get(stage, 0) + 1
        if error:
            rec["last_error"] = error
    _note_event("degraded", family=key, stage=stage, error=error)


def degraded_counts() -> Dict[str, int]:
    """Per-op-family guarded-forcing degradation counts — the assertable
    surface (``collective_counts()``-style) the resilience suite pins."""
    return {key: rec["count"] for key, rec in _cur().degraded.items()}


def _render_degraded(st: _State) -> Dict[str, Dict[str, Any]]:
    return {
        key: {
            "count": rec["count"],
            "stages": dict(rec["stages"]),
            "last_error": rec["last_error"],
        }
        for key, rec in st.degraded.items()
    }


def degraded() -> Dict[str, Dict[str, Any]]:
    """Full degradation accounting: count, per-stage breakdown, last error."""
    return _render_degraded(_cur())


def record_fault(site: str, pattern: str = "") -> None:
    """Count one *injected* fault firing at ``site`` (``core/resilience.py``
    harness) — faults are first-class timeline events, so a trace shows the
    degradation/retry activity right next to the fault that caused it."""
    if not _MODE:
        return
    for st in _states():
        st.faults[site] = st.faults.get(site, 0) + 1
    _note_event("fault", site=site, pattern=pattern)


def fault_events() -> Dict[str, int]:
    """Per-site injected-fault counts as telemetry saw them (the resilience
    harness's own ``fault_counts()`` is the mode-independent ledger)."""
    return dict(_cur().faults)


def record_nonfinite(where: str) -> None:
    """Count one errstate non-finite detection at forcing point ``where``."""
    if not _MODE:
        return
    for st in _states():
        st.nonfinite[where] = st.nonfinite.get(where, 0) + 1
    _note_event("nonfinite", where=where)


def nonfinite_counts() -> Dict[str, int]:
    """Per-forcing-point errstate non-finite detections."""
    return dict(_cur().nonfinite)


def record_io_retry(site: str) -> None:
    """Count one transient-``OSError`` retry at I/O injection site ``site``."""
    if not _MODE:
        return
    for st in _states():
        st.io_retries[site] = st.io_retries.get(site, 0) + 1
    _note_event("io_retry", site=site)


def io_retries() -> Dict[str, int]:
    """Per-site transient I/O retry counts."""
    return dict(_cur().io_retries)


def record_checkpoint(event: str, step: Optional[int] = None, detail: str = "") -> None:
    """Count one checkpoint lifecycle event (``utils/checkpoint.py``):
    ``save`` (manifest committed), ``restore`` (verified restore completed),
    ``corrupt`` (a checkpoint failed verification), ``fallback`` (restore
    skipped unverifiable newer checkpoints), ``gc`` (retention/debris sweep
    removed something). The assertable surface the checkpoint suite pins;
    finer-grained phase boundaries ride :func:`record_event`
    (``checkpoint_phase``) so they land on the timeline without disturbing
    these counts."""
    if not _MODE:
        return
    for st in _states():
        st.checkpoint[event] = st.checkpoint.get(event, 0) + 1
    _note_event("checkpoint", event=event, step=step, detail=detail)
    if _MEM_HOOK is not None:
        _MEM_HOOK("checkpoint")


def checkpoint_events() -> Dict[str, int]:
    """Per-event checkpoint lifecycle counts (``save``/``restore``/
    ``corrupt``/``fallback``/``gc``)."""
    return dict(_cur().checkpoint)


# ----------------------------------------------------------------------
# spans
# ----------------------------------------------------------------------
class _SpanFrame:
    __slots__ = ("path", "t0", "collectives", "forces", "retraces", "timers")

    def __init__(self, path: str):
        self.path = path
        self.t0 = time.perf_counter()
        self.collectives: Dict[str, int] = {}
        self.forces = 0
        self.retraces = 0
        self.timers: Dict[str, float] = {}


@contextmanager
def span(name: str):
    """Scope all counters to a named region. Spans nest (``"fit"`` containing
    ``"fit/iter"``), attribute the collective / forcing / retrace deltas that
    occur inside them, absorb ``utils/profiling.Timer`` records closing
    within them, and mirror their own wall time into the Timer registry as
    ``span:<path>`` so the two report surfaces stay joined. In verbose mode
    each span emits ``span_begin``/``span_end`` timeline events — the B/E
    duration pair of the exported trace. Yields the full span path (or None
    when telemetry is off)."""
    if not _MODE:
        yield None
        return
    spans = _span_stack()
    path = (spans[-1].path + "/" + name) if spans else name
    frame = _SpanFrame(path)
    spans.append(frame)
    if _MODE >= 2:
        _emit("span_begin", name=path)
    try:
        yield path
    finally:
        spans.pop()
        elapsed = time.perf_counter() - frame.t0
        if _MODE >= 2:
            _emit("span_end", name=path, dur=elapsed)
        for st in _states():
            rec = st.spans.get(path)
            if rec is None:
                rec = st.spans[path] = {
                    "calls": 0,
                    "total_s": 0.0,
                    "collectives": {},
                    "forces": 0,
                    "retraces": 0,
                    "timers": {},
                }
            rec["calls"] += 1
            rec["total_s"] += elapsed
            rec["forces"] += frame.forces
            rec["retraces"] += frame.retraces
            for op, cnt in frame.collectives.items():
                rec["collectives"][op] = rec["collectives"].get(op, 0) + cnt
            for tname, secs in frame.timers.items():
                rec["timers"][tname] = rec["timers"].get(tname, 0.0) + secs
        try:  # mirror into the Timer registry (utils/profiling nesting contract)
            from ..utils import profiling

            profiling.record_timing("span:" + path, elapsed)
        except Exception:  # pragma: no cover - report must not die on import order
            pass


def on_timer(name: str, elapsed: float) -> None:
    """Called by ``utils/profiling.Timer`` on every record: timers closing
    inside an active span are attributed to EVERY enclosing span — the same
    roll-up rule as collectives/forces (``span:`` mirrors excluded) — and in
    verbose mode each close lands on the timeline as a ``timer`` event (the
    exporter renders it as a B/E pair over its duration)."""
    if name.startswith("span:"):
        return
    if _MODE >= 2:
        _emit("timer", name=name, dur=elapsed)
    for frame in _span_stack():
        frame.timers[name] = frame.timers.get(name, 0.0) + elapsed


def _render_spans(st: _State) -> Dict[str, Dict[str, Any]]:
    return {
        path: {
            "calls": rec["calls"],
            "total_s": rec["total_s"],
            "collectives": dict(rec["collectives"]),
            "forces": rec["forces"],
            "retraces": rec["retraces"],
            "timers": dict(rec["timers"]),
        }
        for path, rec in st.spans.items()
    }


def spans() -> Dict[str, Dict[str, Any]]:
    """Per-span aggregates: calls, wall seconds, attributed collective
    counts, forces, retraces and nested timer seconds."""
    return _render_spans(_cur())


# ----------------------------------------------------------------------
# report
# ----------------------------------------------------------------------
def _memory_block() -> Dict[str, Any]:
    """Best-effort memory picture: per-device backend stats (TPU exposes
    them; forced-host CPU returns {}), live device-buffer bytes, the
    owner-attributed ledger (``core/memledger.py``) and its high watermark,
    plus the admission-gate configuration and any stored OOM forensic.
    Never forces a chain, never raises — and never INITIALIZES anything:
    until the mesh singleton exists only the jax-free state (watermark,
    gate, OOM report) is included, because report() (and the background
    metrics sink) must not pin the JAX backend before the user flips
    platforms (the lazy-singleton contract in heat_tpu/__init__.py)."""
    out: Dict[str, Any] = {"device": {}, "live_buffers": {}, "ledger": {}}
    try:
        from . import memledger

        # pure module state — safe before any backend exists
        out["watermark"] = memledger.watermark()
        out["budget"] = memledger.budget_info()
        oom = memledger.last_oom()
        if oom is not None:
            out["last_oom"] = oom
    except Exception:  # pragma: no cover - import-order safety only
        pass
    try:
        from . import communication

        if communication.MESH_WORLD is None:
            return out
        from ..utils import health, profiling

        out["device"] = profiling.device_memory_stats()
        out["host"] = profiling.host_memory_stats()
        out["live_buffers"] = health.memory_report()
        from . import memledger

        out["ledger"] = memledger.ledger()
    except Exception:  # pragma: no cover - backend-dependent
        pass
    return out


def _numerics_block() -> Dict[str, Any]:
    """The numerics-observability picture (``core/numlens.py``): sampling
    counters, per-program tensor statistics, the shadow-replay drift
    ledger, SDC canary summary, training-signal streams and numeric
    findings. Pure module state — never forces a chain, never initializes
    a backend (the lens only ever sees values that already landed)."""
    try:
        from . import numlens

        return numlens.numerics_block()
    except Exception:  # pragma: no cover - import-order safety only
        return {}


def _health_block(global_view: bool = False) -> Dict[str, Any]:
    """The runtime-health picture (``core/health_runtime.py``): flight-ring
    occupancy, watchdog state + last stall diagnosis, per-program and
    per-trigger latency histograms (p50/p90/p99) and the rolling SLO gauges.
    Pure module state — never forces a chain, never initializes a backend.
    ``global_view`` mirrors report()'s ``_state`` override: the background
    metrics sink streams the GLOBAL histograms whatever scope is active."""
    try:
        from . import health_runtime

        return health_runtime.health_block(global_view=global_view)
    except Exception:  # pragma: no cover - import-order safety only
        return {}


def _programs_block(top: Optional[int] = None) -> Dict[str, Any]:
    """Top-N cached sharded programs by dispatch count (cheap metadata only;
    memoized cost estimates — including each program's static memory peaks —
    are merged in when :func:`program_costs` has been asked to compute them;
    report() itself never compiles). ``cost_errors`` counts the programs
    whose cost estimate failed in the backend (``fusion.cost_error_count``)
    — failures are counted and warned once per session, never silent."""
    from . import fusion

    progs = fusion.programs()
    ranked = sorted(progs.items(), key=lambda kv: kv[1].get("dispatches", 0), reverse=True)
    n = _TOP_PROGRAMS if top is None else top
    return {
        "cached": len(progs),
        "cost_errors": fusion.cost_error_count(),
        "top": [dict(rec, key=key) for key, rec in ranked[:n]],
    }


def program_costs(top: Optional[int] = None, refresh: bool = False) -> Dict[str, Dict[str, Any]]:
    """Per-cached-program cost estimates keyed by program key: flops and
    bytes-accessed from XLA's cost analysis of the program's HLO, in-program
    collective counts (:func:`hlo_collective_counts`), and the logical
    operand/result bytes from the recorded signature. Estimates are computed
    by AOT-lowering the cached signature from its abstract leaf specs — an
    extra compile per program, so results are memoized (``refresh=True``
    recomputes) and ``report()`` only merges already-computed ones. Never
    touches live data or forces a chain."""
    from . import fusion

    return fusion.program_costs(top=top, refresh=refresh)


def report(*, _state: Optional[_State] = None) -> Dict[str, Any]:
    """The whole telemetry picture as one structured dict (JSON-ready via
    :func:`report_json`). Includes the fusion program-cache counters, the
    ``utils/profiling`` timer registry, the ``memory`` block and every
    completed :func:`scope` — one call answers "where did the time, the
    bytes, the compiles and the memory go". Inside a scope, the counter
    blocks are the scope's own isolated view (``_state`` is the internal
    override the background metrics sink uses to always stream the GLOBAL
    view, whatever scope the main thread happens to be inside)."""
    st = _state if _state is not None else _cur()
    doc: Dict[str, Any] = {
        "enabled": active(),
        "mode": {0: "off", 1: "on", 2: "verbose"}[_MODE],
        "collectives": _render_collectives(st),
        "collective_counts": {op: rec["count"] for op, rec in st.collectives.items()},
        "fused_collectives": dict(st.fused_collectives),
        "async_forcing": _render_async(st),
        "forcing_points": _render_forces(st),
        "dispatches": {k: dict(v) for k, v in st.dispatches.items()},
        "unfused_reasons": {k: dict(v) for k, v in st.unfused.items()},
        "retraces": _render_retraces(st),
        "degraded": _render_degraded(st),
        "nonfinite": dict(st.nonfinite),
        "io_retries": dict(st.io_retries),
        "checkpoint": dict(st.checkpoint),
        "faults": dict(st.faults),
        "jit_compiles": dict(st.compiles),
        "spans": _render_spans(st),
        "timeline": {
            "events": len(st.events),
            "events_dropped": st.events_dropped,
            "cap": _EVENT_CAP,
        },
        "scopes": scope_reports(),
        "memory": _memory_block(),
        "health": _health_block(global_view=_state is not None),
        "numerics": _numerics_block(),
    }
    try:
        from . import fusion

        doc["fusion_cache"] = fusion.cache_stats()
        doc["programs"] = _programs_block()
    except Exception:  # pragma: no cover
        pass
    try:
        from ..utils import profiling

        doc["timers"] = profiling.report()
    except Exception:  # pragma: no cover
        pass
    try:
        from . import serving

        if serving._SESSIONS:  # only when the serving layer has sessions
            doc["serving"] = serving.sessions_block()
    except Exception:  # pragma: no cover - the report never fails
        pass
    if _ELASTIC_HOOK is not None:
        try:
            doc["elastic"] = _ELASTIC_HOOK()
        except Exception:  # pragma: no cover - the report never fails
            pass
    if _AUTOSCALE_HOOK is not None:
        try:
            doc["autoscale"] = _AUTOSCALE_HOOK()
        except Exception:  # pragma: no cover - the report never fails
            pass
    if _MULTIHOST_HOOK is not None:
        try:
            doc["multihost"] = _MULTIHOST_HOOK()
        except Exception:  # pragma: no cover - the report never fails
            pass
    if _MODE >= 2:
        doc["events"] = list(st.events)
    return doc


def _jsonable(obj):
    """Deterministic JSON projection: tuple keys join with "/", sets sort,
    tuples become lists, numpy scalars unbox — the schema-stability contract
    (no ``default=str`` drift for structured content)."""
    if isinstance(obj, dict):
        out = {}
        for k, v in obj.items():
            if isinstance(k, tuple):
                k = "/".join(str(p) for p in k)
            elif not isinstance(k, str):
                k = str(k)
            out[k] = _jsonable(v)
        return out
    if isinstance(obj, (list, tuple, deque)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (set, frozenset)):
        return sorted(str(v) for v in obj)
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    item = getattr(obj, "item", None)  # numpy scalars
    if callable(item):
        try:
            return item()
        except Exception:  # pragma: no cover
            pass
    return str(obj)


def report_json(path: Optional[str] = None, indent: int = 2) -> str:
    """:func:`report` serialized to JSON; written to ``path`` when given.
    Serialization is deterministic (:func:`_jsonable`): every key is a
    string, tuples/sets have a pinned projection, and ``default=str`` is
    only a last-resort safety net — round-tripping through ``json.loads``
    is schema-stable across calls."""
    text = json.dumps(_jsonable(report()), indent=indent, default=str)
    if path is not None:
        with open(path, "w") as fh:
            fh.write(text)
            fh.write("\n")
    return text


# ----------------------------------------------------------------------
# Chrome/Perfetto trace export
# ----------------------------------------------------------------------
def _host_index() -> int:
    try:
        from . import multihost

        return int(multihost.process_index())
    except Exception:  # pragma: no cover - import-order safety
        return 0


def _us(ts: float) -> float:
    return round(ts * 1e6, 3)


#: instant-event rendering: kind -> (category, name builder)
_INSTANT_KINDS = {
    "collective": ("collective", lambda ev: ev.get("op", "collective")),
    "fused_collective": ("collective", lambda ev: "fused:" + str(ev.get("op"))),
    "record": ("record", lambda ev: "record:" + str(ev.get("op"))),
    "compile": ("compile", lambda ev: "compile:" + str(ev.get("label") or ev.get("family") or ev.get("program"))),
    "force": ("force", lambda ev: "force:" + str(ev.get("trigger"))),
    "degraded": ("degrade", lambda ev: "degraded:" + str(ev.get("family"))),
    "fault": ("fault", lambda ev: "fault:" + str(ev.get("site"))),
    "io_retry": ("io", lambda ev: "io_retry:" + str(ev.get("site"))),
    "io": ("io", lambda ev: "io:" + str(ev.get("op", "op"))),
    "checkpoint": ("checkpoint", lambda ev: "checkpoint:" + str(ev.get("event"))),
    "checkpoint_phase": ("checkpoint", lambda ev: "ckpt:" + str(ev.get("phase"))),
    "nonfinite": ("errstate", lambda ev: "nonfinite:" + str(ev.get("where"))),
    "memory_gate": ("memory", lambda ev: "gate:" + str(ev.get("policy"))),
    "memory_oom": ("memory", lambda ev: "oom:" + str(ev.get("program"))),
    "stall": ("health", lambda ev: "stall:" + str(ev.get("site"))),
    "slo_breach": ("health", lambda ev: "slo:" + str(ev.get("metric"))),
    "flight_dump": ("health", lambda ev: "flight_dump:" + str(ev.get("reason"))),
    # numeric stats events additionally render as counter tracks (see
    # trace_events) — this entry covers the drift/sdc/train instants
    "numeric": ("numeric", lambda ev: "numeric:" + str(ev.get("event"))),
}


def async_pairs(evs: Optional[List[dict]] = None) -> List[tuple]:
    """Match the timeline's ``dispatch`` events to the ``blocking_sync``
    events that waited on them via correlation id: a sync waits on the
    dispatch whose root set (``cids``) contains its chain's ``cid``.
    Returns ``[(dispatch_event, sync_event), ...]`` — the exporter's async
    pair source and the assertable surface for "this sync waited on that
    program"."""
    if evs is None:
        evs = list(_cur().events)
    by_cid: Dict[int, dict] = {}
    for ev in evs:
        if ev.get("kind") != "dispatch":
            continue
        for cid in ev.get("cids") or ([ev["cid"]] if ev.get("cid") is not None else []):
            by_cid[cid] = ev
    pairs = []
    for ev in evs:
        if ev.get("kind") != "blocking_sync" or ev.get("cid") is None:
            continue
        disp = by_cid.get(ev["cid"])
        if disp is not None:
            pairs.append((disp, ev))
    return pairs


def trace_events(evs: Optional[List[dict]] = None, pid: Optional[int] = None) -> List[dict]:
    """Render the timeline as a list of Chrome trace-event dicts: spans and
    timers as B/E duration pairs, dispatch→blocking-sync as async ``b``/``e``
    pairs keyed by cid, everything else as thread-scoped instants. One
    process row per host (``pid`` defaults to ``multihost.process_index()``),
    everything on tid 0."""
    if evs is None:
        evs = list(_cur().events)
    if pid is None:
        pid = _host_index()
    tid = 0
    out: List[dict] = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": tid,
         "args": {"name": f"heat_tpu host {pid}"}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
         "args": {"name": "python"}},
    ]

    def args_of(ev, *skip):
        return {
            k: _jsonable(v)
            for k, v in ev.items()
            if k not in ("kind", "ts") and k not in skip and v is not None
        }

    for ev in sorted(evs, key=lambda e: e.get("ts", 0.0)):
        kind = ev.get("kind")
        ts = _us(ev.get("ts", 0.0))
        if kind == "span_begin":
            out.append({"ph": "B", "cat": "span", "name": ev.get("name"),
                        "pid": pid, "tid": tid, "ts": ts, "args": args_of(ev, "name")})
        elif kind == "span_end":
            out.append({"ph": "E", "cat": "span", "name": ev.get("name"),
                        "pid": pid, "tid": tid, "ts": ts})
        elif kind == "timer":
            dur = float(ev.get("dur", 0.0))
            start = _us(ev["ts"] - dur)
            name = str(ev.get("name"))
            out.append({"ph": "B", "cat": "timer", "name": name,
                        "pid": pid, "tid": tid, "ts": start})
            out.append({"ph": "E", "cat": "timer", "name": name,
                        "pid": pid, "tid": tid, "ts": ts})
        elif kind == "blocking_sync":
            name = "sync:" + str(ev.get("where"))
            if "dur" in ev:
                out.append({"ph": "X", "cat": "sync", "name": name,
                            "pid": pid, "tid": tid, "ts": ts,
                            "dur": _us(float(ev["dur"])), "args": args_of(ev, "dur")})
            else:
                out.append({"ph": "i", "s": "t", "cat": "sync", "name": name,
                            "pid": pid, "tid": tid, "ts": ts, "args": args_of(ev)})
        elif kind == "dispatch":
            out.append({"ph": "i", "s": "t", "cat": "dispatch", "name": "dispatch",
                        "pid": pid, "tid": tid, "ts": ts, "args": args_of(ev)})
        elif kind == "memory":
            # counter ("C") tracks per host: Perfetto renders each args key
            # as a stacked series — one track for the owner-attributed live
            # bytes, one for the high watermark
            series = {"total": int(ev.get("total", 0))}
            for owner, nbytes in (ev.get("by_owner") or {}).items():
                series[str(owner)] = int(nbytes)
            out.append({"ph": "C", "cat": "memory", "name": "live_bytes",
                        "pid": pid, "tid": tid, "ts": ts, "args": series})
            out.append({"ph": "C", "cat": "memory", "name": "live_bytes_watermark",
                        "pid": pid, "tid": tid, "ts": ts,
                        "args": {"watermark": int(ev.get("watermark", 0))}})
        elif kind == "numeric" and ev.get("event") == "stats":
            # numerics counter tracks alongside memledger's: one track per
            # sampled program root (rms / absmax as stacked series, plus a
            # saturation track for the nonfinite + exponent-edge counts)
            label = f"numerics:{ev.get('program')}[{ev.get('root')}]"
            out.append({"ph": "C", "cat": "numeric", "name": label,
                        "pid": pid, "tid": tid, "ts": ts,
                        "args": {"rms": float(ev.get("rms", 0.0)),
                                 "absmax": float(ev.get("absmax", 0.0))}})
            out.append({"ph": "C", "cat": "numeric", "name": label + ":saturation",
                        "pid": pid, "tid": tid, "ts": ts,
                        "args": {"nonfinite": int(ev.get("nonfinite", 0)),
                                 "edge_low": int(ev.get("edge_low", 0)),
                                 "edge_high": int(ev.get("edge_high", 0))}})
        else:
            cat, name_of = _INSTANT_KINDS.get(kind, ("event", lambda e, k=kind: str(k)))
            out.append({"ph": "i", "s": "t", "cat": cat, "name": name_of(ev),
                        "pid": pid, "tid": tid, "ts": ts, "args": args_of(ev)})

    # dispatch -> blocking-sync async pairs, keyed by correlation id. The
    # sync event is stamped when the host boundary NOTES the pending chain
    # (just before it triggers the dispatch), so the pair opens at the
    # earlier of the two stamps and closes when the host holds the value.
    for disp, sync in async_pairs(evs):
        start = min(disp["ts"], sync["ts"])
        end = max(disp["ts"], sync["ts"] + float(sync.get("dur", 0.0)))
        ident = str(sync.get("cid"))
        name = "dispatch→sync"
        common = {"cat": "async_forcing", "name": name, "id": ident, "pid": pid, "tid": tid}
        out.append(dict(common, ph="b", ts=_us(start),
                        args={"program": disp.get("program"), "roots": disp.get("roots"),
                              "where": sync.get("where"), "cid": sync.get("cid")}))
        out.append(dict(common, ph="e", ts=_us(end)))
    return out


def export_trace(path: Optional[str] = None, events: Optional[List[dict]] = None) -> Dict[str, Any]:
    """Export the trace timeline as Chrome/Perfetto trace-event JSON
    (`chrome://tracing` / ui.perfetto.dev). Inside a :func:`scope` this
    exports the scope's own timeline. Returns the trace document; written to
    ``path`` when given. Requires ``HEAT_TPU_TELEMETRY=verbose`` to have
    been active while the events of interest were recorded (the timeline is
    empty otherwise — the export itself works in any mode and never forces a
    pending chain)."""
    doc = {
        "traceEvents": trace_events(events),
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "heat_tpu.telemetry",
            "host": _host_index(),
            "mode": {0: "off", 1: "on", 2: "verbose"}[_MODE],
            "events_dropped": _cur().events_dropped,
        },
    }
    if path is not None:
        with open(path, "w") as fh:
            json.dump(doc, fh)
            fh.write("\n")
    return doc


def merge_traces(
    paths: List[str],
    path: Optional[str] = None,
    align: bool = True,
    check_parity: bool = False,
) -> Dict[str, Any]:
    """Stitch per-host trace files (one :func:`export_trace` output per
    controller) into a single multi-process trace: each input keeps its own
    process row (re-pid'd by input order on collision), and ``align=True``
    shifts every input so its earliest timestamp sits at zero — perf_counter
    epochs differ across hosts, so only relative time is meaningful.

    ``check_parity=True`` runs :func:`trace_collective_parity` over the
    merged document — per-cid collective event counts must match across
    process rows (SPMD: every host records the same collectives). Problems
    warn and land under ``otherData["collective_parity"]``; they are the
    runtime signature of an H001 deadlock hazard (one host entered a
    collective its peers never recorded)."""
    merged: List[dict] = []
    seen_pids: set = set()
    dropped_total = 0
    for i, p in enumerate(paths):
        with open(p) as fh:
            doc = json.load(fh)
        other = doc.get("otherData")
        if isinstance(other, dict):
            try:
                dropped_total += int(other.get("events_dropped") or 0)
            except (TypeError, ValueError):
                pass
        evs = doc.get("traceEvents", [])
        pids = {ev.get("pid", 0) for ev in evs}
        remap = {}
        for old in sorted(pids):
            new = old
            while new in seen_pids:
                new = max(seen_pids) + 1
            seen_pids.add(new)
            remap[old] = new
        stamps = [ev["ts"] for ev in evs if "ts" in ev]
        base = min(stamps) if (align and stamps) else 0.0
        for ev in evs:
            ev = dict(ev)
            ev["pid"] = remap.get(ev.get("pid", 0), ev.get("pid", 0))
            if "ts" in ev:
                ev["ts"] = round(ev["ts"] - base, 3)
            merged.append(ev)
    doc = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "heat_tpu.telemetry",
            "merged_from": len(paths),
            "events_dropped": dropped_total,
        },
    }
    if check_parity:
        problems = trace_collective_parity(doc)
        if problems:
            doc["otherData"]["collective_parity"] = problems
            warnings.warn(
                "merged trace fails cross-host collective parity "
                f"({len(problems)} problem(s), first: {problems[0]}) — the runtime "
                "signature of a collective under host-divergent control flow "
                "(heat-lint H001)",
                stacklevel=2,
            )
    if path is not None:
        with open(path, "w") as fh:
            json.dump(doc, fh)
            fh.write("\n")
    return doc


def _load_trace_doc(doc_or_path):
    if not isinstance(doc_or_path, str):
        return doc_or_path, None
    try:
        with open(doc_or_path) as fh:
            return json.load(fh), None
    except Exception as exc:  # noqa: BLE001 - the problem IS the result
        return None, f"not valid JSON: {exc!r}"


def trace_collective_parity(doc_or_path) -> List[str]:
    """Cross-host collective parity of a (merged) trace: for every process
    row, count collective-category events keyed by (name, correlation id)
    and require identical multisets across rows. Under SPMD every host runs
    the same script, so per-host cid sequences align and each host must have
    recorded exactly the same collectives — a row missing (or holding extra)
    collective events is the already-exported-trace signature of the H001
    deadlock hazard: some hosts entered a collective the others never
    reached. Returns problem strings (empty = parity holds); single-row
    traces trivially pass."""
    doc, err = _load_trace_doc(doc_or_path)
    if err is not None:
        return [err]
    if not isinstance(doc, dict) or not isinstance(doc.get("traceEvents"), list):
        return ["missing traceEvents list"]
    per_pid: Dict[Any, Dict[tuple, int]] = {}
    for ev in doc["traceEvents"]:
        if not isinstance(ev, dict):
            continue
        ph = ev.get("ph")
        if ph == "M":
            # a process row exists even if it recorded nothing — an empty
            # row must still be compared (its silence IS the finding)
            per_pid.setdefault(ev.get("pid", 0), {})
            continue
        if ev.get("cat") != "collective":
            continue
        args = ev.get("args") or {}
        key = (str(ev.get("name")), args.get("cid"))
        counts = per_pid.setdefault(ev.get("pid", 0), {})
        counts[key] = counts.get(key, 0) + 1
    if len(per_pid) < 2:
        return []
    problems: List[str] = []
    pids = sorted(per_pid, key=str)
    ref_pid, ref = pids[0], per_pid[pids[0]]
    for pid in pids[1:]:
        counts = per_pid[pid]
        for key in sorted(set(ref) | set(counts), key=str):
            a, b = ref.get(key, 0), counts.get(key, 0)
            if a != b:
                name, cid = key
                where = f"collective {name!r}" + (f" cid {cid}" if cid is not None else "")
                problems.append(
                    f"{where}: host {ref_pid} recorded {a} event(s) but host {pid} "
                    f"recorded {b} — hosts diverged around this collective"
                )
    return problems


def validate_trace(doc_or_path, cross_host: bool = False) -> List[str]:
    """Structural problems of a Chrome trace-event document (or file path):
    empty list = loads and every event carries the required keys. The CLI's
    ``validate-trace`` and the CI matrix leg assert on this.
    ``cross_host=True`` additionally runs :func:`trace_collective_parity`
    (the ``validate-trace --cross-host`` CLI flag) so an exported multi-host
    trace surfaces the runtime signature of an H001 deadlock."""
    problems: List[str] = []
    doc, err = _load_trace_doc(doc_or_path)
    if err is not None:
        return [err]
    if not isinstance(doc, dict) or not isinstance(doc.get("traceEvents"), list):
        return ["missing traceEvents list"]
    open_async: Dict[str, int] = {}
    for i, ev in enumerate(doc["traceEvents"]):
        if not isinstance(ev, dict):
            problems.append(f"event {i} is not an object")
            continue
        ph = ev.get("ph")
        if ph is None or "pid" not in ev:
            problems.append(f"event {i} missing ph/pid: {ev}")
            continue
        if ph != "M" and "ts" not in ev:
            problems.append(f"event {i} ({ph}) missing ts")
        if ph in ("b", "e") and "id" not in ev:
            problems.append(f"async event {i} missing id")
        if ph == "C":
            # counter tracks (memory live-bytes, numerics rms/saturation)
            # must carry numeric series values or Perfetto drops the track
            args = ev.get("args")
            if not isinstance(args, dict) or not args:
                problems.append(f"counter event {i} missing args series")
            elif any(not isinstance(v, (int, float)) or isinstance(v, bool)
                     for v in args.values()):
                problems.append(f"counter event {i} has non-numeric series: {args}")
        if ph == "b":
            open_async[str(ev.get("id"))] = open_async.get(str(ev.get("id")), 0) + 1
        elif ph == "e":
            key = str(ev.get("id"))
            if open_async.get(key, 0) <= 0:
                problems.append(f"async end without begin (id {key})")
            else:
                open_async[key] -= 1
    for key, n in open_async.items():
        if n:
            problems.append(f"async begin without end (id {key})")
    if cross_host:
        problems.extend(trace_collective_parity(doc))
    return problems


# ----------------------------------------------------------------------
# streaming metrics sink: HEAT_TPU_METRICS=<path>
# ----------------------------------------------------------------------
class _MetricsSink:
    """Appends ``report()`` as one JSON line per flush to a file — the
    zero-code-change observability tap for long jobs (``tail -f`` / a
    sidecar scraper). A daemon thread flushes every ``interval`` seconds
    (0 = at-exit only); the atexit hook writes the final line. Flushes never
    raise and never force a pending chain."""

    def __init__(self, path: str, interval: float):
        self.path = path
        self.interval = float(interval)
        self.lines = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self.interval > 0 and self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="heat-tpu-metrics", daemon=True
            )
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.flush("periodic")

    def flush(self, event: str = "flush") -> bool:
        try:
            # always the GLOBAL state: the daemon thread's flush must not
            # snapshot whatever request scope the main thread is inside
            doc = report(_state=_GLOBAL)
            doc.pop("events", None)  # the timeline has its own exporter
            # stable line schema: report() only joins the serving block
            # when sessions exist and the elastic block when the hook is
            # installed, but a streaming consumer needs every line to
            # carry the same keys — fill the conditional blocks in
            if "serving" not in doc:
                try:
                    from . import serving

                    doc["serving"] = serving.sessions_block()
                except Exception:  # noqa: BLE001 - sink lines never fail
                    doc["serving"] = {}
            if "elastic" not in doc:
                try:
                    doc["elastic"] = {} if _ELASTIC_HOOK is None else _ELASTIC_HOOK()
                except Exception:  # noqa: BLE001 - sink lines never fail
                    doc["elastic"] = {}
            if "autoscale" not in doc:
                try:
                    doc["autoscale"] = (
                        {} if _AUTOSCALE_HOOK is None else _AUTOSCALE_HOOK()
                    )
                except Exception:  # noqa: BLE001 - sink lines never fail
                    doc["autoscale"] = {}
            line = json.dumps(
                _jsonable({"ts": time.time(), "event": event, "report": doc}),
                default=str,
            )
            with open(self.path, "a") as fh:
                fh.write(line + "\n")
            self.lines += 1
            return True
        # swallowing is this sink's contract: the flush runs on a daemon
        # thread and at exit; ANY failure (full disk, revoked mount,
        # interpreter teardown) must drop the metrics line, never the job
        # heat-lint: disable=H003 — observability must never take the job down
        except Exception:  # noqa: BLE001
            return False

    def stop(self, final: bool = True) -> None:
        self._stop.set()
        if final:
            self.flush("exit")


_SINK: Optional[_MetricsSink] = None


def set_metrics_sink(path: Optional[str], interval: Optional[float] = None) -> Optional[_MetricsSink]:
    """(Re)configure the JSON-lines metrics sink in-process: ``path=None``
    stops it (no final line), otherwise every ``interval`` seconds (default
    ``HEAT_TPU_METRICS_INTERVAL``, 30s; 0 = at-exit only) and at interpreter
    exit one ``report()`` line is appended to ``path``. Returns the sink."""
    global _SINK
    if _SINK is not None:
        _SINK.stop(final=False)
        _SINK = None
    if path:
        if interval is None:
            interval = float(os.environ.get("HEAT_TPU_METRICS_INTERVAL", "30"))
        _SINK = _MetricsSink(path, interval)
        _SINK.start()
    return _SINK


def _sink_atexit() -> None:
    if _SINK is not None:
        _SINK.stop(final=True)


atexit.register(_sink_atexit)
if os.environ.get("HEAT_TPU_METRICS"):
    set_metrics_sink(os.environ["HEAT_TPU_METRICS"])


# ----------------------------------------------------------------------
# compiled-program (HLO) collective accounting
# ----------------------------------------------------------------------
#: collective opcodes as they appear in HLO text, in call position
#: (``all-reduce(...)`` / async ``all-reduce-start(...)``). Order matters:
#: longest-prefix alternatives first so ``all-to-all`` never half-matches.
_HLO_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce-scatter|reduce-scatter|all-gather|all-reduce|all-to-all|"
    r"collective-permute|collective-broadcast)(?:-start)?\("
)


def hlo_collectives(hlo_text: str) -> List[Dict[str, str]]:
    """Collective *instructions* in an HLO dump: one entry per collective op
    in call position (async ``-start``/``-done`` pairs count once, via the
    start; instruction names and operand references never match). Each entry
    carries the op type and its source line for byte-budget checks."""
    out = []
    for line in hlo_text.splitlines():
        if "(" not in line or "=" not in line:
            continue
        # the regex requires "(" (or "-start(") right after the opcode, so
        # async "-done(" companions and name/operand references never match
        m = _HLO_COLLECTIVE_RE.search(line)
        if m:
            out.append({"op": m.group(1), "line": line.strip()})
    return out


def hlo_collective_counts(hlo_text: str) -> Dict[str, int]:
    """Per-type collective instruction counts of a compiled HLO dump —
    ``{"all-reduce": 3, "all-gather": 1}``. The readable replacement for
    counting regex hits against a single magic number."""
    counts: Dict[str, int] = {}
    for entry in hlo_collectives(hlo_text):
        counts[entry["op"]] = counts.get(entry["op"], 0) + 1
    return counts


def collective_budget_excess(
    counts: Dict[str, int], budget: Dict[str, int]
) -> Dict[str, str]:
    """Violations of a named per-type collective budget: any type over its
    allowance, or present but absent from the budget. Empty dict = within
    budget. Asserting ``collective_budget_excess(...) == {}`` fails with a
    diff that names the collective type instead of a magic total."""
    excess = {}
    for op, count in counts.items():
        allowed = budget.get(op)
        if allowed is None:
            excess[op] = f"{count} present but not budgeted"
        elif count > allowed:
            excess[op] = f"{count} > budget {allowed}"
    return excess

"""Compatibility shims for the jax release the environment provides.

The codebase targets the modern ``jax.shard_map(..., check_vma=...)`` entry
point (jax >= 0.6). Older releases (e.g. 0.4.x, as baked into this image)
only ship ``jax.experimental.shard_map.shard_map`` with the ``check_rep``
keyword — same semantics (disable the per-output replication/VMA check).
Importing this module (done first thing in ``heat_tpu.core``) installs a
forwarding ``jax.shard_map`` when it is absent, so every kernel call site
can use the one modern spelling.
"""

from __future__ import annotations

import jax

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None, check_vma=True, **kwargs):
        """Forward to ``jax.experimental.shard_map.shard_map``.

        The legacy ``check_rep`` replication checker is force-disabled: it
        predates the VMA system the kernels here are written against, and it
        rejects valid programs (e.g. ring-attention's scan carries) with
        "mismatched replication types ... as a temporary workaround pass
        check_rep=False" — its own suggested workaround. ``check_rep`` only
        toggles a validation pass, never results."""
        kwargs.pop("check_rep", None)
        del check_vma
        return _shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_rep=False,
            **kwargs,
        )

    jax.shard_map = shard_map

if not hasattr(jax.lax, "pcast"):

    def _pcast(x, axis_name=None, *, to=None):
        """Modern ``jax.lax.pcast`` marks a value's varying-manual-axes set
        for the VMA checker; with the legacy checker disabled (see above)
        the marking is a semantic no-op — identity."""
        del axis_name, to
        return x

    jax.lax.pcast = _pcast
